#!/usr/bin/env python
"""Measure the REFERENCE's own torch code on this host's CPU — the
measured denominators behind bench.py's ``vs_baseline`` (replacing the
round-1 fabricated ``NOMINAL_BASELINE_VPS``; results recorded with
provenance in BASELINE.md).

The reference cannot run end-to-end in this environment (its CLIP needs
the pip ``clip`` package, its decode needs mmcv, its PWC correlation is
CUDA-only), and it targets CUDA GPUs which this host does not have. What
CAN be measured honestly is its compute path on the CPU both frameworks
share:

- CLIP config: uni_12 cv2 decode + the reference's PIL
  resize/crop/normalize chain + a torch ViT-B/32 vision tower
  (transformers' CLIPVisionModelWithProjection — the same graph the pip
  ``clip`` package builds; random init, which does not change throughput).
- I3D+RAFT config: the reference's actual model sources
  (/root/reference/models/raft/raft_src/raft.py, iters=20, and
  /root/reference/models/i3d/i3d_src/i3d_net.py rgb+flow), driven with
  the reference's _run_on_a_stack windowing (ref
  models/i3d/extract_i3d.py:160-193): 64-pair RAFT per 65-frame stack,
  center-crop 224, flow clamp->uint8->[-1,1] quantization, both I3D
  streams.

Decode for both sides uses the same cv2 path (mmcv is unavailable), so
the comparison isolates framework+compute, not decoder brands.

Run: python scripts/measure_baseline.py [--videos N] [--skip-i3d]
Prints one JSON dict; paste the numbers + provenance into BASELINE.md and
bench.py's MEASURED_BASELINES.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF = "/root/reference"
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def _ref_import(name):
    import importlib

    if REF not in sys.path:
        sys.path.append(REF)
    return importlib.import_module(name)


def measure_clip_torch_cpu(videos) -> float:
    """Reference-equivalent CLIP pipeline in torch on CPU -> videos/s."""
    import torch
    from transformers import CLIPVisionConfig, CLIPVisionModelWithProjection

    from video_features_tpu.io.video import extract_frames
    from video_features_tpu.ops.preprocess import (
        CLIP_MEAN,
        CLIP_STD,
        normalize_chw,
        pil_center_crop,
        pil_resize,
        to_float_chw,
    )
    from PIL import Image

    cfg = CLIPVisionConfig(
        hidden_size=768,
        num_hidden_layers=12,
        num_attention_heads=12,
        intermediate_size=3072,
        image_size=224,
        patch_size=32,
        projection_dim=512,
        hidden_act="quick_gelu",
    )
    torch.manual_seed(0)
    model = CLIPVisionModelWithProjection(cfg).eval()

    def one(path):
        frames, fps, ts = extract_frames(path, "uni_12")
        batch = np.stack(
            [
                normalize_chw(
                    to_float_chw(
                        pil_center_crop(
                            pil_resize(f, 224, interpolation=Image.BICUBIC), 224
                        )
                    ),
                    CLIP_MEAN,
                    CLIP_STD,
                )
                for f in frames
            ]
        )
        with torch.no_grad():
            out = model(pixel_values=torch.from_numpy(batch)).image_embeds
        return out.numpy()

    one(videos[0])  # warmup (allocator, thread pool)
    # best-of-3 passes, SAME methodology as bench.py::bench_clip — the
    # numerator and denominator of vs_baseline must not differ in how
    # they treat run-to-run variance (advisor r02, medium)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for v in videos:
            feats = one(v)
            assert feats.shape == (12, 512)
        best = min(best, time.perf_counter() - t0)
    return len(videos) / best


def measure_i3d_raft_torch_cpu(video, passes: int = 2) -> float:
    """The reference's raft_src + i3d_src driven with its I3D stack loop
    on CPU -> videos/s (one video, typically 2 stacks). Best of
    ``passes`` — same methodology as bench.py::bench_i3d_raft (advisor
    r02, medium: vs_baseline must treat variance symmetrically)."""
    import torch

    from video_features_tpu.io.video import read_all_frames

    raft_mod = _ref_import("models.raft.raft_src.raft")
    i3d_mod = _ref_import("models.i3d.i3d_src.i3d_net")
    torch.manual_seed(0)
    raft = raft_mod.RAFT().eval()
    i3d_rgb = i3d_mod.I3D(num_classes=400, modality="rgb").eval()
    i3d_flow = i3d_mod.I3D(num_classes=400, modality="flow").eval()

    best = float("inf")
    for _ in range(max(passes, 1)):
        best = min(best, _one_i3d_pass(video, raft, i3d_rgb, i3d_flow))
    return 1.0 / best


def _one_i3d_pass(video, raft, i3d_rgb, i3d_flow) -> float:
    import torch

    from video_features_tpu.io.video import read_all_frames

    t0 = time.perf_counter()
    frames, _, _ = read_all_frames(video, None)
    import cv2

    # min-side 256 resize (ref i3d/transforms ResizeImproved); synth video
    # is square so this is a plain resize
    rs = [cv2.resize(f, (256, 256), interpolation=cv2.INTER_LINEAR) for f in frames]
    clip = torch.from_numpy(np.stack(rs)).permute(0, 3, 1, 2).float()  # (T,3,256,256)

    stack, step = 64, 64
    n_stacks = 0
    with torch.no_grad():
        for s in range(0, clip.shape[0] - stack, step):
            window = clip[s : s + stack + 1]
            flow = raft(window[:-1], window[1:], iters=20, test_mode=True)
            # center crop 224 + reference transform chains
            rgb = window[:-1, :, 16:240, 16:240]
            fl = flow[:, :, 16:240, 16:240]
            rgb = (2.0 * rgb / 255.0) - 1.0  # scale_to_1_1 after /255
            fl = torch.clamp(fl, -20, 20)
            fl = torch.floor(128 + 255.0 / 40.0 * fl).clamp(0, 255)  # ToUInt8
            fl = (2.0 * fl / 255.0) - 1.0
            feats_rgb = i3d_rgb(
                rgb.permute(1, 0, 2, 3).unsqueeze(0), features=True
            )
            feats_flow = i3d_flow(
                fl.permute(1, 0, 2, 3).unsqueeze(0), features=True
            )
            assert feats_rgb.shape == feats_flow.shape == (1, 1024)
            n_stacks += 1
    dt = time.perf_counter() - t0
    assert n_stacks >= 1
    return dt


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--videos", type=int, default=8, help="CLIP-config videos")
    ap.add_argument("--skip-i3d", action="store_true")
    ap.add_argument("--skip-clip", action="store_true")
    args = ap.parse_args()

    from video_features_tpu.utils.synth import synth_video

    out = {"host": os.uname().nodename, "cpu_count": os.cpu_count()}
    with tempfile.TemporaryDirectory() as tmp:
        # the same synth specs bench.py uses
        clip_video = synth_video(
            os.path.join(tmp, "clip.mp4"), n_frames=120, width=640, height=360
        )
        i3d_video = synth_video(
            os.path.join(tmp, "i3d.mp4"), n_frames=140, width=256, height=256
        )
        if not args.skip_clip:
            out["clip_torch_cpu_vps"] = round(
                measure_clip_torch_cpu([clip_video] * args.videos), 4
            )
        if not args.skip_i3d:
            out["i3d_raft_torch_cpu_vps"] = round(
                measure_i3d_raft_torch_cpu(i3d_video), 4
            )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
