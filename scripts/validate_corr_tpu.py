#!/usr/bin/env python
"""One-shot on-chip validation of the Pallas correlation kernel at PWC's
real pyramid shapes (VERDICT r4 next #3: the kernel has only ever run in
interpret mode on CPU — prove the COMPILED path on silicon).

Run manually on a host with a healthy TPU backend:

    python scripts/validate_corr_tpu.py

Tiered like validate_flash_tpu.py: a small Mosaic grid compiles first,
so if a bigger compile takes the helper down the artifact still proves
the compiled kernel ran on hardware. Each tier asserts 1e-4 agreement
against the XLA shifted-reduce formulation (itself parity-tested against
the reference CUDA kernel's spec in tests/test_pallas_correlation.py /
tests/test_pwc.py; ref pwc_src/correlation.py:106-108).

Shapes: the decoder cascade correlates at pyramid levels 6..2; for the
bench's 256x256 two-stream config that is 4x4 (level 6) up to 64x64
(level 2, the hottest volume and the one 'auto' routes to Pallas), with
a 64-pair batch (one 65-frame I3D stack). The 32x32 level-3 tier is the
boundary case just under the auto threshold.
"""
import time

import numpy as np

import jax
import jax.numpy as jnp

from video_features_tpu.ops.correlation import local_correlation


def validate(n: int, c: int, hw: int) -> None:
    rng = np.random.RandomState(0)
    f1 = jnp.asarray(rng.randn(n, c, hw, hw).astype(np.float32))
    f2 = jnp.asarray(rng.randn(n, c, hw, hw).astype(np.float32))

    pallas = jax.jit(lambda a, b: local_correlation(a, b, method="pallas"))
    xla = jax.jit(lambda a, b: local_correlation(a, b, method="xla"))

    t0 = time.perf_counter()
    out = pallas(f1, f2)
    out.block_until_ready()
    print(f"{n}x{c}x{hw}x{hw} pallas compile+run: "
          f"{time.perf_counter() - t0:.2f} s", flush=True)
    t0 = time.perf_counter()
    out = np.asarray(pallas(f1, f2))
    print(f"{n}x{c}x{hw}x{hw} pallas steady (incl fetch): "
          f"{time.perf_counter() - t0:.3f} s", flush=True)
    ref = xla(f1, f2)
    ref.block_until_ready()
    t0 = time.perf_counter()
    ref = np.asarray(xla(f1, f2))
    print(f"{n}x{c}x{hw}x{hw} xla steady (incl fetch): "
          f"{time.perf_counter() - t0:.3f} s", flush=True)
    err = float(np.abs(out - ref).max())
    print(f"{n}x{c}x{hw}x{hw} max abs diff: {err:.2e}", flush=True)
    assert err < 1e-4, err
    print(f"{n}x{c}x{hw}x{hw} ok", flush=True)


def main() -> None:
    assert jax.default_backend() == "tpu", jax.default_backend()
    validate(4, 64, 16)    # level 4-ish, small grid compiles first
    validate(64, 64, 32)   # level 3 at full pair batch (auto: xla side)
    validate(64, 32, 64)   # level 2, the hottest volume (auto: pallas)
    print("all tiers ok", flush=True)


if __name__ == "__main__":
    main()
