#!/usr/bin/env python
"""One-shot on-chip validation of the Pallas correlation kernel at PWC's
real pyramid shapes (VERDICT r4 next #3: the kernel has only ever run in
interpret mode on CPU — prove the COMPILED path on silicon), plus the
measured re-derivation of the auto-routing threshold.

Run manually on a host with a healthy TPU backend:

    python scripts/validate_corr_tpu.py

Tiered like validate_flash_tpu.py: a small Mosaic grid compiles first,
so if a bigger compile takes the helper down the artifact still proves
the compiled kernel ran on hardware. Each tier asserts 1e-4 agreement
against the XLA shifted-reduce formulation (itself parity-tested against
the reference CUDA kernel's spec in tests/test_pallas_correlation.py /
tests/test_pwc.py; ref pwc_src/correlation.py:106-108) and times both
methods amortized (K calls chained in one jitted scan — per-dispatch
tunnel latency is ~25 ms, kernels are µs-scale).

After all tiers, the smallest H*W where the Pallas kernel wins becomes
``corr_routing.json`` at the repo root — ops/correlation.py's 'auto'
dispatch loads it, replacing the design-derived 4096 heuristic with
measured data (commit the file).

Shapes: the decoder cascade correlates at pyramid levels 6..2; for the
bench's 256x256 two-stream config that is 4x4 (level 6) up to 64x64
(level 2, the hottest volume), with a 64-pair batch (one 65-frame I3D
stack). The 32x32 level-3 tier is the boundary case just under the
default threshold.
"""
import json
import os
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from video_features_tpu.ops.correlation import local_correlation  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _timed_us(method: str, f1, f2, k: int = 50) -> float:
    """Amortized per-call µs: K chained calls in one jitted scan."""

    @jax.jit
    def fn(a, b):
        def body(carry, _):
            acc, a = carry
            out = local_correlation(a, b, method=method)
            return (acc + jnp.sum(out), jnp.roll(a, 1, axis=0)), None

        (acc, _), _ = jax.lax.scan(body, (0.0, a), None, length=k)
        return acc

    float(fn(f1, f2))  # compile
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        float(fn(f1, f2))
        best = min(best, time.perf_counter() - t0)
    return best / k * 1e6


def validate(n: int, c: int, hw: int) -> dict:
    rng = np.random.RandomState(0)
    f1 = jnp.asarray(rng.randn(n, c, hw, hw).astype(np.float32))
    f2 = jnp.asarray(rng.randn(n, c, hw, hw).astype(np.float32))

    t0 = time.perf_counter()
    out = jax.jit(lambda a, b: local_correlation(a, b, method="pallas"))(f1, f2)
    out.block_until_ready()
    print(f"{n}x{c}x{hw}x{hw} pallas compile+run: "
          f"{time.perf_counter() - t0:.2f} s", flush=True)
    ref = np.asarray(
        jax.jit(lambda a, b: local_correlation(a, b, method="xla"))(f1, f2)
    )
    err = float(np.abs(np.asarray(out) - ref).max())
    print(f"{n}x{c}x{hw}x{hw} max abs diff: {err:.2e}", flush=True)
    assert err < 1e-4, err

    t_pallas = _timed_us("pallas", f1, f2)
    t_xla = _timed_us("xla", f1, f2)
    print(f"{n}x{c}x{hw}x{hw} amortized: pallas {t_pallas:.1f} us, "
          f"xla {t_xla:.1f} us, speedup {t_xla / t_pallas:.2f}x", flush=True)
    return {
        "shape": [n, c, hw, hw],
        "hw": hw * hw,
        "pallas_us": round(t_pallas, 1),
        "xla_us": round(t_xla, 1),
        "speedup": round(t_xla / t_pallas, 3),
    }


def main() -> None:
    assert jax.default_backend() == "tpu", jax.default_backend()
    tiers = [
        validate(4, 64, 16),    # level 4-ish, small grid compiles first
        validate(64, 64, 32),   # level 3 at full pair batch
        validate(64, 32, 64),   # level 2, the hottest volume
    ]
    # measured routing threshold: the smallest H*W from which the kernel
    # wins AT EVERY tier upward (monotone suffix, 5% margin — one noisy
    # small-tier win must not route larger shapes the data says are
    # slower on Pallas); wins nowhere -> impossible threshold, XLA keeps
    # every shape
    tiers.sort(key=lambda t: t["hw"])
    pallas_min_hw = 1 << 30
    for i, t in enumerate(tiers):
        if all(u["speedup"] > 1.05 for u in tiers[i:]):
            pallas_min_hw = t["hw"]
            break
    routing = {
        "pallas_min_hw": pallas_min_hw,
        # device_kind scopes the measurement to this hardware generation:
        # ops/correlation.py ignores the file on a different kind
        "device_kind": jax.devices()[0].device_kind,
        "evidence": {
            "backend": str(jax.devices()[0]),
            "tiers": tiers,
        },
    }
    path = os.path.join(REPO, "corr_routing.json")
    with open(path, "w") as f:
        json.dump(routing, f, indent=1)
    print(f"routing threshold pallas_min_hw={pallas_min_hw} -> {path} "
          "(commit it)", flush=True)
    print("all tiers ok", flush=True)


if __name__ == "__main__":
    main()
