#!/usr/bin/env bash
# One-shot static-quality gate: graftcheck lints + ruff + the analysis
# test tier (seeded-violation fixtures and the GC401 recompilation
# budget). Run from the repo root; exits nonzero on the first failing
# gate. CI's lint job runs exactly this script.
#
#   ./scripts/check.sh            # everything
#   SKIP_PYTEST=1 ./scripts/check.sh   # lints only (sub-second feedback)
#   ./scripts/check.sh --diff origin/main   # incremental: findings on
#                                 # changed lines only (CI's PR mode)
#
# Extra args pass straight to the graftcheck CLI (--rule, --diff,
# --explain, ... — see python -m video_features_tpu.analysis --help).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== graftcheck (python -m video_features_tpu.analysis) =="
JAX_PLATFORMS=cpu python -m video_features_tpu.analysis "$@"

echo
echo "== ruff =="
if command -v ruff >/dev/null 2>&1; then
  # config in pyproject.toml: pyflakes F + targeted bugbear subset
  ruff check video_features_tpu tests bench.py main.py
else
  # this container ships without ruff (and pip installs are off); the
  # config is committed so any env WITH ruff enforces it — CI does.
  echo "ruff not on PATH — skipped (config: pyproject.toml [tool.ruff])"
fi

if [[ "${SKIP_PYTEST:-0}" != "1" ]]; then
  echo
  echo "== pytest -m analysis (fixtures + compile budget) =="
  JAX_PLATFORMS=cpu python -m pytest tests/ -q -m analysis \
    -p no:cacheprovider -p no:randomly
fi

echo
echo "check.sh: all gates passed"
