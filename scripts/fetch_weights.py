#!/usr/bin/env python
"""Fetch a model's public pretrained checkpoint, then convert it.

The extract path itself never touches the network (air-gapped pods);
this script is the opt-in convenience the reference gets from pip
``clip.load`` / torch-hub auto-download (ref models/CLIP/
extract_clip.py:46-63, models/vggish_torch/extract_vggish.py:22-27):

    python scripts/fetch_weights.py CLIP-ViT-B/32 --dest weights/
    python scripts/fetch_weights.py vggish_torch --dest weights/
    python scripts/fetch_weights.py pwc --dest weights/
    python scripts/fetch_weights.py i3d --dest weights/   # rgb + flow

Each entry downloads the SAME file the reference consumes (sources in
docs/weights.md) and invokes scripts/convert_weights.py on it. Models
whose upstream needs an interactive step (RAFT's models.zip, the
torchvision zoo, the TF1 vggish ckpt) print the documented manual
recipe instead of guessing.
"""

from __future__ import annotations

import argparse
import hashlib
import os
import subprocess
import sys
import urllib.request

HERE = os.path.dirname(os.path.abspath(__file__))

# feature_type -> [(url, filename, sha256)]; converter feature_type
# defaults to the key (i3d converts each stream file separately).
# sha256: full 64-hex digest, a torch-hub-style hex PREFIX (matched
# against the digest's head), or None when upstream publishes no hash
# (verified-size-only, warned loudly — advisor r4: a truncated-but-
# nonempty download must not sail into convert_weights).
SOURCES = {
    "CLIP-ViT-B/32": [(
        # the CLIP blob URLs embed their own sha256 path component
        "https://openaipublic.azureedge.net/clip/models/"
        "40d365715913c9da98579312b702a82c18be219cc2a73407c4526f58eba950af/"
        "ViT-B-32.pt",
        "ViT-B-32.pt",
        "40d365715913c9da98579312b702a82c18be219cc2a73407c4526f58eba950af",
    )],
    "CLIP-ViT-B/16": [(
        "https://openaipublic.azureedge.net/clip/models/"
        "5806e77cd80f8b59890b7e101eabd078d9fb84e6937f9e85e4ecb61988df416f/"
        "ViT-B-16.pt",
        "ViT-B-16.pt",
        "5806e77cd80f8b59890b7e101eabd078d9fb84e6937f9e85e4ecb61988df416f",
    )],
    "vggish_torch": [(
        "https://github.com/harritaylor/torchvggish/releases/download/"
        "v0.1/vggish-10086976.pth",
        "vggish-10086976.pth",
        "10086976",  # torch-hub convention: filename carries the digest head
    )],
    "pwc": [(
        # https first (advisor r4); upstream publishes no digest — record
        # one locally after a trusted first download if you need pinning
        "https://content.sniklaus.com/github/pytorch-pwc/"
        "network-default.pytorch",
        "network-default.pytorch",
        None,
    )],
    "i3d": [
        (
            "https://github.com/hassony2/kinetics_i3d_pytorch/raw/master/"
            "model/model_rgb.pth",
            "model_rgb.pth",
            None,  # upstream publishes no digest
        ),
        (
            "https://github.com/hassony2/kinetics_i3d_pytorch/raw/master/"
            "model/model_flow.pth",
            "model_flow.pth",
            None,  # upstream publishes no digest
        ),
    ],
}

MANUAL = {
    "raft": "download princeton-vl/RAFT's models.zip and unzip "
            "raft-sintel.pth — see docs/weights.md",
    "resnet18": "torchvision zoo — see docs/weights.md",
    "resnet50": "torchvision zoo — see docs/weights.md",
    "r21d_rgb": "torchvision zoo — see docs/weights.md",
    "vggish": "TF1 AudioSet ckpt needs a TF export step — see docs/weights.md",
}


def _verify_ok(path: str, sha256) -> bool:
    """True if ``path`` matches the full digest / hex prefix (or, with no
    published digest, is at least non-empty). On failure the file is
    removed (so the caller can re-download) and the reason printed."""
    if sha256 is None:
        if os.path.getsize(path) > 0:
            print(f"WARNING: no published sha256 for {os.path.basename(path)}"
                  " — only checked the download is non-empty")
            return True
        os.remove(path)
        print(f"empty download removed: {path}")
        return False
    digest = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            digest.update(chunk)
    got = digest.hexdigest()
    if not got.startswith(sha256.lower()):
        os.remove(path)
        print(f"sha256 mismatch for {path}: got {got}, want {sha256}[...] — "
              "tampered or truncated file removed")
        return False
    print(f"sha256 ok: {os.path.basename(path)} ({sha256[:16]}...)")
    return True


def fetch(url: str, dest: str, opener=None, sha256=None) -> str:
    """Download ``url`` to ``dest`` (skip if present AND verified);
    return the path."""
    if opener is None:  # resolved at call time so tests can monkeypatch
        opener = urllib.request.urlopen
    if os.path.exists(dest) and os.path.getsize(dest) > 0 and _verify_ok(dest, sha256):
        # a stale/truncated leftover fails _verify_ok, which removes it —
        # falling through to a fresh download in THIS run
        print(f"already present: {dest}")
        return dest
    print(f"fetching {url}")
    tmp = dest + ".part"
    with opener(url) as r, open(tmp, "wb") as f:
        while True:
            chunk = r.read(1 << 20)
            if not chunk:
                break
            f.write(chunk)
    os.replace(tmp, dest)  # atomic: no truncated file left behind on Ctrl-C
    if not _verify_ok(dest, sha256):
        raise SystemExit(
            f"sha256 mismatch on freshly downloaded {dest} — "
            "tampered upstream or corrupted transfer; not converting"
        )
    return dest


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("feature_type", choices=sorted(SOURCES | MANUAL.keys()))
    p.add_argument("--dest", default="weights")
    p.add_argument("--skip-convert", action="store_true",
                   help="download only (convert needs the [convert] extra)")
    args = p.parse_args(argv)

    if args.feature_type in MANUAL:
        print(f"{args.feature_type}: no direct URL — {MANUAL[args.feature_type]}")
        return 1

    os.makedirs(args.dest, exist_ok=True)
    rc = 0
    for url, fname, sha in SOURCES[args.feature_type]:
        src = fetch(url, os.path.join(args.dest, fname), sha256=sha)
        if args.skip_convert:
            continue
        dst = os.path.join(
            args.dest,
            os.path.splitext(fname)[0].replace("/", "-") + ".msgpack",
        )
        cmd = [sys.executable, os.path.join(HERE, "convert_weights.py"),
               "--feature_type", args.feature_type, src, dst]
        print(" ".join(cmd))
        rc |= subprocess.call(cmd)
    return rc


if __name__ == "__main__":
    sys.exit(main())
