#!/usr/bin/env python
"""Convert reference-ecosystem checkpoints to flax ``.msgpack`` once,
ahead of time — the offline analog of the reference's auto-download paths
(pip clip / torch-hub / gcs wget, ref models/vggish_torch/extract_vggish.py:22-27,
SURVEY.md §2 item 21), which a zero-egress TPU host cannot use.

Extractors consume either format at --weights_path; pre-converting skips
the torch-unpickle + layout conversion on every run and drops the torch
dependency from the serving host.

Usage:
  python scripts/convert_weights.py --feature_type resnet50 \
      resnet50-0676ba61.pth resnet50.msgpack
  python scripts/convert_weights.py --feature_type i3d \
      i3d_flow.pt i3d_flow.msgpack
  # orbax checkpoint dir (sharded; mesh/multi-host runs restore each
  # weight directly onto its devices):
  python scripts/convert_weights.py --feature_type CLIP-ViT-B/32 \
      ViT-B-32.pt ./weights/clip_b32_orbax
"""

from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


def convert_fn(feature_type: str):
    """The family's state-dict -> param-tree converter (a closure over any
    per-family config)."""
    from video_features_tpu.config import CLIP_FEATURE_TYPES, RESNET_FEATURE_TYPES

    if feature_type in CLIP_FEATURE_TYPES:
        from video_features_tpu.models.clip.convert import convert_state_dict
        from video_features_tpu.models.clip.model import CONFIGS

        return lambda sd: convert_state_dict(sd, CONFIGS[feature_type].layers)
    if feature_type in RESNET_FEATURE_TYPES:
        from video_features_tpu.models.resnet.convert import convert_state_dict

        return lambda sd: convert_state_dict(sd, feature_type)
    if feature_type == "r21d_rgb":
        from video_features_tpu.models.r21d.convert import convert_state_dict

        return convert_state_dict
    if feature_type == "raft":
        from video_features_tpu.models.raft.convert import convert_state_dict

        return convert_state_dict
    if feature_type == "pwc":
        from video_features_tpu.models.pwc.convert import convert_state_dict

        return convert_state_dict
    if feature_type == "i3d":
        # one checkpoint per stream, same layout for both (i3d_rgb.pt /
        # i3d_flow.pt); raft/pwc flow-model checkpoints convert separately
        # under their own feature types
        from video_features_tpu.models.i3d.convert import convert_state_dict

        return convert_state_dict
    if feature_type in ("vggish", "vggish_torch"):
        from video_features_tpu.models.vggish.convert import convert_state_dict

        return convert_state_dict
    raise SystemExit(f"unknown feature_type: {feature_type}")


def main() -> None:
    from video_features_tpu.config import FEATURE_TYPES
    from video_features_tpu.parallel.devices import pin_platform

    # conversion is pure host work — never dial a TPU backend for it
    pin_platform("cpu")

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--feature_type", required=True, choices=FEATURE_TYPES)
    ap.add_argument("src", help="source checkpoint (.pt/.pth/.pytorch/.bin/.npz)")
    ap.add_argument(
        "dst",
        help="output: a .msgpack file, or an orbax checkpoint directory — "
        "the sharded format a mesh/multi-host run restores directly onto "
        "its devices",
    )
    ap.add_argument(
        "--format",
        choices=["msgpack", "orbax"],
        default=None,
        help="output format; default infers from dst (.msgpack suffix -> "
        "msgpack, otherwise orbax directory). Pass explicitly when the "
        "dst name would mislead inference (advisor r02: a dotted dir "
        "name like ./weights/clip.b32 infers wrong, and a typo'd "
        "extensionless msgpack path silently became a directory)",
    )
    args = ap.parse_args()

    from video_features_tpu.models.common.weights import load_params, save_orbax

    # validate dst BEFORE the (potentially multi-GB) load+convert
    if args.format is not None:
        as_msgpack = args.format == "msgpack"
    else:
        as_msgpack = args.dst.endswith(".msgpack")
        if not as_msgpack and os.path.splitext(os.path.basename(args.dst))[1]:
            # inference refuses ambiguity: a file-like suffix that isn't
            # .msgpack (.msgpak typo, .ckpt, a dotted dir name) needs the
            # explicit --format
            raise SystemExit(
                f"dst {args.dst!r} has a file-like suffix but isn't "
                f".msgpack — pass --format msgpack or --format orbax"
            )
    if not as_msgpack and os.path.exists(args.dst):
        raise SystemExit(f"orbax dst already exists: {args.dst}")

    params = load_params(args.src, convert_fn(args.feature_type))
    if as_msgpack:
        from flax import serialization

        blob = serialization.msgpack_serialize(params)
        tmp = args.dst + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, args.dst)
    else:
        save_orbax(params, args.dst)
    import jax

    n = sum(x.size for x in jax.tree.leaves(params))
    print(f"{args.src} -> {args.dst}: {n / 1e6:.1f}M params")


if __name__ == "__main__":
    main()
