#!/usr/bin/env bash
# Round-5 relay watcher. Rounds 2-4 all lost their bench windows to the
# dead 127.0.0.1:8083 axon compile helper; we poll from minute zero.
# Every probe is timestamped into PROBE_LOG so an outage round is
# auditable (VERDICT r4 "What's weak" #1), and the moment the relay
# listens we run the staged capture runbook (scripts/on_tunnel_up.sh).
#
# Usage: nohup setsid bash scripts/tunnel_watch.sh > /tmp/tunnel_watch_r05.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
PROBE_LOG=${PROBE_LOG:-/tmp/probe_log_r05.txt}
INTERVAL=${INTERVAL:-60}
DEADLINE=$(( $(date +%s) + ${WATCH_HOURS:-12} * 3600 ))
CAPTURED=0

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if ss -tln | grep -qE '[:.]8083([^0-9]|$)'; then
    if [ "$CAPTURED" -eq 1 ]; then
      echo "$(date -u +%FT%TZ) up (already captured)" >> "$PROBE_LOG"
    else
      echo "$(date -u +%FT%TZ) UP — relay listening, starting capture" >> "$PROBE_LOG"
      # append, never truncate: each attempt's failure output is the audit
      # trail VERDICT r3/r4 asked for — a later attempt must not wipe it
      echo "=== capture attempt $(date -u +%FT%TZ) ===" >> /tmp/on_tunnel_up_r05.log
      bash scripts/on_tunnel_up.sh >> /tmp/on_tunnel_up_r05.log 2>&1
      rc=$?
      echo "$(date -u +%FT%TZ) capture finished rc=$rc" >> "$PROBE_LOG"
      if [ $rc -eq 0 ]; then
        CAPTURED=1
      fi
      # on failure (relay flapped?) keep polling for another window; on
      # success keep logging liveness so the window's extent is auditable
    fi
  else
    echo "$(date -u +%FT%TZ) down" >> "$PROBE_LOG"
  fi
  sleep "$INTERVAL"
done
echo "$(date -u +%FT%TZ) watcher deadline reached (captured=$CAPTURED)" >> "$PROBE_LOG"
[ "$CAPTURED" -eq 1 ]
