#!/usr/bin/env bash
# Round-4 relay watcher. Rounds 2 and 3 both lost their bench windows to
# the dead 127.0.0.1:8083 axon compile helper; this round we poll from
# minute zero. Every probe is timestamped into PROBE_LOG so a third
# outage round is auditable (VERDICT r3 "What's weak" #1), and the
# moment the relay listens we run the staged capture runbook
# (scripts/on_tunnel_up.sh) exactly once.
#
# Usage: nohup setsid bash scripts/tunnel_watch.sh > /tmp/tunnel_watch.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
PROBE_LOG=${PROBE_LOG:-/tmp/probe_log_r04.txt}
INTERVAL=${INTERVAL:-60}
DEADLINE=$(( $(date +%s) + ${WATCH_HOURS:-11} * 3600 ))

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
  if ss -tln | grep -qE '[:.]8083([^0-9]|$)'; then
    echo "$(date -u +%FT%TZ) UP — relay listening, starting capture" >> "$PROBE_LOG"
    # append, never truncate: each attempt's failure output is the audit
    # trail VERDICT r3 asked for — a later attempt must not wipe it
    echo "=== capture attempt $(date -u +%FT%TZ) ===" >> /tmp/on_tunnel_up_r04.log
    bash scripts/on_tunnel_up.sh >> /tmp/on_tunnel_up_r04.log 2>&1
    rc=$?
    echo "$(date -u +%FT%TZ) capture finished rc=$rc" >> "$PROBE_LOG"
    if [ $rc -eq 0 ]; then
      exit 0
    fi
    # capture failed (relay flapped?) — keep polling for another window
  else
    echo "$(date -u +%FT%TZ) down" >> "$PROBE_LOG"
  fi
  sleep "$INTERVAL"
done
echo "$(date -u +%FT%TZ) watcher deadline reached without a successful capture" >> "$PROBE_LOG"
exit 1
