#!/usr/bin/env python
"""One-shot on-chip validation of the Pallas flash-attention kernel.

Run manually on a host with a healthy TPU backend (the kernel's L=4096
Mosaic compile once coincided with an axon compile-helper crash, so it
is kept out of the driver bench path; see docs/tpu.md):

    python scripts/validate_flash_tpu.py

Prints compile + steady-state times for the flash kernel vs the fused
core and asserts 1e-4 agreement at L=4096.
"""
import time

import numpy as np

import jax
import jax.numpy as jnp

from video_features_tpu.ops.attention import attention
from video_features_tpu.ops.pallas.flash_attention import flash_attention


def main() -> None:
    assert jax.default_backend() == "tpu", jax.default_backend()
    N, H, L, d = 1, 12, 4096, 64
    rng = np.random.RandomState(0)
    q, k, v = (
        jnp.asarray(rng.randn(N, H, L, d).astype(np.float32)) for _ in range(3)
    )
    t0 = time.perf_counter()
    out = flash_attention(q, k, v)
    out.block_until_ready()
    print(f"flash compile+run: {time.perf_counter() - t0:.2f} s")
    t0 = time.perf_counter()
    out = np.asarray(flash_attention(q, k, v))
    print(f"flash steady (incl fetch): {time.perf_counter() - t0 :.3f} s")
    fused = jax.jit(attention)
    ref = fused(q, k, v)
    ref.block_until_ready()
    t0 = time.perf_counter()
    ref = np.asarray(fused(q, k, v))
    print(f"fused steady (incl fetch): {time.perf_counter() - t0:.3f} s")
    err = float(np.abs(out - ref).max())
    print(f"max abs diff: {err:.2e}")
    assert err < 1e-4, err
    print("ok")


if __name__ == "__main__":
    main()
