#!/usr/bin/env python
"""One-shot on-chip validation of the Pallas flash-attention kernel.

Run manually on a host with a healthy TPU backend (the kernel's L=4096
Mosaic compile once coincided with an axon compile-helper crash, so it
is kept out of the driver bench path; see docs/tpu.md):

    python scripts/validate_flash_tpu.py

Prints compile + steady-state times for the flash kernel vs the fused
core and asserts 1e-4 agreement at L=4096.
"""
import time

import numpy as np

import jax
import jax.numpy as jnp

from video_features_tpu.ops.attention import attention
from video_features_tpu.ops.pallas.flash_attention import flash_attention


def validate(L: int) -> None:
    N, H, d = 1, 12, 64
    rng = np.random.RandomState(0)
    q, k, v = (
        jnp.asarray(rng.randn(N, H, L, d).astype(np.float32)) for _ in range(3)
    )
    t0 = time.perf_counter()
    out = flash_attention(q, k, v)
    out.block_until_ready()
    print(f"L={L} flash compile+run: {time.perf_counter() - t0:.2f} s", flush=True)
    t0 = time.perf_counter()
    out = np.asarray(flash_attention(q, k, v))
    print(f"L={L} flash steady (incl fetch): {time.perf_counter() - t0 :.3f} s",
          flush=True)
    fused = jax.jit(attention)
    ref = fused(q, k, v)
    ref.block_until_ready()
    t0 = time.perf_counter()
    ref = np.asarray(fused(q, k, v))
    print(f"L={L} fused steady (incl fetch): {time.perf_counter() - t0:.3f} s",
          flush=True)
    err = float(np.abs(out - ref).max())
    print(f"L={L} max abs diff: {err:.2e}", flush=True)
    assert err < 1e-4, err
    print(f"L={L} ok", flush=True)


def main() -> None:
    assert jax.default_backend() == "tpu", jax.default_backend()
    # tiered: the small Mosaic grid compiles first, so if the L=4096
    # compile takes the helper down (observed 2026-07-30) the artifact
    # still proves the kernel's compiled path ran on hardware
    validate(512)
    validate(4096)


if __name__ == "__main__":
    main()
