#!/usr/bin/env python
"""Minimal repro + bisect for the I3D 3D-conv TPU compile crash.

Three rounds running, the axon compile helper died with ``UNAVAILABLE:
TPU backend setup/compile error`` at the I3D warmup (BASELINE.md
round-4 chip log) and took the relay down with it — losing every
not-yet-persisted bench number. This script answers VERDICT r4 next #2:
WHICH part of the I3D graph kills the compiler, and does the
sum-of-2D-convs lowering (``VFT_CONV3D_IMPL=decomposed``,
models/common/layers.py::Conv3DCompat) dodge it?

Every case runs in a CHILD process ordered safest-first, so the first
crash is recorded instead of killing the bisect; after each case the
parent re-checks the relay listener and stops early (recording the
outage) if the helper died. Run on a healthy window via
scripts/on_tunnel_up.sh; output is tee'd to I3D_CONV3D_REPRO.txt.

Case ladder (each is the smallest graph adding one suspect):
  conv_tiny_direct     one 3x3x3 lax conv, 8x56x56        — baseline 3D lowering
  conv_stem_direct     7x7x7 stride-2 asymmetric-pad conv — the I3D stem
  pool_ceil            max_pool_tf (-inf fill, ceil pads) — the pool suspect
  avgpool_277          the (2,7,7) VALID avg pool head
  stem_block_direct    Unit3D stem + pool + 1x1 + 3x3     — composite
  full_i3d_decomposed  whole net, decomposed convs        — the workaround
  full_i3d_direct      whole net, direct convs            — the known crasher
Order within the ladder is least→most risky; the known-fatal full
direct graph goes LAST so the workaround verdict is always captured.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# children are invoked as `python scripts/repro_i3d_conv3d.py --case X`,
# which puts scripts/ (not the repo root) on sys.path
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CASES = [
    "conv_tiny_direct",
    "conv_stem_direct",
    "pool_ceil",
    "avgpool_277",
    "stem_block_direct",
    "full_i3d_decomposed",
    "full_i3d_direct",
]

# tiny-but-representative shapes: small T/H/W so a PASS compiles in
# seconds, but real kernels/strides/padding so the lowering is the one
# the north-star config uses
STEM_IN = (1, 17, 112, 112, 3)
FULL_IN = (1, 17, 224, 224, 3)


def _run_case(name: str) -> None:
    """Child entry: build + jit + execute one case, print PASS line."""
    from video_features_tpu.parallel.devices import pin_platform

    pin_platform()
    import jax
    import jax.numpy as jnp
    import numpy as np

    t0 = time.perf_counter()
    rng = np.random.RandomState(0)

    if name.startswith("full_i3d"):
        os.environ["VFT_CONV3D_IMPL"] = (
            "decomposed" if name.endswith("decomposed") else "direct"
        )
        from video_features_tpu.models.i3d.model import build, init_params

        model = build()
        params = jax.device_put(init_params("rgb"))
        x = jnp.asarray(rng.randn(*FULL_IN).astype(np.float32))
        feats, logits = jax.jit(
            lambda p, x: model.apply({"params": p}, x)
        )(params, x)
        out = float(jnp.sum(feats)) + float(jnp.sum(logits))
    elif name == "conv_tiny_direct":
        x = jnp.asarray(rng.randn(1, 8, 56, 56, 32).astype(np.float32))
        w = jnp.asarray(rng.randn(3, 3, 3, 32, 64).astype(np.float32) * 0.01)
        out = float(
            jnp.sum(
                jax.jit(
                    lambda x, w: jax.lax.conv_general_dilated(
                        x, w, (1, 1, 1), [(1, 1)] * 3,
                        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
                    )
                )(x, w)
            )
        )
    elif name == "conv_stem_direct":
        from video_features_tpu.models.i3d.model import tf_same_pads

        x = jnp.asarray(rng.randn(*STEM_IN).astype(np.float32))
        w = jnp.asarray(rng.randn(7, 7, 7, 3, 64).astype(np.float32) * 0.01)
        pads = tf_same_pads((7, 7, 7), (2, 2, 2))
        out = float(
            jnp.sum(
                jax.jit(
                    lambda x, w: jax.lax.conv_general_dilated(
                        x, w, (2, 2, 2), pads,
                        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
                    )
                )(x, w)
            )
        )
    elif name == "pool_ceil":
        from video_features_tpu.models.i3d.model import max_pool_tf

        x = jnp.asarray(np.abs(rng.randn(1, 9, 56, 56, 64)).astype(np.float32))
        out = float(jnp.sum(jax.jit(
            lambda x: max_pool_tf(x, (3, 3, 3), (2, 2, 2))
        )(x)))
    elif name == "avgpool_277":
        from flax import linen as nn

        x = jnp.asarray(rng.randn(1, 3, 7, 7, 128).astype(np.float32))
        out = float(jnp.sum(jax.jit(
            lambda x: nn.avg_pool(x, (2, 7, 7), strides=(1, 1, 1))
        )(x)))
    elif name == "stem_block_direct":
        os.environ["VFT_CONV3D_IMPL"] = "direct"
        import flax.linen as nn

        from video_features_tpu.models.i3d.model import Unit3D, max_pool_tf

        class Stem(nn.Module):
            @nn.compact
            def __call__(self, x):
                x = Unit3D(64, (7, 7, 7), (2, 2, 2), name="conv3d_1a_7x7")(x)
                x = max_pool_tf(x, (1, 3, 3), (1, 2, 2))
                x = Unit3D(64, name="conv3d_2b_1x1")(x)
                x = Unit3D(192, (3, 3, 3), name="conv3d_2c_3x3")(x)
                return x

        model = Stem()
        x = jnp.asarray(rng.randn(*STEM_IN).astype(np.float32))
        params = model.init(jax.random.PRNGKey(0), x)["params"]
        out = float(jnp.sum(jax.jit(
            lambda p, x: model.apply({"params": p}, x)
        )(params, x)))
    else:
        raise SystemExit(f"unknown case {name}")

    print(
        f"CASE_RESULT {json.dumps({'case': name, 'status': 'PASS', 'sum': out, 'seconds': round(time.perf_counter() - t0, 1), 'backend': jax.default_backend()})}"
    )


def _relay_up() -> bool:
    out = subprocess.run(
        ["ss", "-tln"], capture_output=True, text=True
    ).stdout
    import re

    return bool(re.search(r"[:.]8083([^0-9]|$)", out))


def main() -> int:
    results = []
    for case in CASES:
        if os.environ.get("REPRO_IGNORE_RELAY") != "1" and not _relay_up():
            results.append({"case": case, "status": "SKIP_RELAY_DOWN"})
            print(f"{case}: SKIP — relay died earlier in the ladder")
            continue
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--case", case],
                capture_output=True, text=True,
                timeout=float(os.environ.get("REPRO_CASE_TIMEOUT", "600")),
            )
        except subprocess.TimeoutExpired:
            # a hung child (dead helper behind a live listener) must be a
            # recorded verdict, not a parent-killing traceback — the
            # ladder's whole point is that the first crash is data
            results.append({"case": case, "status": "TIMEOUT"})
            print(f"{case}: TIMEOUT — child hung (dead compile helper?)")
            continue
        rec = None
        for line in reversed((proc.stdout or "").strip().splitlines()):
            if line.startswith("CASE_RESULT "):
                rec = json.loads(line[len("CASE_RESULT "):])
                break
        if rec is None:
            tail = (proc.stderr or "").strip().splitlines()[-4:]
            rec = {
                "case": case,
                "status": f"CRASH rc={proc.returncode}",
                "stderr_tail": " | ".join(tail),
            }
        results.append(rec)
        print(json.dumps(rec))
    print("=== VERDICT TABLE ===")
    for r in results:
        print(f"{r['case']:22s} {r['status']}")
    full = {r["case"]: r["status"] for r in results}
    ok = any(
        full.get(c) == "PASS"
        for c in ("full_i3d_decomposed", "full_i3d_direct")
    )
    if full.get("full_i3d_direct") != "PASS" and full.get("full_i3d_decomposed") == "PASS":
        print("RECOMMENDATION: set VFT_CONV3D_IMPL=decomposed on this backend")
    return 0 if ok else 1


if __name__ == "__main__":
    if len(sys.argv) == 3 and sys.argv[1] == "--case":
        _run_case(sys.argv[2])
        sys.exit(0)
    sys.exit(main())
