#!/usr/bin/env python
"""Produce real-weight golden features for the URL-reachable families
(VERDICT r4 next #4) — run this ON A NETWORKED HOST; this build sandbox
has zero egress (BASELINE.md r5 note: DNS itself fails), so the harness
is committed ready-to-run instead of the goldens.

    python scripts/make_goldens.py --dest weights/ \
        --videos sample/v_GGSY1Qvo990.mp4 --wavs sample/audio.wav

Per family with a public URL (CLIP via the OpenAI blob, vggish_torch via
the GitHub release — the same files the reference auto-downloads, ref
models/CLIP/extract_clip.py:46-63, models/vggish_torch/
extract_vggish.py:22-27):
  1. scripts/fetch_weights.py  (sha256-verified download + conversion)
  2. extract features for each input with the REAL weights
  3. write tests/goldens/<family>_<stem>.npy (a few KB each)

tests/test_real_weight_goldens.py then runs green wherever both the
goldens (committed) and the converted weights (VFT_WEIGHTS_DIR) exist.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

GOLDEN_DIR = os.path.join(REPO, "tests", "goldens")

FAMILIES = {
    # feature_type -> (fetch key, converted weights filename, input kind)
    "CLIP-ViT-B/32": ("CLIP-ViT-B/32", "ViT-B-32.msgpack", "video"),
    "vggish_torch": ("vggish_torch", "vggish-10086976.msgpack", "wav"),
}


def extract(feature_type: str, weights: str, media: str, out_dir: str):
    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.extract.registry import build_extractor

    cfg = ExtractionConfig(
        feature_type=feature_type,
        video_paths=[media],
        weights_path=weights,
        extract_method="uni_12" if feature_type.startswith("CLIP") else None,
        cpu=True,
        tmp_path=os.path.join(out_dir, "tmp"),
        output_path=os.path.join(out_dir, "out"),
    )
    ex = build_extractor(cfg, external_call=True)
    (result,) = ex([0])
    return result


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--dest", default="weights")
    p.add_argument("--videos", nargs="+",
                   default=[os.path.join(REPO, "..", "reference", "sample", f)
                            for f in ("v_GGSY1Qvo990.mp4",
                                      "v_ZNVhz7ctTq0.mp4")])
    p.add_argument("--wavs", nargs="+", default=[],
                   help="16 kHz-or-not wav inputs for vggish_torch")
    args = p.parse_args(argv)

    import numpy as np

    os.makedirs(GOLDEN_DIR, exist_ok=True)
    rc = 0
    if not any(os.path.exists(v) for v in args.videos):
        print("ERROR: none of the input videos exist — pass --videos "
              "pointing at the reference sample clips (the defaults "
              "assume the build sandbox's ../reference/ layout):")
        for v in args.videos:
            print(f"  missing: {v}")
        return 1
    for feature_type, (fetch_key, wfile, kind) in FAMILIES.items():
        print(f"=== {feature_type}")
        r = subprocess.call(
            [sys.executable, os.path.join(HERE, "fetch_weights.py"),
             fetch_key, "--dest", args.dest]
        )
        if r != 0:
            print(f"fetch/convert failed for {feature_type} (rc={r})")
            rc |= r
            continue
        weights = os.path.join(args.dest, wfile)
        media_list = args.videos if kind == "video" else args.wavs
        if kind == "wav" and not media_list:
            # vggish rips audio from video containers when ffmpeg exists —
            # fall back so the documented one-liner produces EVERY golden
            # instead of silently skipping the audio family (r5 review)
            from video_features_tpu.io.ffmpeg import which_ffmpeg

            if which_ffmpeg():
                media_list = args.videos
            else:
                print(f"WARNING: no --wavs given and no ffmpeg to rip audio "
                      f"from the sample videos — NO golden will be written "
                      f"for {feature_type}")
                rc |= 1
                continue
        for media in media_list:
            if not os.path.exists(media):
                print(f"skipping missing input {media}")
                continue
            result = extract(feature_type, weights, media, args.dest)
            key = [k for k in result if k not in ("fps", "timestamps_ms")][0]
            feats = np.asarray(result[key], dtype=np.float32)
            stem = os.path.splitext(os.path.basename(media))[0]
            name = f"{feature_type.replace('/', '-')}_{stem}.npy"
            path = os.path.join(GOLDEN_DIR, name)
            np.save(path, feats)
            print(f"golden: {path} {feats.shape} "
                  f"mean={feats.mean():.4f} std={feats.std():.4f}")
    print("commit tests/goldens/*.npy and run "
          "VFT_WEIGHTS_DIR=<dest> pytest tests/test_real_weight_goldens.py")
    return rc


if __name__ == "__main__":
    sys.exit(main())
