#!/usr/bin/env bash
# Chip-work runbook for when the axon relay returns after an outage
# (BASELINE.md "Round-2 outage note"; rounds 2 AND 3 both lost bench
# windows to the dead 127.0.0.1:8083 compile helper). Order matters:
# the cheap probe first, then the BENCH capture (the round's must-have
# artifact), then the riskier one-off validations — the flash L=4096
# Mosaic compile has crashed the helper before, so it goes LAST and its
# result is recorded even if the helper dies right after.
#
# Usage: bash scripts/on_tunnel_up.sh  (from the repo root)
set -u -o pipefail
cd "$(dirname "$0")/.."

echo "== 1/3 probe =="
# anchored: a listener on e.g. :18083 must not read as the relay's :8083
ss -tln | grep -qE '[:.]8083([^0-9]|$)' || {
  echo "relay not listening on 8083; abort"; exit 1; }
timeout 120 python -c "import jax; print('devices:', jax.devices())" || {
  echo "jax.devices() hung/failed despite the listener; abort"; exit 1; }

echo "== 2/3 bench (both north-star configs) =="
# the final line is the JSON artifact; persist it INTO THE REPO so a
# successful capture survives any later helper crash (r04: the first
# window's CLIP numbers died with the process on the I3D compile —
# bench.py is now subprocess-isolated per part, but the copy costs
# nothing and makes the evidence durable either way)
# BENCH_BF16=1: the r4 story is mixed precision — capture the bf16 CLIP
# e2e variant too (one extra XLA compile; the i3d bf16 figures are
# already part of bench_i3d_device_only)
BENCH_BF16=1 python bench.py | tee /tmp/bench_r04_local.json || {
  echo "bench FAILED (rc=$?) — no numbers captured; NOT proceeding to the"
  echo "helper-crash-risk flash compile. Re-run when the relay is stable."
  exit 1; }
tail -n 1 /tmp/bench_r04_local.json > BENCH_r04_local.json
echo "bench JSON persisted to BENCH_r04_local.json (commit it)"

echo "== 3/3 one-off on-chip validations (riskiest compile last) =="
python scripts/validate_flash_tpu.py \
  | tee FLASH_TPU_VALIDATION.txt || echo "flash validation failed"
echo "done — record FLASH_TPU_VALIDATION.txt + bench JSONs in the repo"
