#!/usr/bin/env bash
# Chip-work runbook for when the axon relay returns after an outage
# (BASELINE.md outage notes; rounds 2-4 all lost bench windows to the
# dead 127.0.0.1:8083 compile helper). Ordered by value/risk: the cheap
# probe, then the BENCH capture (the round's must-have artifact, run
# with the safe decomposed conv3d lowering and its direct-lowering
# diagnostic DISABLED), then the Pallas validations, and the I3D
# compile-crash repro ladder DEAD LAST — its final case is the direct
# 3D-conv compile that killed the helper (and the relay) in r2-r4.
#
# Usage: bash scripts/on_tunnel_up.sh  (from the repo root)
set -u -o pipefail
cd "$(dirname "$0")/.."

echo "== 1/4 probe =="
# anchored: a listener on e.g. :18083 must not read as the relay's :8083
ss -tln | grep -qE '[:.]8083([^0-9]|$)' || {
  echo "relay not listening on 8083; abort"; exit 1; }
timeout 120 python -c "import jax; print('devices:', jax.devices())" || {
  echo "jax.devices() hung/failed despite the listener; abort"; exit 1; }

echo "== 2/4 bench (both north-star configs) =="
# bench.py prints a complete-so-far JSON line after the headline and
# after EVERY sub-part (r5): the LAST parseable line in the tee'd file
# is always the fullest artifact, even if the helper dies mid-run.
# BENCH_DIRECT_PROBE=0: the repro ladder below owns that experiment.
BENCH_DIRECT_PROBE=0 python bench.py | tee /tmp/bench_r05_local.json
rc=$?
# persist the last JSON line into the repo regardless of rc — partial
# numbers from a crashed run are still driver-grade evidence
grep -E '^\{' /tmp/bench_r05_local.json | tail -n 1 > BENCH_r05_local.json || true
# SUCCESS means device numbers, not just a parseable line: bench.py
# exits 0 with only host numbers when the backend is unreachable
# (extra.fatal in-band) — that must NOT mark the window captured, or the
# watcher stops retrying with nothing on chip.
python - <<'PY'
import json, sys
try:
    art = json.load(open("BENCH_r05_local.json"))
except Exception:
    sys.exit(1)
extra = art.get("extra", {})
ok = art.get("value") is not None and "fatal" not in extra
sys.exit(0 if ok else 1)
PY
have_device_numbers=$?
if [ $have_device_numbers -eq 0 ]; then
  echo "bench JSON with device numbers persisted to BENCH_r05_local.json (commit it)"
else
  echo "bench rc=$rc but artifact has NO device numbers — window lost;"
  echo "rc=1 so the watcher retries on the next healthy window."
  exit 1
fi

echo "== 3/4 Pallas on-chip validations =="
python scripts/validate_corr_tpu.py | tee CORR_TPU_VALIDATION.txt \
  || echo "correlation validation failed"
python scripts/validate_flash_tpu.py \
  | tee FLASH_TPU_VALIDATION.txt || echo "flash validation failed"

echo "== 4/4 I3D 3D-conv repro ladder (relay-killer case last) =="
# done-marker: a ladder that reached a real verdict on the decisive
# full-net cases is never re-run, so a deterministic helper-killer can't
# burn later windows re-proving itself. The marker requires an actual
# PASS/CRASH/TIMEOUT on a full_i3d_* case — a ladder aborted by a relay
# flap (all SKIP_RELAY_DOWN) still prints the table header and must NOT
# count as done.
if grep -Eq 'full_i3d_(decomposed|direct) +(PASS|CRASH|TIMEOUT)' I3D_CONV3D_REPRO.txt 2>/dev/null; then
  echo "repro already completed (I3D_CONV3D_REPRO.txt) — skipping"
else
  timeout 3600 python scripts/repro_i3d_conv3d.py | tee I3D_CONV3D_REPRO.txt \
    || echo "repro ladder rc!=0 (verdicts above are still the data)"
fi
echo "done — commit BENCH_r05_local.json + *_VALIDATION.txt +"
echo "I3D_CONV3D_REPRO.txt + corr_routing.json (measured auto-routing)"
