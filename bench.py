#!/usr/bin/env python
"""End-to-end throughput benchmarks for both BASELINE.md north-star
configs, printed as ONE JSON line.

- headline: videos/sec/chip, CLIP-ViT-B/32 uni_12 (decode -> preprocess ->
  encode -> fetch), comparable round over round (BENCH_r01 = 3.637 on the
  real chip).
- extra.i3d_raft_vps: videos/sec/chip for the deep pipeline — I3D rgb+flow
  over 64-frame stacks with RAFT (20 GRU iters) computing flow on the fly.
- extra.pallas_corr_speedup_vs_xla: the PWC cost-volume microbench, Pallas
  VMEM-tiled kernel vs the XLA shifted-reduce formulation (TPU backends
  only; omitted on CPU where the Pallas kernel has no fast path).
- extra.clip_bf16_vps (default-on since r5; BENCH_BF16=0 to skip the
  second compile): the CLIP config re-run under --dtype bfloat16.

Every part runs in a child process and the complete-so-far JSON line is
re-printed after each one — consumers should take the LAST parseable
stdout line. A dead tunnel no longer zeroes the artifact: host-side
numbers are measured before the backend probe, and the probe failure is
recorded in-band under extra.fatal.

``vs_baseline`` ratios divide by MEASURED numbers — the reference's own
torch code timed on this host's CPU by scripts/measure_baseline.py
(provenance in BASELINE.md; the reference cannot run on TPU and publishes
no numbers of its own, BASELINE.md "Published reference numbers"). Set
BENCH_MEASURE_BASELINE=1 to re-measure them live instead of using the
recorded values.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np

# Measured by scripts/measure_baseline.py (reference torch code on this
# host's CPU — a SINGLE core on the bench VM; the reference's CUDA/cupy
# path cannot run here at all). Provenance in BASELINE.md "Measured
# baselines"; re-measure with BENCH_MEASURE_BASELINE=1.
MEASURED_BASELINES = {
    # 2026-07-30, host 'vm', 1 CPU core, best-of-N (same methodology as
    # bench.py's passes — advisor r02 symmetry fix; the r02-era numbers
    # were single-pass: clip 0.91, i3d 0.0029)
    "clip_torch_cpu_vps": 0.8548,
    "i3d_raft_torch_cpu_vps": 0.0031,  # ~323 s/video (140 frames, 2 stacks)
}


def _load_measured_baselines() -> dict:
    if os.environ.get("BENCH_MEASURE_BASELINE") == "1":
        import subprocess

        argv = [sys.executable, os.path.join(os.path.dirname(__file__),
                                             "scripts", "measure_baseline.py"),
                "--videos", os.environ.get("BENCH_VIDEOS", "16")]
        if os.environ.get("BENCH_SKIP_I3D") == "1":
            argv.append("--skip-i3d")
        out = subprocess.run(
            argv, capture_output=True, text=True, check=True,
        ).stdout.strip().splitlines()[-1]
        return json.loads(out)
    return MEASURED_BASELINES


# the headline CLIP config's sampler — one constant shared by the run and
# its bench_config record
CLIP_EXTRACT_METHOD = "uni_12"


def _clip_group(n_videos: int) -> int:
    """--video_batch for the headline run: capped at 8, never exceeding
    the video count (a chronically-partial group pads to the full shape
    and would burn that compute for nothing). ONE definition shared by
    main's bench_config record and the clip sub-parts so the recorded
    knob is always the one the measurement used."""
    return min(8, max(n_videos, 1))
# I3D window stacks fused per device call (the bench video yields 2)
I3D_STACK_BATCH = 2
# both north-star synth workloads, shared by main() and the --sub parts
CLIP_SPEC = dict(n_frames=120, width=640, height=360)
I3D_SPEC = dict(n_frames=140, width=256, height=256)
# standalone-flow workload: small enough that the RAFT recurrence doesn't
# dominate the child's timeout on CPU smokes, big enough that the /8
# padder grid (240, 320) -> (240, 320) is a real shape
FLOW_SPEC = dict(n_frames=24, width=320, height=240)
# clip_mixed corpus: (h, w) pairs chosen so each input bucket holds TWO
# distinct source resolutions: (360,640)/(352,620) -> (384,640);
# (240,426)/(232,420) -> (256,448)
MIXED_SPECS = [(360, 640), (352, 620), (240, 426), (232, 420)] * 2


def _device_contract_ids() -> dict:
    """The device-preprocess output contracts the bench workloads land
    on, plus the input-bucket histogram of the mixed corpus — how many
    executables each workload compiles (recorded so a bucket-geometry
    change shows up in the artifact, not just in the timings)."""
    from collections import Counter

    from video_features_tpu.models.pwc.model import internal_grid
    from video_features_tpu.models.raft.model import input_grid
    from video_features_tpu.ops.resize import resized_hw
    from video_features_tpu.ops.window import flow_output_bucket, spatial_bucket

    ih, iw = I3D_SPEC["height"], I3D_SPEC["width"]
    fh, fw = FLOW_SPEC["height"], FLOW_SPEC["width"]
    oh, ow = resized_hw(ih, iw, 256)
    return {
        "i3d_flow_output_bucket": list(flow_output_bucket(oh, ow)),
        "flow_raft_padder_grid": list(input_grid(fh, fw)),
        "flow_pwc_internal_grid": list(internal_grid(fh, fw)),
        "mixed_input_bucket_histogram": dict(
            sorted(
                Counter(
                    str(spatial_bucket(h, w)) for h, w in MIXED_SPECS
                ).items()
            )
        ),
    }


def _pass_stats(n_items: int, times: list) -> dict:
    """videos/s per pass -> {best, median, passes}. Best is the headline
    (tunnel latency varies minute to minute and only ADDS time — the best
    pass is the machine's capability); median + the raw passes ship
    alongside so round-over-round deltas can't be flattered by one lucky
    pass (VERDICT r02 'What's weak' #7)."""
    vps = sorted(n_items / t for t in times)
    mid = len(vps) // 2
    median = vps[mid] if len(vps) % 2 else 0.5 * (vps[mid - 1] + vps[mid])
    return {
        "best": round(vps[-1], 3),
        "median": round(median, 3),
        "passes": [round(v, 3) for v in vps],
    }


def bench_clip(
    n_videos: int,
    video: str,
    tmp: str,
    dtype: str = "float32",
    video_batch: int = 1,
    preprocess: str = "host",
    videos: list = None,
) -> dict:
    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP
    from video_features_tpu.parallel.devices import resolve_devices

    video_paths = list(videos) if videos else [video] * n_videos
    n_videos = len(video_paths)
    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="CLIP-ViT-B/32",
        video_paths=video_paths,
        extract_method=CLIP_EXTRACT_METHOD,
        dtype=dtype,
        video_batch=video_batch,
        preprocess=preprocess,
        tmp_path=os.path.join(tmp, "t"),
        output_path=os.path.join(tmp, "o"),
    )
    ex = ExtractCLIP(cfg, external_call=True)
    ex.progress.disable = True
    device = resolve_devices(cfg)[0]
    # warmup: decode path + XLA compile. Two videos (not one: a single
    # index takes the serial non-pipelined path, which dispatches
    # per-video shapes) so the aggregated run's partial flush pads to the
    # full (video_batch*bucket) shape — the same executable the timed
    # groups use.
    ex(range(min(2, n_videos)), device=device)
    # telemetry spans from the timed passes only (seq0 fences off the
    # warmup, whose compile-dominated dispatch spans would skew the
    # overlap-efficiency report)
    seq0 = max((r["seq"] for r in ex.telemetry.spans()), default=0)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        results = ex(range(n_videos), device=device)
        times.append(time.perf_counter() - t0)
    assert len(results) == n_videos and all(
        r["CLIP-ViT-B/32"].shape == (12, 512) for r in results
    )
    stats = _pass_stats(n_videos, times)
    from video_features_tpu.runtime.telemetry import overlap_report

    rep = overlap_report([r for r in ex.telemetry.spans() if r["seq"] > seq0])
    stats["overlap"] = {
        k: (round(v, 4) if isinstance(v, float) else v) for k, v in rep.items()
    }
    return stats


def bench_i3d_raft(
    video: str, tmp: str, flow_type: str = "raft", preprocess: str = "host"
) -> float:
    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D
    from video_features_tpu.parallel.devices import resolve_devices

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="i3d",
        flow_type=flow_type,
        video_paths=[video],
        # --batch_size 2: both of the video's 64-frame stacks fuse into
        # one RAFT+I3D dispatch (models/i3d stack batching)
        batch_size=I3D_STACK_BATCH,
        preprocess=preprocess,
        tmp_path=os.path.join(tmp, "t" + flow_type + preprocess),
        output_path=os.path.join(tmp, "o" + flow_type + preprocess),
    )
    ex = ExtractI3D(cfg, external_call=True)
    ex.progress.disable = True
    device = resolve_devices(cfg)[0]
    ex([0], device=device)  # warmup: RAFT scan + two I3D towers compile
    times = []
    for _ in range(2):  # 2 passes: tunnel/host variance (see _pass_stats)
        t0 = time.perf_counter()
        (r,) = ex([0], device=device)
        times.append(time.perf_counter() - t0)
    assert r["rgb"].shape[1] == 1024 and r["flow"].shape[1] == 1024
    return _pass_stats(1, times)


def bench_flow(
    video: str, tmp: str, flow_type: str = "raft", preprocess: str = "host",
    dtype: str = "float32",
) -> dict:
    """Standalone flow extraction (RAFT/PWC pair streaming) — the
    --preprocess device comparison rides the InputPadder-grid /
    exact-shape contracts (models/common/flow_extract.py)."""
    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.models.pwc.extract_pwc import ExtractPWC
    from video_features_tpu.models.raft.extract_raft import ExtractRAFT
    from video_features_tpu.parallel.devices import resolve_devices

    cls = ExtractRAFT if flow_type == "raft" else ExtractPWC
    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type=flow_type,
        video_paths=[video],
        batch_size=8,
        preprocess=preprocess,
        dtype=dtype,
        tmp_path=os.path.join(tmp, "ft" + flow_type + preprocess + dtype),
        output_path=os.path.join(tmp, "fo" + flow_type + preprocess + dtype),
    )
    ex = cls(cfg, external_call=True)
    ex.progress.disable = True
    device = resolve_devices(cfg)[0]
    ex([0], device=device)  # warmup compile
    times = []
    for _ in range(2):
        t0 = time.perf_counter()
        (r,) = ex([0], device=device)
        times.append(time.perf_counter() - t0)
    assert r[flow_type].shape[0] == FLOW_SPEC["n_frames"] - 1
    return _pass_stats(1, times)


def bench_i3d_short_corpus(videos, tmp: str, video_batch: int) -> dict:
    """The reference's worst case: a corpus of SHORT clips (one 65-frame
    stack each) on the deepest pipeline, one tiny dispatch per video.
    --video_batch fuses stacks across videos into the --batch_size group
    executable (r4); video_batch=1 is the solo comparison."""
    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D
    from video_features_tpu.parallel.devices import resolve_devices

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="i3d",
        flow_type="raft",
        video_paths=list(videos),
        batch_size=I3D_STACK_BATCH,
        video_batch=video_batch,
        tmp_path=os.path.join(tmp, f"t{video_batch}"),
        output_path=os.path.join(tmp, f"o{video_batch}"),
    )
    ex = ExtractI3D(cfg, external_call=True)
    ex.progress.disable = True
    device = resolve_devices(cfg)[0]
    ex(range(len(videos)), device=device)  # warmup compile
    times = []
    for _ in range(2):
        t0 = time.perf_counter()
        rs = ex(range(len(videos)), device=device)
        times.append(time.perf_counter() - t0)
    assert len(rs) == len(videos)
    assert all(r["rgb"].shape == (1, 1024) for r in rs)
    return _pass_stats(len(videos), times)


def bench_pallas_corr() -> dict:
    """PWC 81-channel cost volume: Pallas kernel vs XLA formulation on the
    hottest PWC shape (level 2: 64 pairs, 32ch, 64x64 — the level 'auto'
    routes to the Pallas kernel). K calls chain inside one jitted scan so
    per-dispatch tunnel latency (~25 ms on axon) doesn't swamp the
    kernel-scale times."""
    import jax
    import jax.numpy as jnp

    from video_features_tpu.ops.correlation import local_correlation

    if jax.default_backend() != "tpu":
        return {}
    N, C, H, W = 64, 32, 64, 64
    K = 50
    rng = np.random.RandomState(0)
    f1 = jnp.asarray(rng.randn(N, C, H, W).astype(np.float32))
    f2 = jnp.asarray(rng.randn(N, C, H, W).astype(np.float32))

    def timed(method):
        @jax.jit
        def fn(a, b):
            def body(carry, _):
                acc, a = carry
                out = local_correlation(a, b, method=method)
                return (acc + jnp.sum(out), jnp.roll(a, 1, axis=0)), None

            (acc, _), _ = jax.lax.scan(body, (0.0, a), None, length=K)
            return acc

        float(fn(f1, f2))  # compile
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            float(fn(f1, f2))
            best = min(best, time.perf_counter() - t0)
        return best / K

    t_pallas, t_xla = timed("pallas"), timed("xla")
    return {
        "pallas_corr_us": round(t_pallas * 1e6, 1),
        "xla_corr_us": round(t_xla * 1e6, 1),
        "pallas_corr_speedup_vs_xla": round(t_xla / t_pallas, 3),
    }


def bench_flash_attention() -> dict:
    """Long-sequence attention: Pallas flash kernel vs the fused
    full-score-matrix core at L=4096, d=64, 12 heads (the single-chip
    long-context core; ring attention runs the same recurrence across
    chips). K calls chained in one jitted scan, per bench_pallas_corr."""
    import jax
    import jax.numpy as jnp

    from video_features_tpu.ops.attention import attention
    from video_features_tpu.ops.pallas.flash_attention import flash_attention

    if jax.default_backend() != "tpu":
        return {}
    N, H, L, d = 1, 12, 4096, 64
    K = 20
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(N, H, L, d).astype(np.float32))
    k = jnp.asarray(rng.randn(N, H, L, d).astype(np.float32))
    v = jnp.asarray(rng.randn(N, H, L, d).astype(np.float32))

    def timed(core):
        @jax.jit
        def fn(q, k, v):
            def body(carry, _):
                acc, q = carry
                out = core(q, k, v)
                return (acc + jnp.sum(out), jnp.roll(q, 1, axis=2)), None

            (acc, _), _ = jax.lax.scan(body, (0.0, q), None, length=K)
            return acc

        float(fn(q, k, v))  # compile
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            float(fn(q, k, v))
            best = min(best, time.perf_counter() - t0)
        return best / K

    t_flash = timed(flash_attention)
    t_fused = timed(attention)
    return {
        "flash_attn_us": round(t_flash * 1e6, 1),
        "fused_attn_us": round(t_fused * 1e6, 1),
        "flash_attn_speedup_vs_fused": round(t_fused / t_flash, 3),
    }


def bench_host_pipeline() -> dict:
    """Host-side decode/preprocess frames/s — the NON-chip half of the
    end-to-end gap (VERDICT r03 next #7). Reported next to the
    device-only numbers so `end-to-end vs device-only` deltas attribute
    to host vs tunnel vs chip. Pure host CPU: runs identically whether
    the relay is alive or not."""
    from concurrent.futures import ThreadPoolExecutor

    from PIL import Image

    from video_features_tpu.io.video import stream_frames
    from video_features_tpu.ops.preprocess import (
        normalize_chw,
        pil_center_crop,
        pil_resize,
        to_float_chw,
    )
    from video_features_tpu.utils.synth import synth_video

    from video_features_tpu import native

    # the denominator every thread-scaling curve below divides into:
    # on a 1-core container no fan-out can win, and native's
    # _resolve_threads clamps accordingly (the dead-knob fix)
    out = {"host_cpu_count": native.cpu_budget()}
    with tempfile.TemporaryDirectory() as tmp:
        video = synth_video(os.path.join(tmp, "host.mp4"), **CLIP_SPEC)

        def decode_all(backend):
            n = 0
            for _f, _ts in stream_frames(video, None, backend):
                n += 1
            return n

        for backend in ("cv2", "native"):
            try:
                decode_all(backend)  # warm: page cache + lazy lib build
                t0 = time.perf_counter()
                n = decode_all(backend)
                out[f"host_decode_{backend}_fps"] = round(
                    n / (time.perf_counter() - t0), 1
                )
            except Exception as e:  # noqa: BLE001 - native lib may not build
                out[f"host_decode_{backend}_error"] = repr(e)

        # --decode_workers scaling: W threads decoding 4 streams — the
        # actual shape of the async pipeline's host stage (parallelism is
        # across videos, not within one)
        for w in (1, 2, 4):
            t0 = time.perf_counter()
            with ThreadPoolExecutor(w) as pool:
                ns = list(pool.map(lambda _i: decode_all("cv2"), range(4)))
            out[f"host_decode_workers_{w}_fps"] = round(
                sum(ns) / (time.perf_counter() - t0), 1
            )

    # CLIP 224 preprocess: the pip-clip-exact PIL chain vs the C++ batch
    rng = np.random.RandomState(0)
    frames = rng.randint(
        0, 255, (32, CLIP_SPEC["height"], CLIP_SPEC["width"], 3), dtype=np.uint8
    )
    mean = (0.48145466, 0.4578275, 0.40821073)
    std = (0.26862954, 0.26130258, 0.27577711)

    def pil_chain():
        for f in frames:
            img = pil_center_crop(
                pil_resize(f, 224, interpolation=Image.BICUBIC), 224
            )
            normalize_chw(to_float_chw(img), mean, std)

    pil_chain()  # warm
    t0 = time.perf_counter()
    pil_chain()
    out["host_preprocess_pil_fps"] = round(
        len(frames) / (time.perf_counter() - t0), 1
    )
    # PIL-chain thread scaling: --decode_workers runs this chain on W
    # threads; PIL/numpy release the GIL for the heavy ops, but the
    # measured curve (not an assumption) is what sizes workers-per-chip
    # (VERDICT r4 next #5)
    for w in (2, 4):
        t0 = time.perf_counter()
        with ThreadPoolExecutor(w) as pool:
            list(pool.map(lambda _i: pil_chain(), range(w)))
        out[f"host_preprocess_pil_{w}thread_fps"] = round(
            w * len(frames) / (time.perf_counter() - t0), 1
        )
    try:
        from video_features_tpu import native

        native.clip_preprocess_batch(frames, size=224)  # warm + build
        # legacy key keeps its historical meaning: threads=0 (auto =
        # min(cpu_count, 16)); the explicit thread counts get new keys so
        # round-over-round comparisons stay apples-to-apples
        t0 = time.perf_counter()
        native.clip_preprocess_batch(frames, size=224)
        out["host_preprocess_native_fps"] = round(
            len(frames) / (time.perf_counter() - t0), 1
        )
        for threads in (1, 2, 4):
            t0 = time.perf_counter()
            native.clip_preprocess_batch(frames, size=224, threads=threads)
            out[f"host_preprocess_native_{threads}thread_fps"] = round(
                len(frames) / (time.perf_counter() - t0), 1
            )
    except Exception as e:  # noqa: BLE001 - native lib may not build
        out["host_preprocess_native_error"] = repr(e)
    return {"host_pipeline": out}


# v5e peak: 197 TFLOP/s bf16 per chip (the MXU's native dtype; fp32
# matmuls pass through the MXU slower — both MFU figures below are
# reported against THIS number so they compare on one scale).
V5E_BF16_PEAK_FLOPS = 197e12


def _device_only_gate() -> tuple:
    """(run, forced): the device-only bodies run on the chip, or anywhere
    under BENCH_FORCE_DEVICE_ONLY=1 — a CPU smoke at tiny shapes so the
    model-building wrapper code around the unit-tested timing core never
    executes for the first time during the precious tunnel window
    (VERDICT r03 weak #6). Forced numbers are smoke-only, never reported
    as chip figures: callers must drop/label them when forced is True."""
    import jax

    forced = os.environ.get("BENCH_FORCE_DEVICE_ONLY") == "1"
    return (jax.default_backend() == "tpu" or forced), forced


def _time_device_only(step_fn, args, k: int):
    """Shared chip-only timing harness: XLA's FLOP count for one compiled
    ``step_fn(*args)``, then K calls chained in a jitted scan (inputs roll
    so XLA can't hoist the body), best-of-3 with a real result fetch.
    Returns (flops_per_step or None, best_seconds_per_k_steps)."""
    import jax
    import jax.numpy as jnp

    try:
        ca = jax.jit(step_fn).lower(*args).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0)) or None
    except Exception:  # noqa: BLE001 - cost analysis is best-effort
        flops = None

    @jax.jit
    def loop(*args):
        def body(carry, _):
            acc, x = carry
            outs = step_fn(*args[:-1], x)
            total = sum(
                jnp.sum(o.astype(jnp.float32))
                for o in (outs if isinstance(outs, (tuple, list)) else [outs])
            )
            return (acc + total, jnp.roll(x, 1, 0)), None

        (acc, _), _ = jax.lax.scan(
            body, (jnp.float32(0.0), args[-1]), None, length=k
        )
        return acc

    float(loop(*args))  # compile
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        float(loop(*args))
        best = min(best, time.perf_counter() - t0)
    return flops, best


def bench_clip_device_only() -> dict:
    """Chip-only throughput: a pre-staged 128-image batch through the
    jit-compiled ViT-B/32 tower, K forwards chained in one scan (no
    decode, no host transfer, no tunnel dispatch in the timed loop), plus
    an MFU estimate from XLA's own per-forward FLOP count. This is the
    'how much of the chip are we using' number VERDICT r02 asked for —
    end-to-end videos/s conflates host pipeline + tunnel with compute."""
    import jax
    import jax.numpy as jnp

    from video_features_tpu.models.clip.model import (
        CONFIGS,
        VisionTransformer,
        init_params,
    )
    from video_features_tpu.models.common.weights import cast_floats_for_compute

    run, forced = _device_only_gate()
    if not run:
        return {}
    cfg = CONFIGS["CLIP-ViT-B/32"]
    B, K = (8, 2) if forced else (128, 10)
    host_params = init_params(cfg)
    x_host = np.random.RandomState(0).randn(B, 3, 224, 224).astype(np.float32)
    # forced runs are smoke-only: label them so a leaked env var can never
    # pass tiny-shape numbers off as chip figures in a BENCH artifact
    out = {"device_only_forced_smoke": True} if forced else {}
    for tag, dt in (("fp32", jnp.float32), ("bf16", jnp.bfloat16)):
        model = VisionTransformer(cfg, dtype=dt)
        params = host_params
        if dt != jnp.float32:
            params = cast_floats_for_compute(params, dt, exclude=("proj",))
        params = jax.device_put(params)
        x = jax.device_put(jnp.asarray(x_host))

        def forward(p, x, model=model):
            return model.apply({"params": p}, x)

        flops, best = _time_device_only(forward, (params, x), K)
        ips = B * K / best
        out[f"clip_device_only_ips_{tag}"] = round(ips, 1)
        # uni_12 equivalent: what end-to-end videos/s would be if the host
        # pipeline kept the chip fed — the gap to the measured end-to-end
        # number is the host/tunnel overhead
        out[f"clip_device_only_vps_{tag}"] = round(ips / 12.0, 2)
        if flops:
            out[f"clip_flops_per_image_{tag}"] = round(flops / B / 1e9, 2)  # GFLOP
            out[f"clip_mfu_{tag}_of_bf16_peak"] = round(
                ips * flops / B / V5E_BF16_PEAK_FLOPS, 4
            )
    return out


def bench_i3d_device_only() -> dict:
    """Chip-only throughput for the north-star deep pipeline: one fused
    (RAFT flow -> quantize -> I3D) + (crop -> I3D) step on a pre-staged
    65-frame 256x256 stack, K steps chained in a scan (no decode/tunnel
    in the timed loop), with XLA's FLOP count -> MFU. Pairs with
    bench_clip_device_only: together they bound how much of the end-to-end
    gap is host pipeline vs chip compute."""
    import jax
    import jax.numpy as jnp

    from video_features_tpu.models.i3d.extract_i3d import center_crop
    from video_features_tpu.models.i3d.model import build as i3d_build
    from video_features_tpu.models.i3d.model import init_params as i3d_init
    from video_features_tpu.models.raft.model import build as raft_build
    from video_features_tpu.models.raft.model import init_params as raft_init
    from video_features_tpu.ops.preprocess import flow_to_uint8, scale_to_1_1

    from video_features_tpu.models.common.weights import cast_floats_for_compute

    run, forced = _device_only_gate()
    if not run:
        return {}
    S, H, W, K = (5, 256, 256, 1) if forced else (65, 256, 256, 4)
    p_raft = jax.device_put(raft_init())
    host_rgb, host_flow = i3d_init("rgb"), i3d_init("flow")
    stack = jax.device_put(
        jnp.asarray(
            np.random.RandomState(0).randint(0, 255, (S, H, W, 3)).astype(np.float32)
        )
    )

    out = {}
    if forced:  # smoke-only label, as in bench_clip_device_only
        out["device_only_forced_smoke"] = True
    # fp32 vs --dtype bfloat16 (RAFT mixed-precision graph + bf16 I3D,
    # the r4 north-star uplift — VERDICT r03 next #2 asked for exactly
    # this before/after on one scale)
    for tag, dt in (("fp32", jnp.float32), ("bf16", jnp.bfloat16)):
        raft = raft_build(dtype=dt)
        i3d = i3d_build(dtype=dt)
        if dt == jnp.float32:
            p_rgb, p_flow = host_rgb, host_flow
        else:
            p_rgb = cast_floats_for_compute(host_rgb, dt, exclude=("conv3d_0c_1x1",))
            p_flow = cast_floats_for_compute(host_flow, dt, exclude=("conv3d_0c_1x1",))
        p_rgb, p_flow = jax.device_put(p_rgb), jax.device_put(p_flow)

        def step(p_raft, p_rgb, p_flow, stack, raft=raft, i3d=i3d):
            flow = raft.apply({"params": p_raft}, stack)  # (S-1, H, W, 2)
            f = scale_to_1_1(flow_to_uint8(center_crop(flow)))
            flow_feats, _ = i3d.apply({"params": p_flow}, f[None])
            rgb = scale_to_1_1(center_crop(stack[:-1]))
            rgb_feats, _ = i3d.apply({"params": p_rgb}, rgb[None])
            return flow_feats, rgb_feats

        flops, best = _time_device_only(step, (p_raft, p_rgb, p_flow, stack), K)
        sps = K / best
        sfx = "" if tag == "fp32" else "_bf16"  # fp32 keys keep r03 names
        out[f"i3d_raft_device_only_sps{sfx}"] = round(sps, 3)
        if flops:
            out[f"i3d_raft_flops_per_stack{sfx}"] = round(flops / 1e9, 1)  # GFLOP
            out[f"i3d_raft_mfu_{tag}_of_bf16_peak"] = round(
                sps * flops / V5E_BF16_PEAK_FLOPS, 4
            )
    return out


# EVERY device-touching part (headline included, r5) executes in a child
# process: the axon relay's compile helper has now died mid-bench in
# THREE rounds (r02/r03 outages; r04's first capture lost everything when
# the I3D 3D-conv compile hit "UNAVAILABLE: TPU backend setup/compile
# error" — the whole process died and the already-measured CLIP numbers
# with it). A crash inside a part now costs exactly that part's keys —
# and main() re-prints the complete-so-far JSON line after every part,
# so the LAST parseable stdout line is always the fullest artifact even
# if the parent itself dies mid-run (VERDICT r4 next #1).
_SUB_MARK = "BENCH_SUB "


def _sub_clip_e2e() -> dict:
    """The headline end-to-end CLIP config (aggregated + solo), isolated
    in a child so a helper crash during ITS compile can't zero the run."""
    from video_features_tpu.utils.synth import synth_video

    n_videos = int(os.environ.get("BENCH_VIDEOS", "16"))
    group = _clip_group(n_videos)
    with tempfile.TemporaryDirectory() as tmp:
        video = synth_video(os.path.join(tmp, "bench.mp4"), **CLIP_SPEC)
        agg = bench_clip(n_videos, video, tmp, video_batch=group)
        solo = bench_clip(n_videos, video, tmp)
        # --preprocess device on the SAME workload: raw uint8 H2D + fused
        # on-chip resize/crop/normalize/encode vs the host PIL chain — the
        # acceptance gate is device >= host end-to-end
        dev = bench_clip(n_videos, video, tmp, video_batch=group,
                         preprocess="device")
    return {
        "clip_vps": agg["best"],
        "clip_agg_median_vps": agg["median"],
        "clip_agg_passes": agg["passes"],
        "clip_solo_vps": solo["best"],
        "clip_solo_median_vps": solo["median"],
        "clip_solo_passes": solo["passes"],
        "clip_device_pre_vps": dev["best"],
        "clip_device_pre_median_vps": dev["median"],
        "clip_device_pre_passes": dev["passes"],
        "clip_device_pre_speedup_vs_host": round(dev["best"] / agg["best"], 3),
    }


def _sub_clip_bf16() -> dict:
    """--dtype bfloat16 e2e variant (one extra XLA compile)."""
    from video_features_tpu.utils.synth import synth_video

    n_videos = int(os.environ.get("BENCH_VIDEOS", "16"))
    group = _clip_group(n_videos)
    with tempfile.TemporaryDirectory() as tmp:
        video = synth_video(os.path.join(tmp, "bench.mp4"), **CLIP_SPEC)
        bf16 = bench_clip(n_videos, video, tmp, dtype="bfloat16", video_batch=group)
    return {
        "clip_bf16_vps": bf16["best"],
        "clip_bf16_median_vps": bf16["median"],
        "clip_bf16_passes": bf16["passes"],
    }


def _sub_clip_mixed() -> dict:
    """Mixed-RESOLUTION aggregation workload (the honesty note in
    bench_config: the headline fuses N copies of one video, --video_batch's
    best case). Here 8 videos at 4 source resolutions form 2 spatial
    buckets; under --preprocess device the bucket id joins agg_key, so
    same-bucket videos still fuse while their per-video resize matrices
    ride along — this measures what the bucket-grid + agg_key path
    actually delivers on a heterogeneous corpus, host vs device."""
    from video_features_tpu.utils.synth import synth_video

    specs = MIXED_SPECS
    with tempfile.TemporaryDirectory() as tmp:
        videos = [
            synth_video(os.path.join(tmp, f"m{i}.mp4"), n_frames=60,
                        width=w, height=h, seed=i)
            for i, (h, w) in enumerate(specs)
        ]
        host = bench_clip(0, None, tmp, video_batch=4, videos=videos)
        dev = bench_clip(0, None, tmp, video_batch=4, videos=videos,
                         preprocess="device")
    return {
        "clip_mixed_host_vps": host["best"],
        "clip_mixed_host_passes": host["passes"],
        "clip_mixed_device_vps": dev["best"],
        "clip_mixed_device_passes": dev["passes"],
        "clip_mixed_device_speedup_vs_host": round(
            dev["best"] / host["best"], 3
        ),
        # pipelined mixed-resolution overlap efficiency (runtime/
        # telemetry.py::overlap_report): the measurement baseline the
        # async double-buffered ingest ROADMAP item is judged against
        "clip_mixed_host_overlap": host.get("overlap"),
        "clip_mixed_device_overlap": dev.get("overlap"),
    }


def _sub_device_preprocess() -> dict:
    """The fused device-preprocess program ALONE (no encoder): uint8
    bucket-padded frames -> PIL-semantics bicubic resize + crop +
    normalize, jitted, at the headline CLIP_SPEC resolution. Spawned with
    JAX_PLATFORMS=cpu so it rides next to the host_preprocess_* keys
    (same backend, same 32-frame batch) without ever dialing a tunnel;
    on-chip numbers come from the e2e clip_device_pre_* keys instead."""
    import jax
    import jax.numpy as jnp

    from video_features_tpu.ops.preprocess import (
        CLIP_MEAN,
        CLIP_STD,
        device_preprocess_frames,
    )
    from video_features_tpu.ops.resize import fused_resize_crop_banded
    from video_features_tpu.ops.window import pad_hw, spatial_bucket

    rng = np.random.RandomState(0)
    h, w = CLIP_SPEC["height"], CLIP_SPEC["width"]
    frames = rng.randint(0, 255, (32, h, w, 3)).astype(np.uint8)
    bh, bw = spatial_bucket(h, w)
    wt_y, idx_y, wt_x, idx_x = fused_resize_crop_banded(
        h, w, 224, 224, "bicubic", pad_h=bh, pad_w=bw
    )
    x = jnp.asarray(pad_hw(frames, bh, bw))
    wy_d = (jnp.asarray(wt_y), jnp.asarray(idx_y))
    wx_d = (jnp.asarray(wt_x), jnp.asarray(idx_x))
    fn = jax.jit(
        lambda x, wy, wx: device_preprocess_frames(x, wy, wx, CLIP_MEAN, CLIP_STD)
    )
    jax.block_until_ready(fn(x, wy_d, wx_d))  # compile
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(x, wy_d, wx_d))
        best = min(best, time.perf_counter() - t0)
    return {
        "device_preprocess_fps": round(len(frames) / best, 1),
        "device_preprocess_backend": jax.default_backend(),
    }


def _tiny_i3d_forward() -> float:
    """Compile + run the full I3D graph at a tiny-but-real shape; returns
    elapsed seconds. The conv lowering is whatever VFT_CONV3D_IMPL says."""
    import jax
    import jax.numpy as jnp

    from video_features_tpu.models.i3d.model import build, init_params

    t0 = time.perf_counter()
    model = build()
    params = jax.device_put(init_params("rgb"))
    x = jnp.asarray(
        np.random.RandomState(0).randn(1, 17, 224, 224, 3).astype(np.float32)
    )
    feats, logits = jax.jit(lambda p, x: model.apply({"params": p}, x))(params, x)
    jax.block_until_ready((feats, logits))
    return time.perf_counter() - t0


def _sub_i3d_compile_probe() -> dict:
    """Gate for the i3d parts (VERDICT r4 next #2): prove the chosen
    conv3d lowering compiles the full I3D graph before any expensive i3d
    part risks the relay. On TPU the parent pre-selects the decomposed
    lowering (the direct one killed the compile helper in r2-r4)."""
    from video_features_tpu.models.common.layers import conv3d_impl

    return {
        "i3d_conv3d_impl": conv3d_impl(),
        "i3d_compile_probe_s": round(_tiny_i3d_forward(), 1),
    }


def _sub_conv3d_direct_probe() -> dict:
    """DIAGNOSTIC, runs LAST: does the direct XLA 3D-conv lowering (the
    r2-r4 helper-killer) compile today? Recorded after all numbers are
    already persisted, so a crash here costs only this key — and the
    answer is the committed repro datapoint scripts/repro_i3d_conv3d.py
    exists to collect."""
    os.environ["VFT_CONV3D_IMPL"] = "direct"
    return {"conv3d_direct_compile_s": round(_tiny_i3d_forward(), 1)}


def _sub_i3d_e2e() -> dict:
    import jax

    from video_features_tpu.utils.synth import synth_video

    with tempfile.TemporaryDirectory() as tmp:
        video = synth_video(os.path.join(tmp, "i3d.mp4"), **I3D_SPEC)
        i3d = bench_i3d_raft(video, tmp)
        # the reference's one qualitative perf claim is "PWC is faster
        # while RAFT is more accurate" (ref main.py:123-124) — measure it
        pwc = bench_i3d_raft(video, tmp, flow_type="pwc")
        # --preprocess device on the same workload: raw uint8 H2D + taps
        # vs host PIL min-edge-256 (shape-contracted geometry, PR 2)
        dev = bench_i3d_raft(video, tmp, flow_type="pwc", preprocess="device")
    out = {
        "i3d_raft_vps": i3d["best"],
        "i3d_raft_median_vps": i3d["median"],
        "i3d_raft_passes": i3d["passes"],
        "i3d_pwc_vps": pwc["best"],
        "i3d_pwc_median_vps": pwc["median"],
        "i3d_device_pre_pwc_vps": dev["best"],
        "i3d_device_pre_pwc_median_vps": dev["median"],
        "i3d_device_pre_speedup_vs_host": round(dev["best"] / pwc["best"], 3),
    }
    if jax.default_backend() != "tpu":
        # same convention as clip_device_only_*: off-TPU numbers are a
        # smoke, never a reportable device-path measurement
        out["i3d_device_pre_forced_smoke"] = True
    return out


def _sub_i3d_agg() -> dict:
    from video_features_tpu.utils.synth import synth_video

    with tempfile.TemporaryDirectory() as tmp:
        videos = [
            synth_video(
                os.path.join(tmp, f"s{i}.mp4"), n_frames=66,
                width=256, height=256, seed=i,
            )
            for i in range(4)
        ]
        solo = bench_i3d_short_corpus(videos, tmp, video_batch=1)
        agg = bench_i3d_short_corpus(videos, tmp, video_batch=4)
    return {
        "i3d_agg_vps": agg["best"],
        "i3d_agg_median_vps": agg["median"],
        "i3d_agg_passes": agg["passes"],
        "i3d_solo_short_vps": solo["best"],
        "i3d_agg_speedup_vs_solo": round(agg["best"] / solo["best"], 3),
    }


def _sub_flow_e2e() -> dict:
    """Standalone RAFT/PWC end-to-end, host vs --preprocess device: the
    device path ships raw uint8 windows (quarter H2D bytes) and fuses
    resize+pad into the dispatch via shape-contracted taps."""
    import jax

    from video_features_tpu.utils.synth import synth_video

    with tempfile.TemporaryDirectory() as tmp:
        video = synth_video(os.path.join(tmp, "flow.mp4"), **FLOW_SPEC)
        out = {}
        for ft in ("raft", "pwc"):
            host = bench_flow(video, tmp, flow_type=ft)
            dev = bench_flow(video, tmp, flow_type=ft, preprocess="device")
            out[f"flow_{ft}_vps"] = host["best"]
            out[f"flow_{ft}_passes"] = host["passes"]
            out[f"flow_device_pre_{ft}_vps"] = dev["best"]
            out[f"flow_device_pre_{ft}_speedup_vs_host"] = round(
                dev["best"] / host["best"], 3
            )
    if jax.default_backend() != "tpu":
        # same convention as clip_device_only_*: off-TPU numbers are a
        # smoke, never a reportable device-path measurement
        out["flow_device_pre_forced_smoke"] = True
    return out


def _sub_fault_overhead() -> dict:
    """Happy-path cost of the fault-tolerance bookkeeping (runtime/
    faults.py): per video the pipeline adds four ``faults.fire()`` no-op
    checks (decode/prepare/dispatch/sink stages) plus one manifest 'done'
    record (a flushed JSONL append). Reported in us/video and as a
    percentage of the r01 CLIP headline (3.637 videos/s on the real chip
    -> ~275 ms/video), pinning the <1% budget from ISSUE 3."""
    import timeit

    from video_features_tpu.runtime import faults

    n = 2000
    out = {}
    with tempfile.TemporaryDirectory() as tmp:
        faults.install_injector(None)  # the happy path: no injector at all
        man = faults.RunManifest(tmp)
        seq = iter(range(n * 2))

        def one_video():
            faults.fire("decode")
            faults.fire("prepare")
            faults.fire("dispatch")
            faults.fire("sink")
            man.record(f"/videos/{next(seq)}.mp4", "done", attempts=1, wall_s=0.25)

        total_s = timeit.timeit(one_video, number=n)
        t0 = time.perf_counter()
        summary = faults.merge_manifest(tmp)
        merge_s = time.perf_counter() - t0
        per_video_us = total_s / n * 1e6
        headline_s_per_video = 1.0 / 3.637  # BENCH_r01 chip headline
        out["fault_bookkeeping_us_per_video"] = round(per_video_us, 2)
        out["fault_overhead_pct_vs_headline"] = round(
            per_video_us / 1e6 / headline_s_per_video * 100.0, 4
        )
        out["fault_manifest_merge_s_per_2k_videos"] = round(merge_s, 4)
        out["fault_manifest_merged_total"] = summary["total"] if summary else 0
    return out


def _sub_telemetry_overhead() -> dict:
    """Happy-path cost of structured telemetry (runtime/telemetry.py):
    per video the pipelined loop opens ~5 spans (decode/prepare/dispatch/
    fetch/sink), bumps counters/gauges, and buffers the rows for the
    shared drain thread. Measured as on-minus-off over the same span
    shape — 'off' is the --telemetry off degradation (bare StageTimer
    timing, the pre-telemetry behaviour) — and reported in us/video and
    as a percentage of the r01 CLIP chip headline (3.637 videos/s ->
    ~275 ms/video), pinning ISSUE 6's <1% ceiling."""
    import timeit

    from video_features_tpu.runtime.telemetry import Telemetry

    n = 2000
    payload = np.zeros((12, 224, 224, 3), dtype=np.uint8)

    def one_video(t, key):
        with t.span("prepare", video=key, attempt=1, worker="w0"):
            with t.span("decode", video=key):
                t.metrics.inc("frames_decoded", 12)
        with t.span("dispatch", video=key, attempt=1, worker="w0"):
            t.count_h2d(payload)
        with t.span("fetch", video=key, attempt=1, worker="w0"):
            pass
        with t.span("sink", video=key):
            pass
        t.metrics.inc("videos_done")
        t.metrics.set_gauge("queue_depth.pending", 3)

    out = {}
    with tempfile.TemporaryDirectory() as tmp:
        tele_off = Telemetry(enabled=False)
        tele_on = Telemetry(output_root=tmp, enabled=True)
        seq = iter(range(n * 4))
        off_s = timeit.timeit(
            lambda: one_video(tele_off, f"/videos/{next(seq)}.mp4"), number=n
        )
        on_s = timeit.timeit(
            lambda: one_video(tele_on, f"/videos/{next(seq)}.mp4"), number=n
        )
        tele_on.close()
        spans_written = len(tele_on.spans())
    delta_us = max(on_s - off_s, 0.0) / n * 1e6
    headline_s_per_video = 1.0 / 3.637  # BENCH_r01 chip headline
    pct = delta_us / 1e6 / headline_s_per_video * 100.0
    out["telemetry_on_us_per_video"] = round(on_s / n * 1e6, 2)
    out["telemetry_off_us_per_video"] = round(off_s / n * 1e6, 2)
    out["telemetry_overhead_us_per_video"] = round(delta_us, 2)
    out["telemetry_overhead_pct_vs_headline"] = round(pct, 4)
    out["telemetry_within_budget"] = pct < 1.0
    out["telemetry_spans_written"] = spans_written
    return out


def _sub_preflight_overhead() -> dict:
    """Admission cost of the hostile-media preflight probe (io/probe.py):
    one container open, header-sanity checks against the resource caps,
    and one first-frame grab per video — paid once per admitted request
    (serve) or manifest entry (batch). Measured on a happy-path clip with
    all three caps armed (the most checks the probe ever runs) and
    reported in us/video and as a percentage of the r01 CLIP chip
    headline (3.637 videos/s -> ~275 ms/video), pinning ISSUE 9's <1%
    budget."""
    import timeit

    from video_features_tpu.io.probe import ResourceCaps, preflight
    from video_features_tpu.utils.synth import synth_video

    n = 200
    out = {}
    caps = ResourceCaps(
        max_pixels=3840 * 2160, max_duration_s=3600.0,
        max_decode_bytes=1 << 36,
    )
    with tempfile.TemporaryDirectory() as tmp:
        path = synth_video(
            os.path.join(tmp, "probe.mp4"), n_frames=60, width=320, height=240
        )
        assert preflight(path, need="video", caps=caps).verdict == "ok"
        total_s = timeit.timeit(
            lambda: preflight(path, need="video", caps=caps), number=n
        )
        # the header-only variant (no first-frame grab): what spool
        # re-polls and probe-only callers pay
        header_s = timeit.timeit(
            lambda: preflight(path, need="video", caps=caps, first_frame=False),
            number=n,
        )
    per_video_us = total_s / n * 1e6
    headline_s_per_video = 1.0 / 3.637  # BENCH_r01 chip headline
    pct = per_video_us / 1e6 / headline_s_per_video * 100.0
    out["preflight_us_per_video"] = round(per_video_us, 2)
    out["preflight_header_only_us_per_video"] = round(header_s / n * 1e6, 2)
    out["preflight_pct_vs_headline"] = round(pct, 4)
    out["preflight_within_budget"] = pct < 1.0
    return out


def _sub_analysis_overhead() -> dict:
    """Wall-time of a full graftcheck sweep (docs/analysis.md): the
    static-analysis suite is meant to run on every push via
    scripts/check.sh, so it carries an explicit latency budget — a full
    package lint (parse + the whole-program call graph + interprocedural
    taint + jit-hygiene + thread-reachability + the GC31x concurrency
    proofs + sharding contracts + the GC60x durability and GC70x
    observability contracts over every module, plus the GC80x numerics
    and dtype-flow family) must stay under 10 s on one core — measured
    7.5 s cold with the full v5 28-rule catalogue on a CI-class core
    (the v4 23-rule sweep measured 6.2 s on the same host class, so the
    five GC80x checks — which re-walk every function under the dtype
    lens and cross-check the two committed budget JSONs against the
    test corpus — cost ~1.3 s; the shared call graph + taint build
    still dominates at ~2.7 s). The budget is reported here and pinned
    in-band so a checker that grows an accidentally quadratic pass
    shows up as a bench regression."""
    from video_features_tpu.analysis import run_checks

    budget_s = 10.0
    t0 = time.perf_counter()
    findings = run_checks()
    cold_s = time.perf_counter() - t0  # includes first-parse of the package
    t0 = time.perf_counter()
    run_checks()
    warm_s = time.perf_counter() - t0
    return {
        "analysis_graftcheck_cold_s": round(cold_s, 3),
        "analysis_graftcheck_warm_s": round(warm_s, 3),
        "analysis_budget_s": budget_s,
        "analysis_within_budget": cold_s < budget_s,
        "analysis_findings": len(findings),  # 0 on a clean tree
    }


def _sub_numerics_parity() -> dict:
    """The GC804 precision contract in bench form (docs/tpu.md
    'Precision contract'): the newly admitted standalone RAFT bf16
    extraction must stay inside its committed relative-L2 drift
    ceilings (analysis/parity_budget.json — the same table
    --update-budgets regenerates and tests/test_bfloat16.py asserts),
    and its throughput delta vs the fp32 twin ships alongside so the
    speed/accuracy trade is a measured number, not a claim. Off-TPU the
    vps pair is a smoke only: CPU emulates bf16 by widening, so the
    MXU/HBM win this admission exists for does not show here."""
    import jax

    from video_features_tpu.analysis.parity import max_rel_drift, measure_parity
    from video_features_tpu.utils.synth import synth_video

    with tempfile.TemporaryDirectory() as tmp:
        video = synth_video(os.path.join(tmp, "flow.mp4"), **FLOW_SPEC)
        f32 = bench_flow(video, tmp, flow_type="raft")
        b16 = bench_flow(video, tmp, flow_type="raft", dtype="bfloat16")
    out = {
        "numerics_raft_fp32_vps": f32["best"],
        "numerics_raft_bf16_vps": b16["best"],
        "numerics_raft_bf16_speedup_vs_fp32": round(b16["best"] / f32["best"], 3),
    }
    within = True
    for kind, rel in sorted(measure_parity("parity_raft").items()):
        ceiling = max_rel_drift("raft", "bfloat16", kind)
        out[f"numerics_raft_bf16_{kind}_rel_drift"] = round(rel, 6)
        out[f"numerics_raft_bf16_{kind}_drift_ceiling"] = ceiling
        within = within and rel < ceiling
    out["numerics_parity_within_budget"] = within
    if jax.default_backend() != "tpu":
        out["numerics_bf16_cpu_smoke"] = True
    return out


def _sub_serve_latency() -> dict:
    """Serving-daemon admission path (video_features_tpu/serve, ISSUE 7):
    cold-first-request latency (model build + first jit, the cost
    ``serve warmup`` exists to move off the request path) vs warm-request
    latency on the resident extractor, then batched-vs-serial throughput
    for a burst of same-bucket requests — the coalescing win: the burst
    crosses the loop in ceil(N / max_group_size) fused dispatches instead
    of N serial ones. CPU resnet18 with random init: relative numbers
    (cold/warm ratio, batched speedup) are the artifact, not absolutes."""
    from video_features_tpu.config import parse_serve_args
    from video_features_tpu.serve.daemon import ServeDaemon
    from video_features_tpu.utils.synth import synth_video

    group, n_burst = 3, 6
    out = {}
    with tempfile.TemporaryDirectory() as tmp:
        vids = [
            synth_video(os.path.join(tmp, f"v{i}.mp4"),
                        n_frames=10, width=96, height=64, seed=i)
            for i in range(n_burst)
        ]
        scfg = parse_serve_args([
            "--feature_types", "resnet18",
            "--output_path", os.path.join(tmp, "out"),
            "--tmp_path", os.path.join(tmp, "tmp"),
            "--allow_random_init", "--cpu", "--heartbeat_s", "0",
            "--max_group_size", str(group), "--batch_size", str(group),
        ])
        d = ServeDaemon(scfg)
        seq = iter(range(10_000))

        def run_one(vid: str) -> float:
            # submit + drain inline on this thread: latency is admission
            # -> fused dispatch -> terminal record, no thread wakeups
            t0 = time.perf_counter()
            d.submit({"feature_type": "resnet18", "video_path": vid,
                      "bucket": "96x64", "id": f"bench-{next(seq)}"},
                     source="local")
            for g in d.batcher.take_ready(now=float("inf")):
                d.batcher._run_group(g)
            return time.perf_counter() - t0

        cold_s = run_one(vids[0])  # pays build + first jit
        warm_s = min(run_one(vids[0]) for _ in range(3))
        serial_t0 = time.perf_counter()
        for v in vids:
            run_one(v)
        serial_s = time.perf_counter() - serial_t0
        # the same burst coalesced: admit all, then drain once
        batched_t0 = time.perf_counter()
        for v in vids:
            d.submit({"feature_type": "resnet18", "video_path": v,
                      "bucket": "96x64", "id": f"bench-{next(seq)}"},
                     source="local")
        for g in d.batcher.take_ready(now=float("inf")):
            d.batcher._run_group(g)
        batched_s = time.perf_counter() - batched_t0
        counts = d.tracker.counts()
        d.shutdown()
        out["serve_cold_first_request_s"] = round(cold_s, 3)
        out["serve_warm_request_s"] = round(warm_s, 3)
        out["serve_cold_over_warm"] = round(cold_s / max(warm_s, 1e-9), 1)
        out["serve_serial_rps"] = round(n_burst / serial_s, 3)
        out["serve_batched_rps"] = round(n_burst / batched_s, 3)
        out["serve_batched_speedup"] = round(serial_s / max(batched_s, 1e-9), 2)
        out["serve_burst_n"] = n_burst
        out["serve_max_group_size"] = group
        out["serve_requests_done"] = counts.get("done", 0)
        out["serve_requests_failed"] = counts.get("failed", 0)
    return out


def _sub_serve_scheduling() -> dict:
    """Serve-mode scheduling policy (ISSUE 8): the same deterministic
    mixed-priority/deadline burst dispatched under FIFO vs EDF through
    :func:`~video_features_tpu.serve.scheduler.simulate_dispatch` — the
    exact serial-dispatch model the daemon loop implements. The artifact
    is the deadline-miss rate and the p50/p99 queue-to-completion
    latency per policy: EDF must not miss more deadlines than FIFO on
    any burst, and misses strictly fewer on this one (the pinned tier-1
    test asserts the same invariant on a smaller burst). Pure host —
    no extractor, no jax."""
    from video_features_tpu.serve.lifecycle import ExtractionRequest
    from video_features_tpu.serve.scheduler import (
        EdfScheduler,
        FifoScheduler,
        simulate_dispatch,
    )

    service_s = 0.5
    n, n_keys = 64, 8

    def burst():
        # deterministic burst, admitted at t=0 and served serially: the
        # deadline set is FEASIBLE (0.55 s of deadline headroom per
        # 0.5 s service slot when sorted by deadline) but arrival order
        # is decorrelated from deadline order via the i*7 mod 64
        # permutation, so FIFO burns early slots on late deadlines.
        # Overload is deliberately avoided — under infeasible load EDF's
        # miss count degrades (the classic domino), and the daemon sheds
        # that case through the expired boundary check instead.
        # Priorities/deadlines are fixed functions of the index — no
        # RNG, identical every run.
        groups = []
        for i in range(n):
            deadline = None if i % 4 == 0 else 4.0 + 0.55 * ((i * 7) % 64)
            req = ExtractionRequest(
                feature_type="resnet18",
                video_path=f"/bench/v{i}.mp4",
                id=f"sched-{i}",
                bucket=f"k{i % n_keys}",
                priority=(3 if i % 11 == 0 else 0),
            )
            req.admitted_at = 0.0
            req.deadline_at = deadline
            groups.append(((req.feature_type, req.bucket), [req]))
        return groups

    out = {"serve_sched_burst_n": n, "serve_sched_service_s": service_s}
    for name, sched in (
        ("fifo", FifoScheduler()),
        ("edf", EdfScheduler(default_slack_s=30.0, aging_s=10.0)),
    ):
        results = simulate_dispatch(burst(), sched, service_s=service_s)
        # simulate_dispatch marks deadline-less requests met; count the
        # miss rate over requests that actually declared a deadline
        declared = sum(1 for i in range(n) if i % 4 != 0)
        missed = sum(1 for r in results if not r["met"])
        lats = sorted(r["latency_s"] for r in results)
        out[f"serve_sched_{name}_miss_rate"] = round(missed / declared, 3)
        out[f"serve_sched_{name}_p50_latency_s"] = round(lats[n // 2], 3)
        out[f"serve_sched_{name}_p99_latency_s"] = round(
            lats[min(n - 1, int(n * 0.99))], 3
        )
    out["serve_sched_edf_saves"] = round(
        out["serve_sched_fifo_miss_rate"] - out["serve_sched_edf_miss_rate"], 3
    )
    return out


def _sub_serve_cost_model() -> dict:
    """Cost-aware scheduling (ISSUE 12): the pinned heterogeneous-cost
    burst — one expensive group whose declared deadline is already
    infeasible (10 s of service against a 5 s budget) ahead of eight
    cheap feasible groups — dispatched under FIFO, plain EDF, and
    edf-cost with a ServiceTimeModel trained from the same per-key
    service times the simulation charges. Plain EDF runs the doomed
    group first (earliest deadline) and dominoes every cheap deadline;
    edf-cost demotes it behind the feasible work. The artifact is the
    per-policy deadline-miss rate and p50/p99 latency; the tier-1 test
    pins the same invariant (edf-cost strictly fewer misses at equal or
    better p99). Pure host — no extractor, no jax."""
    from video_features_tpu.serve.costmodel import ServiceTimeModel
    from video_features_tpu.serve.lifecycle import ExtractionRequest
    from video_features_tpu.serve.scheduler import (
        CostAwareEdfScheduler,
        EdfScheduler,
        FifoScheduler,
        simulate_dispatch,
    )

    heavy_s, cheap_s, n_cheap = 10.0, 0.5, 8

    def burst():
        groups = []
        doomed = ExtractionRequest(
            feature_type="i3d", video_path="/bench/big.mp4",
            id="cost-doomed", bucket="big",
        )
        doomed.admitted_at, doomed.deadline_at = 0.0, 5.0
        groups.append((("i3d", "big"), [doomed]))
        for i in range(n_cheap):
            req = ExtractionRequest(
                feature_type="resnet18", video_path=f"/bench/v{i}.mp4",
                id=f"cost-{i}", bucket=f"k{i}",
            )
            req.admitted_at, req.deadline_at = 0.0, 5.5 + 0.5 * i
            groups.append((("resnet18", f"k{i}"), [req]))
        return groups

    def service(key, requests):
        return heavy_s if key[0] == "i3d" else cheap_s

    # train the estimator with exactly the service times the simulation
    # charges (one observation pins the EWMA to the sample)
    model = ServiceTimeModel()
    model.observe("i3d", "big", 1, heavy_s)
    for i in range(n_cheap):
        model.observe("resnet18", f"k{i}", 1, cheap_s)

    n = n_cheap + 1
    out = {"serve_cost_burst_n": n, "serve_cost_heavy_s": heavy_s,
           "serve_cost_cheap_s": cheap_s}
    for name, sched in (
        ("fifo", FifoScheduler()),
        ("edf", EdfScheduler(default_slack_s=30.0, aging_s=10.0)),
        ("edf_cost", CostAwareEdfScheduler(
            model, default_slack_s=30.0, aging_s=10.0)),
    ):
        results = simulate_dispatch(burst(), sched, service_s=service)
        missed = sum(1 for r in results if not r["met"])
        lats = sorted(r["latency_s"] for r in results)
        out[f"serve_cost_{name}_miss_rate"] = round(missed / n, 3)
        out[f"serve_cost_{name}_p50_latency_s"] = round(lats[n // 2], 3)
        out[f"serve_cost_{name}_p99_latency_s"] = round(lats[-1], 3)
    out["serve_cost_edf_cost_saves"] = round(
        out["serve_cost_edf_miss_rate"] - out["serve_cost_edf_cost_miss_rate"], 3
    )
    return out


def _sub_metrics_endpoint_overhead() -> dict:
    """/metrics exposition cost (ISSUE 12): time a full scrape — the
    registry snapshot, family mapping, and text render, plus the HTTP
    round trip — against the warm-request wall time on the same daemon.
    The acceptance bound is render time < 1% of a warm request: the
    observability surface must be free relative to the work it
    observes."""
    import urllib.request

    from video_features_tpu.config import parse_serve_args
    from video_features_tpu.serve.daemon import ServeDaemon
    from video_features_tpu.utils.synth import synth_video

    out = {}
    with tempfile.TemporaryDirectory() as tmp:
        vid = synth_video(os.path.join(tmp, "v0.mp4"),
                          n_frames=10, width=96, height=64, seed=0)
        scfg = parse_serve_args([
            "--feature_types", "resnet18",
            "--output_path", os.path.join(tmp, "out"),
            "--tmp_path", os.path.join(tmp, "tmp"),
            "--allow_random_init", "--cpu", "--heartbeat_s", "0",
            "--port", "0",
        ])
        d = ServeDaemon(scfg)
        d.start()
        seq = iter(range(10_000))

        def run_one() -> float:
            t0 = time.perf_counter()
            d.submit({"feature_type": "resnet18", "video_path": vid,
                      "bucket": "96x64", "id": f"mx-{next(seq)}"},
                     source="local")
            for g in d.batcher.take_ready(now=float("inf")):
                d.batcher._run_group(g)
            return time.perf_counter() - t0

        run_one()  # cold: build + first jit, excluded
        warm_s = min(run_one() for _ in range(3))
        url = f"http://127.0.0.1:{d.http_port}/metrics"
        urllib.request.urlopen(url, timeout=10).read()  # warm the socket path
        n_scrapes = 50
        t0 = time.perf_counter()
        for _ in range(n_scrapes):
            body = urllib.request.urlopen(url, timeout=10).read()
        scrape_s = (time.perf_counter() - t0) / n_scrapes
        # render-only (no HTTP): the in-process floor
        t0 = time.perf_counter()
        for _ in range(n_scrapes):
            text = d.metrics_text()
        render_s = (time.perf_counter() - t0) / n_scrapes
        d.shutdown()
        out["metrics_warm_request_s"] = round(warm_s, 4)
        out["metrics_scrape_s"] = round(scrape_s, 6)
        out["metrics_render_s"] = round(render_s, 6)
        out["metrics_body_bytes"] = len(body)
        out["metrics_render_over_request"] = round(render_s / max(warm_s, 1e-9), 5)
        out["metrics_within_budget"] = render_s < 0.01 * warm_s
    return out


def _sub_ledger_overhead() -> dict:
    """Steady-state cost of the device cost ledger (ISSUE 15 <1%
    ceiling): once an executable's (family, signature) pair is captured,
    every further call through the instrument_state wrapper pays only a
    lock + seen-set membership check. Measured on-minus-off over the
    same pre-compiled jit call (off = the bare state dict), plus one
    DeviceMemorySampler.sample_once() — the memory_stats poll is paid
    per sampling interval, not per video, so it is reported separately
    and added to the per-video figure as a worst case (one poll per
    video)."""
    import timeit

    import jax

    from video_features_tpu.runtime.telemetry import MetricsRegistry
    from video_features_tpu.telemetry.ledger import (
        CostLedger,
        DeviceMemorySampler,
        instrument_state,
    )

    n = 2000
    params = {"w": np.ones((64, 64), np.float32)}
    x = np.ones((8, 64), np.float32)
    fwd = jax.jit(lambda p, v: v @ p["w"])
    fwd(params, x).block_until_ready()  # compile outside the timing

    out = {}
    with tempfile.TemporaryDirectory() as tmp:
        ledger = CostLedger(os.path.join(tmp, "cost_ledger.json"))
        wrapped = instrument_state(
            {"params": params, "forward": fwd}, ledger, model="bench"
        )
        wrapped["forward"](params, x)  # one-time AOT capture, excluded
        off_s = timeit.timeit(lambda: fwd(params, x), number=n)
        on_s = timeit.timeit(lambda: wrapped["forward"](params, x), number=n)
        sampler = DeviceMemorySampler(MetricsRegistry())
        t0 = time.perf_counter()
        for _ in range(50):
            sampler.sample_once()
        sample_us = (time.perf_counter() - t0) / 50 * 1e6
        out["ledger_entries_recorded"] = len(ledger)
    delta_us = max(on_s - off_s, 0.0) / n * 1e6
    headline_s_per_video = 1.0 / 3.637  # BENCH_r01 chip headline
    pct = (delta_us + sample_us) / 1e6 / headline_s_per_video * 100.0
    out["ledger_wrapped_call_us"] = round(on_s / n * 1e6, 2)
    out["ledger_bare_call_us"] = round(off_s / n * 1e6, 2)
    out["ledger_overhead_us_per_video"] = round(delta_us, 2)
    out["ledger_sampler_sample_us"] = round(sample_us, 2)
    out["ledger_overhead_pct_vs_headline"] = round(pct, 4)
    out["ledger_within_budget"] = pct < 1.0
    return out


def _sub_ingest_overlap() -> dict:
    """Async-ingest acceptance part (docs/tpu.md 'Async device ingest'):
    the completion-queue pipelined loop's host/device overlap efficiency
    (runtime/telemetry.py::overlap_report) vs the stage-sequential
    serial loop on the SAME static corpus, plus the device lane's
    busy_frac (utilization_report) and the --frame_delta_threshold skip
    rate. The serial baseline runs every stage back-to-back on one
    thread, so its overlap is structurally 0.0 — the recorded pair pins
    that the pipelined loop's overlap stays a real improvement, and the
    _seq/_async vps pair is the wall-clock discriminator. CPU-pinned by
    main() like the other host parts: the measurement is about LOOP
    structure, not chip speed."""
    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP
    from video_features_tpu.parallel.devices import resolve_devices
    from video_features_tpu.runtime.telemetry import (
        overlap_report,
        utilization_report,
    )
    from video_features_tpu.utils.synth import synth_video

    n = int(os.environ.get("BENCH_INGEST_VIDEOS", "6"))
    # static=True: every frame repeats frame 0 modulo codec noise — the
    # corpus the delta gate must fire on (and a fair overlap workload:
    # decode cost is identical across the three runs)
    spec = dict(n_frames=48, width=320, height=240)
    out: dict = {}
    with tempfile.TemporaryDirectory() as tmp:
        videos = [
            synth_video(os.path.join(tmp, f"static{i}.mp4"), seed=i,
                        static=True, **spec)
            for i in range(n)
        ]

        def run(tag, **kw):
            cfg = ExtractionConfig(
                allow_random_init=True,
                feature_type="CLIP-ViT-B/32",
                video_paths=list(videos),
                extract_method=CLIP_EXTRACT_METHOD,
                video_batch=2,
                tmp_path=os.path.join(tmp, "t" + tag),
                output_path=os.path.join(tmp, "o" + tag),
                **kw,
            )
            ex = ExtractCLIP(cfg, external_call=True)
            ex.progress.disable = True
            device = resolve_devices(cfg)[0]
            ex(range(2), device=device)  # warmup: decode path + compile
            seq0 = max((r["seq"] for r in ex.telemetry.spans()), default=0)
            skipped0 = float(ex.telemetry.metrics.counter("windows_skipped"))
            t0 = time.perf_counter()
            results = ex(range(n), device=device)
            wall = time.perf_counter() - t0
            assert len(results) == n and all(
                r["CLIP-ViT-B/32"].shape == (12, 512) for r in results
            )
            rows = [r for r in ex.telemetry.spans() if r["seq"] > seq0]
            skipped = float(
                ex.telemetry.metrics.counter("windows_skipped")
            ) - skipped0
            return rows, wall, skipped

        # async ingest: decode workers feeding the depth-2 completion queue
        rows, wall, _ = run("async", decode_workers=2, inflight_groups=2)
        rep = overlap_report(rows)
        util = utilization_report(rows)
        out["ingest_overlap_efficiency"] = round(rep["overlap_efficiency"], 4)
        out["ingest_overlap_of_device"] = round(rep["overlap_of_device"], 4)
        out["ingest_busy_frac"] = round(
            max(
                (d["busy_frac"] for d in util["devices"].values()),
                default=0.0,
            ),
            4,
        )
        out["ingest_async_vps"] = round(n / wall, 3)

        # stage-sequential baseline: decode_workers=0 takes _run_serial
        rows_seq, wall_seq, _ = run("seq", decode_workers=0)
        out["ingest_overlap_efficiency_seq"] = round(
            overlap_report(rows_seq)["overlap_efficiency"], 4
        )
        out["ingest_seq_vps"] = round(n / wall_seq, 3)

        # frame-delta gating: skip rate over the timed pass's sampled
        # windows (12 per video), threshold above mp4v codec noise
        _, wall_gate, skipped = run(
            "gate", decode_workers=2, frame_delta_threshold=2.0
        )
        out["ingest_delta_windows_skipped"] = int(skipped)
        out["ingest_delta_skip_rate"] = round(skipped / float(12 * n), 4)
        out["ingest_delta_gated_vps"] = round(n / wall_gate, 3)
    return out


def _sub_cache_serving() -> dict:
    """Content-addressed cache acceptance part (docs/serving.md
    'Feature caching', ISSUE 17): warm-hit latency vs cold extraction on
    the serve admission path, effective throughput under a Zipf-skewed
    request stream, and the shared-decode fan-out's decode-once +
    bit-identity claims on the batch path.

    Gated keys: ``cache_hit_latency_ms`` (the admission short-circuit —
    hash memo + store lookup + materialize; the thing this subsystem
    exists to keep cheap) and ``cache_hit_speedup`` with its >= 10x
    ``cache_hit_within_budget`` hard gate. The fan-out booleans
    (``*_decode_once_*``, ``*_bitmatch_*``) are hard gates too.
    Cold-extraction wall and the effective-vps projections are
    host-capability sizing numbers — named without unit suffixes so the
    --compare sentinel treats them as informational (this part runs
    CPU-pinned on heterogeneous containers)."""
    import statistics

    from video_features_tpu import cli
    from video_features_tpu.config import parse_serve_args
    from video_features_tpu.serve.daemon import ServeDaemon
    from video_features_tpu.utils.synth import synth_video

    n_corpus, n_stream = 6, 24
    out: dict = {}
    with tempfile.TemporaryDirectory() as tmp:
        vids = [
            synth_video(os.path.join(tmp, f"v{i}.mp4"),
                        n_frames=10, width=96, height=64, seed=i)
            for i in range(n_corpus)
        ]
        scfg = parse_serve_args([
            "--feature_types", "resnet18",
            "--output_path", os.path.join(tmp, "out"),
            "--tmp_path", os.path.join(tmp, "tmp"),
            "--cache_dir", os.path.join(tmp, "store"),
            "--allow_random_init", "--cpu", "--heartbeat_s", "0",
            "--on_extraction", "save_numpy",
        ])
        d = ServeDaemon(scfg)
        seq = iter(range(10_000))

        def run_one(vid: str) -> float:
            # submit + inline drain; a cache hit is terminal at submit
            # and the drain is a no-op, so one timer covers both paths
            t0 = time.perf_counter()
            d.submit({"feature_type": "resnet18", "video_path": vid,
                      "id": f"bench-{next(seq)}"}, source="local")
            for g in d.batcher.take_ready(now=float("inf")):
                d.batcher._run_group(g)
            return time.perf_counter() - t0

        run_one(vids[0])  # sacrificial: model build + first jit
        cold = [run_one(v) for v in vids[1:]]   # misses: extract + publish
        hits = [run_one(vids[1]) for _ in range(5)]  # admission hits
        cold_s = statistics.median(cold)
        hit_s = min(hits)
        # Zipf-skewed replay over the (now fully cached) corpus — the
        # skew every real request log has; achieved hit rate is 1.0 here
        # by construction, so the stream measures steady-state hit cost
        rng = np.random.default_rng(0)
        ranks = (rng.zipf(1.5, size=n_stream) - 1) % n_corpus
        t0 = time.perf_counter()
        for r in ranks:
            run_one(vids[int(r)])
        zipf_wall = time.perf_counter() - t0
        counts = d.tracker.counts()
        d.shutdown()

        # shared-decode fan-out on the batch path: one decoder open per
        # video for BOTH models, outputs bit-identical to single runs
        import video_features_tpu.io.video as vio

        fts = ["resnet18", "CLIP-ViT-B/32"]
        fan_vids = vids[:2]
        common = ["--video_paths", *fan_vids, "--tmp_path",
                  os.path.join(tmp, "tmp"), "--allow_random_init", "--cpu",
                  "--extract_method", "fix_2", "--on_extraction",
                  "save_numpy", "--heartbeat_s", "0"]
        for ft in fts:
            cli.main(["--feature_type", ft, "--output_path",
                      os.path.join(tmp, "single"), "--ingest_cache_mb", "0",
                      *common])
        opens = []
        real_init = vio._Reader.__init__
        vio._Reader.__init__ = (
            lambda self, *a, **kw: opens.append(a) or real_init(self, *a, **kw)
        )
        try:
            cli.main(["--feature_types", *fts, "--output_path",
                      os.path.join(tmp, "fanout"), *common])
        finally:
            vio._Reader.__init__ = real_init
        bitmatch = all(
            np.array_equal(
                np.load(os.path.join(tmp, "fanout", ft,
                                     f"v{i}_{ft.replace('/', '-')}.npy")),
                np.load(os.path.join(tmp, "single", ft,
                                     f"v{i}_{ft.replace('/', '-')}.npy")),
            )
            for ft in fts for i in range(len(fan_vids))
        )

    def vps_at(h: float) -> float:
        return 1.0 / (h * hit_s + (1.0 - h) * cold_s)

    out["cache_hit_latency_ms"] = round(hit_s * 1000.0, 3)
    out["cache_cold_extract_wall"] = round(cold_s, 3)  # seconds; info key
    out["cache_hit_speedup"] = round(cold_s / max(hit_s, 1e-9), 1)
    out["cache_hit_within_budget"] = cold_s / max(hit_s, 1e-9) >= 10.0
    out["cache_effective_vps_hit0"] = round(vps_at(0.0), 3)
    out["cache_effective_vps_hit50"] = round(vps_at(0.5), 3)
    out["cache_effective_vps_hit90"] = round(vps_at(0.9), 3)
    out["cache_zipf_stream_requests"] = n_stream
    out["cache_zipf_stream_wall"] = round(zipf_wall, 3)  # seconds; info key
    out["cache_serve_requests_done"] = counts.get("done", 0)
    out["cache_serve_requests_failed"] = counts.get("failed", 0)
    out["cache_fanout_reader_opens"] = len(opens)
    out["cache_fanout_videos"] = len(fan_vids)
    out["cache_fanout_decode_once_within_budget"] = len(opens) == len(fan_vids)
    out["cache_fanout_bitmatch_within_budget"] = bool(bitmatch)
    return out


def _sub_serve_preemption() -> dict:
    """Fleet robustness (ISSUE 18). Part A: the pinned mixed-model
    HBM-overcommit burst replayed through
    :func:`~video_features_tpu.serve.preemptor.simulate_overcommit` with
    preemption OFF (today's behavior: the non-fitting model's burst is
    rejected and scored as deadline misses) vs ON (the idle resident is
    evicted through its breaker, the burst runs after one re-warm toll)
    — ON must strictly lower the deadline-miss rate. Part B: a
    3-replica work-stealing drill: one replica SIGKILLs itself via the
    ``replica_kill`` fault stage while holding spool leases on a
    6-request burst; two survivors reclaim the stale leases and finish —
    the artifact is every-request-terminal and the duplicate-payload
    count (hard 0). Pure host — no extractor, no jax."""
    import hashlib
    import shutil
    import signal as signal_mod
    import subprocess
    import textwrap

    from video_features_tpu.serve.costmodel import ServiceTimeModel
    from video_features_tpu.serve.lifecycle import (
        ReplicaRegistry,
        RequestTracker,
        parse_request,
        requests_root,
    )
    from video_features_tpu.serve.preemptor import (
        Preemptor,
        simulate_overcommit,
    )
    from video_features_tpu.serve.sources import SpoolWatcher
    from video_features_tpu.serve.supervisor import CircuitBreaker
    from video_features_tpu.telemetry.ledger import CostLedger

    out: dict = {}

    # -- part A: preemption ON vs OFF on the pinned overcommit burst ----
    class _Pool:
        def __init__(self):
            self.resident = {"model_warm"}
            self.built_at = {}

        def feature_types(self):
            return set(self.resident)

        def evict(self, ft):
            self.resident.discard(ft)

    ledger = CostLedger(path=None)
    ledger.record("model_warm", "fam", "64x48", "queue", "tpu",
                  {"memory": {"argument_bytes": 800}})
    ledger.record("model_burst", "fam", "64x48", "queue", "tpu",
                  {"memory": {"argument_bytes": 500}})
    # the pinned burst: 8 warm-model requests, then a 12-request burst
    # for the model that cannot fit beside it (needs 500 vs 100 free),
    # then 4 more warm requests riding the same fused groups
    bursts = [("model_warm", 8), ("model_burst", 12), ("model_warm", 4)]
    n_requests = sum(n for _, n in bursts)

    def replay(preempt_on: bool):
        pool = _Pool()
        p = None
        if preempt_on:
            p = Preemptor(
                ledger=ledger,
                cost_model=ServiceTimeModel(path=None),
                pool=pool,
                breaker_for=lambda ft: CircuitBreaker(),
                headroom_fn=lambda: 100,
                cooldown_s=0.0,
                min_residency_s=0.0,
            )
        return simulate_overcommit(
            p, bursts, resident_fits=lambda ft: ft == "model_warm",
            service_s=1.0, deadline_s=2.5, rewarm_s=0.5,
        )

    for label, on in (("off", False), ("on", True)):
        results = replay(on)
        missed = sum(1 for r in results if not r["met"])
        out[f"serve_preempt_{label}_miss_rate"] = round(missed / n_requests, 3)
    out["serve_preempt_burst_n"] = n_requests
    out["serve_preempt_saves"] = round(
        out["serve_preempt_off_miss_rate"] - out["serve_preempt_on_miss_rate"],
        3,
    )

    # -- part B: 3-replica SIGKILL + work-stealing drill ----------------
    root = tempfile.mkdtemp(prefix="bench_fleet_")
    try:
        outdir = os.path.join(root, "out")
        spool = os.path.join(root, "spool")
        feat = os.path.join(root, "features")
        os.makedirs(spool)
        os.makedirs(feat)
        n = 6
        for i in range(n):
            tmp = os.path.join(spool, f".job{i}.tmp")
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump({"feature_type": "toy", "id": f"job{i}",
                           "video_path": f"/media/clip{i}.mp4"}, fh)
            os.replace(tmp, os.path.join(spool, f"job{i}.json"))

        victim_src = textwrap.dedent(
            """
            import sys, time
            from video_features_tpu.runtime import faults
            from video_features_tpu.serve.lifecycle import (
                ReplicaRegistry, RequestTracker, parse_request,
            )
            from video_features_tpu.serve.sources import SpoolWatcher

            out, spool = sys.argv[1:3]

            class Pool:
                def feature_types(self):
                    return {"toy"}

            class Daemon:
                def __init__(self):
                    self.tracker = RequestTracker(out, replica_id="victim")
                    self.pool = Pool()
                    self.telemetry = None

                def submit(self, payload, source):
                    return self.tracker.admit(parse_request(payload, source))

            w = SpoolWatcher(Daemon(), spool, replica_id="victim",
                             lease_timeout_s=1.0,
                             registry=ReplicaRegistry(out, "victim"))
            faults.install_injector(["replica_kill:kill:2"])
            w.poll_once()  # claims + admits the whole burst, holds leases
            while True:
                w.poll_once()  # pinned cadence: poll 2 SIGKILLs mid-drill
                time.sleep(0.05)
            """
        )
        proc = subprocess.run(
            [sys.executable, "-c", victim_src, outdir, spool],
            timeout=120.0, env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        killed = proc.returncode == -signal_mod.SIGKILL
        stale = [os.path.join(spool, f) for f in os.listdir(spool)
                 if f.endswith(".claim.victim")]
        old = time.time() - 30
        for path in stale + [os.path.join(
            requests_root(outdir), "_replicas", "victim.json"
        )]:
            os.utime(path, (old, old))

        writes: list = []

        class _SPool:
            def feature_types(self):
                return {"toy"}

        class Survivor:
            def __init__(self, rid):
                self.rid = rid
                self.tracker = RequestTracker(outdir, replica_id=rid)
                self.pool = _SPool()
                self.telemetry = None

            def submit(self, payload, source):
                req = parse_request(payload, source)
                rec = self.tracker.admit(req)
                data = hashlib.sha256(
                    req.video_path.encode()
                ).hexdigest().encode()
                dest = os.path.join(feat, f"{req.id}.bin")
                duplicate = os.path.exists(dest)
                tmp = f"{dest}.{self.rid}.tmp"
                with open(tmp, "wb") as fh:
                    fh.write(data)
                os.replace(tmp, dest)
                writes.append((req.id, duplicate))
                self.tracker.finish(req, "done", features=[dest])
                return rec

        survivors = []
        for rid in ("sA", "sB"):
            reg = ReplicaRegistry(outdir, rid)
            reg.beat()
            d = Survivor(rid)
            survivors.append((d, SpoolWatcher(
                d, spool, replica_id=rid,
                lease_timeout_s=1.0, registry=reg,
            )))
        for _ in range(3):  # reclaim -> claim/admit -> lease release
            for _, w in survivors:
                w.poll_once()

        probe = survivors[0][0].tracker
        terminal = sum(
            1 for i in range(n)
            if (probe.get(f"job{i}") or {}).get("state") == "done"
        )
        out["serve_steal_requests"] = n
        out["serve_steal_victim_sigkilled_within_budget"] = bool(
            killed and len(stale) == n
        )
        out["serve_steal_terminal"] = terminal
        out["serve_steal_all_terminal_within_budget"] = terminal == n
        out["serve_steal_duplicate_payloads"] = sum(
            1 for _, dup in writes if dup
        )
        out["serve_steal_payload_files"] = len(os.listdir(feat))
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return out


SUB_PARTS = {
    "clip_e2e": _sub_clip_e2e,
    "clip_bf16": _sub_clip_bf16,
    "clip_mixed": _sub_clip_mixed,
    "device_preprocess": _sub_device_preprocess,
    "clip_device_only": lambda: bench_clip_device_only(),
    "i3d_compile_probe": _sub_i3d_compile_probe,
    "conv3d_direct_probe": _sub_conv3d_direct_probe,
    "i3d_device_only": lambda: bench_i3d_device_only(),
    "i3d_e2e": _sub_i3d_e2e,
    "i3d_agg": _sub_i3d_agg,
    "flow_e2e": _sub_flow_e2e,
    "pallas_corr": lambda: bench_pallas_corr(),
    "flash_attention": lambda: bench_flash_attention(),
    "fault_overhead": _sub_fault_overhead,
    "telemetry_overhead": _sub_telemetry_overhead,
    "preflight_overhead": _sub_preflight_overhead,
    "analysis_overhead": _sub_analysis_overhead,
    "numerics_parity": _sub_numerics_parity,
    "serve_latency": _sub_serve_latency,
    "serve_scheduling": _sub_serve_scheduling,
    "serve_cost_model": _sub_serve_cost_model,
    "metrics_endpoint_overhead": _sub_metrics_endpoint_overhead,
    "ledger_overhead": _sub_ledger_overhead,
    "ingest_overlap": _sub_ingest_overlap,
    "cache_serving": _sub_cache_serving,
    "serve_preemption": _sub_serve_preemption,
}


def _run_sub_part(name: str) -> None:
    """Child-process entry (`bench.py --sub <name>`): run one part, print
    its dict on a marker line the parent greps out of stdout."""
    part = SUB_PARTS[name]  # unknown name fails before the slow probe
    _probe_backend()
    cache_dir = os.environ.get("BENCH_COMPILE_CACHE")
    if cache_dir:
        # persistent jit cache shared across the child processes: each
        # part re-compiles the same executables (the isolation is the
        # point), so the cache is where the wall-clock goes on re-runs
        from video_features_tpu.config import (
            ExtractionConfig,
            enable_compile_cache,
        )

        enable_compile_cache(ExtractionConfig(compile_cache=cache_dir))
    print(_SUB_MARK + json.dumps(part()))


def _spawn_sub(name: str, timeout_s: float, env: dict = None) -> dict:
    """Run one bench part in a child process; a TPU-helper crash (or hang)
    there costs only this part's keys, never the parent's collected
    numbers. Failures come back as a single `<name>_error` string so the
    BENCH artifact records WHAT died, not just an absence. ``env`` adds
    overrides on top of the inherited environment (e.g. pinning a part to
    JAX_PLATFORMS=cpu so it can never dial the chip tunnel)."""
    import subprocess

    argv = [sys.executable, os.path.abspath(__file__), "--sub", name]
    try:
        proc = subprocess.run(
            argv, capture_output=True, text=True, timeout=timeout_s,
            env={**os.environ, **env} if env else None,
        )
    except subprocess.TimeoutExpired:
        return {f"{name}_error": f"timed out after {timeout_s:.0f}s"}
    for line in reversed((proc.stdout or "").strip().splitlines()):
        if line.startswith(_SUB_MARK):
            return json.loads(line[len(_SUB_MARK):])
    tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-3:]
    return {f"{name}_error": f"rc={proc.returncode}: " + " | ".join(tail)}


def _probe_backend(timeout_s: float = 180.0, fatal: bool = True) -> bool:
    """Fail fast if the TPU backend is unreachable. The axon tunnel's
    compile helper can die (observed 2026-07-30), after which
    jax.devices() blocks FOREVER — without this guard the whole bench
    hangs instead of reporting an actionable error. ``fatal=False``
    (main, r5): report the outage in-band and let the caller emit an
    artifact carrying the host-side numbers instead of dying with none."""
    import threading

    from video_features_tpu.parallel.devices import pin_platform

    # honor JAX_PLATFORMS (the axon discovery hook ignores the env var —
    # a cpu-pinned bench run must not dial the chip tunnel)
    pin_platform()

    devices: list = []
    errors: list = []

    def probe():
        try:
            import jax

            devices.extend(jax.devices())
        except Exception as e:  # noqa: BLE001 - reported below
            errors.append(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive() or errors or not devices:
        reason = (
            f"did not return within {timeout_s:.0f}s"
            if t.is_alive()
            else (f"raised {errors[0]!r}" if errors else "returned no devices")
        )
        print(
            f"FATAL: jax.devices() {reason} — the TPU backend/tunnel is "
            "unreachable (dead compile helper?). No device benchmark "
            "numbers were produced.",
            file=sys.stderr,
        )
        if fatal:
            os._exit(3)
        return False
    print(f"backend ok: {devices}", file=sys.stderr)
    return True


# -- regression sentinel (`bench.py --compare`) ---------------------------
#
# Pure stdlib (no jax, no numpy math): compares one BENCH artifact
# against the committed trajectory (BENCH_r0*.json) with noise-aware
# tolerances, so CI can fail a PR that regresses a measured number
# without flapping on benchmark jitter. The trajectory is sparse —
# tunnel-dead rounds carry rc!=0 and few or no parsed numbers — so every
# key is judged only against the base files that actually measured it.

# keys that are configuration echoes or environment facts, not
# measurements — never compared
_COMPARE_SKIP_SUBTREES = ("bench_config",)
_COMPARE_SKIP_LEAVES = frozenset({
    "host_cores", "baseline_provenance", "compile_cache",
    "device_contracts", "fatal", "n", "rc",
})


def _flatten_bench(doc: dict) -> tuple:
    """One BENCH artifact -> (numeric {key: float}, budget {key: bool}).
    Accepts the committed shape ({n, cmd, rc, parsed: {value, extra}})
    or a bare parsed dict. The headline `value` flattens to 'headline';
    everything numeric under `extra` flattens dotted."""
    parsed = doc.get("parsed", doc) or {}
    nums, budgets = {}, {}
    v = parsed.get("value")
    if isinstance(v, (int, float)) and not isinstance(v, bool):
        nums["headline"] = float(v)
    vb = parsed.get("vs_baseline")
    if isinstance(vb, (int, float)) and not isinstance(vb, bool):
        nums["vs_baseline"] = float(vb)

    def walk(prefix, obj):
        if isinstance(obj, dict):
            for k, val in sorted(obj.items()):
                if k in _COMPARE_SKIP_SUBTREES or k in _COMPARE_SKIP_LEAVES:
                    continue
                walk(prefix + (k,), val)
        elif isinstance(obj, bool):
            if prefix and prefix[-1].endswith("_within_budget"):
                budgets[".".join(prefix)] = obj
        elif isinstance(obj, (int, float)):
            nums[".".join(prefix)] = float(obj)

    walk((), parsed.get("extra") or {})
    return nums, budgets


def _compare_direction(key: str):
    """'higher' (throughput-like), 'lower' (latency/overhead-like), or
    None (informational: counts, sizes, unknown units — never fails).

    The host_pipeline subtree is informational BY SUBTREE: those keys
    are host-capability sizing numbers (docs/tpu.md tells you to re-run
    them on YOUR host), and rounds land on heterogeneous containers —
    r06's host shifted every decode key ~20% in lockstep (its newer
    ffmpeg even fails the native-decoder build), which is a host change,
    not a code change. Code regressions on the decode/preprocess
    surface still gate through the e2e *_vps keys, which exercise the
    same paths inside the measured loop."""
    if key.startswith("host_pipeline."):
        return None
    # Same reasoning for two raw syscall-capability absolutes: one
    # device-stats/snapshot poll (ledger_sampler_sample_us) and one
    # container-open header probe (preflight_header_only_us_per_video)
    # measure the container's syscall/IO speed — r08's host nearly
    # doubled the sampler poll with zero code change on that path. Their
    # contracts still gate: the *_pct_vs_headline twins and the
    # *_within_budget booleans divide out host speed.
    if key in ("ledger_sampler_sample_us",
               "preflight_header_only_us_per_video"):
        return None
    # The graftcheck sweep seconds measure catalogue size x host speed,
    # and the catalogue GROWS by design (17 -> 23 -> 28 rules across
    # rounds) — round-over-round seconds would flag every deliberate
    # rule-family addition. The gate is analysis_within_budget: an
    # accidentally quadratic pass still blows the in-artifact ceiling.
    if key in ("analysis_graftcheck_cold_s", "analysis_graftcheck_warm_s"):
        return None
    leaf = key.rsplit(".", 1)[-1]
    if (leaf == "headline" or leaf == "vs_baseline"
            or leaf.endswith(("_vps", "_fps", "_per_s"))
            or "speedup" in leaf or "throughput" in leaf):
        return "higher"
    if (leaf.endswith(("_s", "_ms", "_us", "_pct"))
            or "_s_per_" in leaf or "_us_per_" in leaf
            or "overhead" in leaf or "latency" in leaf or "miss" in leaf):
        return "lower"
    return None


def _compare_tolerance(samples: list) -> float:
    """Relative tolerance around the base median. With >= 3 samples the
    spread is measured (3 * MAD / median); fewer samples get a generous
    floor — one sample says nothing about run-to-run noise."""
    import statistics

    med = statistics.median(samples)
    floor = 0.25
    if len(samples) >= 3 and med:
        mad = statistics.median(abs(s - med) for s in samples)
        return max(floor, 3.0 * mad / abs(med))
    return floor


def compare_bench(current: dict, bases: list) -> dict:
    """Compare one parsed BENCH artifact against >= 1 base artifacts.
    Returns {'keys': {key: {...}}, 'regressed': [...], 'improved': [...],
    'base_keys': N}; see _compare_main for the rc contract."""
    import statistics

    cur_nums, cur_budgets = _flatten_bench(current)
    base_flat = [_flatten_bench(b) for b in bases]
    base_keys = sorted({k for nums, _ in base_flat for k in nums})
    out = {"keys": {}, "regressed": [], "improved": [], "base_keys": len(base_keys)}

    for key in base_keys:
        samples = [nums[key] for nums, _ in base_flat if key in nums]
        med = statistics.median(samples)
        direction = _compare_direction(key)
        rec = {
            "direction": direction, "base_median": med,
            "n_samples": len(samples),
        }
        if key not in cur_nums:
            rec["status"] = "missing"  # informational: parts can be skipped
        elif direction is None or med == 0:
            rec.update(current=cur_nums[key], status="info")
        else:
            cur = cur_nums[key]
            tol = _compare_tolerance(samples)
            ratio = cur / med
            rec.update(current=cur, tolerance=round(tol, 4),
                       ratio=round(ratio, 4))
            worse = ratio < 1.0 - tol if direction == "higher" else ratio > 1.0 + tol
            better = ratio > 1.0 + tol if direction == "higher" else ratio < 1.0 - tol
            rec["status"] = "regressed" if worse else ("improved" if better else "ok")
            if worse:
                out["regressed"].append(key)
            elif better:
                out["improved"].append(key)
        out["keys"][key] = rec
    for key in sorted(set(cur_nums) - set(base_keys)):
        out["keys"][key] = {"status": "new", "current": cur_nums[key]}
    # budget booleans are hard gates, not noise-banded measurements: a
    # False *_within_budget in the current artifact is a regression even
    # if no base ever measured that part
    for key, ok in sorted(cur_budgets.items()):
        rec = out["keys"].setdefault(key, {})
        rec.update(current=ok, status="ok" if ok else "regressed")
        if not ok:
            out["regressed"].append(key)
    return out


def _compare_main(argv: list) -> int:
    """``bench.py --compare BASE.json[,BASE2.json...] [BASE3.json ...]
    [--current CUR.json] [-o summary.json]`` — rc 0 pass, 1 regression,
    2 usage / no usable base numbers. --current defaults to the newest
    BENCH_r*.json in the CWD that is not among the bases."""
    import argparse
    import glob as _glob

    p = argparse.ArgumentParser(prog="bench.py --compare")
    p.add_argument("bases", nargs="+",
                   help="base BENCH artifacts (comma- or space-separated)")
    p.add_argument("--current", default=None,
                   help="artifact under test (default: newest BENCH_r*.json "
                        "not among the bases)")
    p.add_argument("-o", "--output", default=None,
                   help="write the comparison summary JSON here (CI artifact)")
    args = p.parse_args(argv)
    base_paths = [b for arg in args.bases for b in arg.split(",") if b]
    current_path = args.current
    if current_path is None:
        pool = sorted(
            set(_glob.glob("BENCH_r*.json")) - {os.path.normpath(b) for b in base_paths}
        )
        if not pool:
            print("compare: no --current and no candidate BENCH_r*.json",
                  file=sys.stderr)
            return 2
        current_path = pool[-1]

    def load(path):
        try:
            with open(path, "r", encoding="utf-8") as f:
                return json.load(f)
        except (OSError, ValueError) as e:
            print(f"compare: cannot read {path}: {e}", file=sys.stderr)
            return None

    bases = [d for d in (load(b) for b in base_paths) if d is not None]
    current = load(current_path)
    if current is None or not bases:
        return 2
    result = compare_bench(current, bases)
    result["current"] = current_path
    result["bases"] = base_paths
    if result["base_keys"] == 0:
        print("compare: no numeric keys in any base artifact", file=sys.stderr)
        if args.output:
            with open(args.output, "w", encoding="utf-8") as f:
                json.dump(result, f, indent=2, sort_keys=True)
        return 2
    print(f"compare: {current_path} vs {len(bases)} base artifact(s), "
          f"{result['base_keys']} base key(s)")
    for key, rec in sorted(result["keys"].items()):
        st = rec.get("status")
        if st in ("regressed", "improved"):
            print(f"  {st.upper():>9} {key}: {rec.get('current')} "
                  f"(base median {rec.get('base_median')}, "
                  f"tol ±{rec.get('tolerance', 0):.0%})"
                  if "tolerance" in rec else
                  f"  {st.upper():>9} {key}: {rec.get('current')}")
    n_ok = sum(1 for r in result["keys"].values() if r.get("status") == "ok")
    print(f"compare: {n_ok} ok, {len(result['improved'])} improved, "
          f"{len(result['regressed'])} regressed")
    if args.output:
        with open(args.output, "w", encoding="utf-8") as f:
            json.dump(result, f, indent=2, sort_keys=True)
    if result["regressed"]:
        print("compare: REGRESSED: " + ", ".join(result["regressed"]),
              file=sys.stderr)
        return 1
    return 0


def main() -> None:
    n_videos = int(os.environ.get("BENCH_VIDEOS", "16"))
    baselines = _load_measured_baselines()
    clip_base = baselines.get("clip_torch_cpu_vps")
    i3d_base = baselines.get("i3d_raft_torch_cpu_vps")

    extra = {}
    state = {
        "metric": "videos/sec/chip (CLIP-ViT-B/32, uni_12, end-to-end)",
        "value": None,
        "unit": "videos/s",
        "vs_baseline": None,
        "extra": extra,
    }

    def emit():
        # a complete-so-far artifact line after EVERY part: the last
        # parseable stdout line is always the fullest capture, so a
        # helper/parent death mid-run can never again zero the artifact
        # (r04 lost its measured CLIP numbers exactly that way)
        print(json.dumps(state), flush=True)

    if clip_base:
        extra["clip_torch_cpu_vps"] = clip_base
    extra["baseline_provenance"] = (
        "reference torch code on this host's CPU (scripts/measure_baseline.py; "
        "BASELINE.md 'Measured baselines')"
    )
    # reproducibility: the knobs this run actually measured with
    group = _clip_group(n_videos)
    extra["bench_config"] = {
        "n_videos": n_videos,
        "clip_video_batch": group,
        "clip_extract_method": CLIP_EXTRACT_METHOD,
        "clip_video_synth": CLIP_SPEC,
        "i3d_video_synth": I3D_SPEC,
        "i3d_stack_batch": I3D_STACK_BATCH,
        # honesty note: the aggregated headline runs N copies of ONE
        # synthetic video, so every row shares one agg_key — grouping
        # efficiency is the best case for --video_batch. Heterogeneous
        # corpora bucket into more keys and flush more padded partial
        # groups; the unaggregated comparison ships in clip_solo_* and
        # the heterogeneous one in clip_mixed_* (2 spatial buckets, 4
        # source resolutions).
        "clip_agg_workload": "same-shape best case (N copies of one video)",
        # the headline number's preprocess path; the --preprocess device
        # comparison ships in clip_device_pre_* / clip_mixed_device_*
        "preprocess_mode": "host",
        "flow_video_synth": FLOW_SPEC,
        # CPU budget the host-preprocess numbers were produced under —
        # the PIL decode+resize pool scales with it, the device path
        # mostly doesn't, so speedup ratios aren't comparable across
        # hosts without it
        "host_cores": len(os.sched_getaffinity(0)),
        "compile_cache": os.environ.get("BENCH_COMPILE_CACHE") or None,
        "device_contracts": _device_contract_ids(),
    }

    # pure-host part FIRST, before any device probe: even a tunnel-dead
    # round carries measured numbers in its artifact (r02-r04 carried none)
    extra.update(bench_host_pipeline())
    emit()
    # the fused device-preprocess program next to the host_preprocess_*
    # keys, in a CPU-pinned child (same backend as the PIL numbers; can't
    # dial a tunnel, so it's safe before the probe)
    extra.setdefault("host_pipeline", {}).update(
        _spawn_sub("device_preprocess", 600.0, env={"JAX_PLATFORMS": "cpu"})
    )
    emit()
    # pure-host like the pipeline part: the fault-tolerance bookkeeping
    # cost (fire() no-ops + manifest appends) vs the chip headline
    extra.update(_spawn_sub("fault_overhead", 300.0, env={"JAX_PLATFORMS": "cpu"}))
    emit()
    # same contract for the telemetry spans/metrics bookkeeping (ISSUE 6
    # <1% ceiling, on-minus-off vs the --telemetry off degradation)
    extra.update(_spawn_sub("telemetry_overhead", 300.0, env={"JAX_PLATFORMS": "cpu"}))
    emit()
    # admission preflight probe cost (ISSUE 9 <1% budget: one container
    # open + header checks + a single-frame grab per video, pure host)
    extra.update(_spawn_sub("preflight_overhead", 300.0, env={"JAX_PLATFORMS": "cpu"}))
    emit()
    # graftcheck latency budget (pure host: AST only, no device work)
    extra.update(_spawn_sub("analysis_overhead", 120.0, env={"JAX_PLATFORMS": "cpu"}))
    emit()
    # GC804 precision contract: admitted bf16 drift vs committed
    # ceilings + the fp32/bf16 throughput pair (smoke off-TPU)
    extra.update(_spawn_sub("numerics_parity", 900.0))
    emit()
    # serving daemon: cold-vs-warm request latency and the coalescing
    # throughput win, on the same CPU backend as the host parts
    extra.update(_spawn_sub("serve_latency", 300.0, env={"JAX_PLATFORMS": "cpu"}))
    emit()
    # scheduling policy part: FIFO-vs-EDF deadline-miss rate and latency
    # percentiles on a pinned deterministic burst (pure host, no device)
    extra.update(_spawn_sub("serve_scheduling", 120.0, env={"JAX_PLATFORMS": "cpu"}))
    emit()
    # device cost ledger steady-state cost (ISSUE 15 <1% ceiling: the
    # instrument_state wrapper's seen-set check + one memory_stats poll)
    extra.update(_spawn_sub("ledger_overhead", 300.0, env={"JAX_PLATFORMS": "cpu"}))
    emit()
    # async-ingest loop structure: completion-queue overlap efficiency vs
    # the stage-sequential serial loop + --frame_delta_threshold skip
    # rate on a static corpus (CPU-pinned: measures the loop, not the chip)
    extra.update(_spawn_sub("ingest_overlap", 900.0, env={"JAX_PLATFORMS": "cpu"}))
    emit()
    # content-addressed cache: warm-hit vs cold-extract on the serve
    # admission path + shared-decode fan-out decode-once/bit-identity
    # hard gates (CPU-pinned: relative numbers are the artifact)
    extra.update(_spawn_sub("cache_serving", 900.0, env={"JAX_PLATFORMS": "cpu"}))
    emit()
    # fleet robustness (ISSUE 18): preemption ON/OFF deadline-miss A/B on
    # the pinned overcommit burst + the 3-replica SIGKILL steal drill
    # (CPU-pinned: the miss-rate delta and the zero-duplicate invariant
    # are the artifact, no device required)
    extra.update(_spawn_sub("serve_preemption", 300.0, env={"JAX_PLATFORMS": "cpu"}))
    emit()

    if not _probe_backend(fatal=False):
        extra["fatal"] = (
            "jax backend unreachable (dead axon compile helper/tunnel?) — "
            "host_pipeline keys above are real; no device numbers exist. "
            "See BASELINE.md outage notes; re-run on a healthy host."
        )
        emit()
        return  # rc 0: the outage is recorded in-band in the artifact

    import jax

    on_tpu = jax.default_backend() == "tpu"
    sub_timeout = float(os.environ.get("BENCH_SUB_TIMEOUT", "1200"))

    def part(name: str) -> dict:
        r = _spawn_sub(name, sub_timeout)
        extra.update(r)
        emit()
        return r

    # headline (child-isolated like everything else, r5)
    clip = part("clip_e2e")
    if "clip_vps" in clip:
        state["value"] = clip["clip_vps"]
        if clip_base:
            state["vs_baseline"] = round(clip["clip_vps"] / clip_base, 3)
        emit()
    # bf16 e2e variant: default-on since r5 (VERDICT r4 next #1 wants it
    # in the DRIVER artifact, which runs plain `python bench.py`); the
    # second XLA compile hits the persistent cache on re-runs
    if os.environ.get("BENCH_BF16") != "0":
        part("clip_bf16")
    # heterogeneous-corpus aggregation, host vs --preprocess device
    part("clip_mixed")
    part("clip_device_only")
    part("pallas_corr")
    # standalone flow extractors, host vs --preprocess device
    part("flow_e2e")

    if os.environ.get("BENCH_SKIP_I3D") != "1":
        # On TPU the i3d parts default to the decomposed conv3d lowering:
        # the direct XLA 3D conv killed the compile helper (and with it
        # the relay + every subsequent part) in rounds 2-4 — see
        # models/common/layers.py::Conv3DCompat and
        # scripts/repro_i3d_conv3d.py. An explicit VFT_CONV3D_IMPL wins.
        if on_tpu and "VFT_CONV3D_IMPL" not in os.environ:
            os.environ["VFT_CONV3D_IMPL"] = "decomposed"
        probe = part("i3d_compile_probe")
        if any(k.endswith("_error") for k in probe):
            extra["i3d_skipped"] = (
                "compile probe failed — i3d parts skipped to protect the relay"
            )
            emit()
        else:
            i3d = part("i3d_e2e")
            if i3d_base and "i3d_raft_vps" in i3d:
                extra["i3d_raft_torch_cpu_vps"] = i3d_base
                extra["i3d_raft_vs_torch_cpu"] = round(
                    i3d["i3d_raft_vps"] / i3d_base, 3
                )
                emit()
            part("i3d_agg")
            part("i3d_device_only")

    if os.environ.get("BENCH_FLASH") == "1":
        # opt-in even in isolation: the L=4096 flash Mosaic compile has
        # crashed the helper before — a crash here would still kill the
        # RELAY for any later run, not just this child
        part("flash_attention")

    # diagnostic, the VERY LAST device-touching action and OPT-IN like
    # flash (same rationale: the direct 3D-conv compile killed the relay
    # — not just the child — in r2-r4, so even after all numbers persist
    # a crash would burn the rest of the window for follow-up chip work).
    # scripts/on_tunnel_up.sh owns this experiment via the repro ladder;
    # set BENCH_DIRECT_PROBE=1 to run it from the bench instead.
    if (
        on_tpu
        and os.environ.get("VFT_CONV3D_IMPL") == "decomposed"
        and os.environ.get("BENCH_DIRECT_PROBE") == "1"
    ):
        part("conv3d_direct_probe")


if __name__ == "__main__":
    if len(sys.argv) == 3 and sys.argv[1] == "--sub":
        sys.exit(_run_sub_part(sys.argv[2]))
    if len(sys.argv) >= 2 and sys.argv[1] == "--compare":
        # pure-host sentinel: no backend probe, no jax import
        sys.exit(_compare_main(sys.argv[2:]))
    sys.exit(main())
