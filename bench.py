#!/usr/bin/env python
"""End-to-end throughput benchmark: videos/sec/chip, CLIP-ViT-B/32 uni_12.

The reference publishes no numbers (BASELINE.md) — its pipeline on GPU is
decode-bound single-threaded per device. The nominal baseline below (1.0
videos/s/device for the full decode->preprocess->encode->fetch loop on
a short clip) stands in for that unpublished number until a measured
reference run replaces it; ``vs_baseline`` is value/nominal.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "videos/s", "vs_baseline": N}
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np

NOMINAL_BASELINE_VPS = 1.0  # unpublished reference throughput stand-in


def main() -> None:
    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP
    from video_features_tpu.parallel.devices import resolve_devices

    from video_features_tpu.utils.synth import synth_video

    n_videos = int(os.environ.get("BENCH_VIDEOS", "16"))
    with tempfile.TemporaryDirectory() as tmp:
        video = synth_video(
            os.path.join(tmp, "bench.mp4"), n_frames=120, width=640, height=360
        )
        cfg = ExtractionConfig(
            allow_random_init=True,
            feature_type="CLIP-ViT-B/32",
            video_paths=[video] * n_videos,
            extract_method="uni_12",
            tmp_path=os.path.join(tmp, "t"),
            output_path=os.path.join(tmp, "o"),
        )
        ex = ExtractCLIP(cfg, external_call=True)
        ex.progress.disable = True
        device = resolve_devices(cfg)[0]
        ex([0], device=device)  # warmup: decode path + XLA compile
        t0 = time.perf_counter()
        results = ex(range(n_videos), device=device)
        dt = time.perf_counter() - t0
        assert len(results) == n_videos and all(
            r["CLIP-ViT-B/32"].shape == (12, 512) for r in results
        )

    vps = n_videos / dt
    print(
        json.dumps(
            {
                "metric": "videos/sec/chip (CLIP-ViT-B/32, uni_12, end-to-end)",
                "value": round(vps, 3),
                "unit": "videos/s",
                "vs_baseline": round(vps / NOMINAL_BASELINE_VPS, 3),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
