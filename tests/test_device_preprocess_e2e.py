"""--preprocess device e2e for the shape-contracted extractors (PR 2).

Full host-vs-device extraction runs for standalone RAFT/PWC and
two-stream I3D. These are the heavyweight companions to the fast
contract-level parity tests in test_shape_contract.py — minutes each on
one CPU core (RAFT's recurrence dominates), so the whole module is
``slow``: excluded from the tier-1 `-m 'not slow'` budget and from the
`-m quick` smoke tier, run by the full CI suite.
"""

import numpy as np
import pytest

from video_features_tpu.config import ExtractionConfig, sanity_check

pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def tiny_flow_videos(tmp_path_factory):
    from video_features_tpu.utils.synth import synth_video

    root = tmp_path_factory.mktemp("devpre_flow")
    # small enough that RAFT's 128-px padder floor dominates: both land
    # on the (128, 128) grid, exercising the identity+edge-pad contract
    return [
        synth_video(str(root / "f1.mp4"), n_frames=8, width=100, height=96, seed=3),
        synth_video(str(root / "f2.mp4"), n_frames=8, width=100, height=96, seed=4),
    ]


def _flow_run(ft, videos, tmp_path, preprocess, video_batch=1, **kw):
    from video_features_tpu.models.pwc.extract_pwc import ExtractPWC
    from video_features_tpu.models.raft.extract_raft import ExtractRAFT

    cls = ExtractRAFT if ft == "raft" else ExtractPWC
    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type=ft,
        video_paths=list(videos),
        batch_size=4,
        preprocess=preprocess,
        video_batch=video_batch,
        tmp_path=str(tmp_path / "tmp"),
        output_path=str(tmp_path / "out"),
        cpu=True,
        **kw,
    )
    sanity_check(cfg)
    return cls(cfg, external_call=True)()


@pytest.mark.parametrize("ft", ["raft", "pwc"])
def test_flow_device_matches_host(ft, tiny_flow_videos, tmp_path):
    """No --side_size: the device contract is identity taps + the padder
    placement, so the model sees bit-identical input and the flow matches
    the host path to float noise."""
    from video_features_tpu.analysis import CompileCounter, assert_within_budget

    host = _flow_run(ft, tiny_flow_videos[:1], tmp_path, "host")
    # device side runs the FULL tiny corpus: the fused path engages in
    # the pipelined loop (>1 video), and the 2-clip run is exactly the
    # {ft}_device_tiny budget scenario (analysis/budget_scenarios.py)
    with CompileCounter() as cc:
        dev = _flow_run(ft, tiny_flow_videos, tmp_path, "device")
    # GC401: one (128, 128) bucket -> one fused executable for the whole
    # corpus, per the committed ceiling (regenerate: --update-budgets).
    assert_within_budget(f"{ft}_device_tiny", cc)
    assert dev[1][ft].shape == (7, 2, 96, 100)
    assert np.isfinite(dev[1][ft]).all()
    h, d = host[0][ft], dev[0][ft]
    assert h.shape == d.shape == (7, 2, 96, 100)
    np.testing.assert_array_equal(host[0]["timestamps_ms"], dev[0]["timestamps_ms"])
    np.testing.assert_allclose(d, h, atol=1e-4, rtol=0)


def test_flow_device_aggregation_matches_solo(tiny_flow_videos, tmp_path):
    """--video_batch under the device contract: per-window taps stack
    across the group; fused results must match solo device results."""
    fused = _flow_run("raft", tiny_flow_videos, tmp_path, "device", video_batch=2)
    for i, v in enumerate(tiny_flow_videos):
        solo = _flow_run("raft", [v], tmp_path, "device")[0]
        np.testing.assert_allclose(
            fused[i]["raft"], solo["raft"], atol=2e-5, rtol=1e-5
        )


def test_flow_device_side_size_contract(tiny_flow_videos, tmp_path):
    """--side_size under device preprocess: fused taps resize onto the
    padder grid of the RESIZED shape; unpad restores that shape."""
    dev = _flow_run(
        "raft", tiny_flow_videos[:1], tmp_path, "device", side_size=64
    )
    flow = dev[0]["raft"]
    # (96, 100) min-edge-64 -> (64, 66); channels-first output
    assert flow.shape == (7, 2, 64, 66)
    assert np.isfinite(flow).all()


def test_flow_device_over_cap_streams_via_host_path(
    tiny_flow_videos, tmp_path, monkeypatch
):
    """Over the prefetch byte cap the device path hands over to the
    streaming host chain (documented parity-identical fallback)."""
    from video_features_tpu.models.pwc import extract_pwc as mod

    prepared = _flow_run("pwc", tiny_flow_videos[:1], tmp_path, "device")
    monkeypatch.setattr(
        mod.ExtractPWC, "PIPELINE_MAX_BYTES", 1, raising=False
    )
    streamed = _flow_run("pwc", tiny_flow_videos[:1], tmp_path, "device")
    np.testing.assert_allclose(
        streamed[0]["pwc"], prepared[0]["pwc"], atol=1e-4, rtol=0
    )


@pytest.mark.parametrize("ft", ["raft", "pwc"])
def test_flow_mesh_device_preprocess_parity(ft, tiny_flow_videos, tmp_path):
    """--sharding mesh --preprocess device for the flow families: the
    fused forward_raw under the declared payload contract (frame axis
    'data', taps replicated, output replicated) against the queue path
    on the same corpus. RAFT is bit-exact; PWC carries the pre-existing
    ~2e-7 sharded-codegen drift of its in-model /64 stretch (the same
    drift the HOST-path mesh shows vs queue — see test_parallel.py), so
    it gets a tight allclose instead.

    The run is also the {ft}_mesh_device_tiny GC401 scenario: mesh
    placement must not add executables over the queue path's one."""
    import jax

    from video_features_tpu.analysis import CompileCounter, assert_within_budget
    from video_features_tpu.parallel.sharding import make_mesh

    queue = _flow_run(ft, tiny_flow_videos, tmp_path / "q", "device")
    from video_features_tpu.models.pwc.extract_pwc import ExtractPWC
    from video_features_tpu.models.raft.extract_raft import ExtractRAFT

    cls = ExtractRAFT if ft == "raft" else ExtractPWC
    cfg = sanity_check(
        ExtractionConfig(
            allow_random_init=True,
            feature_type=ft,
            video_paths=list(tiny_flow_videos),
            batch_size=4,
            preprocess="device",
            sharding="mesh",
            tmp_path=str(tmp_path / "m" / "tmp"),
            output_path=str(tmp_path / "m" / "out"),
            cpu=True,
        )
    )
    with CompileCounter() as cc:
        mesh = cls(cfg, external_call=True)(
            device=make_mesh(jax.devices(), model=1)
        )
    assert_within_budget(f"{ft}_mesh_device_tiny", cc)
    assert len(mesh) == len(queue) == 2
    for m, q in zip(mesh, queue):
        np.testing.assert_array_equal(m["timestamps_ms"], q["timestamps_ms"])
        if ft == "raft":
            np.testing.assert_array_equal(m[ft], q[ft])
        else:
            np.testing.assert_allclose(m[ft], q[ft], atol=1e-5, rtol=0)


def test_i3d_mesh_device_preprocess_parity(sample_video, tmp_path):
    """Two-stream I3D under --sharding mesh --preprocess device: the
    per-stack fused entries (in-body sharding constraint on the uneven
    S+1 frame axis, replicated output) are bit-exact against the queue
    device path on both streams — and stay within the committed
    i3d_mesh_device_two_stream compile budget."""
    import jax

    from video_features_tpu.analysis import CompileCounter, assert_within_budget
    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D
    from video_features_tpu.parallel.sharding import make_mesh

    def cfg(root, sharding):
        return sanity_check(
            ExtractionConfig(
                allow_random_init=True,
                feature_type="i3d",
                video_paths=[sample_video],
                flow_type="pwc",
                extraction_fps=5.0,
                stack_size=10,
                step_size=10,
                preprocess="device",
                sharding=sharding,
                tmp_path=str(root / "tmp"),
                output_path=str(root / "out"),
                cpu=True,
            )
        )

    queue = ExtractI3D(cfg(tmp_path / "q", "queue"), external_call=True)([0])[0]
    with CompileCounter() as cc:
        mesh = ExtractI3D(cfg(tmp_path / "m", "mesh"), external_call=True)(
            [0], device=make_mesh(jax.devices(), model=1)
        )[0]
    assert_within_budget("i3d_mesh_device_two_stream", cc)
    for s in ("rgb", "flow"):
        assert mesh[s].shape == queue[s].shape == (1, 1024)
        np.testing.assert_array_equal(mesh[s], queue[s])
    np.testing.assert_array_equal(mesh["timestamps_ms"], queue["timestamps_ms"])


def test_i3d_device_two_stream_matches_host(sample_video, tmp_path):
    """Both I3D streams under --preprocess device: rgb rides crop-fused
    taps (fixed 224), pwc flow the exact-resized-shape contract. The
    320x240 synth clip resizes to (256, 341) — bit-clean bilinear taps —
    so features match the host path to float noise."""
    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D

    def run(preprocess):
        cfg = ExtractionConfig(
            allow_random_init=True,
            feature_type="i3d",
            video_paths=[sample_video],
            flow_type="pwc",
            extraction_fps=5.0,  # 12 frames -> one 11-frame stack
            stack_size=10,
            step_size=10,
            preprocess=preprocess,
            tmp_path=str(tmp_path / "tmp"),
            output_path=str(tmp_path / "out"),
            cpu=True,
        )
        sanity_check(cfg)
        return ExtractI3D(cfg, external_call=True)([0])[0]

    from video_features_tpu.analysis import CompileCounter, assert_within_budget

    host = run("host")
    with CompileCounter() as cc:
        dev = run("device")
    # GC401: one stack shape -> one executable per stream.
    assert_within_budget("i3d_device_two_stream", cc)
    for s in ("rgb", "flow"):
        assert dev[s].shape == host[s].shape == (1, 1024)
        np.testing.assert_allclose(dev[s], host[s], atol=1e-4, rtol=0)
    np.testing.assert_array_equal(dev["timestamps_ms"], host["timestamps_ms"])
