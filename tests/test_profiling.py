"""utils/profiling.py: StageTimer accumulation and the refcounted
device_trace session (jax.profiler stubbed — a real XPlane trace is
exercised by test_aux.py::test_device_trace_writes_profile; here the
contract under test is the refcounting itself: concurrent workers share
ONE process-global trace, started on the first entry and stopped on the
last exit, surviving a worker that dies inside the region)."""

import re
import threading

import pytest

from video_features_tpu.utils import profiling
from video_features_tpu.utils.profiling import StageTimer, device_trace

pytestmark = pytest.mark.quick


class _FakeProfiler:
    def __init__(self):
        self.events = []

    def start_trace(self, d):
        self.events.append(("start", d))

    def stop_trace(self):
        self.events.append(("stop", None))


@pytest.fixture()
def fake_profiler(monkeypatch):
    import jax

    fake = _FakeProfiler()
    monkeypatch.setattr(jax.profiler, "start_trace", fake.start_trace)
    monkeypatch.setattr(jax.profiler, "stop_trace", fake.stop_trace)
    assert profiling._trace_refs == 0  # suite-level invariant between tests
    return fake


def test_device_trace_none_dir_never_touches_profiler(fake_profiler):
    with device_trace(None):
        pass
    with device_trace(""):
        pass
    assert fake_profiler.events == []
    assert profiling._trace_refs == 0


def test_device_trace_nested_regions_share_one_session(fake_profiler):
    with device_trace("/tmp/prof"):
        with device_trace("/tmp/prof"):
            assert profiling._trace_refs == 2
        # inner exit must NOT stop the shared trace
        assert fake_profiler.events == [("start", "/tmp/prof")]
    assert fake_profiler.events == [("start", "/tmp/prof"), ("stop", None)]
    assert profiling._trace_refs == 0


def test_device_trace_releases_ref_when_body_raises(fake_profiler):
    with pytest.raises(RuntimeError):
        with device_trace("/tmp/prof"):
            raise RuntimeError("worker died mid-trace")
    assert fake_profiler.events[-1] == ("stop", None)
    assert profiling._trace_refs == 0


def test_device_trace_creates_missing_profile_dir(fake_profiler, tmp_path):
    target = tmp_path / "nested" / "prof"
    with device_trace(str(target)):
        pass
    assert target.is_dir()
    assert fake_profiler.events == [("start", str(target)), ("stop", None)]


def test_device_trace_failed_start_leaves_clean_state(monkeypatch, tmp_path):
    """start_trace raising (unwritable dir, wedged profiler) must not
    leak a ref or a half-started session: the very next caller has to be
    able to start cleanly instead of deadlocking or double-starting."""
    import jax

    events = []
    broken = {"on": True}

    def start_trace(d):
        if broken["on"]:
            raise RuntimeError("profiler wedged")
        events.append(("start", d))

    monkeypatch.setattr(jax.profiler, "start_trace", start_trace)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: events.append(("stop", None)))
    assert profiling._trace_refs == 0
    with pytest.raises(RuntimeError, match="wedged"):
        with device_trace(str(tmp_path)):
            pass
    assert profiling._trace_refs == 0
    # cleanup stopped the (possibly half-started) session best-effort
    assert events == [("stop", None)]
    broken["on"] = False
    events.clear()
    with device_trace(str(tmp_path)):
        assert profiling._trace_refs == 1
    assert events == [("start", str(tmp_path)), ("stop", None)]
    assert profiling._trace_refs == 0


def test_device_trace_failed_start_cleanup_error_not_masking(monkeypatch, tmp_path):
    """stop_trace raising during failed-start cleanup (nothing was
    running) must not mask the original start error."""
    import jax

    def start_trace(d):
        raise RuntimeError("no space left on device")

    def stop_trace():
        raise ValueError("no profiler session running")

    monkeypatch.setattr(jax.profiler, "start_trace", start_trace)
    monkeypatch.setattr(jax.profiler, "stop_trace", stop_trace)
    with pytest.raises(RuntimeError, match="no space"):
        with device_trace(str(tmp_path)):
            pass
    assert profiling._trace_refs == 0


def test_device_trace_concurrent_workers_one_start_one_stop(fake_profiler):
    """8 threads racing through the region: exactly one start, exactly
    one stop, and every interleaving keeps the refcount consistent."""
    barrier = threading.Barrier(8)

    def worker():
        barrier.wait()
        with device_trace("/tmp/prof"):
            pass

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    starts = [e for e in fake_profiler.events if e[0] == "start"]
    stops = [e for e in fake_profiler.events if e[0] == "stop"]
    # sequential re-entry after a full drain legitimately restarts, so
    # assert pairing rather than a hard count of 1
    assert len(starts) == len(stops) >= 1
    assert profiling._trace_refs == 0


def test_stage_timer_accumulates_seconds_and_counts(monkeypatch):
    ticks = iter([0.0, 0.25, 1.0, 1.5, 2.0, 2.125])
    monkeypatch.setattr(profiling.time, "perf_counter", lambda: next(ticks))
    t = StageTimer()
    with t.stage("decode"):
        pass
    with t.stage("decode"):
        pass
    with t.stage("device"):
        pass
    assert t.counts["decode"] == 2 and t.counts["device"] == 1
    assert t.seconds["decode"] == pytest.approx(0.75)
    assert t.seconds["device"] == pytest.approx(0.125)


def test_stage_timer_counts_raising_stage(monkeypatch):
    ticks = iter([0.0, 3.0])
    monkeypatch.setattr(profiling.time, "perf_counter", lambda: next(ticks))
    t = StageTimer()
    with pytest.raises(ValueError):
        with t.stage("sink"):
            raise ValueError("disk full")
    assert t.counts["sink"] == 1 and t.seconds["sink"] == pytest.approx(3.0)


def test_stage_timer_summary_format():
    t = StageTimer()
    assert t.summary() == ""  # nothing recorded -> no banner
    with t.stage("decode"):
        pass
    with t.stage("device"):
        pass
    s = t.summary()
    assert s.startswith("per-stage wall time:")
    lines = s.splitlines()[1:]
    # sorted by stage name, one row each, seconds + call count
    assert [ln.split()[0] for ln in lines] == ["decode", "device"]
    assert all(re.search(r"\d+\.\d\ds over 1 calls$", ln) for ln in lines)


def test_stage_timer_threaded_accumulation():
    t = StageTimer()
    n, per = 8, 50

    def worker():
        for _ in range(per):
            with t.stage("prep"):
                pass

    threads = [threading.Thread(target=worker) for _ in range(n)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    assert t.counts["prep"] == n * per
    assert t.seconds["prep"] >= 0.0
