"""Core runtime: config shim, path lists, slices, sink, video/audio IO."""

import argparse
import os
import pickle

import numpy as np
import pytest

from video_features_tpu.config import ExtractionConfig, parse_args, sanity_check
from video_features_tpu.io.audio import read_wav, resample, to_mono
from video_features_tpu.io.paths import form_list_from_user_input, form_slices
from video_features_tpu.io.sink import action_on_extraction
from video_features_tpu.io.video import extract_frames, probe, read_all_frames, stream_frames
from video_features_tpu.utils.labels import load_classes, show_predictions_on_dataset

# whole-module smoke tier (README 'Quick test tier')
pytestmark = pytest.mark.quick


# --- config ---------------------------------------------------------------

def test_parse_args_reference_surface():
    cfg = parse_args(
        ["--feature_type", "CLIP-ViT-B/32", "--cpu", "--extract_method", "uni_12",
         "--on_extraction", "save_numpy"]
    )
    assert cfg.feature_type == "CLIP-ViT-B/32"
    assert cfg.cpu is True
    assert cfg.extract_method == "uni_12"
    assert cfg.on_extraction == "save_numpy"
    assert cfg.batch_size == 1
    assert cfg.flow_type == "pwc"


def test_from_namespace_accepts_reference_style_namespace():
    ns = argparse.Namespace(
        feature_type="resnet50", video_paths=["x.mp4"], batch_size=8,
        device_ids=[0, 1], some_unknown_key="ignored", extraction_fps=None,
    )
    cfg = ExtractionConfig.from_namespace(ns)
    assert cfg.feature_type == "resnet50"
    assert cfg.batch_size == 8
    assert cfg.device_ids == [0, 1]
    assert cfg.extraction_fps is None


def test_sanity_check_rejects_same_out_and_tmp():
    with pytest.raises(AssertionError):
        sanity_check(ExtractionConfig(output_path="./x", tmp_path="./x"))


def test_sanity_check_i3d_stack_size():
    with pytest.raises(AssertionError):
        sanity_check(ExtractionConfig(feature_type="i3d", stack_size=5))
    sanity_check(ExtractionConfig(feature_type="i3d", stack_size=24))


def test_show_pred_pins_one_device():
    cfg = sanity_check(ExtractionConfig(show_pred=True, device_ids=[2, 3]))
    assert cfg.device_ids == [2]


# --- paths / slices -------------------------------------------------------

def test_form_slices_matches_reference_windowing():
    # ref utils/utils.py:117-126 drops the ragged tail
    assert form_slices(100, 15, 15) == [(i * 15, i * 15 + 15) for i in range(6)]
    assert form_slices(64, 64, 64) == [(0, 64)]
    assert form_slices(63, 64, 64) == []
    assert form_slices(10, 4, 2) == [(0, 4), (2, 6), (4, 8), (6, 10)]


def test_form_list_file_with_paths(tmp_path, sample_video):
    listing = tmp_path / "paths.txt"
    listing.write_text(f"{sample_video}\n\n{sample_video}\n")
    cfg = ExtractionConfig(file_with_video_paths=str(listing))
    assert form_list_from_user_input(cfg) == [sample_video, sample_video]


def test_form_list_video_dir_with_flow_dir_pairs(tmp_path):
    vdir, fdir = tmp_path / "v", tmp_path / "f"
    vdir.mkdir(), fdir.mkdir()
    (vdir / "a.mp4").write_bytes(b"x")
    (vdir / "b.mp4").write_bytes(b"x")
    (fdir / "a").mkdir()
    cfg = ExtractionConfig(video_dir=str(vdir), flow_dir=str(fdir))
    pairs = form_list_from_user_input(cfg)
    assert pairs == [(str(vdir / "a.mp4"), str(fdir / "a"))]


def test_form_list_missing_path_raises():
    cfg = ExtractionConfig(video_paths=["/definitely/not/here.mp4"])
    with pytest.raises(ValueError):
        form_list_from_user_input(cfg)


# --- sink -----------------------------------------------------------------

def test_sink_save_numpy_and_pickle_naming(tmp_path):
    feats = {"clip": np.ones((3, 4), np.float32), "fps": 25.0, "timestamps_ms": [0.0]}
    action_on_extraction(feats, "/x/video1.mp4", str(tmp_path), "save_numpy")
    assert (tmp_path / "video1_clip.npy").exists()
    assert not (tmp_path / "video1_fps.npy").exists()
    loaded = np.load(tmp_path / "video1_clip.npy")
    np.testing.assert_array_equal(loaded, feats["clip"])

    action_on_extraction(feats, "/x/video1.mp4", str(tmp_path), "save_pickle",
                         output_direct=True)
    with open(tmp_path / "video1.pkl", "rb") as f:
        np.testing.assert_array_equal(pickle.load(f), feats["clip"])


def test_sink_save_jpg_flow(tmp_path):
    """save_jpg quantizes raw flow with the I3D uint8 map and names files
    the way the flow-from-disk reader globs (flow_x_*.jpg)."""
    import cv2

    # smooth field (like real flow); pure noise would be JPEG's worst case
    yy, xx = np.mgrid[0:48, 0:48].astype(np.float32)
    base = np.stack([np.sin(xx / 8) * 10, np.cos(yy / 8) * 10])
    flow = np.stack([base, -base])  # (2, 2, 48, 48)
    action_on_extraction({"raft": flow}, "v.mp4", str(tmp_path), "save_jpg")
    assert sorted(os.listdir(tmp_path / "v")) == [
        "flow_x_00000.jpg", "flow_x_00001.jpg",
        "flow_y_00000.jpg", "flow_y_00001.jpg",
    ]
    # pixels round-trip the 128 + 255/40*f quantization within JPEG error
    img = cv2.imread(str(tmp_path / "v" / "flow_x_00000.jpg"), cv2.IMREAD_GRAYSCALE)
    expected = np.round(128.0 + 255.0 / 40.0 * np.clip(flow[0, 0], -20, 20))
    assert np.abs(img.astype(np.float32) - expected).mean() < 3.0


def test_sink_save_jpg_rejects_non_flow(tmp_path):
    with pytest.raises(ValueError, match="save_jpg"):
        action_on_extraction(
            {"r21d_rgb": np.zeros((2, 512), np.float32)}, "v.mp4",
            str(tmp_path), "save_jpg",
        )


def test_sink_print_runs(capsys):
    action_on_extraction({"f": np.arange(4.0)}, "v.mp4", ".", "print")
    out = capsys.readouterr().out
    assert "max: 3.0" in out and "mean: 1.5" in out


# --- video IO -------------------------------------------------------------

def test_probe_and_stream(sample_video):
    meta = probe(sample_video)
    assert meta.frame_count == 60
    assert abs(meta.fps - 25.0) < 1e-6
    frames = list(stream_frames(sample_video))
    assert len(frames) == 60
    frame0, ts0 = frames[0]
    assert frame0.shape == (240, 320, 3) and frame0.dtype == np.uint8
    assert ts0 == 0.0
    assert abs(frames[1][1] - 40.0) < 1e-6  # 1000/25


def test_stream_frames_fps_retarget(sample_video):
    frames = list(stream_frames(sample_video, extraction_fps=5.0))
    # 60 frames @25fps = 2.4s -> 12 frames @5fps
    assert len(frames) == 12
    assert abs(frames[1][1] - 200.0) < 1e-6


def test_read_all_frames(sample_video):
    frames, fps, stamps = read_all_frames(sample_video)
    assert len(frames) == 60 and len(stamps) == 60
    assert abs(fps - 25.0) < 1e-6


def test_extract_frames_uni_and_fix(sample_video):
    frames, fps, ts = extract_frames(sample_video, "uni_12")
    assert len(frames) == 12 and len(ts) == 12
    # linspace(1, 58, 12) endpoints
    assert abs(ts[0] - 1000.0 / 25.0) < 1e-6
    assert abs(ts[-1] - 58 * 1000.0 / 25.0) < 1e-6

    frames, fps, ts = extract_frames(sample_video, "fix_5")
    assert len(frames) == 12  # int(60/25*5)


# --- audio IO -------------------------------------------------------------

def test_audio_roundtrip(sample_wav):
    data, sr = read_wav(sample_wav)
    assert sr == 44100 and data.ndim == 2
    mono = to_mono(data)
    assert mono.ndim == 1
    res = resample(mono, sr, 16000)
    expected = int(round(len(mono) * 16000 / 44100))
    assert abs(len(res) - expected) <= 2
    # a 440 Hz tone must survive resampling: check dominant frequency
    spec = np.abs(np.fft.rfft(res * np.hanning(len(res))))
    freq = np.fft.rfftfreq(len(res), 1 / 16000)
    assert abs(freq[spec.argmax()] - 440) < 5


# --- labels ---------------------------------------------------------------

def test_labels_load_and_show(capsys):
    assert len(load_classes("imagenet")) == 1000
    assert len(load_classes("kinetics")) == 400
    logits = np.zeros((1, 1000), np.float32)
    logits[0, 3] = 10.0
    show_predictions_on_dataset(logits, "imagenet")
    out = capsys.readouterr().out
    assert load_classes("imagenet")[3] in out


# --- missing weights are loud (VERDICT r1 #6) ------------------------------

def test_missing_weights_is_hard_error(sample_video, tmp_path):
    """No --weights_path -> RuntimeError naming what was expected; the
    reference never silently runs random weights (ref extract_i3d.py:23-26)."""
    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D

    cfg = ExtractionConfig(
        feature_type="i3d",
        video_paths=[sample_video],
        streams=["rgb"],
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
        cpu=True,
    )
    ex = ExtractI3D(cfg, external_call=True)
    with pytest.raises(RuntimeError, match=r"i3d\[rgb\].*i3d_rgb\.pt"):
        ex([0])


def test_incomplete_weights_dir_is_hard_error(sample_video, tmp_path):
    """A --weights_path directory missing one stream's file names the
    exact absent file."""
    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D

    wdir = tmp_path / "weights"
    wdir.mkdir()
    cfg = ExtractionConfig(
        feature_type="i3d",
        video_paths=[sample_video],
        streams=["rgb"],
        weights_path=str(wdir),
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
        cpu=True,
    )
    ex = ExtractI3D(cfg, external_call=True)
    with pytest.raises(RuntimeError, match="i3d_rgb.pt"):
        ex([0])


def test_sparse_seek_decode_matches_sequential(tmp_path):
    """The sparse random-access path of read_frames_at_indices must return
    bit-identical frames to a sequential decode (seek accuracy check)."""
    from video_features_tpu.io.video import read_frames_at_indices
    from video_features_tpu.utils.synth import synth_video

    video = synth_video(str(tmp_path / "long.mp4"), n_frames=200, width=64, height=48)
    sparse_ix = [3, 50, 120, 199]  # 4*16 < 200 -> seek path (opt-in)
    sparse = read_frames_at_indices(video, sparse_ix, allow_seek=True)
    dense = read_frames_at_indices(video, list(range(200)))  # sequential path
    assert sorted(sparse) == sparse_ix
    for i in sparse_ix:
        np.testing.assert_array_equal(sparse[i], dense[i])


def test_flow_quantize_boundary_no_uint8_wrap():
    """At exactly +bound the reference formula gives 256.0; the storage
    quantizer must clip to 255, not wrap to 0."""
    from video_features_tpu.ops.preprocess import flow_quantize_uint8_np

    q = flow_quantize_uint8_np(np.array([-25.0, -20.0, 0.0, 20.0, 25.0]))
    np.testing.assert_array_equal(q, [0, 0, 128, 255, 255])
    assert q.dtype == np.uint8


def test_fps_retarget_validation():
    from video_features_tpu.config import ExtractionConfig, sanity_check

    base = dict(allow_random_init=True, video_paths=["x.mp4"])
    with pytest.raises(ValueError, match="fps_retarget"):
        sanity_check(ExtractionConfig(feature_type="resnet18",
                                      fps_retarget="bogus", **base))
    # reencode mirrors a reference path that only exists for
    # resnet*/raft/pwc (ref utils/utils.py:222-244)
    with pytest.raises(ValueError, match="reencode"):
        sanity_check(ExtractionConfig(feature_type="i3d",
                                      fps_retarget="reencode", **base))
    sanity_check(ExtractionConfig(feature_type="pwc",
                                  fps_retarget="reencode", **base))


def test_prefetch_frame_cap_byte_budget():
    """The per-video prefetch cap divides the byte budget over the
    decode_workers+2 resident prepared-video slots (advisor r02: flat
    frame caps scaled host RAM with the worker count), with a floor so
    one minimal work unit always prefetches."""
    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.extract.base import BaseExtractor

    def cap(workers, max_bytes=4 << 30, frame_bytes=1 << 20, floor=4):
        ex = BaseExtractor.__new__(BaseExtractor)
        ex.config = ExtractionConfig(decode_workers=workers)
        return ex._prefetch_frame_cap(max_bytes, frame_bytes, floor)

    # 1 worker -> 3 resident slots; 8 workers -> 10
    assert cap(1) == (4 << 30) // 3 // (1 << 20)
    assert cap(8) == (4 << 30) // 10 // (1 << 20)
    assert cap(8) < cap(1)
    # workers=0 (sync decode) still budgets one slot + 2
    assert cap(0) == cap(1)
    # floor wins when the budget rounds down to nothing
    assert cap(1, max_bytes=1 << 20, floor=65) == 65
    assert cap(8, max_bytes=0, floor=4) == 4
