"""Docs-site integrity without mkdocs: this sandbox can't install the
[docs] extra, and CI's `mkdocs build --strict` runs elsewhere — these
pure-python checks catch the same failure classes (nav entries pointing
at missing files, dead relative links between pages) at test time, so a
broken docs tree can't sit green locally and fail only in CI."""

import pathlib
import re

import yaml
import pytest

# whole-module smoke tier (README 'Quick test tier')
pytestmark = pytest.mark.quick

ROOT = pathlib.Path(__file__).resolve().parents[1]
DOCS = ROOT / "docs"


def _nav_files(node):
    if isinstance(node, str):
        yield node
    elif isinstance(node, list):
        for item in node:
            yield from _nav_files(item)
    elif isinstance(node, dict):
        for v in node.values():
            yield from _nav_files(v)


def test_nav_entries_exist():
    cfg = yaml.safe_load((ROOT / "mkdocs.yml").read_text())
    nav = list(_nav_files(cfg.get("nav", [])))
    assert nav, "mkdocs.yml has no nav"
    for rel in nav:
        assert (DOCS / rel).is_file(), f"nav entry {rel!r} missing from docs/"


def test_every_docs_page_is_in_nav():
    cfg = yaml.safe_load((ROOT / "mkdocs.yml").read_text())
    nav = set(_nav_files(cfg.get("nav", [])))
    pages = {
        str(p.relative_to(DOCS)) for p in DOCS.rglob("*.md")
    }
    orphans = pages - nav
    assert not orphans, f"docs pages absent from mkdocs nav: {sorted(orphans)}"


def test_relative_md_links_resolve():
    link = re.compile(r"\]\(([^)#\s]+\.md)(#[^)]*)?\)")
    for page in DOCS.rglob("*.md"):
        for m in link.finditer(page.read_text()):
            target = m.group(1)
            if target.startswith(("http://", "https://")):
                continue
            resolved = (page.parent / target).resolve()
            assert resolved.is_file(), f"{page}: dead link {target!r}"
