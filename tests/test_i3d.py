"""I3D parity vs a torch oracle + end-to-end extraction.

The oracle is a compact torch reimplementation of the reference I3D
(TF-style asymmetric SAME padding, ceil-mode zero-padded max pools) with
state-dict-compatible names (conv3d_*.conv3d/batch3d, mixed_*.branch_*,
conv3d_0c_1x1) — random weights AND random BN stats.
"""

import numpy as np
import pytest
import torch
import torch.nn.functional as F
from torch import nn

import jax.numpy as jnp

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.models.i3d.convert import convert_state_dict
from video_features_tpu.models.i3d.model import build, tf_same_pads


def _fpad(kernel, stride):
    # F.pad wants (wl, wr, ht, hb, dt, db)
    (dt, db), (ht, hb), (wl, wr) = tf_same_pads(kernel, stride)
    return (wl, wr, ht, hb, dt, db)


class TUnit(nn.Module):
    def __init__(self, i, o, k=(1, 1, 1), s=(1, 1, 1), bn=True, bias=False, act=True):
        super().__init__()
        self.pad = _fpad(k, s)
        self.conv3d = nn.Conv3d(i, o, k, s, bias=bias)
        self.bn, self.act = bn, act
        if bn:
            self.batch3d = nn.BatchNorm3d(o)

    def forward(self, x):
        x = self.conv3d(F.pad(x, self.pad))
        if self.bn:
            x = self.batch3d(x)
        return torch.relu(x) if self.act else x


class TPool(nn.Module):
    def __init__(self, k, s):
        super().__init__()
        self.pad, self.k, self.s = _fpad(k, s), k, s

    def forward(self, x):
        return F.max_pool3d(F.pad(x, self.pad), self.k, self.s, ceil_mode=True)


class TMixed(nn.Module):
    def __init__(self, i, o):
        super().__init__()
        self.branch_0 = TUnit(i, o[0])
        self.branch_1 = nn.Sequential(TUnit(i, o[1]), TUnit(o[1], o[2], (3, 3, 3)))
        self.branch_2 = nn.Sequential(TUnit(i, o[3]), TUnit(o[3], o[4], (3, 3, 3)))
        self.branch_3 = nn.Sequential(TPool((3, 3, 3), (1, 1, 1)), TUnit(i, o[5]))

    def forward(self, x):
        return torch.cat(
            [self.branch_0(x), self.branch_1(x), self.branch_2(x), self.branch_3(x)], 1
        )


class TI3D(nn.Module):
    def __init__(self, in_ch=3, classes=400):
        super().__init__()
        self.conv3d_1a_7x7 = TUnit(in_ch, 64, (7, 7, 7), (2, 2, 2))
        self.pool_2a = TPool((1, 3, 3), (1, 2, 2))
        self.conv3d_2b_1x1 = TUnit(64, 64)
        self.conv3d_2c_3x3 = TUnit(64, 192, (3, 3, 3))
        self.pool_3a = TPool((1, 3, 3), (1, 2, 2))
        self.mixed_3b = TMixed(192, [64, 96, 128, 16, 32, 32])
        self.mixed_3c = TMixed(256, [128, 128, 192, 32, 96, 64])
        self.pool_4a = TPool((3, 3, 3), (2, 2, 2))
        self.mixed_4b = TMixed(480, [192, 96, 208, 16, 48, 64])
        self.mixed_4c = TMixed(512, [160, 112, 224, 24, 64, 64])
        self.mixed_4d = TMixed(512, [128, 128, 256, 24, 64, 64])
        self.mixed_4e = TMixed(512, [112, 144, 288, 32, 64, 64])
        self.mixed_4f = TMixed(528, [256, 160, 320, 32, 128, 128])
        self.pool_5a = TPool((2, 2, 2), (2, 2, 2))
        self.mixed_5b = TMixed(832, [256, 160, 320, 32, 128, 128])
        self.mixed_5c = TMixed(832, [384, 192, 384, 48, 128, 128])
        self.conv3d_0c_1x1 = TUnit(1024, classes, bn=False, bias=True, act=False)

    def forward(self, x):
        x = self.pool_2a(self.conv3d_1a_7x7(x))
        x = self.pool_3a(self.conv3d_2c_3x3(self.conv3d_2b_1x1(x)))
        x = self.mixed_3c(self.mixed_3b(x))
        x = self.pool_4a(x)
        x = self.mixed_4f(self.mixed_4e(self.mixed_4d(self.mixed_4c(self.mixed_4b(x)))))
        x = self.pool_5a(x)
        x = self.mixed_5c(self.mixed_5b(x))
        x = F.avg_pool3d(x, (2, 7, 7), (1, 1, 1))
        feats = x.mean(dim=(2, 3, 4))
        logits = self.conv3d_0c_1x1(x).mean(dim=(2, 3, 4))
        return feats, logits


def _torch_oracle(in_ch=3, seed=0):
    torch.manual_seed(seed)
    model = TI3D(in_ch)
    with torch.no_grad():
        for m in model.modules():
            if isinstance(m, nn.BatchNorm3d):
                m.running_mean.normal_(0, 0.3)
                m.running_var.uniform_(0.5, 2.0)
    model.eval()
    return model


@pytest.mark.parametrize("in_ch", [3, 2])
def test_i3d_matches_torch_oracle(in_ch):
    oracle = _torch_oracle(in_ch)
    sd = {k: v.numpy() for k, v in oracle.state_dict().items()}
    params = convert_state_dict(sd)

    rng = np.random.RandomState(0)
    x = rng.uniform(-1, 1, size=(1, 10, 224, 224, in_ch)).astype(np.float32)
    with torch.no_grad():
        ref_f, ref_l = oracle(torch.from_numpy(np.transpose(x, (0, 4, 1, 2, 3))))
    feats, logits = build().apply({"params": params}, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(feats), ref_f.numpy(), atol=2e-4)
    np.testing.assert_allclose(np.asarray(logits), ref_l.numpy(), atol=2e-4)


def test_flow_transform_chain_matches_torch():
    """crop -> clamp[-20,20] -> uint8 quantize -> [-1,1]
    (ref i3d/transforms/transforms.py:21-51)."""
    from video_features_tpu.models.i3d.extract_i3d import center_crop
    from video_features_tpu.ops.preprocess import flow_to_uint8, scale_to_1_1

    rng = np.random.RandomState(0)
    flow = (rng.randn(4, 240, 230, 2) * 15).astype(np.float32)

    t = torch.from_numpy(np.transpose(flow, (0, 3, 1, 2)))
    H, W = t.shape[-2:]
    fh, fw = (H - 224) // 2, (W - 224) // 2
    t = t[..., fh : fh + 224, fw : fw + 224]
    t = torch.clamp(t, -20, 20)
    t = (128 + 255 / 40 * t).round()
    t = 2 * t / 255 - 1

    ours = scale_to_1_1(flow_to_uint8(center_crop(jnp.asarray(flow))))
    np.testing.assert_allclose(
        np.asarray(ours), np.transpose(t.numpy(), (0, 2, 3, 1)), atol=1e-5
    )


def test_converter_rejects_unconsumed():
    sd = {k: v.numpy() for k, v in _torch_oracle().state_dict().items()}
    sd["stray.weight"] = np.zeros(3, np.float32)
    with pytest.raises(ValueError, match="unconsumed"):
        convert_state_dict(sd)


def test_extract_i3d_rgb_end_to_end(sample_video, tmp_path):
    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D

    cfg = ExtractionConfig(
        feature_type="i3d",
        video_paths=[sample_video],
        streams=["rgb"],
        stack_size=10,
        step_size=10,
        on_extraction="save_numpy",
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
        cpu=True,
    )
    ex = ExtractI3D(cfg)
    ex([0])
    import pathlib

    saved = {p.name: p for p in pathlib.Path(tmp_path / "out").rglob("*.npy")}
    assert set(saved) == {"synth_rgb.npy"}
    feats = np.load(saved["synth_rgb.npy"])
    # 60-frame clip < 65 -> upsampled to 65 frames; 11-frame windows step 10
    assert feats.shape == (6, 1024)
    assert np.isfinite(feats).all()


def test_extract_i3d_precomputed_flow(sample_video, tmp_path):
    import cv2

    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D

    # flow dirs pair with videos by matching stem (ref utils/utils.py:172-181)
    flow_dir = tmp_path / "synth"
    flow_dir.mkdir()
    rng = np.random.RandomState(0)
    for i in range(70):
        for axis in ("x", "y"):
            img = rng.randint(0, 256, size=(256, 300), dtype=np.uint8)
            cv2.imwrite(str(flow_dir / f"flow_{axis}_{i:05d}.jpg"), img)

    cfg = ExtractionConfig(
        feature_type="i3d",
        video_paths=[sample_video],
        flow_paths=[str(flow_dir)],
        flow_type="flow",
        streams=["flow"],
        stack_size=10,
        step_size=10,
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
        cpu=True,
    )
    res = ExtractI3D(cfg, external_call=True)([0])
    feats = res[0]["flow"]
    # 65 sampled frames, 10-frame windows step 10 -> 6 stacks
    assert feats.shape == (6, 1024)
    assert np.isfinite(feats).all()


def test_extract_i3d_two_stream_pwc(sample_video, tmp_path):
    """The north-star config: RGB + PWC flow in one pass."""
    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D

    cfg = ExtractionConfig(
        feature_type="i3d",
        video_paths=[sample_video],
        flow_type="pwc",
        extraction_fps=5.0,  # 12 frames -> one 11-frame stack
        stack_size=10,
        step_size=10,
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
        cpu=True,
    )
    res = ExtractI3D(cfg, external_call=True)([0])
    out = res[0]
    assert out["rgb"].shape == (1, 1024)
    assert out["flow"].shape == (1, 1024)
    assert np.isfinite(out["rgb"]).all() and np.isfinite(out["flow"]).all()
    # fps in the output dict is the SOURCE fps (ref extract_i3d.py:240)
    assert float(out["fps"]) == 25.0
