"""I3D transforms + end-to-end extraction.

Model parity lives in tests/test_reference_parity.py, which oracles
against the actual reference source (/root/reference/models/i3d/
i3d_src/i3d_net.py) at the real 64-frame stack size — the round-1
builder-written torch mirror was deleted in its favor.
"""

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.models.i3d.convert import convert_state_dict


@pytest.mark.quick
def test_flow_transform_chain_matches_torch():
    """crop -> clamp[-20,20] -> uint8 quantize -> [-1,1]
    (ref i3d/transforms/transforms.py:21-51)."""
    from video_features_tpu.models.i3d.extract_i3d import center_crop
    from video_features_tpu.ops.preprocess import flow_to_uint8, scale_to_1_1

    rng = np.random.RandomState(0)
    flow = (rng.randn(4, 240, 230, 2) * 15).astype(np.float32)

    t = torch.from_numpy(np.transpose(flow, (0, 3, 1, 2)))
    H, W = t.shape[-2:]
    fh, fw = (H - 224) // 2, (W - 224) // 2
    t = t[..., fh : fh + 224, fw : fw + 224]
    t = torch.clamp(t, -20, 20)
    t = (128 + 255 / 40 * t).round()
    t = 2 * t / 255 - 1

    ours = scale_to_1_1(flow_to_uint8(center_crop(jnp.asarray(flow))))
    np.testing.assert_allclose(
        np.asarray(ours), np.transpose(t.numpy(), (0, 2, 3, 1)), atol=1e-5
    )


def test_converter_rejects_unconsumed():
    from test_reference_parity import _ref_import

    i3d_mod = _ref_import("models.i3d.i3d_src.i3d_net")
    torch.manual_seed(0)
    sd = {k: v.numpy() for k, v in i3d_mod.I3D(400).state_dict().items()}
    sd["stray.weight"] = np.zeros(3, np.float32)
    with pytest.raises(ValueError, match="unconsumed"):
        convert_state_dict(sd)


def test_extract_i3d_rgb_end_to_end(sample_video, tmp_path):
    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="i3d",
        video_paths=[sample_video],
        streams=["rgb"],
        stack_size=10,
        step_size=10,
        on_extraction="save_numpy",
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
        cpu=True,
    )
    ex = ExtractI3D(cfg)
    ex([0])
    import pathlib

    saved = {p.name: p for p in pathlib.Path(tmp_path / "out").rglob("*.npy")}
    assert set(saved) == {"synth_rgb.npy"}
    feats = np.load(saved["synth_rgb.npy"])
    # 60-frame clip < 65 -> upsampled to 65 frames; 11-frame windows step 10
    assert feats.shape == (6, 1024)
    assert np.isfinite(feats).all()


def test_extract_i3d_precomputed_flow(sample_video, tmp_path):
    import cv2

    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D

    # flow dirs pair with videos by matching stem (ref utils/utils.py:172-181)
    flow_dir = tmp_path / "synth"
    flow_dir.mkdir()
    rng = np.random.RandomState(0)
    for i in range(70):
        for axis in ("x", "y"):
            img = rng.randint(0, 256, size=(256, 300), dtype=np.uint8)
            cv2.imwrite(str(flow_dir / f"flow_{axis}_{i:05d}.jpg"), img)

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="i3d",
        video_paths=[sample_video],
        flow_paths=[str(flow_dir)],
        flow_type="flow",
        streams=["flow"],
        stack_size=10,
        step_size=10,
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
        cpu=True,
    )
    res = ExtractI3D(cfg, external_call=True)([0])
    feats = res[0]["flow"]
    # 65 sampled frames, 10-frame windows step 10 -> 6 stacks
    assert feats.shape == (6, 1024)
    assert np.isfinite(feats).all()


def test_extract_i3d_two_stream_pwc(sample_video, tmp_path):
    """The north-star config: RGB + PWC flow in one pass."""
    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="i3d",
        video_paths=[sample_video],
        flow_type="pwc",
        extraction_fps=5.0,  # 12 frames -> one 11-frame stack
        stack_size=10,
        step_size=10,
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
        cpu=True,
    )
    res = ExtractI3D(cfg, external_call=True)([0])
    out = res[0]
    assert out["rgb"].shape == (1, 1024)
    assert out["flow"].shape == (1, 1024)
    assert np.isfinite(out["rgb"]).all() and np.isfinite(out["flow"]).all()
    # fps in the output dict is the SOURCE fps (ref extract_i3d.py:240)
    assert float(out["fps"]) == 25.0


def test_flow_roundtrip_save_jpg_matches_on_the_fly(tmp_path):
    """The reference workflow 'extract flow -> save jpgs -> i3d
    --flow_type flow' (ref utils/utils.py:98-110 + extract_i3d.py:195-229),
    driveable end-to-end here: standalone PWC writes quantized flow JPEGs
    via --on_extraction save_jpg, and i3d consumes them, matching the
    on-the-fly pwc-flow features within the uint8-quantization + JPEG
    budget. RAFT shares the identical save/load path."""
    import pathlib

    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D
    from video_features_tpu.models.pwc.extract_pwc import ExtractPWC
    from video_features_tpu.utils.synth import synth_video

    # >=65 frames dodges the upsample-to-65 quirk so the standalone
    # extractor and i3d see the same frame grid; 128px source upscales to
    # the same 256x256 in both (pil_resize, side 256)
    video = synth_video(
        str(tmp_path / "rt.mp4"), n_frames=65, width=128, height=128
    )

    pwc_cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="pwc",
        video_paths=[video],
        batch_size=8,
        side_size=256,
        on_extraction="save_jpg",
        output_path=str(tmp_path / "flowjpg"),
        tmp_path=str(tmp_path / "tmp"),
        cpu=True,
    )
    ExtractPWC(pwc_cfg)([0])
    flow_dir = pathlib.Path(tmp_path / "flowjpg" / "pwc" / "rt")
    assert len(list(flow_dir.glob("flow_x_*.jpg"))) == 64

    common = dict(
        allow_random_init=True,
        feature_type="i3d",
        streams=["flow"],
        stack_size=10,
        step_size=30,
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
        cpu=True,
    )
    fly = ExtractI3D(
        ExtractionConfig(video_paths=[video], flow_type="pwc", **common),
        external_call=True,
    )([0])[0]["flow"]
    disk = ExtractI3D(
        ExtractionConfig(
            video_paths=[video],
            flow_paths=[str(flow_dir)],
            flow_type="flow",
            **common,
        ),
        external_call=True,
    )([0])[0]["flow"]

    assert fly.shape == disk.shape == (2, 1024)
    rel = np.linalg.norm(fly - disk) / max(np.linalg.norm(fly), 1e-12)
    assert rel < 0.05, f"round-trip relative L2 {rel}"


def test_i3d_pipelined_outputs_identical(sample_video):
    """I3D's new prepare/dispatch/fetch split (--decode_workers + lag-1
    stack fetch) is a pure scheduling change: features bit-identical to
    the serial path across a multi-video run."""
    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D

    def run(workers):
        cfg = ExtractionConfig(
            allow_random_init=True,
            feature_type="i3d",
            flow_type="raft",
            streams=["rgb"],  # rgb alone exercises the split; skips the
            # expensive RAFT compile (the flow stream shares the machinery)
            video_paths=[sample_video] * 2,
            stack_size=10,
            step_size=24,
            decode_workers=workers,
            cpu=True,
        )
        ex = ExtractI3D(cfg, external_call=True)
        ex.progress.disable = True
        return ex(range(2))

    serial = run(0)
    piped = run(2)
    assert len(serial) == len(piped) == 2
    for s, p in zip(serial, piped):
        np.testing.assert_array_equal(s["rgb"], p["rgb"])
        np.testing.assert_array_equal(s["timestamps_ms"], p["timestamps_ms"])


def test_i3d_stack_batching_matches_per_stack(sample_video):
    """--batch_size B fuses B window stacks per device call (3 stacks at
    B=2 exercises one full group AND the zero-padded partial); features
    must match the per-stack run. rgb pins the plain batched path, pwc
    pins the vmapped flow-net path."""
    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D

    def run(batch_size, streams, flow_type):
        cfg = ExtractionConfig(
            allow_random_init=True,
            feature_type="i3d",
            flow_type=flow_type,
            streams=streams,
            video_paths=[sample_video],
            stack_size=10,
            step_size=24,
            batch_size=batch_size,
            cpu=True,
        )
        ex = ExtractI3D(cfg, external_call=True)
        ex.progress.disable = True
        (r,) = ex([0])
        return r

    solo = run(1, ["rgb"], "pwc")
    fused = run(2, ["rgb"], "pwc")
    assert solo["rgb"].shape == fused["rgb"].shape == (3, 1024)
    np.testing.assert_allclose(fused["rgb"], solo["rgb"], atol=1e-5, rtol=1e-5)

    solo_f = run(1, ["flow"], "pwc")
    fused_f = run(2, ["flow"], "pwc")
    assert solo_f["flow"].shape == fused_f["flow"].shape == (3, 1024)
    np.testing.assert_allclose(
        fused_f["flow"], solo_f["flow"], atol=1e-4, rtol=1e-4
    )


def test_i3d_stack_batching_raft_and_disk_flow(sample_video, tmp_path):
    """The two remaining batched branches: the RAFT vmap closure and the
    disk-flow group stacking/zero-padding (each has its own code in
    dispatch_prepared/_fns_for_shape)."""
    import cv2

    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D

    def run(batch_size, flow_type, **extra):
        cfg = ExtractionConfig(
            allow_random_init=True,
            feature_type="i3d",
            flow_type=flow_type,
            streams=["flow"],
            stack_size=10,
            step_size=24,
            batch_size=batch_size,
            cpu=True,
            **extra,
        )
        ex = ExtractI3D(cfg, external_call=True)
        ex.progress.disable = True
        (r,) = ex([0])
        return r

    # raft: the vmapped sequence view over the group
    solo = run(1, "raft", video_paths=[sample_video])
    fused = run(2, "raft", video_paths=[sample_video])
    assert solo["flow"].shape == fused["flow"].shape == (3, 1024)
    np.testing.assert_allclose(fused["flow"], solo["flow"], atol=1e-4, rtol=1e-4)

    # disk flow: stems pair by name; group stacking of the JPEG windows
    flow_dir = tmp_path / "synth"
    flow_dir.mkdir()
    rng = np.random.RandomState(0)
    for i in range(60):
        for axis in ("x", "y"):
            img = rng.randint(0, 256, size=(256, 300), dtype=np.uint8)
            cv2.imwrite(str(flow_dir / f"flow_{axis}_{i:05d}.jpg"), img)
    kw = dict(
        video_paths=[sample_video], flow_paths=[str(flow_dir)]
    )
    solo_d = run(1, "flow", **kw)
    fused_d = run(2, "flow", **kw)
    assert solo_d["flow"].shape == fused_d["flow"].shape
    np.testing.assert_allclose(
        fused_d["flow"], solo_d["flow"], atol=1e-5, rtol=1e-5
    )


def test_i3d_over_cap_video_defers_decode(sample_video, monkeypatch):
    """Videos whose sampled frame count exceeds PIPELINE_MAX_FRAMES skip
    host prefetch (decode happens in the dispatch phase) but produce
    identical features."""
    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D

    def run():
        cfg = ExtractionConfig(
            allow_random_init=True,
            feature_type="i3d",
            flow_type="raft",
            streams=["rgb"],
            video_paths=[sample_video] * 2,
            stack_size=10,
            step_size=24,
            decode_workers=2,
            cpu=True,
        )
        ex = ExtractI3D(cfg, external_call=True)
        ex.progress.disable = True
        payload = ex.prepare(ex.path_list[0])
        return ex, payload

    ex, payload = run()
    assert payload[0] is not None  # under the cap: prefetched
    ref = ex(range(2))

    monkeypatch.setattr(ExtractI3D, "PIPELINE_MAX_FRAMES", 5)
    ex2, payload2 = run()
    assert payload2[:3] == (None, None, False)  # over the cap: deferred
    out = ex2(range(2))
    for s, p in zip(ref, out):
        np.testing.assert_array_equal(s["rgb"], p["rgb"])


@pytest.mark.quick
def test_conv3d_decomposed_matches_direct(monkeypatch):
    """Conv3DCompat's sum-of-2D-convs lowering (the TPU 3D-conv-crash
    workaround, VFT_CONV3D_IMPL=decomposed) is numerically identical to
    the direct lowering on the same params — including strided time,
    asymmetric TF-SAME pads, and bias."""
    import jax

    from video_features_tpu.models.common.layers import Conv3DCompat
    from video_features_tpu.models.i3d.model import tf_same_pads

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 9, 20, 20, 4).astype(np.float32))
    for kernel, stride, bias in [
        ((7, 7, 7), (2, 2, 2), False),  # the I3D stem shape
        ((3, 3, 3), (1, 1, 1), False),
        ((1, 1, 1), (1, 1, 1), True),
        ((2, 3, 3), (2, 1, 1), True),  # even kt + strided time
    ]:
        m = Conv3DCompat(8, kernel, stride, tf_same_pads(kernel, stride),
                         use_bias=bias)
        params = m.init(jax.random.PRNGKey(0), x)
        monkeypatch.setenv("VFT_CONV3D_IMPL", "direct")
        direct = m.apply(params, x)
        monkeypatch.setenv("VFT_CONV3D_IMPL", "decomposed")
        decomp = m.apply(params, x)
        assert direct.shape == decomp.shape
        np.testing.assert_allclose(
            np.asarray(direct), np.asarray(decomp), atol=2e-5,
            err_msg=f"kernel={kernel} stride={stride}",
        )


def test_conv3d_impl_env_validation(monkeypatch):
    from video_features_tpu.models.common.layers import conv3d_impl

    monkeypatch.setenv("VFT_CONV3D_IMPL", "bogus")
    with pytest.raises(ValueError, match="direct|decomposed"):
        conv3d_impl()


def test_extract_i3d_conv3d_impl_flag(monkeypatch, sample_video):
    """--conv3d_impl threads into THIS extractor's model (never the
    process env — r5 review: two extractors with different configs in
    one process must not clobber each other); 'auto' defers to the
    VFT_CONV3D_IMPL env var at trace time."""
    import os

    from video_features_tpu.models.common.layers import conv3d_impl
    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D

    def make(impl):
        return ExtractI3D(
            ExtractionConfig(
                allow_random_init=True,
                feature_type="i3d",
                video_paths=[sample_video],
                conv3d_impl=impl,
            ),
            external_call=True,
        )

    env_before = os.environ.get("VFT_CONV3D_IMPL")
    a = make("decomposed")
    b = make("direct")
    c = make("auto")
    assert a.conv_impl == "decomposed"
    assert b.conv_impl == "direct"  # a's choice did not leak into b
    assert c.conv_impl is None  # auto -> env decides at trace time
    assert os.environ.get("VFT_CONV3D_IMPL") == env_before  # no env writes
    monkeypatch.setenv("VFT_CONV3D_IMPL", "decomposed")
    assert conv3d_impl() == "decomposed"  # what c's model would trace with


@pytest.mark.quick
def test_i3d_agg_key_declines_short_videos(sample_video):
    """A video sampled to fewer than stack_size+1 frames yields zero
    windows — agg_key must decline (advisor r4: an all-short group used
    to IndexError in dispatch_group and ride solo_fallback's spurious
    traceback to the right answer)."""
    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D

    ex = ExtractI3D(
        ExtractionConfig(
            allow_random_init=True,
            feature_type="i3d",
            video_paths=[sample_video],
        ),
        external_call=True,
    )
    frame = np.zeros((32, 32, 3), np.uint8)
    short = (([frame] * 5, 25.0, [0.0] * 5), None, False, None)
    assert ex.agg_key(short) is None
    ok = (([frame] * (ex.stack_size + 1), 25.0, [0.0] * 65), None, False, None)
    assert ex.agg_key(ok) is not None
