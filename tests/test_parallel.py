"""Multi-chip layer: work-queue scheduler over 8 virtual devices, mesh
construction, GSPMD-sharded apply, and the driver's multi-chip dry run.

conftest.py forces XLA_FLAGS=--xla_force_host_platform_device_count=8 —
the CPU simulation of an 8-chip host (SURVEY.md §4c).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.parallel.devices import resolve_devices
from video_features_tpu.parallel.scheduler import parallel_feature_extraction
from video_features_tpu.parallel.sharding import (
    build_sharded_apply,
    clip_vit_param_specs,
    make_mesh,
    shard_params,
)


@pytest.mark.quick
def test_eight_virtual_devices_present():
    assert len(jax.devices()) == 8


@pytest.mark.quick
def test_resolve_devices_ids_and_cpu():
    cfg = ExtractionConfig(device_ids=[0, 2], cpu=False)
    devs = resolve_devices(cfg)
    assert [d.id for d in devs] == [0, 2]
    assert len(resolve_devices(ExtractionConfig(cpu=True))) >= 1


def test_parallel_extraction_covers_all_videos(sample_video, tmp_path):
    """4 devices drain a 6-video queue; every video lands in the sink
    exactly once (the reference loses a dead worker's shard — here the
    queue is shared)."""
    import pathlib

    videos = []
    for i in range(6):
        dst = tmp_path / f"v{i}.mp4"
        dst.write_bytes(pathlib.Path(sample_video).read_bytes())
        videos.append(str(dst))

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="resnet18",
        video_paths=videos,
        extraction_fps=2.0,
        batch_size=4,
        device_ids=[0, 1, 2, 3],
        on_extraction="save_numpy",
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
    )
    from video_features_tpu.models.resnet.extract_resnet import ExtractResNet

    ex = ExtractResNet(cfg)
    parallel_feature_extraction(ex, resolve_devices(cfg))

    saved = sorted(p.name for p in pathlib.Path(tmp_path / "out").rglob("*.npy"))
    assert saved == [f"v{i}_resnet18.npy" for i in range(6)]
    shapes = {
        np.load(p).shape for p in pathlib.Path(tmp_path / "out").rglob("*.npy")
    }
    assert all(s[1] == 512 and s[0] >= 4 for s in shapes)


@pytest.mark.quick
def test_make_mesh_shapes():
    mesh = make_mesh(jax.devices(), model=2)
    assert mesh.shape == {"data": 4, "model": 2}
    with pytest.raises(ValueError):
        make_mesh(jax.devices(), model=3)


def test_sharded_clip_matches_single_device():
    """TP+DP sharded forward == unsharded forward (GSPMD collectives only
    move partials; the math must not change)."""
    from video_features_tpu.models.clip.model import (
        CLIPVisionConfig,
        VisionTransformer,
        init_params,
    )

    cfg = CLIPVisionConfig(
        patch_size=16, width=64, layers=2, heads=2, embed_dim=32, image_size=32
    )
    model = VisionTransformer(cfg)
    params = init_params(cfg)
    x = jnp.asarray(
        np.random.RandomState(0).randn(8, 3, 32, 32).astype(np.float32)
    )
    ref = model.apply({"params": params}, x)

    mesh = make_mesh(jax.devices(), model=2)
    sharded = shard_params(params, mesh)
    fn = build_sharded_apply(model, mesh)
    out = fn(sharded, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    # the TP specs actually shard something
    specs = clip_vit_param_specs(params)
    assert any(tuple(s) != () for s in jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec)
    ))


def test_real_width_clip_tp_matches_single_device():
    """TP at the REAL ViT-B/32 width (768, 12 heads) — model=4 splits
    each 64-d head group across chips; features must match the unsharded
    graph (model=2 at real width is covered end-to-end by
    test_mesh_cli_matches_queue_outputs)."""
    from video_features_tpu.models.clip.model import (
        CLIP_VIT_B32,
        VisionTransformer,
        init_params,
    )

    model = VisionTransformer(CLIP_VIT_B32)
    params = init_params(CLIP_VIT_B32)
    x = jnp.asarray(
        np.random.RandomState(0).randn(8, 3, 224, 224).astype(np.float32)
    )
    ref = np.asarray(jax.jit(lambda p, v: model.apply({"params": p}, v))(params, x))
    mesh = make_mesh(jax.devices(), model=4)
    out = build_sharded_apply(model, mesh)(shard_params(params, mesh), x)
    assert out.shape == (8, 512)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-4)


def test_graft_dryrun_multichip():
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)


# --- fault tolerance (VERDICT r1 #8) ---------------------------------------

class _FakeExtractor:
    """Duck-typed extractor whose __call__ can die OUTSIDE the per-video
    isolation that real extractors provide — simulating a warmup-adjacent
    escape (OOM, sink failure) that kills the worker thread."""

    def __init__(self, n, die_on_device=None):
        import threading
        from tqdm import tqdm

        self.path_list = list(range(n))
        self.config = ExtractionConfig(allow_random_init=True)
        self.progress = tqdm(total=n, disable=True)
        self.done = []
        self.die_on_device = die_on_device
        self._lock = threading.Lock()
        self._died = False

    def warmup(self, device):
        return None

    def __call__(self, indices, device=None):
        with self._lock:
            if (
                self.die_on_device is not None
                and device.id == self.die_on_device
                and not self._died
            ):
                self._died = True
                raise RuntimeError("boom: escape past per-video isolation")
            self.done.extend(int(i) for i in indices)
        import time

        time.sleep(0.02)  # keep the queue alive until the dying worker pulls


def test_worker_death_requeues_in_flight_item(capsys):
    """A worker that dies holding an item must not lose it: the item is
    re-queued and completed by the surviving workers, and the run says so."""
    ex = _FakeExtractor(8, die_on_device=1)
    parallel_feature_extraction(ex, jax.devices()[:2])
    assert sorted(ex.done) == list(range(8))
    assert "died mid-run" in capsys.readouterr().out


def test_all_workers_dead_raises():
    class AlwaysDies(_FakeExtractor):
        def __call__(self, indices, device=None):
            raise RuntimeError("boom")

    ex = AlwaysDies(4)
    with pytest.raises(RuntimeError, match="unprocessed"):
        parallel_feature_extraction(ex, jax.devices()[:2])


# --- product mesh path: --sharding mesh (VERDICT r1 #5) --------------------


def _run_main(sample_video, out, extra):
    import main as cli

    cli.main(
        [
            "--feature_type", "CLIP-ViT-B/32",
            "--video_paths", sample_video,
            "--extract_method", "uni_12",
            "--on_extraction", "save_numpy",
            "--output_path", str(out),
            "--tmp_path", str(out) + "_tmp",
            "--allow_random_init",
        ]
        + extra
    )
    files = sorted((out / "CLIP-ViT-B/32").glob("*.npy")) or sorted(
        out.rglob("*.npy")
    )
    assert len(files) == 1
    return np.load(files[0])


def test_mesh_cli_matches_queue_outputs(sample_video, tmp_path):
    """`--sharding mesh` through the real CLI produces the same features as
    queue mode on the 8-virtual-device mesh (ref main.py:49-55 is the
    surface being upgraded). Pure-DP mesh (model=1) must be byte-identical:
    every frame's math is untouched, only placement changes. TP (model=2)
    reorders the hidden-dim reductions (psum of partials), so it gets a
    tight tolerance instead."""
    queue = _run_main(sample_video, tmp_path / "q", ["--sharding", "queue"])
    mesh_dp = _run_main(
        sample_video, tmp_path / "m1", ["--sharding", "mesh", "--mesh_model", "1"]
    )
    np.testing.assert_array_equal(mesh_dp, queue)
    mesh_tp = _run_main(
        sample_video, tmp_path / "m2", ["--sharding", "mesh", "--mesh_model", "2"]
    )
    np.testing.assert_allclose(mesh_tp, queue, atol=2e-4)


def test_mesh_context_cli_matches_queue_outputs(sample_video, tmp_path):
    """--mesh_context through the real CLI: the ViT's 50-token patch axis
    shards over the mesh 'data' axis and attention runs as a KV ring
    (parallel/ring_attention.py), composed with TP head sharding
    (--mesh_model 2). Features must match queue mode to reduction-order
    tolerance."""
    queue = _run_main(sample_video, tmp_path / "q", ["--sharding", "queue"])
    ctx = _run_main(
        sample_video,
        tmp_path / "cp",
        ["--sharding", "mesh", "--mesh_model", "2", "--mesh_context"],
    )
    np.testing.assert_allclose(ctx, queue, atol=2e-4)


def test_mesh_context_rejects_non_transformer(sample_video, tmp_path):
    from video_features_tpu.models.r21d.extract_r21d import ExtractR21D
    from video_features_tpu.parallel.scheduler import mesh_feature_extraction

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="r21d",
        video_paths=[sample_video],
        tmp_path=str(tmp_path / "t"),
        output_path=str(tmp_path / "o"),
        sharding="mesh",
        mesh_context=True,
    )
    ex = ExtractR21D(cfg)
    ex.progress.disable = True
    with pytest.raises(ValueError, match="mesh_context"):
        mesh_feature_extraction(ex, jax.devices())


def test_mesh_rejects_unsupported_feature_type(sample_video, tmp_path):
    """Every shipped extractor is mesh-capable now, so the refusal path is
    exercised through a non-capable stand-in (it still guards any future
    extractor that forgets to declare support)."""
    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D
    from video_features_tpu.parallel.scheduler import mesh_feature_extraction

    class NoMesh(ExtractI3D):
        mesh_capable = False

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="i3d",
        video_paths=[sample_video],
        tmp_path=str(tmp_path / "t"),
        output_path=str(tmp_path / "o"),
    )
    ex = NoMesh(cfg)
    ex.progress.disable = True
    with pytest.raises(ValueError, match="sharding mesh"):
        mesh_feature_extraction(ex, jax.devices())


def test_mesh_raft_sequence_parallel_matches_single_device(sample_video, tmp_path):
    """Flow extractors shard the FRAME axis over 'data' (the models'
    consecutive-pair views become GSPMD halo exchanges). Features must be
    byte-identical to the single-device run."""
    from video_features_tpu.models.raft.extract_raft import ExtractRAFT

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="raft",
        video_paths=[sample_video],
        batch_size=8,
        side_size=128,
        tmp_path=str(tmp_path / "t"),
        output_path=str(tmp_path / "o"),
    )
    ex = ExtractRAFT(cfg, external_call=True)
    ex.progress.disable = True
    single = ex([0], device=jax.devices()[0])
    mesh = make_mesh(jax.devices(), model=1)
    sharded = ex([0], device=mesh)
    np.testing.assert_array_equal(single[0]["raft"], sharded[0]["raft"])
    assert single[0]["raft"].shape[1] == 2


def test_mesh_model_axis_rejected_for_dp_only_models(sample_video, tmp_path):
    """--mesh_model > 1 on a DP-only model would silently replicate work
    across the 'model' axis; it must be refused, not degraded."""
    from video_features_tpu.models.r21d.extract_r21d import ExtractR21D
    from video_features_tpu.parallel.scheduler import mesh_feature_extraction

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="r21d_rgb",
        video_paths=[sample_video],
        mesh_model=2,
        tmp_path=str(tmp_path / "t"),
        output_path=str(tmp_path / "o"),
    )
    ex = ExtractR21D(cfg)
    ex.progress.disable = True
    with pytest.raises(ValueError, match="tensor-parallel"):
        mesh_feature_extraction(ex, jax.devices())


def test_mesh_r21d_dp_matches_single_device(sample_video, tmp_path):
    """DP-mesh batching for a stack-wise (non-CLIP) model: window batches
    shard over 'data', weights replicate; features byte-identical."""
    from video_features_tpu.models.r21d.extract_r21d import ExtractR21D

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="r21d_rgb",
        video_paths=[sample_video],
        batch_size=4,
        tmp_path=str(tmp_path / "t"),
        output_path=str(tmp_path / "o"),
    )
    ex = ExtractR21D(cfg, external_call=True)
    ex.progress.disable = True
    single = ex([0], device=jax.devices()[0])
    mesh = make_mesh(jax.devices(), model=1)
    sharded = ex([0], device=mesh)
    np.testing.assert_array_equal(single[0]["r21d_rgb"], sharded[0]["r21d_rgb"])
    assert single[0]["r21d_rgb"].shape[1] == 512


def test_decode_workers_pipeline_outputs_identical(sample_video, tmp_path):
    """The async host pipeline (--decode_workers) must be a pure
    scheduling change: features bit-identical to the serial path."""
    from video_features_tpu.models.resnet.extract_resnet import ExtractResNet

    def run(workers):
        cfg = ExtractionConfig(
            allow_random_init=True,
            feature_type="resnet18",
            video_paths=[sample_video] * 3,
            extraction_fps=2.0,
            batch_size=4,
            decode_workers=workers,
            cpu=True,
        )
        ex = ExtractResNet(cfg, external_call=True)
        ex.progress.disable = True
        return ex(range(3))

    serial = run(0)   # decode_workers=0 disables the pipeline
    piped = run(3)
    assert len(serial) == len(piped) == 3
    for s, p in zip(serial, piped):
        np.testing.assert_array_equal(s["resnet18"], p["resnet18"])
        np.testing.assert_array_equal(s["timestamps_ms"], p["timestamps_ms"])


def test_device_pipeline_split_outputs_identical(sample_video):
    """CLIP's dispatch/fetch split (one video's transfer+compute in
    flight while the previous fetches) is a pure scheduling change."""
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    def run(workers):
        cfg = ExtractionConfig(
            allow_random_init=True,
            feature_type="CLIP-ViT-B/32",
            video_paths=[sample_video] * 4,
            extract_method="uni_12",
            decode_workers=workers,
            cpu=True,
        )
        ex = ExtractCLIP(cfg, external_call=True)
        ex.progress.disable = True
        assert ex._supports_device_pipeline()
        return ex(range(4))

    serial = run(0)
    piped = run(2)
    assert len(serial) == len(piped) == 4
    for s, p in zip(serial, piped):
        np.testing.assert_array_equal(s["CLIP-ViT-B/32"], p["CLIP-ViT-B/32"])


def test_device_pipeline_isolates_corrupt_video(sample_video, tmp_path):
    """A corrupt video mid-list must not break the in-flight pipeline:
    the other videos complete, the bad one is skipped, progress counts
    every video exactly once."""
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    bad = tmp_path / "corrupt.mp4"
    bad.write_bytes(b"not a video at all")
    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="CLIP-ViT-B/32",
        video_paths=[sample_video, str(bad), sample_video],
        extract_method="uni_12",
        decode_workers=2,
        cpu=True,
    )
    ex = ExtractCLIP(cfg, external_call=True)
    ex.progress.disable = True
    results = ex(range(3))
    assert len(results) == 2  # the two good videos; the bad one skipped
    np.testing.assert_array_equal(
        results[0]["CLIP-ViT-B/32"], results[1]["CLIP-ViT-B/32"]
    )


@pytest.mark.parametrize("impl", ["auto", "decomposed"])
def test_mesh_i3d_sequence_parallel_matches_single_device(sample_video, tmp_path, impl):
    """I3D mesh mode: the stack's frame axis shards over 'data' inside
    the fused per-stream pipelines — for the rgb stream that is I3D's own
    temporal convs/pools resharding with GSPMD halos. Matches the
    single-device run to reduction-order tolerance (uneven 11-frame
    shards repartition the conv reductions). The flow streams' pair-view
    halos are covered by test_mesh_raft_sequence_parallel... (same
    mechanism, and the PWC double-compile here would dominate CI).

    impl='decomposed' additionally exercises the conv3d TPU-crash
    workaround (bench.py's chip default) on the mesh: the decomposition
    slices exactly the sharded frame axis with strides, so GSPMD must
    insert the halo exchanges there too."""
    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D
    from video_features_tpu.parallel.sharding import make_mesh

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="i3d",
        flow_type="pwc",
        streams=["rgb"],
        video_paths=[sample_video],
        stack_size=10,
        step_size=24,
        conv3d_impl=impl,
        tmp_path=str(tmp_path / "t"),
        output_path=str(tmp_path / "o"),
    )

    def run(device):
        ex = ExtractI3D(cfg, external_call=True)
        ex.progress.disable = True
        return ex([0], device=device)[0]

    single = run(jax.devices()[0])
    mesh = make_mesh(jax.devices(), model=1)
    sharded = run(mesh)
    assert single["rgb"].shape == sharded["rgb"].shape == (3, 1024)
    np.testing.assert_allclose(sharded["rgb"], single["rgb"], atol=2e-4)


def test_multihost_out_kwargs_replicates_only_on_multiprocess(monkeypatch):
    """Single-host mesh: {} (propagation keeps the flow nets' off-by-one
    output axis legal). Multi-controller: every output pinned replicated
    so np.asarray works on all hosts (code-review r04)."""
    import jax
    from jax.sharding import PartitionSpec as P

    from video_features_tpu.parallel.sharding import (
        make_mesh,
        multihost_out_kwargs,
    )

    mesh = make_mesh(jax.devices(), model=1)
    assert multihost_out_kwargs(mesh) == {}
    assert multihost_out_kwargs(jax.devices()[0]) == {}

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    kw = multihost_out_kwargs(mesh)
    assert kw["out_shardings"].spec == P()
    assert multihost_out_kwargs(jax.devices()[0]) == {}
