"""Every CLI feature type resolves and runs.

The per-family suites exercise one representative per family; this matrix
pins the rest of the surface the reference CLI exposes (ref
main.py:96-97): registry dispatch for ALL 14 types, and a real forward
for the variants no other test instantiates (resnet34/101/152,
CLIP4CLIP-ViT-B-32, vggish_torch).
"""

import numpy as np
import pytest

from video_features_tpu.config import FEATURE_TYPES, ExtractionConfig
from video_features_tpu.extract.registry import build_extractor

EXPECTED_CLASS = {
    "i3d": "ExtractI3D",
    "vggish": "ExtractVGGish",
    "vggish_torch": "ExtractVGGish",
    "r21d_rgb": "ExtractR21D",
    "raft": "ExtractRAFT",
    "pwc": "ExtractPWC",
    **{f"resnet{d}": "ExtractResNet" for d in (18, 34, 50, 101, 152)},
    **{
        t: "ExtractCLIP"
        for t in ("CLIP-ViT-B/32", "CLIP-ViT-B/16", "CLIP4CLIP-ViT-B-32")
    },
}


@pytest.mark.parametrize("feature_type", FEATURE_TYPES)
@pytest.mark.quick
def test_registry_dispatches_every_feature_type(feature_type, sample_video):
    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type=feature_type,
        video_paths=[sample_video],
        extract_method="uni_2",  # CLIP family needs one; others ignore it
        cpu=True,
    )
    ex = build_extractor(cfg, external_call=True)
    assert type(ex).__name__ == EXPECTED_CLASS[feature_type]
    assert ex.feature_type == feature_type


@pytest.mark.parametrize("arch,dim", [("resnet34", 512), ("resnet101", 2048),
                                      ("resnet152", 2048)])
def test_deep_resnet_variants_forward(arch, dim):
    """The depths no other test instantiates: graph builds, forward
    emits (N, dim) features + (N, 1000) logits."""
    import jax.numpy as jnp

    from video_features_tpu.models.resnet.model import build, init_params

    params = init_params(arch)
    x = np.random.RandomState(0).randn(1, 3, 224, 224).astype(np.float32)
    feats, logits = build(arch).apply({"params": params}, jnp.asarray(x))
    assert np.asarray(feats).shape == (1, dim)
    assert np.asarray(logits).shape == (1, 1000)
    assert np.isfinite(np.asarray(feats)).all()


def test_clip4clip_end_to_end(sample_video, tmp_path):
    """CLIP4CLIP-ViT-B-32 = the B/32 graph with a fine-tuned checkpoint
    (ref extract_clip.py:58-63); the type must run end to end."""
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="CLIP4CLIP-ViT-B-32",
        video_paths=[sample_video],
        extract_method="uni_2",
        tmp_path=str(tmp_path / "tmp"),
        output_path=str(tmp_path / "out"),
        cpu=True,
    )
    (r,) = ExtractCLIP(cfg, external_call=True)([0])
    assert r["CLIP4CLIP-ViT-B-32"].shape == (2, 512)
    assert np.isfinite(r["CLIP4CLIP-ViT-B-32"]).all()


def test_vggish_torch_end_to_end(sample_wav, tmp_path):
    """vggish_torch shares the unified extractor (both reference variants
    emit raw 128-d) but is its own CLI type; it must run end to end."""
    from video_features_tpu.models.vggish.extract_vggish import ExtractVGGish

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="vggish_torch",
        video_paths=[sample_wav],
        tmp_path=str(tmp_path / "tmp"),
        output_path=str(tmp_path / "out"),
        cpu=True,
    )
    (r,) = ExtractVGGish(cfg, external_call=True)([0])
    feats = r["vggish_torch"]
    assert feats.ndim == 2 and feats.shape[1] == 128 and feats.shape[0] >= 1
    assert np.isfinite(feats).all()
