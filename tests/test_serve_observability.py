"""Live serve observability (ISSUE 12): /metrics exposition, request
tracing, SLO accounting, and the online service-time estimator.

Layers under test, shallow to deep:

- the pure exposition renderer + the in-repo format checker
  (video_features_tpu/telemetry/exposition.py) — the checker is the
  acceptance oracle, so it gets its own negative tests;
- SloTracker and ServiceTimeModel units (fake clock / tmp paths, no
  threads, no sleeps);
- the edf-cost scheduler against the pinned heterogeneous-cost burst
  (simulate_dispatch — the exact serial model the daemon loop runs);
- daemon end-to-end with the ServeToy stub: GET /metrics validates
  against the checker and carries the required series, /v1/stats is its
  JSON twin, the heartbeat line reports live queue state, the
  ``telemetry trace <request_id>`` CLI assembles one request's
  admission -> queue_wait -> dispatch -> fetch -> sink timeline, and
  SIGTERM reaches shutdown() (the lost-final-snapshot fix).
"""

import json
import os
import signal
import threading
import time
import urllib.request

import pytest

from video_features_tpu.config import parse_serve_args
from video_features_tpu.runtime.telemetry import MetricsRegistry, SloTracker
from video_features_tpu.serve.costmodel import (
    WEIGHT_CLASSES,
    ServiceTimeModel,
    default_model_path,
    weight_class,
)
from video_features_tpu.serve.daemon import ServeDaemon, run_until_signalled
from video_features_tpu.serve.lifecycle import ExtractionRequest
from video_features_tpu.serve.scheduler import (
    SCHEDULER_NAMES,
    CostAwareEdfScheduler,
    EdfScheduler,
    FifoScheduler,
    build_scheduler,
    simulate_dispatch,
)
from video_features_tpu.telemetry.exposition import (
    Family,
    families_from_snapshot,
    group_service_metric,
    render_families,
    sanitize_metric_name,
    validate_exposition,
)

pytestmark = pytest.mark.serve


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t


# --- exposition renderer ----------------------------------------------------


def test_render_families_counter_gauge_and_escaping():
    c = Family("vft_requests_total", "counter", "Requests by state.")
    c.add({"state": "done"}, 3)
    g = Family("vft_queue_depth", "gauge", "Depth.")
    g.add({"queue": 'we"ird\\path\nx'}, 1.5)
    text = render_families([c, g])
    assert text.endswith("\n")
    assert 'vft_requests_total{state="done"} 3' in text
    assert '{queue="we\\"ird\\\\path\\nx"}' in text
    assert validate_exposition(text) == []


def test_render_histogram_is_cumulative_with_inf():
    m = MetricsRegistry()
    for v in (0.0005, 0.02, 0.02, 5.0, 1e9):
        m.observe("stage_s.decode", v)
    text = render_families(families_from_snapshot(m.snapshot()))
    assert validate_exposition(text) == []
    lines = [ln for ln in text.splitlines()
             if ln.startswith("vft_stage_seconds_bucket")]
    counts = [int(ln.rsplit(" ", 1)[1]) for ln in lines]
    assert counts == sorted(counts)  # cumulative
    assert 'le="+Inf"' in lines[-1] and counts[-1] == 5
    assert "vft_stage_seconds_count" in text
    assert "vft_stage_seconds_sum" in text


def test_snapshot_mapping_conventions():
    m = MetricsRegistry()
    m.inc("requests_done", 2)
    m.inc("requests_expired")
    m.inc("deadline_missed")
    m.set_gauge("queue_depth.admission", 4)
    m.set_gauge("groups_inflight", 1)
    m.observe(group_service_metric("CLIP-ViT-B/32", "640x480"), 0.7)
    text = render_families(families_from_snapshot(m.snapshot()))
    assert validate_exposition(text) == []
    assert 'vft_requests_total{state="done"} 2' in text
    assert 'vft_requests_total{state="expired"} 1' in text
    assert "vft_deadline_missed_total 1" in text
    assert 'vft_queue_depth{queue="admission"} 4' in text
    assert "vft_groups_inflight 1" in text
    # the '|' separator round-trips a feature type containing '/'
    assert ('vft_group_service_seconds_count{bucket="640x480",'
            'feature_type="CLIP-ViT-B/32"} 1') in text


def test_sanitize_metric_name():
    assert sanitize_metric_name("a.b/c-d") == "a_b_c_d"
    assert sanitize_metric_name("9lives")[0] == "_"


# --- exposition checker negatives (the acceptance oracle must bite) ---------


def _errs(text):
    return validate_exposition(text)


def test_checker_rejects_missing_type():
    assert _errs("vft_x 1\n")


def test_checker_rejects_counter_without_total_suffix():
    assert _errs("# HELP vft_x c\n# TYPE vft_x counter\nvft_x 1\n")


def test_checker_rejects_noncumulative_histogram():
    bad = (
        "# HELP vft_h h\n# TYPE vft_h histogram\n"
        'vft_h_bucket{le="0.1"} 5\nvft_h_bucket{le="1"} 3\n'
        'vft_h_bucket{le="+Inf"} 5\nvft_h_sum 1\nvft_h_count 5\n'
    )
    assert _errs(bad)


def test_checker_rejects_histogram_missing_inf_bucket():
    bad = (
        "# HELP vft_h h\n# TYPE vft_h histogram\n"
        'vft_h_bucket{le="0.1"} 5\nvft_h_sum 1\nvft_h_count 5\n'
    )
    assert _errs(bad)


def test_checker_rejects_count_disagreeing_with_inf():
    bad = (
        "# HELP vft_h h\n# TYPE vft_h histogram\n"
        'vft_h_bucket{le="+Inf"} 5\nvft_h_sum 1\nvft_h_count 4\n'
    )
    assert _errs(bad)


def test_checker_rejects_le_on_non_histogram():
    assert _errs('# HELP vft_g g\n# TYPE vft_g gauge\nvft_g{le="1"} 1\n')


def test_checker_rejects_bad_names_and_missing_newline():
    assert _errs("# HELP 9bad x\n# TYPE 9bad gauge\n9bad 1\n")
    assert _errs("# HELP vft_g g\n# TYPE vft_g gauge\nvft_g 1")  # no final \n
    assert _errs('# HELP vft_g g\n# TYPE vft_g gauge\nvft_g{9l="x"} 1\n')


# --- SloTracker -------------------------------------------------------------


def test_slo_quantiles_and_tiers():
    clock = FakeClock()
    t = SloTracker(window_s=100.0, clock=clock)
    for i in range(100):
        t.record("done", latency_s=(i + 1) / 100.0, queue_wait_s=0.01,
                 priority=0 if i < 50 else 3)
    snap = t.snapshot()
    assert snap["overall"]["count"] == 100
    assert snap["overall"]["latency_s"]["p50"] == 0.5
    assert snap["overall"]["latency_s"]["p99"] == 0.99
    assert set(snap["tiers"]) == {"0", "3"}
    assert snap["tiers"]["3"]["count"] == 50


def test_slo_window_prunes_old_samples():
    clock = FakeClock()
    t = SloTracker(window_s=10.0, clock=clock)
    t.record("done", latency_s=1.0)
    clock.t = 100.0
    t.record("done", latency_s=2.0)
    snap = t.snapshot()
    assert snap["overall"]["count"] == 1
    assert snap["overall"]["latency_s"]["p50"] == 2.0


def test_slo_miss_rate_denominator_excludes_cancelled_and_rejected():
    t = SloTracker(window_s=100.0, clock=FakeClock())
    t.record("done", latency_s=1.0, deadline_missed=False)
    t.record("expired", latency_s=5.0, deadline_missed=True)
    t.record("cancelled", latency_s=0.1)
    t.record("rejected", latency_s=0.0)
    assert t.miss_rate() == 0.5  # 1 missed / 2 in (done, expired)


# --- ServiceTimeModel -------------------------------------------------------


def test_cost_model_predict_fallback_chain(tmp_path):
    m = ServiceTimeModel()
    assert m.predict(("i3d", "640x480"), 4) == 0.0  # cold
    m.observe("i3d", "640x480", 4, 8.0)  # 2 s/item
    assert m.predict(("i3d", "640x480"), 2) == pytest.approx(4.0)
    # same feature type, unseen bucket: feature-type fallback
    assert m.predict(("i3d", "320x240"), 1) == pytest.approx(2.0)
    # unseen feature type in the same weight class (heavy): class prior
    assert m.predict(("raft", "~"), 1) == pytest.approx(2.0)
    # unseen light model: global fallback (only heavy observed so far)
    assert m.predict(("resnet18", "~"), 1) == pytest.approx(2.0)


def test_cost_model_weight_classes_cover_every_feature_type():
    from video_features_tpu.config import FEATURE_TYPES

    for ft in FEATURE_TYPES:
        assert ft in WEIGHT_CLASSES
        assert weight_class(ft) in ("light", "medium", "heavy")


def test_cost_model_persistence_roundtrip_and_torn_file(tmp_path):
    path = str(tmp_path / "model.json")
    m = ServiceTimeModel(path=path, save_every=1)
    m.observe("resnet18", "64x48", 2, 1.0)
    assert os.path.exists(path)
    m2 = ServiceTimeModel(path=path)
    assert m2.predict(("resnet18", "64x48"), 2) == pytest.approx(1.0)
    with open(path, "w", encoding="utf-8") as fh:
        fh.write('{"torn')
    m3 = ServiceTimeModel(path=path)  # torn file: cold start, no raise
    assert m3.predict(("resnet18", "64x48"), 2) == 0.0


def test_cost_model_default_path_prefers_compile_cache(tmp_path):
    from video_features_tpu.config import ExtractionConfig

    cfg = ExtractionConfig(
        feature_type="resnet18",
        output_path=str(tmp_path / "out"),
        compile_cache=str(tmp_path / "cc"),
    )
    assert default_model_path(cfg) == str(
        tmp_path / "cc" / "service_time_model.json"
    )
    cfg2 = cfg.replace(compile_cache=None)
    assert default_model_path(cfg2) == str(
        tmp_path / "out" / "_telemetry" / "service_time_model.json"
    )


# --- edf-cost scheduler -----------------------------------------------------


def _burst():
    """The pinned heterogeneous-cost burst: one 10 s group with a 5 s
    budget (infeasible from admission) ahead of eight 0.5 s groups with
    5.5..9 s budgets. Plain EDF serves the doomed group first and every
    cheap deadline dominoes; edf-cost demotes it behind feasible work."""
    groups = []
    doomed = ExtractionRequest(feature_type="i3d", video_path="/x/big.mp4",
                               id="doomed", bucket="big")
    doomed.admitted_at, doomed.deadline_at = 0.0, 5.0
    groups.append((("i3d", "big"), [doomed]))
    for i in range(8):
        r = ExtractionRequest(feature_type="resnet18", video_path=f"/x/{i}.mp4",
                              id=f"c{i}", bucket=f"k{i}")
        r.admitted_at, r.deadline_at = 0.0, 5.5 + 0.5 * i
        groups.append((("resnet18", f"k{i}"), [r]))
    return groups


def _service(key, requests):
    return 10.0 if key[0] == "i3d" else 0.5


def _trained_model():
    m = ServiceTimeModel()
    m.observe("i3d", "big", 1, 10.0)
    for i in range(8):
        m.observe("resnet18", f"k{i}", 1, 0.5)
    return m


def test_edf_cost_beats_plain_edf_on_pinned_burst():
    edf = simulate_dispatch(
        _burst(), EdfScheduler(default_slack_s=30.0, aging_s=10.0),
        service_s=_service,
    )
    cost = simulate_dispatch(
        _burst(),
        CostAwareEdfScheduler(_trained_model(), default_slack_s=30.0,
                              aging_s=10.0),
        service_s=_service,
    )
    edf_miss = sum(1 for r in edf if not r["met"])
    cost_miss = sum(1 for r in cost if not r["met"])
    assert edf_miss == 9  # the doomed group dominoes everything
    assert cost_miss == 1  # only the infeasible group itself
    # equal-or-better p99 (the doomed group still has to run somewhere)
    assert max(r["latency_s"] for r in cost) <= max(r["latency_s"] for r in edf)


def test_edf_cost_consults_the_model():
    class Recorder:
        def __init__(self):
            self.calls = []

        def predict(self, key, n):
            self.calls.append((key, n))
            return 0.0

    rec = Recorder()
    sched = CostAwareEdfScheduler(rec)
    groups = _burst()
    sched.pick(groups, now=0.0)
    assert rec.calls  # acceptance: edf-cost ranks via model.predict
    assert (("i3d", "big"), 1) in rec.calls


def test_cold_model_degenerates_to_plain_edf():
    edf = simulate_dispatch(
        _burst(), EdfScheduler(default_slack_s=30.0, aging_s=10.0),
        service_s=_service,
    )
    cold = simulate_dispatch(
        _burst(), CostAwareEdfScheduler(ServiceTimeModel(),
                                        default_slack_s=30.0, aging_s=10.0),
        service_s=_service,
    )
    assert [r["id"] for r in cold] == [r["id"] for r in edf]


def test_build_scheduler_names():
    assert set(SCHEDULER_NAMES) == {"edf", "fifo", "edf-cost"}
    assert isinstance(build_scheduler("fifo"), FifoScheduler)
    assert type(build_scheduler("edf")) is EdfScheduler
    s = build_scheduler("edf-cost", cost_model=_trained_model())
    assert isinstance(s, CostAwareEdfScheduler)
    assert build_scheduler("edf-cost").predicted_service_s(
        _burst()[0], now=0.0
    ) == 0.0  # default-constructed model is cold, not None


# --- daemon end to end (ServeToy, inline drain) -----------------------------


@pytest.fixture(scope="module")
def obs_videos(tmp_path_factory):
    from video_features_tpu.utils.synth import synth_video

    d = tmp_path_factory.mktemp("obs_media")
    return [
        synth_video(str(d / f"v{i}.mp4"), n_frames=10, width=64, height=48,
                    seed=i)
        for i in range(3)
    ]


def _daemon(tmp_path, **flags):
    from test_serve import ServeToy

    argv = [
        "--feature_types", "resnet18",
        "--output_path", str(tmp_path / "out"),
        "--tmp_path", str(tmp_path / "tmp"),
        "--allow_random_init", "--cpu",
        "--heartbeat_s", "0",
    ]
    for k, v in flags.items():
        argv += [f"--{k}"] + ([str(v)] if v is not True else [])
    scfg = parse_serve_args(argv)

    class Toy(ServeToy):
        built = 0

    return ServeDaemon(scfg, build=Toy)


def _drain(d):
    for g in d.batcher.take_ready(now=float("inf")):
        d.batcher._run_group(g)


def _submit(d, video, rid, **extra):
    d.submit({"feature_type": "resnet18", "video_path": video, "id": rid,
              **extra}, source="local")


@pytest.fixture(scope="module")
def obs_run(tmp_path_factory, obs_videos):
    """One served 2-request burst (fused group path: dispatch/fetch
    spans exist) behind a live HTTP door, shared by the endpoint,
    heartbeat, and trace tests."""
    tmp = tmp_path_factory.mktemp("obs_run")
    d = _daemon(tmp, port=0, scheduler="edf-cost")
    d.start()
    for i in range(2):
        _submit(d, obs_videos[i], f"obs{i}", bucket="64x48", priority=2,
                deadline_ms=600000)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        states = [
            (d.tracker.get(f"obs{i}") or {}).get("state") for i in range(2)
        ]
        if all(s in ("done", "failed") for s in states):
            break
        time.sleep(0.02)
    assert states == ["done", "done"]
    yield d, tmp
    if d._http_server is not None:
        d.shutdown()


def _get(d, path):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{d.http_port}{path}", timeout=10
    )


def test_metrics_endpoint_is_valid_exposition(obs_run):
    d, _ = obs_run
    resp = _get(d, "/metrics")
    assert resp.headers["Content-Type"] == "text/plain; version=0.0.4; charset=utf-8"
    text = resp.read().decode("utf-8")
    assert validate_exposition(text) == []
    # the acceptance series
    assert 'vft_queue_depth{queue="admission"}' in text
    assert ('vft_group_service_seconds_count{bucket="64x48",'
            'feature_type="resnet18"} 1') in text
    assert 'vft_requests_total{state="done"} 2' in text
    assert "vft_deadline_missed" not in text or "vft_deadline_missed_total 0" in text
    assert 'vft_breaker_state{feature_type="resnet18"} 0' in text
    assert 'vft_slo_latency_seconds{quantile="0.99",tier="overall"}' in text
    assert 'vft_slo_deadline_miss_ratio{tier="2"} 0' in text
    assert "vft_groups_dispatched_total 1" in text
    assert "vft_uptime_seconds" in text
    assert "vft_queue_age_oldest_s 0" in text


def test_stats_endpoint_is_the_json_twin(obs_run):
    d, _ = obs_run
    st = json.load(_get(d, "/v1/stats"))
    assert st["slo"]["overall"]["count"] == 2
    assert st["slo"]["overall"]["miss_rate"] == 0.0
    assert st["slo"]["tiers"]["2"]["latency_s"]["p99"] > 0
    assert st["cost_model"]["keys"]["resnet18|64x48"]["n"] == 1
    assert st["metrics"]["counters"]["requests_done"] == 2
    assert st["uptime_s"] > 0
    assert st["queue_depth"] == 0  # /healthz fields ride along


def test_heartbeat_line_reports_live_serve_state(obs_run):
    d, _ = obs_run
    line = d._heartbeat_line()
    assert line.startswith("serve: queue=0 ")
    assert "inflight=0" in line
    assert "miss_rate=0.0%" in line
    assert "completed/s=" in line
    # the provider is wired into the daemon's telemetry drain loop
    # (== not `is`: bound methods are recreated per attribute access)
    assert d.telemetry.heartbeat_provider == d._heartbeat_line


def test_queue_wait_span_and_record(obs_run):
    d, _ = obs_run
    rec = d.tracker.get("obs0")
    assert rec["queue_wait_s"] >= 0.0
    spans = [s for s in d.telemetry.spans() if s["stage"] == "queue_wait"]
    assert {s["request"] for s in spans} == {"obs0", "obs1"}
    by_req = {s["request"]: s for s in spans}
    # pinned under the request span, annotated with the fused group size
    req_spans = {s["request"]: s for s in d.telemetry.spans()
                 if s["stage"] == "request"}
    assert by_req["obs0"]["parent"] == req_spans["obs0"]["span"]
    assert by_req["obs0"]["group_size"] == 2


def test_trace_cli_covers_request_lifecycle(obs_run, tmp_path, capsys):
    from video_features_tpu.telemetry.__main__ import main as tele_main

    d, run_tmp = obs_run
    # two telemetry instances, two spans files: the daemon's lifecycle
    # spans and the resident extractor's pipeline spans
    d.telemetry.flush()
    d.pool._extractors["resnet18"].telemetry.flush()
    out = tmp_path / "trace.json"
    root = str(run_tmp / "out")
    assert tele_main(["trace", "obs0", root, "-o", str(out)]) == 0
    trace = json.loads(out.read_text())
    stages = {e["name"] for e in trace["traceEvents"] if e.get("ph") == "X"}
    # the acceptance path: admission -> queue_wait -> dispatch -> fetch
    # -> sink, plus the linking request spans
    assert {"admission", "queue_wait", "request",
            "dispatch", "fetch", "sink"} <= stages
    for e in trace["traceEvents"]:
        if e.get("ph") == "X":
            assert e["ts"] >= 0 and e["dur"] >= 0
    # unknown ids are a usage error, not an empty trace
    assert tele_main(["trace", "no-such-request", root]) == 2
    assert "no spans mention" in capsys.readouterr().err


def test_expired_request_counts_as_deadline_miss(tmp_path, obs_videos):
    d = _daemon(tmp_path, max_batch_wait_ms=0)
    try:
        _submit(d, obs_videos[0], "late", deadline_ms=0.001)
        time.sleep(0.01)  # let the 1 µs budget pass on the real clock
        _drain(d)
        rec = d.tracker.get("late")
        assert rec["state"] == "expired"
        assert rec["deadline_missed"] is True
        assert d.telemetry.metrics.counter("deadline_missed") == 1
        assert d.slo.miss_rate() == 1.0
        text = d.metrics_text()
        assert validate_exposition(text) == []
        assert "vft_deadline_missed_total 1" in text
        assert 'vft_requests_total{state="expired"} 1' in text
    finally:
        d.shutdown()


def test_dispatch_feeds_cost_model_and_persists_on_shutdown(
    tmp_path, obs_videos
):
    d = _daemon(tmp_path)
    _submit(d, obs_videos[0], "cm0", bucket="64x48")
    _drain(d)
    assert d.tracker.get("cm0")["state"] == "done"
    assert d.cost_model.predict(("resnet18", "64x48"), 1) > 0.0
    d.shutdown()
    path = default_model_path(d.cfg)
    assert os.path.exists(path)
    reloaded = ServiceTimeModel(path=path)
    assert reloaded.predict(("resnet18", "64x48"), 1) > 0.0


def test_sigterm_reaches_shutdown(tmp_path, obs_videos):
    """The lost-final-snapshot fix: `kill <pid>` must drain and run
    shutdown() — spans flushed, summary written — not die mid-flight."""
    if threading.current_thread() is not threading.main_thread():
        pytest.skip("signal handlers need the main thread")
    d = _daemon(tmp_path)
    _submit(d, obs_videos[0], "sig0")
    _drain(d)
    timer = threading.Timer(0.2, lambda: os.kill(os.getpid(), signal.SIGTERM))
    timer.start()
    try:
        run_until_signalled(d)  # returns only because the handler fired
    finally:
        timer.cancel()
    troot = os.path.join(str(tmp_path / "out"), "_telemetry")
    spans = []
    for name in os.listdir(troot):
        if name.startswith("spans-") and name.endswith(".jsonl"):
            with open(os.path.join(troot, name), "r", encoding="utf-8") as fh:
                spans += [json.loads(ln) for ln in fh if ln.strip()]
    assert any(s["stage"] == "request" for s in spans)  # final flush landed
    assert os.path.exists(
        os.path.join(str(tmp_path / "out"), "_manifest", "summary.json")
    )


# --- graftcheck scope (satellite: new module, zero waivers) -----------------


def test_costmodel_in_graftcheck_scope_no_waivers():
    import fnmatch

    from video_features_tpu.analysis.core import (
        HOT_MODULE_PATTERNS,
        THREAD_ROOT_PATTERNS,
    )

    assert any(fnmatch.fnmatch("serve/costmodel.py", p)
               for p in HOT_MODULE_PATTERNS)
    assert any(fnmatch.fnmatch("serve/costmodel.py", p)
               for p in THREAD_ROOT_PATTERNS)
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for rel in ("video_features_tpu/serve/costmodel.py",
                "video_features_tpu/telemetry/exposition.py",
                "video_features_tpu/serve/batcher.py",
                "video_features_tpu/serve/daemon.py",
                "video_features_tpu/serve/server.py",
                "video_features_tpu/serve/lifecycle.py"):
        with open(os.path.join(pkg, rel), "r", encoding="utf-8") as fh:
            assert "graftcheck:" not in fh.read(), rel
