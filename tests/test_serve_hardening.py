"""Serve-mode hardening (ISSUE 8): scheduling, cancellation,
supervision, crash recovery, and retention.

Deterministic by construction, like test_serve.py: the scheduler is a
pure function of (groups, now), the breaker and admission clocks are
injected fakes, daemon tests drive the batcher's inline drain on the
test thread, and the kill-then-restart recovery test SIGKILLs a
subprocess that only touches the (jax-free) lifecycle module. The only
real-time test is the watchdog hang (bounded at ~0.4 s by the injected
hang's sleep).
"""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

from test_serve import FakeClock, ServeToy, _daemon, _req, serve_videos  # noqa: F401

from video_features_tpu.config import parse_serve_args
from video_features_tpu.runtime import faults
from video_features_tpu.serve.batcher import AdmissionController, QueueFull
from video_features_tpu.serve.daemon import ServeDaemon
from video_features_tpu.serve.lifecycle import (
    BadRequest,
    ExtractionRequest,
    RequestTracker,
    parse_request,
)
from video_features_tpu.serve.scheduler import (
    EdfScheduler,
    FifoScheduler,
    build_scheduler,
    simulate_dispatch,
)
from video_features_tpu.serve.sources import SpoolWatcher, parse_spool_name
from video_features_tpu.serve.supervisor import (
    CircuitBreaker,
    GroupTimeout,
    ModelUnavailable,
    Watchdog,
)

pytestmark = pytest.mark.serve


# --- helpers ----------------------------------------------------------------


def _sreq(i, bucket="64x48", priority=0, deadline_at=None, admitted_at=0.0):
    r = _req(i, bucket=bucket)
    r.priority = priority
    r.admitted_at = admitted_at
    r.deadline_at = deadline_at
    return r


def _group(key_bucket, *reqs):
    return (("resnet18", key_bucket), list(reqs))


def _drain_inline(d):
    """What the dispatcher thread would do, on this thread: pull every
    ready group (scheduler order) and run it."""
    for g in d.batcher.take_ready(now=float("inf")):
        d.batcher._run_group(g)


def _fake_daemon(tmp_path, serve_videos, clock, **flags):
    """test_serve's _daemon, with an injected daemon/batcher/breaker
    clock for no-sleep deadline and breaker tests."""
    argv = [
        "--feature_types", "resnet18",
        "--output_path", str(tmp_path / "out"),
        "--tmp_path", str(tmp_path / "tmp"),
        "--allow_random_init", "--cpu",
        "--heartbeat_s", "0",
    ]
    for k, v in flags.items():
        argv += [f"--{k}"] + ([str(v)] if v is not True else [])
    scfg = parse_serve_args(argv)

    class Toy(ServeToy):
        built = 0

    d = ServeDaemon(scfg, build=Toy, clock=clock)
    return d, Toy


# --- scheduler units (pure, no threads) -------------------------------------


def test_edf_orders_across_keys_by_effective_deadline():
    s = EdfScheduler(default_slack_s=30.0, aging_s=0.0)
    groups = [
        _group("a", _sreq(0, deadline_at=9.0)),
        _group("b", _sreq(1, deadline_at=3.0)),
        _group("c", _sreq(2, deadline_at=6.0)),
    ]
    ordered = s.order(groups, now=0.0)
    assert [k[1] for k, _ in ordered] == ["b", "c", "a"]
    assert s.pick(groups, now=0.0) == 1


def test_edf_group_deadline_is_most_urgent_member():
    s = EdfScheduler(aging_s=0.0)
    groups = [
        _group("a", _sreq(0, deadline_at=5.0), _sreq(1, deadline_at=1.0)),
        _group("b", _sreq(2, deadline_at=2.0)),
    ]
    assert s.pick(groups, now=0.0) == 0  # member deadline 1.0 wins


def test_priority_tier_dominates_deadline():
    s = EdfScheduler(aging_s=0.0)
    groups = [
        _group("a", _sreq(0, priority=0, deadline_at=1.0)),
        _group("b", _sreq(1, priority=5, deadline_at=100.0)),
    ]
    assert s.pick(groups, now=0.0) == 1


def test_aging_promotes_starved_low_priority():
    s = EdfScheduler(default_slack_s=1000.0, aging_s=10.0)
    old = _group("a", _sreq(0, priority=0, admitted_at=0.0))
    fresh = _group("b", _sreq(1, priority=3, admitted_at=100.0))
    # at t=100 the tier-0 group has waited 100 s -> +10 tiers > tier 3
    assert s.pick([old, fresh], now=100.0) == 0
    # freshly admitted, same tiers: the higher declared priority wins
    assert s.pick([old, fresh], now=0.5) == 1
    # infinite drain sweeps must rank deterministically, not overflow
    assert s.pick([old, fresh], now=float("inf")) in (0, 1)


def test_deadline_less_requests_age_via_default_slack():
    s = EdfScheduler(default_slack_s=5.0, aging_s=0.0)
    groups = [
        _group("a", _sreq(0, admitted_at=0.0)),  # effective deadline 5.0
        _group("b", _sreq(1, deadline_at=3.0, admitted_at=1.0)),
        _group("c", _sreq(2, deadline_at=8.0, admitted_at=1.0)),
    ]
    ordered = s.order(groups, now=2.0)
    assert [k[1] for k, _ in ordered] == ["b", "a", "c"]


def test_fifo_scheduler_preserves_arrival_order():
    s = FifoScheduler()
    groups = [
        _group("a", _sreq(0, deadline_at=100.0)),
        _group("b", _sreq(1, deadline_at=1.0)),
    ]
    assert s.pick(groups, now=0.0) == 0
    assert [k[1] for k, _ in s.order(groups, now=0.0)] == ["a", "b"]


def test_build_scheduler_names():
    assert build_scheduler("edf").name == "edf"
    assert build_scheduler("fifo").name == "fifo"
    with pytest.raises(ValueError):
        build_scheduler("lifo")


def test_edf_meets_strictly_more_deadlines_than_fifo():
    """The pinned acceptance burst: a deterministic mixed-deadline burst
    where arrival order is pessimal, simulated through the exact
    simulate_dispatch the serve_scheduling bench part runs."""
    def burst():
        return [
            _group("g0", _sreq(0)),                        # no deadline
            _group("g1", _sreq(1, deadline_at=6.0)),
            _group("g2", _sreq(2, deadline_at=2.0)),
            _group("g3", _sreq(3, deadline_at=3.0)),
            _group("g4", _sreq(4, deadline_at=1.0)),
            _group("g5", _sreq(5, deadline_at=5.0)),
        ]

    fifo = simulate_dispatch(burst(), FifoScheduler(), service_s=1.0)
    edf = simulate_dispatch(
        burst(), EdfScheduler(default_slack_s=30.0, aging_s=10.0), service_s=1.0
    )
    fifo_met = sum(r["met"] for r in fifo)
    edf_met = sum(r["met"] for r in edf)
    assert edf_met == 6  # every deadline met under EDF
    assert fifo_met == 2  # arrival order misses g2/g3/g4/g5
    assert edf_met > fifo_met


# --- batcher integration (fake clock) ---------------------------------------


def test_admit_stamps_admitted_at_and_deadline_at():
    sink, clock = [], FakeClock(10.0)
    c = AdmissionController(
        dispatch=lambda k, r: sink.append(r), clock=clock, max_group_size=3
    )
    r = _req(0)
    r.deadline_ms = 500.0
    c.admit(r)
    assert r.admitted_at == 10.0
    assert r.deadline_at == 10.5
    r2 = _req(1)
    c.admit(r2)
    assert r2.admitted_at == 10.0 and r2.deadline_at is None


def test_take_ready_returns_scheduler_order_across_keys():
    sink, clock = [], FakeClock()
    c = AdmissionController(
        dispatch=lambda k, r: None, clock=clock, max_group_size=1,
        scheduler=EdfScheduler(aging_s=0.0),
    )
    late, soon = _req(0, bucket="a"), _req(1, bucket="b")
    late.deadline_ms, soon.deadline_ms = 9000.0, 1000.0
    c.admit(late)  # arrives first, deadline later
    c.admit(soon)
    groups = c.take_ready(now=0.0)
    assert [k[1] for k, _ in groups] == ["b", "a"]


def test_batcher_cancel_from_buffer_and_ready():
    sink, clock = [], FakeClock()
    c = AdmissionController(
        dispatch=lambda k, r: None, clock=clock, max_group_size=2
    )
    a, b, x = _req(0, bucket="a"), _req(1, bucket="a"), _req(2, bucket="b")
    c.admit(a)
    c.admit(b)  # fills the ("resnet18","a") group -> ready
    c.admit(x)  # still coalescing in its buffer
    assert c.depth() == 3
    got = c.cancel("r2")  # from the open buffer
    assert got is x and c.depth() == 2
    got = c.cancel("r0")  # from a ready group (group survives with r1)
    assert got is a and c.depth() == 1
    assert c.cancel("r0") is None  # already gone
    groups = c.take_ready(now=float("inf"))
    assert [[r.id for r in reqs] for _, reqs in groups] == [["r1"]]


# --- request parsing --------------------------------------------------------


def test_parse_request_priority_and_deadline_validation():
    base = {"feature_type": "resnet18", "video_path": "/v.mp4"}
    ok = parse_request(dict(base, priority=7, deadline_ms=250), "http")
    assert ok.priority == 7 and ok.deadline_ms == 250.0
    assert parse_request(dict(base), "http").priority == 0
    for bad in ({"priority": -1}, {"priority": 10}, {"priority": True},
                {"priority": "3"}, {"deadline_ms": 0}, {"deadline_ms": -5},
                {"deadline_ms": True}, {"deadline_ms": "100"}):
        with pytest.raises(BadRequest):
            parse_request(dict(base, **bad), "http")


def test_parse_spool_name_hints():
    assert parse_spool_name("job") == {}
    assert parse_spool_name("job.p7") == {"priority": 7}
    assert parse_spool_name("job.d500") == {"deadline_ms": 500.0}
    assert parse_spool_name("clip.p2.d1500") == {
        "priority": 2, "deadline_ms": 1500.0,
    }
    # not hints: part of the name
    assert parse_spool_name("v1.part2") == {}


# --- supervisor units (fake clock) ------------------------------------------


def test_circuit_breaker_state_machine():
    clock = FakeClock()
    b = CircuitBreaker(threshold=2, cooldown_s=10.0, clock=clock)
    assert b.state() == "closed" and b.allow_request()
    assert b.record_failure() is False  # 1/2
    assert b.state() == "closed"
    assert b.record_failure() is True  # 2/2 -> open
    assert b.state() == "open" and not b.allow_request()
    assert 0.0 < b.retry_after_s() <= 10.0
    assert b.try_probe() is False  # still open
    clock.t = 10.0
    assert b.state() == "half_open"
    assert b.allow_request()
    assert b.try_probe() is True
    assert b.try_probe() is False  # single probe slot
    assert not b.allow_request()  # probe in flight
    b.record_failure()  # probe failed -> reopen
    assert b.state() == "open"
    clock.t = 20.0
    assert b.try_probe() is True
    b.record_success()
    assert b.state() == "closed" and b.allow_request()
    assert b.snapshot()["opens"] == 2


def test_watchdog_inline_and_timeout():
    w = Watchdog(timeout_s=0.0)
    assert w.run(lambda: 42) == 42  # inline, unbounded
    with pytest.raises(ValueError):
        w.run(lambda: (_ for _ in ()).throw(ValueError("boom")))
    w = Watchdog(timeout_s=0.05)
    assert w.run(lambda: "fast") == "fast"
    with pytest.raises(GroupTimeout):
        w.run(lambda: time.sleep(0.5))
    assert w.timeouts() == 1
    assert faults.classify_error(GroupTimeout("late")) == "transient"


# --- daemon: expired / cancelled paths (inline drain, fake clock) -----------


def test_expired_request_terminal_path(tmp_path, serve_videos):
    clock = FakeClock()
    d, _ = _fake_daemon(tmp_path, serve_videos, clock)
    d.submit({"feature_type": "resnet18", "video_path": serve_videos[0],
              "id": "exp-0", "deadline_ms": 100}, source="local")
    d.submit({"feature_type": "resnet18", "video_path": serve_videos[1],
              "id": "ok-0"}, source="local")
    clock.t = 1.0  # past exp-0's 0.1 s budget before anything dispatches
    _drain_inline(d)
    exp = d.tracker.get("exp-0")
    assert exp["state"] == "expired"
    assert "deadline_ms" in exp["message"]
    assert d.tracker.get("ok-0")["state"] == "done"
    s = faults.merge_manifest(d.tracker.results_dir)
    assert s["expired"] == 1 and s["done"] == 1
    assert s["videos"]["request:exp-0"]["status"] == "expired"
    d.shutdown()


def test_cancel_queued_request(tmp_path, serve_videos):
    d, _ = _daemon(tmp_path, serve_videos, max_group_size=8)
    d.submit({"feature_type": "resnet18", "video_path": serve_videos[0],
              "id": "c-0"}, source="local")
    rec = d.cancel("c-0")
    assert rec["state"] == "cancelled"
    assert d.batcher.depth() == 0
    assert d.cancel("nope") is None
    again = d.cancel("c-0")  # already terminal: record stands
    assert again["state"] == "cancelled" and "cancel_requested" not in again
    s = faults.merge_manifest(d.tracker.results_dir)
    assert s["cancelled"] == 1
    d.shutdown()


def test_cancel_after_group_left_queue_honored_at_boundary(tmp_path, serve_videos):
    d, _ = _daemon(tmp_path, serve_videos, max_group_size=2)
    for i, rid in enumerate(("b-0", "b-1")):
        d.submit({"feature_type": "resnet18", "video_path": serve_videos[i],
                  "id": rid}, source="local")
    groups = d.batcher.take_ready(now=float("inf"))  # dispatcher pulled it
    assert len(groups) == 1
    rec = d.cancel("b-0")  # too late for the queue: cancel-requested
    assert rec.get("cancel_requested") is True
    d.batcher._run_group(groups[0])  # the boundary check
    assert d.tracker.get("b-0")["state"] == "cancelled"
    assert d.tracker.get("b-1")["state"] == "done"
    assert not d._cancel_pending  # consumed at the boundary
    d.shutdown()


def test_http_delete_cancel_endpoint(tmp_path, serve_videos):
    # long coalescing wait so the request stays queued until we cancel
    d, _ = _daemon(tmp_path, serve_videos, port=0, max_batch_wait_ms=60000,
                   max_group_size=8)
    d.start()
    try:
        url = f"http://127.0.0.1:{d.http_port}"
        body = json.dumps({"feature_type": "resnet18",
                           "video_path": serve_videos[0],
                           "id": "h-0", "priority": 3}).encode()
        req = urllib.request.Request(
            f"{url}/v1/extract", data=body,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.status == 202
        with urllib.request.urlopen(urllib.request.Request(
                f"{url}/v1/requests/h-0", method="DELETE"), timeout=10) as resp:
            assert resp.status == 200
            assert json.load(resp)["state"] == "cancelled"
        # repeating the DELETE is idempotent: 200 with the same record
        with urllib.request.urlopen(urllib.request.Request(
                f"{url}/v1/requests/h-0", method="DELETE"), timeout=10) as resp:
            assert resp.status == 200
            assert json.load(resp)["state"] == "cancelled"
        # terminal in another state: too late to cancel -> 409
        done_req = parse_request({"feature_type": "resnet18",
                                  "video_path": serve_videos[1],
                                  "id": "h-done"}, "http")
        d.tracker.admit(done_req)
        d.tracker.finish(done_req, "done")
        try:
            urllib.request.urlopen(urllib.request.Request(
                f"{url}/v1/requests/h-done", method="DELETE"), timeout=10)
            assert False, "expected 409"
        except urllib.error.HTTPError as e:
            assert e.code == 409 and json.load(e)["state"] == "done"
        try:
            urllib.request.urlopen(urllib.request.Request(
                f"{url}/v1/requests/ghost", method="DELETE"), timeout=10)
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        d.shutdown(drain=False)


def test_spool_cancel_file_removes_unadmitted_request(tmp_path, serve_videos):
    d, _ = _daemon(tmp_path, serve_videos,
                   spool_dir=str(tmp_path / "spool"), max_batch_wait_ms=60000)
    spool = tmp_path / "spool"
    w = SpoolWatcher(d, str(spool), poll_s=0.05)  # creates the spool dir
    (spool / "s-0.json").write_text(json.dumps(
        {"feature_type": "resnet18", "video_path": serve_videos[0], "id": "s-0"}
    ))
    (spool / "s-0.cancel").write_text("")
    assert w.poll_once() == 0  # cancelled before admission
    assert not (spool / "s-0.json").exists()
    assert not (spool / "s-0.cancel").exists()
    assert d.tracker.get("s-0")["state"] == "cancelled"
    d.shutdown(drain=False)


# --- spool deferral backoff -------------------------------------------------


class _BouncingDaemon:
    """Stub daemon whose submit raises a scripted backpressure error."""

    def __init__(self, exc):
        self.exc = exc
        self.calls = 0

    def submit(self, payload, source):
        self.calls += 1
        if self.exc is not None:
            raise self.exc


def test_spool_queue_full_defers_with_backoff(tmp_path):
    spool = tmp_path / "spool"
    clock = FakeClock()
    stub = _BouncingDaemon(QueueFull("full"))
    w = SpoolWatcher(stub, str(spool), poll_s=0.5, clock=clock)
    (spool / "a.json").write_text(json.dumps({"feature_type": "resnet18",
                                              "video_path": "/v.mp4"}))
    assert w.poll_once() == 0
    assert stub.calls == 1
    assert (spool / "a.json").exists()  # un-claimed
    # deferred: re-polling at the same instant must NOT re-claim (the
    # old behavior was a tight claim/rename spin)
    assert w.poll_once() == 0
    assert stub.calls == 1
    # past the jittered backoff the file is retried
    clock.t = faults.backoff_delay(1, base=0.5, key="a.json") + 0.001
    stub.exc = None
    assert w.poll_once() == 1
    assert stub.calls == 2
    assert not (spool / "a.json").exists()


def test_spool_breaker_open_defers_but_keeps_scanning(tmp_path):
    spool = tmp_path / "spool"
    clock = FakeClock()

    class OneModelDown:
        def __init__(self):
            self.seen = []

        def submit(self, payload, source):
            self.seen.append(payload["feature_type"])
            if payload["feature_type"] == "resnet18":
                raise ModelUnavailable("resnet18", 5.0)

    stub = OneModelDown()
    w = SpoolWatcher(stub, str(spool), poll_s=0.5, clock=clock)
    (spool / "a.json").write_text(json.dumps({"feature_type": "resnet18",
                                              "video_path": "/v.mp4"}))
    (spool / "b.json").write_text(json.dumps({"feature_type": "clip",
                                              "video_path": "/v.mp4"}))
    assert w.poll_once() == 1  # b admitted despite a's open breaker
    assert stub.seen == ["resnet18", "clip"]
    assert (spool / "a.json").exists()
    assert w.poll_once() == 1 - 1  # a still deferred, nothing else to do
    assert stub.seen == ["resnet18", "clip"]


def test_spool_filename_hints_reach_payload(tmp_path):
    spool = tmp_path / "spool"

    class Capture:
        def __init__(self):
            self.payloads = []

        def submit(self, payload, source):
            self.payloads.append(payload)

    stub = Capture()
    w = SpoolWatcher(stub, str(spool), poll_s=0.5)
    (spool / "clip.p7.d500.json").write_text(json.dumps(
        {"feature_type": "resnet18", "video_path": "/v.mp4"}
    ))
    # payload fields win over filename hints
    (spool / "other.p2.json").write_text(json.dumps(
        {"feature_type": "resnet18", "video_path": "/v.mp4", "priority": 9}
    ))
    assert w.poll_once() == 2
    by_prio = sorted(stub.payloads, key=lambda p: p["priority"])
    assert by_prio[0]["priority"] == 7 and by_prio[0]["deadline_ms"] == 500.0
    assert by_prio[1]["priority"] == 9 and "deadline_ms" not in by_prio[1]


# --- breaker + watchdog through the daemon ----------------------------------


def test_breaker_opens_healthz_reflects_and_probe_recovers(tmp_path, serve_videos):
    """The acceptance path: injected extractor death opens the breaker,
    /healthz (daemon.status) reflects it, and a half-open probe recovers
    the model — daemon never restarts, extractor rebuilds exactly once."""
    clock = FakeClock()
    d, Toy = _fake_daemon(
        tmp_path, serve_videos, clock,
        fault_inject="extractor:error:2",  # second group on each build dies
        breaker_threshold=1, breaker_cooldown_s=10.0,
    )
    def one(rid, vid):
        d.submit({"feature_type": "resnet18", "video_path": vid, "id": rid},
                 source="local")
        _drain_inline(d)
        return d.tracker.get(rid)

    assert one("w-0", serve_videos[0])["state"] == "done"
    assert Toy.built == 1
    bad = one("w-1", serve_videos[1])  # injected extractor death
    assert bad["state"] == "failed" and "injected" in bad["message"]
    st = d.status()
    assert st["status"] == "degraded"
    assert st["breakers"]["resnet18"]["state"] == "open"
    assert st["breakers"]["resnet18"]["retry_after_s"] > 0
    # while open: admission for THIS model 503s with a rejected record
    with pytest.raises(ModelUnavailable):
        d.submit({"feature_type": "resnet18", "video_path": serve_videos[2],
                  "id": "w-2"}, source="local")
    assert d.tracker.get("w-2")["state"] == "rejected"
    # cooldown passes -> half-open; the next group is the probe and the
    # evicted extractor rebuilds (fresh injector counters: call 1 is ok)
    clock.t = 10.0
    assert d.status()["breakers"]["resnet18"]["state"] == "half_open"
    assert one("w-3", serve_videos[3])["state"] == "done"
    assert Toy.built == 2  # torn down on open, rebuilt for the probe
    st = d.status()
    assert st["status"] == "ok"
    assert st["breakers"]["resnet18"]["state"] == "closed"
    assert st["breakers"]["resnet18"]["opens"] == 1
    d.shutdown()


def test_breaker_open_sheds_already_queued_requests(tmp_path, serve_videos):
    clock = FakeClock()
    d, _ = _fake_daemon(
        tmp_path, serve_videos, clock,
        fault_inject="extractor:error:1",  # every group dies
        breaker_threshold=1, breaker_cooldown_s=10.0,
    )
    # two single-member groups in separate buckets: the first opens the
    # breaker, the second (already admitted) must shed, not run
    d.submit({"feature_type": "resnet18", "video_path": serve_videos[0],
              "id": "q-0", "bucket": "a"}, source="local")
    d.submit({"feature_type": "resnet18", "video_path": serve_videos[1],
              "id": "q-1", "bucket": "b"}, source="local")
    _drain_inline(d)
    states = {r: d.tracker.get(r)["state"] for r in ("q-0", "q-1")}
    assert states["q-0"] == "failed"
    q1 = d.tracker.get("q-1")
    assert q1["state"] == "failed" and "breaker open" in q1["message"]
    assert q1["error_class"] == "transient"
    d.shutdown()


def test_watchdog_times_out_hung_group_and_evicts(tmp_path, serve_videos):
    d, Toy = _daemon(
        tmp_path, serve_videos,
        fault_inject="serve_dispatch:hang:1",  # 0.4 s injected hang
        group_timeout_s=0.1, breaker_threshold=3,
    )
    d.submit({"feature_type": "resnet18", "video_path": serve_videos[0],
              "id": "hang-0"}, source="local")
    _drain_inline(d)
    rec = d.tracker.get("hang-0")
    assert rec["state"] == "failed"
    assert rec["error_type"] == "GroupTimeout"
    assert rec["error_class"] == "transient"
    # the abandoned worker's extractor was evicted; status counts the hit
    assert d.pool.feature_types() == []
    assert d.status()["watchdog_timeouts"] == 1
    # the next request rebuilds and (injector counters reset on build;
    # call 1 of serve_dispatch hangs again — wait out the 0.4 s sleep)
    d.shutdown(drain=False)


# --- fault injection on new serve stages ------------------------------------


def test_admission_fault_injection(tmp_path, serve_videos):
    d, _ = _daemon(tmp_path, serve_videos, fault_inject="admission:error:1")
    with pytest.raises(faults.InjectedTransientError):
        d.submit({"feature_type": "resnet18", "video_path": serve_videos[0],
                  "id": "adm-0"}, source="local")
    assert d.batcher.depth() == 0  # never admitted
    d.shutdown(drain=False)


def test_tracker_write_fault_degrades_not_loses(tmp_path):
    faults.install_injector(["tracker_write:error:1"])
    try:
        tr = RequestTracker(str(tmp_path))
        req = _req(0)
        tr.admit(req)
        out = tr.finish(req, "done")  # result write dies; finish survives
        assert out["state"] == "done"
        assert tr.get("r0")["state"] == "done"  # in-memory record answers
        assert not os.path.exists(os.path.join(tr.results_dir, "r0.json"))
        events = [r for r in faults.iter_manifest_records(tr.results_dir)
                  if r.get("event") == "result_write_failed"]
        assert len(events) == 1 and events[0]["request"] == "r0"
    finally:
        faults.install_injector(None)


# --- crash recovery + retention ---------------------------------------------


def test_reconcile_requeues_spool_and_fails_http(tmp_path):
    root, spool = str(tmp_path / "out"), str(tmp_path / "spool")
    t1 = RequestTracker(root)
    http_req = ExtractionRequest(feature_type="resnet18", video_path="/a.mp4",
                                 id="rh", source="http")
    spool_req = ExtractionRequest(feature_type="resnet18", video_path="/b.mp4",
                                  id="rs", source="spool", priority=4,
                                  deadline_ms=2000.0)
    done_req = ExtractionRequest(feature_type="resnet18", video_path="/c.mp4",
                                 id="rd", source="http")
    t1.admit(http_req)
    t1.admit(spool_req)
    t1.admit(done_req)
    t1.dispatched(http_req, group_size=1)
    t1.finish(done_req, "done")
    # "kill": a new tracker (fresh process) reconciles the old manifest
    t2 = RequestTracker(root)
    got = t2.reconcile(spool_dir=spool)
    assert got == {"requeued": 1, "interrupted": 1}
    assert t2.get("rh")["state"] == "failed"
    assert t2.get("rh")["error_class"] == "interrupted"
    assert t2.get("rd")["state"] == "done"  # untouched
    with open(os.path.join(spool, "rs.json"), "r", encoding="utf-8") as fh:
        payload = json.load(fh)
    assert payload == {"feature_type": "resnet18", "video_path": "/b.mp4",
                       "id": "rs", "priority": 4, "deadline_ms": 2000.0}
    # idempotent: a second restart has nothing left to reconcile
    t3 = RequestTracker(root)
    assert t3.reconcile(spool_dir=spool) == {"requeued": 0, "interrupted": 0}


def test_kill9_then_restart_reaches_terminal_states(tmp_path, serve_videos):
    """The acceptance crash: SIGKILL a process that left one request
    dispatched and one spool request queued; a restarted daemon must
    give every request a durable disposition and bound _requests/."""
    out = str(tmp_path / "out")
    script = (
        "import os, signal\n"
        "from video_features_tpu.serve.lifecycle import (\n"
        "    ExtractionRequest, RequestTracker)\n"
        f"tr = RequestTracker({out!r})\n"
        "h = ExtractionRequest(feature_type='resnet18', video_path='/a.mp4',\n"
        "                      id='k-http', source='http')\n"
        "s = ExtractionRequest(feature_type='resnet18', video_path='/b.mp4',\n"
        "                      id='k-spool', source='spool')\n"
        "d = ExtractionRequest(feature_type='resnet18', video_path='/c.mp4',\n"
        "                      id='k-done', source='http')\n"
        "tr.admit(h); tr.admit(s); tr.admit(d)\n"
        "tr.dispatched(h, group_size=2)\n"
        "tr.dispatched(s, group_size=2)\n"
        "tr.finish(d, 'done')\n"
        "os.kill(os.getpid(), signal.SIGKILL)\n"
    )
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-c", script], cwd=str(tmp_path),
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": repo_root + os.pathsep
             + os.environ.get("PYTHONPATH", "")},
        capture_output=True, timeout=120,
    )
    assert proc.returncode == -signal.SIGKILL, proc.stderr.decode()
    # restart: daemon __init__ reconciles, then sweeps to the bound
    d, _ = _daemon(tmp_path, serve_videos,
                   spool_dir=str(tmp_path / "spool"), max_request_records=1)
    assert d.recovered == {"requeued": 1, "interrupted": 1}
    assert d.tracker.get("k-http")["state"] == "failed"
    assert d.tracker.get("k-http")["error_class"] == "interrupted"
    assert os.path.exists(str(tmp_path / "spool" / "k-spool.json"))
    # every request is durably dispositioned in the folded manifest
    s = faults.merge_manifest(d.tracker.results_dir)
    assert s["videos"]["request:k-http"]["status"] == "failed"
    assert s["videos"]["request:k-done"]["status"] == "done"
    assert s["videos"]["request:k-spool"]["status"] == "requeued"
    # and the retention bound holds for result files
    results = [n for n in os.listdir(d.tracker.results_dir)
               if n.endswith(".json")]
    assert len(results) <= 1
    d.shutdown(drain=False)


def test_retention_sweep_ttl_and_count_bound(tmp_path):
    tr = RequestTracker(str(tmp_path))
    now = time.time()
    for i in range(5):
        req = _req(i)
        tr.admit(req)
        tr.finish(req, "done")
    # age r0/r1 past a 100 s TTL
    for rid in ("r0", "r1"):
        path = os.path.join(tr.results_dir, f"{rid}.json")
        os.utime(path, (now - 500, now - 500))
        tr._records[rid]["finished_ts"] = now - 500
    pruned = tr.sweep(ttl_s=100.0, max_records=2, now=now)
    assert pruned >= 2
    left = sorted(n for n in os.listdir(tr.results_dir) if n.endswith(".json"))
    assert len(left) == 2  # TTL killed 2, count bound killed 1 more
    assert "r0.json" not in left and "r1.json" not in left
    # in-memory map obeys the same bound
    with tr._lock:
        live = [r for r in tr._records.values() if r.get("state") == "done"]
    assert len(live) <= 2
    # live (non-terminal) records are never swept
    q = _req(9)
    tr.admit(q)
    tr.sweep(ttl_s=0.000001, max_records=1, now=now + 1000)
    assert tr.get("r9")["state"] == "queued"


# --- graftcheck scope (satellite: new modules, zero waivers) ----------------


def test_new_serve_modules_in_graftcheck_scope():
    import fnmatch

    from video_features_tpu.analysis.core import (
        HOT_MODULE_PATTERNS,
        THREAD_ROOT_PATTERNS,
    )

    for rel in ("serve/scheduler.py", "serve/supervisor.py"):
        assert any(fnmatch.fnmatch(rel, p) for p in HOT_MODULE_PATTERNS)
        assert any(fnmatch.fnmatch(rel, p) for p in THREAD_ROOT_PATTERNS)
    # zero waivers: neither new module asks graftcheck to look away
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for rel in ("video_features_tpu/serve/scheduler.py",
                "video_features_tpu/serve/supervisor.py"):
        with open(os.path.join(pkg, rel), "r", encoding="utf-8") as fh:
            assert "graftcheck:" not in fh.read()
