"""RAFT runtime pieces + end-to-end extraction.

Model parity lives in tests/test_reference_parity.py, which oracles
against the actual reference source (/root/reference/models/raft/
raft_src/raft.py) at full width — the round-1 builder-written torch
mirror was deleted in its favor.
"""

import numpy as np
import pytest
import torch

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.models.raft.convert import convert_state_dict
from video_features_tpu.models.raft.extract_raft import InputPadder


@pytest.mark.quick
def test_converter_rejects_unconsumed():
    from test_reference_parity import _ref_import

    raft_mod = _ref_import("models.raft.raft_src.raft")
    torch.manual_seed(0)
    sd = {f"module.{k}": v.numpy() for k, v in raft_mod.RAFT().state_dict().items()}
    sd["module.stray.weight"] = np.zeros(3, np.float32)
    with pytest.raises(ValueError, match="unconsumed"):
        convert_state_dict(sd)


@pytest.mark.quick
def test_input_padder_roundtrip():
    pad = InputPadder((135, 63))
    x = np.random.RandomState(0).randn(2, 135, 63, 3).astype(np.float32)
    p = pad.pad(x)
    # width hits the 128-px floor (deepest pyramid level needs it)
    assert p.shape == (2, 136, 128, 3)
    np.testing.assert_array_equal(pad.unpad(p), x)
    # replicate semantics on the (heavily padded) left border
    np.testing.assert_array_equal(p[:, :, 0], p[:, :, 1])


@pytest.mark.quick
def test_flow_viz_shapes():
    from video_features_tpu.utils.flow_viz import flow_to_image

    flow = np.random.RandomState(0).randn(20, 30, 2).astype(np.float32)
    img = flow_to_image(flow)
    assert img.shape == (20, 30, 3) and img.dtype == np.uint8


def test_extract_raft_end_to_end(sample_video, tmp_path):
    from video_features_tpu.models.raft.extract_raft import ExtractRAFT

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="raft",
        video_paths=[sample_video],
        extraction_fps=5.0,  # 60-frame 25fps synth clip -> 12 frames
        batch_size=5,
        side_size=64,
        on_extraction="save_numpy",
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
        cpu=True,
    )
    ex = ExtractRAFT(cfg)
    ex([0])
    import pathlib

    saved = {p.name: p for p in pathlib.Path(tmp_path / "out").rglob("*.npy")}
    assert set(saved) == {"synth_raft.npy"}
    flow = np.load(saved["synth_raft.npy"])
    # 12 frames -> 11 pairs; smaller edge resized to 64 keeps aspect
    assert flow.shape[0] == 11 and flow.shape[1] == 2
    assert flow.shape[2] == 64 or flow.shape[3] == 64
    assert np.isfinite(flow).all()


def test_lookup_corr_matches_gather_sampler():
    """The separable one-hot-matmul window lookup (models/raft/model.py
    lookup_corr — the MXU formulation of ref raft_src/corr.py:35-48) must
    equal bilinear gather sampling of the same (2r+1)^2 window, including
    zero padding at volume edges."""
    import jax.numpy as jnp

    from video_features_tpu.models.raft.model import lookup_corr
    from video_features_tpu.ops.sampler import bilinear_sampler

    rng = np.random.RandomState(0)
    N, H, W, r = 2, 16, 12, 4
    levels = []
    for lvl in range(3):
        h, w = H >> lvl, W >> lvl
        levels.append(jnp.asarray(rng.randn(N * H * W, h, w, 1).astype(np.float32)))
    # coords wander past the volume edges to exercise the zero padding
    coords = jnp.asarray(rng.uniform(-3, 18, size=(N, H, W, 2)).astype(np.float32))

    got = np.asarray(lookup_corr(levels, coords, radius=r))

    d = jnp.linspace(-r, r, 2 * r + 1, dtype=jnp.float32)
    delta = jnp.stack(jnp.meshgrid(d, d, indexing="ij"), axis=-1)
    want = []
    for lvl, corr in enumerate(levels):
        centroid = coords.reshape(N * H * W, 1, 1, 2) / (2 ** lvl)
        sampled = bilinear_sampler(
            jnp.transpose(corr, (0, 3, 1, 2)), centroid + delta[None]
        )
        want.append(np.asarray(sampled).reshape(N, H, W, (2 * r + 1) ** 2))
    want = np.concatenate(want, axis=-1)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_mixed_precision_flow_drift():
    """--dtype bfloat16 RAFT (convs bf16, refinement recurrence pinned
    fp32) vs the fp32 graph, full channel widths (VERDICT r03 next #2).

    The quantization-budget claim — flow_to_uint8 buckets flow into
    40/255 ~ 0.157 px levels, so drift under half a level (0.078 px)
    cannot change I3D features — holds for a CONVERGENT refinement, which
    is what trained RAFT is (deltas shrink toward a fixed point; flow
    magnitudes are physical, |flow| clamped to 20 px by the quantizer
    anyway). Fully random init is NOT that regime: the 20 untrained
    iterations form a non-contracting map whose flow wanders to ~100 px
    on a 128 px frame, and any rounding grows with it. So this pins BOTH:

    1. contracting regime (delta head scaled 0.05 — the same full graph,
       per-iteration updates small like a trained net's): absolute drift
       must beat the half-level budget, and the actual uint8 quantizer
       must agree to within one level;
    2. chaotic full-random regime: relative L2 stays at bf16's ~0.5%
       scale, i.e. drift only ever grows WITH the flow magnitude, never
       independently of it.
    """
    import flax
    import jax.numpy as jnp

    from video_features_tpu.models.raft.model import build, init_params
    from video_features_tpu.ops.preprocess import flow_to_uint8

    H = W = 128
    rng = np.random.RandomState(0)
    base = rng.uniform(0, 255, size=(H + 8, W + 8)).astype(np.float32)
    # frame 2 is frame 1 shifted by (3, 2) px: genuine coherent motion
    f1 = base[4 : 4 + H, 4 : 4 + W]
    f2 = base[4 - 3 : 4 - 3 + H, 4 - 2 : 4 - 2 + W]
    frames = jnp.asarray(
        np.stack([np.stack([f1] * 3, -1), np.stack([f2] * 3, -1)])
    )

    params = init_params()
    flat = flax.traverse_util.flatten_dict(params)
    for k in list(flat):
        if "flow_head" in "/".join(map(str, k)) and k[-2] == "conv2":
            flat[k] = flat[k] * 0.05
    params_contracting = flax.traverse_util.unflatten_dict(flat)

    m32, m16 = build(dtype=jnp.float32), build(dtype=jnp.bfloat16)

    # 1. contracting regime: the absolute half-level budget
    f32 = np.asarray(m32.apply({"params": params_contracting}, frames))
    f16 = np.asarray(m16.apply({"params": params_contracting}, frames))
    assert np.abs(f32).max() < 20.0  # physical flow scale, inside the clamp
    drift = np.abs(f32 - f16).max()
    assert drift < 0.078, f"flow drift {drift:.4f} px exceeds half a uint8 level"
    level_diff = np.abs(
        np.asarray(flow_to_uint8(jnp.asarray(f32)), np.int16)
        - np.asarray(flow_to_uint8(jnp.asarray(f16)), np.int16)
    )
    assert level_diff.max() <= 1
    # sub-half-level drift still flips values sitting near bucket edges;
    # what matters is that flips are rare and never exceed one level
    assert (level_diff == 0).mean() > 0.9

    # 2. chaotic regime: drift stays relative (~bf16 scale), nothing blows
    # up independently of the flow magnitude
    from video_features_tpu.analysis.parity import max_rel_drift

    f32 = np.asarray(m32.apply({"params": params}, frames))
    f16 = np.asarray(m16.apply({"params": params}, frames))
    rel = np.linalg.norm(f32 - f16) / np.linalg.norm(f32)
    budget = max_rel_drift("raft", "bfloat16", "model")
    assert rel < budget, (
        f"relative L2 drift {rel:.4f} out of bf16 scale "
        f"(parity_budget.json ceiling {budget})"
    )
