"""RAFT parity vs a torch oracle + end-to-end extraction.

The oracle is a compact torch reimplementation of princeton-vl RAFT
(basic config) with state-dict-compatible parameter names (fnet/cnet
BasicEncoder: conv1, norm1, layer{1..3}.{0,1}.*, downsample.{0,1}, conv2;
update_block.{encoder,gru,flow_head,mask}) — random weights and random
cnet BN running stats so the converter plumbing is exercised.
"""

import numpy as np
import pytest
import torch
import torch.nn.functional as F
from torch import nn

import jax.numpy as jnp

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.models.raft.convert import convert_state_dict
from video_features_tpu.models.raft.extract_raft import InputPadder
from video_features_tpu.models.raft.model import build


def _norm(kind, ch):
    return nn.BatchNorm2d(ch) if kind == "batch" else nn.InstanceNorm2d(ch)


class TorchResBlock(nn.Module):
    def __init__(self, inp, planes, norm, stride=1):
        super().__init__()
        self.conv1 = nn.Conv2d(inp, planes, 3, stride, 1)
        self.conv2 = nn.Conv2d(planes, planes, 3, 1, 1)
        self.norm1 = _norm(norm, planes)
        self.norm2 = _norm(norm, planes)
        self.downsample = None
        if stride != 1:
            self.downsample = nn.Sequential(
                nn.Conv2d(inp, planes, 1, stride), _norm(norm, planes)
            )

    def forward(self, x):
        y = torch.relu(self.norm1(self.conv1(x)))
        y = torch.relu(self.norm2(self.conv2(y)))
        if self.downsample is not None:
            x = self.downsample(x)
        return torch.relu(x + y)


class TorchEncoder(nn.Module):
    def __init__(self, out_dim, norm):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 64, 7, 2, 3)
        self.norm1 = _norm(norm, 64)
        self.layer1 = nn.Sequential(
            TorchResBlock(64, 64, norm), TorchResBlock(64, 64, norm)
        )
        self.layer2 = nn.Sequential(
            TorchResBlock(64, 96, norm, 2), TorchResBlock(96, 96, norm)
        )
        self.layer3 = nn.Sequential(
            TorchResBlock(96, 128, norm, 2), TorchResBlock(128, 128, norm)
        )
        self.conv2 = nn.Conv2d(128, out_dim, 1)

    def forward(self, x):
        x = torch.relu(self.norm1(self.conv1(x)))
        return self.conv2(self.layer3(self.layer2(self.layer1(x))))


class TorchUpdateBlock(nn.Module):
    def __init__(self):
        super().__init__()
        enc = nn.Module()
        enc.convc1 = nn.Conv2d(4 * 81, 256, 1)
        enc.convc2 = nn.Conv2d(256, 192, 3, padding=1)
        enc.convf1 = nn.Conv2d(2, 128, 7, padding=3)
        enc.convf2 = nn.Conv2d(128, 64, 3, padding=1)
        enc.conv = nn.Conv2d(256, 126, 3, padding=1)
        self.encoder = enc
        gru = nn.Module()
        for s, k, p in (("1", (1, 5), (0, 2)), ("2", (5, 1), (2, 0))):
            for g in "zrq":
                setattr(gru, f"conv{g}{s}", nn.Conv2d(384, 128, k, padding=p))
        self.gru = gru
        fh = nn.Module()
        fh.conv1 = nn.Conv2d(128, 256, 3, padding=1)
        fh.conv2 = nn.Conv2d(256, 2, 3, padding=1)
        self.flow_head = fh
        self.mask = nn.Sequential(
            nn.Conv2d(128, 256, 3, padding=1), nn.ReLU(), nn.Conv2d(256, 576, 1)
        )

    def forward(self, net, inp, corr, flow):
        e = self.encoder
        cor = torch.relu(e.convc2(torch.relu(e.convc1(corr))))
        flo = torch.relu(e.convf2(torch.relu(e.convf1(flow))))
        motion = torch.cat([torch.relu(e.conv(torch.cat([cor, flo], 1))), flow], 1)
        x = torch.cat([inp, motion], 1)
        g = self.gru
        for s in ("1", "2"):
            hx = torch.cat([net, x], 1)
            z = torch.sigmoid(getattr(g, f"convz{s}")(hx))
            r = torch.sigmoid(getattr(g, f"convr{s}")(hx))
            q = torch.tanh(getattr(g, f"convq{s}")(torch.cat([r * net, x], 1)))
            net = (1 - z) * net + z * q
        delta = self.flow_head.conv2(torch.relu(self.flow_head.conv1(net)))
        return net, 0.25 * self.mask(net), delta


def _sample(img, coords):
    H, W = img.shape[-2:]
    xg = 2 * coords[..., 0] / (W - 1) - 1
    yg = 2 * coords[..., 1] / (H - 1) - 1
    return F.grid_sample(img, torch.stack([xg, yg], -1), align_corners=True)


class TorchRAFT(nn.Module):
    def __init__(self):
        super().__init__()
        self.fnet = TorchEncoder(256, "instance")
        self.cnet = TorchEncoder(256, "batch")
        self.update_block = TorchUpdateBlock()

    def forward(self, image1, image2, iters):
        i1 = 2 * (image1 / 255.0) - 1
        i2 = 2 * (image2 / 255.0) - 1
        f1, f2 = self.fnet(i1), self.fnet(i2)
        B, C, H, W = f1.shape
        corr = torch.matmul(
            f1.view(B, C, H * W).transpose(1, 2), f2.view(B, C, H * W)
        ) / C ** 0.5
        pyr = [corr.view(B * H * W, 1, H, W)]
        for _ in range(3):
            pyr.append(F.avg_pool2d(pyr[-1], 2, 2))

        def corr_fn(coords):
            coords = coords.permute(0, 2, 3, 1)
            d = torch.linspace(-4, 4, 9)
            delta = torch.stack(torch.meshgrid(d, d, indexing="ij"), -1)
            out = []
            for i, c in enumerate(pyr):
                cl = coords.reshape(B * H * W, 1, 1, 2) / 2 ** i + delta.view(1, 9, 9, 2)
                out.append(_sample(c, cl).view(B, H, W, 81))
            return torch.cat(out, -1).permute(0, 3, 1, 2)

        cnet = self.cnet(i1)
        net, inp = torch.split(cnet, [128, 128], dim=1)
        net, inp = torch.tanh(net), torch.relu(inp)
        yy, xx = torch.meshgrid(
            torch.arange(H).float(), torch.arange(W).float(), indexing="ij"
        )
        coords0 = torch.stack([xx, yy], 0)[None].repeat(B, 1, 1, 1)
        coords1 = coords0.clone()
        for _ in range(iters):
            corr = corr_fn(coords1)
            net, mask, delta = self.update_block(net, inp, corr, coords1 - coords0)
            coords1 = coords1 + delta
        flow = coords1 - coords0
        mask = torch.softmax(mask.view(B, 1, 9, 8, 8, H, W), dim=2)
        up = F.unfold(8 * flow, [3, 3], padding=1).view(B, 2, 9, 1, 1, H, W)
        up = torch.sum(mask * up, dim=2).permute(0, 1, 4, 2, 5, 3)
        return up.reshape(B, 2, 8 * H, 8 * W)


def _torch_oracle(seed=0):
    torch.manual_seed(seed)
    model = TorchRAFT()
    with torch.no_grad():
        for m in model.modules():
            if isinstance(m, nn.BatchNorm2d):
                m.running_mean.normal_(0, 0.3)
                m.running_var.uniform_(0.5, 2.0)
    model.eval()
    return model


def test_raft_matches_torch_oracle():
    oracle = _torch_oracle()
    sd = {f"module.{k}": v.numpy() for k, v in oracle.state_dict().items()}
    params = convert_state_dict(sd)

    rng = np.random.RandomState(0)
    # >=128 px per dim: below that the deepest pyramid level is 1x1 and
    # the (reference-identical) sampler math produces NaN
    frames = rng.uniform(0, 255, size=(3, 128, 128, 3)).astype(np.float32)
    t = torch.from_numpy(np.transpose(frames, (0, 3, 1, 2)))
    with torch.no_grad():
        ref = oracle(t[:-1], t[1:], iters=4).numpy()

    flow = build(iters=4).apply({"params": params}, jnp.asarray(frames))
    flow = np.transpose(np.asarray(flow), (0, 3, 1, 2))
    assert flow.shape == ref.shape == (2, 2, 128, 128)
    assert np.isfinite(ref).all() and np.isfinite(flow).all()
    np.testing.assert_allclose(flow, ref, atol=1e-3, rtol=1e-4)


def test_converter_rejects_unconsumed():
    sd = {f"module.{k}": v.numpy() for k, v in _torch_oracle().state_dict().items()}
    sd["module.stray.weight"] = np.zeros(3, np.float32)
    with pytest.raises(ValueError, match="unconsumed"):
        convert_state_dict(sd)


def test_input_padder_roundtrip():
    pad = InputPadder((135, 63))
    x = np.random.RandomState(0).randn(2, 135, 63, 3).astype(np.float32)
    p = pad.pad(x)
    # width hits the 128-px floor (deepest pyramid level needs it)
    assert p.shape == (2, 136, 128, 3)
    np.testing.assert_array_equal(pad.unpad(p), x)
    # replicate semantics on the (heavily padded) left border
    np.testing.assert_array_equal(p[:, :, 0], p[:, :, 1])


def test_flow_viz_shapes():
    from video_features_tpu.utils.flow_viz import flow_to_image

    flow = np.random.RandomState(0).randn(20, 30, 2).astype(np.float32)
    img = flow_to_image(flow)
    assert img.shape == (20, 30, 3) and img.dtype == np.uint8


def test_extract_raft_end_to_end(sample_video, tmp_path):
    from video_features_tpu.models.raft.extract_raft import ExtractRAFT

    cfg = ExtractionConfig(
        feature_type="raft",
        video_paths=[sample_video],
        extraction_fps=5.0,  # 60-frame 25fps synth clip -> 12 frames
        batch_size=5,
        side_size=64,
        on_extraction="save_numpy",
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
        cpu=True,
    )
    ex = ExtractRAFT(cfg)
    ex([0])
    import pathlib

    saved = {p.name: p for p in pathlib.Path(tmp_path / "out").rglob("*.npy")}
    assert set(saved) == {"synth_raft.npy"}
    flow = np.load(saved["synth_raft.npy"])
    # 12 frames -> 11 pairs; smaller edge resized to 64 keeps aspect
    assert flow.shape[0] == 11 and flow.shape[1] == 2
    assert flow.shape[2] == 64 or flow.shape[3] == 64
    assert np.isfinite(flow).all()
