"""Real-pretrained-weight golden parity (VERDICT r4 next #4).

The build sandbox has zero egress (DNS fails — BASELINE.md r5 note), so
no pretrained blob has ever been loadable here; every in-sandbox parity
test necessarily runs random-init graphs against the reference SOURCES.
This file is the real-weight complement: scripts/make_goldens.py (run on
any networked host) fetches the same public checkpoints the reference
auto-downloads, converts them, extracts features for real media, and
commits small golden vectors into tests/goldens/. Wherever both the
goldens and the converted weights exist, these tests prove the whole
convert -> load -> extract path on the actual blobs.

Skip semantics are deliberate and visible: missing goldens/weights skip
with the exact command to produce them, so the gap is an actionable
instruction, not a silent green.
"""

import os
import pathlib

import numpy as np
import pytest

GOLDEN_DIR = pathlib.Path(__file__).parent / "goldens"
WEIGHTS_DIR = os.environ.get("VFT_WEIGHTS_DIR", "")

CASES = {
    # golden file prefix -> (feature_type, weights file, input kind)
    "CLIP-ViT-B-32": ("CLIP-ViT-B/32", "ViT-B-32.msgpack", "video"),
    "vggish_torch": ("vggish_torch", "vggish-10086976.msgpack", "wav"),
}


def _goldens():
    if not GOLDEN_DIR.is_dir():
        return []
    return sorted(GOLDEN_DIR.glob("*.npy"))


@pytest.mark.parametrize("golden", _goldens() or [None])
def test_real_weight_golden_parity(golden, tmp_path):
    if golden is None:
        pytest.skip(
            "no goldens committed — zero-egress sandbox; on a networked "
            "host run: python scripts/make_goldens.py --dest weights/"
        )
    prefix = next((p for p in CASES if golden.name.startswith(p)), None)
    assert prefix, f"unrecognized golden {golden.name}"
    feature_type, wfile, kind = CASES[prefix]
    weights = os.path.join(WEIGHTS_DIR, wfile)
    if not (WEIGHTS_DIR and os.path.exists(weights)):
        pytest.skip(
            f"converted weights absent ({weights!r}) — set VFT_WEIGHTS_DIR "
            "to the make_goldens.py --dest directory"
        )
    stem = golden.stem[len(prefix) + 1:]
    media = _find_media(stem, kind)
    if media is None:
        pytest.skip(f"input media {stem!r} not found on this host")

    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.extract.registry import build_extractor

    cfg = ExtractionConfig(
        feature_type=feature_type,
        video_paths=[media],
        weights_path=weights,
        extract_method="uni_12" if feature_type.startswith("CLIP") else None,
        cpu=True,
        tmp_path=str(tmp_path / "tmp"),
        output_path=str(tmp_path / "out"),
    )
    (result,) = build_extractor(cfg, external_call=True)([0])
    key = [k for k in result if k not in ("fps", "timestamps_ms")][0]
    got = np.asarray(result[key], dtype=np.float32)
    want = np.load(golden)
    assert got.shape == want.shape
    rel = float(np.linalg.norm(got - want) / max(np.linalg.norm(want), 1e-12))
    # the framework-wide budget vs the reference's torch outputs
    assert rel <= 1e-3, f"{golden.name}: relative L2 {rel}"


def _find_media(stem: str, kind: str):
    roots = [
        pathlib.Path(__file__).parents[1],
        pathlib.Path(__file__).parents[2] / "reference" / "sample",
        pathlib.Path(os.environ.get("VFT_MEDIA_DIR", "/nonexistent")),
    ]
    # vggish accepts video containers too (audio ripped via ffmpeg), and
    # make_goldens.py's no-wav fallback produces goldens from the sample
    # videos — so the wav kind must search video extensions as well
    exts = (
        (".mp4", ".avi", ".mkv")
        if kind == "video"
        else (".wav", ".mp4", ".avi", ".mkv")
    )
    for root in roots:
        if not root.is_dir():
            continue
        for ext in exts:
            hits = list(root.rglob(stem + ext))
            if hits:
                return str(hits[0])
    return None
