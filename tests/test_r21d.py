"""R(2+1)D parity vs a torch oracle + end-to-end extraction.

torchvision is not installed here, so the oracle is a minimal torch
reimplementation of torchvision's VideoResNet (r2plus1d_18 config) with
state-dict-compatible parameter names (stem.{0,1,3,4},
layer{s}.{b}.conv{k}.0.{0,1,3}, conv{k}.1, downsample.{0,1}, fc) —
randomized weights AND randomized BN running stats so the converter's
stat plumbing is actually exercised.
"""

import numpy as np
import pytest
import torch
from torch import nn

import jax.numpy as jnp

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.models.r21d.convert import convert_state_dict
from video_features_tpu.models.r21d.extract_r21d import kinetics_preprocess
from video_features_tpu.models.r21d.model import build, midplanes


def _conv2plus1d(inp, mid, out, stride=1):
    return nn.Sequential(
        nn.Conv3d(inp, mid, (1, 3, 3), (1, stride, stride), (0, 1, 1), bias=False),
        nn.BatchNorm3d(mid),
        nn.ReLU(inplace=True),
        nn.Conv3d(mid, out, (3, 1, 1), (stride, 1, 1), (1, 0, 0), bias=False),
    )


class TorchBlock(nn.Module):
    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        mid = midplanes(inplanes, planes)  # computed once, reused for both convs
        self.conv1 = nn.Sequential(
            _conv2plus1d(inplanes, mid, planes, stride),
            nn.BatchNorm3d(planes),
            nn.ReLU(inplace=True),
        )
        self.conv2 = nn.Sequential(
            _conv2plus1d(planes, mid, planes),
            nn.BatchNorm3d(planes),
        )
        self.downsample = downsample

    def forward(self, x):
        out = self.conv2(self.conv1(x))
        if self.downsample is not None:
            x = self.downsample(x)
        return torch.relu(out + x)


class TorchR2Plus1D(nn.Module):
    def __init__(self, layers=(2, 2, 2, 2), num_classes=400):
        super().__init__()
        self.stem = nn.Sequential(
            nn.Conv3d(3, 45, (1, 7, 7), (1, 2, 2), (0, 3, 3), bias=False),
            nn.BatchNorm3d(45),
            nn.ReLU(inplace=True),
            nn.Conv3d(45, 64, (3, 1, 1), 1, (1, 0, 0), bias=False),
            nn.BatchNorm3d(64),
            nn.ReLU(inplace=True),
        )
        self.inplanes = 64
        self.layer1 = self._make_layer(64, layers[0], 1)
        self.layer2 = self._make_layer(128, layers[1], 2)
        self.layer3 = self._make_layer(256, layers[2], 2)
        self.layer4 = self._make_layer(512, layers[3], 2)
        self.fc = nn.Linear(512, num_classes)

    def _make_layer(self, planes, n, stride):
        downsample = None
        if stride != 1 or self.inplanes != planes:
            downsample = nn.Sequential(
                nn.Conv3d(self.inplanes, planes, 1, stride, bias=False),
                nn.BatchNorm3d(planes),
            )
        blocks = [TorchBlock(self.inplanes, planes, stride, downsample)]
        self.inplanes = planes
        for _ in range(1, n):
            blocks.append(TorchBlock(planes, planes))
        return nn.Sequential(*blocks)

    def forward(self, x):
        x = self.layer4(self.layer3(self.layer2(self.layer1(self.stem(x)))))
        feats = x.mean(dim=(2, 3, 4))
        return feats, self.fc(feats)


def _torch_oracle(seed: int = 0) -> TorchR2Plus1D:
    torch.manual_seed(seed)
    model = TorchR2Plus1D()
    with torch.no_grad():
        for m in model.modules():
            if isinstance(m, nn.BatchNorm3d):
                m.running_mean.normal_(0, 0.5)
                m.running_var.uniform_(0.5, 2.0)
    model.eval()
    return model


def test_r21d_matches_torch_oracle():
    oracle = _torch_oracle()
    sd = {k: v.numpy() for k, v in oracle.state_dict().items()}
    params = convert_state_dict(sd)

    x = np.random.RandomState(0).randn(2, 8, 32, 32, 3).astype(np.float32)
    with torch.no_grad():
        ref_feats, ref_logits = oracle(torch.from_numpy(np.transpose(x, (0, 4, 1, 2, 3))))
    feats, logits = build().apply({"params": params}, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(feats), ref_feats.numpy(), atol=1e-4)
    np.testing.assert_allclose(np.asarray(logits), ref_logits.numpy(), atol=1e-4)


@pytest.mark.quick
def test_converter_rejects_unconsumed():
    sd = {k: v.numpy() for k, v in _torch_oracle().state_dict().items()}
    sd["stray.weight"] = np.zeros(3, np.float32)
    with pytest.raises(ValueError, match="unconsumed"):
        convert_state_dict(sd)


@pytest.mark.quick
def test_kinetics_preprocess_matches_torch():
    """The transform chain vs a torch implementation of the reference's
    ToFloatTensorInZeroOne -> Resize(128,171) -> Normalize -> CenterCrop(112)
    (ref r21d/transforms/rgb_transforms.py)."""
    rng = np.random.RandomState(1)
    vid = rng.randint(0, 256, size=(5, 90, 120, 3), dtype=np.uint8)

    t = torch.from_numpy(vid).permute(3, 0, 1, 2).float() / 255  # C,T,H,W
    t = torch.nn.functional.interpolate(
        t, size=(128, 171), mode="bilinear", align_corners=False
    )
    mean = torch.tensor([0.43216, 0.394666, 0.37645]).reshape(3, 1, 1, 1)
    std = torch.tensor([0.22803, 0.22145, 0.216989]).reshape(3, 1, 1, 1)
    t = (t - mean) / std
    i = int(round((128 - 112) / 2.0))
    j = int(round((171 - 112) / 2.0))
    t = t[..., i : i + 112, j : j + 112]
    ref = t.permute(1, 2, 3, 0).numpy()  # T,H,W,C

    ours = np.asarray(kinetics_preprocess(vid))
    np.testing.assert_allclose(ours, ref, atol=1e-5)


def test_extract_r21d_end_to_end(sample_video, tmp_path):
    from video_features_tpu.models.r21d.extract_r21d import ExtractR21D

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="r21d_rgb",
        video_paths=[sample_video],
        on_extraction="save_numpy",
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
        cpu=True,
    )
    ex = ExtractR21D(cfg)
    ex([0])
    import pathlib

    saved = {p.name: p for p in pathlib.Path(tmp_path / "out").rglob("*.npy")}
    assert set(saved) == {"synth_r21d_rgb.npy"}
    feats = np.load(saved["synth_r21d_rgb.npy"])
    # 60-frame synth clip, stack/step 16 -> 3 full stacks (ragged tail dropped)
    assert feats.shape == (3, 512)
    assert np.isfinite(feats).all()


def test_extract_r21d_show_pred(sample_video, tmp_path, capsys):
    from video_features_tpu.models.r21d.extract_r21d import ExtractR21D

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="r21d_rgb",
        video_paths=[sample_video],
        stack_size=32,
        step_size=32,
        show_pred=True,
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
        cpu=True,
    )
    res = ExtractR21D(cfg, external_call=True)([0])
    out = capsys.readouterr().out
    assert "@ frames (0, 32)" in out
    assert res[0]["r21d_rgb"].shape == (1, 512)


def test_uint8_transfer_off_matches_on(sample_video, tmp_path):
    """--uint8_transfer off (host-side fp32 pre-cast, the slow-uint8-DMA
    escape hatch) must be numerically identical to the uint8 path —
    kinetics_preprocess starts with the same fp32 cast either way."""
    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.models.r21d.extract_r21d import ExtractR21D

    def run(mode):
        cfg = ExtractionConfig(
            allow_random_init=True,
            feature_type="r21d_rgb",
            video_paths=[sample_video],
            uint8_transfer=mode,
            tmp_path=str(tmp_path / "tmp"),
            output_path=str(tmp_path / "out"),
            cpu=True,
        )
        ex = ExtractR21D(cfg, external_call=True)
        ex.progress.disable = True
        return ex([0])[0]["r21d_rgb"]

    np.testing.assert_array_equal(run("on"), run("off"))


def test_agg_cap_accounts_for_widened_transfer(sample_video, tmp_path):
    """--uint8_transfer off widens fused rows to fp32, so the AGG byte cap
    must budget 4 bytes/element — a payload admitted under uint8 near the
    cap must be declined when widened (code-review r04)."""
    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.models.r21d.extract_r21d import ExtractR21D

    def make(mode):
        return ExtractR21D(
            ExtractionConfig(
                allow_random_init=True,
                feature_type="r21d_rgb",
                video_paths=[sample_video],
                uint8_transfer=mode,
                cpu=True,
            ),
            external_call=True,
        )

    # fabricated payload just under the uint8 cap: one batch of shape
    # (1, stack, H, W, 3) with enough slices that uint8 fits, fp32 not
    stack = np.zeros((1, 16, 160, 160, 3), np.uint8)
    per_slice = int(np.prod(stack.shape[1:]))  # ~1.2 MB in uint8 units
    n_slices = (ExtractR21D.AGG_MAX_BYTES // per_slice) - 1
    payload = ([(stack, 1)], [(0, 16)] * n_slices)
    assert make("on").agg_key(payload) is not None
    assert make("off").agg_key(payload) is None


@pytest.mark.quick
def test_r21d_conv3d_decomposed_matches_direct():
    """R(2+1)D's factorized convs now ride Conv3DCompat too (r5): the
    decomposed lowering — (1,k,k) collapses to one 2D conv, (k,1,1) to a
    strided 3-term sum — must match the direct lowering on the same
    params. A truncated stem+two-stage net keeps this in the quick-tier
    budget while still covering all three decomposed paths: both
    factorized kernel shapes AND the strided 1x1x1 downsample (stage 2
    opens with stride 2)."""
    import jax

    from video_features_tpu.models.r21d.model import R2Plus1D

    x = jnp.asarray(
        np.random.RandomState(0).randn(1, 8, 56, 56, 3).astype(np.float32)
    )
    direct = R2Plus1D(layers=(1, 1), conv_impl="direct")
    decomp = R2Plus1D(layers=(1, 1), conv_impl="decomposed")
    params = direct.init(jax.random.PRNGKey(0), x)["params"]
    f1, l1 = direct.apply({"params": params}, x)
    f2, l2 = decomp.apply({"params": params}, x)
    np.testing.assert_allclose(np.asarray(f1), np.asarray(f2), atol=2e-4)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), atol=2e-4)
