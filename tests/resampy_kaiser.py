"""NumPy re-derivation of resampy's ``kaiser_best`` resampler — the test
oracle for quantifying the scipy-polyphase divergence (VERDICT r4 next
#7). resampy itself is not installable in this zero-egress sandbox, so
the published algorithm (Smith's windowed-sinc interpolation as shipped
in resampy 0.2.x — the version the reference's conda env era pins) is
re-implemented from its spec: a right-half sinc window table sampled at
2**precision points per zero crossing, Kaiser-windowed, linearly
interpolated per tap, with the cutoff scaled by the rate ratio on
downsampling. Reference call: ``resampy.resample(data, sr, 16000)``
(ref models/vggish/vggish_src/vggish_input.py:48).

kaiser_best parameters (resampy.filters):
    num_zeros=64, precision=9,
    rolloff=0.9475937167399596, beta=14.769656459379492
"""

from __future__ import annotations

import numpy as np

NUM_ZEROS = 64
PRECISION = 9
ROLLOFF = 0.9475937167399596
BETA = 14.769656459379492


def _sinc_window() -> np.ndarray:
    """The right half of the Kaiser-windowed sinc, num_zeros zero
    crossings at 2**precision samples each (resampy.filters.sinc_window)."""
    num_bits = 2 ** PRECISION
    n = num_bits * NUM_ZEROS
    taps = np.arange(n + 1) / num_bits  # 0 .. num_zeros inclusive
    sinc = ROLLOFF * np.sinc(ROLLOFF * taps)
    # kaiser over the full symmetric support, right half kept
    window = np.kaiser(2 * n + 1, BETA)[n:]
    return (sinc * window).astype(np.float64)


def resample_kaiser_best(x: np.ndarray, sr_orig: int, sr_new: int) -> np.ndarray:
    """resampy.resample(x, sr_orig, sr_new, filter='kaiser_best') on a
    1-D float array, re-derived (resampy.core.resample + interpn)."""
    x = np.asarray(x, dtype=np.float64)
    sample_ratio = sr_new / sr_orig
    win = _sinc_window()
    if sample_ratio < 1:
        # downsampling: scale cutoff (and gain) by the ratio
        win = win * sample_ratio
    delta = np.diff(win, append=0.0)  # per-entry linear-interp slopes

    num_bits = 2 ** PRECISION
    scale = min(1.0, sample_ratio)
    index_step = int(scale * num_bits)
    time_increment = 1.0 / sample_ratio
    # resampy 0.2.x: int(len * ratio) — floor, not ceil
    n_out = (len(x) * int(sr_new)) // int(sr_orig)
    out = np.zeros(n_out, dtype=np.float64)

    for t in range(n_out):
        time = t * time_increment
        n = int(time)

        # left wing: samples x[n], x[n-1], ...
        frac = scale * (time - n)
        index_frac = frac * num_bits
        offset = int(index_frac)
        eta = index_frac - offset
        i_max = min(n + 1, (len(win) - offset) // index_step)
        if i_max > 0:
            idx = offset + index_step * np.arange(i_max)
            weights = win[idx] + eta * delta[idx]
            out[t] += weights @ x[n - np.arange(i_max)]

        # right wing: samples x[n+1], x[n+2], ...
        frac = scale - frac
        index_frac = frac * num_bits
        offset = int(index_frac)
        eta = index_frac - offset
        k_max = min(len(x) - n - 1, (len(win) - offset) // index_step)
        if k_max > 0:
            idx = offset + index_step * np.arange(k_max)
            weights = win[idx] + eta * delta[idx]
            out[t] += weights @ x[n + 1 + np.arange(k_max)]

    return out.astype(np.float32)
