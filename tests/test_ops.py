"""Op-level parity vs torch oracles (torch CPU is in the env; SURVEY.md §4a)."""

import numpy as np
import pytest
import torch
import torch.nn.functional as F

import jax.numpy as jnp

from video_features_tpu.ops.correlation import all_pairs_correlation, local_correlation
from video_features_tpu.ops.padding import InputPadder, same_padding_3d, tf_same_pads
from video_features_tpu.ops.preprocess import (
    flow_to_uint8,
    imagenet_preprocess,
    pil_center_crop,
    pil_resize,
    scale_to_1_1,
    tensor_center_crop,
)
from video_features_tpu.ops.resize import (
    fused_resize_crop_matrices,
    resample_matrix,
    resize_bilinear,
    resized_hw,
)
from video_features_tpu.ops.sampler import bilinear_sampler, grid_sample

# whole-module smoke tier (README 'Quick test tier')
pytestmark = pytest.mark.quick

RNG = np.random.RandomState(42)


# --- grid_sample ----------------------------------------------------------

@pytest.mark.parametrize("align_corners", [True, False])
@pytest.mark.parametrize("padding_mode", ["zeros", "border"])
def test_grid_sample_matches_torch(align_corners, padding_mode):
    img = RNG.randn(2, 3, 11, 17).astype(np.float32)
    # grid partly out of range to exercise padding
    grid = (RNG.rand(2, 5, 7, 2).astype(np.float32) * 2.6 - 1.3)
    ref = F.grid_sample(
        torch.from_numpy(img), torch.from_numpy(grid),
        mode="bilinear", padding_mode=padding_mode, align_corners=align_corners,
    ).numpy()
    out = np.asarray(grid_sample(jnp.asarray(img), jnp.asarray(grid),
                                 padding_mode=padding_mode, align_corners=align_corners))
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_bilinear_sampler_matches_raft_semantics():
    # pixel-coordinate sampling == normalize + align_corners=True grid_sample
    img = RNG.randn(1, 4, 9, 13).astype(np.float32)
    coords = RNG.rand(1, 6, 6, 2).astype(np.float32) * 14 - 1
    H, W = 9, 13
    xg = 2 * coords[..., 0] / (W - 1) - 1
    yg = 2 * coords[..., 1] / (H - 1) - 1
    ref = F.grid_sample(
        torch.from_numpy(img),
        torch.from_numpy(np.stack([xg, yg], -1)),
        align_corners=True,
    ).numpy()
    out = np.asarray(bilinear_sampler(jnp.asarray(img), jnp.asarray(coords)))
    np.testing.assert_allclose(out, ref, atol=1e-5)

    out2, mask = bilinear_sampler(jnp.asarray(img), jnp.asarray(coords), mask=True)
    assert mask.shape == (1, 6, 6)


# --- resize ---------------------------------------------------------------

@pytest.mark.parametrize("align_corners", [True, False])
@pytest.mark.parametrize("size", [(20, 30), (5, 7), (12, 16)])
def test_resize_bilinear_matches_torch_interpolate(align_corners, size):
    x = RNG.randn(2, 3, 12, 16).astype(np.float32)
    ref = F.interpolate(torch.from_numpy(x), size=size, mode="bilinear",
                        align_corners=align_corners).numpy()
    out = np.asarray(resize_bilinear(jnp.asarray(x), size, align_corners=align_corners))
    np.testing.assert_allclose(out, ref, atol=1e-5)


# --- correlation ----------------------------------------------------------

def test_all_pairs_correlation():
    f1 = RNG.randn(2, 8, 5, 6).astype(np.float32)
    f2 = RNG.randn(2, 8, 5, 6).astype(np.float32)
    ref = np.einsum("nchw,ncij->nhwij", f1, f2) / np.sqrt(8)
    out = np.asarray(all_pairs_correlation(jnp.asarray(f1), jnp.asarray(f2)))
    np.testing.assert_allclose(out, ref, atol=1e-4)


def test_local_correlation_matches_naive():
    N, C, H, W, d = 2, 6, 7, 9, 4
    f1 = RNG.randn(N, C, H, W).astype(np.float32)
    f2 = RNG.randn(N, C, H, W).astype(np.float32)
    ref = np.zeros((N, (2 * d + 1) ** 2, H, W), np.float32)
    for dy in range(-d, d + 1):
        for dx in range(-d, d + 1):
            tc = (dy + d) * (2 * d + 1) + (dx + d)
            for y in range(H):
                for x in range(W):
                    y2, x2 = y + dy, x + dx
                    if 0 <= y2 < H and 0 <= x2 < W:
                        ref[:, tc, y, x] = (f1[:, :, y, x] * f2[:, :, y2, x2]).mean(-1)
    out = np.asarray(local_correlation(jnp.asarray(f1), jnp.asarray(f2), d))
    np.testing.assert_allclose(out, ref, atol=1e-5)


# --- padding --------------------------------------------------------------

@pytest.mark.parametrize("shape", [(1, 3, 436, 1024), (1, 3, 241, 321), (1, 3, 240, 320)])
def test_input_padder_matches_torch_replicate(shape):
    x = RNG.randn(*shape).astype(np.float32)
    padder = InputPadder(shape)
    (out,) = padder.pad(jnp.asarray(x))
    assert out.shape[-2] % 8 == 0 and out.shape[-1] % 8 == 0
    # torch oracle with same pad amounts
    ref = F.pad(torch.from_numpy(x), padder._pad, mode="replicate").numpy()
    np.testing.assert_array_equal(np.asarray(out), ref)
    np.testing.assert_array_equal(np.asarray(padder.unpad(out)), x)


def test_tf_same_pads_matches_tf_formula():
    # TF SAME: out = ceil(in/stride); pad_total = max((out-1)*s + k - in, 0)
    for size in (7, 8, 9, 16, 17, 64):
        for k in (1, 2, 3, 7):
            for s in (1, 2, 3):
                out = -(-size // s)
                total = max((out - 1) * s + k - size, 0)
                before, after = tf_same_pads(size, k, s)
                assert before + after == total
                assert after - before in (0, 1)  # extra cell goes after
    pads = same_padding_3d((16, 17, 9), (3, 3, 3), (2, 2, 2))
    assert pads == [(0, 1), (1, 1), (1, 1)]


# --- preprocess -----------------------------------------------------------

def test_pil_resize_min_and_max_edge():
    img = RNG.randint(0, 255, (120, 200, 3)).astype(np.uint8)
    out = pil_resize(img, 60)  # smaller edge (h) -> 60
    assert out.shape == (60, 100, 3)
    out = pil_resize(img, 100, resize_to_smaller_edge=False)  # larger edge -> 100
    assert out.shape == (60, 100, 3)
    out = pil_resize(img, (50, 70))
    assert out.shape == (50, 70, 3)


def test_center_crop_matches_torchvision_rounding():
    img = np.arange(7 * 9 * 3, dtype=np.uint8).reshape(7, 9, 3)
    out = pil_center_crop(img, 4)
    # torchvision crops at round((h-c)/2)=2, round((w-c)/2)=2 (banker's: 2.5->2)
    np.testing.assert_array_equal(out, img[2:6, 2:6])
    small = pil_center_crop(np.ones((2, 2, 3), np.uint8), 4)
    assert small.shape == (4, 4, 3)


def test_imagenet_preprocess_shape_and_range():
    img = RNG.randint(0, 255, (240, 320, 3)).astype(np.uint8)
    out = imagenet_preprocess(img)
    assert out.shape == (3, 224, 224)
    assert out.dtype == np.float32
    assert -3.0 < out.mean() < 3.0


def test_tensor_transforms():
    x = jnp.arange(2 * 8 * 8, dtype=jnp.float32).reshape(2, 8, 8)
    assert tensor_center_crop(x, 4).shape == (2, 4, 4)
    np.testing.assert_allclose(
        np.asarray(scale_to_1_1(jnp.array([0.0, 255.0]))), [-1, 1], atol=1e-6
    )
    # flow quantization: -20 -> 0? no: 128 - 127.5 = 0.5 -> round 0 (banker's)
    q = np.asarray(flow_to_uint8(jnp.array([-30.0, 0.0, 30.0])))
    ref = torch.tensor([-30.0, 0.0, 30.0]).clamp(-20, 20)
    ref = (128 + 255 / 40 * ref).round().numpy()
    np.testing.assert_array_equal(q, ref)


def test_corr_auto_threshold_data_driven(tmp_path, monkeypatch):
    """'auto' routing loads a measured threshold when one exists
    (corr_routing.json written by scripts/validate_corr_tpu.py on chip),
    falls back to the design default otherwise, and never crashes on a
    malformed file."""
    import json

    from video_features_tpu.ops import correlation as C

    # default: no file
    monkeypatch.setenv("VFT_CORR_ROUTING", str(tmp_path / "absent.json"))
    C._auto_threshold.cache_clear()
    assert C._auto_threshold() == C.DEFAULT_PALLAS_MIN_HW

    # measured override wins
    routing = tmp_path / "corr_routing.json"
    routing.write_text(json.dumps({"pallas_min_hw": 1024, "evidence": {}}))
    monkeypatch.setenv("VFT_CORR_ROUTING", str(routing))
    C._auto_threshold.cache_clear()
    assert C._auto_threshold() == 1024

    # malformed -> silent default (routing must never kill an extraction)
    routing.write_text("{not json")
    C._auto_threshold.cache_clear()
    assert C._auto_threshold() == C.DEFAULT_PALLAS_MIN_HW

    # nonsense values -> default (r5 review: 0/negative/bool must not
    # route every tiny shape to the kernel)
    for bad in ('{"pallas_min_hw": 0}', '{"pallas_min_hw": -4}',
                '{"pallas_min_hw": true}', '{"pallas_min_hw": "64"}'):
        routing.write_text(bad)
        C._auto_threshold.cache_clear()
        assert C._auto_threshold() == C.DEFAULT_PALLAS_MIN_HW, bad

    # measured on different hardware -> default (device_kind scoping)
    routing.write_text(json.dumps(
        {"pallas_min_hw": 1024, "device_kind": "TPU v99"}
    ))
    C._auto_threshold.cache_clear()
    assert C._auto_threshold() == C.DEFAULT_PALLAS_MIN_HW
    import jax

    routing.write_text(json.dumps(
        {"pallas_min_hw": 1024, "device_kind": jax.devices()[0].device_kind}
    ))
    C._auto_threshold.cache_clear()
    assert C._auto_threshold() == 1024
    C._auto_threshold.cache_clear()


# --- PIL-semantics resample matrices (--preprocess device) -----------------

def _two_pass_quant(img: np.ndarray, wy: np.ndarray, wx: np.ndarray) -> np.ndarray:
    """PIL's pass structure in numpy: horizontal first, round+clip to the
    uint8 grid between passes and after (ops/preprocess.py::quant8)."""
    def q8(v):
        return np.clip(np.round(v), 0.0, 255.0)

    y = q8(np.einsum("hwc,qw->hqc", img.astype(np.float64), wx))
    return q8(np.einsum("hqc,ph->pqc", y, wy))


@pytest.mark.parametrize("method,pil_filter", [
    ("bicubic", "BICUBIC"), ("bilinear", "BILINEAR"),
])
@pytest.mark.parametrize("in_hw,out_hw", [
    ((240, 426), (224, 398)),   # downsample
    ((64, 48), (160, 120)),     # upsample (support stays at the kernel's)
    ((100, 640), (224, 224)),   # mixed: upsample H, downsample W
])
def test_resample_matrix_matches_pil(method, pil_filter, in_hw, out_hw):
    from PIL import Image

    img = RNG.randint(0, 256, (in_hw[0], in_hw[1], 3)).astype(np.uint8)
    ref = np.asarray(
        Image.fromarray(img).resize(
            (out_hw[1], out_hw[0]), getattr(Image, pil_filter)
        )
    ).astype(np.float64)
    wy = resample_matrix(in_hw[0], out_hw[0], method)
    wx = resample_matrix(in_hw[1], out_hw[1], method)
    got = _two_pass_quant(img, wy, wx)
    # residual vs PIL is its 8-bit fixed-point coefficient table: at most
    # one uint8 step per quantized pass, even on worst-case random noise
    assert np.abs(got - ref).max() <= 2.0
    # taps always renormalize to a partition of unity
    np.testing.assert_allclose(wy.sum(axis=1), 1.0, atol=1e-5)
    np.testing.assert_allclose(wx.sum(axis=1), 1.0, atol=1e-5)


def test_resample_matrix_identity_at_scale_one():
    for method in ("bicubic", "bilinear"):
        np.testing.assert_array_equal(
            resample_matrix(17, 17, method), np.eye(17, dtype=np.float32)
        )


def test_resized_hw_mirrors_pil_resize():
    from PIL import Image

    for h, w in [(360, 640), (240, 426), (224, 500), (100, 640), (224, 224)]:
        img = RNG.randint(0, 256, (h, w, 3)).astype(np.uint8)
        ref = pil_resize(img, 224, interpolation=Image.BICUBIC)
        assert resized_hw(h, w, 224) == ref.shape[:2]


def test_fused_matrices_bucket_padding_cannot_bleed():
    """Columns past (h, w) carry zero weight: garbage in the spatial-bucket
    pad region must not change the output by one ULP."""
    h, w = 240, 426
    wy0, wx0 = fused_resize_crop_matrices(h, w, 224, 224, "bicubic")
    wyp, wxp = fused_resize_crop_matrices(
        h, w, 224, 224, "bicubic", pad_h=256, pad_w=448
    )
    img = RNG.randint(0, 256, (h, w)).astype(np.float32)
    padded = np.full((256, 448), 255.0, np.float32)  # worst-case garbage
    padded[:h, :w] = img
    np.testing.assert_array_equal(wy0 @ img @ wx0.T, wyp @ padded @ wxp.T)


def test_fused_matrices_crop_pad_matches_pil_center_crop():
    """Resized image SMALLER than the crop: pil_center_crop zero-pads with
    floor-divided margins before cropping; the fused matrices must place
    their zero rows/cols identically."""
    from PIL import Image

    h, w, resize_to, crop = 50, 40, 64, 96  # resized (80, 64) < 96
    img = RNG.randint(0, 256, (h, w, 3)).astype(np.uint8)
    oh, ow = resized_hw(h, w, resize_to)
    assert oh < crop and ow < crop
    resized = np.asarray(
        Image.fromarray(img).resize((ow, oh), Image.BICUBIC)
    )
    ref = pil_center_crop(resized, crop).astype(np.float64)
    wy, wx = fused_resize_crop_matrices(h, w, resize_to, crop, "bicubic")
    got = _two_pass_quant(img, wy, wx)
    assert np.abs(got - ref).max() <= 1.0


def test_spatial_bucket_and_pad_hw():
    from video_features_tpu.ops.window import pad_hw, spatial_bucket

    assert spatial_bucket(240, 426) == (256, 448)
    assert spatial_bucket(256, 448) == (256, 448)  # already on the grid
    assert spatial_bucket(1, 1) == (64, 64)        # floor = multiple
    assert spatial_bucket(100, 640, multiple=32) == (128, 640)
    # explicit buckets: smallest (by area) that fits both axes
    bk = [(720, 1280), (256, 448)]
    assert spatial_bucket(240, 426, buckets=bk) == (256, 448)
    assert spatial_bucket(300, 426, buckets=bk) == (720, 1280)
    assert spatial_bucket(800, 1400, buckets=bk) == (832, 1408)  # fallback

    x = RNG.randint(0, 256, (5, 240, 426, 3)).astype(np.uint8)
    p = pad_hw(x, 256, 448)
    assert p.shape == (5, 256, 448, 3)
    np.testing.assert_array_equal(p[:, :240, :426], x)
    assert p[:, 240:].sum() == 0 and p[:, :, 426:].sum() == 0
    assert pad_hw(x, 240, 426) is x  # no-op fast path


def test_banded_taps_reconstruct_dense_and_share_bucket_k():
    from video_features_tpu.ops.resize import banded, fused_resize_crop_banded

    wy, wx = fused_resize_crop_matrices(240, 426, 224, 224, "bicubic",
                                        pad_h=256, pad_w=448)
    for m in (wy, wx):
        wt, idx = banded(m)
        back = np.zeros_like(m)
        for q in range(m.shape[0]):
            for k in range(wt.shape[1]):
                back[q, idx[q, k]] += wt[q, k]  # dup tail indices carry 0
        np.testing.assert_array_equal(back, m)
    # K is computed at the bucket corner: two resolutions sharing the
    # (256, 448) bucket must produce stackable (same-K) tap arrays
    a = fused_resize_crop_banded(240, 426, 224, 224, "bicubic", 256, 448)
    b = fused_resize_crop_banded(232, 420, 224, 224, "bicubic", 256, 448)
    assert a[0].shape == b[0].shape and a[2].shape == b[2].shape
