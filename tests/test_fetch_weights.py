"""scripts/fetch_weights.py: the opt-in download convenience (VERDICT
r03 missing #3). Network is mocked — this sandbox has zero egress; what
matters is the contract: URL registry sanity, atomic skip-if-present
downloads, manual-recipe models refusing with a pointer."""

import hashlib
import io
import re
import sys
import pathlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1] / "scripts"))

import fetch_weights as fw
import pytest

# whole-module smoke tier (README 'Quick test tier')
pytestmark = pytest.mark.quick


def test_url_registry_matches_reference_sources():
    for ft, entries in fw.SOURCES.items():
        for url, fname, sha in entries:
            assert url.startswith(("https://", "http://")), url
            assert any(
                host in url
                for host in (
                    "openaipublic.azureedge.net",  # pip clip's blobs
                    "github.com/harritaylor/torchvggish",  # ref vggish_torch
                    "content.sniklaus.com",  # ref pwc checkpoint README
                    "github.com/hassony2/kinetics_i3d_pytorch",  # ref i3d
                )
            ), url
            assert fname == fname.strip("/")
            assert sha is None or re.fullmatch(r"[0-9a-f]{8,64}", sha), sha
    # every feature type is either fetchable or documented-manual
    assert set(fw.MANUAL) & set(fw.SOURCES) == set()


def test_fetch_writes_atomically_and_skips_existing(tmp_path):
    dest = tmp_path / "w.pt"
    calls = []

    def opener(url):
        calls.append(url)
        return io.BytesIO(b"checkpoint-bytes")

    got = fw.fetch("http://example/w.pt", str(dest), opener=opener)
    assert got == str(dest)
    assert dest.read_bytes() == b"checkpoint-bytes"
    assert not (tmp_path / "w.pt.part").exists()
    # second call: present -> no network
    fw.fetch("http://example/w.pt", str(dest), opener=opener)
    assert calls == ["http://example/w.pt"]


def test_manual_models_refuse_with_pointer(capsys):
    assert fw.main(["raft", "--dest", "x"]) == 1
    assert "docs/weights.md" in capsys.readouterr().out


def test_download_only_flow(tmp_path, monkeypatch):
    monkeypatch.setattr(
        fw.urllib.request, "urlopen", lambda url: io.BytesIO(b"pt-bytes")
    )
    rc = fw.main(["pwc", "--dest", str(tmp_path), "--skip-convert"])
    assert rc == 0
    assert (tmp_path / "network-default.pytorch").read_bytes() == b"pt-bytes"


def test_fetch_verifies_sha256(tmp_path):
    """A tampered/truncated download (or a stale present file) must not
    reach convert_weights (advisor r4): full digests, torch-hub-style
    prefixes, and the None-warn path."""
    import pytest

    body = b"checkpoint-bytes"
    digest = hashlib.sha256(body).hexdigest()
    opener = lambda url: io.BytesIO(body)

    ok = tmp_path / "ok.pt"
    fw.fetch("http://x/ok.pt", str(ok), opener=opener, sha256=digest)
    assert ok.read_bytes() == body
    # prefix form (torch-hub filename convention)
    fw.fetch("http://x/ok.pt", str(ok), opener=opener, sha256=digest[:8])

    bad = tmp_path / "bad.pt"
    with pytest.raises(SystemExit, match="sha256 mismatch"):
        fw.fetch("http://x/bad.pt", str(bad), opener=opener, sha256="0" * 64)
    assert not bad.exists()  # removed so a re-run re-downloads

    # present-but-corrupt file: the skip path re-verifies and falls
    # through to a fresh (good) download — covered in depth by
    # test_fetch_redownloads_stale_file_in_same_run
    stale = tmp_path / "stale.pt"
    stale.write_bytes(b"truncat")
    fw.fetch("http://x/stale.pt", str(stale), opener=opener, sha256=digest)
    assert stale.read_bytes() == body


def test_fetch_warns_without_digest(tmp_path, capsys):
    fw.fetch("http://x/n.pt", str(tmp_path / "n.pt"),
             opener=lambda url: io.BytesIO(b"b"), sha256=None)
    assert "no published sha256" in capsys.readouterr().out


def test_fetch_redownloads_stale_file_in_same_run(tmp_path):
    """A present-but-corrupt file is removed and re-downloaded in the
    SAME run (r5 review: the first cut exited and demanded a re-run)."""
    body = b"checkpoint-bytes"
    digest = hashlib.sha256(body).hexdigest()
    calls = []

    def opener(url):
        calls.append(url)
        return io.BytesIO(body)

    dest = tmp_path / "w.pt"
    dest.write_bytes(b"truncat")
    got = fw.fetch("http://x/w.pt", str(dest), opener=opener, sha256=digest)
    assert calls == ["http://x/w.pt"]  # downloaded despite being "present"
    assert pathlib.Path(got).read_bytes() == body


def test_fetch_rejects_empty_download_without_digest(tmp_path):
    import pytest

    with pytest.raises(SystemExit):
        fw.fetch("http://x/e.pt", str(tmp_path / "e.pt"),
                 opener=lambda url: io.BytesIO(b""), sha256=None)
    assert not (tmp_path / "e.pt").exists()
