"""scripts/fetch_weights.py: the opt-in download convenience (VERDICT
r03 missing #3). Network is mocked — this sandbox has zero egress; what
matters is the contract: URL registry sanity, atomic skip-if-present
downloads, manual-recipe models refusing with a pointer."""

import io
import sys
import pathlib

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1] / "scripts"))

import fetch_weights as fw


def test_url_registry_matches_reference_sources():
    for ft, entries in fw.SOURCES.items():
        for url, fname in entries:
            assert url.startswith(("https://", "http://")), url
            assert any(
                host in url
                for host in (
                    "openaipublic.azureedge.net",  # pip clip's blobs
                    "github.com/harritaylor/torchvggish",  # ref vggish_torch
                    "content.sniklaus.com",  # ref pwc checkpoint README
                    "github.com/hassony2/kinetics_i3d_pytorch",  # ref i3d
                )
            ), url
            assert fname == fname.strip("/")
    # every feature type is either fetchable or documented-manual
    assert set(fw.MANUAL) & set(fw.SOURCES) == set()


def test_fetch_writes_atomically_and_skips_existing(tmp_path):
    dest = tmp_path / "w.pt"
    calls = []

    def opener(url):
        calls.append(url)
        return io.BytesIO(b"checkpoint-bytes")

    got = fw.fetch("http://example/w.pt", str(dest), opener=opener)
    assert got == str(dest)
    assert dest.read_bytes() == b"checkpoint-bytes"
    assert not (tmp_path / "w.pt.part").exists()
    # second call: present -> no network
    fw.fetch("http://example/w.pt", str(dest), opener=opener)
    assert calls == ["http://example/w.pt"]


def test_manual_models_refuse_with_pointer(capsys):
    assert fw.main(["raft", "--dest", "x"]) == 1
    assert "docs/weights.md" in capsys.readouterr().out


def test_download_only_flow(tmp_path, monkeypatch):
    monkeypatch.setattr(
        fw.urllib.request, "urlopen", lambda url: io.BytesIO(b"pt-bytes")
    )
    rc = fw.main(["pwc", "--dest", str(tmp_path), "--skip-convert"])
    assert rc == 0
    assert (tmp_path / "network-default.pytorch").read_bytes() == b"pt-bytes"
