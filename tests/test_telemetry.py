"""Structured telemetry (ISSUE 6): span JSONL schema, nested +
cross-thread emission under the pipelined loop, overlap-efficiency math
on synthetic fixtures, Chrome-trace export, the summary.json telemetry
block, failure-record span linkage, the recompile watch, and the
``python -m video_features_tpu.telemetry`` consumers.

A toy extractor (same shape as tests/test_faults.py) drives the real
pipelined loop once per module; the span files it leaves under
``<out>/_telemetry/`` are the fixture most tests read."""

import glob
import json
import os
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

from video_features_tpu.config import ExtractionConfig, sanity_check
from video_features_tpu.extract.base import BaseExtractor
from video_features_tpu.io.paths import video_path_of
from video_features_tpu.io.video import stream_frames
from video_features_tpu.runtime import faults
from video_features_tpu.runtime import telemetry as tm
from video_features_tpu.telemetry import SCHEMA_PATH, load_schema
from video_features_tpu.telemetry.__main__ import main as tele_main


@pytest.fixture(autouse=True)
def _clear_global_telemetry_state():
    """set_current / the fault injector are process-global latest-wins;
    never leak one test's extractor into the rest of the suite."""
    yield
    tm.set_current(None)
    faults.install_injector(None)


@pytest.fixture(scope="module")
def toy_videos(tmp_path_factory):
    from video_features_tpu.utils.synth import synth_video

    d = tmp_path_factory.mktemp("tele_media")
    return [
        synth_video(str(d / f"v{i}.mp4"), n_frames=8, width=64, height=48, seed=i)
        for i in range(3)
    ]


class ToyExtractor(BaseExtractor):
    feature_type = "toy"

    def _build(self, device):
        return {"device": device}

    def prepare(self, path_entry):
        vals = [float(frame.mean()) for frame, _ in stream_frames(video_path_of(path_entry))]
        return np.asarray(vals, dtype=np.float32)

    def extract_prepared(self, device, state, path_entry, payload):
        return {
            "toy": np.asarray(payload).reshape(-1, 1),
            "fps": 25.0,
            "timestamps_ms": np.arange(len(payload), dtype=np.float64),
        }


class ToyAgg(ToyExtractor):
    def agg_key(self, payload):
        return np.asarray(payload).shape

    def dispatch_group(self, device, state, entries, payloads):
        return [
            ToyExtractor.extract_prepared(self, device, state, e, p)
            for e, p in zip(entries, payloads)
        ]

    def fetch_group(self, handle):
        return handle


def _cfg(videos, out_dir, **kw):
    kw.setdefault("decode_workers", 1)
    kw.setdefault("retry_backoff", 0.01)
    return ExtractionConfig(
        allow_random_init=True,
        video_paths=list(videos),
        on_extraction="save_numpy",
        output_path=str(out_dir / "out"),
        tmp_path=str(out_dir / "tmp"),
        cpu=True,
        **kw,
    )


@pytest.fixture(scope="module")
def agg_run(tmp_path_factory, toy_videos):
    """One real pipelined + aggregated run (2 decode workers,
    --video_batch 2): the span files, summary, and config most tests
    below assert against."""
    tmp = tmp_path_factory.mktemp("tele_run")
    cfg = _cfg(toy_videos, tmp, decode_workers=2, video_batch=2)
    ex = ToyAgg(cfg)
    ex()
    ex.telemetry.close()
    summary = faults.finalize_run(cfg.output_path)
    files = sorted(glob.glob(os.path.join(cfg.output_path, "_telemetry", "spans-*.jsonl")))
    rows = [r for f in files for r in tm.read_spans(f)]
    tm.set_current(None)
    return SimpleNamespace(cfg=cfg, rows=rows, summary=summary, files=files)


# --- span JSONL schema -------------------------------------------------------


def test_spans_schema_is_itself_valid():
    jsonschema = pytest.importorskip("jsonschema")
    schema = load_schema()
    jsonschema.Draft7Validator.check_schema(schema)
    assert os.path.basename(SCHEMA_PATH) == "spans_schema.json"
    assert set(schema["properties"]["stage"]["enum"]) == set(tm.STAGES)


def test_run_spans_validate_against_committed_schema(agg_run):
    jsonschema = pytest.importorskip("jsonschema")
    validator = jsonschema.Draft7Validator(load_schema())
    assert agg_run.rows, "pipelined run recorded no spans"
    for row in agg_run.rows:
        validator.validate(row)


def test_run_emits_every_hot_path_stage(agg_run):
    stages = {r["stage"] for r in agg_run.rows}
    # decode (io/ reader), prepare (decode workers), dispatch/fetch
    # (group path), sink — the full pipelined hot path
    assert {"decode", "prepare", "dispatch", "fetch", "sink"} <= stages
    # every span is a closed interval with a monotonic clock
    for r in agg_run.rows:
        assert r["t1"] >= r["t0"]
    # ids are unique and sequenced within the run
    ids = [r["span"] for r in agg_run.rows]
    assert len(ids) == len(set(ids))


def test_cross_thread_and_nested_spans_under_pipelined_loop(agg_run):
    by_id = {r["span"]: r for r in agg_run.rows}
    prepares = [r for r in agg_run.rows if r["stage"] == "prepare"]
    decodes = [r for r in agg_run.rows if r["stage"] == "decode"]
    assert len(prepares) == 3 and len(decodes) == 3
    # prepare runs on the decode worker pool, not the device loop thread
    for p in prepares:
        assert p["thread_name"].startswith("decode-")
        assert p["video"] and p["worker"] and p["attempt"] == 1
    # >1 worker => prepares actually spread across threads
    assert len({p["thread"] for p in prepares}) > 1
    # each decode span nests under its video's prepare, on the same thread
    for d in decodes:
        parent = by_id[d["parent"]]
        assert parent["stage"] == "prepare"
        assert parent["video"] == d["video"]
        assert parent["thread"] == d["thread"]
        assert parent["t0"] <= d["t0"] and d["t1"] <= parent["t1"] + 0.05
    # dispatch/fetch run on the device loop thread with the group size
    # (3 videos / --video_batch 2 => one full group + a remainder of 1)
    grouped = [
        r for r in agg_run.rows
        if r["stage"] in ("dispatch", "fetch") and r.get("group_size")
    ]
    assert {r["group_size"] for r in grouped} == {1, 2}
    assert all(r["thread_name"] == "MainThread" for r in grouped)


def test_summary_json_gains_telemetry_block(agg_run):
    tele = agg_run.summary["telemetry"]
    assert tele["counters"]["videos_done"] == 3
    assert tele["counters"]["frames_decoded"] == 3 * 8
    # stage totals (the old StageTimer aggregate) now always land here
    assert tele["stages"]["prepare"]["calls"] == 3
    assert tele["stages"]["sink"]["calls"] == 3
    assert tele["stages"]["decode"]["seconds"] > 0
    assert tele["throughput"]["videos_per_s"] > 0
    assert tele["throughput"]["decode_fps"] > 0
    assert tele["overlap"]["spans"] >= 6
    assert tele["span_files"] and all(f.startswith("spans-") for f in tele["span_files"])
    # and the one-line digest prints throughput
    line = faults.format_summary(agg_run.summary)
    assert "videos/s" in line and "decode fps" in line


def test_metrics_snapshot_file_on_disk(agg_run):
    paths = glob.glob(os.path.join(agg_run.cfg.output_path, "_telemetry", "metrics-*.json"))
    assert len(paths) == 1
    with open(paths[0], "r", encoding="utf-8") as f:
        snap = json.load(f)
    assert snap["counters"]["videos_done"] == 3
    hist = snap["histograms"]["stage_s.prepare"]
    assert hist["count"] == 3 and sum(hist["buckets"]) == 3
    assert len(hist["buckets"]) == len(hist["bounds"]) + 1


# --- consumers: export / report CLI ------------------------------------------


def test_export_cli_writes_valid_chrome_trace(agg_run, tmp_path, capsys):
    out = tmp_path / "trace.json"
    assert tele_main(["export", agg_run.cfg.output_path, "-o", str(out)]) == 0
    assert "perfetto" in capsys.readouterr().err
    with open(out, "r", encoding="utf-8") as f:
        trace = json.load(f)
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    ms = [e for e in trace["traceEvents"] if e["ph"] == "M"]
    assert len(xs) == len(agg_run.rows)
    assert ms and all(m["name"] == "thread_name" for m in ms)
    last = -1
    for e in xs:
        assert isinstance(e["ts"], int) and e["ts"] >= 0
        assert isinstance(e["dur"], int) and e["dur"] >= 0
        assert e["name"] in tm.STAGES
        assert e["ts"] >= last  # monotonic ordering
        last = e["ts"]


def test_report_cli_prints_overlap(agg_run, capsys):
    assert tele_main(["report", agg_run.cfg.output_path, "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["spans"] == len(
        [r for r in agg_run.rows if r["stage"] in tm.HOST_STAGES | tm.DEVICE_STAGES]
    )
    assert rep["wall_s"] > 0


def test_cli_no_spans_is_usage_error(tmp_path, capsys):
    assert tele_main(["report", str(tmp_path)]) == 2
    assert "no spans" in capsys.readouterr().err


# --- overlap math on synthetic fixtures --------------------------------------


def _row(stage, t0, t1, pid=1):
    return {"stage": stage, "t0": t0, "t1": t1, "pid": pid}


def test_overlap_report_pinned_values():
    rep = tm.overlap_report([
        _row("prepare", 0.0, 10.0),
        _row("dispatch", 5.0, 15.0),
    ])
    assert rep["wall_s"] == pytest.approx(15.0)
    assert rep["host_busy_s"] == pytest.approx(10.0)
    assert rep["device_busy_s"] == pytest.approx(10.0)
    assert rep["overlap_s"] == pytest.approx(5.0)
    assert rep["overlap_efficiency"] == pytest.approx(5.0 / 15.0)
    assert rep["overlap_of_device"] == pytest.approx(0.5)
    assert rep["spans"] == 2


def test_overlap_report_merges_intervals_before_intersecting():
    # two abutting host spans + an overlapping third must not double count
    rep = tm.overlap_report([
        _row("decode", 0.0, 2.0),
        _row("decode", 2.0, 4.0),
        _row("prepare", 1.0, 3.0),
        _row("fetch", 1.0, 5.0),
    ])
    assert rep["host_busy_s"] == pytest.approx(4.0)
    assert rep["device_busy_s"] == pytest.approx(4.0)
    assert rep["overlap_s"] == pytest.approx(3.0)  # [1,4]
    assert rep["wall_s"] == pytest.approx(5.0)


def test_overlap_report_is_per_pid():
    # monotonic clocks are incomparable across pids: same timestamps in
    # two pids must not be treated as concurrent
    rows = [_row("prepare", 0.0, 1.0, pid=1), _row("dispatch", 0.0, 1.0, pid=2)]
    rep = tm.overlap_report(rows)
    assert rep["overlap_s"] == 0.0
    assert rep["wall_s"] == pytest.approx(2.0)  # summed per-pid walls


def test_overlap_report_ignores_junk_rows():
    rep = tm.overlap_report([
        _row("prepare", 0.0, 1.0),
        _row("extract", 0.0, 50.0),        # serial stage: in neither set
        {"stage": "fetch", "t0": 3.0, "t1": 1.0, "pid": 1},  # t1 < t0
        {"stage": "fetch", "t0": None, "t1": 2.0, "pid": 1},
    ])
    assert rep["spans"] == 1 and rep["host_busy_s"] == pytest.approx(1.0)
    assert rep["device_busy_s"] == 0.0


def test_report_zero_device_stage_spans_no_division_crash():
    # a host-only run (decode smoke test, serve lifecycle spans only):
    # device busy is 0 and both ratios must degrade to 0.0, not ZeroDivision
    rep = tm.overlap_report([
        _row("decode", 0.0, 2.0),
        _row("prepare", 1.0, 3.0),
    ])
    assert rep["device_busy_s"] == 0.0
    assert rep["overlap_s"] == 0.0
    assert rep["overlap_efficiency"] == 0.0
    assert rep["overlap_of_device"] == 0.0
    assert rep["wall_s"] == pytest.approx(3.0)


def test_report_cli_zero_device_spans(tmp_path, capsys):
    # end to end through the CLI: a spans file with no device-stage rows
    # still reports (the ratios are 0.0%, not an error)
    f = tmp_path / "spans-host.jsonl"
    f.write_text(json.dumps({
        "span": "r.1", "seq": 1, "stage": "decode", "t0": 0.0, "t1": 1.0,
        "pid": 1, "run": "r", "thread": 1, "thread_name": "MainThread",
    }) + "\n")
    assert tele_main(["report", str(f), "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["device_busy_s"] == 0.0 and rep["overlap_of_device"] == 0.0


def test_report_single_pid_wall_is_one_window():
    # all spans in one pid: wall is one min->max window, not a sum
    rep = tm.overlap_report([
        _row("prepare", 0.0, 1.0, pid=7),
        _row("dispatch", 10.0, 11.0, pid=7),
    ])
    assert rep["wall_s"] == pytest.approx(11.0)
    assert rep["overlap_s"] == 0.0


def test_report_cli_empty_spans_file_is_usage_error(tmp_path, capsys):
    # an existing-but-empty spans file (a run that died before the first
    # flush) is "no spans", exit 2 — same as a missing directory
    f = tmp_path / "spans-empty.jsonl"
    f.write_text("")
    assert tele_main(["report", str(f)]) == 2
    assert "no spans" in capsys.readouterr().err


def test_chrome_trace_from_synthetic_rows():
    rows = [
        {"span": "r.1", "stage": "prepare", "video": "v", "t0": 10.0, "t1": 10.5,
         "pid": 1, "thread": 7, "thread_name": "decode-cpu_0"},
        {"span": "r.2", "stage": "dispatch", "video": "v", "t0": 10.25, "t1": 10.75,
         "pid": 1, "thread": 8, "thread_name": "MainThread"},
    ]
    trace = tm.spans_to_chrome_trace(rows)
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert [e["ts"] for e in xs] == [0, 250000]
    assert [e["dur"] for e in xs] == [500000, 500000]
    assert xs[0]["args"]["video"] == "v" and xs[0]["args"]["span"] == "r.1"
    names = {e["args"]["name"] for e in trace["traceEvents"] if e["ph"] == "M"}
    assert names == {"decode-cpu_0", "MainThread"}


# --- engine units ------------------------------------------------------------


def test_span_exception_stamps_innermost_span_id():
    tele = tm.Telemetry(enabled=True)
    with pytest.raises(RuntimeError) as ei:
        with tele.span("prepare", video="v"):
            with tele.span("decode", video="v"):
                raise RuntimeError("boom")
    rows = tele.spans()
    decode = next(r for r in rows if r["stage"] == "decode")
    assert ei.value.telemetry_span == decode["span"]
    # both spans still closed, aggregate timer fed
    assert tele.timer.counts["prepare"] == 1 and tele.timer.counts["decode"] == 1
    tele.close()


def test_disabled_mode_is_bare_stage_timer():
    tele = tm.Telemetry(enabled=False)
    with tele.span("prepare") as row:
        assert row is None
    assert tele.timer.counts["prepare"] == 1
    assert tele.spans() == []
    assert tele.begin("decode") is None
    tm.end(None)  # module hook tolerates the disabled token
    tele.close()


def test_begin_end_token_and_memory_retention():
    tele = tm.Telemetry(enabled=True)
    tok = tele.begin("decode", video="v", worker="cpu:0")
    assert tok is not None and tok.span_id.endswith(".1")
    tok.finish(frames=8)
    tok.finish()  # idempotent
    rows = tele.spans()
    assert len(rows) == 1 and rows[0]["frames"] == 8
    assert rows[0]["worker"] == "cpu:0"
    tele.close()


def test_module_hooks_route_to_current_telemetry():
    tele = tm.Telemetry(enabled=True)
    tm.set_current(tele)
    tm.frame_decoded(5)
    tm.note_bucket((64, 64))
    tm.note_bucket((64, 64))
    tm.note_bucket((128, 64))
    tok = tm.begin("decode", video="v")
    tm.end(tok)
    assert tele.metrics.counter("frames_decoded") == 5
    assert tele.buckets_seen() == 2
    assert [r["stage"] for r in tele.spans()] == ["decode"]
    tm.set_current(None)
    tm.frame_decoded(1)  # no current: must not raise
    tele.close()


def test_payload_nbytes_nested():
    a = np.zeros((4, 3), dtype=np.float32)
    assert tm.payload_nbytes(a) == 48
    assert tm.payload_nbytes({"x": a, "y": [a, a]}) == 144
    assert tm.payload_nbytes(("s", 3, None)) == 0


def test_heartbeat_line_format():
    tele = tm.Telemetry(enabled=True, total_videos=10)
    tele.metrics.inc("videos_done", 4)
    tele.metrics.inc("frames_decoded", 100)
    line = tele.heartbeat_line()
    assert line.startswith("telemetry: 4/10 videos,")
    assert "videos/s" in line and "decode fps" in line and "eta" in line
    tele.close()


def test_read_spans_skips_torn_trailing_line(tmp_path):
    p = tmp_path / "spans-x.jsonl"
    p.write_text('{"span": "r.1", "stage": "sink"}\n{"span": "r.2", "sta')
    rows = tm.read_spans(str(p))
    assert len(rows) == 1 and rows[0]["span"] == "r.1"


def test_merge_metrics_files(tmp_path):
    tdir = tmp_path / "_telemetry"
    tdir.mkdir()
    hist = {"count": 2, "sum": 0.5, "min": 0.1, "max": 0.4,
            "bounds": list(tm.HIST_BOUNDS), "buckets": [0] * (len(tm.HIST_BOUNDS) + 1)}
    for i, (done, gauge) in enumerate([(2, 3), (1, 5)]):
        (tdir / f"metrics-{i}.json").write_text(json.dumps({
            "t_start": 100.0 + i, "t_snapshot": 110.0 + i,
            "counters": {"videos_done": done, "frames_decoded": done * 8},
            "gauges": {"queue_depth.pending": gauge},
            "histograms": {"stage_s.decode": hist},
            "buckets_seen": i + 1,
        }))
    (tdir / "metrics-torn.json").write_text("{nope")  # crashed process
    block = tm.merge_metrics_files(str(tmp_path))
    assert block["counters"]["videos_done"] == 3           # counters sum
    assert block["gauges"]["queue_depth.pending"] == 5     # gauges max
    merged = block["histograms"]["stage_s.decode"]
    assert merged["count"] == 4 and merged["sum"] == pytest.approx(1.0)
    assert block["buckets_seen"] == 2
    # wall spans min(t_start)..max(t_snapshot); decode fps uses stage sum
    assert block["throughput"]["wall_s"] == pytest.approx(11.0)
    assert block["throughput"]["videos_per_s"] == pytest.approx(3 / 11.0)
    assert block["throughput"]["decode_fps"] == pytest.approx(24 / 1.0)
    assert tm.merge_metrics_files(str(tmp_path / "nowhere")) is None


def test_flush_concurrent_with_recording():
    tele = tm.Telemetry(enabled=True)
    stop = threading.Event()

    def record():
        while not stop.is_set():
            with tele.span("sink", video="v"):
                pass

    threads = [threading.Thread(target=record) for _ in range(4)]
    for t in threads:
        t.start()
    for _ in range(20):
        tele.flush()
    stop.set()
    for t in threads:
        t.join()
    rows = tele.spans()
    assert len(rows) == tele.timer.counts["sink"]
    tele.close()


# --- recompile watch ---------------------------------------------------------


class _FakeManifest:
    def __init__(self):
        self.records = []

    def record(self, key, status, **fields):
        self.records.append((key, status, fields))


def test_runtime_compile_limits_from_committed_budget():
    limits = tm.runtime_compile_limits()
    assert limits and all(v >= 1 for v in limits.values())
    # the device-preprocess family the watch exists for is budgeted
    assert "encode_raw" in limits


def test_recompile_watch_warns_once_above_per_bucket_allowance():
    tele = tm.Telemetry(enabled=True)
    man = _FakeManifest()
    watch = tm.RecompileWatch(tele, man)  # not attached: unit-test on_compile
    watch.limits = {"encode_raw": 2}
    for _ in range(2):
        watch.on_compile("encode_raw")
    assert man.records == []  # within the ceiling
    watch.on_compile("encode_raw")
    assert len(man.records) == 1
    key, status, fields = man.records[0]
    assert status == "warning" and fields["stage"] == "compile"
    assert "encode_raw" in fields["message"] and "allowance is 2" in fields["message"]
    watch.on_compile("encode_raw")  # one warning per fn name, ever
    assert len(man.records) == 1
    # every build became a counter increment + a zero-duration span
    assert tele.metrics.counter("compiles") == 4
    compiles = [r for r in tele.spans() if r["stage"] == "compile"]
    assert [c["n"] for c in compiles] == [1, 2, 3, 4]
    assert all(c["fn"] == "encode_raw" for c in compiles)
    tele.close()


def test_recompile_watch_allowance_scales_with_buckets():
    tele = tm.Telemetry(enabled=True)
    tele.note_bucket((64, 64))
    tele.note_bucket((128, 128))
    man = _FakeManifest()
    watch = tm.RecompileWatch(tele, man)
    watch.limits = {"encode_raw": 2}
    for _ in range(4):  # 2/bucket x 2 buckets: still legitimate
        watch.on_compile("encode_raw")
    assert man.records == []
    watch.on_compile("encode_raw")
    assert len(man.records) == 1 and "x 2" in man.records[0][2]["message"]
    # unbudgeted names never warn
    for _ in range(50):
        watch.on_compile("totally_novel_fn")
    assert len(man.records) == 1
    tele.close()


# --- config + end-to-end off switch ------------------------------------------


def test_config_flags_validate():
    sanity_check(ExtractionConfig(telemetry="off", heartbeat_s=5.0))
    with pytest.raises(ValueError, match="telemetry"):
        sanity_check(ExtractionConfig(telemetry="sometimes"))
    with pytest.raises(ValueError, match="heartbeat_s"):
        sanity_check(ExtractionConfig(heartbeat_s=-1.0))


def test_telemetry_off_run_keeps_timer_writes_nothing(toy_videos, tmp_path):
    cfg = _cfg(toy_videos[:2], tmp_path, telemetry="off", decode_workers=2)
    ex = ToyExtractor(cfg)
    ex()
    ex.telemetry.close()
    assert not os.path.isdir(os.path.join(cfg.output_path, "_telemetry"))
    # the aggregate timer (--profile_dir's data source) still accumulates
    assert ex.timer.counts["prepare"] == 2 and ex.timer.counts["sink"] == 2
    s = faults.finalize_run(cfg.output_path)
    assert s["done"] == 2 and "telemetry" not in s
    assert "videos/s" not in faults.format_summary(s)


def test_failure_record_links_failing_stage_span(toy_videos, tmp_path):
    # permanent prepare fault on video 2: its manifest record must carry
    # the span id of the failing interval, resolvable in the span file
    cfg = _cfg(
        toy_videos[:2], tmp_path, retries=0, fault_inject=["prepare:corrupt:2"]
    )
    ex = ToyExtractor(cfg)
    ex()
    ex.telemetry.close()
    s = faults.finalize_run(cfg.output_path)
    assert s["done"] == 1 and s["failed"] == 1
    rec = s["videos"][toy_videos[1]]
    assert rec["status"] == "failed" and rec.get("span")
    files = glob.glob(os.path.join(cfg.output_path, "_telemetry", "spans-*.jsonl"))
    rows = [r for f in files for r in tm.read_spans(f)]
    failing = next(r for r in rows if r["span"] == rec["span"])
    assert failing["stage"] == "prepare"
    assert failing["video"] == toy_videos[1]
