"""Device cost ledger (ISSUE 15): per-executable HBM/flops accounting,
live device-memory gauges, the per-device utilization timeline, and the
bench regression sentinel.

Layers under test, shallow to deep:

- CostLedger persistence: atomic save / reload round-trip, torn-file and
  version-mismatch tolerance (same contract costmodel.py pins);
- the AOT capture path on a real CPU jit function (cost_analysis /
  memory_analysis via instrument_state), including the RecompileWatch
  suppression that keeps analysis compiles out of the GC401 budgets;
- HBM projection semantics: CPU entries record honest byte sizes but
  never count toward the resident-HBM projection, so ``vft_hbm_bytes``
  is legitimately absent on CPU backends (absent, never zero-filled);
- exposition mapping (families_from_ledger, the vft_device_mem_bytes
  registry branch) + check_exposition negatives for the new families;
- DeviceMemorySampler: absent gauges on backends without memory_stats
  (CPU), real gauges + headroom from a fake device;
- utilization_report / --device-lanes trace mirroring;
- the ``telemetry ledger`` CLI rc contract (0 rendered, 2 missing);
- ``bench.py --compare``: clean trajectory passes, injected synthetic
  regression and tripped *_within_budget booleans exit nonzero;
- serve wiring: ledger block in stats(), warmup HBM fail-fast against
  --hbm_budget_bytes.
"""

import json
import os

import numpy as np
import pytest

from video_features_tpu.runtime.telemetry import (
    MetricsRegistry,
    RecompileWatch,
    Telemetry,
    compile_watch_suppressed,
    spans_to_chrome_trace,
    suppress_compile_watch,
    utilization_report,
)
from video_features_tpu.telemetry.exposition import (
    check_exposition,
    families_from_ledger,
    families_from_snapshot,
    render_families,
    validate_exposition,
)
from video_features_tpu.telemetry.ledger import (
    LEDGER_FILENAME,
    CostLedger,
    DeviceMemorySampler,
    bucket_of,
    format_bytes,
    instrument_state,
    load_ledger,
)

TPU_MEM = {
    "argument_bytes": 1000,
    "output_bytes": 100,
    "temp_bytes": 50,
    "generated_code_bytes": 10,
}


# --- persistence ------------------------------------------------------------


def test_ledger_roundtrip_and_n_compiles(tmp_path):
    path = str(tmp_path / LEDGER_FILENAME)
    led = CostLedger(path)
    led.record("resnet18", "forward", "4x8", "queue", "cpu",
               {"flops": 512.0, "bytes_accessed": 512.0})
    led.record("resnet18", "forward", "4x8", "queue", "cpu",
               {"flops": 512.0, "bytes_accessed": 512.0})
    assert len(led) == 1
    assert led.entries()[0]["n_compiles"] == 2
    assert os.path.isfile(path)  # every record persists (save_every=1)
    led2 = CostLedger(path)
    assert led2.entries() == led.entries()


def test_ledger_tolerates_torn_and_mismatched_files(tmp_path):
    torn = tmp_path / LEDGER_FILENAME
    torn.write_text('{"version": 1, "entr')  # torn mid-write
    led = CostLedger(str(torn))
    assert len(led) == 0
    led.record("m", "f", "4x8", "queue", "cpu", {"flops": 1.0})
    assert len(CostLedger(str(torn))) == 1  # recovers by rewriting

    wrong = tmp_path / "v999" / LEDGER_FILENAME
    wrong.parent.mkdir()
    wrong.write_text(json.dumps({"version": 999, "entries": [{"model": "x"}]}))
    assert len(CostLedger(str(wrong))) == 0


def test_load_ledger_is_none_when_missing(tmp_path):
    assert load_ledger(str(tmp_path / "nope.json")) is None


def test_shared_returns_one_ledger_per_path(tmp_path):
    path = str(tmp_path / LEDGER_FILENAME)
    assert CostLedger.shared(path) is CostLedger.shared(path)


# --- AOT capture on a real CPU jit fn ---------------------------------------


@pytest.fixture
def captured(tmp_path):
    import jax

    led = CostLedger(str(tmp_path / LEDGER_FILENAME))
    params = {"w": np.ones((8, 8), np.float32)}
    state = {"params": params,
             "forward": jax.jit(lambda p, x: x @ p["w"]),
             "device": jax.devices()[0]}
    wrapped = instrument_state(state, led, model="resnet18", sharding="queue")
    y = wrapped["forward"](params, np.ones((4, 8), np.float32))
    return led, wrapped, params, np.asarray(y)


def test_instrument_state_records_flops_and_memory(captured):
    led, wrapped, params, y = captured
    assert y.shape == (4, 8)  # execution result untouched
    (e,) = led.entries()
    assert e["model"] == "resnet18"
    assert e["family"] == "forward"
    assert e["bucket"] == "4x8"  # largest data leaf, params arg skipped
    assert e["platform"] == "cpu"
    assert e["flops"] > 0
    assert e["bytes_accessed"] > 0
    assert e["memory"]["argument_bytes"] > 0
    assert wrapped["forward"].__wrapped_for_ledger__


def test_capture_is_once_per_signature(captured):
    led, wrapped, params, _ = captured
    wrapped["forward"](params, np.ones((4, 8), np.float32))  # same sig
    assert len(led) == 1
    wrapped["forward"](params, np.ones((2, 8), np.float32))  # new bucket
    assert sorted(e["bucket"] for e in led.entries()) == ["2x8", "4x8"]


def test_bucket_of_skips_params_and_handles_no_leaves():
    params = {"w": np.ones((8, 8), np.float32)}
    assert bucket_of((params, np.ones((2, 3, 4), np.float32))) == "2x3x4"
    assert bucket_of((1, "x")) == "~"


def test_suppress_compile_watch_is_thread_local_and_reentrant():
    assert not compile_watch_suppressed()
    with suppress_compile_watch():
        assert compile_watch_suppressed()
        with suppress_compile_watch():
            assert compile_watch_suppressed()
        assert compile_watch_suppressed()
    assert not compile_watch_suppressed()


def test_recompile_watch_ignores_suppressed_compiles():
    w = RecompileWatch(Telemetry(enabled=False), manifest=None)
    with suppress_compile_watch():
        w.on_compile("fused_fn")
    assert w.counts == {}
    w.on_compile("fused_fn")
    assert w.counts == {"fused_fn": 1}


# --- HBM projection ---------------------------------------------------------


def test_hbm_projection_skips_cpu_and_maxes_weights(tmp_path):
    led = CostLedger(str(tmp_path / LEDGER_FILENAME))
    led.record("resnet18", "forward", "4x8", "queue", "cpu",
               {"flops": 1.0, "memory": dict(TPU_MEM)})
    assert led.hbm_projection() == {}  # CPU bytes are honest but not HBM
    assert led.projected_resident_bytes() == 0

    led.record("i3d", "forward", "2x64", "queue", "tpu",
               {"flops": 1.0, "memory": dict(TPU_MEM)})
    big = {**TPU_MEM, "argument_bytes": 4000, "generated_code_bytes": 7}
    led.record("i3d", "forward", "2x128", "queue", "tpu",
               {"flops": 1.0, "memory": big})
    proj = led.hbm_projection()
    assert list(proj) == ["i3d"]
    # weights are shared across bucket variants: arguments MAX, code SUMs
    assert proj["i3d"]["arguments"] == 4000
    assert proj["i3d"]["generated_code"] == 17
    assert proj["i3d"]["resident"] == 4000 + 100 + 50 + 17
    assert led.projected_resident_bytes(["i3d"]) == proj["i3d"]["resident"]
    assert led.projected_resident_bytes(["resnet18"]) == 0


# --- exposition mapping -----------------------------------------------------


def test_families_from_ledger_renders_and_validates(tmp_path):
    led = CostLedger(str(tmp_path / LEDGER_FILENAME))
    led.record("resnet18", "forward", "4x8", "queue", "cpu",
               {"flops": 512.0, "bytes_accessed": 512.0})
    text = render_families(families_from_ledger(led.snapshot()))
    assert check_exposition(text) == []
    assert ('vft_executable_flops{bucket="4x8",family="forward",'
            'model="resnet18",sharding="queue"} 512') in text
    assert "vft_executable_bytes_accessed" in text
    assert "vft_hbm_bytes" not in text  # absent, not zero, on CPU

    led.record("resnet18", "forward", "4x8", "queue", "tpu",
               {"flops": 512.0, "memory": dict(TPU_MEM)})
    text = render_families(families_from_ledger(led.snapshot()))
    assert check_exposition(text) == []
    assert 'vft_hbm_bytes{kind="resident",model="resnet18"} 1160' in text
    assert 'vft_hbm_bytes{kind="arguments",model="resnet18"} 1000' in text


def test_families_from_ledger_empty_snapshot_has_no_families():
    assert families_from_ledger({"entries": [], "hbm_projection": {}}) == []


def test_device_mem_gauges_map_to_labelled_family():
    reg = MetricsRegistry()
    reg.set_gauge("device_mem_bytes.tpu:0|in_use", 5.0)
    reg.set_gauge("device_mem_bytes.tpu:0|limit", 10.0)
    reg.set_gauge("device_mem_headroom_bytes", 5.0)
    text = render_families(families_from_snapshot(reg.snapshot()))
    assert validate_exposition(text) == []
    assert 'vft_device_mem_bytes{device="tpu:0",kind="in_use"} 5' in text
    assert 'vft_device_mem_bytes{device="tpu:0",kind="limit"} 10' in text
    assert "vft_device_mem_headroom_bytes 5" in text


def test_check_exposition_negatives_for_new_families():
    # counter naming: the checker must reject a miscast ledger family
    bad_counter = ("# HELP vft_hbm_bytes x\n# TYPE vft_hbm_bytes counter\n"
                   'vft_hbm_bytes{model="m",kind="resident"} 1\n')
    assert any("_total" in e for e in check_exposition(bad_counter))
    # sample without TYPE
    orphan = 'vft_device_mem_bytes{device="tpu:0",kind="in_use"} 1\n'
    assert check_exposition(orphan)
    # bad label name
    bad_label = ("# HELP vft_device_mem_bytes x\n"
                 "# TYPE vft_device_mem_bytes gauge\n"
                 'vft_device_mem_bytes{1bad="x"} 1\n')
    assert check_exposition(bad_label)
    # non-float value
    bad_value = ("# HELP vft_hbm_bytes x\n# TYPE vft_hbm_bytes gauge\n"
                 'vft_hbm_bytes{model="m"} lots\n')
    assert check_exposition(bad_value)


# --- device memory sampler --------------------------------------------------


def test_sampler_absent_on_cpu():
    # conftest pins JAX_PLATFORMS=cpu; CpuDevice.memory_stats() is None,
    # so the sampler must leave the registry untouched — never zero-fill
    reg = MetricsRegistry()
    assert DeviceMemorySampler(reg).sample_once() == 0
    snap = reg.snapshot()
    assert not any(k.startswith("device_mem") for k in snap["gauges"])


class _FakeDevice:
    platform = "tpu"
    id = 0

    def __init__(self, stats):
        self._stats = stats

    def memory_stats(self):
        return self._stats


def test_sampler_records_fake_device_stats_and_headroom():
    reg = MetricsRegistry()
    dev = _FakeDevice({"bytes_in_use": 600, "bytes_limit": 1000,
                       "peak_bytes_in_use": 800})
    s = DeviceMemorySampler(reg, devices=[dev])
    assert s.sample_once() == 1
    g = reg.snapshot()["gauges"]
    assert g["device_mem_bytes.tpu:0|in_use"] == 600
    assert g["device_mem_bytes.tpu:0|limit"] == 1000
    assert g["device_mem_bytes.tpu:0|peak"] == 800
    assert g["device_mem_headroom_bytes"] == 400
    s.stop()  # idempotent without start()


def test_format_bytes():
    assert format_bytes(0) == "0 B"
    assert format_bytes(1536) == "1.5 KiB"
    assert format_bytes(953.7 * 2**20).endswith("MiB")


# --- utilization timeline ---------------------------------------------------


def _row(stage, t0, t1, pid=1, worker=None):
    r = {"stage": stage, "t0": t0, "t1": t1, "pid": pid}
    if worker:
        r["worker"] = worker
    return r


def test_utilization_report_per_device_busy_idle():
    rows = [
        _row("decode", 0.0, 10.0),                       # host wall
        _row("dispatch", 1.0, 3.0, worker="tpu:0"),
        _row("fetch", 2.0, 5.0, worker="tpu:0"),         # overlaps -> merged
        _row("h2d", 6.0, 8.0, worker="tpu:1"),
    ]
    rep = utilization_report(rows)
    d0 = rep["devices"]["tpu:0"]
    assert d0["busy_s"] == pytest.approx(4.0)  # [1,5] merged
    assert d0["wall_s"] == pytest.approx(10.0)
    assert d0["busy_frac"] == pytest.approx(0.4)
    assert d0["idle_s"] == pytest.approx(6.0)
    assert rep["devices"]["tpu:1"]["busy_s"] == pytest.approx(2.0)
    assert rep["device_utilization"] == pytest.approx(6.0 / 20.0)


def test_utilization_excludes_pids_without_device_spans():
    rows = [_row("decode", 0.0, 100.0, pid=7)]  # host-only pid
    rep = utilization_report(rows)
    assert rep["devices"] == {}
    assert rep["device_utilization"] == 0.0


def test_chrome_trace_device_lanes_mirror_device_stages():
    rows = [
        _row("decode", 0.0, 1.0),
        _row("dispatch", 1.0, 2.0, worker="tpu:0"),
    ]
    plain = spans_to_chrome_trace(rows)
    lanes = spans_to_chrome_trace(rows, device_lanes=True)
    names = [e["args"]["name"] for e in lanes["traceEvents"]
             if e["ph"] == "M" and e["name"] == "thread_name"]
    assert "device tpu:0" in names
    # one mirrored X event per device span, none for host spans
    assert (len([e for e in lanes["traceEvents"] if e["ph"] == "X"])
            == len([e for e in plain["traceEvents"] if e["ph"] == "X"]) + 1)


# --- `telemetry ledger` CLI -------------------------------------------------


def test_ledger_cli_rc2_on_missing(tmp_path, capsys):
    from video_features_tpu.telemetry.__main__ import main

    assert main(["ledger", str(tmp_path / "none")]) == 2
    assert "no ledger" in capsys.readouterr().err


def test_ledger_cli_renders_table_and_json(tmp_path, capsys):
    from video_features_tpu.telemetry.__main__ import main

    led = CostLedger(str(tmp_path / LEDGER_FILENAME))
    led.record("resnet18", "forward", "4x8", "queue", "cpu",
               {"flops": 512.0, "bytes_accessed": 512.0,
                "memory": {"argument_bytes": 384, "output_bytes": 128,
                           "temp_bytes": 0, "generated_code_bytes": 0}})
    assert main(["ledger", str(tmp_path)]) == 0  # dir resolution
    out = capsys.readouterr().out
    assert "resnet18" in out and "4x8" in out and "512" in out
    assert "CPU-backend runs record flops only" in out
    assert main(["ledger", str(tmp_path / LEDGER_FILENAME), "--json"]) == 0
    snap = json.loads(capsys.readouterr().out)
    assert snap["entries"][0]["bucket"] == "4x8"


# --- bench --compare sentinel -----------------------------------------------


def _bench_doc(value=3.6, **extra):
    return {"n": 1, "cmd": "bench", "rc": 0,
            "parsed": {"metric": "videos/s", "value": value, "unit": "videos/s",
                       "vs_baseline": None, "extra": extra}}


def test_compare_clean_pass_and_injected_regression():
    import bench

    bases = [_bench_doc(host_fps=100.0), _bench_doc(host_fps=104.0),
             _bench_doc(host_fps=96.0)]
    clean = bench.compare_bench(_bench_doc(host_fps=101.0), bases)
    assert clean["regressed"] == []
    assert clean["keys"]["host_fps"]["status"] == "ok"

    reg = bench.compare_bench(_bench_doc(host_fps=40.0), bases)
    assert "host_fps" in reg["regressed"]
    # lower-better keys regress upward
    lat_bases = [_bench_doc(warm_latency_s=0.1) for _ in range(3)]
    worse = bench.compare_bench(_bench_doc(warm_latency_s=0.5), lat_bases)
    assert "warm_latency_s" in worse["regressed"]
    better = bench.compare_bench(_bench_doc(warm_latency_s=0.01), lat_bases)
    assert "warm_latency_s" in better["improved"]
    assert better["regressed"] == []


def test_compare_budget_bool_is_a_hard_gate():
    import bench

    out = bench.compare_bench(_bench_doc(ledger_within_budget=False),
                              [_bench_doc()])
    assert "ledger_within_budget" in out["regressed"]
    ok = bench.compare_bench(_bench_doc(ledger_within_budget=True),
                             [_bench_doc()])
    assert ok["regressed"] == []


def test_compare_tolerates_sparse_bases_and_missing_keys():
    import bench

    # the committed trajectory shape: rc!=0 rounds carry no numbers
    sparse = {"n": 2, "cmd": "bench", "rc": 3, "tail": "died", "parsed": {}}
    out = bench.compare_bench(_bench_doc(host_fps=100.0),
                              [_bench_doc(other_fps=5.0), sparse])
    assert out["keys"]["other_fps"]["status"] == "missing"  # informational
    assert out["keys"]["host_fps"]["status"] == "new"
    assert out["regressed"] == []


def test_compare_host_pipeline_subtree_is_informational():
    """Host-capability sizing numbers (host_pipeline.*) never hard-gate:
    rounds run on heterogeneous containers, so a slower host must not
    read as a code regression — the same leaf OUTSIDE the subtree still
    gates (the e2e vps keys carry the code-regression signal)."""
    import bench

    bases = [_bench_doc(host_pipeline={"host_decode_cv2_fps": 2000.0},
                        host_decode_cv2_fps=2000.0)]
    out = bench.compare_bench(
        _bench_doc(host_pipeline={"host_decode_cv2_fps": 1000.0},
                   host_decode_cv2_fps=1000.0),
        bases,
    )
    assert out["keys"]["host_pipeline.host_decode_cv2_fps"]["status"] == "info"
    assert "host_pipeline.host_decode_cv2_fps" not in out["regressed"]
    assert "host_decode_cv2_fps" in out["regressed"]


def test_compare_syscall_capability_absolutes_are_informational():
    """The raw sampler-poll and preflight-header microsecond absolutes
    track the container's syscall/IO speed, not the code (r08 precedent:
    the host nearly doubled them with no code change on those paths) —
    informational. Their normalized pct twins still gate."""
    import bench

    bases = [_bench_doc(ledger_sampler_sample_us=1.0,
                        preflight_header_only_us_per_video=300.0,
                        ledger_overhead_pct_vs_headline=0.008)]
    out = bench.compare_bench(
        _bench_doc(ledger_sampler_sample_us=2.2,
                   preflight_header_only_us_per_video=500.0,
                   ledger_overhead_pct_vs_headline=0.02),
        bases,
    )
    assert out["keys"]["ledger_sampler_sample_us"]["status"] == "info"
    assert out["keys"]["preflight_header_only_us_per_video"]["status"] == "info"
    assert out["regressed"] == ["ledger_overhead_pct_vs_headline"]


def test_compare_main_rc_contract(tmp_path):
    import bench

    base = tmp_path / "BENCH_base.json"
    base.write_text(json.dumps(_bench_doc(host_fps=100.0)))
    good = tmp_path / "cur_good.json"
    good.write_text(json.dumps(_bench_doc(host_fps=99.0)))
    bad = tmp_path / "cur_bad.json"
    bad.write_text(json.dumps(_bench_doc(host_fps=10.0)))
    out = tmp_path / "summary.json"
    assert bench._compare_main([str(base), "--current", str(good)]) == 0
    assert bench._compare_main(
        [str(base), "--current", str(bad), "-o", str(out)]
    ) == 1
    assert json.loads(out.read_text())["regressed"] == ["host_fps"]
    empty = tmp_path / "empty.json"
    empty.write_text(json.dumps({"parsed": {}}))
    assert bench._compare_main([str(empty), "--current", str(good)]) == 2


def test_compare_passes_on_the_committed_trajectory():
    import bench

    bases = sorted(
        p for p in os.listdir(".")
        if p.startswith("BENCH_r") and p.endswith(".json")
    )
    if len(bases) < 2:
        pytest.skip("no committed BENCH trajectory")
    assert bench._compare_main([",".join(bases[:-1]), "--current", bases[-1]]) == 0


# --- serve wiring -----------------------------------------------------------


@pytest.mark.serve
def test_daemon_ledger_block_and_hbm_budget(tmp_path):
    from test_serve import ServeToy

    from video_features_tpu.config import parse_serve_args
    from video_features_tpu.serve.daemon import ServeDaemon

    scfg = parse_serve_args([
        "--feature_types", "resnet18",
        "--output_path", str(tmp_path / "out"),
        "--tmp_path", str(tmp_path / "tmp"),
        "--allow_random_init", "--cpu", "--heartbeat_s", "0",
        "--hbm_budget_bytes", "1000",
    ])
    d = ServeDaemon(scfg, build=ServeToy)
    try:
        assert d.stats()["ledger"]["entries"] == []
        assert validate_exposition(d.metrics_text()) == []
        d._check_hbm_budget()  # empty ledger: nothing projected, passes
        assert d._warmup_hbm("resnet18") == "n/a"
        d.ledger.record("resnet18", "forward", "1x3x64x96", "queue", "tpu",
                        {"flops": 1.0, "memory": dict(TPU_MEM)})
        assert d._warmup_hbm("resnet18") == format_bytes(1160)
        with pytest.raises(RuntimeError, match="hbm_budget_bytes"):
            d._check_hbm_budget()
        text = d.metrics_text()
        assert validate_exposition(text) == []
        assert 'vft_hbm_bytes{kind="resident",model="resnet18"} 1160' in text
    finally:
        d.shutdown()


@pytest.mark.serve
def test_hbm_budget_knob_validation():
    from video_features_tpu.config import parse_serve_args

    with pytest.raises(ValueError, match="hbm_budget_bytes"):
        parse_serve_args([
            "--feature_types", "resnet18", "--allow_random_init", "--cpu",
            "--hbm_budget_bytes", "-5",
        ])
