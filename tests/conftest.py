"""Test harness: simulate an 8-device mesh on CPU.

Must run before jax is imported anywhere: force the CPU platform and 8
virtual host devices so multi-chip sharding tests run without a TPU pod
(SURVEY.md §4c). The real-chip benchmark path is exercised separately by
bench.py.
"""

import os

# transformers (torch oracles) must not import tensorflow into this process
os.environ.setdefault("USE_TF", "0")
# 8 virtual CPU devices; must land before the cpu backend is created
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# jax may ALREADY be imported here: on TPU hosts a sitecustomize imports it
# at interpreter startup, capturing JAX_PLATFORMS from the environment. Env
# edits are therefore no-ops — pin the platform through the config API so
# the unit suite never initializes the TPU backend (whose plugin dials a
# network relay) regardless of import order.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(scope="session")
def sample_video(tmp_path_factory):
    """A small deterministic synthetic mp4 (moving gradient + box)."""
    import cv2

    path = str(tmp_path_factory.mktemp("media") / "synth.mp4")
    w, h, fps, n = 320, 240, 25.0, 60
    writer = cv2.VideoWriter(path, cv2.VideoWriter_fourcc(*"mp4v"), fps, (w, h))
    assert writer.isOpened(), "cv2.VideoWriter could not open mp4 writer"
    rng = np.random.RandomState(0)
    for t in range(n):
        yy, xx = np.mgrid[0:h, 0:w]
        frame = np.stack(
            [
                ((xx + 2 * t) % 256),
                ((yy + t) % 256),
                np.full((h, w), (t * 4) % 256),
            ],
            axis=-1,
        ).astype(np.uint8)
        x0 = (10 + 3 * t) % (w - 40)
        y0 = (20 + 2 * t) % (h - 40)
        frame[y0 : y0 + 30, x0 : x0 + 30] = rng.randint(0, 255, 3)
        writer.write(frame)
    writer.release()
    return path


@pytest.fixture(scope="session")
def sample_wav(tmp_path_factory):
    """1.5 s stereo 44.1 kHz wav with two tones."""
    from scipy.io import wavfile

    path = str(tmp_path_factory.mktemp("media") / "synth.wav")
    sr = 44100
    t = np.arange(int(1.5 * sr)) / sr
    left = 0.5 * np.sin(2 * np.pi * 440 * t)
    right = 0.3 * np.sin(2 * np.pi * 1000 * t)
    data = (np.stack([left, right], axis=1) * 32767).astype(np.int16)
    wavfile.write(path, sr, data)
    return path
