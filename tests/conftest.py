"""Test harness: simulate an 8-device mesh on CPU.

Must run before jax is imported anywhere: force the CPU platform and 8
virtual host devices so multi-chip sharding tests run without a TPU pod
(SURVEY.md §4c). The real-chip benchmark path is exercised separately by
bench.py.
"""

import os

# transformers (torch oracles) must not import tensorflow into this process
os.environ.setdefault("USE_TF", "0")
# 8 virtual CPU devices; must land before the cpu backend is created
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# jax may ALREADY be imported here: on TPU hosts a sitecustomize imports it
# at interpreter startup, capturing JAX_PLATFORMS from the environment. Env
# edits are therefore no-ops — pin the platform through the config API so
# the unit suite never initializes the TPU backend (whose plugin dials a
# network relay) regardless of import order.
import jax

jax.config.update("jax_platforms", "cpu")
# ... and pin the ENV VAR too: entry points re-assert the platform from it
# (parallel/devices.py::pin_platform), and on axon hosts JAX_PLATFORMS=axon
# would flip the whole suite from the 8 virtual CPU devices to the one real
# chip the moment a test drives main().
os.environ["JAX_PLATFORMS"] = "cpu"

import numpy as np
import pytest


@pytest.fixture(scope="session")
def sample_video(tmp_path_factory):
    """A small deterministic synthetic mp4 (moving gradient + box)."""
    from video_features_tpu.utils.synth import synth_video

    path = str(tmp_path_factory.mktemp("media") / "synth.mp4")
    return synth_video(path)


@pytest.fixture(scope="session")
def sample_wav(tmp_path_factory):
    """1.5 s stereo 44.1 kHz wav with two tones."""
    from scipy.io import wavfile

    path = str(tmp_path_factory.mktemp("media") / "synth.wav")
    sr = 44100
    t = np.arange(int(1.5 * sr)) / sr
    left = 0.5 * np.sin(2 * np.pi * 440 * t)
    right = 0.3 * np.sin(2 * np.pi * 1000 * t)
    data = (np.stack([left, right], axis=1) * 32767).astype(np.int16)
    wavfile.write(path, sr, data)
    return path
