"""Shape-contracted device resize geometry (ISSUE PR 2 tentpole).

The banded-tap machinery generalized from "fixed 224/256 crop output" to
arbitrary output contracts: min-edge-256 onto padded output buckets (the
I3D flow grid), InputPadder /8 grids with the image placed at the host
pad offsets (standalone RAFT), and exact resized shapes (PWC). Parity is
pinned against the host oracle — ``pil_resize`` + ``np.pad(mode="edge")``
— at source resolutions spanning multiple output buckets; the identity
(no-resize) contracts must be BIT-exact because the inter-pass uint8
quantization is the identity on integer-valued frames.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from video_features_tpu.models.raft.model import input_grid
from video_features_tpu.ops.preprocess import device_resize_frames, pil_resize
from video_features_tpu.ops.resize import resized_hw, shape_contract_banded
from video_features_tpu.ops.window import flow_output_bucket, pad_hw, spatial_bucket

pytestmark = pytest.mark.quick

RNG = np.random.RandomState(11)

# one uint8 step of PIL's 8-bit fixed-point coefficient table, plus the
# second pass compounding it — raw [0, 255] scale (the flow models and
# I3D's chains consume unnormalized frames)
PIXEL_TOL = 2.5

# >= 4 source resolutions spanning >= 2 output buckets for the min-edge
# contract: (240,426)/(232,420) -> (256,512); (240,320) -> (256,384);
# portrait (320,240) -> (384,256)
SOURCES = [(240, 426), (232, 420), (240, 320), (320, 240)]


def _min_edge_oracle(img):
    """Host chain for the I3D flow grid: min-edge-256 PIL resize, then
    edge-replicate onto the output bucket at the centered placement."""
    resized = pil_resize(img, 256)
    oh, ow = resized.shape[:2]
    out_h, out_w = flow_output_bucket(oh, ow)
    top, left = (out_h - oh) // 2, (out_w - ow) // 2
    padded = np.pad(
        resized,
        [(top, out_h - oh - top), (left, out_w - ow - left), (0, 0)],
        mode="edge",
    )
    return padded, (oh, ow), (out_h, out_w), (top, left)


def _run_contract(img, resize_to, out_h, out_w, top, left):
    h, w = img.shape[:2]
    bh, bw = spatial_bucket(h, w)
    wt_y, idx_y, wt_x, idx_x = shape_contract_banded(
        h, w, resize_to, out_h, out_w, top, left, "bilinear",
        pad_h=bh, pad_w=bw, pad_mode="edge",
    )
    out = device_resize_frames(
        jnp.asarray(pad_hw(img[None], bh, bw)), (wt_y, idx_y), (wt_x, idx_x)
    )
    return np.asarray(out)[0]


@pytest.mark.parametrize("hw", SOURCES)
def test_min_edge_bucket_contract_parity(hw):
    """I3D flow-grid contract: min-edge-256 resize placed centered on the
    flow output bucket, within one PIL coefficient step of the host
    resize + edge-pad chain — including the replicated pad rows."""
    img = RNG.randint(0, 256, (hw[0], hw[1], 3)).astype(np.uint8)
    ref, (oh, ow), (out_h, out_w), (top, left) = _min_edge_oracle(img)
    got = _run_contract(img, 256, out_h, out_w, top, left)
    assert got.shape == (out_h, out_w, 3)
    assert np.abs(got - ref.astype(np.float32)).max() <= PIXEL_TOL


def test_min_edge_sources_span_two_buckets():
    grids = {
        flow_output_bucket(*resized_hw(h, w, 256)) for h, w in SOURCES
    }
    assert len(grids) >= 2, grids


@pytest.mark.parametrize("hw", [(96, 100), (120, 96), (128, 200)])
def test_identity_padder_contract_bit_exact(hw):
    """Standalone-RAFT contract without --side_size: no resize, just the
    InputPadder placement — taps must reproduce host
    ``np.pad(mode='edge')`` BIT-exactly (quant8 is the identity on
    integer frames)."""
    h, w = hw
    img = RNG.randint(0, 256, (h, w, 3)).astype(np.uint8)
    tgt_h, tgt_w = input_grid(h, w)
    top, left = (tgt_h - h) // 2, (tgt_w - w) // 2
    ref = np.pad(
        img,
        [(top, tgt_h - h - top), (left, tgt_w - w - left), (0, 0)],
        mode="edge",
    ).astype(np.float32)
    got = _run_contract(img, 0, tgt_h, tgt_w, top, left)
    np.testing.assert_array_equal(got, ref)


def test_resize_padder_contract_parity():
    """Standalone-flow contract WITH --side_size: min-edge resize onto
    the exact /8 padder grid of the resized shape."""
    img = RNG.randint(0, 256, (240, 426, 3)).astype(np.uint8)
    resized = pil_resize(img, 256)
    oh, ow = resized.shape[:2]
    tgt_h, tgt_w = input_grid(oh, ow)
    top, left = (tgt_h - oh) // 2, (tgt_w - ow) // 2
    ref = np.pad(
        resized,
        [(top, tgt_h - oh - top), (left, tgt_w - ow - left), (0, 0)],
        mode="edge",
    ).astype(np.float32)
    got = _run_contract(img, 256, tgt_h, tgt_w, top, left)
    assert got.shape == ref.shape
    assert np.abs(got - ref).max() <= PIXEL_TOL


def test_larger_edge_contract_parity():
    """--resize_to_larger_edge threads through the contract (the flow
    extractors expose both modes)."""
    img = RNG.randint(0, 256, (240, 426, 3)).astype(np.uint8)
    resized = pil_resize(img, 256, resize_to_smaller_edge=False)
    oh, ow = resized.shape[:2]
    assert (oh, ow) == resized_hw(240, 426, 256, smaller_edge=False)
    got = _run_contract_larger(img, 256, oh, ow)
    assert np.abs(got - resized.astype(np.float32)).max() <= PIXEL_TOL


def _run_contract_larger(img, resize_to, out_h, out_w):
    h, w = img.shape[:2]
    bh, bw = spatial_bucket(h, w)
    wt_y, idx_y, wt_x, idx_x = shape_contract_banded(
        h, w, resize_to, out_h, out_w, 0, 0, "bilinear",
        pad_h=bh, pad_w=bw, pad_mode="edge", smaller_edge=False,
    )
    out = device_resize_frames(
        jnp.asarray(pad_hw(img[None], bh, bw)), (wt_y, idx_y), (wt_x, idx_x)
    )
    return np.asarray(out)[0]


@pytest.mark.parametrize("smaller_edge", [True, False])
@pytest.mark.parametrize(
    "hw", [(240, 426), (426, 240), (256, 256), (100, 640), (256, 300)]
)
def test_resized_hw_matches_pil(hw, smaller_edge):
    """resized_hw replays PIL's integer output geometry in both edge
    modes, including the matched-edge early return."""
    img = np.zeros((hw[0], hw[1], 3), np.uint8)
    ref = pil_resize(img, 256, resize_to_smaller_edge=smaller_edge)
    assert resized_hw(hw[0], hw[1], 256, smaller_edge) == ref.shape[:2]


def test_flow_output_bucket_geometry():
    # multiple=div collapses to the exact padder grid
    assert flow_output_bucket(256, 454, multiple=8) == input_grid(256, 454)
    # default 64-multiple rounds the padder grid up
    assert flow_output_bucket(256, 454) == (256, 512)
    assert flow_output_bucket(256, 341) == (256, 384)
    # the 128-px padder floor survives the bucketing
    assert flow_output_bucket(96, 100) == (128, 128)


def test_per_window_taps_match_solo():
    """The fused flow agg path stacks per-window (G, P, K) taps; results
    must be bit-identical to running each window solo."""
    imgs = [
        RNG.randint(0, 256, (96, 100, 3)).astype(np.uint8),
        RNG.randint(0, 256, (96, 100, 3)).astype(np.uint8),
    ]
    h, w = 96, 100
    bh, bw = spatial_bucket(h, w)
    tgt_h, tgt_w = input_grid(h, w)
    top, left = (tgt_h - h) // 2, (tgt_w - w) // 2
    wt_y, idx_y, wt_x, idx_x = shape_contract_banded(
        h, w, 0, tgt_h, tgt_w, top, left, "bilinear",
        pad_h=bh, pad_w=bw, pad_mode="edge",
    )
    frames = np.stack([pad_hw(im[None], bh, bw)[0] for im in imgs])
    solo = [
        np.asarray(
            device_resize_frames(
                jnp.asarray(f[None]), (wt_y, idx_y), (wt_x, idx_x)
            )
        )[0]
        for f in frames
    ]
    g = lambda a: np.stack([a, a])
    group = np.asarray(
        device_resize_frames(
            jnp.asarray(frames[:, None]), (g(wt_y), g(idx_y)), (g(wt_x), g(idx_x))
        )
    )
    np.testing.assert_array_equal(group[0, 0], solo[0])
    np.testing.assert_array_equal(group[1, 0], solo[1])


def test_contract_rejects_escaping_placement():
    with pytest.raises(ValueError):
        shape_contract_banded(
            240, 426, 256, 200, 200, 0, 0, "bilinear", pad_mode="edge"
        )
