"""ResNet parity vs a torch oracle + end-to-end extraction.

torchvision is not installed in this environment, so the oracle is a
minimal torch reimplementation of torchvision's ResNet v1 with
state-dict-compatible parameter names (conv1, bn1, layer{s}.{b}.*,
downsample.{0,1}, fc) — randomized weights AND randomized BN running
stats so the converter's stat plumbing is actually exercised.
"""

import numpy as np
import pytest
import torch
from torch import nn

import jax.numpy as jnp

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.models.resnet.convert import convert_state_dict
from video_features_tpu.models.resnet.model import ARCHS, build


class TorchBasicBlock(nn.Module):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2d(inplanes, planes, 3, stride, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(planes)
        self.conv2 = nn.Conv2d(planes, planes, 3, 1, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(planes)
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = torch.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return torch.relu(out + identity)


class TorchBottleneck(nn.Module):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2d(inplanes, planes, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(planes)
        self.conv2 = nn.Conv2d(planes, planes, 3, stride, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(planes)
        self.conv3 = nn.Conv2d(planes, planes * 4, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(planes * 4)
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = torch.relu(self.bn1(self.conv1(x)))
        out = torch.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return torch.relu(out + identity)


class TorchResNet(nn.Module):
    def __init__(self, block, layers, num_classes=1000):
        super().__init__()
        self.inplanes = 64
        self.conv1 = nn.Conv2d(3, 64, 7, 2, 3, bias=False)
        self.bn1 = nn.BatchNorm2d(64)
        self.maxpool = nn.MaxPool2d(3, 2, 1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], 2)
        self.layer3 = self._make_layer(block, 256, layers[2], 2)
        self.layer4 = self._make_layer(block, 512, layers[3], 2)
        self.avgpool = nn.AdaptiveAvgPool2d(1)
        self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, n, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2d(self.inplanes, planes * block.expansion, 1, stride, bias=False),
                nn.BatchNorm2d(planes * block.expansion),
            )
        blocks = [block(self.inplanes, planes, stride, downsample)]
        self.inplanes = planes * block.expansion
        for _ in range(1, n):
            blocks.append(block(self.inplanes, planes))
        return nn.Sequential(*blocks)

    def forward(self, x):
        x = self.maxpool(torch.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        feats = torch.flatten(self.avgpool(x), 1)
        return feats, self.fc(feats)


def _torch_oracle(arch: str, seed: int = 0) -> TorchResNet:
    block = TorchBasicBlock if ARCHS[arch][0].__name__ == "BasicBlock" else TorchBottleneck
    torch.manual_seed(seed)
    model = TorchResNet(block, list(ARCHS[arch][1]))
    # randomize BN running stats so converted stats actually matter
    with torch.no_grad():
        for m in model.modules():
            if isinstance(m, nn.BatchNorm2d):
                m.running_mean.normal_(0, 0.5)
                m.running_var.uniform_(0.5, 2.0)
    model.eval()
    return model


@pytest.mark.parametrize("arch", ["resnet18", "resnet50"])
def test_resnet_matches_torch_oracle(arch):
    oracle = _torch_oracle(arch)
    sd = {k: v.numpy() for k, v in oracle.state_dict().items()}
    params = convert_state_dict(sd, arch)

    x = np.random.RandomState(0).randn(2, 3, 64, 64).astype(np.float32)
    with torch.no_grad():
        ref_feats, ref_logits = oracle(torch.from_numpy(x))
    feats, logits = build(arch).apply({"params": params}, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(feats), ref_feats.numpy(), atol=1e-4)
    np.testing.assert_allclose(np.asarray(logits), ref_logits.numpy(), atol=1e-4)


@pytest.mark.quick
def test_converter_rejects_unconsumed():
    oracle = _torch_oracle("resnet18")
    sd = {k: v.numpy() for k, v in oracle.state_dict().items()}
    sd["stray.weight"] = np.zeros(3, np.float32)
    with pytest.raises(ValueError, match="unconsumed"):
        convert_state_dict(sd, "resnet18")


@pytest.mark.quick
def test_msgpack_weights_roundtrip(tmp_path):
    """Already-converted flax params saved as .msgpack load without going
    through the torch-key converter."""
    from flax import serialization

    from video_features_tpu.models.common.weights import load_params
    from video_features_tpu.models.resnet.model import init_params

    params = init_params("resnet18")
    path = str(tmp_path / "rn18.msgpack")
    with open(path, "wb") as f:
        f.write(serialization.msgpack_serialize(params))

    def _fail(sd):
        raise AssertionError("converter must not run for .msgpack")

    loaded = load_params(path, _fail)
    x = np.random.RandomState(0).randn(1, 3, 64, 64).astype(np.float32)
    a, _ = build("resnet18").apply({"params": params}, jnp.asarray(x))
    b, _ = build("resnet18").apply({"params": loaded}, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_extract_resnet_end_to_end(sample_video, tmp_path):
    from video_features_tpu.models.resnet.extract_resnet import ExtractResNet

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="resnet18",
        video_paths=[sample_video],
        extraction_fps=5.0,  # 60-frame 25fps synth clip -> 12 frames
        batch_size=5,
        on_extraction="save_numpy",
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
        cpu=True,
    )
    ex = ExtractResNet(cfg)
    ex([0])
    import pathlib

    saved = {p.name: p for p in pathlib.Path(tmp_path / "out").rglob("*.npy")}
    # meta keys (fps, timestamps_ms) are never saved (ref utils/utils.py:70-72)
    assert set(saved) == {"synth_resnet18.npy"}
    feats = np.load(saved["synth_resnet18.npy"])
    assert feats.shape[1] == 512 and feats.shape[0] >= 10
    assert np.isfinite(feats).all()


def test_extract_resnet_show_pred(sample_video, tmp_path, capsys):
    from video_features_tpu.models.resnet.extract_resnet import ExtractResNet

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="resnet18",
        video_paths=[sample_video],
        extraction_fps=1.0,
        batch_size=4,
        show_pred=True,
        tmp_path=str(tmp_path / "tmp"),
        output_path=str(tmp_path / "out"),
        cpu=True,
    )
    res = ExtractResNet(cfg, external_call=True)([0])
    out = capsys.readouterr().out
    assert len(out.strip().splitlines()) >= 5  # top-5 lines per batch
    assert res[0]["resnet18"].shape[1] == 512
    # timestamps follow the 1 fps grid
    np.testing.assert_allclose(np.diff(res[0]["timestamps_ms"]), 1000.0)


@pytest.mark.quick
def test_fps_retarget_reencode_decodes_the_reencoded_file(sample_video, tmp_path, monkeypatch):
    """--fps_retarget reencode routes decode through io/ffmpeg.py's
    re-encode (ref utils/utils.py:222-244) instead of in-process nearest
    selection. ffmpeg is absent in this sandbox, so the re-encode is
    faked with a sentinel clip — features switching to the sentinel's
    proves the decode source really changed (VERDICT r4 next #6)."""
    import video_features_tpu.io.ffmpeg as ff
    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.models.resnet.extract_resnet import ExtractResNet
    from video_features_tpu.utils.synth import synth_video

    sentinel = synth_video(str(tmp_path / "sentinel.mp4"), n_frames=6,
                           width=96, height=64, seed=123)
    calls = []

    def fake_reencode(video_path, tmp_dir, fps, timeout_s=None):
        calls.append((video_path, tmp_dir, fps))
        return sentinel

    monkeypatch.setattr(ff, "reencode_video_with_diff_fps", fake_reencode)

    def run(retarget):
        cfg = ExtractionConfig(
            allow_random_init=True,
            feature_type="resnet18",
            video_paths=[sample_video],
            extraction_fps=5.0,
            fps_retarget=retarget,
            tmp_path=str(tmp_path / "t" / retarget),
            cpu=True,
        )
        return ExtractResNet(cfg, external_call=True)([0])[0]

    nearest = run("nearest")
    assert calls == []  # default path never shells out
    reenc = run("reencode")
    (call,) = calls
    assert call[0] == sample_video and call[2] == 5.0
    # the sentinel has 6 frames at native fps and is decoded WITHOUT
    # further selection (selection_fps=None): frame count follows it
    assert reenc["resnet18"].shape[0] == 6
    assert reenc["resnet18"].shape != nearest["resnet18"].shape


@pytest.mark.quick
def test_fps_retarget_reencode_requires_ffmpeg_error(sample_video, tmp_path):
    """Without ffmpeg the re-encode path fails with the actionable
    io/ffmpeg.py message, not a deep decode error."""
    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.io.ffmpeg import which_ffmpeg
    from video_features_tpu.models.resnet.extract_resnet import ExtractResNet

    if which_ffmpeg():
        pytest.skip("ffmpeg present — the missing-binary path can't fire")
    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="resnet18",
        video_paths=[sample_video],
        extraction_fps=5.0,
        fps_retarget="reencode",
        tmp_path=str(tmp_path / "t"),
        cpu=True,
    )
    ex = ExtractResNet(cfg, external_call=True)
    with pytest.raises(RuntimeError, match="ffmpeg"):
        ex.prepare(sample_video)
