"""Aux subsystems: --resume skip-if-done, stage timing, error isolation."""

import numpy as np
import pytest

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.io.sink import expected_output_files
from video_features_tpu.utils.profiling import StageTimer, device_trace


@pytest.mark.quick
def test_expected_output_files_naming():
    files = expected_output_files(
        ["CLIP-ViT-B/32"], "/v/clip.mp4", "/o", "save_numpy", False
    )
    assert files == ["/o/clip_CLIP-ViT-B-32.npy"]
    assert expected_output_files(["x"], "/v/a.mp4", "/o", "save_numpy", True) == [
        "/o/a.npy"
    ]
    assert expected_output_files(["x"], "/v/a.mp4", "/o", "print") == []


def test_resume_skips_existing(sample_video, tmp_path, monkeypatch):
    from video_features_tpu.models.resnet.extract_resnet import ExtractResNet

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="resnet18",
        video_paths=[sample_video],
        extraction_fps=2.0,
        batch_size=4,
        on_extraction="save_numpy",
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
        resume=True,
        cpu=True,
    )
    ex = ExtractResNet(cfg)
    ex([0])
    import pathlib

    (out,) = pathlib.Path(tmp_path / "out").rglob("*.npy")
    mtime = out.stat().st_mtime_ns

    # second run must skip: extract() raising proves it was never called
    def boom(*a, **k):
        raise AssertionError("resume failed to skip a finished video")

    ex2 = ExtractResNet(cfg)
    monkeypatch.setattr(ex2, "extract", boom)
    ex2([0])
    assert out.stat().st_mtime_ns == mtime


def test_error_isolation_continues(sample_video, tmp_path, capsys):
    """A corrupt video in the list is reported and the rest still runs
    (ref extract_clip.py:78-84)."""
    bad = tmp_path / "bad.mp4"
    bad.write_bytes(b"not a video at all")
    from video_features_tpu.models.resnet.extract_resnet import ExtractResNet

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="resnet18",
        video_paths=[str(bad), sample_video],
        extraction_fps=2.0,
        batch_size=4,
        on_extraction="save_numpy",
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
        cpu=True,
    )
    ExtractResNet(cfg)([0, 1])
    out = capsys.readouterr().out
    assert "An error occurred" in out and "Continuing" in out
    import pathlib

    saved = [p.name for p in pathlib.Path(tmp_path / "out").rglob("*.npy")]
    assert saved == ["synth_resnet18.npy"]


@pytest.mark.quick
def test_stage_timer_accumulates():
    t = StageTimer()
    with t.stage("decode"):
        pass
    with t.stage("decode"):
        pass
    with t.stage("device"):
        pass
    assert t.counts["decode"] == 2 and t.counts["device"] == 1
    assert "decode" in t.summary() and "device" in t.summary()


def test_device_trace_writes_profile(tmp_path):
    import jax
    import jax.numpy as jnp

    with device_trace(str(tmp_path / "prof")):
        jax.jit(lambda x: x * 2)(jnp.ones(8)).block_until_ready()
    files = list((tmp_path / "prof").rglob("*"))
    assert files, "profiler trace directory is empty"


@pytest.mark.quick
def test_device_trace_noop_without_dir():
    with device_trace(None):
        pass
