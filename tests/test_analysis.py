"""graftcheck static-analysis suite (video_features_tpu/analysis).

Seeded-violation fixtures for every checker: each writes a small module
with a KNOWN bug, runs the suite over it, and asserts the finding fires
with the right rule id and location — then that a waiver comment or the
documented safe form silences it. The last tests pin the acceptance
criteria: the shipped package itself is clean, and the CLI speaks the
documented exit codes.

Everything here is pure AST work (no jax tracing, no extraction), so the
file adds seconds, not minutes, to tier-1.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from video_features_tpu.analysis import all_rules, check_counts, run_checks

pytestmark = [pytest.mark.quick, pytest.mark.analysis]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _check(tmp_path, source, name="mod.py", prefix=""):
    p = tmp_path / name
    p.write_text(prefix + textwrap.dedent(source))
    return run_checks([str(p)])


def _ids(findings):
    return [f.rule.id for f in findings]


# --- GC10x host-sync --------------------------------------------------------

HOT = "# graftcheck: hot-module\n"


def test_hostsync_flags_item_and_casts(tmp_path):
    fs = _check(
        tmp_path,
        """
        import jax.numpy as jnp

        def hot(x):
            y = jnp.square(x)
            a = y.item()            # GC101
            b = float(y)            # GC102
            c = int(jnp.sum(y))     # GC102
            return a + b + c
        """,
        prefix=HOT,
    )
    assert _ids(fs) == ["GC101", "GC102", "GC102"]
    assert fs[0].line == 7 and "item()" in fs[0].message


def test_hostsync_flags_fetch_and_block(tmp_path):
    fs = _check(
        tmp_path,
        """
        import jax
        import numpy as np
        import jax.numpy as jnp

        def hot(x):
            y = jnp.square(x)
            h = np.asarray(y)           # GC103
            g = jax.device_get(y)       # GC103
            y.block_until_ready()       # GC104
            return h, g
        """,
        prefix=HOT,
    )
    assert _ids(fs) == ["GC103", "GC103", "GC104"]


def test_hostsync_allows_sink_boundary_and_untainted(tmp_path):
    fs = _check(
        tmp_path,
        """
        import numpy as np
        import jax.numpy as jnp

        def fetch_group(y):
            # allowlisted boundary: fetch_* IS where results come home
            return np.asarray(y)

        def sink_features(y):
            return float(y)

        def hot(vals):
            # plain python / numpy values never taint
            n = float(sum(vals))
            return np.asarray(vals), int(n)
        """,
        prefix=HOT,
    )
    assert fs == []


def test_hostsync_waiver_silences(tmp_path):
    fs = _check(
        tmp_path,
        """
        import jax.numpy as jnp

        def hot(x):
            y = jnp.square(x)
            # graftcheck: host-sync — deliberate sync at the epoch boundary
            return float(y)
        """,
        prefix=HOT,
    )
    assert fs == []


def test_hostsync_only_runs_on_hot_modules(tmp_path):
    fs = _check(
        tmp_path,
        """
        import jax.numpy as jnp

        def cold(x):
            return float(jnp.square(x))
        """,
    )
    assert fs == []


# --- GC20x jit hygiene ------------------------------------------------------


def test_jit_mutable_closure_flagged(tmp_path):
    fs = _check(
        tmp_path,
        """
        import jax

        def build():
            table = {}

            @jax.jit
            def fn(x):
                return x * table["scale"]   # GC201: captured mutable

            table["scale"] = 2.0
            return fn
        """,
    )
    assert "GC201" in _ids(fs)
    assert "table" in fs[0].message


def test_jit_rebind_in_dead_branch_not_flagged(tmp_path):
    """The mesh/single-device factory pattern: the def's branch ends in
    ``return``, so a later rebind of the same name can never be observed
    by the closure — no finding."""
    fs = _check(
        tmp_path,
        """
        import jax

        def build(mesh):
            if mesh:
                net = make_mesh_net()

                @jax.jit
                def fn(x):
                    return net(x)

                return fn
            net = make_solo_net()

            @jax.jit
            def fn(x):
                return net(x)

            return fn
        """,
    )
    assert fs == []


def test_jit_traced_branch_flagged_and_static_attrs_exempt(tmp_path):
    fs = _check(
        tmp_path,
        """
        import jax

        @jax.jit
        def fn(x, y):
            if x.ndim == 3:        # fine: trace-time static
                y = y + 1
            if y > 0:              # GC202: value branch on a tracer
                return x
            return x - y
        """,
    )
    assert _ids(fs) == ["GC202"]
    assert "'y'" in fs[0].message


def test_jit_static_argnames_must_name_params(tmp_path):
    fs = _check(
        tmp_path,
        """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("mode",))
        def ok(x, mode):
            return x

        @partial(jax.jit, static_argnames=("moed",))
        def typo(x, mode):
            return x

        @partial(jax.jit, static_argnums=(3,))
        def out_of_range(x, y):
            return x + y
        """,
    )
    assert _ids(fs) == ["GC203", "GC203"]
    assert "moed" in fs[0].message


def test_jit_static_param_branch_allowed(tmp_path):
    fs = _check(
        tmp_path,
        """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("training",))
        def fn(x, training):
            if training:           # static: selects an executable
                return x * 2
            return x
        """,
    )
    assert fs == []


# --- GC301 thread safety ----------------------------------------------------

ROOT = "# graftcheck: thread-root\n"


def test_unlocked_global_write_flagged(tmp_path):
    fs = _check(
        tmp_path,
        """
        _CACHE = {}

        def remember(k, v):
            _CACHE[k] = v          # GC301: no lock on a thread path
        """,
        prefix=ROOT,
    )
    assert _ids(fs) == ["GC301"]
    assert "_CACHE" in fs[0].message


def test_locked_and_local_writes_pass(tmp_path):
    fs = _check(
        tmp_path,
        """
        import threading

        _CACHE = {}
        _LOCK = threading.Lock()
        _TLS = threading.local()

        def remember(k, v):
            with _LOCK:
                _CACHE[k] = v

        def stash(v):
            _TLS.value = v

        def rebind(v):
            global _STATE
            with _LOCK:
                _STATE = v
        """,
        prefix=ROOT,
    )
    assert fs == []


def test_unlocked_waiver_silences(tmp_path):
    fs = _check(
        tmp_path,
        """
        _MODE = "auto"

        def set_mode(v):
            global _MODE
            _MODE = v  # graftcheck: unlocked — config-set-once before threads
        """,
        prefix=ROOT,
    )
    assert fs == []


def test_thread_safety_covers_modules_imported_by_roots(tmp_path):
    (tmp_path / "root_mod.py").write_text(
        ROOT + "import helper\n\ndef run():\n    helper.poke('k', 1)\n"
    )
    (tmp_path / "helper.py").write_text(
        "_STATE = {}\n\ndef poke(k, v):\n    _STATE[k] = v\n"
    )
    (tmp_path / "bystander.py").write_text(
        "_STATE = {}\n\ndef poke(k, v):\n    _STATE[k] = v\n"
    )
    fs = run_checks([str(tmp_path)])
    assert _ids(fs) == ["GC301"]
    assert fs[0].path.endswith("helper.py")


# --- GC401 budget arithmetic (the live counter runs in
# test_device_preprocess.py against a real extraction) ----------------------


def test_budget_flags_inflated_count():
    out = check_counts("clip_device_mixed", {"encode_raw": 3})
    assert len(out) == 1 and "GC401" in out[0] and "3" in out[0]


def test_budget_flags_dead_scenario():
    out = check_counts("clip_device_mixed", {})
    assert len(out) == 1 and "0 times" in out[0]


def test_budget_unknown_scenario():
    out = check_counts("no_such_scenario", {"encode_raw": 1})
    assert len(out) == 1 and "unknown" in out[0]


def test_budget_within():
    assert check_counts("clip_device_mixed", {"encode_raw": 2}) == []


# --- acceptance: the shipped package is clean, the CLI behaves --------------


def test_explicit_path_gets_hot_patterns(tmp_path):
    """An explicit file (or dir) arg pointing inside a video_features_tpu
    package tree matches the path-based hot patterns WITHOUT needing the
    `# graftcheck: hot-module` marker — `graftcheck some/extract/file.py`
    must lint like the full-package run does."""
    pkg = tmp_path / "video_features_tpu" / "extract"
    pkg.mkdir(parents=True)
    bad = pkg / "bad.py"
    bad.write_text("def hot(feats):\n    return feats.mean().item()\n")
    for arg in (str(bad), str(pkg)):
        found = run_checks([arg])
        assert [f.rule.id for f in found] == ["GC101"], arg


def test_repo_is_clean():
    """`python -m video_features_tpu.analysis` exits 0 on the repo: every
    genuine violation is fixed, every intentional one carries an
    explanatory waiver (audit: `git grep 'graftcheck:'`)."""
    assert run_checks() == []


def test_rule_catalogue_complete():
    ids = [r.id for r in all_rules()]
    assert ids == ["GC101", "GC102", "GC103", "GC104",
                   "GC201", "GC202", "GC203", "GC301", "GC401"]


def _cli(*args, cwd=REPO):
    env = dict(os.environ, PYTHONPATH=REPO)
    return subprocess.run(
        [sys.executable, "-m", "video_features_tpu.analysis", *args],
        capture_output=True, text=True, env=env, cwd=cwd,
    )


def test_cli_violation_exit_and_format(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        HOT + "import jax.numpy as jnp\n\ndef hot(x):\n"
        "    return float(jnp.square(x))\n"
    )
    r = _cli(str(bad))
    assert r.returncode == 1
    assert f"{bad}:5:" in r.stdout and "GC102" in r.stdout
    assert "fix:" in r.stdout


def test_cli_json_and_rule_filter(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        HOT + "import jax.numpy as jnp\n\ndef hot(x):\n"
        "    y = jnp.square(x)\n    return float(y), y.item()\n"
    )
    r = _cli("--json", "--rule", "GC101", str(bad))
    assert r.returncode == 1
    doc = json.loads(r.stdout)
    assert [d["rule"] for d in doc] == ["GC101"]
    assert doc[0]["path"] == str(bad) and doc[0]["line"] == 6


def test_cli_list_rules():
    r = _cli("--list-rules")
    assert r.returncode == 0
    for rid in ("GC101", "GC203", "GC301", "GC401"):
        assert rid in r.stdout
