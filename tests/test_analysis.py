"""graftcheck static-analysis suite (video_features_tpu/analysis).

Seeded-violation fixtures for every checker: each writes a small module
with a KNOWN bug, runs the suite over it, and asserts the finding fires
with the right rule id and location — then that a waiver comment or the
documented safe form silences it. The last tests pin the acceptance
criteria: the shipped package itself is clean, and the CLI speaks the
documented exit codes.

Everything here is pure AST work (no jax tracing, no extraction), so the
file adds seconds, not minutes, to tier-1.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from video_features_tpu.analysis import all_rules, check_counts, run_checks

pytestmark = [pytest.mark.quick, pytest.mark.analysis]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _check(tmp_path, source, name="mod.py", prefix=""):
    p = tmp_path / name
    p.write_text(prefix + textwrap.dedent(source))
    return run_checks([str(p)])


def _ids(findings):
    return [f.rule.id for f in findings]


# --- GC10x host-sync --------------------------------------------------------

HOT = "# graftcheck: hot-module\n"


def test_hostsync_flags_item_and_casts(tmp_path):
    fs = _check(
        tmp_path,
        """
        import jax.numpy as jnp

        def hot(x):
            y = jnp.square(x)
            a = y.item()            # GC101
            b = float(y)            # GC102
            c = int(jnp.sum(y))     # GC102
            return a + b + c
        """,
        prefix=HOT,
    )
    assert _ids(fs) == ["GC101", "GC102", "GC102"]
    assert fs[0].line == 7 and "item()" in fs[0].message


def test_hostsync_flags_fetch_and_block(tmp_path):
    fs = _check(
        tmp_path,
        """
        import jax
        import numpy as np
        import jax.numpy as jnp

        def hot(x):
            y = jnp.square(x)
            h = np.asarray(y)           # GC103
            g = jax.device_get(y)       # GC103
            y.block_until_ready()       # GC104
            return h, g
        """,
        prefix=HOT,
    )
    assert _ids(fs) == ["GC103", "GC103", "GC104"]


def test_hostsync_allows_sink_boundary_and_untainted(tmp_path):
    fs = _check(
        tmp_path,
        """
        import numpy as np
        import jax.numpy as jnp

        def fetch_group(y):
            # allowlisted boundary: fetch_* IS where results come home
            return np.asarray(y)

        def sink_features(y):
            return float(y)

        def hot(vals):
            # plain python / numpy values never taint
            n = float(sum(vals))
            return np.asarray(vals), int(n)
        """,
        prefix=HOT,
    )
    assert fs == []


def test_hostsync_drain_allowlist_is_scope_pinned(tmp_path):
    """The async-ingest drain boundary (extract/base.py::drain_completed)
    is allowlisted BY NAME — this pins that scope: the same blocking
    fetch under any other name refires GC103, so a rename out of the
    ``drain_*`` family cannot silently widen the allowlist."""
    drain_body = """
        import numpy as np
        import jax.numpy as jnp

        def {name}(handle):
            # completion-queue drain: the ONE sync point per group
            y = jnp.square(handle)
            return np.asarray(y)
        """
    assert _check(tmp_path, drain_body.format(name="drain_completed"),
                  prefix=HOT) == []
    assert _check(tmp_path, drain_body.format(name="_drain_inflight"),
                  prefix=HOT) == []
    refire = _check(tmp_path, drain_body.format(name="pop_completed"),
                    prefix=HOT)
    assert _ids(refire) == ["GC103"]
    assert "pop_completed" in refire[0].message


def test_hostsync_waiver_silences(tmp_path):
    fs = _check(
        tmp_path,
        """
        import jax.numpy as jnp

        def hot(x):
            y = jnp.square(x)
            # graftcheck: host-sync — deliberate sync at the epoch boundary
            return float(y)
        """,
        prefix=HOT,
    )
    assert fs == []


def test_hostsync_only_runs_on_hot_modules(tmp_path):
    fs = _check(
        tmp_path,
        """
        import jax.numpy as jnp

        def cold(x):
            return float(jnp.square(x))
        """,
    )
    assert fs == []


# --- GC10x interprocedural taint (v2) ---------------------------------------


def test_hostsync_flags_device_value_returned_through_helper(tmp_path):
    """THE v2 acceptance fixture: a helper returns a device value that the
    caller syncs — v1's per-function scan could not see it; the call-graph
    taint pass must, and must carry the propagation chain."""
    fs = _check(
        tmp_path,
        """
        import jax.numpy as jnp

        def _score(x):
            return jnp.square(x).mean()

        def hot(x):
            s = _score(x)
            return float(s)         # GC102 via _score's return
        """,
        prefix=HOT,
    )
    assert _ids(fs) == ["GC102"]
    assert fs[0].line == 10
    assert fs[0].trace, "interprocedural finding must carry a trace"
    assert "_score" in " ".join(fs[0].trace)


def test_hostsync_taint_flows_through_param_passthrough(tmp_path):
    """A helper that merely forwards its argument propagates the caller's
    device taint back out (param-index summaries)."""
    fs = _check(
        tmp_path,
        """
        import jax.numpy as jnp

        def _ident(v):
            return v

        def hot(x):
            d = _ident(jnp.ones(3))
            return d.item()         # GC101 through the pass-through
        """,
        prefix=HOT,
    )
    assert _ids(fs) == ["GC101"]
    assert fs[0].trace


def test_hostsync_helper_returning_host_value_is_clean(tmp_path):
    """A helper whose return is a host value (np reduction of python
    input, .shape metadata) must NOT taint the caller — the precision
    that makes the interprocedural pass adoptable."""
    fs = _check(
        tmp_path,
        """
        import numpy as np
        import jax.numpy as jnp

        def _geometry(x):
            return x.shape[0] - 1

        def hot(x, vals):
            y = jnp.square(x)
            n = _geometry(y)        # metadata: host-side
            return float(n) + float(np.sum(vals))
        """,
        prefix=HOT,
    )
    assert fs == []


def test_hostsync_retired_broadcast_waiver_would_refire(monkeypatch, tmp_path):
    """PR-4 waived the multihost broadcast sync in extract/base.py; v2
    retired the waiver by teaching taint that broadcast_one_to_all
    returns a HOST value. Pin both directions: the fixture is clean with
    the fact in place, and re-fires if the fact regresses."""
    from video_features_tpu.analysis import taint

    src = """
        import numpy as np
        from jax.experimental import multihost_utils

        def hot(done):
            return bool(multihost_utils.broadcast_one_to_all(np.int32(done)))
        """
    assert _check(tmp_path, src, name="clean.py", prefix=HOT) == []
    monkeypatch.setattr(
        taint,
        "_HOST_RESULTS",
        taint._HOST_RESULTS
        - {"jax.experimental.multihost_utils.broadcast_one_to_all"},
    )
    fs = _check(tmp_path, src, name="regressed.py", prefix=HOT)
    assert _ids(fs) == ["GC102"]


# --- GC20x jit hygiene ------------------------------------------------------


def test_jit_mutable_closure_flagged(tmp_path):
    fs = _check(
        tmp_path,
        """
        import jax

        def build():
            table = {}

            @jax.jit
            def fn(x):
                return x * table["scale"]   # GC201: captured mutable

            table["scale"] = 2.0
            return fn
        """,
    )
    assert "GC201" in _ids(fs)
    assert "table" in fs[0].message


def test_jit_rebind_in_dead_branch_not_flagged(tmp_path):
    """The mesh/single-device factory pattern: the def's branch ends in
    ``return``, so a later rebind of the same name can never be observed
    by the closure — no finding."""
    fs = _check(
        tmp_path,
        """
        import jax

        def build(mesh):
            if mesh:
                net = make_mesh_net()

                @jax.jit
                def fn(x):
                    return net(x)

                return fn
            net = make_solo_net()

            @jax.jit
            def fn(x):
                return net(x)

            return fn
        """,
    )
    assert fs == []


def test_jit_traced_branch_flagged_and_static_attrs_exempt(tmp_path):
    fs = _check(
        tmp_path,
        """
        import jax

        @jax.jit
        def fn(x, y):
            if x.ndim == 3:        # fine: trace-time static
                y = y + 1
            if y > 0:              # GC202: value branch on a tracer
                return x
            return x - y
        """,
    )
    assert _ids(fs) == ["GC202"]
    assert "'y'" in fs[0].message


def test_jit_static_argnames_must_name_params(tmp_path):
    fs = _check(
        tmp_path,
        """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("mode",))
        def ok(x, mode):
            return x

        @partial(jax.jit, static_argnames=("moed",))
        def typo(x, mode):
            return x

        @partial(jax.jit, static_argnums=(3,))
        def out_of_range(x, y):
            return x + y
        """,
    )
    assert _ids(fs) == ["GC203", "GC203"]
    assert "moed" in fs[0].message


def test_jit_static_param_branch_allowed(tmp_path):
    fs = _check(
        tmp_path,
        """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("training",))
        def fn(x, training):
            if training:           # static: selects an executable
                return x * 2
            return x
        """,
    )
    assert fs == []


# --- GC301 thread safety ----------------------------------------------------

ROOT = "# graftcheck: thread-root\n"


def test_unlocked_global_write_flagged(tmp_path):
    fs = _check(
        tmp_path,
        """
        _CACHE = {}

        def remember(k, v):
            _CACHE[k] = v          # GC301: no lock on a thread path
        """,
        prefix=ROOT,
    )
    assert _ids(fs) == ["GC301"]
    assert "_CACHE" in fs[0].message


def test_locked_and_local_writes_pass(tmp_path):
    fs = _check(
        tmp_path,
        """
        import threading

        _CACHE = {}
        _LOCK = threading.Lock()
        _TLS = threading.local()

        def remember(k, v):
            with _LOCK:
                _CACHE[k] = v

        def stash(v):
            _TLS.value = v

        def rebind(v):
            global _STATE
            with _LOCK:
                _STATE = v
        """,
        prefix=ROOT,
    )
    assert fs == []


def test_unlocked_waiver_silences(tmp_path):
    fs = _check(
        tmp_path,
        """
        _MODE = "auto"

        def set_mode(v):
            global _MODE
            _MODE = v  # graftcheck: unlocked — config-set-once before threads
        """,
        prefix=ROOT,
    )
    assert fs == []


def test_thread_safety_covers_modules_imported_by_roots(tmp_path):
    (tmp_path / "root_mod.py").write_text(
        ROOT + "import helper\n\ndef run():\n    helper.poke('k', 1)\n"
    )
    (tmp_path / "helper.py").write_text(
        "_STATE = {}\n\ndef poke(k, v):\n    _STATE[k] = v\n"
    )
    (tmp_path / "bystander.py").write_text(
        "_STATE = {}\n\ndef poke(k, v):\n    _STATE[k] = v\n"
    )
    fs = run_checks([str(tmp_path)])
    assert _ids(fs) == ["GC301"]
    assert fs[0].path.endswith("helper.py")


# --- GC301 v2: call-graph lock resolution + thread reachability -------------


def test_thread_reachability_exempts_init_only_setters(tmp_path):
    """The retired video.py/faults.py waiver shape: a config-set-once
    setter NOT reachable from the spawn target is exempt by analysis;
    the write on the worker path still fires — with the entry chain."""
    fs = _check(
        tmp_path,
        """
        import threading

        _STATE = {}

        def set_mode(v):
            _STATE["mode"] = v      # init-only: not thread-reachable

        def worker():
            _STATE["k"] = 1         # GC301: on the thread path

        def start():
            threading.Thread(target=worker, daemon=True).start()
        """,
        prefix=ROOT,
    )
    assert _ids(fs) == ["GC301"]
    assert fs[0].line == 11 and "worker" in fs[0].message
    assert any("thread entry" in s for s in fs[0].trace)


def test_retired_waiver_shape_refires_when_reached_from_thread(tmp_path):
    """Regression pin for the retired waivers: the SAME setter flagged
    the moment a thread path can actually reach it."""
    fs = _check(
        tmp_path,
        """
        import threading

        _STATE = {}

        def set_mode(v):
            _STATE["mode"] = v      # GC301 again: worker calls it now

        def worker():
            set_mode("native")

        def start():
            threading.Thread(target=worker, daemon=True).start()
        """,
        prefix=ROOT,
    )
    assert _ids(fs) == ["GC301"]
    assert "set_mode" in fs[0].message


def test_decorator_lock_exempts(tmp_path):
    fs = _check(
        tmp_path,
        """
        import threading

        _LOCK = threading.Lock()
        _STATE = {}

        def synchronized(fn):
            def inner(*a, **k):
                with _LOCK:
                    return fn(*a, **k)
            return inner

        @synchronized
        def poke(k, v):
            _STATE[k] = v
        """,
        prefix=ROOT,
    )
    assert fs == []


def test_contextmanager_lock_helper_exempts(tmp_path):
    fs = _check(
        tmp_path,
        """
        import threading
        from contextlib import contextmanager

        _GUARD = threading.Lock()
        _STATE = {}

        @contextmanager
        def transaction():
            with _GUARD:
                yield

        def poke(k, v):
            with transaction():
                _STATE[k] = v
        """,
        prefix=ROOT,
    )
    assert fs == []


def test_guarded_callers_exempt_until_an_unlocked_site_appears(tmp_path):
    guarded = """
        import threading

        _LOCK = threading.Lock()
        _STATE = {}

        def _poke(k, v):
            _STATE[k] = v           # every caller holds _LOCK

        def public(k, v):
            with _LOCK:
                _poke(k, v)

        def worker():
            public("a", 1)

        def start():
            threading.Thread(target=worker, daemon=True).start()
        """
    assert _check(tmp_path, guarded, name="guarded.py", prefix=ROOT) == []
    leaky = guarded + """
        def sneak(k, v):
            _poke(k, v)             # unlocked site: the proof collapses

        def worker2():
            sneak("b", 2)

        def start2():
            threading.Thread(target=worker2, daemon=True).start()
        """
    fs = _check(tmp_path, leaky, name="leaky.py", prefix=ROOT)
    assert _ids(fs) == ["GC301"]
    assert "_poke" in fs[0].message


# --- GC31x concurrency soundness --------------------------------------------

HOTROOT = HOT + ROOT


def test_gc311_conflicting_lock_order_flagged(tmp_path):
    fs = _check(
        tmp_path,
        """
        import threading

        _A = threading.Lock()
        _B = threading.Lock()

        def forward():
            with _A:
                with _B:
                    pass

        def backward():
            with _B:
                with _A:
                    pass
        """,
        prefix=ROOT,
    )
    assert _ids(fs) == ["GC311"]
    assert "_A" in fs[0].message and "_B" in fs[0].message
    assert any("acquired" in s for s in fs[0].trace)


def test_gc311_consistent_order_and_disjoint_locks_clean(tmp_path):
    fs = _check(
        tmp_path,
        """
        import threading

        _A = threading.Lock()
        _B = threading.Lock()
        _C = threading.Lock()

        def one():
            with _A:
                with _B:
                    pass

        def two():
            with _A:
                with _B:
                    pass

        def solo():
            with _C:
                pass
        """,
        prefix=ROOT,
    )
    assert fs == []


def test_gc311_cycle_through_resolvable_callee(tmp_path):
    """The dangerous shape: the B-under-A edge only exists through a
    call chain, the reverse edge is lexical — the closure must stitch
    them into one cycle with the call hop in the trace."""
    fs = _check(
        tmp_path,
        """
        import threading

        _A = threading.Lock()
        _B = threading.Lock()

        def _publish():
            with _B:
                pass

        def ingest():
            with _A:
                _publish()

        def drain():
            with _B:
                with _A:
                    pass
        """,
        prefix=ROOT,
    )
    assert _ids(fs) == ["GC311"]
    assert any("_publish" in s or "reaches" in s for s in fs[0].trace)


def test_gc312_blocking_under_lock_flagged_timed_forms_pass(tmp_path):
    fs = _check(
        tmp_path,
        """
        import queue
        import threading
        import time

        _LOCK = threading.Lock()
        _Q = queue.Queue()

        def drain():
            with _LOCK:
                item = _Q.get()            # GC312: untimed
                time.sleep(0.5)            # GC312
            return item

        def timed():
            with _LOCK:
                return _Q.get(timeout=1.0)  # statically timed: fine

        def unlocked():
            return _Q.get()                 # no lock held: fine
        """,
        prefix=HOTROOT,
    )
    assert _ids(fs) == ["GC312", "GC312"]
    assert "untimed .get()" in fs[0].message
    assert "time.sleep" in fs[1].message
    assert any("acquired here" in s for s in fs[0].trace)


def test_gc312_condition_wait_consumer_loop_is_clean(tmp_path):
    fs = _check(
        tmp_path,
        """
        import threading

        _COND = threading.Condition()
        _ITEMS = []

        def consume():
            with _COND:
                while not _ITEMS:
                    _COND.wait()    # wait releases the lock: canonical
                return _ITEMS.pop()
        """,
        prefix=HOTROOT,
    )
    assert _ids(fs) == []


def test_gc312_sink_boundary_fetch_under_lock_stays_clean(tmp_path):
    """Satellite pin: calls INTO the fetch_*/ *sink* boundary are not
    descended (those functions exist to block) — but the same body under
    a non-boundary name fires through the callee summary."""
    clean = """
        import threading
        import time

        _LOCK = threading.Lock()

        def fetch_group(handle):
            time.sleep(0.01)       # the sanctioned blocking boundary
            return handle

        def publish(handle):
            with _LOCK:
                return fetch_group(handle)
        """
    assert _check(tmp_path, clean, name="ok.py", prefix=HOTROOT) == []
    leaky = clean.replace("fetch_group", "_pull_group")
    fs = _check(tmp_path, leaky, name="bad.py", prefix=HOTROOT)
    assert _ids(fs) == ["GC312"]
    assert "_pull_group" in " ".join(fs[0].trace)


def test_gc313_unjoined_thread_and_unreaped_popen_flagged(tmp_path):
    fs = _check(
        tmp_path,
        """
        import subprocess
        import threading

        def spawn():
            t = threading.Thread(target=print)
            t.start()              # GC313: non-daemon, no join anywhere

        def probe(cmd):
            p = subprocess.Popen(cmd)   # GC313: never reaped
            return None
        """,
        prefix=ROOT,
    )
    assert _ids(fs) == ["GC313", "GC313"]
    assert "Thread" in fs[0].message
    assert "Popen" in fs[1].message


def test_gc313_joined_reaped_and_context_forms_clean(tmp_path):
    fs = _check(
        tmp_path,
        """
        import subprocess
        import threading

        def spawn_and_join():
            t = threading.Thread(target=print)
            t.start()
            t.join()

        def run(cmd, path):
            with subprocess.Popen(cmd) as p:
                p.wait()
            with open(path) as f:
                return f.read()

        def reap(cmd):
            p = subprocess.Popen(cmd)
            try:
                p.communicate()
            finally:
                p.kill()

        def handoff(path):
            f = open(path)
            return f               # caller owns the handle

        def leaky_background():
            threading.Thread(target=print, daemon=True).start()
        """,
        prefix=ROOT,
    )
    assert fs == []


def test_gc313_unclosed_open_handle_flagged(tmp_path):
    fs = _check(
        tmp_path,
        """
        def peek(path):
            f = open(path)
            line = f.readline()
            return len(line)
        """,
        prefix=ROOT,
    )
    assert _ids(fs) == ["GC313"]
    assert "open() file handle" in fs[0].message


def test_telemetry_flush_sink_fix_would_refire(tmp_path):
    """Satellite wire: the shipped telemetry flush pushes its file I/O
    into the ``_flush_sink`` boundary. Renaming that boundary out of the
    allowlist must refire GC312 on the flush path — proving the fix (and
    the rule) are both live."""
    real = os.path.join(
        REPO, "video_features_tpu", "runtime", "telemetry.py"
    )
    with open(real, encoding="utf-8") as fh:
        src = fh.read()
    assert "_flush_sink" in src, "the sink boundary must exist"
    assert not run_checks([real], rules=["GC312"])
    broken = tmp_path / "video_features_tpu" / "runtime" / "telemetry.py"
    broken.parent.mkdir(parents=True)
    broken.write_text(src.replace("_flush_sink", "_flush_rows"))
    fs = run_checks([str(broken)], rules=["GC312"])
    assert fs and all(f.rule.id == "GC312" for f in fs)
    assert any("file I/O" in f.message for f in fs)


# --- GC401 budget arithmetic (the live counter runs in
# test_device_preprocess.py against a real extraction) ----------------------


def test_budget_flags_inflated_count():
    out = check_counts("clip_device_mixed", {"encode_raw": 3})
    assert len(out) == 1 and "GC401" in out[0] and "3" in out[0]


def test_budget_flags_dead_scenario():
    out = check_counts("clip_device_mixed", {})
    assert len(out) == 1 and "0 times" in out[0]


def test_budget_unknown_scenario():
    out = check_counts("no_such_scenario", {"encode_raw": 1})
    assert len(out) == 1 and "unknown" in out[0]


def test_budget_within():
    assert check_counts("clip_device_mixed", {"encode_raw": 2}) == []


# --- GC50x sharding contracts -----------------------------------------------

MESH_SCOPE = (
    "import jax\n"
    "from video_features_tpu.parallel.sharding import is_mesh\n"
    "from video_features_tpu.ops.preprocess import device_preprocess_frames\n\n\n"
    "class Fixture:\n"
    "    mesh_capable = True\n"
)


def test_gc501_flags_unsharded_mesh_possible_jit(tmp_path):
    fs = _check(
        tmp_path,
        """
        def build(self, device):
            @jax.jit
            def plain(p, x):            # GC501: mesh-possible, no spec
                return p @ x
            return plain
        """,
        prefix=MESH_SCOPE,
    )
    assert _ids(fs) == ["GC501"]
    assert "plain" in fs[0].message


def test_gc501_accepts_contracted_and_guarded_forms(tmp_path):
    fs = _check(
        tmp_path,
        """
        from video_features_tpu.parallel.sharding import multihost_out_kwargs

        def build(self, device):
            fwd = jax.jit(inner, **multihost_out_kwargs(device))  # splat

            @jax.jit
            def constrained(p, x):
                x = jax.lax.with_sharding_constraint(x, spec(device))
                return p @ x

            if is_mesh(device):
                out = make_sharded(device)
                return out
            @jax.jit
            def solo(p, x):             # after the terminal mesh branch
                return p @ x
            return solo

        def build2(self, device):
            if not is_mesh(device):
                @jax.jit
                def queue_only(p, x):   # provably single-device
                    return p @ x
                return queue_only
        """,
        prefix=MESH_SCOPE,
    )
    assert fs == []


def test_gc502_fused_entry_needs_both_shardings(tmp_path):
    fs = _check(
        tmp_path,
        """
        def build(self, device, batch_sh, rep, out_sh):
            def encode_raw(p, x_u8, wy, wx):
                return device_preprocess_frames(x_u8, wy, wx)

            if is_mesh(device):
                return jax.jit(encode_raw, out_shardings=out_sh)  # GC502
            return jax.jit(encode_raw)
        """,
        prefix=MESH_SCOPE,
    )
    assert _ids(fs) == ["GC502"]
    assert "in_shardings" in fs[0].message


def test_gc502_inshardings_tuple_must_cover_every_input(tmp_path):
    fs = _check(
        tmp_path,
        """
        def build(self, device, batch_sh, rep, out_sh):
            def encode_raw(p, x_u8, wy, wx):
                return device_preprocess_frames(x_u8, wy, wx)

            if is_mesh(device):
                return jax.jit(
                    encode_raw,
                    in_shardings=(None, batch_sh, (rep, rep)),  # 3 of 4
                    out_shardings=out_sh,
                )
            return jax.jit(encode_raw)
        """,
        prefix=MESH_SCOPE,
    )
    assert _ids(fs) == ["GC502"]
    assert "3 of 4" in fs[0].message


def test_gc503_flags_raw_device_put_under_mesh_polarity(tmp_path):
    fs = _check(
        tmp_path,
        """
        def place(self, device, batch):
            if is_mesh(device):
                return jax.device_put(batch, device)  # GC503
            return jax.device_put(batch, device)      # queue: fine
        """,
        prefix=MESH_SCOPE,
    )
    assert _ids(fs) == ["GC503"]


def test_gc50x_ignores_files_outside_mesh_scope(tmp_path):
    fs = _check(
        tmp_path,
        """
        import jax

        @jax.jit
        def plain(p, x):
            return p @ x
        """,
    )
    assert fs == []


def test_dropping_inshardings_from_shipped_fused_entry_fires_gc502(tmp_path):
    """The acceptance wire: strip the in_shardings spec from the REAL
    CLIP fused entry and GC502 must fail the sweep — the contract that
    lets sanity_check admit --sharding mesh --preprocess device."""
    real = os.path.join(
        REPO, "video_features_tpu", "models", "clip", "extract_clip.py"
    )
    with open(real, encoding="utf-8") as fh:
        src = fh.read()
    spec = "in_shardings=(None, batch_sh, (rep, rep), (rep, rep)),"
    assert spec in src, "the shipped fused entry must pin in_shardings"
    assert not run_checks([real], rules=["GC502"])
    stripped = tmp_path / "extract_clip.py"
    stripped.write_text(src.replace(spec, ""))
    fs = run_checks([str(stripped)], rules=["GC502"])
    assert _ids(fs) == ["GC502"]
    assert "encode_raw" in fs[0].message


# --- GC504/GC505: payload roles + admission coverage -------------------------

PAYLOAD_SCOPE = MESH_SCOPE + (
    "from video_features_tpu.parallel.sharding import "
    "fused_payload_shardings\n"
)


def test_gc504_swapped_payload_roles_flagged(tmp_path):
    fs = _check(
        tmp_path,
        """
        def build(self, device):
            batch_sh, rep = fused_payload_shardings(device)

            def encode_raw(p, x_u8, wy, wx):
                return device_preprocess_frames(x_u8, wy, wx)

            if is_mesh(device):
                return jax.jit(
                    encode_raw,
                    in_shardings=(None, rep, batch_sh, rep),  # roles swapped
                    out_shardings=rep,
                )
            return jax.jit(encode_raw)
        """,
        prefix=PAYLOAD_SCOPE,
    )
    assert _ids(fs) == ["GC504", "GC504"]
    assert "replicates its frame batch" in fs[0].message
    assert "'wy'" in fs[1].message and "must replicate" in fs[1].message


def test_gc504_declared_and_body_constrained_forms_pass(tmp_path):
    fs = _check(
        tmp_path,
        """
        from jax.sharding import NamedSharding, PartitionSpec as P

        def build(self, device):
            batch_sh, rep = fused_payload_shardings(device)
            seq = NamedSharding(device, P("data"))

            def encode_raw(p, x_u8, wy, wx):
                return device_preprocess_frames(x_u8, wy, wx)

            def stack_fn(p, stack, wy, wx):
                stack = jax.lax.with_sharding_constraint(stack, seq)
                return device_preprocess_frames(stack, wy, wx)

            if is_mesh(device):
                a = jax.jit(
                    encode_raw,
                    in_shardings=(None, batch_sh, (rep, rep), (rep, rep)),
                    out_shardings=rep,
                )
                b = jax.jit(
                    stack_fn,   # frames constrained in the body instead
                    in_shardings=(None, rep, (rep, rep), (rep, rep)),
                    out_shardings=rep,
                )
                return a, b
            return jax.jit(encode_raw)
        """,
        prefix=PAYLOAD_SCOPE,
    )
    assert fs == []


def test_gc504_swapping_shipped_flow_payload_roles_would_refire(tmp_path):
    """Acceptance wire for the new mesh families: replicate the frame
    batch in the REAL fused flow entry and GC504 fails the sweep."""
    real = os.path.join(
        REPO, "video_features_tpu", "models", "common", "flow_extract.py"
    )
    with open(real, encoding="utf-8") as fh:
        src = fh.read()
    spec = "in_shardings=(None, batch_sh, (rep, rep), (rep, rep)),"
    assert spec in src, "the shipped fused flow entry must pin in_shardings"
    assert not run_checks([real], rules=["GC504"])
    broken = tmp_path / "flow_extract.py"
    broken.write_text(
        src.replace(spec, "in_shardings=(None, rep, (rep, rep), (rep, rep)),")
    )
    fs = run_checks([str(broken)], rules=["GC504"])
    assert _ids(fs) == ["GC504"]
    assert "frame batch" in fs[0].message


def _gc505_tree(tmp_path, other_has_fused: bool):
    pkg = tmp_path / "video_features_tpu"
    (pkg / "extract").mkdir(parents=True)
    (pkg / "models").mkdir()
    (pkg / "config.py").write_text(textwrap.dedent(
        """
        CLIP_FEATURE_TYPES = ["clip"]
        MESH_DEVICE_PREPROCESS_FEATURE_TYPES = CLIP_FEATURE_TYPES + ["other"]
        """
    ))
    (pkg / "extract" / "registry.py").write_text(textwrap.dedent(
        """
        from video_features_tpu.config import CLIP_FEATURE_TYPES


        def build_extractor(ft):
            if ft in CLIP_FEATURE_TYPES:
                from video_features_tpu.models.extract_clip import ExtractCLIP
                return ExtractCLIP()
            if ft == "other":
                from video_features_tpu.models.extract_other import (
                    ExtractOther,
                )
                return ExtractOther()
            raise ValueError(ft)
        """
    ))
    fused = textwrap.dedent(
        """
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from video_features_tpu.ops.preprocess import device_resize_frames
        from video_features_tpu.parallel.sharding import is_mesh


        class {cls}:
            mesh_capable = True


        def build(device):
            batch_sh = NamedSharding(device, P("data"))
            rep = NamedSharding(device, P())

            def forward(p, x, wy, wx):
                return device_resize_frames(x, wy, wx)

            if is_mesh(device):
                return jax.jit(
                    forward,
                    in_shardings=(None, batch_sh, rep, rep),
                    out_shardings=rep,
                )
            return jax.jit(forward)
        """
    )
    bare = "class {cls}:\n    mesh_capable = True\n"
    (pkg / "models" / "extract_clip.py").write_text(
        fused.format(cls="ExtractCLIP")
    )
    (pkg / "models" / "extract_other.py").write_text(
        (fused if other_has_fused else bare).format(cls="ExtractOther")
    )
    return pkg


def test_gc505_admitted_type_without_fused_entry_flagged(tmp_path):
    pkg = _gc505_tree(tmp_path, other_has_fused=False)
    fs = [f for f in run_checks([str(pkg)]) if f.rule.id == "GC505"]
    assert len(fs) == 1
    assert "'other'" in fs[0].message and fs[0].path.endswith("config.py")
    assert "extract_other" in fs[0].message


def test_gc505_full_coverage_is_clean(tmp_path):
    pkg = _gc505_tree(tmp_path, other_has_fused=True)
    assert [f for f in run_checks([str(pkg)]) if f.rule.id == "GC505"] == []


def test_gc505_shipped_admission_list_is_covered_and_live():
    """The real config admits raft/pwc/i3d (+ CLIP): the sweep must
    prove every entry, and dropping a family's extractor coverage must
    fire — here by checking the rule resolves the real registry (a
    non-vacuous pass: the admitted list is non-empty)."""
    from video_features_tpu.analysis.sharding_contract import (
        _admitted_types,
        _string_consts,
    )
    from video_features_tpu.analysis.core import collect_sources

    sources = collect_sources(None)
    cfg = next(s for s in sources if s.rel == "config.py")
    admitted, line = _admitted_types(cfg, _string_consts(cfg))
    assert line > 0
    assert {"raft", "pwc", "i3d"} <= set(admitted)
    assert not [f for f in run_checks() if f.rule.id == "GC505"]


# --- budget scenarios: the registry and the JSON stay in lockstep -----------


def test_budget_scenarios_match_committed_json():
    """Every committed scenario has a runnable regenerator and tracks
    exactly the entries its ceiling names (--update-budgets keeps them in
    sync; this pins that nobody hand-edits one side)."""
    from video_features_tpu.analysis.budget_scenarios import SCENARIOS
    from video_features_tpu.analysis.compile_budget import load_budget

    budget = load_budget()
    assert set(budget) == set(SCENARIOS)
    for name, sc in SCENARIOS.items():
        assert set(budget[name]["max_compiles"]) == set(sc.tracked), name
        assert sc.description == budget[name]["description"], name


def test_bf16_budget_scenarios_match_fp32_twins():
    """The GC401 dtype axis: each *_bf16 scenario must exist and pin the
    SAME executable ceiling as its fp32 twin — bf16 swaps the compiled
    program, it must never multiply programs (a second executable per
    dtype would double compile latency and HBM program space)."""
    from video_features_tpu.analysis.compile_budget import load_budget

    budget = load_budget()
    twins = {
        "clip_device_mixed_bf16": "clip_device_mixed",
        "raft_device_tiny_bf16": "raft_device_tiny",
        "pwc_device_tiny_bf16": "pwc_device_tiny",
    }
    for bf16, fp32 in twins.items():
        assert bf16 in budget, bf16
        assert budget[bf16]["max_compiles"] == budget[fp32]["max_compiles"], bf16


def test_budget_covers_every_device_preprocess_family():
    """The GC401 satellite: RAFT/PWC and I3D device scenarios exist
    alongside CLIP's — the budget follows --preprocess device coverage,
    including the mesh-admitted fused families."""
    from video_features_tpu.analysis.compile_budget import load_budget

    names = set(load_budget())
    assert {"clip_device_mixed", "clip_device_grouped", "raft_device_tiny",
            "pwc_device_tiny", "i3d_device_two_stream",
            "raft_mesh_device_tiny", "pwc_mesh_device_tiny",
            "i3d_mesh_device_two_stream"} <= names


# --- acceptance: the shipped package is clean, the CLI behaves --------------


def test_explicit_path_gets_hot_patterns(tmp_path):
    """An explicit file (or dir) arg pointing inside a video_features_tpu
    package tree matches the path-based hot patterns WITHOUT needing the
    `# graftcheck: hot-module` marker — `graftcheck some/extract/file.py`
    must lint like the full-package run does."""
    pkg = tmp_path / "video_features_tpu" / "extract"
    pkg.mkdir(parents=True)
    bad = pkg / "bad.py"
    bad.write_text("def hot(feats):\n    return feats.mean().item()\n")
    for arg in (str(bad), str(pkg)):
        found = run_checks([arg])
        assert [f.rule.id for f in found] == ["GC101"], arg


# --- GC60x durability contracts ---------------------------------------------


def test_gc601_flags_raw_durable_write(tmp_path):
    """Would-refire pin: the pre-fix serve/sources.py _quarantine shape —
    a raw write whose target mentions a durable root, no staged rename."""
    fs = _check(
        tmp_path,
        """
        import json
        import os

        def publish(root, doc):
            path = os.path.join(root, "_manifest", "summary.json")
            with open(path, "w") as fh:
                json.dump(doc, fh)
        """,
    )
    assert _ids(fs) == ["GC601"]
    assert "_manifest" in fs[0].message and "torn" in fs[0].message
    assert "atomic_write_json" in fs[0].hint


def test_gc601_interprocedural_helper_write(tmp_path):
    """A helper that raw-writes a parameter path is judged at the caller
    passing the durable path — with the write site in the trace."""
    fs = _check(
        tmp_path,
        """
        import json

        def write_doc(path, doc):
            with open(path, "w") as fh:
                json.dump(doc, fh)

        def publish(root, doc):
            write_doc(root + "/_requests/rec.json", doc)
        """,
    )
    assert _ids(fs) == ["GC601"]
    assert "write_doc" in fs[0].message
    assert len(fs[0].trace) == 2 and "raw write" in fs[0].trace[1]


def test_gc601_staged_rename_is_clean(tmp_path):
    """The tmp-sibling + os.replace shape (io/sink.py atomic_write_json)
    passes, inline or through a helper that renames."""
    fs = _check(
        tmp_path,
        """
        import json
        import os

        def atomic_write(path, doc):
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(doc, fh)
            os.replace(tmp, path)

        def publish(root, doc):
            atomic_write(os.path.join(root, "_manifest", "summary.json"), doc)
        """,
    )
    assert fs == []


def test_gc602_unguarded_claim_sites(tmp_path):
    """Both claim shapes must branch on losing: O_EXCL create and
    rename-to-.claim each fire without an enclosing failure handler."""
    fs = _check(
        tmp_path,
        """
        import os

        def claim_excl(path):
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.close(fd)

        def claim_rename(spool, name, rid):
            os.rename(spool + "/" + name, spool + "/" + name + ".claim." + rid)
        """,
    )
    assert sorted(_ids(fs)) == ["GC602", "GC602", "GC602"]
    # third finding: the module claims leases but never heartbeats them
    assert any("O_EXCL" in f.message for f in fs)
    assert any("assumes victory" in f.message for f in fs)
    assert any("heartbeat" in f.message for f in fs)


def test_gc602_lease_without_heartbeat(tmp_path):
    """A module that acquires lease files by (guarded) rename but has no
    os.utime anywhere fires the heartbeat leg of GC602."""
    fs = _check(
        tmp_path,
        """
        import os

        def poll_once(spool, rid):
            src = spool + "/job.json"
            try:
                os.rename(src, src + ".claim." + rid)
            except OSError:
                return None
            return src
        """,
    )
    assert _ids(fs) == ["GC602"]
    assert "never" in fs[0].message and "heartbeat" in fs[0].message


def test_gc602_heartbeat_reachable_from_poll_is_clean(tmp_path):
    """The serve/sources.py shape: guarded claim + an os.utime refresh
    reachable from the poll loop through an exact callee."""
    fs = _check(
        tmp_path,
        """
        import os

        def _lease_pass(claims):
            for c in claims:
                try:
                    os.utime(c)
                except OSError:
                    pass

        def poll_once(spool, rid, claims):
            _lease_pass(claims)
            src = spool + "/job.json"
            try:
                os.rename(src, src + ".claim." + rid)
            except OSError:
                return None
            return src
        """,
    )
    assert fs == []


def test_gc603_bare_rename_and_foreign_tmpdir(tmp_path):
    """os.rename with no failure branch (publish wants os.replace), and
    tempfile staging without dir= feeding a rename (EXDEV hazard)."""
    fs = _check(
        tmp_path,
        """
        import os
        import tempfile

        def publish(src, dst):
            os.rename(src, dst)

        def stage(doc, dst):
            fd, tmp = tempfile.mkstemp()
            with os.fdopen(fd, "w") as fh:
                fh.write(doc)
            os.replace(tmp, dst)
        """,
    )
    assert sorted(_ids(fs)) == ["GC603", "GC603"]
    assert any("os.replace" in f.message for f in fs)
    assert any("tmpdir" in f.message for f in fs)


def test_gc603_same_dir_tempfile_and_guarded_rename_are_clean(tmp_path):
    fs = _check(
        tmp_path,
        """
        import os
        import tempfile

        def publish(src, dst):
            try:
                os.rename(src, dst)
            except OSError:
                pass

        def stage(doc, dst):
            fd, tmp = tempfile.mkstemp(dir=os.path.dirname(dst))
            with os.fdopen(fd, "w") as fh:
                fh.write(doc)
            os.replace(tmp, dst)
        """,
    )
    assert fs == []


# --- GC70x observability contracts -------------------------------------------

_EXPO_FIXTURE = textwrap.dedent(
    """
    _PLAIN_COUNTERS = {"frames_seen": "Frames seen."}

    def families_from_snapshot(snap):
        out = []
        for name, value in snap.get("counters", {}).items():
            if name.startswith("requests_"):
                out.append(("requests_total", value))
            elif name == "lease_expired":
                out.append(("lease_expired_total", value))
            elif name in _PLAIN_COUNTERS:
                out.append((name, value))
        return out
    """
)


def _check_two(tmp_path, expo, producer):
    (tmp_path / "expo.py").write_text(textwrap.dedent(expo))
    (tmp_path / "prod.py").write_text(textwrap.dedent(producer))
    return run_checks([str(tmp_path)])


def test_gc701_orphan_producer_fires(tmp_path):
    """Would-refire pin: the pre-fix in-tree shape — a registry series
    (inc/set_gauge/f-string prefix) no exposition convention maps, like
    'frames_decoded' before the _PLAIN_COUNTERS table existed."""
    fs = _check_two(
        tmp_path,
        _EXPO_FIXTURE,
        """
        class Worker:
            def tick(self, status):
                self.metrics.inc("ghost_series")
                self.metrics.inc("frames_seen")
                self.metrics.inc(f"requests_{status}")
                self.metrics.inc("lease_expired")
        """,
    )
    assert _ids(fs) == ["GC701"]
    assert "ghost_series" in fs[0].message and "fallback" in fs[0].message
    assert fs[0].trace and "families_from_snapshot" in fs[0].trace[0]


def test_gc701_orphan_family_fires_reverse(tmp_path):
    """A convention nothing produces (== exact, startswith prefix, or a
    _PLAIN_* table entry) is an orphaned family."""
    fs = _check_two(
        tmp_path,
        _EXPO_FIXTURE,
        """
        class Worker:
            def tick(self):
                self.metrics.inc("frames_seen")
                self.metrics.inc("requests_done")
        """,
    )
    # 'lease_expired' has no producer in this sweep
    assert _ids(fs) == ["GC701"]
    assert "lease_expired" in fs[0].message and "no producer" in fs[0].message


def test_gc701_mapped_producers_are_clean_and_gated(tmp_path):
    fs = _check_two(
        tmp_path,
        _EXPO_FIXTURE,
        """
        class Worker:
            def tick(self, status):
                self.metrics.inc("frames_seen")
                self.metrics.inc(f"requests_{status}")
                self.metrics.inc("lease_expired")
        """,
    )
    assert fs == []
    # no exposition module in the sweep -> the contract has no anchor
    fs = _check(
        tmp_path,
        """
        class W:
            def t(self):
                self.metrics.inc("anything_at_all")
        """,
        name="lone.py",
    )
    assert [f for f in fs if f.rule.id == "GC701"] == []


def test_gc702_unknown_and_dead_stages(tmp_path):
    fs = _check(
        tmp_path,
        """
        STAGES = ("decode", "ghost")

        def drill(fire):
            fire("decode")
            fire("typo")
        """,
    )
    assert sorted(_ids(fs)) == ["GC702", "GC702"]
    assert any("'typo'" in f.message and "not declared" in f.message for f in fs)
    assert any("'ghost'" in f.message and "no fire() site" in f.message for f in fs)


def test_gc702_matched_stages_are_clean(tmp_path):
    fs = _check(
        tmp_path,
        """
        STAGES = ("decode", "sink")

        def drill(fire):
            fire("decode")
            fire("sink")
        """,
    )
    assert fs == []


_CONFIG_FIXTURE_BAD = """
    import argparse
    import dataclasses

    @dataclasses.dataclass
    class Cfg:
        alpha: str = ""
        hidden: int = 0

    def build():
        p = argparse.ArgumentParser()
        p.add_argument("--alpha")
        p.add_argument("--ghost")
        return p

    def sanity_check(cfg):
        if not cfg.alhpa:
            raise ValueError("alpha required")
        return cfg
"""


def test_gc703_flag_field_sanity_drift(tmp_path):
    """Would-refire pin: every pre-fix config.py shape at once — a flag
    parsing into nothing (--ghost), a free-form flag nobody validates
    (--alpha, the pre-fix --extract_method), a field no flag can set
    (hidden, the pre-fix shape_buckets), and a sanity-check typo."""
    fs = _check(tmp_path, _CONFIG_FIXTURE_BAD, name="config.py")
    assert _ids(fs) == ["GC703"] * 4
    msgs = "\n".join(f.message for f in fs)
    assert "--ghost" in msgs and "goes nowhere" in msgs
    assert "--alpha" in msgs and "no parser-side constraint" in msgs
    assert "'hidden'" in msgs and "never be set from the CLI" in msgs
    assert "cfg.alhpa" in msgs and "typo" in msgs


def test_gc703_wired_config_is_clean(tmp_path):
    fs = _check(
        tmp_path,
        """
        import argparse
        import dataclasses

        @dataclasses.dataclass
        class Cfg:
            alpha: str = ""
            hidden: int = 0

        def build():
            p = argparse.ArgumentParser()
            p.add_argument("--alpha")
            return p

        def parse(argv=None):
            args = build().parse_args(argv)
            return sanity_check(Cfg(alpha=args.alpha, hidden=1))

        def sanity_check(cfg):
            if not cfg.alpha.strip():
                raise ValueError("alpha required")
            return cfg
        """,
        name="config.py",
    )
    assert fs == []


def test_gc703_only_fires_on_config_modules(tmp_path):
    """The contract is anchored to config.py: the same drift in any other
    module (an ad-hoc argparse in a script) is out of scope."""
    fs = _check(tmp_path, _CONFIG_FIXTURE_BAD, name="tool.py")
    assert [f for f in fs if f.rule.id == "GC703"] == []


def test_new_rules_render_in_sarif_with_fix_hints(tmp_path):
    """Every GC60x/GC70x id reaches SARIF: in the driver catalogue, and
    as a result whose message folds the fix hint."""
    bad = tmp_path / "mod.py"
    bad.write_text(textwrap.dedent(
        """
        import json
        import os

        def publish(root, doc):
            with open(root + "/_manifest/s.json", "w") as fh:
                json.dump(doc, fh)
        """
    ))
    r = _cli("--sarif", str(bad))
    assert r.returncode == 1
    doc = json.loads(r.stdout)
    catalogue = {ru["id"] for ru in doc["runs"][0]["tool"]["driver"]["rules"]}
    assert {"GC601", "GC602", "GC603", "GC701", "GC702", "GC703",
            "GC801", "GC802", "GC803", "GC804", "GC805"} <= catalogue
    (res,) = doc["runs"][0]["results"]
    assert res["ruleId"] == "GC601"
    assert "(fix:" in res["message"]["text"]
    assert "atomic_write_json" in res["message"]["text"]


# --- GC80x numerics & dtype-flow -------------------------------------------

PK = "# graftcheck: pallas-kernel\n"


def _gc8(findings, rule=None):
    return [
        f for f in findings
        if f.rule.id.startswith("GC8") and (rule is None or f.rule.id == rule)
    ]


def _clear_tests_text_cache():
    from video_features_tpu.analysis import numerics

    numerics._TESTS_TEXT_CACHE.clear()


def test_promotion_flags_f64_constructs_in_jit(tmp_path):
    fs = _gc8(_check(
        tmp_path,
        """
        import numpy as np
        import jax

        @jax.jit
        def hot(x):
            scale = np.float64(2.0)
            bias = np.zeros((4,))
            return x * scale + bias
        """,
    ), "GC801")
    assert len(fs) == 2
    assert any("float64 scalar" in f.message for f in fs)
    assert any("defaults to float64" in f.message for f in fs)
    assert all("jit" in " ".join(f.trace) for f in fs)


def test_promotion_interprocedural_return_trace(tmp_path):
    """A helper RETURNING an f64 value is flagged at its jit-side
    caller, construct site leading the via: trace (the tentpole's
    interprocedural leg)."""
    fs = _gc8(_check(
        tmp_path,
        """
        import numpy as np
        import jax

        def _grid():
            return np.linspace(0.0, 1.0, 16)

        @jax.jit
        def hot(x):
            return x + _grid()
        """,
    ), "GC801")
    assert len(fs) == 1
    (f,) = fs
    assert "_grid" in f.message and "returns float64" in f.message
    assert f.line == 10  # the CALL site, not the construct site
    assert any("linspace" in step for step in f.trace)
    assert any("jitted entry" in step for step in f.trace)


def test_promotion_good_and_islanded(tmp_path):
    fs = _gc8(_check(
        tmp_path,
        """
        import numpy as np
        import jax

        @jax.jit
        def hot(x):
            bias = np.zeros((4,), dtype=np.float32)
            # graftcheck: fp32-island — host-side f64 quadrature weights,
            # cast before they meet traced values
            w = np.linspace(0.0, 1.0, 16)
            return x * np.float32(2.0) + bias + w.astype(np.float32)
        """,
    ), "GC801")
    assert fs == []


def test_accum_dtype_flags_unpinned_matmul_and_softmax(tmp_path):
    fs = _gc8(_check(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        class Block:
            dtype: jnp.dtype = jnp.float32

            def __call__(self, x):
                w = jnp.ones((4, 4), dtype=self.dtype)
                y = jnp.einsum("ij,jk->ik", x, w)
                return jax.nn.softmax(y, axis=-1)
        """,
    ), "GC802")
    assert len(fs) == 2
    assert any("einsum" in f.message for f in fs)
    assert any("softmax" in f.message for f in fs)
    assert all("'__call__'" in f.message for f in fs)


def test_accum_dtype_reaches_helpers_with_trace(tmp_path):
    fs = _gc8(_check(
        tmp_path,
        """
        import jax.numpy as jnp

        def _norm(v):
            return v / jnp.linalg.norm(v)

        def entry(x, dtype=jnp.float32):
            return _norm(x.astype(dtype))
        """,
    ), "GC802")
    assert len(fs) == 1
    (f,) = fs
    assert "norm" in f.message and "'entry'" in f.message
    assert any("bf16-polymorphic entry" in step for step in f.trace)


def test_accum_dtype_election_passes_matmul_not_softmax(tmp_path):
    """Casting operands to the entry's own dtype is a visible precision
    election for MXU matmuls (they accumulate f32 internally) — but no
    pass for sensitive reductions."""
    fs = _gc8(_check(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        class Conv:
            dtype: jnp.dtype = jnp.float32

            def __call__(self, x, w):
                x = x.astype(self.dtype)
                w = w.astype(self.dtype)
                y = jax.lax.dot(x, w)
                return jax.nn.softmax(y, axis=-1)
        """,
    ), "GC802")
    assert len(fs) == 1 and "softmax" in fs[0].message


def test_accum_dtype_good_pins_and_island(tmp_path):
    fs = _gc8(_check(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp

        HIGHEST = jax.lax.Precision.HIGHEST

        class Block:
            dtype: jnp.dtype = jnp.float32

            def __call__(self, x):
                w = jnp.ones((4, 4), dtype=self.dtype)
                hp = jax.lax.Precision.HIGHEST
                y = jnp.einsum("ij,jk->ik", x, w, precision=hp)
                z = jax.nn.softmax(y.astype(jnp.float32), axis=-1)
                return z.mean(axis=-1, dtype=jnp.float32)

        # graftcheck: fp32-island — callers pin the carry fp32 upstream
        def stats(x, dtype=jnp.float32):
            return x.mean(), x.var()
        """,
    ), "GC802")
    assert fs == []


def test_accum_dtype_bf16_entry_token_widens(tmp_path):
    fs = _gc8(_check(
        tmp_path,
        """
        import jax

        # graftcheck: bf16-entry — activations arrive in caller dtype
        def attention_core(q):
            return jax.nn.softmax(q, axis=-1)
        """,
    ), "GC802")
    assert len(fs) == 1 and "softmax" in fs[0].message


def test_cast_discipline_flags_host_f32_on_frames(tmp_path):
    fs = _gc8(_check(
        tmp_path,
        """
        import numpy as np

        def prepare(frames):
            return frames.astype(np.float32)
        """,
        prefix=HOT,
    ), "GC803")
    assert len(fs) == 1 and "4x the uint8 wire bytes" in fs[0].message


def test_cast_discipline_flags_np_wrapper(tmp_path):
    fs = _gc8(_check(
        tmp_path,
        """
        import numpy as np

        def stack_windows(clip_list):
            return np.asarray(clip_list, dtype=np.float32)
        """,
        prefix=HOT,
    ), "GC803")
    assert len(fs) == 1


def test_cast_discipline_good_device_dtype_and_island(tmp_path):
    """A jnp.float32 target implies a device-side cast (GC802's business,
    e.g. the RAFT corr-pyramid pins); islands cover host parity paths."""
    fs = _gc8(_check(
        tmp_path,
        """
        import numpy as np
        import jax.numpy as jnp

        def pin_on_device(frames):
            return frames.astype(jnp.float32)

        # graftcheck: fp32-island — host-only PIL parity reference
        def reference(frames):
            return frames.astype(np.float32)

        def wire(frames):
            return np.ascontiguousarray(frames)  # uint8 stays uint8
        """,
        prefix=HOT,
    ), "GC803")
    assert fs == []


def test_parity_coverage_requires_admission_table(tmp_path):
    fs = _gc8(_check(
        tmp_path,
        """
        import argparse

        def build_parser():
            p = argparse.ArgumentParser()
            p.add_argument("--dtype", choices=["float32", "bfloat16"])
            return p
        """,
        name="config.py",
    ), "GC804")
    assert len(fs) == 1
    assert "LOW_PRECISION_MODEL_FAMILIES" in fs[0].message


def test_parity_coverage_requires_budget_file_entry_and_test(tmp_path):
    _clear_tests_text_cache()
    cfg = 'LOW_PRECISION_MODEL_FAMILIES = {"bfloat16": ("raft", "pwc")}\n'
    fs = _gc8(_check(tmp_path, cfg, name="config.py"), "GC804")
    assert len(fs) == 1 and "parity_budget.json" in fs[0].message

    adir = tmp_path / "analysis"
    adir.mkdir()
    (adir / "parity_budget.json").write_text(json.dumps(
        {"raft": {"bfloat16": {"model": {"max_rel": 0.02}}}}
    ))
    (tmp_path / "tests").mkdir()
    (tmp_path / "tests" / "test_nothing.py").write_text("def test_a(): pass\n")
    fs = _gc8(_check(tmp_path, cfg, name="config.py"), "GC804")
    msgs = sorted(f.message for f in fs)
    assert len(fs) == 2
    assert any("('pwc', 'bfloat16') has no max_rel" in m for m in msgs)
    assert any(
        "('raft', 'bfloat16') has a parity budget but no e2e test" in m
        for m in msgs
    )


def test_parity_coverage_good_and_orphan(tmp_path):
    _clear_tests_text_cache()
    cfg = 'LOW_PRECISION_MODEL_FAMILIES = {"bfloat16": ("raft",)}\n'
    adir = tmp_path / "analysis"
    adir.mkdir()
    (adir / "parity_budget.json").write_text(json.dumps(
        {"raft": {"bfloat16": {"model": {"max_rel": 0.02}}}}
    ))
    tdir = tmp_path / "tests"
    tdir.mkdir()
    (tdir / "test_parity.py").write_text(
        'def test_raft_drift():\n'
        '    assert_drift_within("raft", "bfloat16", "model", a, b)\n'
    )
    assert _gc8(_check(tmp_path, cfg, name="config.py"), "GC804") == []

    _clear_tests_text_cache()
    (adir / "parity_budget.json").write_text(json.dumps({
        "raft": {"bfloat16": {"model": {"max_rel": 0.02}}},
        "pwc": {"bfloat16": {"model": {"max_rel": 0.02}}},
    }))
    fs = _gc8(_check(tmp_path, cfg, name="config.py"), "GC804")
    assert len(fs) == 1 and "orphan parity budget" in fs[0].message


def test_pallas_hygiene_flags_accumulator_grid_interpret(tmp_path):
    """The kitchen-sink bad kernel: bf16 scratch accumulator, unpinned
    reduction, //-grid without guard, no interpret= exposure — and the
    kernel is bound through the idiomatic local functools.partial."""
    fs = _gc8(_check(
        tmp_path,
        """
        import functools
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def _kernel(x_ref, o_ref, acc):
            acc[...] += jnp.sum(x_ref[...])
            o_ref[...] = acc[...]

        def launch_fixture(x):
            kernel = functools.partial(_kernel)
            return pl.pallas_call(
                kernel,
                grid=(x.shape[0] // 8,),
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                scratch_shapes=[pltpu.VMEM((8, 128), jnp.bfloat16)],
            )(x)
        """,
        prefix=PK,
    ), "GC805")
    msgs = " | ".join(f.message for f in fs)
    assert "accumulator scratch 'acc'" in msgs and "not float32" in msgs
    assert "sum in kernel '_kernel' accumulates in the input dtype" in msgs
    assert "no divisibility guard" in msgs
    assert "exposes no interpret=" in msgs


def test_pallas_hygiene_flags_nonscratch_accum_and_cdiv(tmp_path):
    _clear_tests_text_cache()
    (tmp_path / "tests").mkdir()  # nearest tests dir: empty, no parity test
    fs = _gc8(_check(
        tmp_path,
        """
        import jax
        from jax.experimental import pallas as pl

        def _kernel2(x_ref, o_ref):
            o_ref[...] = o_ref[...] + x_ref[...]

        def launch_fixture2(x, interpret=False):
            return pl.pallas_call(
                _kernel2,
                grid=(pl.cdiv(x.shape[0], 8),),
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                interpret=interpret,
            )(x)
        """,
        prefix=PK,
    ), "GC805")
    msgs = " | ".join(f.message for f in fs)
    assert "accumulates into non-scratch ref 'o_ref'" in msgs
    assert "rounds up but nothing pads" in msgs
    assert "no interpret-mode parity test exercises 'launch_fixture2'" in msgs


def test_pallas_hygiene_good_kernel(tmp_path):
    _clear_tests_text_cache()
    tdir = tmp_path / "tests"
    tdir.mkdir()
    (tdir / "test_kernel.py").write_text(
        "def test_parity():\n"
        "    launch_fixture3(x, interpret=True)\n"
    )
    fs = _gc8(_check(
        tmp_path,
        """
        import functools
        import jax
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def _kernel3(x_ref, o_ref, acc):
            acc[...] += jnp.sum(x_ref[...], dtype=jnp.float32)
            o_ref[...] = acc[...].astype(o_ref.dtype)

        def launch_fixture3(x, interpret=False):
            pad = (-x.shape[0]) % 8
            x = jnp.pad(x, ((0, pad), (0, 0)))
            kernel = functools.partial(_kernel3)
            return pl.pallas_call(
                kernel,
                grid=(pl.cdiv(x.shape[0], 8),),
                out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
                scratch_shapes=[pltpu.VMEM((8, 128), jnp.float32)],
                interpret=interpret,
            )(x)
        """,
        prefix=PK,
    ), "GC805")
    assert fs == []


def test_declaration_tokens_are_not_waivers(tmp_path):
    """fp32-island / bf16-entry / pallas-kernel declare facts for GC80x;
    they must not silence any OTHER rule (zero-waiver policy intact)."""
    fs = _check(
        tmp_path,
        """
        import jax.numpy as jnp

        def hot(x):
            # graftcheck: fp32-island — declarations are not waivers
            return float(jnp.square(x))
        """,
        prefix=HOT,
    )
    assert "GC102" in _ids(fs)


# --- GC80x would-refire pins for the in-tree fixes --------------------------

def test_raft_softmax_pin_would_refire(tmp_path):
    """THE acceptance pin: stripping the fp32 cast from RAFT's
    bf16-reachable upsample softmax refires GC802 and fails tier-1."""
    src_path = os.path.join(
        REPO, "video_features_tpu", "models", "raft", "model.py"
    )
    with open(src_path, encoding="utf-8") as fh:
        src = fh.read()
    pinned = "mask.reshape(N, H, W, 9, 8, 8).astype(jnp.float32)"
    assert pinned in src, "the GC802 softmax pin left raft/model.py"
    stripped = src.replace(pinned, "mask.reshape(N, H, W, 9, 8, 8)")
    p = tmp_path / "model.py"
    p.write_text(stripped)
    fs = [f for f in run_checks([str(p)]) if f.rule.id == "GC802"]
    assert any("softmax" in f.message for f in fs)
    # control: the shipped source is clean
    assert [f for f in run_checks([src_path]) if f.rule.id == "GC802"] == []


def test_correlation_kernel_pin_would_refire(tmp_path):
    """Stripping dtype=jnp.float32 from the Pallas cost-volume sum
    refires GC805."""
    src_path = os.path.join(
        REPO, "video_features_tpu", "ops", "pallas", "correlation_kernel.py"
    )
    with open(src_path, encoding="utf-8") as fh:
        src = fh.read()
    pinned = "jnp.sum(f1 * f2, axis=0, dtype=jnp.float32)"
    assert pinned in src
    stripped = src.replace(pinned, "jnp.sum(f1 * f2, axis=0)")
    p = tmp_path / "kernel.py"
    p.write_text(PK + stripped)
    fs = [f for f in run_checks([str(p)]) if f.rule.id == "GC805"]
    assert any("accumulates in the input dtype" in f.message for f in fs)
    control = tmp_path / "kernel_ok.py"
    control.write_text(PK + src)
    assert [f for f in run_checks([str(control)]) if f.rule.id == "GC805"] == []


def test_i3d_island_annotations_would_refire(tmp_path):
    """Deleting the fp32-island declarations from the I3D host parity
    paths refires GC803 for each annotated cast."""
    src_path = os.path.join(
        REPO, "video_features_tpu", "models", "i3d", "extract_i3d.py"
    )
    with open(src_path, encoding="utf-8") as fh:
        src = fh.read()
    assert src.count("fp32-island") == 2
    stripped = "\n".join(
        ln for ln in src.splitlines() if "fp32-island" not in ln
    )
    p = tmp_path / "extract_i3d.py"
    p.write_text(HOT + stripped)
    fs = [f for f in run_checks([str(p)]) if f.rule.id == "GC803"]
    assert len(fs) >= 2
    control = tmp_path / "extract_i3d_ok.py"
    control.write_text(HOT + src)
    assert [f for f in run_checks([str(control)]) if f.rule.id == "GC803"] == []


def test_cli_sarif_carries_gc80x_fix_hint(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n\n"
        "# graftcheck: bf16-entry — fixture\n"
        "def core(q):\n"
        "    return jax.nn.softmax(q, axis=-1)\n"
    )
    r = _cli("--sarif", "--rule", "GC802", str(bad))
    assert r.returncode == 1
    doc = json.loads(r.stdout)
    (res,) = doc["runs"][0]["results"]
    assert res["ruleId"] == "GC802"
    assert "preferred_element_type" in res["message"]["text"]


def test_repo_is_clean():
    """`python -m video_features_tpu.analysis` exits 0 on the repo: every
    genuine violation is fixed, every intentional one carries an
    explanatory waiver (audit: `git grep 'graftcheck:'`)."""
    assert run_checks() == []


def test_rule_catalogue_complete():
    ids = [r.id for r in all_rules()]
    assert ids == ["GC101", "GC102", "GC103", "GC104",
                   "GC201", "GC202", "GC203",
                   "GC301", "GC311", "GC312", "GC313", "GC401",
                   "GC501", "GC502", "GC503", "GC504", "GC505",
                   "GC601", "GC602", "GC603",
                   "GC701", "GC702", "GC703",
                   "GC801", "GC802", "GC803", "GC804", "GC805"]


def _cli(*args, cwd=REPO):
    env = dict(os.environ, PYTHONPATH=REPO)
    return subprocess.run(
        [sys.executable, "-m", "video_features_tpu.analysis", *args],
        capture_output=True, text=True, env=env, cwd=cwd,
    )


def test_cli_violation_exit_and_format(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        HOT + "import jax.numpy as jnp\n\ndef hot(x):\n"
        "    return float(jnp.square(x))\n"
    )
    r = _cli(str(bad))
    assert r.returncode == 1
    assert f"{bad}:5:" in r.stdout and "GC102" in r.stdout
    assert "fix:" in r.stdout


def test_cli_json_and_rule_filter(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        HOT + "import jax.numpy as jnp\n\ndef hot(x):\n"
        "    y = jnp.square(x)\n    return float(y), y.item()\n"
    )
    r = _cli("--json", "--rule", "GC101", str(bad))
    assert r.returncode == 1
    doc = json.loads(r.stdout)
    assert [d["rule"] for d in doc] == ["GC101"]
    assert doc[0]["path"] == str(bad) and doc[0]["line"] == 6


def test_cli_list_rules():
    r = _cli("--list-rules")
    assert r.returncode == 0
    for rid in ("GC101", "GC203", "GC301", "GC401"):
        assert rid in r.stdout


def test_cli_rule_accepts_comma_separated_tokens(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        HOT + "import jax.numpy as jnp\n\ndef hot(x):\n"
        "    y = jnp.square(x)\n    return float(y), y.item()\n"
    )
    r = _cli("--json", "--rule", "GC101,GC102", str(bad))
    assert r.returncode == 1
    assert sorted(d["rule"] for d in json.loads(r.stdout)) == [
        "GC101", "GC102"]


def test_cli_json_matches_committed_schema(tmp_path):
    """findings_schema.json is the CI contract for --json: validate a
    real interprocedural finding against it, trace lines included."""
    jsonschema = pytest.importorskip("jsonschema")
    schema_path = os.path.join(
        REPO, "video_features_tpu", "analysis", "findings_schema.json"
    )
    with open(schema_path, encoding="utf-8") as fh:
        schema = json.load(fh)
    bad = tmp_path / "bad.py"
    bad.write_text(
        HOT + "import jax.numpy as jnp\n\n"
        "def _score(x):\n    return jnp.square(x).mean()\n\n"
        "def hot(x):\n    return float(_score(x))\n"
    )
    r = _cli("--json", str(bad))
    assert r.returncode == 1
    doc = json.loads(r.stdout)
    jsonschema.validate(doc, schema)
    assert any(d["trace"] for d in doc), "interprocedural trace missing"


def test_cli_sarif_output(tmp_path):
    """--sarif speaks SARIF 2.1.0: driver named graftcheck, the FULL rule
    catalogue in the run (clean uploads keep their ruleset), results with
    repo-relative 1-based locations and the hint folded in."""
    bad = tmp_path / "bad.py"
    bad.write_text(
        HOT + "import jax.numpy as jnp\n\ndef hot(x):\n"
        "    return float(jnp.square(x))\n"
    )
    r = _cli("--sarif", str(bad))
    assert r.returncode == 1
    doc = json.loads(r.stdout)
    assert doc["version"] == "2.1.0"
    driver = doc["runs"][0]["tool"]["driver"]
    assert driver["name"] == "graftcheck"
    assert [ru["id"] for ru in driver["rules"]] == [
        r2.id for r2 in all_rules()
    ]
    (res,) = doc["runs"][0]["results"]
    assert res["ruleId"] == "GC102" and res["level"] == "error"
    assert "(fix:" in res["message"]["text"]
    loc = res["locations"][0]["physicalLocation"]
    assert loc["region"]["startLine"] == 5
    assert loc["region"]["startColumn"] >= 1
    assert loc["artifactLocation"]["uriBaseId"] == "%SRCROOT%"
    assert not loc["artifactLocation"]["uri"].startswith("/")

    clean = tmp_path / "ok.py"
    clean.write_text("x = 1\n")
    r = _cli("--sarif", str(clean))
    assert r.returncode == 0
    doc = json.loads(r.stdout)
    assert doc["runs"][0]["results"] == []
    assert len(doc["runs"][0]["tool"]["driver"]["rules"]) == len(all_rules())


def test_cli_explain_prints_propagation_chain(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        HOT + "import jax.numpy as jnp\n\n"
        "def _score(x):\n    return jnp.square(x).mean()\n\n"
        "def hot(x):\n    return float(_score(x))\n"
    )
    r = _cli("--explain", "GC102", str(bad))
    assert r.returncode == 1
    assert "via:" in r.stdout and "_score" in r.stdout


def test_cli_diff_reports_only_changed_lines(tmp_path):
    """--diff BASE: a pre-existing violation on an untouched line stays
    quiet; the violation the diff introduces fails the run."""
    def git(*args):
        subprocess.run(
            ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
            cwd=str(tmp_path), check=True, capture_output=True,
        )

    git("init", "-q")
    mod = tmp_path / "mod.py"
    mod.write_text(
        HOT + "import jax.numpy as jnp\n\ndef hot(x):\n"
        "    return float(jnp.square(x))\n"
    )
    git("add", "mod.py")
    git("commit", "-q", "-m", "seed")
    r = _cli("--diff", "HEAD", str(mod), cwd=str(tmp_path))
    assert r.returncode == 0, r.stdout + r.stderr
    mod.write_text(
        mod.read_text()
        + "\ndef hotter(x):\n    return jnp.square(x).item()\n"
    )
    r = _cli("--diff", "HEAD", str(mod), cwd=str(tmp_path))
    assert r.returncode == 1
    assert "GC101" in r.stdout and "GC102" not in r.stdout


def test_cli_diff_bad_ref_is_exit_2(tmp_path):
    mod = tmp_path / "mod.py"
    mod.write_text("x = 1\n")
    r = _cli("--diff", "no-such-ref", str(mod))
    assert r.returncode == 2
    assert "--diff" in r.stderr
