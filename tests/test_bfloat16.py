"""--dtype bfloat16: numeric-drift gates + plumbing checks.

bf16 runs the residual stream / conv stacks and every MXU matmul in
bfloat16 while LayerNorm statistics, attention softmax, BatchNorm fold
math, pools, and the final feature/logit heads stay fp32
(VERDICT r1 #4). Expected drift at full model width, measured on random
weights + random inputs (documented in PARITY.md):

- CLIP ViT-B/32: ~1e-2 relative L2 on the 512-d embedding
- ResNet-50:     ~1e-2 relative L2 on the 2048-d features
- R(2+1)D / I3D: same order (conv stacks, fp32 heads)

The flow nets (RAFT/PWC) and VGGish intentionally ignore --dtype: flow
refinement is iterative (drift compounds over 20 GRU steps / 5 decoder
levels) and VGGish is too small to matter.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.models.common.weights import cast_floats_for_compute


def _rel(a, b):
    a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
    return np.linalg.norm(a - b) / max(np.linalg.norm(b), 1e-12)


@pytest.mark.quick
def test_clip_bf16_drift_bounded():
    from video_features_tpu.models.clip.model import (
        CLIP_VIT_B32,
        VisionTransformer,
        init_params,
    )

    params = init_params(CLIP_VIT_B32)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 3, 224, 224).astype(np.float32))
    ref = VisionTransformer(CLIP_VIT_B32).apply({"params": params}, x)
    p16 = cast_floats_for_compute(params, jnp.bfloat16, exclude=("proj",))
    out = VisionTransformer(CLIP_VIT_B32, dtype=jnp.bfloat16).apply({"params": p16}, x)
    assert np.asarray(out).dtype == np.float32  # fp32 output contract
    assert _rel(out, ref) < 0.03


def test_resnet_bf16_drift_bounded():
    from video_features_tpu.models.resnet.model import build, init_params

    params = init_params("resnet50")
    x = jnp.asarray(np.random.RandomState(0).randn(2, 3, 224, 224).astype(np.float32))
    ref, _ = build("resnet50").apply({"params": params}, x)
    p16 = cast_floats_for_compute(params, jnp.bfloat16, exclude=("fc",))
    out, _ = build("resnet50", dtype=jnp.bfloat16).apply({"params": p16}, x)
    assert _rel(out, ref) < 0.03


def test_r21d_bf16_drift_bounded():
    from video_features_tpu.models.r21d.model import build, init_params

    params = init_params()
    x = jnp.asarray(np.random.RandomState(0).randn(1, 8, 112, 112, 3).astype(np.float32))
    ref, _ = build().apply({"params": params}, x)
    p16 = cast_floats_for_compute(params, jnp.bfloat16, exclude=("fc",))
    out, _ = build(dtype=jnp.bfloat16).apply({"params": p16}, x)
    assert _rel(out, ref) < 0.03


def test_i3d_bf16_drift_bounded():
    from video_features_tpu.models.i3d.model import build, init_params

    params = init_params("rgb")
    x = jnp.asarray(
        np.random.RandomState(0).uniform(-1, 1, (1, 16, 224, 224, 3)).astype(np.float32)
    )
    ref, _ = build().apply({"params": params}, x)
    p16 = cast_floats_for_compute(params, jnp.bfloat16, exclude=("conv3d_0c_1x1",))
    out, _ = build(dtype=jnp.bfloat16).apply({"params": p16}, x)
    assert _rel(out, ref) < 0.03


def test_dtype_flag_reaches_extractor(sample_video, tmp_path):
    """--dtype bfloat16 end-to-end: the extractor consumes the flag (the
    round-1 dead knob, VERDICT r1 weak #2) and produces fp32 features
    close to the fp32 run."""
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    def run(dtype):
        cfg = ExtractionConfig(
            allow_random_init=True,
            feature_type="CLIP-ViT-B/32",
            video_paths=[sample_video],
            extract_method="uni_4",
            dtype=dtype,
            cpu=True,
        )
        ex = ExtractCLIP(cfg, external_call=True)
        ex.progress.disable = True
        return ex([0])[0]["CLIP-ViT-B/32"]

    f32 = run("float32")
    bf16 = run("bfloat16")
    assert bf16.dtype == np.float32 and bf16.shape == f32.shape
    assert 0 < _rel(bf16, f32) < 0.03  # different numerics, same features


def test_i3d_raft_bf16_flow_stream(sample_video, tmp_path):
    """--dtype bfloat16 on the north-star config (i3d + raft flow): the
    flow stream now runs RAFT's mixed-precision graph (r4) feeding a bf16
    I3D through the fp32-pinned flow_to_uint8 quantizer. Features must
    stay fp32 and land near the fp32 run — through BOTH bf16 nets AND the
    one-level quantizer flips the raft drift budget allows."""
    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D

    def run(dtype):
        cfg = ExtractionConfig(
            allow_random_init=True,
            feature_type="i3d",
            streams=["flow"],
            flow_type="raft",
            video_paths=[sample_video],
            dtype=dtype,
            cpu=True,
        )
        ex = ExtractI3D(cfg, external_call=True)
        ex.progress.disable = True
        return ex([0])[0]["flow"]

    f32 = run("float32")
    bf16 = run("bfloat16")
    assert bf16.dtype == np.float32 and bf16.shape == f32.shape
    assert 0 < _rel(bf16, f32) < 0.05
