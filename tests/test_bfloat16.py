"""--dtype bfloat16: numeric-drift gates + plumbing checks.

bf16 runs the residual stream / conv stacks and every MXU matmul in
bfloat16 while LayerNorm statistics, attention softmax, BatchNorm fold
math, pools, flow refinement carries and the final feature/logit heads
stay fp32 (VERDICT r1 #4, r4 for the flow nets).

Drift ceilings are NOT inlined here: every bound lives in
``analysis/parity_budget.json`` — the committed (family, dtype) table
graftcheck GC804 cross-checks against ``config.LOW_PRECISION_MODEL_
FAMILIES`` — and is asserted through
``analysis.parity.assert_drift_within``. Deleting a family's budget
entry makes its assertion here raise KeyError (the would-refire pin);
regenerate measured drift with ``python -m video_features_tpu.analysis
--update-budgets --scenario parity_<family>``.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from video_features_tpu.analysis.parity import assert_drift_within, rel_drift
from video_features_tpu.config import ExtractionConfig


@pytest.mark.quick
def test_clip_bf16_drift_bounded():
    from video_features_tpu.models.clip.model import (
        CLIP_VIT_B32,
        VisionTransformer,
        init_params,
    )
    from video_features_tpu.models.common.weights import cast_floats_for_compute

    params = init_params(CLIP_VIT_B32)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 3, 224, 224).astype(np.float32))
    ref = VisionTransformer(CLIP_VIT_B32).apply({"params": params}, x)
    p16 = cast_floats_for_compute(params, jnp.bfloat16, exclude=("proj",))
    out = VisionTransformer(CLIP_VIT_B32, dtype=jnp.bfloat16).apply({"params": p16}, x)
    assert np.asarray(out).dtype == np.float32  # fp32 output contract
    assert_drift_within("clip", "bfloat16", "model", out, ref)


def test_resnet_bf16_drift_bounded():
    from video_features_tpu.models.common.weights import cast_floats_for_compute
    from video_features_tpu.models.resnet.model import build, init_params

    params = init_params("resnet50")
    x = jnp.asarray(np.random.RandomState(0).randn(2, 3, 224, 224).astype(np.float32))
    ref, _ = build("resnet50").apply({"params": params}, x)
    p16 = cast_floats_for_compute(params, jnp.bfloat16, exclude=("fc",))
    out, _ = build("resnet50", dtype=jnp.bfloat16).apply({"params": p16}, x)
    assert_drift_within("resnet", "bfloat16", "model", out, ref)


def test_r21d_bf16_drift_bounded():
    from video_features_tpu.models.common.weights import cast_floats_for_compute
    from video_features_tpu.models.r21d.model import build, init_params

    params = init_params()
    x = jnp.asarray(np.random.RandomState(0).randn(1, 8, 112, 112, 3).astype(np.float32))
    ref, _ = build().apply({"params": params}, x)
    p16 = cast_floats_for_compute(params, jnp.bfloat16, exclude=("fc",))
    out, _ = build(dtype=jnp.bfloat16).apply({"params": p16}, x)
    assert_drift_within("r21d", "bfloat16", "model", out, ref)


def test_i3d_bf16_drift_bounded():
    from video_features_tpu.models.common.weights import cast_floats_for_compute
    from video_features_tpu.models.i3d.model import build, init_params

    params = init_params("rgb")
    x = jnp.asarray(
        np.random.RandomState(0).uniform(-1, 1, (1, 16, 224, 224, 3)).astype(np.float32)
    )
    ref, _ = build().apply({"params": params}, x)
    p16 = cast_floats_for_compute(params, jnp.bfloat16, exclude=("conv3d_0c_1x1",))
    out, _ = build(dtype=jnp.bfloat16).apply({"params": p16}, x)
    assert_drift_within("i3d", "bfloat16", "model", out, ref)


def test_dtype_flag_reaches_extractor(sample_video, tmp_path):
    """--dtype bfloat16 end-to-end: the extractor consumes the flag (the
    round-1 dead knob, VERDICT r1 weak #2) and produces fp32 features
    close to the fp32 run."""
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    def run(dtype):
        cfg = ExtractionConfig(
            allow_random_init=True,
            feature_type="CLIP-ViT-B/32",
            video_paths=[sample_video],
            extract_method="uni_4",
            dtype=dtype,
            cpu=True,
        )
        ex = ExtractCLIP(cfg, external_call=True)
        ex.progress.disable = True
        return ex([0])[0]["CLIP-ViT-B/32"]

    f32 = run("float32")
    bf16 = run("bfloat16")
    assert bf16.dtype == np.float32 and bf16.shape == f32.shape
    # different numerics, same features: a zero drift would mean the
    # bf16 graph never ran
    assert assert_drift_within("clip", "bfloat16", "e2e", bf16, f32) > 0


def test_i3d_raft_bf16_flow_stream(sample_video, tmp_path):
    """--dtype bfloat16 on the north-star config (i3d + raft flow): the
    flow stream runs RAFT's mixed-precision graph (r4) feeding a bf16
    I3D through the fp32-pinned flow_to_uint8 quantizer. Features must
    stay fp32 and land near the fp32 run — through BOTH bf16 nets AND the
    one-level quantizer flips the raft drift budget allows."""
    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D

    def run(dtype):
        cfg = ExtractionConfig(
            allow_random_init=True,
            feature_type="i3d",
            streams=["flow"],
            flow_type="raft",
            video_paths=[sample_video],
            dtype=dtype,
            cpu=True,
        )
        ex = ExtractI3D(cfg, external_call=True)
        ex.progress.disable = True
        return ex([0])[0]["flow"]

    f32 = run("float32")
    bf16 = run("bfloat16")
    assert bf16.dtype == np.float32 and bf16.shape == f32.shape
    assert assert_drift_within("i3d", "bfloat16", "e2e_flow", bf16, f32) > 0


@pytest.mark.slow
@pytest.mark.parametrize("ft", ["raft", "pwc"])
def test_flow_bf16_e2e_admitted(ft, sample_video, tmp_path):
    """--dtype bfloat16 standalone flow extraction (the PR-20 admission):
    feature_type=raft/pwc now passes sanity_check under bf16 and the
    extracted flow stays within the committed e2e parity budget."""
    from video_features_tpu.extract.registry import build_extractor

    def run(dtype):
        cfg = ExtractionConfig(
            allow_random_init=True,
            feature_type=ft,
            video_paths=[sample_video],
            batch_size=4,
            dtype=dtype,
            tmp_path=str(tmp_path / f"tmp_{dtype}"),
            output_path=str(tmp_path / f"out_{dtype}"),
            cpu=True,
        )
        ex = build_extractor(cfg, external_call=True)
        ex.progress.disable = True
        return ex([0])[0][ft]

    f32 = run("float32")
    bf16 = run("bfloat16")
    assert bf16.dtype == np.float32 and bf16.shape == f32.shape
    assert assert_drift_within(ft, "bfloat16", "e2e", bf16, f32) > 0


def test_unadmitted_dtype_rejected(tmp_path):
    """sanity_check enforces the GC804 admission table: a family outside
    LOW_PRECISION_MODEL_FAMILIES cannot take --dtype bfloat16."""
    from video_features_tpu.config import sanity_check

    with pytest.raises(ValueError, match="not admitted"):
        sanity_check(
            ExtractionConfig(
                feature_type="vggish",
                dtype="bfloat16",
                tmp_path=str(tmp_path / "tmp"),
                output_path=str(tmp_path / "out"),
            )
        )


def test_parity_budget_would_refire():
    """Would-refire pin (GC804 satellite): deleting a model's budget
    entry must fail loudly — the helper raises KeyError naming the
    regeneration command, so the e2e assertions above cannot silently
    pass without a committed ceiling."""
    with pytest.raises(KeyError, match="update-budgets"):
        assert_drift_within("clip", "bfloat16", "nonexistent-kind", [1.0], [1.0])
    # and the metric itself: identical inputs -> 0, scaled -> relative
    assert rel_drift([1.0, 0.0], [1.0, 0.0]) == 0.0
    assert abs(rel_drift([1.01, 0.0], [1.0, 0.0]) - 0.01) < 1e-12
