"""Fleet robustness (ISSUE 18): HBM-aware preemption, multi-replica
work-stealing, and chaos-tested recovery.

Deterministic by construction, like the rest of the serve suite: the
preemptor is driven with injected ledgers/clocks/pools (no device), the
lease protocol with a fake-clock SpoolWatcher whose staleness is
backdated via ``os.utime`` (mtimes are the one clock replicas share),
and the chaos drill SIGKILLs a jax-free subprocess replica through the
``replica_kill`` fault stage — no sleep-based races anywhere except the
bounded subprocess waits.
"""

import glob
import hashlib
import json
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from test_faults import ToyExtractor, _cfg, toy_videos  # noqa: F401
from test_serve import FakeClock, ServeToy, serve_videos  # noqa: F401

from video_features_tpu.config import parse_serve_args
from video_features_tpu.runtime import faults
from video_features_tpu.runtime.telemetry import MetricsRegistry
from video_features_tpu.serve.batcher import QueueFull
from video_features_tpu.serve.costmodel import ServiceTimeModel
from video_features_tpu.serve.daemon import ServeDaemon
from video_features_tpu.serve.lifecycle import (
    ExtractionRequest,
    ReplicaRegistry,
    RequestTracker,
    requests_root,
)
from video_features_tpu.serve.preemptor import Preemptor, simulate_overcommit
from video_features_tpu.serve.sources import SpoolWatcher
from video_features_tpu.serve.supervisor import CircuitBreaker, ModelUnavailable
from video_features_tpu.telemetry.exposition import (
    families_from_snapshot,
    render_families,
    validate_exposition,
)
from video_features_tpu.telemetry.ledger import CostLedger

pytestmark = pytest.mark.serve


@pytest.fixture(autouse=True)
def _clear_global_fault_state():
    yield
    faults.install_injector(None)


# --- preemptor units (injected ledger/pool/clock, no daemon) -----------------


class FakePool:
    def __init__(self, residents=(), built_at=None):
        self._resident = set(residents)
        self.built_at = dict(built_at or {})
        self.evicted = []

    def feature_types(self):
        return set(self._resident)

    def evict(self, ft):
        self._resident.discard(ft)
        self.built_at.pop(ft, None)
        self.evicted.append(ft)


class EventLog:
    def __init__(self):
        self.log = []

    def event(self, name, **fields):
        self.log.append((name, fields))

    def names(self):
        return [n for n, _ in self.log]


def _ledger(entries):
    """A CostLedger holding one tpu entry per (model, resident_bytes)."""
    led = CostLedger(path=None)
    for model, resident in entries.items():
        led.record(
            model, "fam", "64x48", "queue", "tpu",
            {"memory": {"argument_bytes": int(resident)}},
        )
    return led


def _preemptor(
    ledger,
    pool,
    breakers,
    clock,
    headroom=None,
    queued=None,
    budget=0,
    cooldown_s=0.0,
    min_residency_s=0.0,
    metrics=None,
    manifest=None,
):
    return Preemptor(
        ledger=ledger,
        cost_model=ServiceTimeModel(path=None),
        pool=pool,
        breaker_for=lambda ft: breakers.setdefault(
            ft, CircuitBreaker(clock=clock)
        ),
        headroom_fn=(lambda: headroom) if headroom is not None else None,
        queued_fn=(lambda: queued) if queued is not None else None,
        hbm_budget_bytes=budget,
        cooldown_s=cooldown_s,
        min_residency_s=min_residency_s,
        clock=clock,
        metrics=metrics,
        manifest=manifest,
    )


def test_preemptor_unknown_without_projection_never_crashes():
    """CPU backends land here: the ledger has no HBM entries, so the
    verdict is 'unknown' and preemption stays entirely out of the way."""
    clk = FakeClock()
    led = CostLedger(path=None)  # empty: a pure-CPU run projects nothing
    led.record("m_cpu", "fam", "64x48", "queue", "cpu",
               {"memory": {"argument_bytes": 10**9}})  # cpu: still nothing
    p = _preemptor(led, FakePool({"a"}), {}, clk, headroom=0)
    assert p.check("m_cpu") == ("unknown", 0, None)
    assert p.ensure_room("m_cpu") is None
    assert p.value_score("m_cpu") >= 1.0  # ranking never crashes either


def test_preemptor_unknown_without_headroom_signal():
    clk = FakeClock()
    led = _ledger({"b": 500})
    p = _preemptor(led, FakePool({"a"}), {}, clk)  # no headroom_fn, no budget
    verdict, needed, available = p.check("b")
    assert (verdict, needed, available) == ("unknown", 500, None)
    assert p.ensure_room("b") is None


def test_preemptor_resident_always_fits():
    clk = FakeClock()
    p = _preemptor(_ledger({"a": 500}), FakePool({"a"}), {}, clk, headroom=0)
    assert p.check("a")[0] == "fits"


def test_preemptor_evicts_lowest_value_and_trips_breaker(tmp_path):
    clk = FakeClock(100.0)
    led = _ledger({"a": 400, "b": 400, "c": 500})
    pool = FakePool({"a", "b"}, built_at={"a": 0.0, "b": 0.0})
    breakers = {}
    metrics = MetricsRegistry()
    events = EventLog()
    # b has queued work (priority 5); a is idle -> a is the victim
    queued = {"b": {"count": 3, "max_priority": 5, "buckets": ["64x48"]}}
    p = _preemptor(led, pool, breakers, clk, headroom=200, queued=queued,
                   metrics=metrics, manifest=events)
    assert p.check("c") == ("overcommit", 500, 200)
    plan = p.ensure_room("c")
    assert plan is not None and plan.victims == ["a"]
    assert pool.evicted == ["a"] and "b" in pool.feature_types()
    assert breakers["a"].state() == "open"  # tripped, not just evicted
    assert metrics.snapshot()["counters"]["preemptions.a"] == 1
    assert events.names() == ["preempted"]
    assert events.log[0][1]["beneficiary"] == "c"


def test_preemptor_equal_value_tie_breaks_by_name():
    clk = FakeClock(100.0)
    led = _ledger({"x": 400, "m": 400, "z": 400, "new": 300})
    pool = FakePool({"z", "x", "m"}, built_at={})
    p = _preemptor(led, pool, {}, clk, headroom=0)
    # all three residents are idle/cold: identical score 1.0 each -> the
    # victim list is lexicographic and stable across repeated ranking
    assert p.value_score("x") == p.value_score("m") == p.value_score("z")
    plan = p.ensure_room("new")
    assert plan is not None and plan.victims == ["m"]


def test_preemptor_min_residency_guard():
    clk = FakeClock(100.0)
    led = _ledger({"a": 400, "b": 400})
    pool = FakePool({"a"}, built_at={"a": 95.0})  # built 5s ago
    p = _preemptor(led, pool, {}, clk, headroom=0, min_residency_s=60.0)
    assert p.ensure_room("b") is None  # a is too young to thrash
    assert pool.evicted == []
    clk.t = 200.0  # now resident 105s: eligible
    assert p.ensure_room("b") is not None
    assert pool.evicted == ["a"]


def test_preemptor_cooldown_hysteresis():
    clk = FakeClock(0.0)
    led = _ledger({"a": 400, "b": 400, "c": 400})
    pool = FakePool({"a", "b"}, built_at={})
    p = _preemptor(led, pool, {}, clk, headroom=0, cooldown_s=30.0)
    assert p.ensure_room("c") is not None
    clk.t = 10.0  # within the cooldown: a second burst cannot evict
    assert p.ensure_room("c") is None and pool.evicted == ["a"]
    clk.t = 31.0
    assert p.ensure_room("c") is not None
    assert pool.evicted == ["a", "b"]


def test_preemptor_rollback_restores_preempted_breakers():
    clk = FakeClock()
    led = _ledger({"a": 400, "b": 400})
    pool = FakePool({"a"}, built_at={})
    breakers = {}
    events = EventLog()
    p = _preemptor(led, pool, breakers, clk, headroom=0, manifest=events)
    plan = p.ensure_room("b")
    assert plan is not None and breakers["a"].state() == "open"
    p.rollback(plan)
    assert breakers["a"].state() == "closed"  # serves again, no cooldown
    assert events.names() == ["preempted", "preemption_rollback"]


def test_preemptor_rejects_when_full_sweep_cannot_fit():
    clk = FakeClock()
    led = _ledger({"a": 100, "big": 10_000})
    pool = FakePool({"a"}, built_at={})
    breakers = {}
    p = _preemptor(led, pool, breakers, clk, headroom=50)
    assert p.ensure_room("big") is None  # 50 + 100 << 10_000: reject
    assert pool.evicted == [] and pool.feature_types() == {"a"}
    assert not breakers or breakers["a"].state() == "closed"


def test_hbm_squeeze_fault_collapses_headroom():
    clk = FakeClock()
    led = _ledger({"b": 10})
    p = _preemptor(led, FakePool({"a"}), {}, clk, headroom=10**12)
    assert p.check("b")[0] == "fits"
    faults.install_injector(["hbm_squeeze:error:1"])
    assert p.check("b") == ("overcommit", 10, 0)  # squeezed: headroom 0


def test_simulate_overcommit_preemption_lowers_miss_rate():
    """The pinned A/B the serve_preemption bench runs: same burst, with
    and without the preemptor — ON must strictly beat OFF on misses."""
    clk = FakeClock()
    led = _ledger({"a": 400, "b": 500})
    bursts = [("a", 4), ("b", 6)]

    def run(preemptor):
        pool = FakePool({"a"}, built_at={})
        p = None
        if preemptor:
            p = _preemptor(led, pool, {}, clk, headroom=100)
        return simulate_overcommit(
            p, bursts, resident_fits=lambda ft: ft == "a",
            service_s=1.0, deadline_s=2.5, rewarm_s=0.5,
        )

    off = run(False)
    on = run(True)
    assert [r["met"] for r in off] == [True] * 4 + [False] * 6
    assert all(r["met"] for r in on)
    # first preempted group pays the re-warm toll, the rest do not
    b_latencies = [r["latency_s"] for r in on if r["feature_type"] == "b"]
    assert b_latencies == [1.5] * 6  # one fused group: all share the toll
    off_miss = sum(not r["met"] for r in off) / len(off)
    on_miss = sum(not r["met"] for r in on) / len(on)
    assert on_miss < off_miss


# --- daemon integration: the admission HBM gate ------------------------------


def _fleet_daemon(tmp_path, build=None, clock=None, **flags):
    argv = [
        "--feature_types", "resnet18", "resnet34",
        "--output_path", str(tmp_path / "out"),
        "--tmp_path", str(tmp_path / "tmp"),
        "--allow_random_init", "--cpu",
        "--heartbeat_s", "0",
    ]
    for k, v in flags.items():
        argv += [f"--{k}"] + ([str(v)] if v is not True else [])
    scfg = parse_serve_args(argv)

    class Toy(ServeToy):
        built = 0

    kw = {"build": build or Toy}
    if clock is not None:
        kw["clock"] = clock
    return ServeDaemon(scfg, **kw), Toy


def _drain_inline(d):
    for g in d.batcher.take_ready(now=float("inf")):
        d.batcher._run_group(g)


def _events(d):
    return [
        r for r in faults.iter_manifest_records(requests_root(d.cfg.output_path))
        if r.get("event")
    ]


def test_daemon_gate_preempts_resident_for_overcommit_burst(tmp_path, serve_videos):
    d, _ = _fleet_daemon(
        tmp_path, preempt="on", hbm_budget_bytes=1000,
        preempt_min_residency_s=0, preempt_cooldown_s=0,
    )
    try:
        # price both models as if a chip had compiled them (CPU runs
        # record platform=cpu entries, which project nothing)
        d.ledger.record("resnet18", "resnet", "64x48", "queue", "tpu",
                        {"memory": {"argument_bytes": 800}})
        d.ledger.record("resnet34", "resnet", "64x48", "queue", "tpu",
                        {"memory": {"argument_bytes": 500}})
        d.submit({"feature_type": "resnet18", "video_path": serve_videos[0],
                  "id": "w1"}, source="local")
        _drain_inline(d)
        assert set(d.pool.feature_types()) == {"resnet18"}
        # resnet34 needs 500 beside resnet18's 800 in a 1000 budget:
        # overcommit -> the idle resident is preempted, not the burst 503d
        d.submit({"feature_type": "resnet34", "video_path": serve_videos[1],
                  "id": "b1"}, source="local")
        _drain_inline(d)
        assert d.tracker.get("b1")["state"] == "done"
        assert "resnet18" not in d.pool.feature_types()
        assert d._breaker("resnet18").state() == "open"  # tripped teardown
        counters = d.telemetry.metrics.snapshot()["counters"]
        assert counters["preemptions.resnet18"] == 1
        assert [e["event"] for e in _events(d) if e["event"] == "preempted"] \
            == ["preempted"]
        assert d.status()["preemptor"]["preemptions"] == 1
    finally:
        d.shutdown()


def test_daemon_gate_rejects_when_residents_protected(tmp_path, serve_videos):
    """Min-residency guard at the daemon level: a just-built resident is
    not preemptible, so the burst is refused with the ledger numbers in
    the error and a durable rejected record."""
    d, _ = _fleet_daemon(
        tmp_path, preempt="on", hbm_budget_bytes=1000,
        preempt_min_residency_s=3600, preempt_cooldown_s=0,
    )
    try:
        d.ledger.record("resnet18", "resnet", "64x48", "queue", "tpu",
                        {"memory": {"argument_bytes": 800}})
        d.ledger.record("resnet34", "resnet", "64x48", "queue", "tpu",
                        {"memory": {"argument_bytes": 500}})
        d.submit({"feature_type": "resnet18", "video_path": serve_videos[0],
                  "id": "w1"}, source="local")
        _drain_inline(d)
        with pytest.raises(ModelUnavailable) as ei:
            d.submit({"feature_type": "resnet34",
                      "video_path": serve_videos[1], "id": "b1"},
                     source="local")
        assert "cannot fit" in str(ei.value)
        rec = d.tracker.get("b1")
        assert rec["state"] == "rejected" and "cannot fit" in rec["message"]
        assert set(d.pool.feature_types()) == {"resnet18"}  # untouched
    finally:
        d.shutdown()


def test_daemon_preemption_rollback_on_beneficiary_build_failure(
    tmp_path, serve_videos
):
    """The gamble fails: the beneficiary's build crashes after the victim
    was sacrificed — the victim's breaker is force-closed so the
    pre-preemption resident set rebuilds on demand."""

    class Toy(ServeToy):
        built = 0

    def build(cfg):
        if cfg.feature_type == "resnet34":
            raise RuntimeError("RESOURCE_EXHAUSTED: hbm")
        return Toy(cfg)

    d, _ = _fleet_daemon(
        tmp_path, build=build, preempt="on", hbm_budget_bytes=1000,
        preempt_min_residency_s=0, preempt_cooldown_s=0,
    )
    try:
        d.ledger.record("resnet18", "resnet", "64x48", "queue", "tpu",
                        {"memory": {"argument_bytes": 800}})
        d.ledger.record("resnet34", "resnet", "64x48", "queue", "tpu",
                        {"memory": {"argument_bytes": 500}})
        d.submit({"feature_type": "resnet18", "video_path": serve_videos[0],
                  "id": "w1"}, source="local")
        _drain_inline(d)
        d.submit({"feature_type": "resnet34", "video_path": serve_videos[1],
                  "id": "b1"}, source="local")
        _drain_inline(d)  # build crashes -> rollback
        assert d.tracker.get("b1")["state"] == "failed"
        assert d._breaker("resnet18").state() == "closed"  # handed back
        assert [e["event"] for e in _events(d)
                if e["event"] == "preemption_rollback"]
        # the victim serves again immediately: rebuild on demand
        d.submit({"feature_type": "resnet18", "video_path": serve_videos[2],
                  "id": "w2"}, source="local")
        _drain_inline(d)
        assert d.tracker.get("w2")["state"] == "done"
    finally:
        d.shutdown()


# --- breaker probe-slot leak (ISSUE 18 satellite bugfix) ---------------------


def test_half_open_probe_verdict_lands_before_tracker_writes(tmp_path, serve_videos):
    """Regression: a re-warm failure whose tracker.finish ALSO raises
    (fault injection, full disk) used to leave the half-open probe slot
    claimed forever — this model 503d until restart. The verdict must
    land first: the breaker re-opens (would-refire) and a later probe
    slot is claimable."""
    clk = FakeClock()
    fail = {"build": False}

    class Toy(ServeToy):
        built = 0

    def build(cfg):
        if fail["build"]:
            raise RuntimeError("weights host unreachable")
        return Toy(cfg)

    d, _ = _fleet_daemon(
        tmp_path, build=build, clock=clk,
        breaker_threshold=1, breaker_cooldown_s=30,
    )
    try:
        fail["build"] = True
        d.submit({"feature_type": "resnet18", "video_path": serve_videos[0],
                  "id": "r1"}, source="local")
        _drain_inline(d)  # build fails -> breaker opens (threshold 1)
        breaker = d._breaker("resnet18")
        assert breaker.state() == "open"
        clk.t += 31.0
        assert breaker.state() == "half_open"
        real_finish = d.tracker.finish

        def finish_raises(*a, **k):
            raise RuntimeError("tracker write failed")

        d.tracker.finish = finish_raises
        d.submit({"feature_type": "resnet18", "video_path": serve_videos[1],
                  "id": "r2"}, source="local")
        try:
            _drain_inline(d)  # probe build fails AND the tracker raises
        except RuntimeError:
            pass
        finally:
            d.tracker.finish = real_finish
        # the verdict landed before the tracker crash: re-opened, and the
        # slot is NOT leaked — after the cooldown the next group can probe
        assert breaker.state() == "open"
        clk.t += 31.0
        assert breaker.state() == "half_open"
        assert breaker.try_probe() is True
        breaker.record_ignored()  # release the slot we just claimed
    finally:
        d.shutdown()


# --- hit-rate-aware shedding (ISSUE 18 satellite) ----------------------------


def test_shed_likely_cache_miss_when_saturated(tmp_path, serve_videos):
    d, _ = _fleet_daemon(
        tmp_path, shed_watermark=0.5, max_queue=2,
        cache_dir=str(tmp_path / "cache"),
    )
    try:
        # a hot cache: hits dominate, so shedding known misses preserves
        # admission room for the ~ms hit path
        d.telemetry.metrics.inc("cache_hit.resnet18", 25)
        d.submit({"feature_type": "resnet18", "video_path": serve_videos[0],
                  "id": "q1"}, source="local")  # queued, not drained
        assert d.batcher.depth() == 1  # >= 0.5 * max_queue
        with pytest.raises(QueueFull) as ei:
            d.submit({"feature_type": "resnet18",
                      "video_path": serve_videos[1], "id": "q2"},
                     source="local")
        assert "missed the feature cache" in str(ei.value)
        rec = d.tracker.get("q2")
        assert rec["state"] == "rejected"
        counters = d.telemetry.metrics.snapshot()["counters"]
        assert counters["requests_shed.likely_cache_miss"] == 1
        # the first request still drains normally
        _drain_inline(d)
        assert d.tracker.get("q1")["state"] == "done"
    finally:
        d.shutdown()


def test_shed_disabled_on_cold_or_miss_heavy_cache(tmp_path, serve_videos):
    d, _ = _fleet_daemon(
        tmp_path, shed_watermark=0.5, max_queue=4,
        cache_dir=str(tmp_path / "cache"),
    )
    try:
        d.submit({"feature_type": "resnet18", "video_path": serve_videos[0],
                  "id": "q1"}, source="local")
        assert d.batcher.depth() >= 0.5 * 4 / 2  # saturation irrelevant:
        # the cache is cold (< 20 lookups), so nothing is shed
        d.submit({"feature_type": "resnet18", "video_path": serve_videos[1],
                  "id": "q2"}, source="local")
        assert d.tracker.get("q2")["state"] == "queued"
        _drain_inline(d)
    finally:
        d.shutdown()


# --- exposition: the fleet metric families -----------------------------------


def test_exposition_fleet_series():
    m = MetricsRegistry()
    m.inc("requests_shed.likely_cache_miss", 3)
    m.inc("requests_shed.queue_full", 2)
    m.inc("preemptions.resnet18", 1)
    m.inc("lease_steals.resnet18", 4)
    m.inc("lease_expired", 4)
    m.set_gauge("replica_up.rA", 1)
    m.set_gauge("replica_up.rB", 0)
    text = render_families(families_from_snapshot(m.snapshot()))
    assert validate_exposition(text) == []
    assert ('vft_requests_total{shed_reason="likely_cache_miss",'
            'state="shed"} 3') in text
    assert 'vft_requests_total{shed_reason="queue_full",state="shed"} 2' in text
    assert 'vft_preemptions_total{feature_type="resnet18"} 1' in text
    assert 'vft_lease_steals_total{feature_type="resnet18"} 4' in text
    assert "vft_lease_expired_total 4" in text
    assert 'vft_replica_up{replica="rA"} 1' in text
    assert 'vft_replica_up{replica="rB"} 0' in text


# --- replica registry --------------------------------------------------------


def test_replica_registry_beat_live_retire(tmp_path):
    out = str(tmp_path / "out")
    ra = ReplicaRegistry(out, "rA")
    rb = ReplicaRegistry(out, "rB")
    ra.beat()
    rb.beat()
    assert ra.live(5.0) == {"rA", "rB"}
    # a stale heartbeat ages out of the live set (backdated mtime)
    old = time.time() - 100
    os.utime(rb.path, (old, old))
    assert ra.live(5.0) == {"rA"}
    # timeout <= 0: liveness is never inferred, everyone counts as live
    assert ra.live(0.0) == {"rA", "rB"}
    rb.retire()
    assert ra.live(0.0) == {"rA"}


# --- spool leases + work stealing --------------------------------------------


def _spool_file(spool, name, ft="resnet18", video="/v.mp4", rid=None):
    os.makedirs(spool, exist_ok=True)
    payload = {"feature_type": ft, "video_path": video}
    if rid:
        payload["id"] = rid
    tmp = os.path.join(spool, f".{name}.tmp")
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(payload, fh)
    os.replace(tmp, os.path.join(spool, name))
    return os.path.join(spool, name)


def test_lease_held_until_terminal_then_released(tmp_path, serve_videos):
    d, _ = _fleet_daemon(tmp_path)
    spool = str(tmp_path / "spool")
    try:
        w = SpoolWatcher(
            d, spool, clock=FakeClock(), replica_id="rA",
            lease_timeout_s=5.0,
            registry=ReplicaRegistry(d.cfg.output_path, "rA"),
        )
        _spool_file(spool, "s1.json", video=serve_videos[0], rid="s1")
        assert w.poll_once() == 1
        claim = os.path.join(spool, "s1.json.claim.rA")
        assert os.path.exists(claim)  # the lease: held while in flight
        # the next poll heartbeats the lease (mtime refresh)
        old = time.time() - 100
        os.utime(claim, (old, old))
        w.poll_once()
        assert time.time() - os.stat(claim).st_mtime < 50
        _drain_inline(d)
        assert d.tracker.get("s1")["state"] == "done"
        w.poll_once()  # terminal: the lease is released
        assert not os.path.exists(claim)
        assert w._inflight == {}
    finally:
        d.shutdown()


def test_lease_stall_fault_skips_heartbeat(tmp_path, serve_videos):
    d, _ = _fleet_daemon(tmp_path)
    spool = str(tmp_path / "spool")
    try:
        w = SpoolWatcher(
            d, spool, clock=FakeClock(), replica_id="rA",
            lease_timeout_s=5.0,
            registry=ReplicaRegistry(d.cfg.output_path, "rA"),
        )
        _spool_file(spool, "s1.json", video=serve_videos[0], rid="s1")
        w.poll_once()
        claim = os.path.join(spool, "s1.json.claim.rA")
        old = time.time() - 100
        os.utime(claim, (old, old))
        faults.install_injector(["lease_stall:error:1"])
        w.poll_once()
        # wedged replica: the lease mtime was NOT refreshed, so peers
        # will see it age out and steal the work
        assert time.time() - os.stat(claim).st_mtime > 50
    finally:
        d.shutdown()


def test_stale_foreign_claim_stolen_with_warm_affinity(tmp_path, serve_videos):
    d, _ = _fleet_daemon(tmp_path)
    spool = str(tmp_path / "spool")
    try:
        # warm resnet18 locally: steals of warm models use the base
        # threshold, cold ones wait COLD_STEAL_FACTOR x longer
        d.submit({"feature_type": "resnet18", "video_path": serve_videos[0],
                  "id": "w1"}, source="local")
        _drain_inline(d)
        w = SpoolWatcher(
            d, spool, clock=FakeClock(), replica_id="rA",
            lease_timeout_s=5.0,
            registry=ReplicaRegistry(d.cfg.output_path, "rA"),
        )
        warm_claim = _spool_file(
            spool, "s2.json.claim.rB", ft="resnet18",
            video=serve_videos[1], rid="s2",
        )
        cold_claim = _spool_file(
            spool, "s3.json.claim.rB", ft="resnet34",
            video=serve_videos[2], rid="s3",
        )
        # rB has no registry heartbeat at all: dead. Both claims aged 6s:
        # past the warm threshold (5s), inside the cold one (7.5s)
        old = time.time() - 6
        os.utime(warm_claim, (old, old))
        os.utime(cold_claim, (old, old))
        assert w.poll_once() == 1  # the warm steal re-admitted s2
        assert not os.path.exists(warm_claim)
        assert os.path.exists(cold_claim)  # cold: warm peers get first crack
        counters = d.telemetry.metrics.snapshot()["counters"]
        assert counters["lease_expired"] == 1
        assert counters["lease_steals.resnet18"] == 1
        assert [e for e in _events(d) if e["event"] == "lease_stolen"]
        # past the cold threshold the cold claim is stolen too
        old = time.time() - 8
        os.utime(cold_claim, (old, old))
        assert w.poll_once() == 1
        assert not os.path.exists(cold_claim)
        _drain_inline(d)
        assert d.tracker.get("s2")["state"] == "done"
        assert d.tracker.get("s3")["state"] == "done"
    finally:
        d.shutdown()


def test_live_owners_claim_is_never_stolen(tmp_path, serve_videos):
    d, _ = _fleet_daemon(tmp_path)
    spool = str(tmp_path / "spool")
    try:
        ReplicaRegistry(d.cfg.output_path, "rB").beat()  # rB is alive
        w = SpoolWatcher(
            d, spool, clock=FakeClock(), replica_id="rA",
            lease_timeout_s=5.0,
            registry=ReplicaRegistry(d.cfg.output_path, "rA"),
        )
        claim = _spool_file(
            spool, "s1.json.claim.rB", video=serve_videos[0], rid="s1",
        )
        old = time.time() - 100  # mtime stale, but the OWNER is live
        os.utime(claim, (old, old))
        assert w.poll_once() == 0
        assert os.path.exists(claim)
    finally:
        d.shutdown()


# --- fleet reconcile: foreign replicas ---------------------------------------


def test_reconcile_skips_live_peer_reclaims_dead(tmp_path):
    out = str(tmp_path / "out")
    spool = str(tmp_path / "spool")
    tb = RequestTracker(out, replica_id="rB")
    tb.admit(ExtractionRequest(feature_type="toy", video_path="/v.mp4",
                               id="q1", source="spool"))
    ta = RequestTracker(out, replica_id="rA")
    # rB is live: its in-flight request is not a casualty
    res = ta.reconcile(spool, live_replicas={"rB"}, require_replica=True)
    assert res == {"requeued": 0, "interrupted": 0}
    assert not os.path.exists(os.path.join(spool, "q1.json"))
    # rB is dead: the request is re-queued into the spool
    res = ta.reconcile(spool, live_replicas=set(), require_replica=True)
    assert res == {"requeued": 1, "interrupted": 0}
    assert os.path.exists(os.path.join(spool, "q1.json"))


def test_reconcile_require_replica_skips_unattributed(tmp_path):
    out = str(tmp_path / "out")
    t0 = RequestTracker(out)  # legacy: no replica attribution
    t0.admit(ExtractionRequest(feature_type="toy", video_path="/v.mp4",
                               id="q1", source="local"))
    ta = RequestTracker(out, replica_id="rA")
    # the runtime fleet sweep must NOT disposition unattributed records —
    # mid-flight they are indistinguishable from a live legacy request
    res = ta.reconcile(live_replicas={"rA"}, require_replica=True)
    assert res == {"requeued": 0, "interrupted": 0}
    # the startup pass (no require_replica) may: it runs before sources
    res = ta.reconcile(live_replicas={"rA"})
    assert res == {"requeued": 0, "interrupted": 1}
    assert ta.get("q1")["state"] == "failed"


# --- cross-host skip-probe dedup (ISSUE 18 satellite) ------------------------


def test_claim_skip_record_single_winner(tmp_path):
    root = str(tmp_path / "out")
    assert faults.claim_skip_record(root, "/v/a.mp4") is True
    assert faults.claim_skip_record(root, "/v/a.mp4") is False  # claimed
    assert faults.claim_skip_record(root, "/v/b.mp4") is True  # independent


def test_claim_skip_record_two_processes_one_winner(tmp_path):
    root = str(tmp_path / "out")
    script = textwrap.dedent(
        """
        import sys
        from video_features_tpu.runtime import faults
        print(faults.claim_skip_record(sys.argv[1], "/shared/v.mp4"))
        """
    )
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(__file__)))
    procs = [
        subprocess.Popen([sys.executable, "-c", script, root],
                         stdout=subprocess.PIPE, env=env)
        for _ in range(2)
    ]
    outs = sorted(p.communicate(timeout=60)[0].decode().strip() for p in procs)
    assert outs == ["False", "True"]  # exactly one winner across processes


def test_resume_skip_recorded_once_across_repeat_resumes(toy_videos, tmp_path):
    cfg = _cfg(toy_videos[:2], tmp_path)
    ToyExtractor(cfg)()
    for _ in range(2):  # two replicas/runs resuming one shared output root
        ToyExtractor(_cfg(toy_videos[:2], tmp_path, resume=True))()
    skips = [
        r for r in faults.iter_manifest_records(cfg.output_path)
        if r.get("status") == "skipped"
    ]
    # both done videos probed on BOTH resume passes, recorded ONCE each
    assert sorted(r["video"] for r in skips) == sorted(toy_videos[:2])


# --- chaos drill: SIGKILLed replica, surviving fleet recovers ----------------


_CHAOS_VICTIM = textwrap.dedent(
    """
    import os, sys, time
    from video_features_tpu.runtime import faults
    from video_features_tpu.serve.lifecycle import (
        ReplicaRegistry, RequestTracker, parse_request,
    )
    from video_features_tpu.serve.sources import SpoolWatcher

    out, spool, rid = sys.argv[1:4]

    class FakePool:
        def feature_types(self):
            return {"toy"}

    class VictimDaemon:
        # admits requests but never finishes them: everything this
        # replica claims is in flight when the kill stage fires
        def __init__(self):
            self.tracker = RequestTracker(out, replica_id=rid)
            self.pool = FakePool()
            self.telemetry = None

        def submit(self, payload, source):
            return self.tracker.admit(parse_request(payload, source))

    d = VictimDaemon()
    reg = ReplicaRegistry(out, rid)
    w = SpoolWatcher(d, spool, replica_id=rid, lease_timeout_s=1.0,
                     registry=reg)
    # pinned cadence: the SECOND poll SIGKILLs this process mid-drill —
    # after the first poll claimed the whole burst (no cleanup, no flush)
    faults.install_injector(["replica_kill:kill:2"])
    w.poll_once()
    while True:
        w.poll_once()
        time.sleep(0.05)
    """
)


@pytest.mark.chaos
def test_chaos_replica_kill_survivors_steal_and_finish(tmp_path):
    """The ISSUE 18 acceptance drill: a replica SIGKILLs itself (via the
    ``replica_kill`` fault stage) holding leases on a whole burst; two
    survivors reclaim the stale leases and finish every request — all
    terminal, zero duplicated feature writes, bit-identical payloads."""
    out = str(tmp_path / "out")
    spool = str(tmp_path / "spool")
    feat = str(tmp_path / "features")
    os.makedirs(feat, exist_ok=True)
    n = 6
    for i in range(n):
        _spool_file(spool, f"job{i}.json", ft="toy",
                    video=f"/media/clip{i}.mp4", rid=f"job{i}")

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(__file__)))
    proc = subprocess.Popen(
        [sys.executable, "-c", _CHAOS_VICTIM, out, spool, "victim"],
        env=env,
    )
    proc.wait(timeout=120)
    assert proc.returncode == -signal.SIGKILL  # the fault stage fired
    claims = glob.glob(os.path.join(spool, "*.claim.victim"))
    assert len(claims) == n  # died holding every lease

    # the shared clock is file mtime: age the victim's heartbeat and
    # leases past the 1s lease timeout without sleeping
    old = time.time() - 30
    for path in claims + [os.path.join(
        requests_root(out), "_replicas", "victim.json"
    )]:
        os.utime(path, (old, old))

    class FakePool:
        def feature_types(self):
            return {"toy"}

    writes = []

    class SurvivorDaemon:
        def __init__(self, rid):
            self.rid = rid
            self.tracker = RequestTracker(out, replica_id=rid)
            self.pool = FakePool()
            self.telemetry = None

        def submit(self, payload, source):
            from video_features_tpu.serve.lifecycle import parse_request

            req = parse_request(payload, source)
            rec = self.tracker.admit(req)
            # deterministic payload + atomic publish: a duplicate write
            # would be bit-identical, but there must not BE one
            data = hashlib.sha256(req.video_path.encode()).hexdigest().encode()
            dest = os.path.join(feat, f"{req.id}.bin")
            tmp = f"{dest}.{self.rid}.tmp"
            with open(tmp, "wb") as fh:
                fh.write(data)
            os.replace(tmp, dest)
            writes.append((self.rid, req.id))
            self.tracker.finish(req, "done", features=[dest])
            return rec

    survivors = []
    for rid in ("sA", "sB"):
        d = SurvivorDaemon(rid)
        reg = ReplicaRegistry(out, rid)
        reg.beat()
        survivors.append((d, SpoolWatcher(
            d, spool, replica_id=rid, lease_timeout_s=1.0, registry=reg,
        )))
    for _ in range(3):  # reclaim pass + claim/admit pass + lease release
        for _, w in survivors:
            w.poll_once()

    # every request terminal 'done' (result files are fleet-shared)
    for i in range(n):
        rec = survivors[0][0].tracker.get(f"job{i}")
        assert rec is not None and rec["state"] == "done", rec
    # zero duplicated feature writes, each with the expected bytes
    assert sorted(rid for _, rid in writes) == [f"job{i}" for i in range(n)]
    files = sorted(os.listdir(feat))
    assert files == [f"job{i}.bin" for i in range(n)]
    for i in range(n):
        with open(os.path.join(feat, f"job{i}.bin"), "rb") as fh:
            expect = hashlib.sha256(
                f"/media/clip{i}.mp4".encode()
            ).hexdigest().encode()
            assert fh.read() == expect
    # the spool is fully drained: no jsons, no leases left behind
    assert [f for f in os.listdir(spool) if not f.startswith(".")] == []
    # the steal trail is durable
    stolen = [
        r for r in faults.iter_manifest_records(requests_root(out))
        if r.get("event") == "lease_stolen"
    ]
    assert len(stolen) == n
    assert {r["from_replica"] for r in stolen} == {"victim"}
    # and the fleet sweep has nothing left to disposition
    res = survivors[0][0].tracker.reconcile(
        spool, live_replicas={"sA", "sB"}, require_replica=True
    )
    assert res == {"requeued": 0, "interrupted": 0}
