"""VGGish: log-mel frontend properties, VGG parity vs a torch oracle,
postprocessor math, end-to-end wav extraction.

The net oracle is a torch VGG with torchvggish state-dict names
(features.{0,3,6,8,11,13}, embeddings.{0,2,4}); the frontend's property
tests here (shapes, silence, pure tones hitting the right mel band) are
complemented by tests/test_reference_parity.py, which checks the mel
pipeline and the PCA postprocessor bit-for-bit against the reference's
pure-NumPy sources (mel_features.py, vggish_postprocess.py).
"""

import numpy as np
import pytest
import torch
from torch import nn

import jax.numpy as jnp

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.models.vggish import mel
from video_features_tpu.models.vggish.convert import convert_pca_params, convert_state_dict
from video_features_tpu.models.vggish.model import build, postprocess


# --- frontend ---------------------------------------------------------------

@pytest.mark.quick
def test_log_mel_shapes_and_silence():
    # 1 s of silence at 16 kHz: 98 STFT frames -> one (96, 64) example
    examples = mel.waveform_to_examples(np.zeros(16000, np.float32), 16000)
    assert examples.shape == (1, 96, 64)
    np.testing.assert_allclose(examples, np.log(0.01), atol=1e-5)


@pytest.mark.quick
def test_pure_tone_lights_matching_mel_band():
    t = np.arange(16000 * 2) / 16000.0
    for hz in (440.0, 1000.0, 3000.0):
        tone = np.sin(2 * np.pi * hz * t).astype(np.float32)
        examples = mel.waveform_to_examples(tone, 16000)
        band_energy = examples.mean(axis=(0, 1))  # (64,)
        # center frequencies of the 64 bands on the HTK mel scale
        edges = np.linspace(mel.hertz_to_mel(125.0), mel.hertz_to_mel(7500.0), 66)
        centers_hz = 700.0 * (np.exp(edges[1:-1] / 1127.0) - 1.0)
        expected = np.argmin(np.abs(centers_hz - hz))
        assert abs(int(band_energy.argmax()) - expected) <= 1


@pytest.mark.quick
def test_frame_drops_ragged_tail():
    framed = mel.frame(np.arange(10.0), window_length=4, hop_length=3)
    np.testing.assert_array_equal(framed, [[0, 1, 2, 3], [3, 4, 5, 6], [6, 7, 8, 9]])


@pytest.mark.quick
def test_resample_tone_preserved():
    from video_features_tpu.io.audio import resample

    t = np.arange(44100) / 44100.0
    tone = np.sin(2 * np.pi * 440 * t).astype(np.float32)
    out = resample(tone, 44100, 16000)
    assert abs(out.shape[0] - 16000) <= 1
    spec = np.abs(np.fft.rfft(out))
    assert abs(spec.argmax() - 440) <= 2  # 1 Hz bins


# --- net --------------------------------------------------------------------

class TorchVGGish(nn.Module):
    def __init__(self):
        super().__init__()
        layers, in_ch = [], 1
        for v in (64, "M", 128, "M", 256, 256, "M", 512, 512, "M"):
            if v == "M":
                layers.append(nn.MaxPool2d(2, 2))
            else:
                layers += [nn.Conv2d(in_ch, v, 3, padding=1), nn.ReLU(True)]
                in_ch = v
        self.features = nn.Sequential(*layers)
        self.embeddings = nn.Sequential(
            nn.Linear(512 * 4 * 6, 4096), nn.ReLU(True),
            nn.Linear(4096, 4096), nn.ReLU(True),
            nn.Linear(4096, 128), nn.ReLU(True),
        )

    def forward(self, x):
        x = self.features(x)
        x = x.permute(0, 2, 3, 1).reshape(x.size(0), -1)
        return self.embeddings(x)


def test_vggish_matches_torch_oracle():
    torch.manual_seed(0)
    oracle = TorchVGGish().eval()
    sd = {k: v.numpy() for k, v in oracle.state_dict().items()}
    params = convert_state_dict(sd)

    x = np.random.RandomState(0).randn(2, 96, 64, 1).astype(np.float32)
    with torch.no_grad():
        ref = oracle(torch.from_numpy(np.transpose(x, (0, 3, 1, 2)))).numpy()
    out = build().apply({"params": params}, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-4)


def test_converter_rejects_unconsumed():
    torch.manual_seed(0)
    sd = {k: v.numpy() for k, v in TorchVGGish().state_dict().items()}
    sd["stray.weight"] = np.zeros(3, np.float32)
    with pytest.raises(ValueError, match="unconsumed"):
        convert_state_dict(sd)


@pytest.mark.quick
def test_postprocessor_matches_torch_math():
    rng = np.random.RandomState(0)
    emb = rng.randn(5, 128).astype(np.float32)
    eig = rng.randn(128, 128).astype(np.float32) * 0.1
    means = rng.randn(128).astype(np.float32)

    t = torch.mm(torch.from_numpy(eig), torch.from_numpy(emb).t() - torch.from_numpy(means).reshape(-1, 1)).t()
    t = torch.clamp(t, -2.0, 2.0)
    ref = torch.round((t - (-2.0)) * (255.0 / 4.0)).numpy()

    pca = convert_pca_params({"pca_eigen_vectors": eig, "pca_means": means.reshape(-1, 1)})
    out = postprocess(jnp.asarray(emb), {k: jnp.asarray(v) for k, v in pca.items()})
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-4)
    assert float(np.asarray(out).min()) >= 0 and float(np.asarray(out).max()) <= 255


# --- end to end -------------------------------------------------------------

@pytest.fixture(scope="module")
def sample_wav(tmp_path_factory):
    from scipy.io import wavfile

    path = str(tmp_path_factory.mktemp("audio") / "chirp.wav")
    t = np.arange(16000 * 3) / 16000.0
    sig = 0.5 * np.sin(2 * np.pi * (200 + 300 * t) * t)
    wavfile.write(path, 16000, (sig * 32767).astype(np.int16))
    return path


def test_extract_vggish_end_to_end(sample_wav, tmp_path):
    from video_features_tpu.models.vggish.extract_vggish import ExtractVGGish

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="vggish",
        video_paths=[sample_wav],
        on_extraction="save_numpy",
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
        cpu=True,
    )
    ex = ExtractVGGish(cfg)
    ex([0])
    import pathlib

    saved = {p.name: p for p in pathlib.Path(tmp_path / "out").rglob("*.npy")}
    assert set(saved) == {"chirp_vggish.npy"}
    feats = np.load(saved["chirp_vggish.npy"])
    # 3 s of audio -> 3 x 0.96 s examples
    assert feats.shape == (3, 128)
    assert np.isfinite(feats).all()
    assert (feats >= 0).all()  # final ReLU


def test_vggish_mesh_matches_single_device(sample_wav, tmp_path):
    """--sharding mesh (pure DP over the example batch) matches the
    single-device run. Not byte-compared: the mesh pads the batch to a
    data-divisible row count, and a different batch shape reassociates
    XLA's conv reductions at the ulp level."""
    import jax

    from video_features_tpu.models.vggish.extract_vggish import ExtractVGGish
    from video_features_tpu.parallel.sharding import make_mesh

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="vggish",
        video_paths=[sample_wav],
        tmp_path=str(tmp_path / "tmp"),
        output_path=str(tmp_path / "out"),
    )

    def run(device):
        ex = ExtractVGGish(cfg, external_call=True)
        ex.progress.disable = True
        return ex([0], device=device)[0]["vggish"]

    single = run(jax.devices()[0])
    mesh = make_mesh(jax.devices(), model=1)
    np.testing.assert_allclose(run(mesh), single, atol=1e-5)
    assert single.shape == (3, 128)


@pytest.mark.quick
def test_resampler_matches_resampy_kaiser_best_end_to_end():
    """VERDICT r4 next #7: a number, not a claim. The reference resamples
    with resampy's kaiser_best windowed sinc (ref vggish_src/
    vggish_input.py:27-71); resampy is uninstallable here (zero egress),
    so the oracle is tests/resampy_kaiser.py — the published kaiser_best
    algorithm re-derived per-sample in NumPy. The r4-era scipy
    resample_poly substitute measured a 2.6e-3 relative-L2 drift on
    final VGGish embeddings with this very harness — past the 1e-3
    budget — which is why io/audio.py now implements kaiser_best
    natively (phase-decomposed polyphase matmul). This test pins BOTH
    numbers: the product resampler's parity with the oracle through the
    full pipeline, and the recorded scipy divergence that motivated it."""
    from scipy.signal import resample_poly

    from video_features_tpu.io.audio import resample, to_mono
    from video_features_tpu.models.vggish.mel import (
        SAMPLE_RATE,
        frame,
        log_mel_spectrogram,
    )
    from video_features_tpu.models.vggish.model import build, init_params
    from resampy_kaiser import resample_kaiser_best

    sr = 44100
    rng = np.random.RandomState(0)
    t = np.arange(int(1.5 * sr)) / sr
    wave = (
        0.4 * np.sin(2 * np.pi * 440 * t)
        + 0.2 * np.sin(2 * np.pi * 1870 * t)
        + 0.15 * np.sin(2 * np.pi * t * (300 + 2000 * t))  # chirp
        + 0.05 * rng.randn(len(t))
    ).astype(np.float32)

    model, params = build(), init_params()

    def embeddings(wave16k):
        log_mel = log_mel_spectrogram(wave16k.astype(np.float64), SAMPLE_RATE)
        ex = frame(log_mel, 96, 96).astype(np.float32)[..., None]
        return np.asarray(model.apply({"params": params}, jnp.asarray(ex)))

    ref = embeddings(resample_kaiser_best(wave, sr, SAMPLE_RATE))
    ours = embeddings(resample(to_mono(wave), sr, SAMPLE_RATE))
    assert ours.shape == ref.shape == (1, 128)
    rel = float(np.linalg.norm(ours - ref) / np.linalg.norm(ref))
    # product resampler == reference algorithm, through the whole model
    assert rel < 1e-5, f"embedding relative L2 vs kaiser oracle: {rel}"

    # waveform-level parity with the oracle across down- AND up-sampling
    # ratios (8k->16k exercises the scale=1 branch), on a non-divisible
    # length that pins resampy's FLOOR output sizing (r5 review: ceil
    # emitted one extra sample and could shift the 0.96 s frame count).
    # The interpolation machinery is independently derived (per-sample
    # loop vs phase-bank matmul); the sinc TABLE is shared, so its
    # properties are asserted separately below.
    probe = rng.randn(15442).astype(np.float32)
    for rate in (44100, 48000, 22050, 8000):
        a = resample(probe, rate, SAMPLE_RATE)
        b = resample_kaiser_best(probe, rate, SAMPLE_RATE)
        assert len(a) == len(b) == (15442 * SAMPLE_RATE) // rate, rate
        assert float(np.abs(a - b).max()) < 1e-6, rate

    # the shared kaiser_best table, validated against the algorithm's
    # mathematical properties rather than a copy of itself: unit DC gain
    # at tap 0 x rolloff, zeros at (scaled) integer crossings, and the
    # advertised ~-96 dB kaiser stopband
    from resampy_kaiser import _sinc_window, NUM_ZEROS, PRECISION, ROLLOFF

    win = _sinc_window()
    num_bits = 2 ** PRECISION
    assert win[0] == pytest.approx(ROLLOFF)
    # the sinc's true zeros sit at taps k/rolloff (NOT integer taps —
    # the rolled-off cutoff shifts them); the table must vanish there
    zeros = (np.arange(1, 40) / ROLLOFF * num_bits).round().astype(int)
    assert np.abs(win[zeros]).max() < 1e-3
    # kaiser envelope decays monotonically toward the tail
    assert abs(win[32 * num_bits]) < abs(win[8 * num_bits]) < abs(win[num_bits])
    # and the advertised kaiser_best stopband: < -80 dB past the
    # transition band of the full symmetric filter
    spectrum = np.abs(np.fft.rfft(np.concatenate([win[::-1], win[1:]]), 1 << 18))
    spectrum /= spectrum[0]
    stop = spectrum[int(1.3 / NUM_ZEROS * (1 << 17)):]
    assert 20 * np.log10(stop.max() + 1e-12) < -80

    # the recorded motivation: scipy's polyphase (the r4-era default)
    # diverges past the 1e-3 budget on embeddings — if this ever DROPS
    # below budget, the native implementation could be reconsidered
    g = np.gcd(sr, SAMPLE_RATE)
    scipy_16k = resample_poly(
        to_mono(wave), SAMPLE_RATE // g, sr // g, axis=0
    ).astype(np.float32)
    scipy_rel = float(
        np.linalg.norm(embeddings(scipy_16k) - ref) / np.linalg.norm(ref)
    )
    assert scipy_rel > 1e-3, (
        f"scipy polyphase now within budget ({scipy_rel:.2e}) — "
        "PARITY.md's rationale for the native kaiser resampler is stale"
    )
    print(f"\nembedding rel L2: native kaiser {rel:.2e}, "
          f"scipy polyphase {scipy_rel:.2e}")


def test_resample_matches_real_resampy_when_installed():
    """The direct cross-check the [oracle] extra exists for: on a
    networked host with `pip install .[oracle]`, our native resampler is
    compared against resampy ITSELF (not the offline re-derivation)."""
    resampy = pytest.importorskip("resampy")

    from video_features_tpu.io.audio import resample

    rng = np.random.RandomState(0)
    x = rng.randn(15442).astype(np.float32)
    for rate in (44100, 48000, 22050, 8000):
        ours = resample(x, rate, 16000)
        theirs = resampy.resample(x.astype(np.float64), rate, 16000)
        assert len(ours) == len(theirs), rate
        assert float(np.abs(ours - theirs).max()) < 1e-6, rate
