"""PWC-Net end-to-end extraction.

Model parity lives in tests/test_reference_parity.py, which oracles
against the actual reference source (/root/reference/models/pwc/
pwc_src/pwc_net.py, cupy correlation monkeypatched by the XLA op) —
the round-1 builder-written torch mirror was deleted in its favor.
"""

import numpy as np
import pytest
import torch

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.models.pwc.convert import convert_state_dict


def test_converter_rejects_unconsumed():
    from test_reference_parity import _load_reference_pwc

    pwc_mod = _load_reference_pwc()
    torch.manual_seed(0)
    sd = {k: v.numpy() for k, v in pwc_mod.PWCNet().state_dict().items()}
    sd["stray.weight"] = np.zeros(3, np.float32)
    with pytest.raises(ValueError, match="unconsumed"):
        convert_state_dict(sd)


def test_extract_pwc_end_to_end(sample_video, tmp_path):
    from video_features_tpu.models.pwc.extract_pwc import ExtractPWC

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="pwc",
        video_paths=[sample_video],
        extraction_fps=5.0,  # 60-frame 25fps synth clip -> 12 frames
        batch_size=5,
        on_extraction="save_numpy",
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
        cpu=True,
    )
    ex = ExtractPWC(cfg)
    ex([0])
    import pathlib

    saved = {p.name: p for p in pathlib.Path(tmp_path / "out").rglob("*.npy")}
    assert set(saved) == {"synth_pwc.npy"}
    flow = np.load(saved["synth_pwc.npy"])
    # 12 frames -> 11 pairs, flow at source resolution
    assert flow.shape[0] == 11 and flow.shape[1] == 2
    assert np.isfinite(flow).all()
