"""PWC-Net parity vs a torch oracle + end-to-end extraction.

The oracle is a compact torch reimplementation of sniklaus pytorch-pwc
with state-dict-compatible names (moduleExtractor.module{One..Six}
Sequentials, module{Two..Six} decoders with moduleUpflow/moduleUpfeat
ConvTranspose2d, moduleRefiner.moduleMain) so the converter — including
its ConvTranspose kernel flip — is exercised with random weights.
"""

import numpy as np
import pytest
import torch
import torch.nn.functional as F
from torch import nn

import jax.numpy as jnp

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.models.pwc.convert import convert_state_dict
from video_features_tpu.models.pwc.model import BACKWARD_SCALE, DECODER_IN, build

_ORD = {1: "One", 2: "Two", 3: "Thr", 4: "Fou", 5: "Fiv", 6: "Six"}


def _corr(f1, f2):
    B, C, H, W = f1.shape
    f2p = F.pad(f2, (4, 4, 4, 4))
    planes = [
        (f1 * f2p[:, :, dy : dy + H, dx : dx + W]).mean(1)
        for dy in range(9)
        for dx in range(9)
    ]
    return F.leaky_relu(torch.stack(planes, 1), 0.1)


def _warp(x, flow):
    B, C, H, W = x.shape
    gx = torch.linspace(-1, 1, W).view(1, 1, 1, W).expand(B, 1, H, W)
    gy = torch.linspace(-1, 1, H).view(1, 1, H, 1).expand(B, 1, H, W)
    grid = torch.cat([gx, gy], 1)
    nflow = torch.cat(
        [flow[:, 0:1] / ((W - 1) / 2.0), flow[:, 1:2] / ((H - 1) / 2.0)], 1
    )
    xo = torch.cat([x, torch.ones(B, 1, H, W)], 1)
    out = F.grid_sample(
        xo, (grid + nflow).permute(0, 2, 3, 1), mode="bilinear",
        padding_mode="zeros", align_corners=False,
    )
    mask = (out[:, -1:] > 0.999).float()
    return out[:, :-1] * mask


def _block(i, o):
    return nn.Sequential(
        nn.Conv2d(i, o, 3, 2, 1), nn.LeakyReLU(0.1),
        nn.Conv2d(o, o, 3, 1, 1), nn.LeakyReLU(0.1),
        nn.Conv2d(o, o, 3, 1, 1), nn.LeakyReLU(0.1),
    )


class TorchDecoder(nn.Module):
    def __init__(self, lvl):
        super().__init__()
        self.lvl = lvl
        cur = DECODER_IN[lvl]
        if lvl < 6:
            prev = DECODER_IN[lvl + 1]
            self.moduleUpflow = nn.ConvTranspose2d(2, 2, 4, 2, 1)
            self.moduleUpfeat = nn.ConvTranspose2d(prev + 448, 2, 4, 2, 1)
        for i, ch in enumerate((128, 128, 96, 64, 32)):
            inc = cur + sum((128, 128, 96, 64, 32)[:i])
            setattr(self, f"module{_ORD[i + 1]}",
                    nn.Sequential(nn.Conv2d(inc, ch, 3, 1, 1), nn.LeakyReLU(0.1)))
        self.moduleSix = nn.Sequential(nn.Conv2d(cur + 448, 2, 3, 1, 1))

    def forward(self, f1, f2, prev):
        if prev is None:
            feat = _corr(f1, f2)
        else:
            flow_up = self.moduleUpflow(prev[0])
            feat_up = self.moduleUpfeat(prev[1])
            warped = _warp(f2, flow_up * BACKWARD_SCALE[self.lvl])
            feat = torch.cat([_corr(f1, warped), f1, flow_up, feat_up], 1)
        for i in range(5):
            feat = torch.cat([getattr(self, f"module{_ORD[i + 1]}")(feat), feat], 1)
        return self.moduleSix(feat), feat


class TorchPWC(nn.Module):
    def __init__(self):
        super().__init__()
        ext = nn.Module()
        dims = (3, 16, 32, 64, 96, 128, 196)
        for lvl in range(1, 7):
            setattr(ext, f"module{_ORD[lvl]}", _block(dims[lvl - 1], dims[lvl]))
        self.moduleExtractor = ext
        for lvl in range(2, 7):
            setattr(self, f"module{_ORD[lvl]}", TorchDecoder(lvl))
        main = []
        for i, (inc, ch, dil) in enumerate((
            (565, 128, 1), (128, 128, 2), (128, 128, 4),
            (128, 96, 8), (96, 64, 16), (64, 32, 1),
        )):
            main += [nn.Conv2d(inc, ch, 3, 1, dil, dil), nn.LeakyReLU(0.1)]
        main.append(nn.Conv2d(32, 2, 3, 1, 1))
        ref = nn.Module()
        ref.moduleMain = nn.Sequential(*main)
        self.moduleRefiner = ref

    def forward(self, first, second):
        first = first[:, [2, 1, 0]] / 255.0
        second = second[:, [2, 1, 0]] / 255.0
        B, C, H, W = first.shape
        Hp, Wp = -(-H // 64) * 64, -(-W // 64) * 64
        first = F.interpolate(first, (Hp, Wp), mode="bilinear", align_corners=False)
        second = F.interpolate(second, (Hp, Wp), mode="bilinear", align_corners=False)

        def pyramid(x):
            feats = []
            for lvl in range(1, 7):
                x = getattr(self.moduleExtractor, f"module{_ORD[lvl]}")(x)
                feats.append(x)
            return feats

        p1, p2 = pyramid(first), pyramid(second)
        prev = None
        for lvl in (6, 5, 4, 3, 2):
            prev = getattr(self, f"module{_ORD[lvl]}")(p1[lvl - 1], p2[lvl - 1], prev)
        flow = prev[0] + self.moduleRefiner.moduleMain(prev[1])
        flow = 20.0 * F.interpolate(flow, (H, W), mode="bilinear", align_corners=False)
        flow = torch.cat([flow[:, 0:1] * W / Wp, flow[:, 1:2] * H / Hp], 1)
        return flow


def _torch_oracle(seed=0):
    torch.manual_seed(seed)
    model = TorchPWC()
    model.eval()
    return model


def test_pwc_matches_torch_oracle():
    oracle = _torch_oracle()
    sd = {k: v.numpy() for k, v in oracle.state_dict().items()}
    params = convert_state_dict(sd)

    rng = np.random.RandomState(0)
    frames = rng.uniform(0, 255, size=(3, 96, 128, 3)).astype(np.float32)
    t = torch.from_numpy(np.transpose(frames, (0, 3, 1, 2)))
    with torch.no_grad():
        ref = oracle(t[:-1], t[1:]).numpy()

    flow = build().apply({"params": params}, jnp.asarray(frames))
    flow = np.transpose(np.asarray(flow), (0, 3, 1, 2))
    assert flow.shape == ref.shape == (2, 2, 96, 128)
    assert np.isfinite(ref).all() and np.isfinite(flow).all()
    np.testing.assert_allclose(flow, ref, atol=1e-3, rtol=1e-4)


def test_converter_rejects_unconsumed():
    sd = {k: v.numpy() for k, v in _torch_oracle().state_dict().items()}
    sd["stray.weight"] = np.zeros(3, np.float32)
    with pytest.raises(ValueError, match="unconsumed"):
        convert_state_dict(sd)


def test_extract_pwc_end_to_end(sample_video, tmp_path):
    from video_features_tpu.models.pwc.extract_pwc import ExtractPWC

    cfg = ExtractionConfig(
        feature_type="pwc",
        video_paths=[sample_video],
        extraction_fps=5.0,  # 60-frame 25fps synth clip -> 12 frames
        batch_size=5,
        on_extraction="save_numpy",
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
        cpu=True,
    )
    ex = ExtractPWC(cfg)
    ex([0])
    import pathlib

    saved = {p.name: p for p in pathlib.Path(tmp_path / "out").rglob("*.npy")}
    assert set(saved) == {"synth_pwc.npy"}
    flow = np.load(saved["synth_pwc.npy"])
    # 12 frames -> 11 pairs, flow at source resolution
    assert flow.shape[0] == 11 and flow.shape[1] == 2
    assert np.isfinite(flow).all()
