"""PWC-Net end-to-end extraction.

Model parity lives in tests/test_reference_parity.py, which oracles
against the actual reference source (/root/reference/models/pwc/
pwc_src/pwc_net.py, cupy correlation monkeypatched by the XLA op) —
the round-1 builder-written torch mirror was deleted in its favor.
"""

import numpy as np
import pytest
import torch

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.models.pwc.convert import convert_state_dict


@pytest.mark.quick
def test_converter_rejects_unconsumed():
    from test_reference_parity import _load_reference_pwc

    pwc_mod = _load_reference_pwc()
    torch.manual_seed(0)
    sd = {k: v.numpy() for k, v in pwc_mod.PWCNet().state_dict().items()}
    sd["stray.weight"] = np.zeros(3, np.float32)
    with pytest.raises(ValueError, match="unconsumed"):
        convert_state_dict(sd)


def test_extract_pwc_end_to_end(sample_video, tmp_path):
    from video_features_tpu.models.pwc.extract_pwc import ExtractPWC

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="pwc",
        video_paths=[sample_video],
        extraction_fps=5.0,  # 60-frame 25fps synth clip -> 12 frames
        batch_size=5,
        on_extraction="save_numpy",
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
        cpu=True,
    )
    ex = ExtractPWC(cfg)
    ex([0])
    import pathlib

    saved = {p.name: p for p in pathlib.Path(tmp_path / "out").rglob("*.npy")}
    assert set(saved) == {"synth_pwc.npy"}
    flow = np.load(saved["synth_pwc.npy"])
    # 12 frames -> 11 pairs, flow at source resolution
    assert flow.shape[0] == 11 and flow.shape[1] == 2
    assert np.isfinite(flow).all()


def test_mixed_precision_flow_drift():
    """--dtype bfloat16 PWC (conv stacks bf16; flow estimates, upflow,
    warp grid, correlation volumes pinned fp32) vs the fp32 graph at full
    channel widths — the same two-regime pin as RAFT's
    (tests/test_raft.py): absolute half-quantizer-level budget in a
    convergent-scale regime, relative-only drift in the raw random-init
    regime (PWC is feedforward, but random decoders still emit large
    unphysical flows that scale any rounding with them)."""
    import flax
    import jax.numpy as jnp

    from video_features_tpu.models.pwc.model import build, init_params
    from video_features_tpu.ops.preprocess import flow_to_uint8

    H = W = 128
    rng = np.random.RandomState(0)
    base = rng.uniform(0, 255, size=(H + 8, W + 8)).astype(np.float32)
    f1 = base[4 : 4 + H, 4 : 4 + W]
    f2 = base[1 : 1 + H, 2 : 2 + W]  # coherent (3, 2) px shift
    frames = jnp.asarray(
        np.stack([np.stack([f1] * 3, -1), np.stack([f2] * 3, -1)])
    )

    params = init_params()
    flat = flax.traverse_util.flatten_dict(params)
    for k in list(flat):
        path = "/".join(map(str, k))
        # scale every flow-emitting conv: decoder 'flow' heads + refiner
        # conv6 — physical-magnitude proxy, same graph
        if ("flow" in path and k[-2] == "flow") or (
            "refiner" in path and k[-2] == "conv6"
        ):
            flat[k] = flat[k] * 0.05
    params_small = flax.traverse_util.unflatten_dict(flat)

    m32, m16 = build(dtype=jnp.float32), build(dtype=jnp.bfloat16)

    f32out = np.asarray(m32.apply({"params": params_small}, frames))
    f16out = np.asarray(m16.apply({"params": params_small}, frames))
    assert np.abs(f32out).max() < 20.0
    drift = np.abs(f32out - f16out).max()
    assert drift < 0.078, f"flow drift {drift:.4f} px exceeds half a uint8 level"
    level_diff = np.abs(
        np.asarray(flow_to_uint8(jnp.asarray(f32out)), np.int16)
        - np.asarray(flow_to_uint8(jnp.asarray(f16out)), np.int16)
    )
    assert level_diff.max() <= 1
    assert (level_diff == 0).mean() > 0.9

    from video_features_tpu.analysis.parity import max_rel_drift

    f32out = np.asarray(m32.apply({"params": params}, frames))
    f16out = np.asarray(m16.apply({"params": params}, frames))
    rel = np.linalg.norm(f32out - f16out) / np.linalg.norm(f32out)
    budget = max_rel_drift("pwc", "bfloat16", "model")
    assert rel < budget, (
        f"relative L2 drift {rel:.4f} out of bf16 scale "
        f"(parity_budget.json ceiling {budget})"
    )
