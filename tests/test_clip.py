"""CLIP visual tower: parity vs a torch oracle + end-to-end extraction.

Oracle: transformers' CLIPVisionModelWithProjection with *random* weights
(no downloads in this env), run in torch, converted through our HF
converter — checks the Flax graph AND the converter in one shot.
"""

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.models.clip.convert import convert_state_dict, from_hf_vision
from video_features_tpu.models.clip.model import (
    CLIP_VIT_B32,
    CLIPVisionConfig,
    VisionTransformer,
    init_params,
)

SMALL = CLIPVisionConfig(
    patch_size=16, width=64, layers=2, heads=2, embed_dim=32, image_size=64
)


def _hf_model(cfg: CLIPVisionConfig):
    from transformers import CLIPVisionConfig as HFConfig
    from transformers import CLIPVisionModelWithProjection

    hf_cfg = HFConfig(
        hidden_size=cfg.width,
        intermediate_size=cfg.width * 4,
        num_hidden_layers=cfg.layers,
        num_attention_heads=cfg.heads,
        image_size=cfg.image_size,
        patch_size=cfg.patch_size,
        projection_dim=cfg.embed_dim,
        hidden_act="quick_gelu",
        layer_norm_eps=cfg.eps,
        attention_dropout=0.0,
    )
    torch.manual_seed(0)
    model = CLIPVisionModelWithProjection(hf_cfg)
    model.eval()
    return model


def test_flax_clip_matches_hf_torch_oracle():
    torch_model = _hf_model(SMALL)
    sd = {k: v.numpy() for k, v in torch_model.state_dict().items()}
    params = from_hf_vision(sd, layers=SMALL.layers)

    x = np.random.RandomState(0).randn(3, 3, SMALL.image_size, SMALL.image_size)
    x = x.astype(np.float32)
    with torch.no_grad():
        ref = torch_model(pixel_values=torch.from_numpy(x)).image_embeds.numpy()
    out = np.asarray(VisionTransformer(SMALL).apply({"params": params}, jnp.asarray(x)))
    assert out.shape == ref.shape == (3, SMALL.embed_dim)
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_convert_auto_detects_hf():
    torch_model = _hf_model(SMALL)
    sd = {k: v.numpy() for k, v in torch_model.state_dict().items()}
    params = convert_state_dict(sd, layers=SMALL.layers)
    assert "resblock_0" in params


@pytest.mark.quick
def test_openai_converter_roundtrip():
    """Build an OpenAI-style state dict with the right shapes and check the
    converted tree matches the flax init tree exactly (structure+shapes)."""
    import jax

    cfg = SMALL
    rng = np.random.RandomState(1)
    D, L = cfg.width, cfg.layers
    grid = cfg.image_size // cfg.patch_size
    sd = {
        "visual.class_embedding": rng.randn(D).astype(np.float32),
        "visual.positional_embedding": rng.randn(grid * grid + 1, D).astype(np.float32),
        "visual.proj": rng.randn(D, cfg.embed_dim).astype(np.float32),
        "visual.conv1.weight": rng.randn(D, 3, cfg.patch_size, cfg.patch_size).astype(np.float32),
        "visual.ln_pre.weight": np.ones(D, np.float32),
        "visual.ln_pre.bias": np.zeros(D, np.float32),
        "visual.ln_post.weight": np.ones(D, np.float32),
        "visual.ln_post.bias": np.zeros(D, np.float32),
        # text tower noise that must be ignored
        "transformer.resblocks.0.ln_1.weight": np.ones(4, np.float32),
        "token_embedding.weight": rng.randn(10, 4).astype(np.float32),
    }
    for i in range(L):
        p = f"visual.transformer.resblocks.{i}"
        sd[f"{p}.attn.in_proj_weight"] = rng.randn(3 * D, D).astype(np.float32)
        sd[f"{p}.attn.in_proj_bias"] = rng.randn(3 * D).astype(np.float32)
        sd[f"{p}.attn.out_proj.weight"] = rng.randn(D, D).astype(np.float32)
        sd[f"{p}.attn.out_proj.bias"] = rng.randn(D).astype(np.float32)
        sd[f"{p}.ln_1.weight"] = np.ones(D, np.float32)
        sd[f"{p}.ln_1.bias"] = np.zeros(D, np.float32)
        sd[f"{p}.ln_2.weight"] = np.ones(D, np.float32)
        sd[f"{p}.ln_2.bias"] = np.zeros(D, np.float32)
        sd[f"{p}.mlp.c_fc.weight"] = rng.randn(4 * D, D).astype(np.float32)
        sd[f"{p}.mlp.c_fc.bias"] = rng.randn(4 * D).astype(np.float32)
        sd[f"{p}.mlp.c_proj.weight"] = rng.randn(D, 4 * D).astype(np.float32)
        sd[f"{p}.mlp.c_proj.bias"] = rng.randn(D).astype(np.float32)

    params = convert_state_dict(sd, layers=L)
    ref_tree = jax.tree_util.tree_map(lambda a: a.shape, init_params(cfg))
    got_tree = jax.tree_util.tree_map(lambda a: np.asarray(a).shape, params)
    assert ref_tree == got_tree


def test_extract_clip_end_to_end(sample_video, tmp_path):
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="CLIP-ViT-B/32",
        video_paths=[sample_video],
        extract_method="uni_12",
        on_extraction="save_numpy",
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
        cpu=True,
    )
    ex = ExtractCLIP(cfg)
    ex([0])
    import pathlib

    # feature_type contains '/', so both the subdir and the file name nest
    saved = list(pathlib.Path(tmp_path / "out").rglob("*.npy"))
    assert len(saved) == 1
    feats = np.load(saved[0])
    assert feats.shape == (12, 512)
    assert np.isfinite(feats).all()


def test_extract_clip_external_call(sample_video, tmp_path):
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="CLIP-ViT-B/32",
        video_paths=[sample_video],
        extract_method="uni_3",
        tmp_path=str(tmp_path / "tmp"),
        output_path=str(tmp_path / "out"),
        cpu=True,
    )
    ex = ExtractCLIP(cfg, external_call=True)
    res = ex([0])
    assert len(res) == 1
    assert res[0]["CLIP-ViT-B/32"].shape == (3, 512)
    assert float(np.asarray(res[0]["fps"])) == 25.0
    assert len(res[0]["timestamps_ms"]) == 3


def test_extract_clip_attn_flash_matches_fused(sample_video, tmp_path):
    """--attn flash on the REAL extraction path (VERDICT r02 #8): the
    Pallas kernel (interpret mode off-TPU) must reproduce the fused
    core's features bit-for-bit-ish through all 12 layers."""
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    def run(attn):
        cfg = ExtractionConfig(
            allow_random_init=True,
            feature_type="CLIP-ViT-B/32",
            video_paths=[sample_video],
            extract_method="uni_3",
            attn=attn,
            tmp_path=str(tmp_path / "tmp"),
            output_path=str(tmp_path / "out"),
            cpu=True,
        )
        (r,) = ExtractCLIP(cfg, external_call=True)([0])
        return r["CLIP-ViT-B/32"]

    fused = run("fused")
    flash = run("flash")
    assert flash.shape == fused.shape == (3, 512)
    np.testing.assert_allclose(flash, fused, atol=2e-5, rtol=1e-5)
    blockwise = run("blockwise")
    np.testing.assert_allclose(blockwise, fused, atol=2e-5, rtol=1e-5)


@pytest.mark.quick
def test_mesh_context_rejects_attn_override():
    from video_features_tpu.config import sanity_check

    with pytest.raises(ValueError, match="ring"):
        sanity_check(
            ExtractionConfig(
                feature_type="CLIP-ViT-B/32",
                sharding="mesh",
                mesh_context=True,
                attn="flash",
            )
        )


def test_extract_clip_requires_method(sample_video):
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    with pytest.raises(ValueError, match="extract_method"):
        ExtractCLIP(ExtractionConfig(video_paths=[sample_video]))
