"""Parity against the ACTUAL reference sources in /root/reference.

The round-1 parity suites oracled against builder-written torch
reimplementations, which could share a misreading with the Flax port.
These tests import the reference code itself and assert parity at full
model width on realistic input shapes:

- RAFT:   /root/reference/models/raft/raft_src/raft.py (pure torch)
- I3D:    /root/reference/models/i3d/i3d_src/i3d_net.py (pure torch),
          rgb AND flow modalities
- PWC:    /root/reference/models/pwc/pwc_src/pwc_net.py with its cupy-only
          FunctionCorrelation monkeypatched by ops.correlation
          .local_correlation (itself validated against a naive
          implementation in tests/test_ops.py::test_local_correlation_matches_naive)
- VGGish: /root/reference/models/vggish/vggish_src/mel_features.py and
          vggish_postprocess.py (pure NumPy, loaded standalone — only
          vggish_input.py's resampy import is blocked in this env)

The reference tree has no __init__.py files; with /root/reference appended
to sys.path its ``models.*`` imports resolve as implicit namespace
packages. CLIP's independent oracle is transformers'
CLIPVisionModelWithProjection — exercised at full ViT-B/32 width here.
ResNet/R21D are oracled against the REAL torchvision modules the
reference consumes (skip-if-unimportable: CI installs torchvision via
the [oracle] extra; this env doesn't ship it, where the
torchvision-format builder oracles in tests/test_resnet.py /
tests/test_r21d.py still run).
"""

import importlib
import importlib.util
import sys
import types

import numpy as np
import pytest
import torch

import jax.numpy as jnp

REF = "/root/reference"


def _ref_import(name: str):
    """Import ``models.*`` from the reference tree as namespace packages."""
    if REF not in sys.path:
        sys.path.append(REF)  # append: never shadow repo/stdlib names
    return importlib.import_module(name)


def _load_standalone(mod_name: str, rel_path: str):
    """Load one reference file by path, without triggering sibling imports."""
    spec = importlib.util.spec_from_file_location(mod_name, f"{REF}/{rel_path}")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _randomize_bn_stats(model: torch.nn.Module, seed: int = 7) -> None:
    g = torch.Generator().manual_seed(seed)
    with torch.no_grad():
        for m in model.modules():
            if isinstance(m, (torch.nn.BatchNorm2d, torch.nn.BatchNorm3d)):
                m.running_mean.normal_(0, 0.3, generator=g)
                m.running_var.uniform_(0.5, 2.0, generator=g)


# --- RAFT -------------------------------------------------------------------


def test_raft_matches_reference_source():
    """Full-width RAFT (256-d encoders, 12 GRU iters) vs raft_src/raft.py."""
    from video_features_tpu.models.raft.convert import convert_state_dict
    from video_features_tpu.models.raft.model import build

    raft_mod = _ref_import("models.raft.raft_src.raft")
    torch.manual_seed(0)
    oracle = raft_mod.RAFT()
    _randomize_bn_stats(oracle)
    oracle.eval()

    # checkpoint convention: DataParallel 'module.' prefix (ref
    # models/raft/extract_raft.py:59)
    sd = {f"module.{k}": v.numpy() for k, v in oracle.state_dict().items()}
    params = convert_state_dict(sd)

    rng = np.random.RandomState(0)
    frames = rng.uniform(0, 255, size=(3, 160, 224, 3)).astype(np.float32)
    t = torch.from_numpy(np.transpose(frames, (0, 3, 1, 2)))
    with torch.no_grad():
        ref = oracle(t[:-1], t[1:], iters=12, test_mode=True).numpy()

    flow = build(iters=12).apply({"params": params}, jnp.asarray(frames))
    flow = np.transpose(np.asarray(flow), (0, 3, 1, 2))
    assert flow.shape == ref.shape == (2, 2, 160, 224)
    assert np.isfinite(ref).all() and np.isfinite(flow).all()
    # L2 budget (BASELINE.md): well under 1e-3 relative
    l2 = np.linalg.norm(flow - ref) / max(np.linalg.norm(ref), 1e-12)
    assert l2 <= 1e-3, f"relative L2 {l2}"
    np.testing.assert_allclose(flow, ref, atol=5e-3, rtol=1e-4)


# --- I3D --------------------------------------------------------------------


@pytest.mark.parametrize("modality,t_frames", [("rgb", 64), ("flow", 16)])
def test_i3d_matches_reference_source(modality, t_frames):
    """Full I3D vs i3d_src/i3d_net.py, rgb at the real 64-frame stack size."""
    from video_features_tpu.models.i3d.convert import convert_state_dict
    from video_features_tpu.models.i3d.model import build

    i3d_mod = _ref_import("models.i3d.i3d_src.i3d_net")
    torch.manual_seed(0)
    oracle = i3d_mod.I3D(num_classes=400, modality=modality)
    _randomize_bn_stats(oracle)
    oracle.eval()

    sd = {k: v.numpy() for k, v in oracle.state_dict().items()}
    params = convert_state_dict(sd)

    in_ch = 3 if modality == "rgb" else 2
    rng = np.random.RandomState(0)
    x = rng.uniform(-1, 1, size=(1, t_frames, 224, 224, in_ch)).astype(np.float32)
    xt = torch.from_numpy(np.transpose(x, (0, 4, 1, 2, 3)))
    with torch.no_grad():
        ref_feats = oracle(xt, features=True).numpy()
        _, ref_logits = oracle(xt, features=False)
        ref_logits = ref_logits.numpy()

    feats, logits = build().apply({"params": params}, jnp.asarray(x))
    assert np.asarray(feats).shape == ref_feats.shape == (1, 1024)
    np.testing.assert_allclose(np.asarray(feats), ref_feats, atol=5e-4)
    np.testing.assert_allclose(np.asarray(logits), ref_logits, atol=5e-4)


# --- PWC-Net ----------------------------------------------------------------


def _load_reference_pwc():
    """Import pwc_src/pwc_net.py with its cupy correlation monkeypatched.

    The reference kernel (pwc_src/correlation.py:287-397) is CUDA-only; the
    stub routes through our XLA formulation, which tests/test_ops.py
    validates against a naive implementation independently. pwc_net.py also
    asserts on a torch<2 version-string format at import (pwc_net.py:21),
    patched around for the duration of the import only.
    """
    from video_features_tpu.ops.correlation import local_correlation

    name = "models.pwc.pwc_src.pwc_net"
    if name in sys.modules:
        return sys.modules[name]

    def fn_correlation(tensorFirst, tensorSecond, device=None):
        out = local_correlation(
            jnp.asarray(tensorFirst.detach().numpy()),
            jnp.asarray(tensorSecond.detach().numpy()),
            method="xla",
        )
        return torch.from_numpy(np.asarray(out))

    stub = types.ModuleType("models.pwc.pwc_src.correlation")
    stub.FunctionCorrelation = fn_correlation
    # parent namespace packages must exist before the submodule import
    _ref_import("models.pwc.pwc_src")
    sys.modules["models.pwc.pwc_src.correlation"] = stub
    real_ver = torch.__version__
    try:
        torch.__version__ = "1.6.0"
        return _ref_import(name)
    finally:
        torch.__version__ = real_ver


def test_pwc_matches_reference_source():
    from video_features_tpu.models.pwc.convert import convert_state_dict
    from video_features_tpu.models.pwc.model import build

    pwc_mod = _load_reference_pwc()
    torch.manual_seed(0)
    oracle = pwc_mod.PWCNet()
    oracle.eval()

    sd = {k: v.numpy() for k, v in oracle.state_dict().items()}
    params = convert_state_dict(sd)

    rng = np.random.RandomState(0)
    frames = rng.uniform(0, 255, size=(3, 128, 192, 3)).astype(np.float32)
    t = torch.from_numpy(np.transpose(frames, (0, 3, 1, 2)))
    with torch.no_grad():
        ref = oracle(t[:-1], t[1:]).numpy()

    flow = build().apply({"params": params}, jnp.asarray(frames))
    flow = np.transpose(np.asarray(flow), (0, 3, 1, 2))
    assert flow.shape == ref.shape == (2, 2, 128, 192)
    assert np.isfinite(ref).all() and np.isfinite(flow).all()
    np.testing.assert_allclose(flow, ref, atol=1e-3, rtol=1e-4)


# --- VGGish frontend + postprocessor ---------------------------------------


@pytest.mark.quick
def test_log_mel_matches_reference_source():
    """mel.waveform_to_examples vs the reference NumPy pipeline
    (mel_features.log_mel_spectrogram + the example framing of
    vggish_input.py:44-64, reproduced with reference constants since
    vggish_input.py itself imports resampy at module scope)."""
    from video_features_tpu.models.vggish import mel

    ref_params = _load_standalone(
        "ref_vggish_params", "models/vggish/vggish_src/vggish_params.py"
    )
    ref_mel = _load_standalone(
        "ref_mel_features", "models/vggish/vggish_src/mel_features.py"
    )

    rng = np.random.RandomState(0)
    data = rng.uniform(-1, 1, size=int(16000 * 2.5)).astype(np.float64)

    lm = ref_mel.log_mel_spectrogram(
        data,
        audio_sample_rate=ref_params.SAMPLE_RATE,
        log_offset=ref_params.LOG_OFFSET,
        window_length_secs=ref_params.STFT_WINDOW_LENGTH_SECONDS,
        hop_length_secs=ref_params.STFT_HOP_LENGTH_SECONDS,
        num_mel_bins=ref_params.NUM_MEL_BINS,
        lower_edge_hertz=ref_params.MEL_MIN_HZ,
        upper_edge_hertz=ref_params.MEL_MAX_HZ,
    )
    feats_rate = 1.0 / ref_params.STFT_HOP_LENGTH_SECONDS
    win = int(round(ref_params.EXAMPLE_WINDOW_SECONDS * feats_rate))
    hop = int(round(ref_params.EXAMPLE_HOP_SECONDS * feats_rate))
    ref_examples = ref_mel.frame(lm, window_length=win, hop_length=hop)

    ours = mel.waveform_to_examples(data, ref_params.SAMPLE_RATE)
    assert ours.shape == ref_examples.shape == (2, 96, 64)
    np.testing.assert_allclose(ours, ref_examples, atol=1e-6)


def test_pca_postprocess_matches_reference_source():
    from video_features_tpu.models.vggish.model import postprocess

    # vggish_postprocess imports vggish_params via the models.* namespace
    _ref_import("models.vggish.vggish_src")
    ref_pp = _ref_import("models.vggish.vggish_src.vggish_postprocess")

    rng = np.random.RandomState(0)
    means = rng.randn(128, 1).astype(np.float64)
    # a random orthonormal-ish PCA matrix
    eigen = np.linalg.qr(rng.randn(128, 128))[0].astype(np.float64)
    emb = rng.randn(5, 128).astype(np.float32)

    import tempfile, os

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "pca.npz")
        np.savez(path, pca_eigen_vectors=eigen, pca_means=means)
        oracle = ref_pp.Postprocessor(path)
        ref_out = oracle.postprocess(emb.astype(np.float64))

    ours = np.asarray(
        postprocess(
            jnp.asarray(emb),
            {"pca_eigen_vectors": jnp.asarray(eigen, jnp.float32),
             "pca_means": jnp.asarray(means.reshape(-1), jnp.float32)},
        )
    )
    assert ours.shape == ref_out.shape == (5, 128)
    assert ours.dtype == np.uint8 and ref_out.dtype == np.uint8
    # fp32 vs fp64 matmul can flip a value sitting exactly on a rounding
    # boundary by 1 quantization step
    assert np.abs(ours.astype(int) - ref_out.astype(int)).max() <= 1


# --- CLIP at full ViT-B/32 width (independent transformers oracle) ---------


def test_resnet50_matches_real_torchvision():
    """Full-width resnet50 vs the REAL torchvision module the reference
    consumes (ref models/resnet/extract_resnet.py:55) — randomized weights
    AND BN running stats through our converter. Replaces the last
    builder-written oracle risk for this family (VERDICT r02 #3); skips
    where torchvision isn't installed (CI installs it via [oracle])."""
    tv = pytest.importorskip("torchvision")

    from video_features_tpu.models.resnet.convert import convert_state_dict
    from video_features_tpu.models.resnet.model import build

    torch.manual_seed(0)
    oracle = tv.models.resnet50(weights=None)
    _randomize_bn_stats(oracle)
    oracle.eval()
    sd = {k: v.numpy() for k, v in oracle.state_dict().items()}
    params = convert_state_dict(sd, "resnet50")

    x = np.random.RandomState(0).randn(2, 3, 224, 224).astype(np.float32)
    with torch.no_grad():
        xt = torch.from_numpy(x)
        feats_ref = torch.flatten(
            oracle.avgpool(
                oracle.layer4(
                    oracle.layer3(
                        oracle.layer2(
                            oracle.layer1(
                                oracle.maxpool(
                                    torch.relu(oracle.bn1(oracle.conv1(xt)))
                                )
                            )
                        )
                    )
                )
            ),
            1,
        ).numpy()
        logits_ref = oracle(xt).numpy()
    feats, logits = build("resnet50").apply({"params": params}, jnp.asarray(x))
    assert np.asarray(feats).shape == feats_ref.shape == (2, 2048)
    np.testing.assert_allclose(np.asarray(feats), feats_ref, atol=2e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(logits), logits_ref, atol=2e-4, rtol=1e-4)


def test_r2plus1d_matches_real_torchvision():
    """Full-width r2plus1d_18 vs the REAL torchvision video model the
    reference consumes (ref models/r21d/extract_r21d.py:65), through our
    converter; skips where torchvision isn't installed."""
    tv = pytest.importorskip("torchvision")

    from video_features_tpu.models.r21d.convert import convert_state_dict
    from video_features_tpu.models.r21d.model import build

    torch.manual_seed(0)
    oracle = tv.models.video.r2plus1d_18(weights=None)
    _randomize_bn_stats(oracle)
    oracle.eval()
    sd = {k: v.numpy() for k, v in oracle.state_dict().items()}
    params = convert_state_dict(sd)

    # (N, T, H, W, C) fp32 in [0,1]-ish post-preprocess space; torchvision
    # wants (N, C, T, H, W)
    x = np.random.RandomState(1).randn(1, 8, 112, 112, 3).astype(np.float32)
    with torch.no_grad():
        xt = torch.from_numpy(x.transpose(0, 4, 1, 2, 3))
        stem = oracle.stem(xt)
        h = oracle.layer4(oracle.layer3(oracle.layer2(oracle.layer1(stem))))
        feats_ref = torch.flatten(oracle.avgpool(h), 1).numpy()
        logits_ref = oracle(xt).numpy()
    feats, logits = build().apply({"params": params}, jnp.asarray(x))
    assert np.asarray(feats).shape == feats_ref.shape == (1, 512)
    np.testing.assert_allclose(np.asarray(feats), feats_ref, atol=3e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(logits), logits_ref, atol=3e-4, rtol=1e-4)


def test_clip_full_width_matches_hf_oracle():
    """Round 1 proved the graph at a toy config; this runs the real
    ViT-B/32 (12 layers, width 768, 12 heads, 224px) through the HF
    converter — transformers' implementation is an independent codebase,
    not builder-written."""
    from transformers import CLIPVisionConfig as HFConfig
    from transformers import CLIPVisionModelWithProjection

    from video_features_tpu.models.clip.convert import from_hf_vision
    from video_features_tpu.models.clip.model import CLIP_VIT_B32, VisionTransformer

    hf_cfg = HFConfig(
        hidden_size=768,
        intermediate_size=3072,
        num_hidden_layers=12,
        num_attention_heads=12,
        image_size=224,
        patch_size=32,
        projection_dim=512,
        hidden_act="quick_gelu",
        attention_dropout=0.0,
    )
    torch.manual_seed(0)
    oracle = CLIPVisionModelWithProjection(hf_cfg)
    oracle.eval()
    sd = {k: v.numpy() for k, v in oracle.state_dict().items()}
    params = from_hf_vision(sd, layers=12)

    x = np.random.RandomState(0).randn(2, 3, 224, 224).astype(np.float32)
    with torch.no_grad():
        ref = oracle(pixel_values=torch.from_numpy(x)).image_embeds.numpy()
    out = np.asarray(
        VisionTransformer(CLIP_VIT_B32).apply({"params": params}, jnp.asarray(x))
    )
    assert out.shape == ref.shape == (2, 512)
    np.testing.assert_allclose(out, ref, atol=2e-4, rtol=1e-4)
