"""Deterministic corrupt-media corpus for the hostile-input tests.

Every file is GENERATED at test time from cv2-written synthetic clips
plus byte-level surgery — no binary fixtures live in the repo, and no
ffmpeg is needed. Each generator documents the real-world failure it
stands in for and was verified against this environment's OpenCV: the
byte offsets below are structural (RIFF/AVI chunk layout, JPEG SOF0
markers), not magic numbers for one encoder build.

The corpus is the shared substrate for three test layers:

- probe unit tests (verdict per entry — tests/test_hostile_media.py)
- batch acceptance (every entry reaches a defined terminal manifest
  state with zero retries burned on permanents)
- serve acceptance (every entry reaches a terminal request state over
  live HTTP and spool; zero breaker openings, zero worker deaths)

Entry expectations are encoded HERE, next to the bytes that cause them,
so the acceptance loops stay data-driven.
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from video_features_tpu.utils.synth import synth_video


@dataclass
class HostileEntry:
    """One corpus file plus its expected handling.

    probe_verdict: expected io/probe.py verdict for ``need='video'``.
    batch_terminal: expected manifest status when run through a
        frame-consuming batch extractor ('done' or 'failed'); None for
        entries that only make sense under a specific need/cap setup.
    expect_warnings: substrings that must appear in recorded warnings
        (probe cautions or decode notes) when the entry goes through.
    """

    name: str
    path: str
    probe_verdict: str
    batch_terminal: Optional[str] = None
    reason_contains: Optional[str] = None
    expect_warnings: List[str] = field(default_factory=list)


# -- low-level byte surgery -------------------------------------------


def _write_avi_mjpg(
    path: str, n_frames: int = 60, width: int = 64, height: int = 48,
    fps: float = 25.0,
) -> str:
    """MJPG-in-AVI: every frame is an independent JPEG, so a truncated
    file still decodes its prefix — the container for salvage vectors
    (an mp4 with its moov atom at the tail just refuses to open)."""
    import cv2

    writer = cv2.VideoWriter(
        path, cv2.VideoWriter_fourcc(*"MJPG"), fps, (width, height)
    )
    assert writer.isOpened(), "cv2.VideoWriter could not open MJPG/avi writer"
    yy, xx = np.mgrid[0:height, 0:width]
    for t in range(n_frames):
        frame = np.stack(
            [(xx + 2 * t) % 256, (yy + t) % 256,
             np.full((height, width), (t * 4) % 256)],
            axis=-1,
        ).astype(np.uint8)
        writer.write(frame)
    writer.release()
    return path


def _truncate(src: str, dst: str, frac: float) -> str:
    data = open(src, "rb").read()
    with open(dst, "wb") as f:
        f.write(data[: max(int(len(data) * frac), 1)])
    return dst


def _patch_fps_zero(src: str, dst: str) -> str:
    """Rewrite the AVI video stream header so fps computes to ~0:
    strh.dwRate/dwScale is the frame rate, and dwScale=0xFFFFFFF0 with
    dwRate=1 yields ~4.7e-10 fps — the 'metadata says zero/absent frame
    rate' class that silently became 25.0 downstream before this PR.
    Offsets: 'strh' tag, 8 bytes of chunk header, then fccType(4)
    fccHandler(4) dwFlags(4) wPriority(2) wLanguage(2) dwInitialFrames(4)
    = 20 bytes to dwScale, 24 to dwRate."""
    data = bytearray(open(src, "rb").read())
    i = data.find(b"strh")
    assert i >= 0, "no strh chunk in generated AVI"
    struct.pack_into("<I", data, i + 8 + 20, 0xFFFFFFF0)  # dwScale
    struct.pack_into("<I", data, i + 8 + 24, 1)  # dwRate
    open(dst, "wb").write(data)
    return dst


def _patch_sof_dims(src: str, dst: str, width: int, height: int) -> str:
    """Lie about frame dimensions INSIDE every MJPEG frame's SOF0
    marker (container headers are sanitized away by self-describing
    JPEG frames, so the lie must live in the bitstream). A 65500x65500
    claim makes every frame undecodable while the container still opens
    — the header-lie class the probe's first-frame check exists for.
    SOF0 layout: ff c0 | len(2) | precision(1) | height(2) | width(2),
    big-endian."""
    data = bytearray(open(src, "rb").read())
    patched = 0
    j = data.find(b"\xff\xc0")
    while j >= 0:
        # guard against \xff\xc0 appearing in entropy-coded data: a real
        # SOF0 for 3-component MJPEG has len=17 and precision=8
        if data[j + 2 : j + 5] == b"\x00\x11\x08":
            struct.pack_into(">H", data, j + 5, height)
            struct.pack_into(">H", data, j + 7, width)
            patched += 1
        j = data.find(b"\xff\xc0", j + 2)
    assert patched > 0, "no SOF0 markers found in generated MJPG AVI"
    open(dst, "wb").write(data)
    return dst


def _write_wav(path: str, seconds: float = 1.0, rate: int = 16000) -> str:
    from scipy.io import wavfile

    t = np.arange(int(seconds * rate)) / rate
    wave = (0.3 * np.sin(2 * np.pi * 440.0 * t) * 32767).astype(np.int16)
    wavfile.write(path, rate, wave)
    return path


# -- the corpus -------------------------------------------------------


def build_corpus(root: str) -> Dict[str, HostileEntry]:
    """Generate every corpus file under ``root`` and return the entries
    keyed by name. Deterministic: same root -> byte-identical files."""
    os.makedirs(root, exist_ok=True)
    p = lambda n: os.path.join(root, n)  # noqa: E731
    entries: Dict[str, HostileEntry] = {}

    def add(e: HostileEntry) -> None:
        entries[e.name] = e

    # healthy baseline: proves the pipeline under test actually works,
    # so a corpus-wide 'everything failed' cannot pass vacuously
    synth_video(p("ok.mp4"), n_frames=60, width=64, height=48)
    add(HostileEntry("ok", p("ok.mp4"), "ok", batch_terminal="done"))

    # zero-byte upload (interrupted transfer)
    open(p("zero_byte.mp4"), "wb").close()
    add(HostileEntry("zero_byte", p("zero_byte.mp4"), "reject",
                     batch_terminal="failed", reason_contains="empty file"))

    # wrong bytes behind a media extension (text served as .mp4)
    with open(p("text_as.mp4"), "w") as f:
        f.write("this is not a video\n" * 64)
    add(HostileEntry("text_as_mp4", p("text_as.mp4"), "reject",
                     batch_terminal="failed",
                     reason_contains="container does not open"))

    # truncated mp4: moov atom lives at the tail, so a cut upload
    # loses the index entirely and the container refuses to open
    synth_video(p("full.mp4"), n_frames=60, width=64, height=48)
    _truncate(p("full.mp4"), p("truncated.mp4"), 0.6)
    add(HostileEntry("truncated_mp4", p("truncated.mp4"), "reject",
                     batch_terminal="failed",
                     reason_contains="container does not open"))

    # bit-flipped mp4 header (bytes 4..40 inverted): the container
    # still opens but declares an insane NEGATIVE frame count; frames
    # themselves decode. The probe must sanitize the declared count to
    # a warning, not reject a recoverable stream.
    data = bytearray(open(p("full.mp4"), "rb").read())
    for i in range(4, 40):
        data[i] ^= 0xFF
    open(p("bitflip.mp4"), "wb").write(data)
    add(HostileEntry("bitflip_mp4", p("bitflip.mp4"), "caution",
                     batch_terminal="done",
                     expect_warnings=["frame count"]))

    # audio-only container where video is needed
    _write_wav(p("audio_only.wav"))
    add(HostileEntry("audio_only_wav", p("audio_only.wav"), "reject",
                     batch_terminal="failed",
                     reason_contains="audio-only container"))

    # the same RIFF/WAVE bytes hiding behind a video extension: caught
    # by magic-byte sniff, not the name
    with open(p("wav_as.mp4"), "wb") as f:
        f.write(open(p("audio_only.wav"), "rb").read())
    add(HostileEntry("wav_as_mp4", p("wav_as.mp4"), "reject",
                     batch_terminal="failed",
                     reason_contains="audio-only container"))

    # 1-frame video: healthy media, but shorter than any model window —
    # must fail at sampling with counts, not crash a worker
    _write_avi_mjpg(p("one_frame.avi"), n_frames=1)
    add(HostileEntry("one_frame", p("one_frame.avi"), "ok",
                     batch_terminal="failed"))

    # fps ~= 0 in the stream header: timestamps need a recorded default
    _write_avi_mjpg(p("fps_base.avi"), n_frames=12)
    _patch_fps_zero(p("fps_base.avi"), p("fps_zero.avi"))
    add(HostileEntry("fps_zero", p("fps_zero.avi"), "caution",
                     batch_terminal="done",
                     expect_warnings=["fps"]))

    # dimension lie inside the bitstream: container opens, zero frames
    # decode — only the probe's first-frame grab catches it pre-queue
    _write_avi_mjpg(p("dims_base.avi"), n_frames=8)
    _patch_sof_dims(p("dims_base.avi"), p("huge_dims.avi"), 65500, 65500)
    add(HostileEntry("huge_dims", p("huge_dims.avi"), "reject",
                     batch_terminal="failed",
                     reason_contains="no decodable frames"))

    # truncated MJPG AVI: opens, declares 60 frames, decodes ~half —
    # THE salvage vector: features for the prefix + partial_decode
    _write_avi_mjpg(p("avi_full.avi"), n_frames=60)
    _truncate(p("avi_full.avi"), p("truncated_half.avi"), 0.5)
    add(HostileEntry("truncated_half_avi", p("truncated_half.avi"), "ok",
                     batch_terminal="done",
                     expect_warnings=["partial decode"]))

    # truncated so deep only ~2 frames survive (any deeper and the AVI
    # header itself is cut and the container rejects at open): cannot
    # fill one model window -> permanent with decoded/declared counts
    _truncate(p("avi_full.avi"), p("truncated_deep.avi"), 0.25)
    add(HostileEntry("truncated_deep_avi", p("truncated_deep.avi"), "ok",
                     batch_terminal="failed"))

    # video with no audio stream, submitted to an audio consumer
    # (vggish): cv2-written mp4 never carries audio. Probe under
    # need='audio' is caution (openable container; stream presence
    # resolves at rip time) — the rip itself needs ffmpeg, so the
    # end-to-end variant is gated on its presence in tests.
    synth_video(p("video_only.mp4"), n_frames=12, width=64, height=48)
    add(HostileEntry("video_only_mp4", p("video_only.mp4"), "ok"))

    return entries
