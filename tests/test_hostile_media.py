"""Hostile-media hardening (ISSUE 9): preflight probe, resource caps,
salvage decode, audio failure taxonomy, and breaker correctness —
exercised over the generated corrupt-media corpus (tests/hostile_media.py)
through BOTH the batch extractor loop and the live serve daemon.

The acceptance contract pinned here: every corpus file reaches a defined
terminal state on both paths, zero worker deaths, zero breaker openings,
zero retries burned on permanent (input-classified) failures; a
truncated stream whose decodable prefix fills >=1 model window yields
features plus a ``partial_decode`` warning, one that cannot fails
permanent with decoded/declared counts.
"""

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from video_features_tpu.config import ExtractionConfig, parse_serve_args, sanity_check
from video_features_tpu.extract.base import BaseExtractor
from video_features_tpu.io import audio as audio_mod
from video_features_tpu.io import probe
from video_features_tpu.io.paths import video_path_of
from video_features_tpu.io.video import (
    pop_decode_warnings,
    read_all_frames,
    read_all_frames_with_meta,
    require_window,
    set_resource_caps,
)
from video_features_tpu.runtime import faults
from video_features_tpu.serve.daemon import ServeDaemon
from video_features_tpu.serve.lifecycle import InvalidMedia
from video_features_tpu.serve.sources import SpoolWatcher
from video_features_tpu.serve.supervisor import CircuitBreaker

from hostile_media import build_corpus

pytestmark = pytest.mark.hostile


@pytest.fixture(autouse=True)
def _clear_global_decode_state():
    """Caps and the injector are process-global (installed per
    extractor __init__); never leak one test's setup into the suite."""
    yield
    set_resource_caps(None)
    faults.install_injector(None)
    pop_decode_warnings()


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    return build_corpus(str(tmp_path_factory.mktemp("hostile_corpus")))


# --- probe unit layer --------------------------------------------------------


def test_probe_verdicts_across_corpus(corpus):
    for e in corpus.values():
        rep = probe.preflight(e.path, need="video")
        assert rep.verdict == e.probe_verdict, (e.name, rep.reason, rep.warnings)
        if e.reason_contains:
            assert e.reason_contains in rep.reason, (e.name, rep.reason)
        if rep.verdict == "caution":
            assert rep.warnings, e.name


def test_probe_report_is_structured(corpus):
    rep = probe.preflight(corpus["ok"].path, need="video")
    d = rep.as_dict()
    assert d["verdict"] == "ok" and d["width"] == 64 and d["height"] == 48
    assert d["frame_count"] == 60 and d["first_frame_ok"] is True
    assert rep.fps == pytest.approx(25.0)
    assert rep.duration_s == pytest.approx(60 / 25.0)


def test_probe_reject_maps_to_permanent_input_error(corpus):
    rep = probe.preflight(corpus["truncated_mp4"].path, need="video")
    exc = rep.to_error()
    assert isinstance(exc, faults.MediaRejected)
    assert faults.classify_error(exc) == "permanent"
    assert faults.is_input_error(exc)
    assert exc.stage == "preflight"
    assert corpus["truncated_mp4"].path in str(exc)


def test_probe_audio_need(corpus):
    # a bare .wav is a legitimate vggish input
    assert probe.preflight(corpus["audio_only_wav"].path, need="audio").verdict == "ok"
    # RIFF/WAVE behind a video extension: sniffed, still fine for audio
    assert probe.preflight(corpus["wav_as_mp4"].path, need="audio").verdict == "ok"
    # a video container under need=audio: admitted with a caution (the
    # audio stream's existence only resolves at rip time)
    rep = probe.preflight(corpus["video_only_mp4"].path, need="audio")
    assert rep.verdict == "caution"
    assert any("audio stream" in w for w in rep.warnings)


def test_probe_missing_and_directory(tmp_path):
    assert probe.preflight(str(tmp_path / "nope.mp4")).verdict == "reject"
    rep = probe.preflight(str(tmp_path))
    assert rep.verdict == "caution"  # i3d flow-dir entries: skip, don't lie


# --- resource caps -----------------------------------------------------------


def test_preflight_caps_reject_on_declared_metadata(corpus):
    ok = corpus["ok"].path  # 64x48, 60 frames @ 25 fps
    for caps, what in [
        (probe.ResourceCaps(max_pixels=1000), "--max_pixels"),
        (probe.ResourceCaps(max_duration_s=1.0), "--max_duration_s"),
        (probe.ResourceCaps(max_decode_bytes=100_000), "--max_decode_bytes"),
    ]:
        rep = probe.preflight(ok, need="video", caps=caps)
        assert rep.verdict == "reject" and rep.cap_exceeded, what
        assert what in rep.reason
        exc = rep.to_error()
        assert isinstance(exc, faults.ResourceCapExceeded)
        assert faults.classify_error(exc) == "permanent"
    # generous caps admit
    roomy = probe.ResourceCaps(
        max_pixels=10_000, max_duration_s=10.0, max_decode_bytes=10**8
    )
    assert probe.preflight(ok, need="video", caps=roomy).verdict == "ok"


def test_running_byte_budget_catches_lying_metadata(corpus):
    # bitflip: declared frame count is insane (unknown), so declared-
    # metadata cap checks can't fire — the reader's running budget must
    bad = corpus["bitflip_mp4"].path
    set_resource_caps(probe.ResourceCaps(max_decode_bytes=5 * 64 * 48 * 3))
    with pytest.raises(faults.ResourceCapExceeded, match="max_decode_bytes"):
        read_all_frames(bad)
    set_resource_caps(probe.ResourceCaps(max_duration_s=0.2))  # ~5 frames
    with pytest.raises(faults.ResourceCapExceeded, match="max_duration_s"):
        read_all_frames(bad)
    set_resource_caps(None)
    frames, _, _ = read_all_frames(bad)  # uncapped: the stream is fine
    assert len(frames) == 60


def test_caps_config_validation():
    sanity_check(ExtractionConfig(max_pixels=1, max_duration_s=0.5,
                                  max_decode_bytes=1))
    for kw in ({"max_pixels": 0}, {"max_duration_s": 0.0},
               {"max_decode_bytes": 0}, {"preflight": "maybe"}):
        with pytest.raises(ValueError):
            sanity_check(ExtractionConfig(**kw))


# --- salvage decode ----------------------------------------------------------


def test_truncated_prefix_decodes_with_partial_note(corpus):
    frames, fps, stamps, declared = read_all_frames_with_meta(
        corpus["truncated_half_avi"].path
    )
    assert declared == 60 and 0 < len(frames) < 60
    assert fps == pytest.approx(25.0)
    notes = pop_decode_warnings()
    partial = [n for n in notes if n["kind"] == "partial_decode"]
    assert len(partial) == 1
    assert partial[0]["decoded"] == len(frames) and partial[0]["declared"] == 60


def test_require_window_reports_counts(corpus):
    frames, _, _, declared = read_all_frames_with_meta(
        corpus["truncated_deep_avi"].path
    )
    assert declared == 60 and 0 < len(frames) < 4
    with pytest.raises(faults.CorruptVideoError) as ei:
        require_window(frames, 4, corpus["truncated_deep_avi"].path,
                       declared=declared)
    msg = str(ei.value)
    assert f"{len(frames)} of 60 declared frames" in msg
    assert "window needs 4" in msg
    assert faults.classify_error(ei.value) == "permanent"


def test_fps_zero_becomes_recorded_default_not_silence(corpus):
    frames, fps, stamps = read_all_frames(corpus["fps_zero"].path)
    assert frames and fps == pytest.approx(25.0)
    notes = pop_decode_warnings()
    assert any(n["kind"] == "fps_defaulted" for n in notes)
    # healthy video: no notes at all
    read_all_frames(corpus["ok"].path)
    assert pop_decode_warnings() == []


# --- audio failure taxonomy --------------------------------------------------


def test_read_wav_wraps_parse_failures_permanent(corpus, tmp_path):
    junk = tmp_path / "junk.wav"
    junk.write_bytes(b"RIFFxxxxWAVEjunkjunk")
    with pytest.raises(faults.AudioDecodeError) as ei:
        audio_mod.read_wav(str(junk))
    assert faults.classify_error(ei.value) == "permanent"
    assert faults.is_input_error(ei.value)
    data, rate = audio_mod.read_wav(corpus["audio_only_wav"].path)
    assert rate == 16000 and len(data) > 0


def test_rip_failures_classified_by_cause(tmp_path, monkeypatch):
    from video_features_tpu.io import ffmpeg as ffmpeg_mod

    vid = str(tmp_path / "v.mp4")
    open(vid, "wb").write(b"x")

    def rip_raising(msg):
        def _rip(*a, **k):
            raise RuntimeError(msg)
        return _rip

    # no audio stream: precise permanent reason, not a generic rip error
    monkeypatch.setattr(ffmpeg_mod, "extract_wav_from_video",
                        rip_raising("ffmpeg failed (exit 1): Stream map 'a' "
                                    "matches no streams"))
    with pytest.raises(faults.MissingStreamError, match="no audio stream"):
        audio_mod.load_audio_for_model(vid, 16000, str(tmp_path), False)
    # corrupt bitstream: permanent AudioDecodeError
    monkeypatch.setattr(ffmpeg_mod, "extract_wav_from_video",
                        rip_raising("ffmpeg failed (exit 1): invalid data "
                                    "found when processing input"))
    with pytest.raises(faults.AudioDecodeError, match="bitstream"):
        audio_mod.load_audio_for_model(vid, 16000, str(tmp_path), False)
    # missing ffmpeg is INFRA, not input: must pass through unclassified
    monkeypatch.setattr(ffmpeg_mod, "extract_wav_from_video",
                        rip_raising("ffmpeg binary not found. install it"))
    with pytest.raises(RuntimeError) as ei:
        audio_mod.load_audio_for_model(vid, 16000, str(tmp_path), False)
    assert not faults.is_input_error(ei.value)


# --- batch acceptance over the corpus ----------------------------------------


class WindowToy(BaseExtractor):
    """Windowed toy: decode everything, demand a 4-frame window — the
    smallest extractor that exercises preflight, salvage, and
    require_window through the real run loop."""

    feature_type = "toy"
    WINDOW = 4

    def _build(self, device):
        return {"device": device}

    def prepare(self, path_entry):
        path = video_path_of(path_entry)
        frames, _, _, declared = read_all_frames_with_meta(path)
        require_window(frames, self.WINDOW, path, declared=declared)
        return np.asarray([float(f.mean()) for f in frames], dtype=np.float32)

    def extract_prepared(self, device, state, path_entry, payload):
        return {"toy": np.asarray(payload).reshape(-1, 1)}


def _batch_cfg(videos, tmp_path, **kw):
    kw.setdefault("decode_workers", 1)
    kw.setdefault("retry_backoff", 0.01)
    return ExtractionConfig(
        allow_random_init=True,
        video_paths=list(videos),
        on_extraction="save_numpy",
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
        cpu=True,
        **kw,
    )


def test_batch_acceptance_every_file_terminal(corpus, tmp_path):
    entries = [e for e in corpus.values() if e.batch_terminal]
    cfg = _batch_cfg([e.path for e in entries], tmp_path, retries=2)
    WindowToy(cfg)()
    s = faults.finalize_run(cfg.output_path)
    assert s is not None
    # every file reached a defined terminal state; nothing died or retried
    assert s["total"] == len(entries)
    assert s["worker_deaths"] == []
    assert s["retries"] == 0
    warn_by_video = {}
    for w in s["warnings"]:
        warn_by_video.setdefault(w["video"], []).append(w["message"])
    for e in entries:
        rec = s["videos"][e.path]
        assert rec["status"] == e.batch_terminal, (e.name, rec)
        if rec["status"] == "failed":
            assert rec["error_class"] == "permanent", (e.name, rec)
            assert rec["attempts"] == 1, (e.name, rec)
            if e.reason_contains:
                assert e.reason_contains in rec["message"], (e.name, rec)
        for frag in e.expect_warnings:
            assert any(frag in m for m in warn_by_video.get(e.path, [])), (
                e.name, frag, warn_by_video.get(e.path))
    # the salvage contract, nailed to specific entries: enough prefix ->
    # features + partial_decode; not enough -> permanent with counts
    half = s["videos"][corpus["truncated_half_avi"].path]
    assert half["status"] == "done"
    deep = s["videos"][corpus["truncated_deep_avi"].path]
    assert deep["status"] == "failed"
    assert "of 60 declared frames decoded, window needs 4" in deep["message"]
    one = s["videos"][corpus["one_frame"].path]
    assert "1 of 1 declared frames decoded" in one["message"]
    # preflight rejects carry their stage
    assert s["videos"][corpus["zero_byte"].path]["stage"] == "preflight"
    assert s["videos"][corpus["zero_byte"].path]["error_type"] == "MediaRejected"


def test_batch_preflight_off_still_terminal(corpus, tmp_path):
    # --preflight off: the decode path itself must absorb the same files
    bad = [corpus["zero_byte"].path, corpus["truncated_half_avi"].path]
    cfg = _batch_cfg(bad, tmp_path, retries=1, preflight="off")
    WindowToy(cfg)()
    s = faults.finalize_run(cfg.output_path)
    assert s["videos"][bad[0]]["status"] == "failed"
    assert s["videos"][bad[0]]["error_class"] == "permanent"
    assert s["videos"][bad[1]]["status"] == "done"


def test_batch_cap_as_flag_rejects_at_preflight(corpus, tmp_path):
    cfg = _batch_cfg([corpus["ok"].path], tmp_path, max_pixels=1000)
    WindowToy(cfg)()
    s = faults.finalize_run(cfg.output_path)
    rec = s["videos"][corpus["ok"].path]
    assert rec["status"] == "failed"
    assert rec["error_type"] == "ResourceCapExceeded"
    assert "--max_pixels" in rec["message"]
    assert rec["attempts"] == 1 and s["retries"] == 0


# --- serve acceptance --------------------------------------------------------


def _daemon(tmp_path, **flags):
    argv = [
        "--feature_types", "resnet18",
        "--output_path", str(tmp_path / "srv_out"),
        "--tmp_path", str(tmp_path / "srv_tmp"),
        "--allow_random_init", "--cpu",
        "--heartbeat_s", "0",
    ]
    for k, v in flags.items():
        argv += [f"--{k}"] + ([str(v)] if v is not True else [])
    scfg = parse_serve_args(argv)

    class Toy(WindowToy):
        pass

    return ServeDaemon(scfg, build=Toy)


def _drain(d):
    for g in d.batcher.take_ready(now=float("inf")):
        d.batcher._run_group(g)


def _submit(d, rid, path):
    return d.submit({"feature_type": "resnet18", "video_path": path,
                     "id": rid}, source="local")


def test_serve_acceptance_every_file_terminal(corpus, tmp_path):
    d = _daemon(tmp_path, max_group_size=4)
    rejected, admitted = [], []
    for e in corpus.values():
        if e.batch_terminal is None:
            continue
        try:
            _submit(d, f"h-{e.name}", e.path)
            admitted.append(e)
        except InvalidMedia as exc:
            rejected.append(e)
            # durable rejected record written BEFORE the raise, and the
            # exception carries it for the HTTP 422 body
            rec = d.tracker.get(f"h-{e.name}")
            assert rec["state"] == "rejected"
            assert exc.record["state"] == "rejected"
            if e.reason_contains:
                assert e.reason_contains in rec["message"], (e.name, rec)
    # exactly the probe-reject entries bounce at admission
    assert {e.name for e in rejected} == {
        e.name for e in corpus.values()
        if e.batch_terminal and e.probe_verdict == "reject"
    }
    _drain(d)
    for e in admitted:
        rec = d.tracker.get(f"h-{e.name}")
        want = "done" if e.batch_terminal == "done" else "failed"
        assert rec["state"] == want, (e.name, rec)
    # the whole corpus moved nothing on the breaker and killed no worker
    assert d.status()["status"] == "ok"
    for b in d._breakers.values():
        assert b.state() == "closed" and b.snapshot()["opens"] == 0
    ext = d.pool._extractors["resnet18"]
    assert faults.merge_manifest(d.cfg.output_path)["worker_deaths"] == []
    assert ext is not None
    d.shutdown()


def test_serve_http_422_body_shape(corpus, tmp_path):
    d = _daemon(tmp_path, port=0, max_batch_wait_ms=10)
    d.start()
    try:
        def post(payload):
            req = urllib.request.Request(
                f"http://127.0.0.1:{d.http_port}/v1/extract",
                data=json.dumps(payload).encode(), method="POST",
                headers={"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    return r.status, json.loads(r.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        code, body = post({"feature_type": "resnet18", "id": "bad-0",
                           "video_path": corpus["truncated_mp4"].path})
        assert code == 422
        assert body["reason_code"] == "invalid_media"
        assert "container does not open" in body["error"]
        assert body["record"]["state"] == "rejected"
        assert d.tracker.get("bad-0")["state"] == "rejected"
        # plain malformed requests keep their 400 (not 422)
        assert post({"feature_type": "resnet18"})[0] == 400
        # and a healthy file still rides straight through
        code, rec = post({"feature_type": "resnet18", "id": "good-0",
                          "video_path": corpus["ok"].path})
        assert code == 202 and rec["state"] == "queued"
    finally:
        d.shutdown()


def test_serve_spool_quarantines_invalid_media(corpus, tmp_path):
    d = _daemon(tmp_path)
    spool = str(tmp_path / "spool")
    w = SpoolWatcher(d, spool, poll_s=0.05)
    os.makedirs(spool, exist_ok=True)
    with open(os.path.join(spool, "bad.json"), "w") as fh:
        json.dump({"feature_type": "resnet18", "id": "sp-0",
                   "video_path": corpus["zero_byte"].path}, fh)
    assert w.poll_once() == 0
    assert os.path.exists(os.path.join(spool, "bad.json.bad"))
    why = open(os.path.join(spool, "bad.json.bad.why")).read()
    assert "InvalidMedia" in why and "empty file" in why
    assert d.tracker.get("sp-0")["state"] == "rejected"
    d.shutdown()


# --- breaker correctness -----------------------------------------------------


def test_breaker_ignores_input_classified_group_crash(corpus, tmp_path):
    """N corrupt-input group crashes leave the breaker closed; the same
    N infra crashes open it — the regression ISSUE 9 exists to pin."""
    d = _daemon(tmp_path, fault_inject="extractor:corrupt:1",
                breaker_threshold=1, breaker_cooldown_s=60.0)
    for i in range(3):
        _submit(d, f"c-{i}", corpus["ok"].path)
        _drain(d)
        rec = d.tracker.get(f"c-{i}")
        assert rec["state"] == "failed" and "corrupt" in rec["message"]
    b = d._breaker("resnet18")
    assert b.state() == "closed" and b.snapshot()["opens"] == 0
    assert d.status()["status"] == "ok"
    d.shutdown()

    d2 = _daemon(tmp_path, fault_inject="extractor:error:1",
                 breaker_threshold=1, breaker_cooldown_s=60.0)
    _submit(d2, "e-0", corpus["ok"].path)
    _drain(d2)
    assert d2._breaker("resnet18").state() == "open"
    d2.shutdown()


def test_breaker_record_ignored_state_machine():
    clock = [0.0]
    b = CircuitBreaker(threshold=2, cooldown_s=10.0, clock=lambda: clock[0])
    # closed: ignored outcomes neither advance nor reset the streak
    assert not b.record_failure()
    b.record_ignored()
    assert b.state() == "closed"
    assert b.record_failure()  # second REAL failure still opens
    assert b.state() == "open"
    # half-open: an input-classified probe outcome releases the slot
    # without a verdict — the next group re-probes, state unchanged
    clock[0] = 10.0
    assert b.state() == "half_open"
    assert b.try_probe()
    assert not b.allow_request()  # probe slot held
    b.record_ignored()
    assert b.state() == "half_open"
    assert b.allow_request() and b.try_probe()  # slot free again
    b.record_success()
    assert b.state() == "closed"


# --- graftcheck scope --------------------------------------------------------


@pytest.mark.analysis
def test_probe_is_in_graftcheck_fastpath_scope():
    from video_features_tpu.analysis.core import collect_sources

    src = {s.rel: s for s in collect_sources()}["io/probe.py"]
    assert src.is_hot and src.is_thread_root
    assert "graftcheck:" not in src.text  # zero waivers, per ISSUE 9
