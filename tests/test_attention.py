"""Attention cores + ring attention (context parallelism).

Oracle is the fused full-score-matrix core (itself checked against a
plain numpy softmax-attention here), so blockwise and ring — the
long-sequence paths — are validated against the exact math they must
reproduce. Ring runs on the 8-virtual-device CPU mesh from conftest.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from video_features_tpu.ops.attention import attention, blockwise_attention
from video_features_tpu.parallel.ring_attention import (
    ring_attention_sharded,
)
from video_features_tpu.parallel.sharding import make_mesh


def _qkv(rng, n=2, h=3, lq=17, lk=23, d=8, dtype=np.float32):
    q = rng.standard_normal((n, h, lq, d)).astype(dtype)
    k = rng.standard_normal((n, h, lk, d)).astype(dtype)
    v = rng.standard_normal((n, h, lk, d)).astype(dtype)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


def _numpy_attention(q, k, v, kv_len=None):
    q, k, v = map(np.asarray, (q, k, v))
    s = np.einsum("nhqd,nhkd->nhqk", q, k).astype(np.float64)
    s *= q.shape[-1] ** -0.5
    if kv_len is not None:
        s[..., kv_len:] = -np.inf
    s -= s.max(axis=-1, keepdims=True)
    p = np.exp(s)
    p /= p.sum(axis=-1, keepdims=True)
    return np.einsum("nhqk,nhkd->nhqd", p, v)


@pytest.mark.quick
def test_fused_attention_matches_numpy():
    q, k, v = _qkv(np.random.default_rng(0))
    out = attention(q, k, v)
    ref = _numpy_attention(q, k, v)
    assert np.allclose(np.asarray(out), ref, atol=1e-5)


def test_all_masked_prefix_is_cancelled():
    """Masked-block pollution of (l, acc) must be erased by the fp32
    underflow of the correction factor once a valid block arrives: an
    all-masked PREFIX (garbage v in the padding) must not leak into the
    output (the contract _finalize documents)."""
    from video_features_tpu.ops.attention import init_carry, online_softmax_step

    rng = np.random.default_rng(7)
    q, k, v = _qkv(rng, lk=16)
    scale = q.shape[-1] ** -0.5
    # poison the first 8 KV positions with huge values, then mask them
    v = v.at[:, :, :8].set(1e6)
    m, l, acc = init_carry(q)
    mask0 = jnp.zeros((1, 1, 1, 8), bool)
    m, l, acc = online_softmax_step(q, k[:, :, :8], v[:, :, :8], m, l, acc, scale, mask0)
    assert float(jnp.max(l)) > 0  # the documented pollution is real
    m, l, acc = online_softmax_step(q, k[:, :, 8:], v[:, :, 8:], m, l, acc, scale)
    from video_features_tpu.ops.attention import _finalize

    out_masked = _finalize(m, l, acc, q.dtype)
    ref = _numpy_attention(q, k[:, :, 8:], v[:, :, 8:])
    np.testing.assert_allclose(np.asarray(out_masked), ref, atol=1e-5)
    assert np.abs(np.asarray(out_masked)).max() < 1e3  # no 1e6 leakage


def test_kv_len_zero_rejected():
    q, k, v = _qkv(np.random.default_rng(8))
    with pytest.raises(ValueError, match="kv_len"):
        attention(q, k, v, kv_len=0)
    with pytest.raises(ValueError, match="kv_len"):
        blockwise_attention(q, k, v, kv_len=0)


def test_fused_attention_kv_mask():
    q, k, v = _qkv(np.random.default_rng(1))
    out = attention(q, k, v, kv_len=13)
    ref = _numpy_attention(q, k, v, kv_len=13)
    assert np.allclose(np.asarray(out), ref, atol=1e-5)
    # masked == physically truncated
    trunc = attention(q, k[:, :, :13], v[:, :, :13])
    assert np.allclose(np.asarray(out), np.asarray(trunc), atol=1e-6)


@pytest.mark.parametrize("block", [4, 16, 64])
@pytest.mark.quick
def test_blockwise_matches_fused(block):
    q, k, v = _qkv(np.random.default_rng(2), lq=31, lk=57)
    ref = attention(q, k, v)
    out = blockwise_attention(q, k, v, block_size=block)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_blockwise_kv_len_composes_with_block_padding():
    q, k, v = _qkv(np.random.default_rng(3), lk=57)
    ref = attention(q, k[:, :, :40], v[:, :, :40])
    out = blockwise_attention(q, k, v, block_size=16, kv_len=40)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_blockwise_bf16_inputs_fp32_statistics():
    q, k, v = _qkv(np.random.default_rng(4), lk=32)
    qb, kb, vb = (t.astype(jnp.bfloat16) for t in (q, k, v))
    out = blockwise_attention(qb, kb, vb, block_size=8)
    assert out.dtype == jnp.bfloat16
    ref = attention(q, k, v)
    assert np.allclose(
        np.asarray(out, dtype=np.float32), np.asarray(ref), atol=3e-2
    )


def test_ring_attention_matches_fused_on_mesh():
    mesh = make_mesh(jax.devices()[:8], data=8, model=1)
    # 64 tokens over 8 chips — evenly divisible, no mask needed
    q, k, v = _qkv(np.random.default_rng(5), lq=64, lk=64, d=16)
    ref = attention(q, k, v)

    @jax.jit
    def fn(q, k, v):
        return ring_attention_sharded(q, k, v, mesh, axis_name="data")

    out = fn(q, k, v)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_attention_padded_tokens_masked():
    """ViT case: 50 patch tokens padded to 56 over a 8-way ring."""
    mesh = make_mesh(jax.devices()[:8], data=8, model=1)
    q, k, v = _qkv(np.random.default_rng(6), lq=50, lk=50, d=16)
    ref = attention(q, k, v)
    pad = ((0, 0), (0, 0), (0, 6), (0, 0))
    qp = jnp.pad(q, pad)
    kp = jnp.pad(k, pad)
    vp = jnp.pad(v, pad)

    @jax.jit
    def fn(q, k, v):
        return ring_attention_sharded(
            q, k, v, mesh, axis_name="data", kv_len=50
        )

    out = fn(qp, kp, vp)[:, :, :50]
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_attention_inside_gspmd_jit_sharded_io():
    """The product shape: inputs arrive sharded, jit keeps them sharded."""
    mesh = make_mesh(jax.devices()[:8], data=4, model=2)
    q, k, v = _qkv(np.random.default_rng(7), lq=32, lk=32, d=16)
    ref = attention(q, k, v)
    sh = NamedSharding(mesh, P(None, None, "data", None))
    qs, ks, vs = (jax.device_put(t, sh) for t in (q, k, v))

    @jax.jit
    def fn(q, k, v):
        return ring_attention_sharded(q, k, v, mesh, axis_name="data")

    out = fn(qs, ks, vs)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("block", [4, 16])
def test_ring_attention_blockwise_shards_match_fused(block):
    """block_size chunks each arriving KV shard through the blockwise
    accumulator (ring x flash composition) — still exact, including with
    a padded+masked token axis."""
    mesh = make_mesh(jax.devices()[:8], data=8, model=1)
    q, k, v = _qkv(np.random.default_rng(11), lq=50, lk=50, d=16)
    ref = attention(q, k, v)
    pad = ((0, 0), (0, 0), (0, 6), (0, 0))
    qp, kp, vp = (jnp.pad(t, pad) for t in (q, k, v))

    @jax.jit
    def fn(q, k, v):
        return ring_attention_sharded(
            q, k, v, mesh, axis_name="data", kv_len=50, block_size=block
        )

    out = fn(qp, kp, vp)[:, :, :50]
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_attention_single_shard_axis():
    mesh = make_mesh(jax.devices()[:2], data=1, model=2)
    q, k, v = _qkv(np.random.default_rng(8), lq=8, lk=8)
    ref = attention(q, k, v)
    out = ring_attention_sharded(q, k, v, mesh, axis_name="data")
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_ring_attention_rejects_indivisible_tokens():
    mesh = make_mesh(jax.devices()[:8], data=8, model=1)
    q, k, v = _qkv(np.random.default_rng(9), lq=50, lk=50)
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention_sharded(q, k, v, mesh, axis_name="data")


@pytest.mark.parametrize("block", [None, 8])
def test_context_parallel_core_pads_and_masks(block):
    """make_context_parallel_core handles the ViT's ragged token axis
    (grid*grid+1) transparently — same answer as fused attention — with
    and without per-shard blockwise chunking."""
    from video_features_tpu.parallel.ring_attention import (
        make_context_parallel_core,
    )

    mesh = make_mesh(jax.devices()[:8], data=4, model=2)
    core = make_context_parallel_core(mesh, block_size=block)
    # 50 tokens (B/32 grid), 4 heads over model=2
    q, k, v = _qkv(np.random.default_rng(10), h=4, lq=50, lk=50, d=16)
    ref = attention(q, k, v)
    out = jax.jit(core)(q, k, v)
    assert out.shape == q.shape
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_clip_vit_mesh_context_matches_single_device():
    """The --mesh_context model path: a CLIP ViT with ring attention
    injected as attn_core, token axis sharded over the mesh, equals the
    plain single-device forward."""
    from video_features_tpu.models.clip.model import (
        CLIPVisionConfig,
        VisionTransformer,
        init_params,
    )
    from video_features_tpu.parallel.ring_attention import (
        make_context_parallel_core,
    )
    from video_features_tpu.parallel.sharding import (
        build_sharded_apply,
        clip_vit_param_specs,
        shard_params,
    )
    from jax.sharding import PartitionSpec as P

    cfg = CLIPVisionConfig(
        patch_size=8, width=64, layers=2, heads=4, embed_dim=32, image_size=48
    )  # 6x6 grid -> 37 tokens: exercises the pad+mask path on every mesh
    params = init_params(cfg)
    x = jnp.asarray(
        np.random.RandomState(0).randn(3, 3, 48, 48).astype(np.float32)
    )
    plain = VisionTransformer(cfg)
    ref = np.asarray(jax.jit(lambda p, v: plain.apply({"params": p}, v))(params, x))

    mesh = make_mesh(jax.devices(), data=4, model=2)
    model = VisionTransformer(cfg, attn_core=make_context_parallel_core(mesh))
    sharded = shard_params(params, mesh, clip_vit_param_specs(params))
    fn = build_sharded_apply(model, mesh, batch_spec=P(), out_spec=P())
    out = np.asarray(fn(sharded, x))
    assert out.shape == ref.shape
    np.testing.assert_allclose(out, ref, atol=2e-4)
