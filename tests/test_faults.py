"""Fault tolerance: classification, retries, degradation, manifest.

Every path ISSUE 3 promises is exercised here on CPU via the
deterministic ``--fault_inject`` hook (runtime/faults.py): decode
error/hang, prepare failure, simulated-OOM fused dispatch, sink kill —
classified, retried per policy, and either recovered or recorded failed;
plus the ``--resume`` contract over the resulting manifest. A toy
extractor keeps the loop mechanics fast; one test drives the real CLIP
CLI for the ``--strict`` exit contract.
"""

import glob
import json
import os
import threading

import numpy as np
import pytest

from video_features_tpu.config import ExtractionConfig, sanity_check
from video_features_tpu.extract.base import BaseExtractor
from video_features_tpu.io.paths import video_path_of
from video_features_tpu.io.video import stream_frames
from video_features_tpu.runtime import faults

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clear_global_fault_state():
    """The injector and decode deadline are process-global (installed by
    each extractor's __init__); never leak one test's faults into the
    rest of the suite."""
    yield
    faults.install_injector(None)
    from video_features_tpu.io.video import set_decode_timeout

    set_decode_timeout(None)


@pytest.fixture(scope="module")
def toy_videos(tmp_path_factory):
    from video_features_tpu.utils.synth import synth_video

    d = tmp_path_factory.mktemp("toy_media")
    return [
        synth_video(str(d / f"v{i}.mp4"), n_frames=10, width=64, height=48, seed=i)
        for i in range(4)
    ]


class ToyExtractor(BaseExtractor):
    """Minimal prepare/extract_prepared extractor: per-frame means. One
    real decode (one _Reader open => one 'decode' injection call) per
    prepare; trivial compute; the real sink."""

    feature_type = "toy"

    def _build(self, device):
        return {"device": device}

    def prepare(self, path_entry):
        vals = [float(frame.mean()) for frame, _ in stream_frames(video_path_of(path_entry))]
        return np.asarray(vals, dtype=np.float32)

    def extract_prepared(self, device, state, path_entry, payload):
        return {
            "toy": np.asarray(payload).reshape(-1, 1),
            "fps": 25.0,
            "timestamps_ms": np.arange(len(payload), dtype=np.float64),
        }


class ToyAgg(ToyExtractor):
    """Adds the --video_batch aggregation protocol (same-shape payloads
    fuse; the fused dispatch is where the OOM injection lands)."""

    def agg_key(self, payload):
        return np.asarray(payload).shape

    def dispatch_group(self, device, state, entries, payloads):
        return [
            ToyExtractor.extract_prepared(self, device, state, e, p)
            for e, p in zip(entries, payloads)
        ]

    def fetch_group(self, handle):
        return handle


class DevToy(ToyExtractor):
    """Models --preprocess device: prepare returns a tagged device
    payload whose dispatch always dies with a compile-marker error, so
    the device->host fallback (re-prepare with the thread-local
    force-host flag) is the only road to 'done'."""

    def prepare(self, path_entry):
        base = super().prepare(path_entry)
        if self._device_preprocess_enabled():
            return ("device-payload", base)
        return base

    def extract_prepared(self, device, state, path_entry, payload):
        if isinstance(payload, tuple):
            raise RuntimeError("Mosaic lowering failed for fused preprocess program")
        return super().extract_prepared(device, state, path_entry, payload)


def _cfg(videos, tmp_path, **kw):
    kw.setdefault("decode_workers", 1)  # serial prep order => deterministic injection counters
    kw.setdefault("retry_backoff", 0.01)
    return ExtractionConfig(
        allow_random_init=True,
        video_paths=list(videos),
        on_extraction="save_numpy",
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
        cpu=True,
        **kw,
    )


def _summary(cfg):
    s = faults.finalize_run(cfg.output_path)
    assert s is not None
    return s


# --- classification / policy units ------------------------------------------


def test_classification_buckets():
    assert faults.classify_error(faults.CorruptVideoError("bad bytes")) == "permanent"
    assert faults.classify_error(faults.DecodeTimeout("stall")) == "transient"
    assert faults.classify_error(OSError("io flake")) == "transient"
    assert faults.classify_error(TimeoutError("t")) == "transient"
    assert faults.classify_error(MemoryError()) == "oom"
    assert faults.classify_error(RuntimeError("RESOURCE_EXHAUSTED: hbm")) == "oom"
    assert faults.classify_error(RuntimeError("error during lowering")) == "compile"
    assert faults.classify_error(ValueError("shape mismatch")) == "permanent"
    # corrupt IS an OSError subclass but must not take the transient rule
    assert issubclass(faults.CorruptVideoError, IOError)
    assert faults.is_retryable("transient") and faults.is_retryable("oom")
    assert not faults.is_retryable("compile") and not faults.is_retryable("permanent")


def test_backoff_deterministic_and_exponential():
    a1 = faults.backoff_delay(1, 0.5, "/v/a.mp4")
    assert a1 == faults.backoff_delay(1, 0.5, "/v/a.mp4")
    assert 0.25 <= a1 <= 0.5
    assert 0.5 <= faults.backoff_delay(2, 0.5, "/v/a.mp4") <= 1.0
    assert faults.backoff_delay(3, 0.0, "k") == 0.0
    # jitter desynchronizes different videos
    keys = [f"/v/{i}.mp4" for i in range(16)]
    assert len({faults.backoff_delay(1, 0.5, k) for k in keys}) > 4


def test_fault_spec_validation():
    specs = faults.parse_fault_specs(["decode:hang:2", "sink:kill:1"])
    assert specs[0] == faults.FaultSpec("decode", "hang", 2)
    for bad in ("decode:error", "warp:error:1", "decode:melt:1", "decode:error:0"):
        with pytest.raises(ValueError, match="fault_inject"):
            faults.parse_fault_specs([bad])
    with pytest.raises(ValueError, match="fault_inject"):
        sanity_check(ExtractionConfig(fault_inject=["decode:error:nope"]))
    with pytest.raises(ValueError, match="retry_failed"):
        sanity_check(ExtractionConfig(retry_failed=True))
    with pytest.raises(ValueError, match="retries"):
        sanity_check(ExtractionConfig(retries=-1))
    with pytest.raises(ValueError, match="decode_timeout"):
        sanity_check(ExtractionConfig(decode_timeout=0.0))


def test_manifest_merge_last_terminal_wins(tmp_path):
    m = faults.RunManifest(str(tmp_path))
    m.record("/v/a.mp4", "retry", stage="decode", error_class="transient", attempts=1)
    m.record("/v/a.mp4", "done", attempts=2)
    m.record("/v/b.mp4", "failed", stage="prepare", error_class="permanent")
    s = faults.merge_manifest(str(tmp_path))
    assert s["videos"]["/v/a.mp4"]["status"] == "done"
    assert s["videos"]["/v/a.mp4"]["attempts"] == 2
    assert s["retries"] == 1 and s["failed"] == 1
    # a later resume run's 'skipped' probe must never demote a 'done'
    m2 = faults.RunManifest(str(tmp_path))
    m2.record("/v/a.mp4", "skipped", message="outputs exist")
    s2 = faults.merge_manifest(str(tmp_path))
    assert s2["videos"]["/v/a.mp4"]["status"] == "done"
    assert faults.permanently_failed_videos(str(tmp_path)) == {"/v/b.mp4"}


def test_strict_failures_cover_warnings_and_deaths(tmp_path):
    m = faults.RunManifest(str(tmp_path))
    m.record("/v/a.mp4", "done", attempts=1)
    m.record("/v/a.mp4", "warning", stage="sink", message="the value is empty for toy")
    m.event("worker_death", device="cpu:0", error_type="RuntimeError", message="boom")
    s = faults.finalize_run(str(tmp_path))
    probs = faults.strict_failures(s)
    assert len(probs) == 2
    assert any("empty" in p for p in probs) and any("worker death" in p for p in probs)


# --- injected faults through the real extractor loop ------------------------


def test_decode_error_retries_and_recovers(toy_videos, tmp_path, capsys):
    # decode call 3 (third reader open) fires: v2's first attempt fails
    # transient, its retry (call 4) succeeds
    cfg = _cfg(toy_videos[:3], tmp_path, retries=1, fault_inject=["decode:error:3"])
    ToyExtractor(cfg)()
    s = _summary(cfg)
    assert s["done"] == 3 and s["failed"] == 0 and s["retries"] == 1
    v2 = s["videos"][toy_videos[2]]
    assert v2["status"] == "done" and v2["attempts"] == 2
    assert "retrying in" in capsys.readouterr().out
    assert len(glob.glob(os.path.join(cfg.output_path, "toy", "*.npy"))) == 3


def test_decode_hang_hits_deadline_and_exhausts_retries(toy_videos, tmp_path, capsys):
    # every reader open hangs HANG_SECONDS=0.4 > the 0.1 s deadline: the
    # REAL DecodeTimeout fires on the next grab(), each retry re-hangs,
    # and the video is recorded failed-transient after the budget.
    # One video => the serial loop, so both loops' retry paths get covered.
    cfg = _cfg(
        toy_videos[:1],
        tmp_path,
        retries=1,
        decode_timeout=0.1,
        fault_inject=["decode:hang:1"],
    )
    ToyExtractor(cfg)()
    s = _summary(cfg)
    assert s["failed"] == 1 and s["retries"] == 1
    rec = s["videos"][toy_videos[0]]
    assert rec["status"] == "failed"
    assert rec["error_type"] == "DecodeTimeout"
    assert rec["error_class"] == "transient"
    assert rec["stage"] == "decode"
    assert rec["attempts"] == 2
    assert "An error occurred" in capsys.readouterr().out


def test_corrupt_video_fails_fast_no_retry(toy_videos, tmp_path, capsys):
    bad = tmp_path / "bad.mp4"
    bad.write_bytes(b"not a video at all")
    # preflight off: this test pins the decode-path classification; the
    # preflight-on rejection of the same file is covered in
    # tests/test_hostile_media.py
    cfg = _cfg([toy_videos[0], str(bad)], tmp_path, retries=2, preflight="off")
    ToyExtractor(cfg)()
    s = _summary(cfg)
    assert s["done"] == 1 and s["failed"] == 1 and s["retries"] == 0
    rec = s["videos"][str(bad)]
    assert rec["error_class"] == "permanent" and rec["attempts"] == 1
    assert rec["error_type"] == "CorruptVideoError"
    out = capsys.readouterr().out
    assert out.count("An error occurred") == 1


def test_injected_prepare_permanent_fails_fast(toy_videos, tmp_path):
    # prepare call 2 (v1) raises the unfixable kind: no retry records
    cfg = _cfg(toy_videos[:3], tmp_path, retries=2, fault_inject=["prepare:corrupt:2"])
    ToyExtractor(cfg)()
    s = _summary(cfg)
    assert s["done"] == 2 and s["failed"] == 1 and s["retries"] == 0
    rec = s["videos"][toy_videos[1]]
    assert rec["error_class"] == "permanent" and rec["stage"] == "prepare"


def test_group_oom_dispatch_splits_and_recovers(toy_videos, tmp_path, capsys):
    # EVERY fused dispatch OOMs; the solo fallback re-runs members with
    # injection suppressed, so all four videos recover individually
    cfg = _cfg(
        toy_videos,
        tmp_path,
        video_batch=2,
        retries=0,
        fault_inject=["dispatch:oom:1"],
    )
    ToyAgg(cfg)()
    s = _summary(cfg)
    assert s["done"] == 4 and s["failed"] == 0
    falls = [e for e in s["events"] if e.get("event") == "group_fallback"]
    assert len(falls) == 2 and all(f["size"] == 2 for f in falls)
    out = capsys.readouterr().out
    assert out.count("Fused --video_batch dispatch failed") == 2
    assert "An error occurred" not in out
    assert len(glob.glob(os.path.join(cfg.output_path, "toy", "*.npy"))) == 4


def test_sink_kill_is_atomic_and_resume_retries(toy_videos, tmp_path):
    # killed between tmp write and rename: nothing the resume probe
    # trusts may exist (ISSUE 3 satellite: atomic-write + --resume)
    cfg = _cfg(toy_videos[:2], tmp_path, retries=0, fault_inject=["sink:kill:1"])
    ToyExtractor(cfg)()
    s = _summary(cfg)
    assert s["failed"] == 2
    assert all(v["stage"] == "sink" for v in s["videos"].values())
    feat_dir = os.path.join(cfg.output_path, "toy")
    assert glob.glob(os.path.join(feat_dir, "*.npy")) == []
    assert glob.glob(os.path.join(feat_dir, "*.tmp*")) == []
    # second invocation: --resume --retry_failed re-attempts (the kill is
    # classified permanent) with no injection -> completes the run
    cfg2 = _cfg(toy_videos[:2], tmp_path, resume=True, retry_failed=True)
    ToyExtractor(cfg2)()
    s2 = _summary(cfg2)
    assert s2["done"] == 2 and s2["failed"] == 0
    assert len(glob.glob(os.path.join(feat_dir, "*.npy"))) == 2


def test_resume_skips_permanent_failures_unless_retry_failed(toy_videos, tmp_path):
    bad = tmp_path / "bad.mp4"
    bad.write_bytes(b"junk")
    videos = [toy_videos[0], str(bad)]
    cfg = _cfg(videos, tmp_path)
    ToyExtractor(cfg)()
    assert _summary(cfg)["failed"] == 1
    # resume: the permanent failure is skipped, not re-decoded
    cfg2 = _cfg(videos, tmp_path, resume=True)
    ex2 = ToyExtractor(cfg2)
    assert str(bad) in ex2._prior_failed
    ex2()
    s2 = _summary(cfg2)
    assert s2["videos"][str(bad)]["status"] == "failed"  # skip never demotes
    records = [
        r
        for r in faults.iter_manifest_records(cfg2.output_path)
        if r.get("video") == str(bad) and r.get("status") == "skipped"
    ]
    assert records and "permanent failure" in records[-1]["message"]
    # --retry_failed: re-attempted (and fails again — the bytes are junk)
    cfg3 = _cfg(videos, tmp_path, resume=True, retry_failed=True)
    ex3 = ToyExtractor(cfg3)
    assert ex3._prior_failed == set()
    ex3()
    attempts = [
        r
        for r in faults.iter_manifest_records(cfg3.output_path)
        if r.get("video") == str(bad) and r.get("status") == "failed"
    ]
    assert len(attempts) == 2


def test_device_preprocess_falls_back_to_host(toy_videos, tmp_path, capsys):
    cfg = _cfg(toy_videos[:2], tmp_path, preprocess="device", retries=0)
    DevToy(cfg)()
    s = _summary(cfg)
    assert s["failed"] == 0 and s["done"] == 2
    for v in toy_videos[:2]:
        assert s["videos"][v]["status"] == "done"
    fallbacks = [
        r
        for r in faults.iter_manifest_records(cfg.output_path)
        if r.get("status") == "fallback"
    ]
    assert len(fallbacks) == 2
    assert all(r["error_class"] == "compile" for r in fallbacks)
    done_notes = [
        r.get("note")
        for r in faults.iter_manifest_records(cfg.output_path)
        if r.get("status") == "done"
    ]
    assert done_notes.count("device->host preprocess fallback") == 2
    assert "falling back to the host chain" in capsys.readouterr().out
    assert len(glob.glob(os.path.join(cfg.output_path, "toy", "*.npy"))) == 2


def test_output_direct_resume_probes_collapsed_name(toy_videos, tmp_path):
    cfg = _cfg(toy_videos[:1], tmp_path, output_direct=True)
    ToyExtractor(cfg)()
    stem = os.path.splitext(os.path.basename(toy_videos[0]))[0]
    assert os.path.exists(os.path.join(cfg.output_path, f"{stem}.npy"))
    cfg2 = _cfg(toy_videos[:1], tmp_path, output_direct=True, resume=True)
    ToyExtractor(cfg2)()
    skips = [
        r
        for r in faults.iter_manifest_records(cfg2.output_path)
        if r.get("status") == "skipped"
    ]
    assert skips and skips[-1]["message"] == "outputs exist"


def test_empty_feature_recorded_as_manifest_warning(toy_videos, tmp_path):
    class EmptyToy(ToyExtractor):
        def extract_prepared(self, device, state, path_entry, payload):
            d = super().extract_prepared(device, state, path_entry, payload)
            d["toy"] = np.zeros((0, 1), dtype=np.float32)
            return d

    cfg = _cfg(toy_videos[:1], tmp_path)
    EmptyToy(cfg)()
    s = _summary(cfg)
    assert s["done"] == 1
    assert len(s["warnings"]) == 1 and "empty" in s["warnings"][0]["message"]
    assert faults.strict_failures(s)  # --strict would fail the run on it


# --- sink atomicity under concurrency (the tmp-name race satellite) ----------


def test_concurrent_sink_threads_do_not_clobber_tmp(tmp_path):
    from video_features_tpu.io.sink import action_on_extraction

    value = np.arange(4096, dtype=np.float32).reshape(64, 64)
    errors = []

    def save():
        try:
            for _ in range(25):
                action_on_extraction(
                    {"toy": value}, "/v/same.mp4", str(tmp_path), "save_numpy"
                )
        except BaseException as e:  # noqa: BLE001 - the race under test
            errors.append(e)

    threads = [threading.Thread(target=save) for _ in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []
    np.testing.assert_array_equal(np.load(tmp_path / "same_toy.npy"), value)
    assert glob.glob(str(tmp_path / "*.tmp*")) == []


# --- scheduler: worker deaths --------------------------------------------


class _SchedFake:
    def __init__(self, n, tmp, retries=2, die_on=()):
        from tqdm import tqdm

        self.config = ExtractionConfig(allow_random_init=True, retries=retries)
        self.path_list = [f"/v/{i}.mp4" for i in range(n)]
        self.progress = tqdm(total=n, disable=True)
        self.manifest = faults.RunManifest(str(tmp))
        self.die_on = set(die_on)
        self.done = []

    def warmup(self, device):
        return {}

    def _video_key(self, entry):
        return str(entry)

    def __call__(self, chunk, device=None):
        if device in self.die_on:
            raise RuntimeError(f"hbm fault on {device}")
        self.done.extend(chunk)
        for _ in chunk:
            self.progress.update()


def test_all_dead_error_summarizes_every_death(tmp_path):
    from video_features_tpu.parallel.scheduler import parallel_feature_extraction

    fake = _SchedFake(6, tmp_path, die_on={"devA", "devB"})
    with pytest.raises(RuntimeError, match="unprocessed") as ei:
        parallel_feature_extraction(fake, devices=["devA", "devB"])
    msg = str(ei.value)
    assert "devA" in msg and "devB" in msg and "2 worker death(s)" in msg
    deaths = [
        e
        for e in faults.iter_manifest_records(str(tmp_path))
        if e.get("event") == "worker_death"
    ]
    assert len(deaths) == 2
    assert all(d["error_type"] == "RuntimeError" for d in deaths)


def test_worker_death_requeue_cap_records_failed(tmp_path):
    from video_features_tpu.parallel.scheduler import parallel_feature_extraction

    # retries=0: the dying worker's chunk is dropped + recorded instead
    # of ping-ponging, and the run completes without raising
    fake = _SchedFake(4, tmp_path, retries=0, die_on={"devA"})
    parallel_feature_extraction(fake, devices=["devA"])
    failed = [
        r
        for r in faults.iter_manifest_records(str(tmp_path))
        if r.get("status") == "failed"
    ]
    assert len(failed) == 4
    assert all(r["stage"] == "worker" for r in failed)


# --- subprocess decode deadline ---------------------------------------------


def test_subprocess_timeout_becomes_decode_timeout():
    from video_features_tpu.io.ffmpeg import _run

    with pytest.raises(faults.DecodeTimeout, match="decode_timeout"):
        _run(["sleep", "5"], timeout_s=0.2)


# --- the acceptance matrix: mixed faults, then --resume ----------------------


def test_acceptance_faulted_run_then_resume_touches_only_undone(
    toy_videos, tmp_path
):
    # one permanent decode failure (v1 corrupt) + one sink kill (first
    # sink call = v0): run 1 finishes 2/4, records both failures classified
    bad = tmp_path / "bad.mp4"
    bad.write_bytes(b"definitely not mp4")
    videos = [toy_videos[0], str(bad), toy_videos[2], toy_videos[3]]
    cfg = _cfg(videos, tmp_path, retries=1, fault_inject=["sink:kill:3"])
    ToyExtractor(cfg)()
    s = _summary(cfg)
    assert s["done"] == 2 and s["failed"] == 2
    assert s["videos"][str(bad)]["error_class"] == "permanent"
    killed = [k for k, v in s["videos"].items() if v.get("stage") == "sink"]
    assert len(killed) == 1
    feat_dir = os.path.join(cfg.output_path, "toy")
    done_files = sorted(glob.glob(os.path.join(feat_dir, "*.npy")))
    assert len(done_files) == 2
    mtimes = {f: os.path.getmtime(f) for f in done_files}

    # run 2: --resume --retry_failed completes the run touching ONLY the
    # non-done videos (done outputs' mtimes unchanged; the corrupt one
    # re-fails — its bytes are still junk)
    cfg2 = _cfg(videos, tmp_path, resume=True, retry_failed=True)
    ToyExtractor(cfg2)()
    s2 = _summary(cfg2)
    assert s2["done"] == 3 and s2["failed"] == 1
    assert len(glob.glob(os.path.join(feat_dir, "*.npy"))) == 3
    for f, t in mtimes.items():
        assert os.path.getmtime(f) == t, f"resume re-touched a done output: {f}"
    skipped = [
        r
        for r in faults.iter_manifest_records(cfg2.output_path)
        if r.get("status") == "skipped"
    ]
    assert len(skipped) == 2  # both done videos probed + skipped


# --- --strict through the real CLI -------------------------------------------


def test_strict_exit_nonzero_through_cli(tmp_path, sample_video):
    from video_features_tpu import cli

    argv = [
        "--feature_type", "CLIP-ViT-B/32",
        "--video_paths", sample_video,
        "--extract_method", "uni_4",
        "--cpu", "--allow_random_init",
        "--on_extraction", "save_numpy",
        "--output_path", str(tmp_path / "out"),
        "--tmp_path", str(tmp_path / "tmp"),
        "--retries", "0",
        "--strict",
        "--fault_inject", "sink:kill:1",
    ]
    with pytest.raises(SystemExit, match="--strict"):
        cli.main(argv)
    summary = json.load(
        open(os.path.join(tmp_path, "out", "_manifest", "summary.json"))
    )
    assert summary["failed"] == 1
    # the same run without --strict completes with exit 0 (drop the kill
    # so the sink succeeds; resume re-attempts the failed video)
    cli.main([a for a in argv if a not in ("--strict", "--fault_inject", "sink:kill:1")]
             + ["--resume", "--retry_failed"])
    summary2 = json.load(
        open(os.path.join(tmp_path, "out", "_manifest", "summary.json"))
    )
    assert summary2["done"] == 1 and summary2["failed"] == 0
