"""Async device ingest (extract/ingest.py + the restructured
_run_pipelined): completion-queue depth/ordering, fused-failure -> solo
fallback with >2 groups in flight, donation-safe payload lifetime,
timer-scheduled retry backoff, frame-delta gating parity and skip
behavior, and the ingest heartbeat/metrics gauges.
"""

import threading
import time

import numpy as np
import pytest

from video_features_tpu.config import ExtractionConfig, sanity_check
from video_features_tpu.extract import ingest
from video_features_tpu.extract.base import BaseExtractor
from video_features_tpu.io.paths import video_path_of
from video_features_tpu.io.video import stream_frames
from video_features_tpu.ops.sampler import copy_forward, frame_delta_keep_mask
from video_features_tpu.runtime import faults

pytestmark = pytest.mark.faults


@pytest.fixture(autouse=True)
def _clear_global_fault_state():
    yield
    faults.install_injector(None)
    from video_features_tpu.io.video import set_decode_timeout

    set_decode_timeout(None)


@pytest.fixture(scope="module")
def toy_videos(tmp_path_factory):
    from video_features_tpu.utils.synth import synth_video

    d = tmp_path_factory.mktemp("ingest_media")
    return [
        synth_video(str(d / f"v{i}.mp4"), n_frames=10, width=64, height=48, seed=i)
        for i in range(6)
    ]


def _cfg(videos, tmp_path, **kw):
    kw.setdefault("decode_workers", 1)
    kw.setdefault("retry_backoff", 0.01)
    return ExtractionConfig(
        allow_random_init=True,
        video_paths=list(videos),
        on_extraction="save_numpy",
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
        cpu=True,
        **kw,
    )


class ToyExtractor(BaseExtractor):
    feature_type = "toy"

    def _build(self, device):
        return {"device": device}

    def prepare(self, path_entry):
        vals = [
            float(frame.mean())
            for frame, _ in stream_frames(video_path_of(path_entry))
        ]
        return np.asarray(vals, dtype=np.float32)

    def extract_prepared(self, device, state, path_entry, payload):
        return {
            "toy": np.asarray(payload).reshape(-1, 1),
            "fps": 25.0,
            "timestamps_ms": np.arange(len(payload), dtype=np.float64),
        }


class _Handle:
    """A dispatch handle with controllable device-side readiness: the
    loop's non-blocking drain must treat ready=False as still-computing
    (never popping it early) and ready=True as drainable."""

    def __init__(self, value, ready=False):
        self.value = value
        self._ready = ready

    def is_ready(self):
        return self._ready


class ToyAggDeep(ToyExtractor):
    """Aggregation toy whose handles report not-ready until fetched,
    so the completion queue genuinely FILLS to --inflight_groups (a
    real jax handle on CPU completes near-instantly and would be
    opportunistically drained at depth 1)."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.events = []  # ("dispatch"|"fetch", [video names...])
        self.max_inflight = 0
        self._open = 0

    def agg_key(self, payload):
        return np.asarray(payload).shape

    def dispatch_group(self, device, state, entries, payloads):
        self._open += 1
        self.max_inflight = max(self.max_inflight, self._open)
        self.events.append(("dispatch", [str(e) for e in entries]))
        dicts = [
            ToyExtractor.extract_prepared(self, device, state, e, p)
            for e, p in zip(entries, payloads)
        ]
        return _Handle(dicts, ready=False)

    def fetch_group(self, handle):
        self._open -= 1
        self.events.append(("fetch", [len(handle.value)]))
        return handle.value


# --- pure units --------------------------------------------------------------


def test_completion_queue_fifo_and_head_readiness():
    q = ingest.CompletionQueue(3)
    assert len(q) == 0 and not q and not q.head_ready()
    h1, h2 = _Handle(1), _Handle(2)
    q.push(["a"], h1, False, None)
    q.push(["b"], h2, False, None)
    assert len(q) == 2 and not q.full
    assert not q.head_ready()  # h1 still computing
    h2._ready = True  # a LATER entry finishing never unblocks the head
    assert not q.head_ready()
    h1._ready = True
    assert q.head_ready()
    assert q.pop()[0] == ["a"]  # FIFO
    assert q.pop()[0] == ["b"]
    q2 = ingest.CompletionQueue(1)
    q2.push(["x"], _Handle(0), False, None)
    assert q2.full


def test_handle_ready_mixed_leaves():
    # host-only handles (numpy, floats, nested tuples) are always ready
    assert ingest.handle_ready((np.zeros(3), 1.0, [("meta", 2)]))
    # one not-ready probe anywhere in the tree blocks the whole handle
    assert not ingest.handle_ready((np.zeros(3), _Handle(0)))
    assert ingest.handle_ready((np.zeros(3), _Handle(0, ready=True)))


def test_requeue_timers_schedule_and_pending():
    timers = ingest.RequeueTimers()
    fired = []
    timers.schedule(0.0, lambda: fired.append("now"))  # zero delay: inline
    assert fired == ["now"] and timers.pending() == 0
    timers.schedule(0.05, lambda: fired.append("later"))
    assert timers.pending() == 1
    deadline = time.monotonic() + 2.0
    while timers.pending() and time.monotonic() < deadline:
        timers.wait_any(0.05)
    assert timers.pending() == 0
    # pending() hit zero only AFTER the fire ran (the drain-loop contract)
    assert fired == ["now", "later"]


def test_frame_delta_keep_mask_semantics():
    a = np.zeros((4, 4, 3), dtype=np.uint8)
    b = np.full((4, 4, 3), 200, dtype=np.uint8)
    # static: only frame 0 kept
    assert frame_delta_keep_mask([a, a, a, a], 3.0).tolist() == [
        True, False, False, False,
    ]
    # threshold 0 keeps everything (strictly-below skip rule)
    assert frame_delta_keep_mask([a, a, a], 0.0).all()
    # comparison is against the last KEPT frame: a slow drift of +2/frame
    # under threshold 5 re-keys once the accumulated delta crosses it
    drift = [np.full((4, 4, 3), v, dtype=np.uint8) for v in (0, 2, 4, 6, 8)]
    assert frame_delta_keep_mask(drift, 5.0).tolist() == [
        True, False, False, True, False,
    ]
    # a hard cut is always kept
    assert frame_delta_keep_mask([a, b, a], 3.0).all()


def test_copy_forward_expands_kept_rows():
    rows = np.array([[1.0], [2.0]])
    keep = np.array([True, False, True, False, False])
    np.testing.assert_array_equal(
        copy_forward(rows, keep), np.array([[1.0], [1.0], [2.0], [2.0], [2.0]])
    )
    # all-kept is the identity (the threshold-0 parity contract)
    full = np.arange(6, dtype=np.float64).reshape(3, 2)
    np.testing.assert_array_equal(copy_forward(full, np.ones(3, dtype=bool)), full)


def test_config_validates_ingest_knobs(toy_videos, tmp_path):
    sanity_check(_cfg(toy_videos, tmp_path, inflight_groups=4))
    with pytest.raises(ValueError, match="inflight_groups"):
        sanity_check(_cfg(toy_videos, tmp_path, inflight_groups=0))
    with pytest.raises(ValueError, match="frame_delta_threshold"):
        sanity_check(_cfg(toy_videos, tmp_path, frame_delta_threshold=-1.0))
    # the gate is only sound for frame-level (CLIP-family) extractors
    sanity_check(_cfg(toy_videos, tmp_path, frame_delta_threshold=2.0))
    with pytest.raises(ValueError, match="frame-level"):
        sanity_check(
            _cfg(toy_videos, tmp_path, feature_type="resnet50",
                 frame_delta_threshold=2.0)
        )


# --- completion-queue drain through the real loop ----------------------------


def test_deep_queue_fills_and_drains_fifo(toy_videos, tmp_path):
    """With not-ready handles and --inflight_groups 3, the loop must hold
    three dispatched groups in flight before blocking on the OLDEST
    (FIFO), and every video still sinks exactly once."""
    cfg = _cfg(toy_videos, tmp_path, video_batch=2, inflight_groups=3)
    ex = ToyAggDeep(cfg, external_call=True)
    results = ex()
    assert len(results) == 6  # 3 groups of 2
    assert ex.max_inflight == 3
    # drains are FIFO: the i-th fetch closes the i-th dispatch
    dispatches = [e for e in ex.events if e[0] == "dispatch"]
    fetches = [e for e in ex.events if e[0] == "fetch"]
    assert len(dispatches) == 3 and len(fetches) == 3
    inflight = ex.telemetry.metrics.gauge("queue_depth.inflight")
    assert inflight == 0  # fully drained at exit


def test_inflight_groups_one_is_lockstep(toy_videos, tmp_path):
    cfg = _cfg(toy_videos, tmp_path, video_batch=2, inflight_groups=1)
    ex = ToyAggDeep(cfg, external_call=True)
    results = ex()
    assert len(results) == 6
    assert ex.max_inflight == 1  # dispatch-then-fetch, never two in flight


def test_fused_fetch_failure_solo_fallback_deep_queue(toy_videos, tmp_path, capsys):
    """A fused fetch that dies while THREE groups are in flight recovers
    exactly its own members through the solo path; the other in-flight
    groups drain normally."""
    cfg = _cfg(toy_videos, tmp_path, video_batch=2, inflight_groups=3)
    ex = ToyAggDeep(cfg, external_call=True)
    real = ToyAggDeep.fetch_group
    calls = {"n": 0}

    def flaky(self, handle):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("injected fused-fetch failure")
        return real(self, handle)

    ex.fetch_group = flaky.__get__(ex)
    results = ex()
    assert len(results) == 6
    assert "falling back to per-video dispatch" in capsys.readouterr().out
    assert ex.progress.n == 6
    # outputs are identical to a clean solo run
    solo = ToyExtractor(_cfg(toy_videos, tmp_path), external_call=True)()
    for s, f in zip(solo, results):
        np.testing.assert_array_equal(f["toy"], s["toy"])


class DonatingToy(ToyAggDeep):
    """Simulates the donation contract: transfer_group stages host
    payloads into jax device buffers, dispatch consumes them and then
    DELETES the staged buffers (what donate_argnums does on TPU). The
    solo fallback must still succeed afterwards — from the HOST
    payloads the completion queue kept resident."""

    def transfer_group(self, device, state, entries, payloads):
        import jax

        staged = [jax.device_put(np.asarray(p)) for p in payloads]
        return ingest.StagedGroup(tuple(staged), [str(e) for e in entries])

    def dispatch_group(self, device, state, entries, payloads):
        assert isinstance(payloads, ingest.StagedGroup)
        self._open += 1
        self.max_inflight = max(self.max_inflight, self._open)
        dicts = [
            {
                "toy": np.asarray(arr).reshape(-1, 1),
                "fps": 25.0,
                "timestamps_ms": np.arange(np.asarray(arr).size, dtype=np.float64),
            }
            for arr in payloads.arrays
        ]
        for arr in payloads.arrays:  # donation: the staged buffers die here
            arr.delete()
        return _Handle(dicts, ready=False)


def test_donation_safe_payload_lifetime(toy_videos, tmp_path, capsys):
    """Staged device buffers are donated (deleted) at dispatch; a fused
    fetch failure must still recover every member solo, proving the
    HOST payloads stayed alive in the completion queue for the whole
    in-flight window."""
    cfg = _cfg(toy_videos, tmp_path, video_batch=2, inflight_groups=3)
    ex = DonatingToy(cfg, external_call=True)
    real = DonatingToy.fetch_group
    calls = {"n": 0}

    def flaky(self, handle):
        calls["n"] += 1
        if calls["n"] == 2:  # fail the MIDDLE group of three in flight
            raise RuntimeError("injected fused-fetch failure")
        return real(self, handle)

    ex.fetch_group = flaky.__get__(ex)
    results = ex()
    assert len(results) == 6
    assert "falling back to per-video dispatch" in capsys.readouterr().out
    solo = ToyExtractor(_cfg(toy_videos, tmp_path), external_call=True)()
    for s, f in zip(solo, results):
        np.testing.assert_array_equal(f["toy"], s["toy"])


# --- fault / resume parity at inflight_groups > 2 ----------------------------


def test_faults_and_resume_parity_at_deep_inflight(toy_videos, tmp_path):
    """The PR-3 contracts survive the restructure at --inflight_groups 4:
    an injected fused-dispatch OOM falls back per-video, the manifest
    records it, and a --resume pass over the same output dir skips the
    completed videos — outputs byte-identical to a clean shallow run."""
    import glob
    import os

    cfg = _cfg(
        toy_videos, tmp_path, video_batch=2, inflight_groups=4,
        fault_inject=["dispatch:oom:2"],
    )
    ex = ToyAggDeep(cfg, external_call=False)
    ex()
    outs = sorted(glob.glob(os.path.join(cfg.output_path, "**", "*toy.npy"),
                            recursive=True))
    assert len(outs) == 6  # every video delivered despite the OOM groups
    s = faults.finalize_run(cfg.output_path)
    assert s is not None and s["failed"] == 0

    # resume over the same dir: everything skips
    cfg2 = _cfg(
        toy_videos, tmp_path, video_batch=2, inflight_groups=4, resume=True,
    )
    ex2 = ToyAggDeep(cfg2, external_call=False)
    ex2()
    assert ex2.events == []  # nothing dispatched: resume skipped all

    # values match a clean lockstep run
    clean_dir = tmp_path / "clean"
    cfg3 = _cfg(toy_videos, clean_dir, video_batch=2, inflight_groups=2)
    ToyAggDeep(cfg3, external_call=False)()
    for out in outs:
        clean = os.path.join(
            cfg3.output_path, os.path.relpath(out, cfg.output_path)
        )
        np.testing.assert_array_equal(np.load(out), np.load(clean))


# --- timer-scheduled backoff -------------------------------------------------


class FlakyPrep(ToyExtractor):
    """Every video's FIRST prepare fails transiently (OSError), so every
    video takes exactly one backoff delay before its retry."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self._failed = set()
        self._lock = threading.Lock()

    def prepare(self, path_entry):
        key = str(path_entry)
        with self._lock:
            first = key not in self._failed
            self._failed.add(key)
        if first:
            raise OSError("io flake")
        return super().prepare(path_entry)


def test_backoff_timers_do_not_serialize_on_the_decode_worker(
    toy_videos, tmp_path
):
    """With ONE decode worker and every video retrying once, the old
    sleep-in-worker backoff would serialize the delays (>= sum); the
    timer scheduler overlaps them (~ max). The deterministic jitter
    makes both bounds computable exactly."""
    base = 1.0
    cfg = _cfg(toy_videos, tmp_path, decode_workers=1, retry_backoff=base,
               retries=2)
    delays = [faults.backoff_delay(1, base, str(v)) for v in toy_videos]
    ex = FlakyPrep(cfg, external_call=True)
    t0 = time.monotonic()
    results = ex()
    wall = time.monotonic() - t0
    assert len(results) == 6
    # decisive margin: six serialized delays are >= sum(delays) (>= 3s
    # at jitter floor); overlapped timers finish in ~max(delays) (< 1s)
    assert wall < sum(delays), (
        f"wall {wall:.2f}s suggests backoff serialized on the decode "
        f"worker (sum of delays = {sum(delays):.2f}s)"
    )
    assert int(ex.telemetry.metrics.counter("retries")) == 6


# --- heartbeat / metrics -----------------------------------------------------


def test_heartbeat_line_includes_ingest_depths(toy_videos, tmp_path):
    cfg = _cfg(toy_videos, tmp_path, video_batch=2, inflight_groups=3)
    ex = ToyAggDeep(cfg, external_call=True)
    ex()
    ex.telemetry.metrics.set_gauge("queue_depth.inflight", 2)
    ex.telemetry.metrics.set_gauge("queue_depth.prepared", 1)
    line = ex.telemetry.heartbeat_line()
    assert "inflight 2" in line and "prepared 1" in line


def test_metrics_exposition_ingest_families(toy_videos, tmp_path):
    from video_features_tpu.telemetry.exposition import (
        families_from_snapshot,
        render_families,
    )

    cfg = _cfg(toy_videos, tmp_path, video_batch=2)
    ex = ToyAggDeep(cfg, external_call=True)
    ex()
    ex.telemetry.metrics.inc("windows_skipped", 7)
    text = render_families(
        families_from_snapshot(ex.telemetry.metrics.snapshot())
    )
    assert "vft_windows_skipped_total 7" in text
    assert 'vft_queue_depth{queue="inflight"}' in text
    assert 'vft_queue_depth{queue="prepared"}' in text


# --- frame-delta gating on the real CLIP path --------------------------------


@pytest.fixture(scope="module")
def gating_videos(tmp_path_factory):
    """One static clip (the near-duplicate corpus) + one moving clip."""
    from video_features_tpu.utils.synth import synth_video

    d = tmp_path_factory.mktemp("gating_media")
    return [
        synth_video(str(d / "static.mp4"), n_frames=16, width=128, height=96,
                    seed=0, static=True),
        synth_video(str(d / "moving.mp4"), n_frames=16, width=128, height=96,
                    seed=1),
    ]


def _clip_cfg(paths, tmp_path, **kw):
    return ExtractionConfig(
        allow_random_init=True,
        feature_type="CLIP-ViT-B/32",
        video_paths=list(paths),
        extract_method="uni_4",
        tmp_path=str(tmp_path / "tmp"),
        output_path=str(tmp_path / "out"),
        cpu=True,
        **kw,
    )


def test_frame_delta_threshold_zero_is_bit_identical(gating_videos, tmp_path):
    """The pinned parity contract: --frame_delta_threshold 0 runs the
    gating code path (mask computed, all frames kept) and must produce
    byte-identical features to the gating-off default."""
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    off = ExtractCLIP(_clip_cfg(gating_videos, tmp_path), external_call=True)()
    zero = ExtractCLIP(
        _clip_cfg(gating_videos, tmp_path, frame_delta_threshold=0.0),
        external_call=True,
    )()
    assert len(off) == len(zero) == 2
    for a, b in zip(off, zero):
        np.testing.assert_array_equal(b["CLIP-ViT-B/32"], a["CLIP-ViT-B/32"])
        np.testing.assert_array_equal(b["timestamps_ms"], a["timestamps_ms"])


def test_frame_delta_gating_skips_static_scene(gating_videos, tmp_path):
    """On the static clip the gate must skip >0 frames, count them in
    the windows_skipped metric + delta_gated manifest note, and fill
    the skipped rows by copy-forward — keeping the (T, 512) shape
    contract over the FULL sampling grid."""
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    ungated = ExtractCLIP(
        _clip_cfg(gating_videos, tmp_path / "ref"), external_call=True
    )()
    # external_call=False + save_numpy: the one combination that roots a
    # real RunManifest (base.py gates it off for external/print runs), so
    # the delta_gated note lands on disk. Features come back off the .npy
    # sink output instead of a return value.
    ex = ExtractCLIP(
        _clip_cfg(gating_videos, tmp_path, frame_delta_threshold=2.0,
                  on_extraction="save_numpy"),
        external_call=False,
    )
    ex()
    skipped = int(ex.telemetry.metrics.counter("windows_skipped"))
    assert skipped > 0  # static corpus: the gate fired

    import glob
    import json
    import os

    def _load(stem, key):
        return np.load(
            os.path.join(
                ex.output_path, f"{stem}_{key.replace('/', '-')}.npy"
            )
        )

    static_feats = _load("static", "CLIP-ViT-B/32")
    assert static_feats.shape == ungated[0]["CLIP-ViT-B/32"].shape == (4, 512)
    # every skipped row equals its copy-forward source; frame 0 is kept
    # and static frames collapse onto it
    np.testing.assert_array_equal(
        static_feats, np.broadcast_to(static_feats[:1], static_feats.shape)
    )
    # the kept frame's feature matches the ungated run's frame 0
    np.testing.assert_allclose(
        static_feats[0], ungated[0]["CLIP-ViT-B/32"][0], atol=2e-5, rtol=1e-5
    )
    # the moving clip is untouched by the gate (scene drifts > threshold)
    np.testing.assert_allclose(
        _load("moving", "CLIP-ViT-B/32"),
        ungated[1]["CLIP-ViT-B/32"],
        atol=2e-5, rtol=1e-5,
    )
    # the manifest carries the per-video note
    rows = []
    for p in glob.glob(
        os.path.join(ex.config.output_path, "_manifest", "*.jsonl")
    ):
        with open(p, encoding="utf-8") as f:
            rows += [json.loads(line) for line in f if line.strip()]
    events = [r for r in rows if r.get("event") == "delta_gated"]
    assert events and any("static" in str(e.get("video")) for e in events)
    assert all(e["skipped"] > 0 and e["total"] == 4 for e in events)
