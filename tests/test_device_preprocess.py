"""--preprocess device: fused uint8 ingest (ISSUE PR 1 tentpole).

Chain parity is pinned against the host PIL oracle (ops/preprocess.py),
end-to-end CLIP/ResNet features against the host path with a drift
budget, and the config surface (flag validation + compilation cache)
against its documented behavior. Everything runs on the CPU backend the
conftest forces; measured drift there is ~7e-4 so the 5e-3 budgets have
~7x headroom without masking real regressions.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from video_features_tpu.config import (
    ExtractionConfig,
    enable_compile_cache,
    sanity_check,
)
from video_features_tpu.ops.preprocess import (
    CLIP_MEAN,
    CLIP_STD,
    IMAGENET_MEAN,
    IMAGENET_STD,
    device_preprocess_frames,
    normalize_chw,
    pil_center_crop,
    pil_resize,
    to_float_chw,
)
from video_features_tpu.ops.resize import fused_resize_crop_banded
from video_features_tpu.ops.window import pad_hw, spatial_bucket

pytestmark = pytest.mark.quick

RNG = np.random.RandomState(7)

# the device chain replays PIL's inter-pass uint8 quantization, so the
# residual is PIL's 8-bit fixed-point coefficient table: one uint8 step
# per pixel, scaled into normalized space by the smallest std channel
CLIP_PIXEL_TOL = 1.5 / 255.0 / min(CLIP_STD)
IMAGENET_PIXEL_TOL = 2.5 / 255.0 / min(IMAGENET_STD)

# e2e feature drift budget (measured max ~7e-4 on CPU with seed-0 init)
E2E_DRIFT = 5e-3


def _banded(h, w, resize_to, crop, method):
    bh, bw = spatial_bucket(h, w)
    wt_y, idx_y, wt_x, idx_x = fused_resize_crop_banded(
        h, w, resize_to, crop, method, pad_h=bh, pad_w=bw
    )
    return (bh, bw), (wt_y, idx_y), (wt_x, idx_x)


def _device_chain(img, resize_to, crop, method, mean, std):
    """Exactly what the extractors dispatch: bucket-pad + banded taps."""
    h, w = img.shape[:2]
    (bh, bw), wy, wx = _banded(h, w, resize_to, crop, method)
    out = device_preprocess_frames(
        jnp.asarray(pad_hw(img[None], bh, bw)), wy, wx, mean, std
    )
    return np.asarray(out)[0]


def _host_clip_chain(img, size=224):
    from PIL import Image

    x = pil_center_crop(pil_resize(img, size, interpolation=Image.BICUBIC), size)
    return normalize_chw(to_float_chw(x), CLIP_MEAN, CLIP_STD)


@pytest.mark.parametrize(
    "hw", [(360, 640), (240, 426), (224, 224), (100, 640), (232, 420)]
)
def test_clip_chain_parity_vs_pil(hw):
    img = RNG.randint(0, 256, (hw[0], hw[1], 3)).astype(np.uint8)
    ref = _host_clip_chain(img)
    got = _device_chain(img, 224, 224, "bicubic", CLIP_MEAN, CLIP_STD)
    assert got.shape == ref.shape == (3, 224, 224)
    assert np.abs(got - ref).max() <= CLIP_PIXEL_TOL


def test_resnet_chain_parity_vs_pil():
    img = RNG.randint(0, 256, (240, 320, 3)).astype(np.uint8)
    resized = pil_resize(img, 256)  # host default: bilinear smaller-edge
    ref = normalize_chw(
        to_float_chw(pil_center_crop(resized, 224)), IMAGENET_MEAN, IMAGENET_STD
    )
    got = _device_chain(img, 256, 224, "bilinear", IMAGENET_MEAN, IMAGENET_STD)
    assert np.abs(got - ref).max() <= IMAGENET_PIXEL_TOL


def test_device_preprocess_batched_layouts_match_solo():
    """Group (N,T,H,W,C) and row (R,H,W,C) einsum layouts must be
    bit-identical to the solo (T,H,W,C) path."""
    h, w = 120, 180
    (bh, bw), wy, wx = _banded(h, w, 64, 56, "bicubic")
    frames = RNG.randint(0, 256, (4, bh, bw, 3)).astype(np.uint8)
    solo = np.asarray(
        device_preprocess_frames(jnp.asarray(frames), wy, wx, CLIP_MEAN, CLIP_STD)
    )
    stack2 = lambda pair: tuple(np.stack([a, a]) for a in pair)
    group = np.asarray(
        device_preprocess_frames(
            jnp.asarray(np.stack([frames, frames])),
            stack2(wy), stack2(wx), CLIP_MEAN, CLIP_STD,
        )
    )
    np.testing.assert_array_equal(group[0], solo)
    np.testing.assert_array_equal(group[1], solo)
    stack4 = lambda pair: tuple(np.stack([a] * 4) for a in pair)
    rows = np.asarray(
        device_preprocess_frames(
            jnp.asarray(frames), stack4(wy), stack4(wx), CLIP_MEAN, CLIP_STD
        )
    )
    np.testing.assert_array_equal(rows, solo)


# --- end-to-end: uint8 ingest vs host path --------------------------------

@pytest.fixture(scope="module")
def mixed_videos(tmp_path_factory):
    from video_features_tpu.utils.synth import synth_video

    root = tmp_path_factory.mktemp("devpre_media")
    # two resolutions sharing the (256, 448) bucket + one other bucket
    return [
        synth_video(str(root / "a.mp4"), n_frames=24, width=426, height=240, seed=0),
        synth_video(str(root / "b.mp4"), n_frames=32, width=420, height=232, seed=1),
        synth_video(str(root / "c.mp4"), n_frames=28, width=320, height=240, seed=2),
    ]


def _clip_run(videos, tmp_path, preprocess, video_batch=1, **kw):
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="CLIP-ViT-B/32",
        video_paths=list(videos),
        extract_method="uni_4",
        preprocess=preprocess,
        video_batch=video_batch,
        tmp_path=str(tmp_path / "tmp"),
        output_path=str(tmp_path / "out"),
        cpu=True,
        **kw,
    )
    return ExtractCLIP(cfg, external_call=True)()


@pytest.fixture(scope="module")
def clip_device_counted(mixed_videos, tmp_path_factory):
    """The device run, traced by the GC401 compile counter: the SAME
    extraction both the drift tests and the recompilation budget
    (analysis/compile_budget.json) assert against."""
    from video_features_tpu.analysis import CompileCounter

    tmp = tmp_path_factory.mktemp("devpre_clip_dev")
    with CompileCounter() as cc:
        dev = _clip_run(mixed_videos, tmp, "device")
    return dev, dict(cc.counts)


@pytest.fixture(scope="module")
def clip_host_and_device(mixed_videos, clip_device_counted, tmp_path_factory):
    tmp = tmp_path_factory.mktemp("devpre_clip")
    return (
        _clip_run(mixed_videos, tmp, "host"),
        clip_device_counted[0],
    )


def test_clip_uint8_e2e_drift_budget(clip_host_and_device):
    """Acceptance: device-path CLIP features within the pinned drift budget
    of the host path across mixed resolutions."""
    host, dev = clip_host_and_device
    assert len(host) == len(dev) == 3
    for h, d in zip(host, dev):
        assert d["CLIP-ViT-B/32"].shape == h["CLIP-ViT-B/32"].shape == (4, 512)
        np.testing.assert_array_equal(d["timestamps_ms"], h["timestamps_ms"])
        drift = np.abs(d["CLIP-ViT-B/32"] - h["CLIP-ViT-B/32"]).max()
        assert drift <= E2E_DRIFT, f"device-vs-host drift {drift:.2e}"


def test_clip_device_aggregation_matches_solo(
    mixed_videos, clip_host_and_device, tmp_path
):
    """--video_batch with device preprocess: mixed resolutions split into
    per-bucket agg groups; fused results must match solo device results."""
    from video_features_tpu.analysis import CompileCounter, assert_within_budget

    _, solo = clip_host_and_device
    with CompileCounter() as cc:
        fused = _clip_run(mixed_videos, tmp_path, "device", video_batch=2)
    for s, f in zip(solo, fused):
        np.testing.assert_allclose(
            f["CLIP-ViT-B/32"], s["CLIP-ViT-B/32"], atol=2e-5, rtol=1e-5
        )
    assert_within_budget("clip_device_grouped", cc)


@pytest.mark.analysis
def test_clip_device_compile_budget(clip_device_counted):
    """GC401: the mixed-resolution device run builds executables per
    spatial bucket (2 here), never per video (3) — enforced against the
    committed ceiling in analysis/compile_budget.json."""
    from video_features_tpu.analysis import check_counts

    _, counts = clip_device_counted
    assert counts.get("encode_raw") == 2, counts
    assert check_counts("clip_device_mixed", counts) == []


@pytest.mark.analysis
def test_broken_bucket_sharing_fails_budget(mixed_videos, tmp_path):
    """Inflating the executable count must FAIL the budget: shrinking
    --spatial_bucket to 8 splits the shared (256, 448) bucket, so each
    of the 3 resolutions compiles its own encode_raw — 3 > the committed
    ceiling of 2, and check_counts says so with the rule id."""
    from video_features_tpu.analysis import CompileCounter, check_counts

    with CompileCounter() as cc:
        _clip_run(mixed_videos, tmp_path, "device", spatial_bucket=8)
    assert cc.counts["encode_raw"] == 3, dict(cc.counts)
    violations = check_counts("clip_device_mixed", dict(cc.counts))
    assert violations and "GC401" in violations[0] and "encode_raw" in violations[0]


def test_clip_mesh_device_preprocess_parity(mixed_videos, tmp_path):
    """Acceptance (graftcheck v2 tentpole): --sharding mesh --preprocess
    device passes sanity_check for CLIP and matches the queue device path
    on the 2-bucket mixed-resolution corpus — the fused batch axis shards
    over 'data' with bucket padding applied pre-split (place_raw_payload),
    under the in/out_shardings contract GC502 enforces statically."""
    import jax

    from video_features_tpu.models.clip.extract_clip import ExtractCLIP
    from video_features_tpu.parallel.sharding import make_mesh

    mesh_cfg = sanity_check(
        ExtractionConfig(
            allow_random_init=True,
            feature_type="CLIP-ViT-B/32",
            video_paths=list(mixed_videos),
            extract_method="uni_4",
            preprocess="device",
            sharding="mesh",
            tmp_path=str(tmp_path / "m" / "tmp"),
            output_path=str(tmp_path / "m" / "out"),
            cpu=True,
        )
    )
    mesh = ExtractCLIP(mesh_cfg, external_call=True)(
        device=make_mesh(jax.devices(), model=1)
    )
    queue = _clip_run(mixed_videos, tmp_path / "q", "device")
    assert len(mesh) == len(queue) == 3
    for m, q in zip(mesh, queue):
        np.testing.assert_array_equal(m["timestamps_ms"], q["timestamps_ms"])
        np.testing.assert_allclose(
            m["CLIP-ViT-B/32"], q["CLIP-ViT-B/32"], atol=2e-5, rtol=1e-5
        )


def test_mesh_device_preprocess_sanity_gate():
    """sanity_check admits mesh+device for exactly the feature types whose
    fused entry carries a GC502/GC504-checked sharding contract (CLIP,
    RAFT/PWC flow, and two-stream I3D); everything else still gets the
    actionable rejection."""
    from video_features_tpu.config import MESH_DEVICE_PREPROCESS_FEATURE_TYPES

    def cfg(ft, **kw):
        return ExtractionConfig(
            allow_random_init=True,
            feature_type=ft,
            video_paths=["x.mp4"],
            sharding="mesh",
            preprocess="device",
            cpu=True,
            **kw,
        )

    assert "CLIP-ViT-B/32" in MESH_DEVICE_PREPROCESS_FEATURE_TYPES
    assert {"raft", "pwc", "i3d"} <= set(MESH_DEVICE_PREPROCESS_FEATURE_TYPES)
    sanity_check(cfg("CLIP-ViT-B/32", extract_method="uni_4"))
    sanity_check(cfg("raft"))
    sanity_check(cfg("pwc"))
    sanity_check(cfg("i3d", flow_type="raft"))
    for ft in ("resnet18", "resnet50"):
        with pytest.raises(ValueError, match="GC502"):
            sanity_check(cfg(ft))
    with pytest.raises(ValueError, match="mesh_context"):
        sanity_check(
            cfg("CLIP-ViT-B/32", extract_method="uni_4", mesh_context=True)
        )


def _resnet_cfg(videos, tmp_path, **kw):
    return ExtractionConfig(
        allow_random_init=True,
        feature_type="resnet18",
        video_paths=list(videos),
        batch_size=8,
        tmp_path=str(tmp_path / "tmp"),
        output_path=str(tmp_path / "out"),
        cpu=True,
        **kw,
    )


def test_resnet_device_vs_host_drift(mixed_videos, tmp_path):
    from video_features_tpu.models.resnet.extract_resnet import ExtractResNet

    vids = mixed_videos[:2]
    host = ExtractResNet(_resnet_cfg(vids, tmp_path), external_call=True)()
    dev = ExtractResNet(
        _resnet_cfg(vids, tmp_path, preprocess="device"), external_call=True
    )()
    for h, d in zip(host, dev):
        assert d["resnet18"].shape == h["resnet18"].shape
        assert np.abs(d["resnet18"] - h["resnet18"]).max() <= E2E_DRIFT


def test_resnet_device_streaming_fallback_matches(mixed_videos, tmp_path, monkeypatch):
    """Over the prefetch byte cap the device path falls back to streaming
    decode; features must match the prepared device path."""
    from video_features_tpu.models.resnet import extract_resnet as mod

    vids = mixed_videos[:1]
    prepared = mod.ExtractResNet(
        _resnet_cfg(vids, tmp_path, preprocess="device"), external_call=True
    )()
    monkeypatch.setattr(mod.ExtractResNet, "PIPELINE_MAX_BYTES", 1)
    streamed = mod.ExtractResNet(
        _resnet_cfg(vids, tmp_path, preprocess="device"), external_call=True
    )()
    np.testing.assert_allclose(
        streamed[0]["resnet18"], prepared[0]["resnet18"], atol=2e-5, rtol=1e-5
    )


# the heavyweight flow/i3d device-vs-host extraction runs live in
# test_device_preprocess_e2e.py (slow tier — RAFT's recurrence is
# minutes per run on one CPU core); the contract-level parity they
# depend on is pinned fast in test_shape_contract.py


# --- config surface -------------------------------------------------------

def test_preprocess_flag_validation():
    def cfg(**kw):
        return ExtractionConfig(allow_random_init=True, cpu=True, **kw)

    # accepted: CLIP / ResNet families, the flow models, and i3d with an
    # on-the-fly flow model (PR 2)
    sanity_check(cfg(feature_type="resnet18", preprocess="device"))
    sanity_check(
        cfg(feature_type="CLIP-ViT-B/32", extract_method="uni_4", preprocess="device")
    )
    sanity_check(cfg(feature_type="raft", preprocess="device"))
    sanity_check(cfg(feature_type="pwc", preprocess="device"))
    sanity_check(cfg(feature_type="i3d", preprocess="device"))
    sanity_check(cfg(feature_type="i3d", preprocess="device", flow_type="raft"))
    with pytest.raises(ValueError, match="preprocess"):
        sanity_check(cfg(feature_type="resnet18", preprocess="nonsense"))
    # the rejection message names the supported set (single source of
    # truth: config.DEVICE_PREPROCESS_FEATURE_TYPES)
    with pytest.raises(ValueError, match="raft.*resnet18|resnet18.*raft"):
        sanity_check(cfg(feature_type="vggish", preprocess="device"))
    # pre-extracted disk flow keeps the host chain
    with pytest.raises(ValueError, match="flow"):
        sanity_check(cfg(feature_type="i3d", preprocess="device", flow_type="flow"))
    # --show_pred draws onto host-resized frames the flow device path
    # never materializes
    with pytest.raises(ValueError, match="show_pred"):
        sanity_check(cfg(feature_type="raft", preprocess="device", show_pred=True))
    with pytest.raises(ValueError, match="show_pred"):
        sanity_check(cfg(feature_type="pwc", preprocess="device", show_pred=True))
    with pytest.raises(ValueError, match="mesh"):
        sanity_check(
            cfg(feature_type="resnet18", preprocess="device", sharding="mesh")
        )
    with pytest.raises(ValueError, match="spatial_bucket"):
        sanity_check(cfg(feature_type="resnet18", spatial_bucket=0))


def test_cli_preprocess_flags_parse():
    from video_features_tpu.config import parse_args

    cfg = parse_args(
        [
            "--feature_type", "resnet18",
            "--video_paths", "x.mp4",
            "--allow_random_init",
            "--cpu",
            "--preprocess", "device",
            "--spatial_bucket", "32",
            "--compile_cache", "/tmp/ccache",
            "--compile_cache_min_s", "0.5",
        ]
    )
    assert cfg.preprocess == "device"
    assert cfg.spatial_bucket == 32
    assert cfg.compile_cache == "/tmp/ccache"
    assert cfg.compile_cache_min_s == 0.5


def test_enable_compile_cache(tmp_path):
    import jax

    cache_dir = tmp_path / "jit_cache"
    enable_compile_cache(
        ExtractionConfig(compile_cache=str(cache_dir), compile_cache_min_s=0.25)
    )
    try:
        assert cache_dir.is_dir()
        assert jax.config.jax_compilation_cache_dir == str(cache_dir)
        assert jax.config.jax_persistent_cache_min_compile_time_secs == 0.25
    finally:
        jax.config.update("jax_compilation_cache_dir", None)

    # disabled by default: no directory side effects
    enable_compile_cache(ExtractionConfig())
    assert jax.config.jax_compilation_cache_dir is None
