"""Pallas flash-attention kernel vs the fused core (interpret mode on CPU;
the same kernel runs compiled on TPU — bench.py microbenches it there)."""

import numpy as np
import pytest

import jax.numpy as jnp

from video_features_tpu.ops.attention import attention
from video_features_tpu.ops.pallas.flash_attention import flash_attention


def _qkv(rng, n=2, h=3, lq=64, lk=64, d=32, dtype=np.float32):
    q = rng.standard_normal((n, h, lq, d)).astype(dtype)
    k = rng.standard_normal((n, h, lk, d)).astype(dtype)
    v = rng.standard_normal((n, h, lk, d)).astype(dtype)
    return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)


@pytest.mark.parametrize("bq,bk", [(16, 16), (32, 64), (64, 16)])
def test_flash_matches_fused(bq, bk):
    q, k, v = _qkv(np.random.default_rng(0), lq=96, lk=128)
    ref = attention(q, k, v)
    out = flash_attention(q, k, v, block_q=bq, block_k=bk, interpret=True)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_flash_ragged_lengths_pad_and_mask():
    """L not a block multiple + explicit kv_len: pads masked, rows sliced."""
    q, k, v = _qkv(np.random.default_rng(1), lq=50, lk=50)
    ref = attention(q, k[:, :, :37], v[:, :, :37])
    out = flash_attention(
        q, k, v, block_q=16, block_k=16, kv_len=37, interpret=True
    )
    assert out.shape == q.shape
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_flash_bf16_fp32_accumulation():
    q, k, v = _qkv(np.random.default_rng(2))
    qb, kb, vb = (t.astype(jnp.bfloat16) for t in (q, k, v))
    out = flash_attention(qb, kb, vb, block_q=32, block_k=32, interpret=True)
    assert out.dtype == jnp.bfloat16
    ref = attention(q, k, v)
    assert np.allclose(
        np.asarray(out, dtype=np.float32), np.asarray(ref), atol=3e-2
    )


@pytest.mark.quick
def test_flash_single_block():
    """Whole sequence in one (block_q, block_k): degenerate grid."""
    q, k, v = _qkv(np.random.default_rng(3), lq=16, lk=16)
    ref = attention(q, k, v)
    out = flash_attention(q, k, v, interpret=True)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
