"""End-to-end runs on the reference's REAL sample videos (VERDICT r02 #4).

The synth fixtures (utils/synth.py) exercise every code path but are
mp4v-encoded CFR streams; the reference ships two real H.264 UCF101 clips
(ref sample/v_GGSY1Qvo990.mp4, sample/sample_video_paths.txt, used by
run.sh:1-15 and every docs page) with B-frames, audio tracks, and real
encoder quirks. These tests pin: both decode backends return bit-identical
frames on real H.264, and the CLIP, ResNet, VGGish, I3D (rgb,
stack-batched), R(2+1)D, and PWC-flow contracts hold end to end. Skipped
wholesale when the reference mount is absent.
"""

import os
import pathlib

import numpy as np
import pytest

from video_features_tpu.config import ExtractionConfig

SAMPLE_DIR = "/root/reference/sample"
SAMPLES = [
    os.path.join(SAMPLE_DIR, "v_GGSY1Qvo990.mp4"),
    os.path.join(SAMPLE_DIR, "v_ZNVhz7ctTq0.mp4"),
]

pytestmark = pytest.mark.skipif(
    not all(os.path.exists(s) for s in SAMPLES),
    reason="reference sample videos not mounted",
)


@pytest.mark.parametrize("sample", SAMPLES, ids=["GGSY", "ZNVh"])
def test_decoders_bit_identical_on_real_h264(sample):
    """cv2 and the native libav loader share libavcodec; on a real H.264
    stream (B-frames, open GOPs) every frame must still match bitwise."""
    from video_features_tpu.io.video import probe, read_all_frames

    m_cv, m_na = probe(sample, "cv2"), probe(sample, "native")
    assert (m_cv.frame_count, m_cv.width, m_cv.height) == (
        m_na.frame_count,
        m_na.width,
        m_na.height,
    )
    fr_cv, fps_cv, ts_cv = read_all_frames(sample, None, "cv2")
    fr_na, fps_na, ts_na = read_all_frames(sample, None, "native")
    assert len(fr_cv) == len(fr_na) == m_cv.frame_count
    assert fps_cv == fps_na
    for a, b in zip(fr_cv, fr_na):
        np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("decoder", ["cv2", "native"])
def test_clip_uni12_contract_on_real_sample(decoder, tmp_path):
    """BASELINE config #1 on the real clip: (12, 512), finite, and
    decoder-independent (bit-identical frames -> identical features)."""
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="CLIP-ViT-B/32",
        video_paths=[SAMPLES[0]],
        extract_method="uni_12",
        decoder=decoder,
        tmp_path=str(tmp_path / "tmp"),
        output_path=str(tmp_path / "out"),
        cpu=True,
    )
    (r,) = ExtractCLIP(cfg, external_call=True)([0])
    feats = r["CLIP-ViT-B/32"]
    assert feats.shape == (12, 512) and np.isfinite(feats).all()
    assert len(r["timestamps_ms"]) == 12
    # cross-decoder identity: bit-identical frames -> identical features
    prev = _CACHE.get("clip")
    if prev is not None:
        np.testing.assert_allclose(feats, prev, atol=1e-6)
    _CACHE["clip"] = feats


_CACHE: dict = {}


def test_resnet_contract_on_real_sample(tmp_path):
    """Frame-level contract on a real stream, subsampled to ~2 fps so the
    CPU-oracle run stays fast: (T, 512) for resnet18, T = grid length."""
    from video_features_tpu.models.resnet.extract_resnet import ExtractResNet

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="resnet18",
        video_paths=[SAMPLES[1]],
        extraction_fps=2.0,
        batch_size=16,
        tmp_path=str(tmp_path / "tmp"),
        output_path=str(tmp_path / "out"),
        cpu=True,
    )
    (r,) = ExtractResNet(cfg, external_call=True)([0])
    feats = r["resnet18"]
    assert feats.ndim == 2 and feats.shape[1] == 512
    assert feats.shape[0] == len(r["timestamps_ms"]) > 0
    assert np.isfinite(feats).all()


def test_vggish_contract_on_real_sample(tmp_path):
    """Audio contract on the real clip's own audio track: (Ta, 128),
    Ta = duration / 0.96 s (ref docs/models/vggish.md). Needs ffmpeg to
    rip the wav from the mp4 container."""
    from video_features_tpu.io.ffmpeg import which_ffmpeg

    if not which_ffmpeg():
        pytest.skip("ffmpeg binary not installed")
    from video_features_tpu.models.vggish.extract_vggish import ExtractVGGish

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="vggish",
        video_paths=[SAMPLES[0]],
        tmp_path=str(tmp_path / "tmp"),
        output_path=str(tmp_path / "out"),
        cpu=True,
    )
    (r,) = ExtractVGGish(cfg, external_call=True)([0])
    feats = r["vggish"]
    assert feats.ndim == 2 and feats.shape[1] == 128
    assert feats.shape[0] >= 1 and np.isfinite(feats).all()


def test_i3d_rgb_contract_on_real_sample(tmp_path):
    """I3D rgb stream on the real 355-frame clip: small stacks on a wide
    step keep the CPU cost low while exercising the real decode + window
    grid end to end."""
    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="i3d",
        streams=["rgb"],  # rgb-only: no flow model is built or needed
        stack_size=10,
        step_size=64,
        batch_size=2,  # the stack-batched path on a real stream
        video_paths=[SAMPLES[0]],
        tmp_path=str(tmp_path / "tmp"),
        output_path=str(tmp_path / "out"),
        cpu=True,
    )
    (r,) = ExtractI3D(cfg, external_call=True)([0])
    feats = r["rgb"]
    # 355 frames, 11-frame windows, step 64 -> 6 stacks
    assert feats.shape == (6, 1024) and np.isfinite(feats).all()


def test_r21d_contract_on_real_sample(tmp_path):
    """R(2+1)D clip-level contract on the real stream (wide step keeps
    the 3D-conv cost down): (S, 512)."""
    from video_features_tpu.models.r21d.extract_r21d import ExtractR21D

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="r21d_rgb",
        stack_size=16,
        step_size=160,
        batch_size=2,
        video_paths=[SAMPLES[1]],
        tmp_path=str(tmp_path / "tmp"),
        output_path=str(tmp_path / "out"),
        cpu=True,
    )
    (r,) = ExtractR21D(cfg, external_call=True)([0])
    feats = r["r21d_rgb"]
    assert feats.ndim == 2 and feats.shape[1] == 512 and feats.shape[0] >= 1
    assert np.isfinite(feats).all()


def test_pwc_flow_contract_on_real_sample(tmp_path):
    """PWC flow on the real stream at ~1 fps: per-pair 2-channel flow at
    input resolution (BASELINE.md flow contract)."""
    from video_features_tpu.models.pwc.extract_pwc import ExtractPWC

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="pwc",
        extraction_fps=1.0,
        batch_size=8,
        video_paths=[SAMPLES[0]],
        tmp_path=str(tmp_path / "tmp"),
        output_path=str(tmp_path / "out"),
        cpu=True,
    )
    (r,) = ExtractPWC(cfg, external_call=True)([0])
    flow = r["pwc"]
    assert flow.ndim == 4 and flow.shape[1] == 2  # (T-1, 2, H, W)
    assert flow.shape[0] == len(r["timestamps_ms"]) - 1
    assert np.isfinite(flow).all()


@pytest.mark.quick
def test_sample_video_paths_txt_round_trip(tmp_path):
    """--file_with_video_paths consumes the reference's own list file
    format (ref sample/sample_video_paths.txt, utils/utils.py:153-204)."""
    from video_features_tpu.io.paths import form_list_from_user_input

    listing = tmp_path / "paths.txt"
    listing.write_text("\n".join(SAMPLES) + "\n")
    cfg = ExtractionConfig(
        feature_type="resnet18", file_with_video_paths=str(listing)
    )
    paths = form_list_from_user_input(cfg)
    assert [str(pathlib.Path(p)) for p in paths] == SAMPLES


def test_pwc_video_batch_on_real_samples(tmp_path):
    """Cross-video window fusion (r4) on the real H.264 stream: the same
    clip twice shares one agg key, so windows fuse across the two
    'videos'; features must reproduce the solo run's."""
    from video_features_tpu.models.pwc.extract_pwc import ExtractPWC

    def run(video_batch):
        cfg = ExtractionConfig(
            allow_random_init=True,
            feature_type="pwc",
            extraction_fps=1.0,
            batch_size=8,
            video_paths=[SAMPLES[0], SAMPLES[0]],
            video_batch=video_batch,
            tmp_path=str(tmp_path / f"tmp{video_batch}"),
            output_path=str(tmp_path / f"out{video_batch}"),
            cpu=True,
        )
        ex = ExtractPWC(cfg, external_call=True)
        ex.progress.disable = True
        return ex()

    solo = run(1)
    fused = run(2)
    assert len(solo) == len(fused) == 2
    for s, f in zip(solo, fused):
        np.testing.assert_allclose(f["pwc"], s["pwc"], atol=1e-3, rtol=1e-3)
        np.testing.assert_array_equal(f["timestamps_ms"], s["timestamps_ms"])
