"""Multi-host (multi-controller) execution: two real OS processes form a
jax.distributed cluster and run collectives across the process boundary.

This is the CPU analog of a two-host TPU slice: each process owns 4
virtual devices (one host's chips), ``jax.distributed.initialize`` joins
them into one 8-device runtime (the role JAX_COORDINATOR_ADDRESS plays
for main.py on a pod), and a shard_map psum + ring attention run over the
*global* mesh — the collectives cross processes, which is exactly what
rides DCN/ICI on real multi-host slices. The reference has no multi-host
story at all (SURVEY.md §2: comms backend 'None'; its workers never
exchange tensors).
"""

import os
import pathlib
import socket
import subprocess
import sys

_REPO_ROOT = str(pathlib.Path(__file__).resolve().parents[1])

_WORKER = r"""
import os, sys
port, proc_id = sys.argv[1], int(sys.argv[2])

import numpy as np
import jax

# jax may already be imported by a sitecustomize that captured the env at
# interpreter start — re-pin cpu through the config API so the axon
# plugin's backend discovery (which dials the chip tunnel) never runs
# (see tests/conftest.py / parallel/devices.py::pin_platform)
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=proc_id
)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.local_devices()) == 4
assert len(jax.devices()) == 8, "global device view must span both processes"

from video_features_tpu.parallel.ring_attention import ring_attention_sharded
from video_features_tpu.parallel.sharding import make_mesh

mesh = make_mesh(jax.devices(), data=8, model=1)

# 1) cross-process psum: every device contributes its shard; the reduction
#    crosses the process boundary (DCN on a real pod)
rows = np.arange(8, dtype=np.float32) + 1.0  # global: [1..8]
sh = NamedSharding(mesh, P("data"))
x = jax.make_array_from_process_local_data(sh, rows[proc_id * 4:(proc_id + 1) * 4])
total = jax.jit(
    jax.shard_map(
        lambda v: jax.lax.psum(v, "data"), mesh=mesh,
        in_specs=P("data"), out_specs=P(),
    ),
    out_shardings=NamedSharding(mesh, P()),
)(x)
np.testing.assert_allclose(np.asarray(total), [36.0])

# 2) ring attention over the global mesh: KV shards ppermute around an
#    8-stop ring that alternates between the two processes
rng = np.random.default_rng(0)
N, H, L, d = 1, 2, 64, 8
q, k, v = (rng.standard_normal((N, H, L, d)).astype(np.float32) for _ in range(3))
spec = P(None, None, "data", None)
shq = NamedSharding(mesh, spec)
lo, hi = proc_id * (L // 2), (proc_id + 1) * (L // 2)
qs, ks, vs = (
    jax.make_array_from_process_local_data(shq, t[:, :, lo:hi]) for t in (q, k, v)
)
out = jax.jit(
    lambda a, b, c: ring_attention_sharded(a, b, c, mesh, axis_name="data"),
    out_shardings=NamedSharding(mesh, P()),
)(qs, ks, vs)

# numpy oracle, fully local
s = np.einsum("nhqd,nhkd->nhqk", q, k) * d ** -0.5
p = np.exp(s - s.max(-1, keepdims=True))
ref = np.einsum("nhqk,nhkd->nhqd", p / p.sum(-1, keepdims=True), v)
np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)
print(f"proc {proc_id} ok")
"""


def test_two_process_cluster_runs_cross_host_collectives(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = {k: v for k, v in os.environ.items() if k != "JAX_COORDINATOR_ADDRESS"}
    # 4 virtual cpu devices per process = one simulated host each; must be
    # in the env BEFORE the interpreter starts (a sitecustomize may import
    # jax at startup, capturing these)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["JAX_PLATFORMS"] = "cpu"
    env["USE_TF"] = "0"
    env["PYTHONPATH"] = (
        _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    ).rstrip(os.pathsep)
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(port), str(i)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"proc {i} ok" in out
