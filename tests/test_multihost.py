"""Multi-host (multi-controller) execution: two real OS processes form a
jax.distributed cluster and run collectives across the process boundary.

This is the CPU analog of a two-host TPU slice: each process owns 4
virtual devices (one host's chips), ``jax.distributed.initialize`` joins
them into one 8-device runtime (the role JAX_COORDINATOR_ADDRESS plays
for main.py on a pod), and a shard_map psum + ring attention run over the
*global* mesh — the collectives cross processes, which is exactly what
rides DCN/ICI on real multi-host slices. The reference has no multi-host
story at all (SURVEY.md §2: comms backend 'None'; its workers never
exchange tensors).
"""

import os
import pathlib
import socket
import subprocess
import sys

_REPO_ROOT = str(pathlib.Path(__file__).resolve().parents[1])

_WORKER = r"""
import os, sys
port, proc_id = sys.argv[1], int(sys.argv[2])

import numpy as np
import jax

# jax may already be imported by a sitecustomize that captured the env at
# interpreter start — re-pin cpu through the config API so the axon
# plugin's backend discovery (which dials the chip tunnel) never runs
# (see tests/conftest.py / parallel/devices.py::pin_platform)
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=proc_id
)
assert jax.process_count() == 2, jax.process_count()
assert len(jax.local_devices()) == 4
assert len(jax.devices()) == 8, "global device view must span both processes"

from video_features_tpu.parallel.ring_attention import ring_attention_sharded
from video_features_tpu.parallel.sharding import make_mesh

mesh = make_mesh(jax.devices(), data=8, model=1)

# 1) cross-process psum: every device contributes its shard; the reduction
#    crosses the process boundary (DCN on a real pod)
rows = np.arange(8, dtype=np.float32) + 1.0  # global: [1..8]
sh = NamedSharding(mesh, P("data"))
x = jax.make_array_from_process_local_data(sh, rows[proc_id * 4:(proc_id + 1) * 4])
total = jax.jit(
    jax.shard_map(
        lambda v: jax.lax.psum(v, "data"), mesh=mesh,
        in_specs=P("data"), out_specs=P(),
    ),
    out_shardings=NamedSharding(mesh, P()),
)(x)
np.testing.assert_allclose(np.asarray(total), [36.0])

# 2) ring attention over the global mesh: KV shards ppermute around an
#    8-stop ring that alternates between the two processes
rng = np.random.default_rng(0)
N, H, L, d = 1, 2, 64, 8
q, k, v = (rng.standard_normal((N, H, L, d)).astype(np.float32) for _ in range(3))
spec = P(None, None, "data", None)
shq = NamedSharding(mesh, spec)
lo, hi = proc_id * (L // 2), (proc_id + 1) * (L // 2)
qs, ks, vs = (
    jax.make_array_from_process_local_data(shq, t[:, :, lo:hi]) for t in (q, k, v)
)
out = jax.jit(
    lambda a, b, c: ring_attention_sharded(a, b, c, mesh, axis_name="data"),
    out_shardings=NamedSharding(mesh, P()),
)(qs, ks, vs)

# numpy oracle, fully local
s = np.einsum("nhqd,nhkd->nhqk", q, k) * d ** -0.5
p = np.exp(s - s.max(-1, keepdims=True))
ref = np.einsum("nhqk,nhkd->nhqd", p / p.sum(-1, keepdims=True), v)
np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)
print(f"proc {proc_id} ok")
"""


def test_two_process_cluster_runs_cross_host_collectives(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = {k: v for k, v in os.environ.items() if k != "JAX_COORDINATOR_ADDRESS"}
    # 4 virtual cpu devices per process = one simulated host each; must be
    # in the env BEFORE the interpreter starts (a sitecustomize may import
    # jax at startup, capturing these)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["JAX_PLATFORMS"] = "cpu"
    env["USE_TF"] = "0"
    env["PYTHONPATH"] = (
        _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    ).rstrip(os.pathsep)
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(port), str(i)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"proc {i} ok" in out


_EXTRACT_WORKER = r"""
import os, sys
port, proc_id, video, out_dir, tmp_dir, resume, weights = sys.argv[1:8]

import numpy as np
import jax

# re-pin cpu before the axon plugin's discovery can dial the chip tunnel
# (same dance as the collectives worker above)
jax.config.update("jax_platforms", "cpu")

jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2,
    process_id=int(proc_id),
)
assert len(jax.devices()) == 8, "global device view must span both processes"

from video_features_tpu.cli import main as cli_main

# the full product path: argv -> config -> registry -> mesh scheduler.
# Every process runs the SAME path list in lockstep (each sharded
# dispatch is collective); the sink gate writes on process 0 only.
common = [
    "--cpu", "--allow_random_init", "--sharding", "mesh",
    "--video_paths", video, "--on_extraction", "save_numpy",
    "--tmp_path", tmp_dir,
]
clip = [
    "--feature_type", "CLIP-ViT-B/32", "--extract_method", "uni_4",
    "--output_path", os.path.join(out_dir, "clip"),
] + common
if resume == "1":
    # the divergence trap: process 0's out dir holds the first run's
    # files, process 1's holds nothing — without the broadcast in
    # _already_done, process 1 would dispatch a collective process 0
    # never joins (deadlock; the test timeout would fire)
    clip.append("--resume")
cli_main(clip)
if resume != "1":
    # flow extractor on the mesh too: its jitted forwards pin outputs
    # replicated under multihost (sharding.py::multihost_out_kwargs) —
    # without that, np.asarray on the cross-host-sharded flow raises.
    # batch_size 11 -> the 12-frame clip is ONE window: a single sharded
    # compile keeps this phase's 2-process CPU cost bounded
    cli_main([
        "--feature_type", "pwc", "--batch_size", "11",
        "--output_path", os.path.join(out_dir, "pwc"),
    ] + common)
if resume != "1" and weights:
    # orbax sharded restore on the MULTI-PROCESS mesh: each process
    # streams its addressable shards straight from the checkpoint dir
    # (weights.py::load_orbax with a global mesh) — the multi-host-safe
    # claim on the checkpoints registry, proven on the product path
    cli_main([
        "--feature_type", "CLIP-ViT-B/32", "--extract_method", "uni_4",
        "--weights_path", weights,
        "--output_path", os.path.join(out_dir, "clip_orbax"),
    ] + [a for a in common if a != "--allow_random_init"])
print(f"proc {proc_id} extraction ok")
"""


def _spawn_cluster(script, video, out_dirs, tmp_path, env, resume, weights=""):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(port), str(i), video,
             out_dirs[i], str(tmp_path / f"tmp{resume}{i}"), resume, weights],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} (resume={resume}) failed:\n{out}"
        assert f"proc {i} extraction ok" in out


def test_two_process_cluster_runs_extraction_job(tmp_path):
    """A real multi-host EXTRACTION job, not just collectives (VERDICT r03
    next #4): both processes drive main.py's mesh path end-to-end on a
    tiny CLIP config AND a flow (pwc) config AND a CLIP config restoring
    orbax weights sharded onto the multi-process mesh. Features must be
    byte-identical to a single-process mesh run, the sink must write
    exactly once (process 0), and a --resume rerun must not deadlock even
    though the processes' local filesystems disagree about what is done
    (code-review r04: the per-process resume probe diverged; process 0's
    answer is now broadcast)."""
    import numpy as np

    from video_features_tpu.utils.synth import synth_video

    video = synth_video(str(tmp_path / "mh.mp4"), n_frames=12, width=96, height=64)

    env = {k: v for k, v in os.environ.items() if k != "JAX_COORDINATOR_ADDRESS"}
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["JAX_PLATFORMS"] = "cpu"
    env["USE_TF"] = "0"
    env["PYTHONPATH"] = (
        _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    ).rstrip(os.pathsep)
    script = tmp_path / "extract_worker.py"
    script.write_text(_EXTRACT_WORKER)
    out_dirs = [str(tmp_path / f"out{i}") for i in range(2)]

    # an orbax checkpoint for the sharded-restore phase (deterministic
    # random init — the restore mechanics are what is under test)
    from video_features_tpu.models.clip.model import CONFIGS, init_params
    from video_features_tpu.models.common.weights import save_orbax

    weights = str(tmp_path / "clip_orbax_ckpt")
    save_orbax(init_params(CONFIGS["CLIP-ViT-B/32"]), weights)

    _spawn_cluster(script, video, out_dirs, tmp_path, env, resume="0",
                   weights=weights)

    # exactly-once sink: process 0 wrote every file set, process 1 nothing
    wrote0 = sorted(pathlib.Path(out_dirs[0]).rglob("*.npy"))
    assert len(wrote0) == 3, wrote0  # clip/ + clip_orbax/ + pwc/
    assert not list(pathlib.Path(out_dirs[1]).rglob("*.npy"))

    # byte-identical to a single-process 8-device mesh run of the same
    # argv
    ref_env = dict(env)
    ref_env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    ref_out = str(tmp_path / "ref_out")
    ref_script = tmp_path / "ref_worker.py"
    ref_script.write_text(
        _EXTRACT_WORKER.replace(
            "jax.distributed.initialize(\n"
            "    coordinator_address=f\"127.0.0.1:{port}\", num_processes=2,\n"
            "    process_id=int(proc_id),\n"
            ")\n",
            "",
        )
    )
    r = subprocess.run(
        [sys.executable, str(ref_script), "0", "0", video, ref_out,
         str(tmp_path / "ref_tmp"), "0", weights],
        env=ref_env, capture_output=True, text=True, timeout=600,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    ref_files = sorted(pathlib.Path(ref_out).rglob("*.npy"))
    assert len(ref_files) == 3
    for got_f, want_f in zip(wrote0, ref_files):
        assert got_f.name == want_f.name
        got, want = np.load(got_f), np.load(want_f)
        if "pwc" in str(got_f):
            # flow crosses a sharded warp/correlation cascade whose
            # reduction ORDER differs between the 2-process (4+4) and
            # single-process (8) device layouts — fp32 rounding noise
            # (observed max 3e-7), not a semantic difference
            np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)
        else:
            np.testing.assert_array_equal(got, want)

    # --resume rerun across the SAME cluster shape: process 1 has no
    # local outputs, process 0 has them all — must complete, not hang
    _spawn_cluster(script, video, out_dirs, tmp_path, env, resume="1")
    assert len(sorted(pathlib.Path(out_dirs[0]).rglob("*.npy"))) == 3


_QUEUE_WORKER = r"""
import os, sys
port, proc_id, out_dir, tmp_dir = sys.argv[1:5]
videos = sys.argv[5:]

import jax

# re-pin cpu before the axon plugin's discovery can dial the chip tunnel
jax.config.update("jax_platforms", "cpu")

jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}", num_processes=2,
    process_id=int(proc_id),
)
assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4

from video_features_tpu.cli import main as cli_main

# DEFAULT --sharding queue under jax.distributed: embarrassingly
# parallel — this process must extract (and SINK) its own strided slice
# of the video list on its own local devices, no collectives anywhere.
# --device_ids 0 indexes the LOCAL device list (per-host contract).
cli_main([
    "--feature_type", "CLIP-ViT-B/32", "--extract_method", "uni_4",
    "--device_ids", "0",
    "--allow_random_init",
    "--video_paths", *videos,
    "--on_extraction", "save_numpy",
    "--output_path", out_dir, "--tmp_path", tmp_dir,
])
print(f"proc {proc_id} extraction ok")
"""


def test_two_process_queue_mode_partitions_and_sinks_locally(tmp_path):
    """Queue-mode (default) multi-process runs: advisor r4 found the
    process-0-only sink gate silently dropped every other process's
    outputs and the resume broadcast could deadlock. Now: each process
    owns the strided slice of the video list, drives only its local
    devices, and writes its own outputs — features identical to a
    single-process run over the same list."""
    import numpy as np

    from video_features_tpu.utils.synth import synth_video

    videos = [
        synth_video(str(tmp_path / f"q{i}.mp4"), n_frames=8, width=96,
                    height=64, seed=i)
        for i in range(4)
    ]

    env = {k: v for k, v in os.environ.items() if k != "JAX_COORDINATOR_ADDRESS"}
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["JAX_PLATFORMS"] = "cpu"
    env["USE_TF"] = "0"
    env["PYTHONPATH"] = (
        _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    ).rstrip(os.pathsep)
    script = tmp_path / "queue_worker.py"
    script.write_text(_QUEUE_WORKER)
    out_dirs = [str(tmp_path / f"qout{i}") for i in range(2)]

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(port), str(i), out_dirs[i],
             str(tmp_path / f"qtmp{i}")] + videos,
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"queue worker {i} failed:\n{out}"
        assert f"proc {i} extraction ok" in out

    # disjoint strided ownership: proc0 sank q0,q2; proc1 sank q1,q3
    got0 = sorted(f.name for f in pathlib.Path(out_dirs[0]).rglob("*.npy"))
    got1 = sorted(f.name for f in pathlib.Path(out_dirs[1]).rglob("*.npy"))
    assert got0 == ["q0_CLIP-ViT-B-32.npy", "q2_CLIP-ViT-B-32.npy"], got0
    assert got1 == ["q1_CLIP-ViT-B-32.npy", "q3_CLIP-ViT-B-32.npy"], got1

    # features identical to a single-process run over the same list
    from video_features_tpu.config import ExtractionConfig
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    ex = ExtractCLIP(
        ExtractionConfig(
            allow_random_init=True,
            feature_type="CLIP-ViT-B/32",
            extract_method="uni_4",
            video_paths=videos,
            cpu=True,
        ),
        external_call=True,
    )
    ref = ex(range(4))
    for i, out_dir in ((0, out_dirs[0]), (2, out_dirs[0]),
                       (1, out_dirs[1]), (3, out_dirs[1])):
        (f,) = pathlib.Path(out_dir).rglob(f"q{i}_CLIP-ViT-B-32.npy")
        np.testing.assert_array_equal(np.load(f), ref[i]["CLIP-ViT-B/32"])
