"""Cross-video batch aggregation (--video_batch).

The reference dispatches one video at a time (ref models/CLIP/
extract_clip.py:107-128 — a single ~12-frame batch per forward); with
frozen weights nothing distinguishes frames of different videos, so N
videos' batches can share one fused forward (SURVEY.md §5). These tests
pin the contract: aggregated features == individual features, per-video
error isolation survives fused dispatch, partial groups flush, and the
save path still writes one file set per video.
"""

import pathlib

import numpy as np
import pytest

from video_features_tpu.config import ExtractionConfig


@pytest.fixture(scope="module")
def four_videos(tmp_path_factory):
    from video_features_tpu.utils.synth import synth_video

    root = tmp_path_factory.mktemp("agg_media")
    return [
        synth_video(str(root / f"v{i}.mp4"), n_frames=24 + 8 * i, seed=i)
        for i in range(4)
    ]


def _clip_cfg(paths, tmp_path, **kw):
    return ExtractionConfig(
        allow_random_init=True,
        feature_type="CLIP-ViT-B/32",
        video_paths=list(paths),
        extract_method="uni_4",
        tmp_path=str(tmp_path / "tmp"),
        output_path=str(tmp_path / "out"),
        cpu=True,
        **kw,
    )


def test_clip_aggregated_matches_individual(four_videos, tmp_path):
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    solo = ExtractCLIP(_clip_cfg(four_videos, tmp_path), external_call=True)()
    # group=3 over 4 videos: one full group + one partial flush
    fused = ExtractCLIP(
        _clip_cfg(four_videos, tmp_path, video_batch=3), external_call=True
    )()
    assert len(solo) == len(fused) == 4
    for s, f in zip(solo, fused):
        assert f["CLIP-ViT-B/32"].shape == (4, 512)
        np.testing.assert_allclose(
            f["CLIP-ViT-B/32"], s["CLIP-ViT-B/32"], atol=2e-5, rtol=1e-5
        )
        np.testing.assert_array_equal(f["timestamps_ms"], s["timestamps_ms"])


def test_clip_aggregated_save_numpy(four_videos, tmp_path):
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    cfg = _clip_cfg(
        four_videos, tmp_path, video_batch=4, on_extraction="save_numpy"
    )
    ExtractCLIP(cfg)()
    saved = sorted(pathlib.Path(tmp_path / "out").rglob("*.npy"))
    assert len(saved) == 4
    for f in saved:
        assert np.load(f).shape == (4, 512)


def test_clip_aggregation_isolates_bad_video(four_videos, tmp_path, capsys):
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    bad = tmp_path / "bad.mp4"
    bad.write_bytes(b"not a video")
    paths = [four_videos[0], str(bad), four_videos[1]]
    fused = ExtractCLIP(
        _clip_cfg(paths, tmp_path, video_batch=3), external_call=True
    )()
    # the bad video fails in prepare; the two good ones still fuse + return
    assert len(fused) == 2
    assert "An error occurred" in capsys.readouterr().out
    for r in fused:
        assert r["CLIP-ViT-B/32"].shape == (4, 512)


def test_resnet_aggregated_matches_individual(four_videos, tmp_path):
    from video_features_tpu.models.resnet.extract_resnet import ExtractResNet

    def cfg(vb):
        return ExtractionConfig(
            allow_random_init=True,
            feature_type="resnet18",
            video_paths=list(four_videos[:3]),
            batch_size=8,
            video_batch=vb,
            tmp_path=str(tmp_path / "tmp"),
            output_path=str(tmp_path / "out"),
            cpu=True,
        )

    solo = ExtractResNet(cfg(1), external_call=True)()
    fused = ExtractResNet(cfg(3), external_call=True)()
    assert len(solo) == len(fused) == 3
    for i, (s, f) in enumerate(zip(solo, fused)):
        # videos have 24/32/40 frames — re-chunked rows must split back
        assert f["resnet18"].shape == (24 + 8 * i, 512)
        np.testing.assert_allclose(f["resnet18"], s["resnet18"], atol=2e-4, rtol=1e-4)
        np.testing.assert_array_equal(f["timestamps_ms"], s["timestamps_ms"])


def test_resnet_agg_key_declines_oversized_and_stream(four_videos, tmp_path):
    from video_features_tpu.models.resnet.extract_resnet import ExtractResNet

    ex = ExtractResNet(
        ExtractionConfig(
            allow_random_init=True,
            feature_type="resnet18",
            video_paths=list(four_videos[:1]),
            batch_size=4,
            video_batch=2,
            tmp_path=str(tmp_path / "tmp"),
            output_path=str(tmp_path / "out"),
            cpu=True,
        ),
        external_call=True,
    )
    payload = ex.prepare(four_videos[0])
    assert ex.agg_key(payload) is not None
    assert ex.agg_key(("stream", four_videos[0])) is None
    old = ex.AGG_MAX_FRAMES
    try:
        ex.AGG_MAX_FRAMES = 3  # the 24-frame video now exceeds the cap
        assert ex.agg_key(payload) is None
    finally:
        ex.AGG_MAX_FRAMES = old


def test_r21d_aggregated_matches_individual(four_videos, tmp_path):
    from video_features_tpu.models.r21d.extract_r21d import ExtractR21D

    def cfg(vb):
        return ExtractionConfig(
            allow_random_init=True,
            feature_type="r21d_rgb",
            video_paths=list(four_videos[:3]),
            batch_size=2,
            video_batch=vb,
            tmp_path=str(tmp_path / "tmp"),
            output_path=str(tmp_path / "out"),
            cpu=True,
        )

    solo = ExtractR21D(cfg(1), external_call=True)()
    fused = ExtractR21D(cfg(3), external_call=True)()
    assert len(solo) == len(fused) == 3
    for i, (s, f) in enumerate(zip(solo, fused)):
        # 24/32/40 frames -> 1/2/2 complete 16-frame stacks
        assert f["r21d_rgb"].shape == s["r21d_rgb"].shape
        np.testing.assert_allclose(f["r21d_rgb"], s["r21d_rgb"], atol=2e-4, rtol=1e-4)


def test_mixed_agg_paths_preserve_input_order(four_videos, tmp_path):
    """external_call results must come back in input order even when an
    agg_key=None video dispatches (and completes) ahead of videos still
    buffering in a group (code-review r03 finding #1)."""
    from video_features_tpu.models.resnet.extract_resnet import ExtractResNet

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="resnet18",
        video_paths=list(four_videos),  # 24/32/40/48 frames
        batch_size=8,
        video_batch=3,
        tmp_path=str(tmp_path / "tmp"),
        output_path=str(tmp_path / "out"),
        cpu=True,
    )
    ex = ExtractResNet(cfg, external_call=True)
    # v1 (32 frames) exceeds the cap -> individual path, overtaking v0/v2
    ex.AGG_MAX_FRAMES = 30
    solo = ExtractResNet(cfg.replace(video_batch=1), external_call=True)()
    fused = ex()
    assert len(fused) == 4
    for i, (s, f) in enumerate(zip(solo, fused)):
        assert f["resnet18"].shape[0] == 24 + 8 * i  # order = input order
        np.testing.assert_allclose(f["resnet18"], s["resnet18"], atol=2e-4, rtol=1e-4)


@pytest.mark.quick
def test_video_batch_requires_decode_workers():
    from video_features_tpu.config import sanity_check

    with pytest.raises(ValueError, match="decode_workers"):
        sanity_check(
            ExtractionConfig(
                feature_type="resnet18", video_batch=4, decode_workers=0
            )
        )


def test_clip_agg_key_declines_oversized(four_videos, tmp_path):
    """fix_N over a long video yields huge payloads; they must dispatch
    alone (code-review r03 finding #2)."""
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    ex = ExtractCLIP(
        _clip_cfg(four_videos[:1], tmp_path, video_batch=2), external_call=True
    )
    payload = ex.prepare(four_videos[0])
    assert ex.agg_key(payload) is not None
    ex.AGG_MAX_FRAMES = 2
    assert ex.agg_key(payload) is None


def test_clip_aggregation_on_mesh_matches_queue(four_videos, tmp_path):
    """--video_batch composes with --sharding mesh: the fused (N*bucket)
    batch shards over 'data' (pad_batch_for rounds it up), features match
    the single-device aggregated run."""
    import jax

    from video_features_tpu.models.clip.extract_clip import ExtractCLIP
    from video_features_tpu.parallel.sharding import make_mesh

    solo = ExtractCLIP(
        _clip_cfg(four_videos[:3], tmp_path, video_batch=3), external_call=True
    )()
    mesh = make_mesh(jax.devices(), model=1)
    ex = ExtractCLIP(
        _clip_cfg(four_videos[:3], tmp_path, video_batch=3, sharding="mesh"),
        external_call=True,
    )
    fused = ex(device=mesh)
    assert len(fused) == 3
    for s, f in zip(solo, fused):
        # pure-DP mesh: same math, only placement differs
        np.testing.assert_allclose(
            f["CLIP-ViT-B/32"], s["CLIP-ViT-B/32"], atol=2e-5, rtol=1e-5
        )


def test_every_feature_type_supports_aggregation(four_videos, tmp_path):
    """r4 closed the last --video_batch gaps (flow windows, i3d stacks):
    EVERY registry extractor now implements dispatch_group. An extractor
    can still decline per-payload via agg_key=None — i3d on a mesh pins
    the solo path, where the frame axis is what shards."""
    from video_features_tpu.config import FEATURE_TYPES
    from video_features_tpu.extract.registry import build_extractor
    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D

    for ft in FEATURE_TYPES:
        cfg = ExtractionConfig(
            allow_random_init=True,
            feature_type=ft,
            video_paths=list(four_videos[:1]),
            video_batch=4,
            extract_method="uni_4",
            tmp_path=str(tmp_path / "tmp"),
            output_path=str(tmp_path / "out"),
            cpu=True,
        )
        assert build_extractor(cfg, external_call=True)._aggregation_enabled(), ft

    mesh_i3d = ExtractI3D(
        ExtractionConfig(
            allow_random_init=True,
            feature_type="i3d",
            flow_type="raft",
            video_paths=list(four_videos[:1]),
            video_batch=4,
            sharding="mesh",
            tmp_path=str(tmp_path / "tmp"),
            output_path=str(tmp_path / "out"),
            cpu=True,
        ),
        external_call=True,
    )
    fake_payload = ((["frame"], 25.0, []), None, False, None)
    assert mesh_i3d.agg_key(fake_payload) is None


def test_aggregation_through_queue_scheduler(four_videos, tmp_path):
    """--video_batch through parallel_feature_extraction on TWO devices
    (the virtual-CPU mesh): the multi-device branch's chunk floor
    (2*video_batch, scheduler.py) is actually exercised — a 1-device run
    takes the chunk=n shortcut — and each video still lands in its own
    output file."""
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP
    from video_features_tpu.parallel.devices import resolve_devices
    from video_features_tpu.parallel.scheduler import parallel_feature_extraction

    cfg = _clip_cfg(
        four_videos, tmp_path, video_batch=2, on_extraction="save_numpy"
    ).replace(cpu=False, device_ids=[0, 1])
    devices = resolve_devices(cfg)
    assert len(devices) == 2  # conftest pins 8 virtual CPU devices
    ex = ExtractCLIP(cfg)
    parallel_feature_extraction(ex, devices)
    saved = sorted(pathlib.Path(tmp_path / "out").rglob("*.npy"))
    assert len(saved) == 4
    solo = ExtractCLIP(
        _clip_cfg(four_videos, tmp_path / "solo"), external_call=True
    )()
    for f, s in zip(saved, solo):  # both sorted by video stem v0..v3
        np.testing.assert_allclose(
            np.load(f), s["CLIP-ViT-B/32"], atol=2e-5, rtol=1e-5
        )


def test_aggregation_with_resume_skips_done(four_videos, tmp_path):
    """--resume composes with --video_batch: already-extracted videos are
    skipped before prepare, the remaining ones still group correctly."""
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    cfg = _clip_cfg(
        four_videos, tmp_path, video_batch=2, on_extraction="save_numpy"
    )
    ExtractCLIP(cfg.replace(video_paths=list(four_videos[:2])))()
    done = sorted(pathlib.Path(tmp_path / "out").rglob("*.npy"))
    assert len(done) == 2
    stamps = {f: f.stat().st_mtime_ns for f in done}
    ExtractCLIP(cfg.replace(resume=True))()
    saved = sorted(pathlib.Path(tmp_path / "out").rglob("*.npy"))
    assert len(saved) == 4
    for f in done:  # untouched, not recomputed
        assert f.stat().st_mtime_ns == stamps[f]


def test_clip_bf16_aggregated_matches_bf16_solo(four_videos, tmp_path):
    """--dtype bfloat16 composes with --video_batch: the fused bf16 batch
    must match per-video bf16 dispatch (same dtype both sides, so only
    batch-shape reduction order differs — tight budget)."""
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    solo = ExtractCLIP(
        _clip_cfg(four_videos[:3], tmp_path, dtype="bfloat16"),
        external_call=True,
    )()
    fused = ExtractCLIP(
        _clip_cfg(four_videos[:3], tmp_path, dtype="bfloat16", video_batch=3),
        external_call=True,
    )()
    assert len(solo) == len(fused) == 3
    for s, f in zip(solo, fused):
        np.testing.assert_allclose(
            f["CLIP-ViT-B/32"], s["CLIP-ViT-B/32"], atol=1e-3, rtol=1e-2
        )


def test_group_dispatch_failure_falls_back_to_solo(four_videos, tmp_path, capsys):
    """A fused dispatch that dies (OOM, compile error) must NOT discard
    the group: every member is re-run through the individual path, so all
    videos still deliver features identical to a solo run (advisor r03
    medium: one bad interaction was costing up to N-1 good videos)."""
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    cfg = _clip_cfg(four_videos, tmp_path, video_batch=2)
    ex = ExtractCLIP(cfg, external_call=True)
    calls = {"n": 0}
    real = ExtractCLIP.dispatch_group

    def flaky(self, device, state, entries, payloads):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("injected fused-dispatch failure")
        return real(self, device, state, entries, payloads)

    ex.dispatch_group = flaky.__get__(ex)
    results = ex()
    # group 1's members recovered via the solo path, group 2 fused
    assert len(results) == 4
    out = capsys.readouterr().out
    assert "An error occurred" not in out
    assert "falling back to per-video dispatch" in out  # fused failure logged
    assert ex.progress.n == 4  # every video counted exactly once
    solo = ExtractCLIP(_clip_cfg(four_videos, tmp_path), external_call=True)()
    for s, f in zip(solo, results):
        np.testing.assert_allclose(
            f["CLIP-ViT-B/32"], s["CLIP-ViT-B/32"], atol=2e-5, rtol=1e-5
        )


def test_group_fetch_failure_falls_back_to_solo(four_videos, tmp_path, capsys):
    """Same contract on the blocking half: a fused fetch_group that dies
    re-dispatches each member individually (payloads are kept host-side
    until the group's fetch succeeds, exactly for this)."""
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    cfg = _clip_cfg(four_videos, tmp_path, video_batch=2)
    ex = ExtractCLIP(cfg, external_call=True)
    calls = {"n": 0}
    real = ExtractCLIP.fetch_group

    def flaky(self, handle):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("injected fused-fetch failure")
        return real(self, handle)

    ex.fetch_group = flaky.__get__(ex)
    results = ex()
    assert len(results) == 4
    out = capsys.readouterr().out
    assert "An error occurred" not in out
    assert "falling back to per-video dispatch" in out
    assert ex.progress.n == 4


def test_group_fetch_fallback_reruns_every_member_solo(four_videos, tmp_path, capsys):
    """The fetch-phase fallback must re-run EXACTLY the failed group's
    members through the solo path (reusing their kept payloads) — not
    the whole corpus, and not fewer."""
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    cfg = _clip_cfg(four_videos, tmp_path, video_batch=2)
    ex = ExtractCLIP(cfg, external_call=True)
    calls = {"fetch": 0, "solo": []}
    real_fetch = ExtractCLIP.fetch_group
    real_extract = ExtractCLIP.extract_prepared

    def flaky(self, handle):
        calls["fetch"] += 1
        if calls["fetch"] == 1:
            raise RuntimeError("injected fused-fetch failure")
        return real_fetch(self, handle)

    def counting(self, device, state, entry, payload):
        calls["solo"].append(entry)
        return real_extract(self, device, state, entry, payload)

    ex.fetch_group = flaky.__get__(ex)
    ex.extract_prepared = counting.__get__(ex)
    results = ex()
    assert len(results) == 4
    # exactly the two members of the failed first group re-ran solo
    assert sorted(calls["solo"]) == sorted(four_videos[:2])
    assert "An error occurred" not in capsys.readouterr().out
    assert ex.progress.n == 4
    solo = ExtractCLIP(_clip_cfg(four_videos, tmp_path), external_call=True)()
    for s, f in zip(solo, results):
        np.testing.assert_allclose(
            f["CLIP-ViT-B/32"], s["CLIP-ViT-B/32"], atol=2e-5, rtol=1e-5
        )


def test_group_fetch_fallback_isolates_truly_bad_member(
    four_videos, tmp_path, capsys
):
    """Fetch-phase counterpart of the dispatch-phase poisoned-member
    test: when the fused fetch fails AND one member's solo re-run fails
    too, only that member is lost."""
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    cfg = _clip_cfg(four_videos[:2], tmp_path, video_batch=2)
    ex = ExtractCLIP(cfg, external_call=True)
    real_extract = ExtractCLIP.extract_prepared

    def fetch_dies(self, handle):
        raise RuntimeError("injected fused-fetch failure")

    def solo_poisoned(self, device, state, entry, payload):
        if entry == four_videos[0]:
            raise RuntimeError("poisoned member")
        return real_extract(self, device, state, entry, payload)

    ex.fetch_group = fetch_dies.__get__(ex)
    ex.extract_prepared = solo_poisoned.__get__(ex)
    results = ex()
    assert len(results) == 1  # the good member survived
    assert capsys.readouterr().out.count("An error occurred") == 1
    assert ex.progress.n == 2


def test_group_fallback_isolates_truly_bad_member(four_videos, tmp_path, capsys):
    """When the fused dispatch fails AND one member really is poisoned
    (its solo dispatch fails too), only that member is reported — the
    rest of the group still delivers."""
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    cfg = _clip_cfg(four_videos[:2], tmp_path, video_batch=2)
    ex = ExtractCLIP(cfg, external_call=True)
    real_extract = ExtractCLIP.extract_prepared

    def group_dies(self, device, state, entries, payloads):
        raise RuntimeError("injected fused-dispatch failure")

    def solo_poisoned(self, device, state, entry, payload):
        if entry == four_videos[0]:
            raise RuntimeError("poisoned member")
        return real_extract(self, device, state, entry, payload)

    ex.dispatch_group = group_dies.__get__(ex)
    ex.extract_prepared = solo_poisoned.__get__(ex)
    results = ex()
    assert len(results) == 1  # the good member survived
    out = capsys.readouterr().out
    assert out.count("An error occurred") == 1
    assert ex.progress.n == 2


@pytest.fixture(scope="module")
def three_wavs(tmp_path_factory):
    from scipy.io import wavfile

    root = tmp_path_factory.mktemp("agg_audio")
    sr, paths = 16000, []
    for i, secs in enumerate((1.5, 2.5, 3.5)):
        t = np.arange(int(secs * sr)) / sr
        data = (0.4 * np.sin(2 * np.pi * (300 + 200 * i) * t) * 32767).astype(
            np.int16
        )
        p = str(root / f"a{i}.wav")
        wavfile.write(p, sr, data)
        paths.append(p)
    return paths


def test_vggish_aggregated_matches_individual(three_wavs, tmp_path):
    from video_features_tpu.models.vggish.extract_vggish import ExtractVGGish

    def cfg(vb):
        return ExtractionConfig(
            allow_random_init=True,
            feature_type="vggish",
            video_paths=list(three_wavs),
            video_batch=vb,
            tmp_path=str(tmp_path / "tmp"),
            output_path=str(tmp_path / "out"),
            cpu=True,
        )

    solo = ExtractVGGish(cfg(1), external_call=True)()
    fused = ExtractVGGish(cfg(3), external_call=True)()
    assert len(solo) == len(fused) == 3
    for i, (s, f) in enumerate(zip(solo, fused)):
        assert f["vggish"].shape == (i + 1, 128)  # 1.5/2.5/3.5 s -> 1/2/3
        np.testing.assert_allclose(f["vggish"], s["vggish"], atol=2e-5, rtol=1e-5)


# --- r4: flow (raft/pwc) and i3d stack aggregation -------------------------


@pytest.fixture(scope="module")
def three_flow_videos(tmp_path_factory):
    from video_features_tpu.utils.synth import synth_video

    root = tmp_path_factory.mktemp("agg_flow_media")
    # 9/13/17 frames at B=4 pairs -> 2/3/4 windows: fused chunks of 3
    # windows cross video boundaries twice AND flush a partial chunk
    return [
        synth_video(
            str(root / f"f{i}.mp4"), n_frames=9 + 4 * i,
            width=96, height=64, seed=10 + i,
        )
        for i in range(3)
    ]


def _flow_cfg(feature_type, paths, tmp_path, **kw):
    return ExtractionConfig(
        allow_random_init=True,
        feature_type=feature_type,
        video_paths=list(paths),
        batch_size=4,
        tmp_path=str(tmp_path / "tmp"),
        output_path=str(tmp_path / "out"),
        cpu=True,
        **kw,
    )


@pytest.mark.parametrize("feature_type", ["raft", "pwc"])
def test_flow_aggregated_matches_individual(
    feature_type, three_flow_videos, tmp_path
):
    """--video_batch on the flow extractors: windows fused across videos
    (vmapped forward) must reproduce the per-video dispatch path — the
    reference only ever batches pairs WITHIN one video (ref
    extract_raft.py:143-146)."""
    from video_features_tpu.extract.registry import build_extractor

    solo = build_extractor(
        _flow_cfg(feature_type, three_flow_videos, tmp_path), external_call=True
    )()
    fused = build_extractor(
        _flow_cfg(feature_type, three_flow_videos, tmp_path, video_batch=3),
        external_call=True,
    )()
    assert len(solo) == len(fused) == 3
    for i, (s, f) in enumerate(zip(solo, fused)):
        n_frames = 9 + 4 * i
        assert f[feature_type].shape[0] == n_frames - 1
        assert f[feature_type].shape[1] == 2
        np.testing.assert_allclose(
            f[feature_type], s[feature_type], atol=1e-3, rtol=1e-3
        )
        np.testing.assert_array_equal(f["timestamps_ms"], s["timestamps_ms"])


def test_flow_aggregation_isolates_bad_video(three_flow_videos, tmp_path, capsys):
    from video_features_tpu.models.pwc.extract_pwc import ExtractPWC

    bad = tmp_path / "bad.mp4"
    bad.write_bytes(b"not a video")
    paths = [three_flow_videos[0], str(bad), three_flow_videos[1]]
    fused = ExtractPWC(
        _flow_cfg("pwc", paths, tmp_path, video_batch=3), external_call=True
    )()
    assert len(fused) == 2
    assert "An error occurred" in capsys.readouterr().out


def test_flow_agg_key_declines_stream_and_groups_by_shape(
    three_flow_videos, tmp_path
):
    """Unit contract: show_pred and over-cap videos route solo
    (agg_key=None); same-resolution payloads share a key, different
    resolutions do not."""
    from video_features_tpu.models.raft.extract_raft import ExtractRAFT
    from video_features_tpu.utils.synth import synth_video

    ex = ExtractRAFT(
        _flow_cfg("raft", three_flow_videos, tmp_path), external_call=True
    )
    p0 = ex.prepare(three_flow_videos[0])
    p1 = ex.prepare(three_flow_videos[1])
    assert ex.agg_key(p0) == ex.agg_key(p1) is not None
    other = synth_video(
        str(tmp_path / "wide.mp4"), n_frames=9, width=160, height=64
    )
    assert ex.agg_key(ex.prepare(other)) != ex.agg_key(p0)
    assert ex.agg_key(("stream", three_flow_videos[0])) is None
    ex.AGG_MAX_BYTES = 1
    assert ex.agg_key(p0) is None


def test_i3d_stacks_aggregated_match_individual(four_videos, tmp_path):
    """--video_batch on i3d: three 1-stack videos fill --batch_size stack
    groups ACROSS videos (2+1-padded chunks) through the same compiled
    executable; features must match the per-video dispatch."""
    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D

    def cfg(vb):
        return ExtractionConfig(
            allow_random_init=True,
            feature_type="i3d",
            streams=["rgb"],
            video_paths=list(four_videos[:3]),
            batch_size=2,
            video_batch=vb,
            tmp_path=str(tmp_path / "tmp"),
            output_path=str(tmp_path / "out"),
            cpu=True,
        )

    solo = ExtractI3D(cfg(1), external_call=True)()
    fused = ExtractI3D(cfg(3), external_call=True)()
    assert len(solo) == len(fused) == 3
    for s, f in zip(solo, fused):
        assert f["rgb"].shape == (1, 1024)
        np.testing.assert_allclose(f["rgb"], s["rgb"], atol=2e-4, rtol=1e-4)
        np.testing.assert_array_equal(f["timestamps_ms"], s["timestamps_ms"])


def test_i3d_aggregation_isolates_bad_video(four_videos, tmp_path, capsys):
    from video_features_tpu.models.i3d.extract_i3d import ExtractI3D

    bad = tmp_path / "bad.mp4"
    bad.write_bytes(b"not a video")
    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="i3d",
        streams=["rgb"],
        video_paths=[four_videos[0], str(bad), four_videos[1]],
        batch_size=2,
        video_batch=3,
        tmp_path=str(tmp_path / "tmp"),
        output_path=str(tmp_path / "out"),
        cpu=True,
    )
    fused = ExtractI3D(cfg, external_call=True)()
    assert len(fused) == 2
    assert "An error occurred" in capsys.readouterr().out
    for r in fused:
        assert r["rgb"].shape == (1, 1024)


def test_flow_one_frame_video_routes_solo(tmp_path):
    """A 1-frame video makes zero pairs hence zero windows: agg_key must
    decline (not IndexError) and the solo path must return the empty flow
    array — same contract as video_batch=1 (code-review r04)."""
    from video_features_tpu.models.pwc.extract_pwc import ExtractPWC
    from video_features_tpu.utils.synth import synth_video

    one = synth_video(str(tmp_path / "one.mp4"), n_frames=1, width=96, height=64)
    ex = ExtractPWC(
        _flow_cfg("pwc", [one], tmp_path, video_batch=2), external_call=True
    )
    payload = ex.prepare(one)
    assert payload[0] == [] or payload[0] == "stream" or len(payload[0]) == 0
    assert ex.agg_key(payload) is None
    (res,) = ex()
    assert res["pwc"].shape[0] == 0


def test_flow_over_cap_video_streams_serially(three_flow_videos, tmp_path):
    """A flow video over the prefetch byte budget must fall back to the
    serial streaming loop (prepare -> ("stream", entry) ->
    dispatch_prepared -> extract) and still produce identical features to
    the prepared path."""
    from video_features_tpu.models.pwc.extract_pwc import ExtractPWC

    normal = ExtractPWC(
        _flow_cfg("pwc", three_flow_videos[:1], tmp_path), external_call=True
    )
    (want,) = normal()

    capped = ExtractPWC(
        _flow_cfg("pwc", three_flow_videos[:1], tmp_path), external_call=True
    )
    # the byte budget floors at 4 windows (a tiny budget still prefetches
    # a little), so pin the cap itself below the 9-frame video
    capped._window_cap = lambda frame: 4
    assert capped.prepare(three_flow_videos[0])[0] == "stream"
    (got,) = capped()
    np.testing.assert_allclose(got["pwc"], want["pwc"], atol=1e-4, rtol=1e-4)
    np.testing.assert_array_equal(got["timestamps_ms"], want["timestamps_ms"])


def test_flow_aggregation_through_queue_scheduler(three_flow_videos, tmp_path):
    """--video_batch on a flow extractor through parallel_feature_extraction
    on TWO devices: the r4 fused-window dispatch_group runs inside the
    multi-device queue branch (per-chip chunking, per-video output files)
    with features matching the solo run."""
    from video_features_tpu.models.pwc.extract_pwc import ExtractPWC
    from video_features_tpu.parallel.devices import resolve_devices
    from video_features_tpu.parallel.scheduler import parallel_feature_extraction

    cfg = _flow_cfg(
        "pwc", three_flow_videos, tmp_path, video_batch=2,
        on_extraction="save_numpy",
    ).replace(cpu=False, device_ids=[0, 1])
    devices = resolve_devices(cfg)
    assert len(devices) == 2
    ex = ExtractPWC(cfg)
    parallel_feature_extraction(ex, devices)
    saved = sorted(pathlib.Path(tmp_path / "out").rglob("*.npy"))
    assert len(saved) == 3
    solo = ExtractPWC(
        _flow_cfg("pwc", three_flow_videos, tmp_path / "solo"),
        external_call=True,
    )()
    for f, s in zip(saved, solo):  # both sorted by stem f0..f2
        np.testing.assert_allclose(np.load(f), s["pwc"], atol=1e-3, rtol=1e-3)
