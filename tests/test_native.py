"""Native C++ preprocess library: build, parity vs the PIL chain,
threading invariance, and the ResNet opt-in path."""

import numpy as np
import pytest

from video_features_tpu import native
from video_features_tpu.config import ExtractionConfig
from video_features_tpu.ops.preprocess import imagenet_preprocess

pytestmark = pytest.mark.skipif(
    not native.available(), reason=f"no native toolchain: {native.build_error()}"
)


def _frames(n=3, h=240, w=320, seed=0):
    rng = np.random.RandomState(seed)
    # smooth-ish content so resize differences are representative
    base = rng.randint(0, 256, size=(n, h // 8, w // 8, 3), dtype=np.uint8)
    return np.stack(
        [np.kron(f, np.ones((8, 8, 1))).astype(np.uint8) for f in base]
    )


@pytest.mark.quick
def test_matches_pil_chain_closely():
    frames = _frames()
    ref = np.stack([imagenet_preprocess(f) for f in frames])
    out = native.imagenet_preprocess_batch(frames)
    assert out.shape == ref.shape == (3, 3, 224, 224)
    # PIL quantizes filter coefficients to 8-bit fixed point; the native
    # path is float. Per-pixel differences stay at the quantization scale.
    diff = np.abs(out - ref)
    assert diff.mean() < 0.01
    assert diff.max() < 0.08


def test_threading_is_deterministic():
    frames = _frames(n=8, h=120, w=160)
    a = native.imagenet_preprocess_batch(frames, threads=1)
    b = native.imagenet_preprocess_batch(frames, threads=8)
    np.testing.assert_array_equal(a, b)


def test_upscale_path():
    frames = _frames(n=1, h=112, w=100)  # smaller than the 256 resize target
    out = native.imagenet_preprocess_batch(frames)
    assert out.shape == (1, 3, 224, 224)
    assert np.isfinite(out).all()


@pytest.mark.quick
def test_rejects_bad_shapes():
    with pytest.raises(ValueError):
        native.imagenet_preprocess_batch(np.zeros((2, 8, 8), np.uint8))


def test_extract_resnet_native_preprocess(sample_video, tmp_path):
    from video_features_tpu.models.resnet.extract_resnet import ExtractResNet

    cfg = ExtractionConfig(
        allow_random_init=True,
        feature_type="resnet18",
        video_paths=[sample_video],
        extraction_fps=2.0,
        batch_size=4,
        host_preprocess="native",
        output_path=str(tmp_path / "out"),
        tmp_path=str(tmp_path / "tmp"),
        cpu=True,
    )
    res = ExtractResNet(cfg, external_call=True)([0])
    assert res[0]["resnet18"].shape[1] == 512
    assert np.isfinite(res[0]["resnet18"]).all()


def test_clip_chain_matches_pil_closely():
    """The C++ BICUBIC CLIP chain vs the pip-clip-exact PIL path."""
    from PIL import Image

    from video_features_tpu.ops.preprocess import (
        CLIP_MEAN,
        CLIP_STD,
        normalize_chw,
        pil_center_crop,
        pil_resize,
        to_float_chw,
    )

    frames = _frames(n=3, h=360, w=640)

    def pil_one(f):
        img = pil_center_crop(pil_resize(f, 224, interpolation=Image.BICUBIC), 224)
        return normalize_chw(to_float_chw(img), CLIP_MEAN, CLIP_STD)

    ref = np.stack([pil_one(f) for f in frames])
    out = native.clip_preprocess_batch(frames)
    assert out.shape == ref.shape == (3, 3, 224, 224)
    # same budget as the bilinear chain: PIL's 8-bit fixed-point filter
    # coefficients vs float taps; bicubic overshoot makes extremes a bit
    # wider but the scale stays ~quantization-level (normalized units)
    diff = np.abs(out - ref)
    assert diff.mean() < 0.02
    assert diff.max() < 0.15


def test_extract_clip_native_preprocess(sample_video, tmp_path):
    """--host_preprocess native end-to-end for CLIP: same shapes, features
    close to the PIL run (budget follows test_bfloat16-style drift, the
    preprocess delta is ~1/255/pixel)."""
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP

    def run(mode):
        cfg = ExtractionConfig(
            allow_random_init=True,
            feature_type="CLIP-ViT-B/32",
            video_paths=[sample_video],
            extract_method="uni_4",
            host_preprocess=mode,
            cpu=True,
        )
        ex = ExtractCLIP(cfg, external_call=True)
        ex.progress.disable = True
        return ex([0])[0]["CLIP-ViT-B/32"]

    pil = run("pil")
    nat = run("native")
    assert pil.shape == nat.shape == (4, 512)
    # random-init features still track preprocess closely
    denom = np.linalg.norm(pil)
    assert np.linalg.norm(pil - nat) / max(denom, 1e-9) < 0.05


# --- native decode loader (decoder.cpp) ------------------------------------

decoder_skip = pytest.mark.skipif(
    not native.decoder_available(),
    reason=f"no native decoder: {native.decoder_build_error()}",
)


@decoder_skip
def test_native_decoder_bit_identical_to_cv2(sample_video):
    """Both backends decode through libavcodec; every frame, timestamp,
    and probe field must match bit-for-bit."""
    from video_features_tpu.io import video as vio

    try:
        vio.set_decoder("cv2")
        ref_meta = vio.probe(sample_video)
        ref = list(vio.stream_frames(sample_video))
        ref_sampled, ref_fps, ref_ts = vio.extract_frames(sample_video, "uni_7")
        vio.set_decoder("native")
        nat_meta = vio.probe(sample_video)
        nat = list(vio.stream_frames(sample_video))
        nat_sampled, nat_fps, nat_ts = vio.extract_frames(sample_video, "uni_7")
    finally:
        vio.set_decoder("auto")

    assert nat_meta == ref_meta
    assert len(nat) == len(ref) and len(ref) > 0
    for (fr_n, ts_n), (fr_c, ts_c) in zip(nat, ref):
        np.testing.assert_array_equal(fr_n, fr_c)
        assert ts_n == ts_c
    assert nat_fps == ref_fps and nat_ts == ref_ts
    for a, b in zip(nat_sampled, ref_sampled):
        np.testing.assert_array_equal(a, b)


@decoder_skip
def test_native_decoder_fps_grid_matches_cv2(sample_video):
    from video_features_tpu.io import video as vio

    try:
        vio.set_decoder("cv2")
        ref = list(vio.stream_frames(sample_video, extraction_fps=7.0))
        vio.set_decoder("native")
        nat = list(vio.stream_frames(sample_video, extraction_fps=7.0))
    finally:
        vio.set_decoder("auto")
    assert len(nat) == len(ref) > 0
    for (fr_n, ts_n), (fr_c, ts_c) in zip(nat, ref):
        np.testing.assert_array_equal(fr_n, fr_c)
        assert ts_n == ts_c


def test_decoder_knob_rejects_unknown():
    from video_features_tpu.io import video as vio

    with pytest.raises(ValueError):
        vio.set_decoder("gstreamer")


@decoder_skip
def test_extract_resnet_with_native_decoder(sample_video, tmp_path):
    """--decoder native end-to-end: identical features to --decoder cv2."""
    from video_features_tpu.models.resnet.extract_resnet import ExtractResNet

    def run(decoder):
        cfg = ExtractionConfig(
            allow_random_init=True,
            feature_type="resnet18",
            video_paths=[sample_video],
            extraction_fps=3.0,
            batch_size=4,
            decoder=decoder,
            cpu=True,
        )
        ex = ExtractResNet(cfg, external_call=True)
        ex.progress.disable = True
        return ex([0])[0]

    a = run("cv2")
    b = run("native")
    np.testing.assert_array_equal(a["resnet18"], b["resnet18"])
    np.testing.assert_array_equal(a["timestamps_ms"], b["timestamps_ms"])
