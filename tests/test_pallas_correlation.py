"""Pallas cost-volume kernel vs the XLA formulation (interpret mode on
CPU; the same kernel compiles for real on TPU backends)."""

import numpy as np
import pytest

import jax.numpy as jnp

from video_features_tpu.ops.correlation import local_correlation
from video_features_tpu.ops.pallas.correlation_kernel import local_correlation_pallas


@pytest.mark.parametrize(
    "shape,tile_h",
    [
        ((2, 16, 16, 24), 8),   # H divides tile
        ((1, 8, 13, 17), 8),    # ragged H and W
        ((1, 32, 8, 8), 8),     # small spatial, single tile
    ],
)
def test_pallas_matches_xla(shape, tile_h):
    rng = np.random.RandomState(0)
    f1 = rng.randn(*shape).astype(np.float32)
    f2 = rng.randn(*shape).astype(np.float32)
    ref = np.asarray(local_correlation(jnp.asarray(f1), jnp.asarray(f2), method="xla"))
    out = np.asarray(
        local_correlation_pallas(
            jnp.asarray(f1), jnp.asarray(f2), tile_h=tile_h, interpret=True
        )
    )
    assert out.shape == ref.shape == (shape[0], 81, shape[2], shape[3])
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


@pytest.mark.quick
def test_zero_padding_semantics():
    """Displacements that land outside f2 must contribute exact zeros
    (ref correlation.py zero-pads, no edge replication)."""
    f1 = np.ones((1, 4, 8, 8), np.float32)
    f2 = np.ones((1, 4, 8, 8), np.float32)
    out = np.asarray(
        local_correlation_pallas(jnp.asarray(f1), jnp.asarray(f2), interpret=True)
    )
    # channel 0 = (dy=-4, dx=-4): at pixel (0, 0) it samples f2[-4, -4] -> 0
    assert out[0, 0, 0, 0] == 0.0
    # center channel 40 = (0, 0): everywhere mean(1*1) = 1
    np.testing.assert_allclose(out[0, 40], 1.0, atol=1e-6)
