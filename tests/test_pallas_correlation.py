"""Pallas cost-volume kernel vs the XLA formulation (interpret mode on
CPU; the same kernel compiles for real on TPU backends)."""

import numpy as np
import pytest

import jax.numpy as jnp

from video_features_tpu.ops.correlation import local_correlation
from video_features_tpu.ops.pallas.correlation_kernel import local_correlation_pallas


@pytest.mark.parametrize(
    "shape,tile_h",
    [
        ((2, 16, 16, 24), 8),   # H divides tile
        ((1, 8, 13, 17), 8),    # ragged H and W
        ((1, 32, 8, 8), 8),     # small spatial, single tile
        # the EXACT on-chip validation tiers (scripts/validate_corr_tpu.py)
        # at default tiling, so interpret-mode parity covers the same
        # (shape, grid) configurations the compiled runs will execute —
        # N reduced (the kernel grid is per-pair; more pairs repeat it)
        ((2, 64, 16, 16), None),   # tier 1, pyramid level ~4
        ((2, 64, 32, 32), None),   # tier 2, level 3
        ((2, 32, 64, 64), None),   # tier 3, level 2 (the hottest volume)
    ],
)
def test_pallas_matches_xla(shape, tile_h):
    rng = np.random.RandomState(0)
    f1 = rng.randn(*shape).astype(np.float32)
    f2 = rng.randn(*shape).astype(np.float32)
    ref = np.asarray(local_correlation(jnp.asarray(f1), jnp.asarray(f2), method="xla"))
    kw = {} if tile_h is None else {"tile_h": tile_h}  # None = default tiling
    out = np.asarray(
        local_correlation_pallas(
            jnp.asarray(f1), jnp.asarray(f2), interpret=True, **kw
        )
    )
    assert out.shape == ref.shape == (shape[0], 81, shape[2], shape[3])
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


@pytest.mark.quick
def test_zero_padding_semantics():
    """Displacements that land outside f2 must contribute exact zeros
    (ref correlation.py zero-pads, no edge replication)."""
    f1 = np.ones((1, 4, 8, 8), np.float32)
    f2 = np.ones((1, 4, 8, 8), np.float32)
    out = np.asarray(
        local_correlation_pallas(jnp.asarray(f1), jnp.asarray(f2), interpret=True)
    )
    # channel 0 = (dy=-4, dx=-4): at pixel (0, 0) it samples f2[-4, -4] -> 0
    assert out[0, 0, 0, 0] == 0.0
    # center channel 40 = (0, 0): everywhere mean(1*1) = 1
    np.testing.assert_allclose(out[0, 40], 1.0, atol=1e-6)
