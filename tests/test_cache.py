"""Content-addressed feature cache + shared-decode fan-out (ISSUE 17).

Pins the cache's safety contract — a wrong hit is never possible — and
the fan-out's economy contract — N models over one video decode its
bytes exactly once, bit-identically to N separate runs:

- content_hash: fast/full modes both detect a content change; the
  (path, size, mtime) memo spares repeat hashing; unreadable input
  raises (the callers treat that as "not cacheable", never as a hit).
- config_digest: any knob that changes extracted bytes changes the
  digest; knobs that don't (output_path) don't.
- FeatureCache: publish/lookup roundtrip, claim-by-rename makes the
  second publisher a no-op loser, corrupt entry.json and torn payloads
  degrade to a miss.
- Batch: a second identical run resolves every video as a manifest
  ``cache_hit``; a config change or content change misses.
- Fan-out: CLIP+ResNet over one corpus opens exactly one decoder per
  video and matches the single-model runs byte for byte.
- Serve: admission-time hits return a terminal record with no dispatch,
  the multi-model request form fans out, and the cache shows up on
  /v1/stats and as ``vft_cache_*`` on /metrics.
"""

import json
import os
import shutil
import threading
import urllib.request

import numpy as np
import pytest

from video_features_tpu import cli
from video_features_tpu.config import ExtractionConfig, parse_serve_args, sanity_check
from video_features_tpu.extract import cache as fcache
from video_features_tpu.extract.cache import (
    FeatureCache,
    config_digest,
    content_hash,
    feature_keys_for,
)
from video_features_tpu.extract.plan import SharedFrameCache
from video_features_tpu.extract.registry import media_need_for
from video_features_tpu.runtime.faults import iter_manifest_records
from video_features_tpu.serve.daemon import ServeDaemon
from video_features_tpu.serve.server import start_http_server
from video_features_tpu.telemetry.exposition import (
    check_exposition,
    families_from_snapshot,
    render_families,
)

pytestmark = pytest.mark.cache


# --- content hashing --------------------------------------------------------


def _blob(tmp_path, name="blob.bin", size=4 << 20, seed=0):
    rng = np.random.default_rng(seed)
    p = str(tmp_path / name)
    with open(p, "wb") as fh:
        fh.write(rng.integers(0, 256, size, dtype=np.uint8).tobytes())
    return p


def _flip_byte(p, offset):
    with open(p, "r+b") as fh:
        fh.seek(offset)
        b = fh.read(1)
        fh.seek(-1, os.SEEK_CUR)
        fh.write(bytes([b[0] ^ 0xFF]))


@pytest.mark.parametrize("mode", ["fast", "full"])
def test_content_hash_detects_header_edit(tmp_path, mode):
    # the header is covered by BOTH modes (fast reads the first 1 MiB)
    p = _blob(tmp_path)
    before = content_hash(p, mode)
    assert before == content_hash(p, mode)  # deterministic
    _flip_byte(p, 4096)
    assert content_hash(p, mode) != before


def test_full_hash_covers_what_fast_samples_past(tmp_path):
    # fast is a sampled hash: a flip BETWEEN its sampled chunks is the
    # blind spot --cache_hash full exists for. Pin both sides of the
    # tradeoff so a resampling change that closes (or widens) the gap
    # shows up here.
    p = _blob(tmp_path)
    fast, full = content_hash(p, "fast"), content_hash(p, "full")
    _flip_byte(p, (4 << 20) // 2)  # mid-file, outside fast's samples
    assert content_hash(p, "full") != full
    assert content_hash(p, "fast") == fast


def test_content_hash_modes_differ_and_size_prefix(tmp_path):
    p = _blob(tmp_path)
    assert content_hash(p, "fast") != content_hash(p, "full")
    with pytest.raises(ValueError):
        content_hash(p, "sampled")


def test_content_hash_memo_spares_rereads(tmp_path, monkeypatch):
    p = _blob(tmp_path, size=1 << 20)
    content_hash(p, "fast")  # prime
    calls = []
    real = fcache._hash_bytes
    monkeypatch.setattr(
        fcache, "_hash_bytes", lambda *a: calls.append(a) or real(*a)
    )
    h1 = content_hash(p, "fast")
    h2 = content_hash(p, "fast")
    assert h1 == h2 and calls == []  # memo hit: bytes never re-read
    # a rewrite (new mtime) invalidates the memo
    with open(p, "r+b") as fh:
        fh.write(b"\x00")
    os.utime(p, ns=(1, 1))
    content_hash(p, "fast")
    assert len(calls) == 1


def test_content_hash_unreadable_raises(tmp_path):
    with pytest.raises(OSError):
        content_hash(str(tmp_path / "missing.mp4"))


def test_audio_inputs_hash_like_video(tmp_path, sample_wav):
    # VGGish requests key on the same byte-content hash — audio is
    # cacheable through the identical code path
    assert media_need_for("vggish") == "audio"
    assert media_need_for("resnet18") == "video"
    assert len(content_hash(sample_wav)) == 64


# --- config digest ----------------------------------------------------------


def _cfg(**kw):
    kw.setdefault("feature_type", "resnet18")
    kw.setdefault("video_paths", ["/v.mp4"])
    return ExtractionConfig(**kw)


def test_config_digest_tracks_extraction_knobs():
    base = _cfg()
    assert config_digest(base) == config_digest(_cfg())
    # knobs that change the bytes change the digest
    assert config_digest(base) != config_digest(_cfg(extraction_fps=5.0))
    assert config_digest(base) != config_digest(_cfg(feature_type="resnet50"))
    assert config_digest(base) != config_digest(_cfg(side_size=100))
    # knobs that don't (where the files land, batch shape) don't
    assert config_digest(base) == config_digest(_cfg(output_path="/elsewhere"))
    assert config_digest(base) == config_digest(_cfg(video_paths=["/other.mp4"]))


def test_feature_keys_for_i3d_streams():
    assert feature_keys_for(_cfg()) == ["resnet18"]
    assert feature_keys_for(_cfg(feature_type="i3d")) == ["rgb", "flow"]
    assert feature_keys_for(_cfg(feature_type="i3d", streams="rgb")) == ["rgb"]


# --- store: publish / lookup / corruption ----------------------------------


def _store_with_entry(tmp_path, key="resnet18"):
    video = _blob(tmp_path, "clip.bin", size=1 << 16, seed=3)
    feat = str(tmp_path / f"x_{key}.npy")
    np.save(feat, np.arange(12, dtype=np.float32))
    store = FeatureCache(str(tmp_path / "store"))
    chash = store.content_hash(video)
    assert store.publish(chash, "d" * 16, {key: feat}, feature_type=key)
    return store, chash, video, feat


def test_publish_lookup_materialize_roundtrip(tmp_path):
    store, chash, _, feat = _store_with_entry(tmp_path)
    hit = store.lookup(chash, "d" * 16, ["resnet18"])
    assert hit is not None
    dest = str(tmp_path / "out" / "x_resnet18.npy")
    assert store.materialize(hit, {"resnet18": dest}) == [dest]
    np.testing.assert_array_equal(np.load(dest), np.load(feat))
    # wrong digest or wrong keys: miss, never a partial hit
    assert store.lookup(chash, "e" * 16, ["resnet18"]) is None
    assert store.lookup(chash, "d" * 16, ["resnet18", "flow"]) is None


def test_second_publisher_loses_claim_by_rename(tmp_path):
    store, chash, _, feat = _store_with_entry(tmp_path)
    entry = store.entry_dir(chash, "d" * 16)
    mtime = os.path.getmtime(os.path.join(entry, "entry.json"))
    # replica 2 finishes the same work: publish is a no-op loser, the
    # winner's entry is untouched, and no stage dir leaks
    assert not store.publish(chash, "d" * 16, {"resnet18": feat})
    assert os.path.getmtime(os.path.join(entry, "entry.json")) == mtime
    assert os.listdir(os.path.join(store.root, ".tmp")) == []


def test_corrupt_entry_json_is_a_miss(tmp_path):
    store, chash, _, _ = _store_with_entry(tmp_path)
    entry = store.entry_dir(chash, "d" * 16)
    with open(os.path.join(entry, "entry.json"), "w") as fh:
        fh.write('{"format_version"')  # torn mid-write
    assert store.lookup(chash, "d" * 16, ["resnet18"]) is None


def test_torn_payload_is_a_miss(tmp_path):
    store, chash, _, _ = _store_with_entry(tmp_path)
    entry = store.entry_dir(chash, "d" * 16)
    with open(os.path.join(entry, "resnet18.npy"), "wb") as fh:
        fh.write(b"\x00\x00")  # not the numpy magic: torn/corrupt
    assert store.lookup(chash, "d" * 16, ["resnet18"]) is None


# --- batch: hit / miss semantics end to end ---------------------------------


@pytest.fixture(scope="module")
def cache_videos(tmp_path_factory):
    from video_features_tpu.utils.synth import synth_video

    d = tmp_path_factory.mktemp("cache_media")
    return [
        synth_video(str(d / f"v{i}.mp4"), n_frames=8, width=64, height=48, seed=i)
        for i in range(2)
    ]


def _batch_argv(tmp_path, videos, out="out", **extra):
    argv = [
        "--feature_type", "resnet18",
        "--video_paths", *videos,
        "--output_path", str(tmp_path / out),
        "--tmp_path", str(tmp_path / "tmp"),
        "--cache_dir", str(tmp_path / "store"),
        "--allow_random_init", "--cpu", "--on_extraction", "save_numpy",
        "--heartbeat_s", "0",
    ]
    for k, v in extra.items():
        argv += [f"--{k}"] + ([str(v)] if v is not True else [])
    return argv


def _hit_notes(out_dir):
    return [
        r for r in iter_manifest_records(str(out_dir))
        if r.get("status") == "done" and r.get("note") == "cache_hit"
    ]


def test_batch_second_run_is_all_cache_hits(tmp_path, cache_videos):
    cli.main(_batch_argv(tmp_path, cache_videos))
    assert _hit_notes(tmp_path / "out") == []  # cold: all misses
    first = np.load(tmp_path / "out" / "resnet18" / "v0_resnet18.npy")

    cli.main(_batch_argv(tmp_path, cache_videos, out="out2"))
    assert len(_hit_notes(tmp_path / "out2")) == len(cache_videos)
    np.testing.assert_array_equal(
        np.load(tmp_path / "out2" / "resnet18" / "v0_resnet18.npy"), first
    )

    # a digest-relevant knob change misses (and repopulates under the
    # new digest, so both entries coexist)
    cli.main(_batch_argv(tmp_path, cache_videos, out="out3", extraction_fps=5))
    assert _hit_notes(tmp_path / "out3") == []

    # a content change misses: same path, new bytes
    edited = str(tmp_path / "edited.mp4")
    shutil.copyfile(cache_videos[0], edited)
    with open(edited, "r+b") as fh:
        fh.seek(-64, os.SEEK_END)
        fh.write(b"\xff" * 8)
    cli.main(_batch_argv(tmp_path, [edited], out="out4"))
    assert _hit_notes(tmp_path / "out4") == []


# --- fan-out: decode once, bit-identical ------------------------------------


def test_fanout_decodes_once_and_matches_single_runs(tmp_path, cache_videos, monkeypatch):
    import video_features_tpu.io.video as vio

    fts = ["resnet18", "CLIP-ViT-B/32"]
    # single-model baselines, no caches in play
    for ft in fts:
        cli.main([
            "--feature_type", ft, "--video_paths", *cache_videos,
            "--output_path", str(tmp_path / "single"),
            "--tmp_path", str(tmp_path / "tmp"),
            "--allow_random_init", "--cpu", "--extract_method", "fix_2",
            "--on_extraction", "save_numpy", "--heartbeat_s", "0",
            "--ingest_cache_mb", "0",
        ])

    opened = []
    real_init = vio._Reader.__init__
    monkeypatch.setattr(
        vio._Reader, "__init__",
        lambda self, *a, **kw: opened.append(a) or real_init(self, *a, **kw),
    )
    cli.main([
        "--feature_types", *fts, "--video_paths", *cache_videos,
        "--output_path", str(tmp_path / "fanout"),
        "--tmp_path", str(tmp_path / "tmp"),
        "--allow_random_init", "--cpu", "--extract_method", "fix_2",
        "--on_extraction", "save_numpy", "--heartbeat_s", "0",
    ])
    # the economy claim: one decoder per video for BOTH models
    assert len(opened) == len(cache_videos)
    # the correctness claim: shared decode is bit-identical per model
    for ft in fts:
        sub = ft.replace("/", "-")
        for i in range(len(cache_videos)):
            np.testing.assert_array_equal(
                np.load(tmp_path / "fanout" / ft / f"v{i}_{sub}.npy"),
                np.load(tmp_path / "single" / ft / f"v{i}_{sub}.npy"),
            )


def test_shared_frame_cache_budget_and_latch(tmp_path, cache_videos):
    big = SharedFrameCache(max_bytes=64 << 20)
    clip = big.acquire(cache_videos[0])
    assert clip is not None and len(clip.frames) == 8
    assert big.acquire(cache_videos[0]) is clip  # LRU hit, same object
    assert big.stats()["populated"] == 1 and big.stats()["hits"] == 1
    # an over-budget clip is abandoned: caller falls back to direct decode
    tiny = SharedFrameCache(max_bytes=1024)
    assert tiny.acquire(cache_videos[0]) is None
    assert tiny.stats()["clips"] == 0
    # concurrent acquirers converge on one decode
    shared = SharedFrameCache(max_bytes=64 << 20)
    got = []
    ts = [
        threading.Thread(target=lambda: got.append(shared.acquire(cache_videos[1])))
        for _ in range(4)
    ]
    [t.start() for t in ts]
    [t.join() for t in ts]
    assert len({id(c) for c in got}) == 1 and shared.stats()["populated"] == 1


# --- serve: admission-time hits, fan-out request form -----------------------


def _post(port, body):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/extract", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req) as r:
        return r.status, json.loads(r.read())


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}") as r:
        return r.status, r.read().decode()


def test_serve_cache_and_fanout_end_to_end(tmp_path, cache_videos):
    scfg = parse_serve_args([
        "--feature_types", "resnet18",
        "--output_path", str(tmp_path / "out"),
        "--tmp_path", str(tmp_path / "tmp"),
        "--cache_dir", str(tmp_path / "store"),
        "--allow_random_init", "--cpu", "--heartbeat_s", "0",
        "--on_extraction", "save_numpy",
    ])
    d = ServeDaemon(scfg)
    d.start()
    server, _ = start_http_server(d, "127.0.0.1", 0)
    port = server.server_address[1]
    try:
        # warm the store through the real miss path: two cold videos
        # queue normally, one inline drain finishes both
        for rid, video in (("a", cache_videos[0]), ("e", cache_videos[1])):
            code, rec = _post(port, {
                "feature_type": "resnet18", "video_path": video, "id": rid,
            })
            assert code == 202 and rec["state"] == "queued"
        d.batcher.close(drain=True)  # inline drain: deterministic
        assert json.loads(_get(port, "/v1/requests/a")[1])["state"] == "done"

        # identical request: terminal at admission, features listed, and
        # the dispatch queue never sees it (the batcher is already
        # closed — a hit that touched it would 503)
        code, rec = _post(port, {
            "feature_type": "resnet18", "video_path": cache_videos[0], "id": "b",
        })
        assert code == 202 and rec["state"] == "done" and rec["features"]
        assert all(os.path.exists(f) for f in rec["features"])

        # the fan-out request form (single-model daemon: list of one)
        code, agg = _post(port, {
            "feature_types": ["resnet18"], "video_path": cache_videos[0],
            "id": "c",
        })
        assert code == 202 and agg["fanout"] is True
        assert agg["requests"]["resnet18"]["state"] == "done"  # hit again
        assert agg["requests"]["resnet18"]["id"] == "c.resnet18"

        stats = json.loads(_get(port, "/v1/stats")[1])
        assert stats["cache"]["enabled"]
        assert stats["cache"]["hits"] == 2 and stats["cache"]["misses"] == 2
        assert stats["cache"]["hit_rate"] == 0.5

        text = _get(port, "/metrics")[1]
        assert 'vft_cache_hit_total{feature_type="resnet18"} 2' in text
        assert 'vft_cache_miss_total{feature_type="resnet18"} 2' in text
        assert check_exposition(text) == []
    finally:
        server.shutdown()
        d.shutdown()


def test_fanout_request_validation(tmp_path, cache_videos):
    scfg = parse_serve_args([
        "--feature_types", "resnet18",
        "--output_path", str(tmp_path / "out"),
        "--tmp_path", str(tmp_path / "tmp"),
        "--allow_random_init", "--cpu", "--heartbeat_s", "0",
    ])
    d = ServeDaemon(scfg)
    try:
        from video_features_tpu.serve.lifecycle import BadRequest

        with pytest.raises(BadRequest):  # empty list
            d.submit({"feature_types": [], "video_path": cache_videos[0]}, source="http")
        with pytest.raises(BadRequest):  # both forms at once
            d.submit({
                "feature_types": ["resnet18"], "feature_type": "resnet18",
                "video_path": cache_videos[0],
            }, source="http")
        with pytest.raises(BadRequest):  # unserved member rejects the WHOLE list
            d.submit({
                "feature_types": ["resnet18", "i3d"],
                "video_path": cache_videos[0],
            }, source="http")
        assert d.tracker.counts().get("queued", 0) == 0  # nothing half-admitted
    finally:
        d.shutdown()


# --- exposition mapping -----------------------------------------------------


def test_cache_counters_render_as_labelled_families():
    fams = families_from_snapshot({
        "counters": {"cache_hit.resnet18": 3, "cache_miss.CLIP-ViT-B/32": 1},
        "gauges": {}, "histograms": {},
    })
    text = render_families(fams)
    assert 'vft_cache_hit_total{feature_type="resnet18"} 3' in text
    assert 'vft_cache_miss_total{feature_type="CLIP-ViT-B/32"} 1' in text
    assert check_exposition(text) == []
