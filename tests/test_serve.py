"""Serving daemon (video_features_tpu/serve): ISSUE 7's contracts.

Deterministic by construction: the admission controller's deadline logic
is a pure sweep over an injected clock (no sleeps), and daemon-level
tests drive the batcher's inline drain path on the test thread with a
stub extractor — so the acceptance criteria (a burst of N same-key
requests dispatches in exactly ceil(N / max_group_size) fused groups,
mixed buckets never share a group, repeat requests pay no rebuild, every
request ends in a queryable manifest-backed terminal record) are pinned
without a single race. One test each then exercises the real dispatcher
thread, the HTTP door, and the spool watcher end to end.
"""

import json
import os
import textwrap
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from video_features_tpu.config import parse_serve_args, parse_warmup_spec
from video_features_tpu.extract.base import BaseExtractor
from video_features_tpu.io.paths import video_path_of
from video_features_tpu.io.video import stream_frames
from video_features_tpu.runtime import faults
from video_features_tpu.serve.batcher import AdmissionController, QueueFull
from video_features_tpu.serve.daemon import ServeDaemon
from video_features_tpu.serve.lifecycle import (
    BadRequest,
    ExtractionRequest,
    RequestTracker,
    parse_request,
)
from video_features_tpu.serve.sources import SpoolWatcher

pytestmark = pytest.mark.serve


# --- helpers ----------------------------------------------------------------


class FakeClock:
    def __init__(self, t: float = 0.0) -> None:
        self.t = t

    def __call__(self) -> float:
        return self.t


def _req(i, bucket="64x48", ft="resnet18", video="/v.mp4"):
    return ExtractionRequest(
        feature_type=ft, video_path=video, bucket=bucket, id=f"r{i}"
    )


def _controller(sink, clock, **kw):
    kw.setdefault("max_group_size", 3)
    kw.setdefault("max_batch_wait_s", 0.05)
    return AdmissionController(
        dispatch=lambda key, reqs: sink.append((key, [r.id for r in reqs])),
        clock=clock,
        **kw,
    )


@pytest.fixture(scope="module")
def serve_videos(tmp_path_factory):
    from video_features_tpu.utils.synth import synth_video

    d = tmp_path_factory.mktemp("serve_media")
    return [
        synth_video(str(d / f"v{i}.mp4"), n_frames=10, width=64, height=48, seed=i)
        for i in range(8)
    ]


class ServeToy(BaseExtractor):
    """Stub extractor with the --video_batch aggregation protocol and a
    build counter: groups of same-shape payloads fuse through
    dispatch_group, and ``built`` counts weight loads (the no-reload
    acceptance assert)."""

    feature_type = "toy"

    def _build(self, device):
        type(self).built = getattr(type(self), "built", 0) + 1
        return {"device": device}

    def prepare(self, path_entry):
        vals = [float(f.mean()) for f, _ in stream_frames(video_path_of(path_entry))]
        return np.asarray(vals, dtype=np.float32)

    def extract_prepared(self, device, state, path_entry, payload):
        return {
            "toy": np.asarray(payload).reshape(-1, 1),
            "fps": 25.0,
            "timestamps_ms": np.arange(len(payload), dtype=np.float64),
        }

    def agg_key(self, payload):
        return np.asarray(payload).shape

    def dispatch_group(self, device, state, entries, payloads):
        return [
            self.extract_prepared(device, state, e, p)
            for e, p in zip(entries, payloads)
        ]

    def fetch_group(self, handle):
        return handle


def _daemon(tmp_path, videos, **flags):
    argv = [
        "--feature_types", "resnet18",
        "--output_path", str(tmp_path / "out"),
        "--tmp_path", str(tmp_path / "tmp"),
        "--allow_random_init", "--cpu",
        "--heartbeat_s", "0",
    ]
    for k, v in flags.items():
        argv += [f"--{k}"] + ([str(v)] if v is not True else [])
    scfg = parse_serve_args(argv)
    class Toy(ServeToy):  # per-daemon build counter
        built = 0
    d = ServeDaemon(scfg, build=Toy)
    return d, Toy


def _request_spans(daemon):
    ext = daemon.pool._extractors["resnet18"]
    return [s for s in ext.telemetry.spans() if s["stage"] == "request"]


def _wait(pred, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return False


# --- admission controller units (fake clock, no threads) --------------------


def test_coalesce_waits_for_deadline():
    sink, clock = [], FakeClock()
    c = _controller(sink, clock)
    c.admit(_req(0))
    c.admit(_req(1))
    assert c.take_ready(now=0.049) == []  # deadline not reached: still coalescing
    groups = c.take_ready(now=0.05)
    assert [(k[1], [r.id for r in reqs]) for k, reqs in groups] == [
        ("64x48", ["r0", "r1"])
    ]


def test_deadline_is_set_by_first_member_never_extended():
    sink, clock = [], FakeClock()
    c = _controller(sink, clock)
    c.admit(_req(0))
    clock.t = 0.04
    c.admit(_req(1))  # joins r0's buffer; must NOT push the deadline out
    groups = c.take_ready(now=0.051)
    assert [[r.id for r in reqs] for _, reqs in groups] == [["r0", "r1"]]


def test_full_group_dispatches_before_deadline():
    sink, clock = [], FakeClock()
    c = _controller(sink, clock, max_group_size=2)
    c.admit(_req(0))
    c.admit(_req(1))
    groups = c.take_ready(now=0.0)  # no time has passed at all
    assert [[r.id for r in reqs] for _, reqs in groups] == [["r0", "r1"]]


def test_burst_splits_into_ceil_n_over_group():
    sink, clock = [], FakeClock()
    c = _controller(sink, clock, max_group_size=4)
    for i in range(10):
        c.admit(_req(i))
    groups = c.take_ready(now=1.0)
    sizes = [len(reqs) for _, reqs in groups]
    assert sizes == [4, 4, 2]  # ceil(10/4) == 3 groups, order preserved
    assert [r.id for r in groups[0][1]] == ["r0", "r1", "r2", "r3"]


def test_mixed_buckets_never_share_a_group():
    sink, clock = [], FakeClock()
    c = _controller(sink, clock, max_group_size=8)
    for i in range(6):
        c.admit(_req(i, bucket="64x48" if i % 2 == 0 else "320x240"))
    groups = c.take_ready(now=1.0)
    assert sorted((k[1], tuple(r.id for r in reqs)) for k, reqs in groups) == [
        ("320x240", ("r1", "r3", "r5")),
        ("64x48", ("r0", "r2", "r4")),
    ]


def test_queue_bound_rejects_and_tracks_depth():
    sink, clock = [], FakeClock()
    c = _controller(sink, clock, max_queue=2)
    c.admit(_req(0))
    c.admit(_req(1))
    assert c.depth() == 2
    with pytest.raises(QueueFull):
        c.admit(_req(2))
    # depth is admitted-not-terminal: it only falls after dispatch runs
    for g in c.take_ready(now=1.0):
        c._run_group(g)
    assert c.depth() == 0
    assert sink  # the dispatch callback actually ran


def test_close_drains_inline_when_thread_never_started():
    sink, clock = [], FakeClock()
    c = _controller(sink, clock, max_group_size=2)
    for i in range(5):
        c.admit(_req(i))
    dropped = c.close(drain=True)
    assert dropped == []
    assert [len(ids) for _, ids in sink] == [2, 2, 1]
    with pytest.raises(QueueFull):  # closed: no new admissions
        c.admit(_req(9))


def test_close_without_drain_returns_undispatched():
    sink, clock = [], FakeClock()
    c = _controller(sink, clock)
    c.admit(_req(0))
    c.admit(_req(1))
    dropped = c.close(drain=False)
    assert [r.id for r in dropped] == ["r0", "r1"]
    assert sink == [] and c.depth() == 0


def test_dispatcher_thread_end_to_end():
    """The one real-thread batcher test: groups flow through the
    dispatcher thread and close() joins it after the backlog drains."""
    sink = []
    c = AdmissionController(
        dispatch=lambda key, reqs: sink.append([r.id for r in reqs]),
        max_group_size=2, max_batch_wait_s=0.005,
    )
    c.start()
    for i in range(5):
        c.admit(_req(i))
    assert _wait(lambda: sum(len(g) for g in sink) == 5, timeout=10)
    c.close(drain=True)
    assert sorted(x for g in sink for x in g) == [f"r{i}" for i in range(5)]


# --- request lifecycle -------------------------------------------------------


def test_parse_request_validation():
    ok = parse_request(
        {"feature_type": "resnet18", "video_path": "/v.mp4", "bucket": "64x48"},
        source="http",
    )
    assert ok.key() == ("resnet18", "64x48") and ok.source == "http"
    for bad in [
        "not a dict",
        {},
        {"feature_type": "resnet18"},
        {"feature_type": "resnet18", "video_path": "/v.mp4", "id": "../escape"},
        {"feature_type": "resnet18", "video_path": "/v.mp4", "id": ""},
        {"feature_type": "resnet18", "video_path": "/v.mp4", "bucket": "x" * 40},
    ]:
        with pytest.raises(BadRequest):
            parse_request(bad, source="http")


def test_tracker_full_lifecycle_is_manifest_backed(tmp_path):
    tr = RequestTracker(str(tmp_path))
    req = _req(0, video="/v.mp4")
    rec = tr.admit(req)
    assert rec["state"] == "queued"
    tr.dispatched(req, group_size=3)
    assert tr.get("r0")["state"] == "dispatched"
    tr.finish(req, "done", features=["/out/f.npy"])
    got = tr.get("r0")
    assert got["state"] == "done" and got["features"] == ["/out/f.npy"]
    # durable: the result JSON answers status queries after a "restart"
    tr._records.clear()
    disk = tr.get("r0")
    assert disk["state"] == "done" and "wall_s" in disk
    assert tr.get("no-such-id") is None
    assert tr.get("../escape") is None
    # and the request manifest folds to a terminal 'done'
    s = faults.merge_manifest(tr.results_dir)
    assert s["videos"]["request:r0"]["status"] == "done"
    assert s["done"] == 1


def test_tracker_reject_is_terminal_in_merge(tmp_path):
    tr = RequestTracker(str(tmp_path))
    req = _req(1)
    tr.admit(req)
    tr.reject(req, "queue full (2)")
    assert tr.get("r1")["state"] == "rejected"
    s = faults.merge_manifest(tr.results_dir)
    assert s["rejected"] == 1
    # a later non-terminal record can never resurrect a rejected request
    tr.manifest.record("request:r1", "retry")
    s = faults.merge_manifest(tr.results_dir)
    assert s["videos"]["request:r1"]["status"] == "rejected"


def test_duplicate_request_id_rejected(tmp_path):
    tr = RequestTracker(str(tmp_path))
    tr.admit(_req(0))
    with pytest.raises(BadRequest):
        tr.admit(_req(0))


# --- daemon acceptance (stub extractor, inline drain: fully deterministic) --


def test_burst_dispatches_in_ceil_groups_with_warm_reuse(tmp_path, serve_videos):
    d, Toy = _daemon(tmp_path, serve_videos, max_group_size=3)
    n = 7
    for i in range(n):
        d.submit(
            {"feature_type": "resnet18", "video_path": serve_videos[i % 8],
             "bucket": "64x48", "id": f"req-{i}"},
            source="local",
        )
    d.batcher.close(drain=True)  # inline drain on this thread
    # ceil(7/3) == 3 fused groups, asserted via the request telemetry
    # spans' group_size — and the per-video dispatch path really fused
    # (pipelined dispatch spans carry the same group_size)
    spans = _request_spans(d)
    assert sorted((s["group_size"] for s in spans), reverse=True) == [3, 3, 1]
    ext = d.pool._extractors["resnet18"]
    fused = [s for s in ext.telemetry.spans()
             if s["stage"] == "dispatch" and (s.get("group_size") or 0) > 1]
    assert {s["group_size"] for s in fused} == {3}
    # one build across all groups: the resident extractor reloads nothing
    assert Toy.built == 1
    assert d.pool.build_count == {"resnet18": 1}
    # every request: queryable, manifest-backed, terminal, with features
    for i in range(n):
        rec = d.tracker.get(f"req-{i}")
        assert rec["state"] == "done"
        assert rec["features"] and all(os.path.exists(f) for f in rec["features"])
        assert os.path.exists(
            os.path.join(str(tmp_path / "out"), "_requests", f"req-{i}.json")
        )
    s = faults.merge_manifest(d.tracker.results_dir)
    assert s["done"] == n and s["failed"] == 0
    d.shutdown()


def test_mixed_buckets_isolated_through_daemon(tmp_path, serve_videos):
    d, _ = _daemon(tmp_path, serve_videos, max_group_size=8)
    for i in range(4):
        d.submit(
            {"feature_type": "resnet18", "video_path": serve_videos[i],
             "bucket": "64x48" if i % 2 == 0 else "320x240", "id": f"m-{i}"},
            source="local",
        )
    d.batcher.close(drain=True)
    spans = _request_spans(d)
    buckets = sorted((s["bucket"], tuple(sorted(s["requests"]))) for s in spans)
    assert buckets == [
        ("320x240", ("m-1", "m-3")),
        ("64x48", ("m-0", "m-2")),
    ]
    d.shutdown()


def test_failed_video_yields_failed_request_record(tmp_path, serve_videos):
    bad = str(tmp_path / "corrupt.mp4")
    with open(bad, "wb") as fh:
        fh.write(b"not a video at all")
    # preflight off so the corrupt file reaches extraction: this test pins
    # the in-flight failure record; admission-time rejection is covered in
    # tests/test_hostile_media.py
    d, _ = _daemon(tmp_path, serve_videos, max_group_size=2, preflight="off")
    d.submit({"feature_type": "resnet18", "video_path": bad, "id": "bad-0"},
             source="local")
    d.submit({"feature_type": "resnet18", "video_path": serve_videos[0],
              "id": "good-0"}, source="local")
    d.batcher.close(drain=True)
    assert d.tracker.get("bad-0")["state"] == "failed"
    assert d.tracker.get("bad-0")["error_class"] in ("permanent", "transient")
    assert d.tracker.get("good-0")["state"] == "done"
    d.shutdown()


def test_submit_validates_before_admission(tmp_path, serve_videos):
    d, _ = _daemon(tmp_path, serve_videos)
    with pytest.raises(BadRequest):  # model not served
        d.submit({"feature_type": "i3d", "video_path": serve_videos[0]}, "local")
    with pytest.raises(BadRequest):  # missing file
        d.submit({"feature_type": "resnet18", "video_path": "/nope.mp4"}, "local")
    assert d.batcher.depth() == 0
    d.shutdown()


def test_shutdown_drains_admitted_requests(tmp_path, serve_videos):
    d, _ = _daemon(tmp_path, serve_videos, max_group_size=4)
    for i in range(3):
        d.submit({"feature_type": "resnet18", "video_path": serve_videos[i],
                  "id": f"dr-{i}"}, source="local")
    d.shutdown(drain=True)  # no request admitted before shutdown is dropped
    for i in range(3):
        assert d.tracker.get(f"dr-{i}")["state"] == "done"
    # shutdown finalized BOTH summaries: per-video and per-request
    assert os.path.exists(
        os.path.join(str(tmp_path / "out"), "_manifest", "summary.json")
    )
    req_summary = os.path.join(
        str(tmp_path / "out"), "_requests", "_manifest", "summary.json"
    )
    with open(req_summary, "r", encoding="utf-8") as fh:
        assert json.load(fh)["done"] == 3


def test_shutdown_without_drain_fails_backlog_interrupted(tmp_path, serve_videos):
    # ISSUE 8 satellite: an undrained shutdown must leave a durable
    # terminal record for every undispatched request — failed/interrupted
    # for non-spool sources (spool ones are re-queued instead)
    d, _ = _daemon(tmp_path, serve_videos, max_group_size=4)
    d.submit({"feature_type": "resnet18", "video_path": serve_videos[0],
              "id": "nd-0"}, source="local")
    d.shutdown(drain=False)
    rec = d.tracker.get("nd-0")
    assert rec["state"] == "failed"
    assert rec["error_class"] == "interrupted"
    assert "shutdown" in rec["message"]


def test_warmup_prebuilds_and_requests_reuse(tmp_path, serve_videos):
    d, Toy = _daemon(tmp_path, serve_videos, warmup="resnet18:64x48")
    results = d.warmup()
    assert [r["state"] for r in results] == ["done"]
    assert Toy.built == 1
    # first real request after warmup: same executable, no rebuild
    d.submit({"feature_type": "resnet18", "video_path": serve_videos[0],
              "id": "w-0"}, source="local")
    d.batcher.close(drain=True)
    assert d.tracker.get("w-0")["state"] == "done"
    assert Toy.built == 1
    d.shutdown()


def test_warmup_spec_parsing():
    assert parse_warmup_spec("CLIP-ViT-B/32:640x480") == ("CLIP-ViT-B/32", 640, 480)
    for bad in ["resnet18", "resnet18:640", "nope:64x48", "resnet18:4x4"]:
        with pytest.raises(ValueError):
            parse_warmup_spec(bad)


# --- HTTP source -------------------------------------------------------------


def _post(port, body, path="/v1/extract"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(body).encode() if not isinstance(body, bytes) else body,
        method="POST", headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e)


def _get(port, path):
    try:
        with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=30) as r:
            return r.status, json.load(r)
    except urllib.error.HTTPError as e:
        return e.code, json.load(e)


def test_http_end_to_end(tmp_path, serve_videos):
    d, _ = _daemon(tmp_path, serve_videos, port=0, max_group_size=4,
                   max_batch_wait_ms=10)
    d.start()
    try:
        port = d.http_port
        code, rec = _post(port, {"feature_type": "resnet18",
                                 "video_path": serve_videos[0], "id": "h-0"})
        assert code == 202 and rec["state"] == "queued"
        assert _wait(lambda: d.tracker.get("h-0")["state"] == "done")
        code, got = _get(port, "/v1/requests/h-0")
        assert code == 200 and got["state"] == "done" and got["features"]
        assert _get(port, "/v1/requests/nope")[0] == 404
        code, health = _get(port, "/healthz")
        assert code == 200
        assert health["requests"]["done"] >= 1
        assert health["warm"] == ["resnet18"]
        assert "queue_depth" in health and "max_queue" in health
        # malformed requests -> 400, never a record
        assert _post(port, b"{not json")[0] == 400
        assert _post(port, {"feature_type": "resnet18"})[0] == 400
        assert _post(port, {}, path="/v1/wrong")[0] == 404
    finally:
        d.shutdown()


def test_http_503_past_queue_bound(tmp_path, serve_videos):
    d, _ = _daemon(tmp_path, serve_videos, port=0, max_queue=1)
    # open ONLY the HTTP door — the dispatcher thread stays unstarted, so
    # the queue cannot drain under us and the bound is hit deterministically
    from video_features_tpu.serve.server import start_http_server

    d._http_server, d._http_thread = start_http_server(d, "127.0.0.1", 0)
    try:
        port = d.http_port
        code, _rec = _post(port, {"feature_type": "resnet18",
                                  "video_path": serve_videos[0], "id": "q-0"})
        assert code == 202
        code, err = _post(port, {"feature_type": "resnet18",
                                 "video_path": serve_videos[1], "id": "q-1"})
        assert code == 503 and "full" in err["error"]
        # the rejected request still ends queryable + manifest-backed
        assert d.tracker.get("q-1")["state"] == "rejected"
        # backpressure is visible: gauge wired into the heartbeat line
        assert "queue 1" in d.telemetry.heartbeat_line()
    finally:
        d.shutdown()  # drains q-0 inline
    assert d.tracker.get("q-0")["state"] == "done"


# --- spool source ------------------------------------------------------------


def _spool_write(spool, name, payload):
    tmp = os.path.join(spool, f".{name}.tmp")
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(payload, fh)
    os.replace(tmp, os.path.join(spool, name))


def test_spool_admits_quarantines_and_defers(tmp_path, serve_videos):
    d, _ = _daemon(tmp_path, serve_videos, max_queue=2)
    spool = str(tmp_path / "spool")
    w = SpoolWatcher(d, spool, poll_s=0.05)
    _spool_write(spool, "a.json", {"feature_type": "resnet18",
                                   "video_path": serve_videos[0], "id": "s-0"})
    _spool_write(spool, "broken.json", {"feature_type": "resnet18"})
    with open(os.path.join(spool, "garbage.json"), "w") as fh:
        fh.write("{not json")
    assert w.poll_once() == 1
    assert d.tracker.get("s-0")["state"] in ("queued", "dispatched")
    # admitted file consumed; malformed ones quarantined with a reason
    assert not os.path.exists(os.path.join(spool, "a.json"))
    assert os.path.exists(os.path.join(spool, "broken.json.bad"))
    assert os.path.exists(os.path.join(spool, "broken.json.bad.why"))
    assert os.path.exists(os.path.join(spool, "garbage.json.bad"))
    # queue full -> the file is un-claimed and left for the next poll
    _spool_write(spool, "b.json", {"feature_type": "resnet18",
                                   "video_path": serve_videos[1], "id": "s-1"})
    _spool_write(spool, "c.json", {"feature_type": "resnet18",
                                   "video_path": serve_videos[2], "id": "s-2"})
    assert w.poll_once() == 1  # b admitted (depth 2 == max_queue), c deferred
    assert os.path.exists(os.path.join(spool, "c.json"))
    d.batcher.close(drain=True)  # drain s-0/s-1
    assert d.tracker.get("s-0")["state"] == "done"
    assert d.tracker.get("s-1")["state"] == "done"
    # the controller is closed now: c is un-claimed again, still spooled
    # for the next daemon — a spooled request is never lost, and its
    # deferral left no record behind to collide with the re-submit
    assert w.poll_once() == 0
    assert os.path.exists(os.path.join(spool, "c.json"))
    assert d.tracker.get("s-2") is None
    d.shutdown()


def test_spool_watcher_thread_runs(tmp_path, serve_videos):
    d, _ = _daemon(tmp_path, serve_videos, max_batch_wait_ms=10)
    spool = str(tmp_path / "spool")
    d.batcher.start()
    w = SpoolWatcher(d, spool, poll_s=0.02)
    w.start()
    try:
        _spool_write(spool, "t.json", {"feature_type": "resnet18",
                                       "video_path": serve_videos[0], "id": "t-0"})
        assert _wait(lambda: (d.tracker.get("t-0") or {}).get("state") == "done")
    finally:
        w.stop()
        d.shutdown()


# --- serve CLI plumbing ------------------------------------------------------


def test_cli_routes_serve_warmup(tmp_path, serve_videos, monkeypatch):
    """`video-features-tpu serve warmup ...` goes through cli.main into
    serve_main's preflight-only path (stubbed build, real arg plumbing)."""
    from video_features_tpu.cli import main

    built = []

    class Toy(ServeToy):
        built = 0

    real_init = ServeDaemon.__init__
    monkeypatch.setattr(
        ServeDaemon, "__init__",
        lambda self, scfg, build=None: (built.append(scfg),
                                        real_init(self, scfg, build=Toy))[1],
    )
    main([
        "serve", "warmup",
        "--feature_types", "resnet18", "--warmup", "resnet18:64x48",
        "--output_path", str(tmp_path / "out"),
        "--tmp_path", str(tmp_path / "tmp"),
        "--allow_random_init", "--cpu", "--heartbeat_s", "0",
    ])
    assert built and built[0].warmup_only
    # the preflight left a queryable terminal record behind
    path = os.path.join(str(tmp_path / "out"), "_requests",
                        "warmup-resnet18-64x48.json")
    with open(path, "r", encoding="utf-8") as fh:
        assert json.load(fh)["state"] == "done"


def test_parse_serve_args_validation(tmp_path):
    with pytest.raises(SystemExit):  # unknown model
        parse_serve_args(["--feature_types", "nope"])
    with pytest.raises(ValueError):
        parse_serve_args(["--feature_types", "resnet18", "--max_queue", "0"])
    with pytest.raises(ValueError):  # warmup names an unserved model
        parse_serve_args(["--feature_types", "resnet18",
                          "--warmup", "i3d:64x48"])
    with pytest.raises(ValueError):  # warmup-only with nothing to warm
        parse_serve_args(["warmup", "--feature_types", "resnet18"])
    scfg = parse_serve_args(["--feature_types", "resnet18"])
    assert scfg.extraction.on_extraction == "save_numpy"  # 'print' coerced


# --- extractor pool: builds never hold the pool lock (GC312) -----------------


def test_pool_get_builds_outside_the_pool_lock(tmp_path):
    """The fixed GC312 finding, behaviorally: a slow first build must not
    hold the pool lock — feature_types()/status() answer promptly
    mid-build — and the loser of a build race waits on the latch and
    reuses the winner's extractor (exactly one build)."""
    import threading
    from types import SimpleNamespace

    from video_features_tpu.serve.daemon import ExtractorPool

    scfg = parse_serve_args([
        "--feature_types", "resnet18",
        "--output_path", str(tmp_path / "out"),
        "--tmp_path", str(tmp_path / "tmp"),
        "--allow_random_init", "--cpu",
    ])
    release = threading.Event()
    in_build = threading.Event()

    def build(cfg):
        in_build.set()
        assert release.wait(30.0), "test never released the build"
        return SimpleNamespace(
            manifest=SimpleNamespace(record=lambda *a, **k: None),
            telemetry=SimpleNamespace(close=lambda: None),
        )

    pool = ExtractorPool(scfg.extraction, scfg.max_group_size, build=build)
    got = []
    getters = [
        threading.Thread(
            target=lambda: got.append(pool.get("resnet18")), daemon=True
        )
        for _ in range(2)
    ]
    for t in getters:
        t.start()
    assert in_build.wait(30.0)
    # the pool lock must be free while the build runs: this returns
    # immediately (a regression re-serializing the build behind _lock
    # deadlocks here until the pytest timeout)
    assert pool.feature_types() == []
    release.set()
    for t in getters:
        t.join(30.0)
    assert len(got) == 2 and got[0] is got[1]
    assert pool.build_count == {"resnet18": 1}
    assert pool.feature_types() == ["resnet18"]


def test_pool_failed_build_clears_latch_and_retries():
    """A crashed builder must not wedge the latch: the next get() retries
    from scratch instead of waiting forever on a latch nobody will set."""
    from types import SimpleNamespace

    from video_features_tpu.serve.daemon import ExtractorPool

    calls = []

    def build(cfg):
        calls.append(cfg.feature_type)
        if len(calls) == 1:
            raise RuntimeError("weights missing")
        return SimpleNamespace(
            manifest=SimpleNamespace(record=lambda *a, **k: None),
            telemetry=SimpleNamespace(close=lambda: None),
        )

    pool = ExtractorPool.__new__(ExtractorPool)
    import threading
    import time
    pool._cfg = None
    pool._max_group_size = 1
    pool._build = build
    pool._clock = time.monotonic
    pool._lock = threading.Lock()
    pool._extractors = {}
    pool._building = {}
    pool.build_count = {}
    pool.built_at = {}
    pool._serving_config = lambda ft: SimpleNamespace(feature_type=ft)
    with pytest.raises(RuntimeError):
        pool.get("resnet18")
    assert pool._building == {}, "failed build must clear its latch"
    assert pool.get("resnet18") is not None
    assert pool.build_count == {"resnet18": 1}


# --- graftcheck scope (satellite): serve/ is hot + thread-root ---------------


def test_unguarded_batcher_dict_fires_gc301(tmp_path):
    """Regression: an unguarded shared dict in a serve/ module must fire
    GC301 purely from the path-based scope (no marker comment) — pinning
    that serve/*.py stays in THREAD_ROOT_PATTERNS."""
    from video_features_tpu.analysis import run_checks

    bad = tmp_path / "video_features_tpu" / "serve" / "batcher.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(textwrap.dedent(
        """
        import threading

        _BUFFERS = {}

        def admit(key, req):
            _BUFFERS.setdefault(key, []).append(req)  # unguarded shared dict

        def worker():
            admit('k', 1)

        def start():
            threading.Thread(target=worker).start()
        """
    ))
    fs = run_checks([str(bad)])
    assert "GC301" in [f.rule.id for f in fs]


def test_shipped_serve_package_is_clean():
    from video_features_tpu.analysis import run_checks
    from video_features_tpu.analysis.core import package_root

    fs = run_checks([os.path.join(package_root(), "serve")])
    assert fs == [], [f"{f.rule.id}:{f.path}:{f.line}" for f in fs]


def test_pool_wait_under_lock_would_refire_gc312(tmp_path):
    """Would-refire wire for the fixed pool finding: put the latch wait
    back under the pool lock (untimed) and GC312 must fail the sweep —
    proving both the fix and the rule are live on serve/daemon.py."""
    from video_features_tpu.analysis import run_checks
    from video_features_tpu.analysis.core import package_root

    real = os.path.join(package_root(), "serve", "daemon.py")
    with open(real, encoding="utf-8") as fh:
        src = fh.read()
    fixed = "latch.wait(1.0)"
    assert fixed in src, "the off-lock timed latch wait must exist"
    assert not run_checks([real], rules=["GC312"])
    broken = tmp_path / "video_features_tpu" / "serve" / "daemon.py"
    broken.parent.mkdir(parents=True)
    broken.write_text(src.replace(
        "            if not builder:\n"
        "                latch.wait(1.0)",
        "            if not builder:\n"
        "                with self._lock:\n"
        "                    latch.wait()",
    ))
    fs = run_checks([str(broken)], rules=["GC312"])
    assert fs and all(f.rule.id == "GC312" for f in fs)
    assert any("untimed .wait()" in f.message for f in fs)
