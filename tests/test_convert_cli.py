"""scripts/convert_weights.py: pre-convert reference checkpoints to
flax .msgpack (the offline replacement for the reference's auto-download
registry, SURVEY.md §2 item 21)."""

import pathlib
import runpy
import sys

import numpy as np
import pytest
import torch

import jax

SCRIPT = str(
    pathlib.Path(__file__).resolve().parents[1] / "scripts" / "convert_weights.py"
)


def _run_cli(argv):
    old = sys.argv
    sys.argv = ["convert_weights.py"] + argv
    try:
        runpy.run_path(SCRIPT, run_name="__main__")
    finally:
        sys.argv = old


def test_convert_cli_resnet_roundtrip(tmp_path, capsys):
    """torch .pt -> msgpack via the CLI; the jitted forward must be
    bit-identical whichever format --weights_path gets."""
    from tests.test_resnet import _torch_oracle
    from video_features_tpu.models.common.weights import load_params
    from video_features_tpu.models.resnet.convert import convert_state_dict
    from video_features_tpu.models.resnet.model import build

    oracle = _torch_oracle("resnet18")
    src = tmp_path / "resnet18.pt"
    dst = tmp_path / "resnet18.msgpack"
    torch.save(oracle.state_dict(), src)

    _run_cli(["--feature_type", "resnet18", str(src), str(dst)])
    assert dst.exists() and "M params" in capsys.readouterr().out

    from_msgpack = load_params(str(dst), None)  # .msgpack skips the converter
    from_pt = load_params(str(src), lambda sd: convert_state_dict(sd, "resnet18"))

    x = np.random.RandomState(0).randn(2, 3, 224, 224).astype(np.float32)
    model = build("resnet18")
    f1, _ = jax.jit(model.apply)({"params": from_pt}, x)
    f2, _ = jax.jit(model.apply)({"params": from_msgpack}, x)
    np.testing.assert_array_equal(np.asarray(f1), np.asarray(f2))


@pytest.mark.quick
def test_convert_cli_rejects_checkpoint_suffix_dst(tmp_path):
    """Suffix inference refuses ambiguity: a non-.msgpack file-like dst
    needs an explicit --format (advisor r02: dotted dir names inferred
    wrong; typo'd extensionless paths silently became directories)."""
    from tests.test_resnet import _torch_oracle

    src = tmp_path / "w.pt"
    torch.save(_torch_oracle("resnet18").state_dict(), src)
    with pytest.raises(SystemExit, match="--format"):
        _run_cli(["--feature_type", "resnet18", str(src), str(tmp_path / "o.npz")])


def test_convert_cli_explicit_format_overrides_inference(tmp_path):
    """--format orbax allows a dotted directory name; --format msgpack
    allows an extensionless file path."""
    from tests.test_resnet import _torch_oracle

    pytest.importorskip("orbax.checkpoint")
    src = tmp_path / "w.pt"
    torch.save(_torch_oracle("resnet18").state_dict(), src)
    dotted_dir = tmp_path / "resnet.v1"
    _run_cli(
        ["--feature_type", "resnet18", "--format", "orbax", str(src), str(dotted_dir)]
    )
    assert dotted_dir.is_dir()
    bare_file = tmp_path / "resnet_msgpack"
    _run_cli(
        ["--feature_type", "resnet18", "--format", "msgpack", str(src), str(bare_file)]
    )
    assert bare_file.is_file()
