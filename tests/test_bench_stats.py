"""bench.py's pure helpers: the {best, median, passes} contract the
round-over-round BENCH artifacts depend on (VERDICT r02 weak #7 asked
for medians + raw passes precisely so deltas can't be flattered)."""

import numpy as np

from bench import _pass_stats, _time_device_only


def test_pass_stats_odd():
    s = _pass_stats(4, [2.0, 1.0, 4.0])  # 2, 4, 1 videos/s
    assert s["best"] == 4.0
    assert s["median"] == 2.0
    assert s["passes"] == [1.0, 2.0, 4.0]  # sorted ascending


def test_pass_stats_even():
    s = _pass_stats(6, [1.0, 2.0, 3.0, 6.0])  # 6, 3, 2, 1 videos/s
    assert s["best"] == 6.0
    assert s["median"] == 2.5  # mean of the middle two
    assert s["passes"] == [1.0, 2.0, 3.0, 6.0]


def test_time_device_only_counts_flops():
    import jax.numpy as jnp

    def step(p, x):
        return x @ p

    p = jnp.asarray(np.eye(16, dtype=np.float32))
    x = jnp.asarray(np.ones((4, 16), dtype=np.float32))
    flops, best = _time_device_only(step, (p, x), 3)
    assert best > 0
    # cost_analysis is best-effort (the helper returns None when the
    # backend reports nothing); when present it must be in the right
    # ballpark of the matmul's 2*M*N*K — not an exact-count pin, which
    # would encode an XLA implementation detail
    if flops is not None:
        assert 0.5 * 2 * 4 * 16 * 16 <= flops <= 4 * 2 * 4 * 16 * 16
