"""bench.py's pure helpers: the {best, median, passes} contract the
round-over-round BENCH artifacts depend on (VERDICT r02 weak #7 asked
for medians + raw passes precisely so deltas can't be flattered)."""

import numpy as np

from bench import _pass_stats, _time_device_only
import pytest


@pytest.mark.quick
def test_pass_stats_odd():
    s = _pass_stats(4, [2.0, 1.0, 4.0])  # 2, 4, 1 videos/s
    assert s["best"] == 4.0
    assert s["median"] == 2.0
    assert s["passes"] == [1.0, 2.0, 4.0]  # sorted ascending


@pytest.mark.quick
def test_pass_stats_even():
    s = _pass_stats(6, [1.0, 2.0, 3.0, 6.0])  # 6, 3, 2, 1 videos/s
    assert s["best"] == 6.0
    assert s["median"] == 2.5  # mean of the middle two
    assert s["passes"] == [1.0, 2.0, 3.0, 6.0]


def test_time_device_only_counts_flops():
    import jax.numpy as jnp

    def step(p, x):
        return x @ p

    p = jnp.asarray(np.eye(16, dtype=np.float32))
    x = jnp.asarray(np.ones((4, 16), dtype=np.float32))
    flops, best = _time_device_only(step, (p, x), 3)
    assert best > 0
    # cost_analysis is best-effort (the helper returns None when the
    # backend reports nothing); when present it must be in the right
    # ballpark of the matmul's 2*M*N*K — not an exact-count pin, which
    # would encode an XLA implementation detail
    if flops is not None:
        assert 0.5 * 2 * 4 * 16 * 16 <= flops <= 4 * 2 * 4 * 16 * 16


def test_device_only_bodies_smoke_on_cpu(monkeypatch):
    """BENCH_FORCE_DEVICE_ONLY=1 runs the FULL bench_clip_device_only /
    bench_i3d_device_only bodies (model build, param cast, scan loop, MFU
    math) on CPU at tiny shapes — so the first on-chip run of the capture
    sequence cannot die on a Python-level bug (VERDICT r03 weak #6)."""
    from bench import bench_clip_device_only, bench_i3d_device_only

    monkeypatch.setenv("BENCH_FORCE_DEVICE_ONLY", "1")

    clip = bench_clip_device_only()
    assert clip["clip_device_only_ips_fp32"] > 0
    assert clip["clip_device_only_ips_bf16"] > 0
    assert clip["clip_device_only_vps_fp32"] > 0
    # forced numbers must be self-labelling so a leaked env var can never
    # pass tiny-shape smoke figures off as chip figures in a BENCH artifact
    assert clip["device_only_forced_smoke"] is True

    i3d = bench_i3d_device_only()
    assert i3d["i3d_raft_device_only_sps"] > 0
    assert i3d["device_only_forced_smoke"] is True


def test_device_only_bodies_gated_off_cpu(monkeypatch):
    """Without the force flag, CPU backends return {} (chip figures must
    come from the chip)."""
    from bench import bench_clip_device_only, bench_i3d_device_only

    monkeypatch.delenv("BENCH_FORCE_DEVICE_ONLY", raising=False)
    assert bench_clip_device_only() == {}
    assert bench_i3d_device_only() == {}


@pytest.mark.quick
def test_spawn_sub_isolates_child_failure():
    """_spawn_sub must survive a dead child and come back with a
    <name>_error string instead of raising — this is the containment that
    keeps one helper crash from erasing the whole BENCH artifact."""
    from bench import _spawn_sub

    out = _spawn_sub("no_such_part", 120)
    assert list(out) == ["no_such_part_error"]
    assert "rc=" in out["no_such_part_error"]


def test_spawn_sub_runs_real_part_on_cpu():
    """End-to-end child run: pallas_corr on the CPU backend returns {}
    (TPU-gated body) via the marker-line protocol, proving the parent can
    parse a healthy child."""
    from bench import _spawn_sub

    assert _spawn_sub("pallas_corr", 300) == {}


def test_host_pipeline_bench_runs_on_cpu():
    """bench_host_pipeline is pure host CPU (no device risk) and must
    always produce decode + preprocess figures so the end-to-end vs
    device-only delta stays attributable even in relay-outage rounds."""
    from bench import bench_host_pipeline

    out = bench_host_pipeline()["host_pipeline"]
    assert out["host_decode_cv2_fps"] > 0
    assert out["host_preprocess_pil_fps"] > 0
    assert any(k.startswith("host_decode_workers_") for k in out)


def test_i3d_short_corpus_wrapper_logic(monkeypatch, tmp_path):
    """bench_i3d_short_corpus's wrapper code (cfg construction, warmup +
    timed passes, shape assertion, stats) must not run for the FIRST time
    during the tunnel window — same de-risking as the device-only smoke.
    The extractor itself is stubbed; its real aggregation math is pinned
    by tests/test_aggregation.py."""
    import numpy as np

    import bench
    import video_features_tpu.models.i3d.extract_i3d as mod

    class StubExtractor:
        def __init__(self, cfg, external_call=False):
            self.cfg = cfg
            self.progress = type("P", (), {"disable": False})()

        def __call__(self, idxs, device=None):
            return [
                {"rgb": np.zeros((1, 1024)), "flow": np.zeros((1, 1024))}
                for _ in idxs
            ]

    monkeypatch.setattr(mod, "ExtractI3D", StubExtractor)
    videos = [str(tmp_path / f"v{i}.mp4") for i in range(4)]
    stats = bench.bench_i3d_short_corpus(videos, str(tmp_path), video_batch=4)
    assert stats["best"] > 0 and len(stats["passes"]) == 2


@pytest.mark.quick
def test_main_emits_incremental_parseable_artifacts(monkeypatch, capsys):
    """The r5 driver contract: main() re-prints a complete-so-far JSON
    line after every part, so the LAST parseable stdout line is always
    the fullest artifact even if the process dies mid-run (r04 lost its
    measured CLIP numbers to exactly that). Parts are stubbed; the
    emission/assembly logic is what's under test."""
    import json

    import bench

    stub_results = {
        "clip_e2e": {"clip_vps": 4.0, "clip_solo_vps": 3.5},
        "clip_bf16": {"clip_bf16_vps": 5.0},
        "clip_mixed": {"clip_mixed_vps": 2.0},
        "clip_device_only": {"clip_device_only_ips_fp32": 100.0},
        "pallas_corr": {},
        "flow_e2e": {"flow_raft_vps": 0.3, "flow_device_pre_raft_vps": 0.4},
        "i3d_compile_probe": {"i3d_conv3d_impl": "direct"},
        "i3d_e2e": {"i3d_raft_vps": 0.2},
        "i3d_agg": {"i3d_agg_vps": 0.5},
        "i3d_device_only": {"i3d_raft_device_only_sps": 0.6},
    }
    # device_preprocess / fault_overhead are the CPU-pinned children run
    # in the host-only section, not top-level parts — stub them apart
    # from stub_results
    cpu_pinned = {
        "device_preprocess": {"device_preprocess_fps": 11.0},
        "fault_overhead": {"fault_bookkeeping_us_per_video": 12.0},
        "analysis_overhead": {"analysis_graftcheck_cold_s": 0.7},
        "preflight_overhead": {"preflight_us_per_video": 14.0},
        "telemetry_overhead": {"telemetry_overhead_us_per_video": 15.0},
        "serve_latency": {"serve_warm_request_s": 0.5},
        "serve_scheduling": {"serve_sched_edf_miss_rate": 0.0},
        "ledger_overhead": {"ledger_overhead_us_per_video": 16.0},
        "ingest_overlap": {"ingest_overlap_efficiency": 0.02},
        "cache_serving": {"cache_hit_speedup": 400.0},
        "serve_preemption": {"serve_preempt_on_miss_rate": 0.0},
    }
    monkeypatch.setattr(
        bench, "_spawn_sub",
        lambda name, timeout, **kw: (dict(cpu_pinned[name])
                                     if name in cpu_pinned
                                     else dict(stub_results[name])))
    monkeypatch.setattr(bench, "bench_host_pipeline",
                        lambda: {"host_pipeline": {"host_decode_cv2_fps": 1.0}})
    monkeypatch.setattr(bench, "_probe_backend",
                        lambda timeout_s=180.0, fatal=True: True)
    monkeypatch.setenv("BENCH_BF16", "1")
    for var in ("BENCH_SKIP_I3D", "BENCH_FLASH", "BENCH_MEASURE_BASELINE"):
        monkeypatch.delenv(var, raising=False)

    bench.main()
    lines = [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")]
    # one artifact line per completed stage, every one parseable
    assert len(lines) >= len(stub_results)
    arts = [json.loads(l) for l in lines]
    final = arts[-1]
    assert final["value"] == 4.0  # headline from the clip_e2e child
    clip_base = bench.MEASURED_BASELINES["clip_torch_cpu_vps"]
    assert final["vs_baseline"] == pytest.approx(4.0 / clip_base, abs=1e-3)
    for part in stub_results.values():
        for key, val in part.items():
            assert final["extra"][key] == val
    assert final["extra"]["host_pipeline"]["device_preprocess_fps"] == 11.0
    assert final["extra"]["fault_bookkeeping_us_per_video"] == 12.0
    assert final["extra"]["analysis_graftcheck_cold_s"] == 0.7
    assert final["extra"]["preflight_us_per_video"] == 14.0
    assert final["extra"]["telemetry_overhead_us_per_video"] == 15.0
    assert final["extra"]["serve_warm_request_s"] == 0.5
    assert final["extra"]["serve_sched_edf_miss_rate"] == 0.0
    assert final["extra"]["ledger_overhead_us_per_video"] == 16.0
    assert final["extra"]["ingest_overlap_efficiency"] == 0.02
    assert final["extra"]["cache_hit_speedup"] == 400.0
    assert final["extra"]["serve_preempt_on_miss_rate"] == 0.0
    i3d_base = bench.MEASURED_BASELINES["i3d_raft_torch_cpu_vps"]
    assert final["extra"]["i3d_raft_vs_torch_cpu"] == pytest.approx(
        0.2 / i3d_base, abs=0.1
    )
    # monotone accumulation: each emission is a superset of the previous
    for prev, nxt in zip(arts, arts[1:]):
        assert set(prev["extra"]) <= set(nxt["extra"])


@pytest.mark.quick
def test_main_dead_backend_still_emits_host_artifact(monkeypatch, capsys):
    """r02-r04 recorded rc=3 and parsed=null when the tunnel was dead;
    since r5 the artifact itself must carry the host numbers plus an
    in-band extra.fatal, with rc 0."""
    import json

    import bench

    monkeypatch.setattr(bench, "bench_host_pipeline",
                        lambda: {"host_pipeline": {"host_decode_cv2_fps": 9.0}})
    monkeypatch.setattr(bench, "_probe_backend",
                        lambda timeout_s=180.0, fatal=True: False)
    monkeypatch.delenv("BENCH_MEASURE_BASELINE", raising=False)

    def boom(name, timeout, **kw):  # no device part may run on a dead backend
        if name == "device_preprocess":  # JAX_PLATFORMS=cpu pinned: tunnel-safe
            return {"device_preprocess_fps": 7.0}
        if name == "fault_overhead":  # likewise CPU-pinned, host-only section
            return {"fault_bookkeeping_us_per_video": 12.0}
        if name == "analysis_overhead":  # pure-AST graftcheck sweep, no device
            return {"analysis_graftcheck_cold_s": 0.7}
        if name == "preflight_overhead":  # probe micro-bench, pure host
            return {"preflight_us_per_video": 14.0}
        if name == "telemetry_overhead":  # span engine micro-bench, CPU-pinned
            return {"telemetry_overhead_us_per_video": 15.0}
        if name == "serve_latency":  # serve admission bench, CPU-pinned
            return {"serve_warm_request_s": 0.5}
        if name == "serve_scheduling":  # pure-host FIFO-vs-EDF simulation
            return {"serve_sched_edf_miss_rate": 0.0}
        if name == "ledger_overhead":  # AOT analysis micro-bench, CPU-pinned
            return {"ledger_overhead_us_per_video": 16.0}
        if name == "ingest_overlap":  # loop-structure bench, CPU-pinned
            return {"ingest_overlap_efficiency": 0.02}
        if name == "cache_serving":  # cache + fan-out bench, CPU-pinned
            return {"cache_hit_speedup": 400.0}
        if name == "serve_preemption":  # fleet A/B + steal drill, pure host
            return {"serve_preempt_on_miss_rate": 0.0}
        raise AssertionError(f"part {name} ran despite dead backend")

    monkeypatch.setattr(bench, "_spawn_sub", boom)
    bench.main()
    lines = [l for l in capsys.readouterr().out.splitlines()
             if l.startswith("{")]
    final = json.loads(lines[-1])
    assert final["value"] is None
    assert "unreachable" in final["extra"]["fatal"]
    assert final["extra"]["host_pipeline"]["host_decode_cv2_fps"] == 9.0
    assert final["extra"]["host_pipeline"]["device_preprocess_fps"] == 7.0


@pytest.mark.quick
def test_i3d_compile_probe_failure_skips_i3d_parts(monkeypatch, capsys):
    """One bad compile must cost the probe's keys, never the run: when
    i3d_compile_probe errors, no i3d part may spawn (each would crash the
    relay again) and the artifact records the skip."""
    import json

    import bench

    ran = []

    def spawn(name, timeout, **kw):
        ran.append(name)
        if name == "i3d_compile_probe":
            return {"i3d_compile_probe_error": "rc=3: helper died"}
        return {}

    monkeypatch.setattr(bench, "_spawn_sub", spawn)
    monkeypatch.setattr(bench, "bench_host_pipeline", lambda: {})
    monkeypatch.setattr(bench, "_probe_backend",
                        lambda timeout_s=180.0, fatal=True: True)
    monkeypatch.setenv("BENCH_BF16", "0")
    for var in ("BENCH_SKIP_I3D", "BENCH_FLASH", "BENCH_MEASURE_BASELINE"):
        monkeypatch.delenv(var, raising=False)
    bench.main()
    assert "i3d_compile_probe" in ran
    assert not any(n in ran for n in ("i3d_e2e", "i3d_agg", "i3d_device_only"))
    final = json.loads(
        [l for l in capsys.readouterr().out.splitlines()
         if l.startswith("{")][-1]
    )
    assert "i3d_skipped" in final["extra"]
