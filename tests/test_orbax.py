"""Orbax sharded checkpoints (SURVEY.md §2 #21, TPU-native upgrade).

Converted param trees can be written as orbax checkpoint directories
(scripts/convert_weights.py with a non-.msgpack dst); --weights_path
accepts them everywhere, and a --sharding mesh CLIP build restores each
weight DIRECTLY onto its destination devices under the Megatron TP
specs — no full-tree host copy, the multi-host-safe load path.
"""

import pathlib
import runpy
import sys

import numpy as np
import torch

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from video_features_tpu.config import ExtractionConfig
from video_features_tpu.models.common.weights import (
    is_orbax_checkpoint,
    load_orbax,
    load_params,
    save_orbax,
)
from video_features_tpu.parallel.sharding import make_mesh
import pytest

SCRIPT = str(
    pathlib.Path(__file__).resolve().parents[1] / "scripts" / "convert_weights.py"
)


def _run_cli(argv):
    old = sys.argv
    sys.argv = ["convert_weights.py"] + argv
    try:
        runpy.run_path(SCRIPT, run_name="__main__")
    finally:
        sys.argv = old


def test_convert_cli_orbax_roundtrip(tmp_path, capsys):
    """torch .pt -> orbax dir via the CLI; load_params reads it back
    leaf-identical to the direct conversion."""
    from tests.test_resnet import _torch_oracle
    from video_features_tpu.models.resnet.convert import convert_state_dict

    oracle = _torch_oracle("resnet18")
    src = tmp_path / "resnet18.pt"
    dst = tmp_path / "resnet18_orbax"
    torch.save(oracle.state_dict(), src)

    _run_cli(["--feature_type", "resnet18", str(src), str(dst)])
    assert is_orbax_checkpoint(str(dst))
    assert "M params" in capsys.readouterr().out

    from_orbax = load_params(str(dst), None)
    from_pt = load_params(str(src), lambda sd: convert_state_dict(sd, "resnet18"))
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        from_orbax,
        from_pt,
    )


@pytest.mark.quick
def test_load_orbax_sharded_restore_places_leaves(tmp_path):
    """Restore-with-mesh places every leaf under the requested specs
    (metadata-driven abstract target, no host tree)."""
    from video_features_tpu.models.clip.model import CLIPVisionConfig, init_params
    from video_features_tpu.parallel.sharding import clip_vit_param_specs

    cfg = CLIPVisionConfig(
        patch_size=16, width=64, layers=2, heads=4, embed_dim=32, image_size=32
    )
    params = init_params(cfg)
    path = str(tmp_path / "clip_ck")
    save_orbax(params, path)
    mesh = make_mesh(jax.devices(), data=4, model=2)
    sharded = load_orbax(path, mesh, clip_vit_param_specs)

    specs = clip_vit_param_specs(params)
    flat_s = jax.tree_util.tree_leaves_with_path(sharded)
    flat_spec = dict(
        (jax.tree_util.keystr(p), s)
        for p, s in jax.tree_util.tree_leaves_with_path(specs, is_leaf=lambda x: isinstance(x, P))
    )
    assert flat_s
    for path_k, leaf in flat_s:
        assert leaf.sharding.spec == flat_spec[jax.tree_util.keystr(path_k)]
    # values survive the sharded restore
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        sharded,
        params,
    )


def test_mesh_clip_with_orbax_weights_matches_msgpack(tmp_path):
    """The product path: --sharding mesh + --weights_path <orbax dir>
    restores sharded and produces the same features as the msgpack host
    load on the same mesh."""
    from flax import serialization
    from video_features_tpu.models.clip.extract_clip import ExtractCLIP
    from video_features_tpu.models.clip.model import init_params
    from video_features_tpu.utils.synth import synth_video

    video = synth_video(str(tmp_path / "v.mp4"))
    params = init_params(ExtractCLIP(
        ExtractionConfig(
            allow_random_init=True, feature_type="CLIP-ViT-B/32",
            video_paths=[video], extract_method="uni_12",
        ),
        external_call=True,
    ).model_cfg)
    mp = tmp_path / "w.msgpack"
    mp.write_bytes(serialization.msgpack_serialize(params))
    ob = tmp_path / "w_orbax"
    save_orbax(params, str(ob))

    def run(wp):
        cfg = ExtractionConfig(
            feature_type="CLIP-ViT-B/32",
            video_paths=[video],
            extract_method="uni_12",
            weights_path=str(wp),
            sharding="mesh",
            mesh_model=2,
        )
        ex = ExtractCLIP(cfg, external_call=True)
        ex.progress.disable = True
        mesh = make_mesh(jax.devices(), model=2)
        return ex([0], device=mesh)[0]["CLIP-ViT-B/32"]

    np.testing.assert_array_equal(run(mp), run(ob))
