"""CLI entry point: ``python main.py --feature_type <X> ...``

Thin shim over :mod:`video_features_tpu.cli` kept at the repo root so the
reference's invocation (ref main.py:94-149) works verbatim; the installed
console script (``video-features-tpu``, pyproject.toml) targets the
package module directly — a top-level module named ``main`` must not land
in site-packages, where it would collide with anyone else's.
"""

import sys

from video_features_tpu.cli import main

if __name__ == "__main__":
    main(sys.argv[1:])
