"""Telemetry consumers: span schema + ``python -m video_features_tpu.telemetry``.

The recording engine lives in :mod:`video_features_tpu.runtime.telemetry`
(it is part of the hot path and belongs with faults.py under runtime/);
this package is the read side — the committed span JSONL schema
(``spans_schema.json``, validated in tests like
``analysis/findings_schema.json``) and the CLI consumers in
``__main__.py``: ``export`` (spans → Chrome-trace/Perfetto JSON) and
``report`` (overlap-efficiency summary). The engine's public names are
re-exported here so consumers can import one module.
"""

from __future__ import annotations

import json
import os

from video_features_tpu.runtime.telemetry import (  # noqa: F401
    DEVICE_STAGES,
    HOST_STAGES,
    STAGES,
    MetricsRegistry,
    SloTracker,
    Telemetry,
    collect,
    overlap_report,
    read_spans,
    request_trace_rows,
    spans_to_chrome_trace,
)
from video_features_tpu.telemetry.exposition import (  # noqa: F401
    families_from_snapshot,
    render_families,
    validate_exposition,
)

SCHEMA_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "spans_schema.json")


def load_schema() -> dict:
    with open(SCHEMA_PATH, "r", encoding="utf-8") as f:
        return json.load(f)
