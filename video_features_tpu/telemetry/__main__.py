"""CLI: ``python -m video_features_tpu.telemetry <export|report> ...``.

Consumers for the span files a run leaves under ``<output>/_telemetry/``:

- ``export SPANS... [-o trace.json]`` — Chrome-trace / Perfetto JSON.
  Arguments are spans-*.jsonl files, a ``_telemetry`` directory, or the
  run's output root (the ``_telemetry`` subdir is found either way).
  Open the result at https://ui.perfetto.dev or chrome://tracing.
- ``report PATH`` — the overlap-efficiency summary (same math that
  lands in ``summary.json``): host-busy vs device-busy vs overlapped
  wall time, per the span intervals.
- ``trace REQUEST_ID PATHS... [-o trace.json]`` — the per-request
  Perfetto trace for ONE serve request: the daemon's lifecycle spans
  (admission / request / queue_wait), the group span linking the
  member ids, and the group's pipeline stages (dispatch / fetch /
  sink, plus the worker-thread decode/prepare spans for the request's
  video) assembled across the daemon's and the resident extractor's
  spans files. See docs/observability.md "Live serve metrics".
- ``ledger PATH [--json]`` — render the device cost ledger
  (telemetry/ledger.py): per-(model, fn family, bucket, sharding)
  flops / bytes-accessed / memory_analysis bytes, plus the per-model
  resident-HBM projection. PATH is the ledger JSON, a ``--compile_cache``
  directory, or a run's output root. See docs/observability.md
  "Device cost ledger".

Exit codes: 0 ok, 2 usage error / no spans found / no ledger at PATH.
No jax import — these run fine on a laptop against files rsynced off a
TPU host.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, List

from video_features_tpu.runtime.telemetry import (
    overlap_report,
    read_spans,
    request_trace_rows,
    spans_to_chrome_trace,
)


def _resolve_span_files(paths: List[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            tdir = p
            if os.path.isdir(os.path.join(p, "_telemetry")):
                tdir = os.path.join(p, "_telemetry")
            out.extend(sorted(glob.glob(os.path.join(tdir, "spans-*.jsonl"))))
        else:
            out.append(p)
    return out


def _resolve_ledger_path(path: str) -> str:
    """PATH may be the ledger file itself, a --compile_cache directory,
    or a run's output root (ledger under ``_telemetry/``)."""
    from video_features_tpu.telemetry.ledger import LEDGER_FILENAME

    if os.path.isdir(path):
        for candidate in (
            os.path.join(path, LEDGER_FILENAME),
            os.path.join(path, "_telemetry", LEDGER_FILENAME),
        ):
            if os.path.isfile(candidate):
                return candidate
        return os.path.join(path, LEDGER_FILENAME)  # for the error message
    return path


def _ledger_main(args: Any) -> int:
    from video_features_tpu.telemetry.ledger import format_bytes, load_ledger

    path = _resolve_ledger_path(args.path)
    ledger = load_ledger(path)
    if ledger is None:
        print(f"telemetry: no ledger at {path}", file=sys.stderr)
        return 2
    snap = ledger.snapshot()
    if args.json:
        print(json.dumps(snap, indent=2, sort_keys=True))
        return 0
    entries = snap["entries"]
    print(f"ledger: {path} ({len(entries)} executable(s))")
    header = (
        f"{'model':<20} {'family':<20} {'bucket':<16} {'sharding':<8} "
        f"{'platform':<8} {'flops':>12} {'moved':>10} {'hbm args':>10} "
        f"{'temp':>10}"
    )
    print(header)
    print("-" * len(header))
    for e in entries:
        mem = e.get("memory", {})
        flops = e.get("flops")
        moved = e.get("bytes_accessed")
        print(
            f"{e.get('model', '~'):<20} {e.get('family', '~'):<20} "
            f"{e.get('bucket', '~'):<16} {e.get('sharding', '~'):<8} "
            f"{e.get('platform', '~'):<8} "
            f"{(f'{flops:.3g}' if flops is not None else '-'):>12} "
            f"{(format_bytes(moved) if moved is not None else '-'):>10} "
            f"{(format_bytes(mem['argument_bytes']) if 'argument_bytes' in mem else '-'):>10} "
            f"{(format_bytes(mem['temp_bytes']) if 'temp_bytes' in mem else '-'):>10}"
        )
    proj = snap["hbm_projection"]
    if proj:
        print("projected resident HBM per model:")
        for model, p in sorted(proj.items()):
            print(
                f"  {model}: {format_bytes(p['resident'])} "
                f"(arguments {format_bytes(p['arguments'])}, outputs "
                f"{format_bytes(p['outputs'])}, temp {format_bytes(p['temp'])}, "
                f"code {format_bytes(p['generated_code'])})"
            )
    else:
        print("projected resident HBM: none (no HBM-platform entries — "
              "CPU-backend runs record flops only)")
    return 0


def main(argv: List[str]) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m video_features_tpu.telemetry",
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_export = sub.add_parser("export", help="spans JSONL -> Chrome-trace JSON")
    p_export.add_argument("paths", nargs="+",
                          help="spans-*.jsonl files, a _telemetry dir, or an output root")
    p_export.add_argument("-o", "--output", default=None,
                          help="trace JSON path (default: stdout)")
    p_export.add_argument("--device-lanes", action="store_true",
                          help="mirror device-stage spans (h2d/dispatch/"
                               "fetch) into one Perfetto lane per device")
    p_report = sub.add_parser("report", help="overlap-efficiency summary")
    p_report.add_argument("paths", nargs="+",
                          help="spans-*.jsonl files, a _telemetry dir, or an output root")
    p_report.add_argument("--json", action="store_true", help="emit the raw report dict")
    p_trace = sub.add_parser(
        "trace", help="one serve request's spans -> Chrome-trace JSON"
    )
    p_trace.add_argument("request_id", help="the request id (lifecycle record id)")
    p_trace.add_argument("paths", nargs="+",
                         help="spans-*.jsonl files, a _telemetry dir, or an output root")
    p_trace.add_argument("-o", "--output", default=None,
                         help="trace JSON path (default: stdout)")
    p_ledger = sub.add_parser(
        "ledger", help="render the device cost ledger (flops/HBM per executable)"
    )
    p_ledger.add_argument(
        "path",
        help="cost_ledger.json, a --compile_cache dir, or an output root",
    )
    p_ledger.add_argument("--json", action="store_true",
                          help="emit the raw ledger snapshot")
    args = parser.parse_args(argv)

    if args.cmd == "ledger":
        return _ledger_main(args)

    files = _resolve_span_files(args.paths)
    rows = []
    for f in files:
        try:
            rows.extend(read_spans(f))
        except OSError as e:
            print(f"telemetry: cannot read {f}: {e}", file=sys.stderr)
            return 2
    if not rows:
        print("telemetry: no spans found", file=sys.stderr)
        return 2

    if args.cmd in ("export", "trace"):
        if args.cmd == "trace":
            rows = request_trace_rows(rows, args.request_id)
            if not rows:
                print(
                    f"telemetry: no spans mention request {args.request_id!r}",
                    file=sys.stderr,
                )
                return 2
        trace = spans_to_chrome_trace(
            rows, device_lanes=getattr(args, "device_lanes", False)
        )
        text = json.dumps(trace)
        if args.output:
            with open(args.output, "w", encoding="utf-8") as f:
                f.write(text)
            print(
                f"telemetry: wrote {len(trace['traceEvents'])} events to "
                f"{args.output} — open at https://ui.perfetto.dev",
                file=sys.stderr,
            )
        else:
            print(text)
        return 0

    rep = overlap_report(rows)
    if args.json:
        print(json.dumps(rep, indent=2))
        return 0
    print(
        f"spans: {rep['spans']} | wall {rep['wall_s']:.2f}s | "
        f"host busy {rep['host_busy_s']:.2f}s | device busy {rep['device_busy_s']:.2f}s"
    )
    print(
        f"overlap: {rep['overlap_s']:.2f}s = {rep['overlap_efficiency']:.1%} of wall, "
        f"{rep['overlap_of_device']:.1%} of device-busy time"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
