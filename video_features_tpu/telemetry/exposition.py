"""Prometheus text exposition (v0.0.4) rendering + a strict checker.

The serve daemon's ``GET /metrics`` endpoint (serve/server.py) renders
the live :class:`~video_features_tpu.runtime.telemetry.MetricsRegistry`
snapshot — counters, gauges, and the log-bucketed stage/service-time
histograms — as Prometheus text exposition, **stdlib only**: the
container bakes no prometheus_client and the format is simple enough
that a renderer plus a validating checker is smaller than the
dependency would be.

Two halves:

- :func:`render_families` / :func:`families_from_snapshot` — the write
  side. Registry names follow the repo's dotted conventions
  (``stage_s.decode``, ``queue_depth.admission``,
  ``group_service_s.<feature_type>|<bucket>``,
  ``requests_<state>``); this module maps them onto properly labelled
  Prometheus families (``vft_stage_seconds{stage="decode"}`` …) so the
  same dashboards hold whatever hardware is behind the daemon (the
  VirtualFlow framing: per-(model, bucket) series, never per-device).
- :func:`validate_exposition` — the read side: a pure-python checker of
  the exposition grammar (metric/label name charsets, label-value
  escaping, HELP/TYPE pairing, counter ``_total`` convention, histogram
  ``_bucket``/``_sum``/``_count`` shape with cumulative ``le`` buckets
  ending at ``+Inf``). The tier-1 test validates the live endpoint's
  bytes through this, so a format regression fails CI instead of a
  scrape.

No jax, no daemon imports: this module is pure data-in/text-out and is
also used by the ``metrics_endpoint_overhead`` bench part.
"""

from __future__ import annotations

import math
import re
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

METRIC_PREFIX = "vft_"

_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_SANITIZE_RE = re.compile(r"[^a-zA-Z0-9_:]")

# the serve-native group service-time histograms are registry-named
# "group_service_s.<feature_type>|<bucket>" — '|' never appears in a
# feature type (slashes do: CLIP-ViT-B/32) or a WxH bucket string
GROUP_SERVICE_SEP = "|"


# Unlabelled registry series with curated HELP text. Every producer-side
# metric name must map to a family here or to one of the labelled
# branches in families_from_snapshot — the sanitized fallback renders a
# name nobody documented, and graftcheck GC701 flags producers that
# would land there (and table entries nothing produces).
_PLAIN_COUNTERS = {
    "frames_decoded": (
        "Video frames decoded across all decode workers (sampled frames "
        "entering the host pipeline, not raw container frames)."
    ),
    "h2d_bytes": (
        "Bytes staged host-to-device through the async ingest "
        "double-buffer (docs/tpu.md)."
    ),
    "videos_done": (
        "Videos fully extracted and committed by the sink (resume-safe "
        "completions, not attempts)."
    ),
    "compiles": (
        "XLA compilations observed by RecompileWatch — growth after "
        "warmup means a shape leaked past bucketing."
    ),
    "retries": (
        "Per-video extraction retries after a retryable worker failure "
        "(--max_retries bounds these per video)."
    ),
    "groups_dispatched": (
        "Fused request groups handed to a device executor by the "
        "serve batcher."
    ),
    "deadline_missed": (
        "Requests that finished after their --deadline_ms budget "
        "(completed late, not dropped)."
    ),
}
_PLAIN_GAUGES = {
    "buckets_seen": (
        "Distinct shape buckets observed this run — the compile-surface "
        "cardinality the bucketing policy is holding."
    ),
    "groups_inflight": (
        "1 while a fused group occupies the device executor, else 0 "
        "(single-executor dispatch; see docs/serving.md)."
    ),
    "queue_age_oldest_s": (
        "Age in seconds of the oldest request waiting in the batcher "
        "queue — the head-of-line latency the scheduler is quoting."
    ),
    "device_mem_headroom_bytes": (
        "HBM budget minus the cost ledger's resident-bytes projection "
        "(what the preemptor spends; negative means overcommit)."
    ),
}


def group_service_metric(feature_type: str, bucket: str) -> str:
    """The registry histogram name for one (feature_type, bucket) group
    service-time series (daemon observes it; /metrics renders it)."""
    return f"group_service_s.{feature_type}{GROUP_SERVICE_SEP}{bucket}"


class Family:
    """One exposition family: a TYPE, a HELP line, and its samples.

    ``type`` is ``counter`` / ``gauge`` / ``histogram``. Counter and
    gauge samples are ``(labels, value)``; histogram samples are
    ``(labels, hist)`` where ``hist`` is the registry snapshot dict
    (``count``/``sum``/``bounds``/``buckets``, buckets non-cumulative
    with one overflow bucket past the last bound)."""

    def __init__(self, name: str, type: str, help: str) -> None:
        assert type in ("counter", "gauge", "histogram"), type
        self.name = name
        self.type = type
        self.help = help
        self.samples: List[Tuple[Dict[str, str], Any]] = []

    def add(self, labels: Optional[Dict[str, str]], value: Any) -> "Family":
        self.samples.append((dict(labels or {}), value))
        return self


def sanitize_metric_name(name: str) -> str:
    out = _SANITIZE_RE.sub("_", name)
    if not out or not _METRIC_NAME_RE.match(out):
        out = "_" + out
    return out


def escape_label_value(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(value: float) -> str:
    v = float(value)
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _labels_text(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{escape_label_value(str(v))}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def render_families(families: Sequence[Family]) -> str:
    """Families -> exposition text (deterministic: families sorted by
    name, labels sorted within a sample). Ends with a newline, as the
    format requires."""
    lines: List[str] = []
    for fam in sorted(families, key=lambda f: f.name):
        lines.append(f"# HELP {fam.name} {fam.help}")
        lines.append(f"# TYPE {fam.name} {fam.type}")
        for labels, value in fam.samples:
            if fam.type == "histogram":
                cum = 0
                for bound, n in zip(value["bounds"], value["buckets"]):
                    cum += int(n)
                    ls = _labels_text({**labels, "le": _fmt(bound)})
                    lines.append(f"{fam.name}_bucket{ls} {cum}")
                ls = _labels_text({**labels, "le": "+Inf"})
                lines.append(f"{fam.name}_bucket{ls} {int(value['count'])}")
                lines.append(f"{fam.name}_sum{_labels_text(labels)} {_fmt(value['sum'])}")
                lines.append(f"{fam.name}_count{_labels_text(labels)} {int(value['count'])}")
            else:
                lines.append(f"{fam.name}{_labels_text(labels)} {_fmt(value)}")
    return "\n".join(lines) + "\n"


# -- registry snapshot -> families ---------------------------------------


def families_from_snapshot(snap: Dict[str, Any]) -> List[Family]:
    """Map a MetricsRegistry snapshot onto labelled families using the
    registry's dotted naming conventions. Unrecognized names degrade to
    a sanitized unlabelled series rather than being dropped: /metrics
    must never silently hide a counter someone added."""
    fams: Dict[str, Family] = {}

    def fam(name: str, type: str, help: str) -> Family:
        f = fams.get(name)
        if f is None:
            f = fams[name] = Family(name, type, help)
        return f

    for name, value in sorted(snap.get("counters", {}).items()):
        # "requests_shed.<reason>" must be matched BEFORE the generic
        # "requests_" prefix below (it IS a requests_ name)
        if name.startswith("requests_shed."):
            fam(
                f"{METRIC_PREFIX}requests_total", "counter",
                "Serve requests reaching each lifecycle state (terminal "
                "states plus admitted/deferred/requeued).",
            ).add(
                {"state": "shed", "shed_reason": name[len("requests_shed."):]},
                value,
            )
        elif name.startswith("requests_"):
            fam(
                f"{METRIC_PREFIX}requests_total", "counter",
                "Serve requests reaching each lifecycle state (terminal "
                "states plus admitted/deferred/requeued).",
            ).add({"state": name[len("requests_"):]}, value)
        elif name.startswith("preemptions."):
            fam(
                f"{METRIC_PREFIX}preemptions_total", "counter",
                "HBM-aware preemptions per evicted feature type (the "
                "victim's extractor was torn down to fit an "
                "overcommitting burst; see docs/serving.md \"Fleet "
                "operation\").",
            ).add({"feature_type": name[len("preemptions."):]}, value)
        elif name.startswith("lease_steals."):
            fam(
                f"{METRIC_PREFIX}lease_steals_total", "counter",
                "Spool lease files stolen from dead/stalled replicas, "
                "per feature type of the reclaimed request.",
            ).add({"feature_type": name[len("lease_steals."):]}, value)
        elif name == "lease_expired":
            fam(
                f"{METRIC_PREFIX}lease_expired_total", "counter",
                "Spool leases that aged past --lease_timeout_s without a "
                "heartbeat and were reclaimed by a surviving replica.",
            ).add(None, value)
        elif name == "windows_skipped":
            fam(
                f"{METRIC_PREFIX}windows_skipped_total", "counter",
                "Near-duplicate sampled frames skipped before H2D by "
                "--frame_delta_threshold (features filled by "
                "copy-forward; see docs/tpu.md).",
            ).add(None, value)
        elif name.startswith("cache_hit."):
            fam(
                f"{METRIC_PREFIX}cache_hit_total", "counter",
                "Content-addressed feature cache hits per feature type "
                "(request served from the store without decode or "
                "dispatch; see docs/serving.md).",
            ).add({"feature_type": name[len("cache_hit."):]}, value)
        elif name.startswith("cache_miss."):
            fam(
                f"{METRIC_PREFIX}cache_miss_total", "counter",
                "Content-addressed feature cache misses per feature type "
                "(extraction ran and populated the store).",
            ).add({"feature_type": name[len("cache_miss."):]}, value)
        elif name in _PLAIN_COUNTERS:
            fam(
                f"{METRIC_PREFIX}{name}_total", "counter",
                _PLAIN_COUNTERS[name],
            ).add(None, value)
        else:
            fam(
                f"{METRIC_PREFIX}{sanitize_metric_name(name)}_total", "counter",
                f"Registry counter {name!r}.",
            ).add(None, value)
    for name, value in sorted(snap.get("gauges", {}).items()):
        if name.startswith("queue_depth."):
            fam(
                f"{METRIC_PREFIX}queue_depth", "gauge",
                "Live queue depths by queue name (admission = requests "
                "admitted but not yet terminal; inflight = dispatched "
                "device groups not yet fetched; prepared = host-resident "
                "payloads waiting to dispatch; the backpressure bounds).",
            ).add({"queue": name[len("queue_depth."):]}, value)
        elif name.startswith("replica_up."):
            fam(
                f"{METRIC_PREFIX}replica_up", "gauge",
                "Fleet membership: 1 when the replica's heartbeat file "
                "is fresher than --lease_timeout_s, else 0 (survivors "
                "reclaim a down replica's leases and requests).",
            ).add({"replica": name[len("replica_up."):]}, value)
        elif name.startswith("device_mem_bytes."):
            # DeviceMemorySampler gauges: "device_mem_bytes.<device>|<kind>"
            # (absent entirely on backends without device.memory_stats())
            dev, _, kind = name[len("device_mem_bytes."):].partition(
                GROUP_SERVICE_SEP
            )
            fam(
                f"{METRIC_PREFIX}device_mem_bytes", "gauge",
                "Live device memory by device and kind (in_use/limit/"
                "peak/reserved), polled from device.memory_stats(); "
                "absent on backends without the API.",
            ).add({"device": dev, "kind": kind or "~"}, value)
        elif name in _PLAIN_GAUGES:
            fam(
                f"{METRIC_PREFIX}{name}", "gauge",
                _PLAIN_GAUGES[name],
            ).add(None, value)
        else:
            fam(
                f"{METRIC_PREFIX}{sanitize_metric_name(name)}", "gauge",
                f"Registry gauge {name!r}.",
            ).add(None, value)
    for name, hist in sorted(snap.get("histograms", {}).items()):
        if name.startswith("stage_s."):
            fam(
                f"{METRIC_PREFIX}stage_seconds", "histogram",
                "Per-stage latency (seconds) over the pipeline's own "
                "stage names (docs/observability.md).",
            ).add({"stage": name[len("stage_s."):]}, hist)
        elif name.startswith("group_service_s."):
            ft, _, bucket = name[len("group_service_s."):].partition(GROUP_SERVICE_SEP)
            fam(
                f"{METRIC_PREFIX}group_service_seconds", "histogram",
                "Fused-group service time (seconds) per (feature_type, "
                "bucket) — the series the edf-cost scheduler's "
                "ServiceTimeModel is calibrated from.",
            ).add({"feature_type": ft, "bucket": bucket or "~"}, hist)
        else:
            fam(
                f"{METRIC_PREFIX}{sanitize_metric_name(name)}", "histogram",
                f"Registry histogram {name!r}.",
            ).add(None, hist)
    return list(fams.values())


# -- ledger snapshot -> families -----------------------------------------


def families_from_ledger(snapshot: Dict[str, Any]) -> List[Family]:
    """Exposition families from a CostLedger snapshot
    (telemetry/ledger.py): per-executable flops / bytes-accessed for
    every entry (present on any backend — CPU included, the
    cost_analysis API is portable), and the per-model resident-HBM
    projection ``vft_hbm_bytes{model,kind}`` — which only exists for
    entries built on an HBM platform, so a CPU daemon's /metrics
    legitimately has no ``vft_hbm_*`` series."""
    fams: List[Family] = []
    f_flops = Family(
        f"{METRIC_PREFIX}executable_flops", "gauge",
        "Flops per built executable (cost_analysis), keyed by model, "
        "fn family, spatial bucket, and sharding mode.",
    )
    f_moved = Family(
        f"{METRIC_PREFIX}executable_bytes_accessed", "gauge",
        "Bytes accessed per built executable (cost_analysis).",
    )
    for e in snapshot.get("entries", []):
        labels = {
            "model": str(e.get("model", "~")),
            "family": str(e.get("family", "~")),
            "bucket": str(e.get("bucket", "~")),
            "sharding": str(e.get("sharding", "~")),
        }
        if "flops" in e:
            f_flops.add(labels, e["flops"])
        if "bytes_accessed" in e:
            f_moved.add(labels, e["bytes_accessed"])
    if f_flops.samples:
        fams.append(f_flops)
    if f_moved.samples:
        fams.append(f_moved)
    f_hbm = Family(
        f"{METRIC_PREFIX}hbm_bytes", "gauge",
        "Projected resident HBM bytes per model and kind (arguments/"
        "outputs/temp/generated_code/resident), from memory_analysis "
        "of each built executable; absent on CPU backends.",
    )
    for model, proj in sorted(snapshot.get("hbm_projection", {}).items()):
        for kind, v in sorted(proj.items()):
            f_hbm.add({"model": model, "kind": kind}, v)
    if f_hbm.samples:
        fams.append(f_hbm)
    return fams


# -- the checker ---------------------------------------------------------


def _parse_labels(text: str) -> Tuple[Optional[Dict[str, str]], Optional[str]]:
    """Parse the ``{...}`` label block body (no braces). Returns
    (labels, None) or (None, error)."""
    labels: Dict[str, str] = {}
    i, n = 0, len(text)
    while i < n:
        j = i
        while j < n and text[j] not in "=,{}\"":
            j += 1
        name = text[i:j]
        if not _LABEL_NAME_RE.match(name):
            return None, f"bad label name {name!r}"
        if j >= n or text[j] != "=":
            return None, f"expected '=' after label {name!r}"
        j += 1
        if j >= n or text[j] != '"':
            return None, f"label {name!r} value is not quoted"
        j += 1
        buf: List[str] = []
        while j < n and text[j] != '"':
            c = text[j]
            if c == "\\":
                if j + 1 >= n:
                    return None, f"dangling escape in label {name!r}"
                esc = text[j + 1]
                if esc not in ('\\', '"', 'n'):
                    return None, f"bad escape '\\{esc}' in label {name!r}"
                buf.append({"\\": "\\", '"': '"', "n": "\n"}[esc])
                j += 2
            else:
                if c == "\n":
                    return None, f"raw newline in label {name!r}"
                buf.append(c)
                j += 1
        if j >= n:
            return None, f"unterminated value for label {name!r}"
        if name in labels:
            return None, f"duplicate label {name!r}"
        labels[name] = "".join(buf)
        j += 1  # closing quote
        if j < n:
            if text[j] != ",":
                return None, f"expected ',' after label {name!r}"
            j += 1
        i = j
    return labels, None


def validate_exposition(text: str) -> List[str]:
    """Check ``text`` against the Prometheus text-exposition grammar
    plus this repo's conventions. Returns a list of human-readable
    errors — empty means valid. Enforced rules:

    - every line is a ``# HELP``/``# TYPE`` comment or a sample;
      the document ends with a newline;
    - metric names match ``[a-zA-Z_:][a-zA-Z0-9_:]*``, label names
      match ``[a-zA-Z_][a-zA-Z0-9_]*``, label values are quoted with
      only ``\\\\``/``\\"``/``\\n`` escapes, values parse as floats;
    - HELP/TYPE pairing: each family has exactly one of each, TYPE
      before any of its samples, and no sample lacks a TYPE;
    - counters are named ``*_total``; histogram families expose
      ``_bucket`` (with ``le``, cumulative, ending at ``+Inf``),
      ``_sum`` and ``_count`` (equal to the ``+Inf`` bucket) per
      label set, and nothing else.
    """
    errors: List[str] = []
    if not text:
        return ["empty exposition"]
    if not text.endswith("\n"):
        errors.append("exposition must end with a newline")
    types: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    sampled_before_type: set = set()
    # family -> base-labels-key -> {"buckets": [(le, v)], "sum": v, "count": v}
    hists: Dict[str, Dict[Tuple, Dict[str, Any]]] = {}
    sample_names: set = set()

    def base_of(name: str) -> str:
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix):
                base = name[: -len(suffix)]
                if types.get(base) == "histogram":
                    return base
        return name

    for ln, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 2 and parts[1] in ("HELP", "TYPE"):
                if len(parts) < 3:
                    errors.append(f"line {ln}: # {parts[1]} without a metric name")
                    continue
                name = parts[2]
                if not _METRIC_NAME_RE.match(name):
                    errors.append(f"line {ln}: bad metric name {name!r} in {parts[1]}")
                    continue
                if parts[1] == "HELP":
                    if name in helps:
                        errors.append(f"line {ln}: duplicate HELP for {name}")
                    helps[name] = parts[3] if len(parts) > 3 else ""
                else:
                    kind = parts[3].strip() if len(parts) > 3 else ""
                    if kind not in ("counter", "gauge", "histogram", "summary", "untyped"):
                        errors.append(f"line {ln}: bad TYPE {kind!r} for {name}")
                        continue
                    if name in types:
                        errors.append(f"line {ln}: duplicate TYPE for {name}")
                    if name in sampled_before_type:
                        errors.append(f"line {ln}: TYPE for {name} appears after its samples")
                    types[name] = kind
                    if kind == "counter" and not name.endswith("_total"):
                        errors.append(f"line {ln}: counter {name} must end in _total")
            continue
        # sample line: name[{labels}] value [timestamp]
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)", line)
        if not m:
            errors.append(f"line {ln}: bad sample line {line!r}")
            continue
        name = m.group(1)
        rest = line[m.end():]
        labels: Dict[str, str] = {}
        if rest.startswith("{"):
            close = rest.rfind("}")
            if close < 0:
                errors.append(f"line {ln}: unterminated label block")
                continue
            parsed, err = _parse_labels(rest[1:close])
            if err:
                errors.append(f"line {ln}: {err}")
                continue
            labels = parsed or {}
            rest = rest[close + 1:]
        fields = rest.split()
        if len(fields) not in (1, 2):
            errors.append(f"line {ln}: expected '<value> [timestamp]', got {rest!r}")
            continue
        try:
            value = float(fields[0])
        except ValueError:
            errors.append(f"line {ln}: bad sample value {fields[0]!r}")
            continue
        if len(fields) == 2:
            try:
                int(fields[1])
            except ValueError:
                errors.append(f"line {ln}: bad timestamp {fields[1]!r}")
        base = base_of(name)
        sample_names.add(base)
        if base not in types:
            sampled_before_type.add(base)
        kind = types.get(base)
        if kind == "histogram":
            key = tuple(sorted((k, v) for k, v in labels.items() if k != "le"))
            slot = hists.setdefault(base, {}).setdefault(
                key, {"buckets": [], "sum": None, "count": None}
            )
            if name.endswith("_bucket"):
                le = labels.get("le")
                if le is None:
                    errors.append(f"line {ln}: histogram bucket for {base} lacks 'le'")
                else:
                    slot["buckets"].append((le, value))
            elif name.endswith("_sum"):
                slot["sum"] = value
            elif name.endswith("_count"):
                slot["count"] = value
            else:
                errors.append(
                    f"line {ln}: sample {name} of histogram {base} is not "
                    "_bucket/_sum/_count"
                )
        elif "le" in labels:
            errors.append(f"line {ln}: 'le' label on non-histogram sample {name}")

    for name in sample_names:
        if name not in types:
            errors.append(f"sampled metric {name} has no # TYPE line")
        if name not in helps:
            errors.append(f"sampled metric {name} has no # HELP line")
    for name in types:
        if name not in helps:
            errors.append(f"# TYPE {name} has no matching # HELP")
    for name in helps:
        if name not in types:
            errors.append(f"# HELP {name} has no matching # TYPE")

    def _le_key(le: str) -> float:
        return float("inf") if le == "+Inf" else float(le)

    for base, series in hists.items():
        for key, slot in series.items():
            where = f"{base}{dict(key) if key else ''}"
            les = [le for le, _ in slot["buckets"]]
            if "+Inf" not in les:
                errors.append(f"{where}: no le=\"+Inf\" bucket")
                continue
            try:
                ordered = sorted(slot["buckets"], key=lambda p: _le_key(p[0]))
            except ValueError:
                errors.append(f"{where}: unparsable le bound")
                continue
            vals = [v for _, v in ordered]
            if any(b > a for a, b in zip(vals[1:], vals)):
                errors.append(f"{where}: bucket counts are not cumulative")
            if slot["count"] is None or slot["sum"] is None:
                errors.append(f"{where}: missing _count or _sum")
            elif vals and slot["count"] != vals[-1]:
                errors.append(
                    f"{where}: _count {slot['count']} != +Inf bucket {vals[-1]}"
                )
    return errors


# the name the tests and docs use for the read side; same contract as
# validate_exposition (returns the error list, empty == valid)
check_exposition = validate_exposition
