"""Device cost ledger: what each built executable *costs*.

PR 12's cost model (serve/costmodel.py) learns wall-clock service time;
nothing measured what an executable costs in device terms — HBM resident
bytes, flops, bytes moved — so the ROADMAP's HBM-aware preemption /
placement items would be guessing. This module records those facts at
the only seam that has them: the compiled executable itself, via the
portable JAX AOT APIs (``jitted.lower(*args).compile()`` →
``memory_analysis()`` / ``cost_analysis()``) — the VirtualFlow framing:
no TPU-only tooling, the same accounting on any backend.

Three pieces:

- :class:`CostLedger` — the persistent ledger: one entry per
  (model, fn family, spatial bucket, sharding mode), carrying flops /
  bytes-accessed (``cost_analysis``) and the argument / output / temp /
  generated-code byte sizes (``memory_analysis``) plus the platform the
  executable was built for. Persistence mirrors
  serve/costmodel.py::ServiceTimeModel: atomic ``os.replace`` rewrite
  next to ``--compile_cache`` (the other warm-start artifact), torn /
  missing files load silently as empty, snapshot under the lock but
  file I/O outside it (GC312). :meth:`CostLedger.shared` hands every
  component of one process (daemon + pooled extractors) the same
  instance per path, so /metrics and the warmup budget see one ledger.
- :func:`instrument_state` — the capture seam. ``BaseExtractor.warmup``
  wraps the built state dict's jitted callables; the first call per
  (family, argument signature) runs a one-time AOT
  ``lower().compile()`` purely for analysis (execution stays on the
  proven jit path), under
  :func:`~video_features_tpu.runtime.telemetry.suppress_compile_watch`
  so the analysis compile is never double-counted by RecompileWatch.
- :class:`DeviceMemorySampler` — live gauges: a thread polling
  ``device.memory_stats()`` into the MetricsRegistry
  (``device_mem_bytes.<device>|<kind>``, rendered as
  ``vft_device_mem_bytes{device,kind}``). Backends without the API
  (CPU, old jax) degrade to **absent** gauges — never zero-filled.

HBM semantics: ``memory_analysis`` figures are recorded wherever the
API answers (they are honest host-byte sizes on CPU too), but the
``vft_hbm_bytes{model,kind}`` projection and the warmup
``--hbm_budget_bytes`` gate only count entries whose platform has HBM
(anything except ``cpu``) — on a CPU backend the HBM families are
legitimately absent.

No jax at module scope (the ``python -m video_features_tpu.telemetry``
CLI renders ledgers on laptops); jax is imported lazily inside the
capture/sampling paths, which only run where jax already runs.
"""

from __future__ import annotations

import functools
import json
import os
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

LEDGER_FILENAME = "cost_ledger.json"
SCHEMA_VERSION = 1

# entry-key separator; shared with the exposition conventions ('|' never
# appears in a feature type, fn family, WxH/shape bucket, or sharding mode)
KEY_SEP = "|"

# state-dict slots that are not jitted callables (extract/*/_build)
_NON_CALLABLE_KEYS = frozenset({"params", "device", "mesh"})

# memory_analysis attribute -> ledger field (absent attributes and
# failing calls leave the field out entirely — never zero-filled)
_MEMORY_FIELDS = (
    ("argument_size_in_bytes", "argument_bytes"),
    ("output_size_in_bytes", "output_bytes"),
    ("temp_size_in_bytes", "temp_bytes"),
    ("generated_code_size_in_bytes", "generated_code_bytes"),
)

# device.memory_stats() key -> gauge kind label
_MEMSTAT_KINDS = (
    ("bytes_in_use", "in_use"),
    ("bytes_limit", "limit"),
    ("peak_bytes_in_use", "peak"),
    ("bytes_reserved", "reserved"),
)


def default_ledger_path(cfg: Any) -> str:
    """Where the ledger persists: next to the compile cache when one is
    configured (the executables it describes live there), else under the
    run's ``_telemetry`` directory — the same rule as the service-time
    model (serve/costmodel.py::default_model_path)."""
    cache = getattr(cfg, "compile_cache", None)
    if cache:
        return os.path.join(cache, LEDGER_FILENAME)
    return os.path.join(cfg.output_path, "_telemetry", LEDGER_FILENAME)


def entry_key(model: str, family: str, bucket: str, sharding: str) -> str:
    return KEY_SEP.join((model, family, bucket, sharding))


def analyze_compiled(compiled: Any) -> Dict[str, Any]:
    """Portable cost/memory facts from one ``jax.stages.Compiled``.

    Returns any of ``flops`` / ``bytes_accessed`` (cost_analysis) and a
    ``memory`` sub-dict (memory_analysis); each piece is omitted when
    the backend does not answer (old jax, exotic runtimes) — the
    graceful-degradation contract is *absent*, never zero."""
    out: Dict[str, Any] = {}
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            flops = ca.get("flops")
            if flops is not None and float(flops) >= 0:
                out["flops"] = float(flops)
            moved = ca.get("bytes accessed")
            if moved is not None and float(moved) >= 0:
                out["bytes_accessed"] = float(moved)
    except Exception:  # noqa: BLE001 - observability must never kill the run
        pass
    try:
        ma = compiled.memory_analysis()
        mem: Dict[str, int] = {}
        for attr, field in _MEMORY_FIELDS:
            v = getattr(ma, attr, None)
            if v is not None and int(v) >= 0:
                mem[field] = int(v)
        if mem:
            out["memory"] = mem
    except Exception:  # noqa: BLE001 - graceful degradation: no memory block
        pass
    return out


class CostLedger:
    """Per-executable cost facts keyed by (model, family, bucket,
    sharding), persisted like the service-time model. Thread-safe: the
    capture path records from extractor build/dispatch threads while
    /metrics snapshots from HTTP handler threads; no I/O under the
    lock (GC312)."""

    _SHARED_LOCK = threading.Lock()
    _SHARED: Dict[str, "CostLedger"] = {}

    def __init__(self, path: Optional[str] = None, save_every: int = 1) -> None:
        # save_every=1: captures happen once per (family, signature) —
        # a handful per run — so every record can afford its atomic
        # rewrite, and a short run (or a crash) never loses the ledger.
        self.path = path
        self.save_every = max(int(save_every), 1)
        self._lock = threading.Lock()
        self._entries: Dict[str, Dict[str, Any]] = {}
        self._dirty = 0
        if path is not None:
            self._load(path)

    @classmethod
    def shared(cls, path: str) -> "CostLedger":
        """The process-shared instance for ``path`` (normalized): the
        daemon and every pooled extractor must append to ONE ledger so
        the /metrics projection and the warmup budget agree."""
        key = os.path.abspath(path)
        with cls._SHARED_LOCK:
            led = cls._SHARED.get(key)
            if led is None:
                led = cls._SHARED[key] = cls(key)
            return led

    # -- the write side (extractor build / first-dispatch threads) -------

    def record(
        self,
        model: str,
        family: str,
        bucket: str,
        sharding: str,
        platform: Optional[str],
        analysis: Dict[str, Any],
    ) -> None:
        """Fold one executable's analysis in. Re-records of the same key
        (a rebuilt extractor, a daemon restart against the same compile
        cache) overwrite the facts and bump ``n_compiles``."""
        entry: Dict[str, Any] = {
            "model": model,
            "family": family,
            "bucket": bucket,
            "sharding": sharding,
        }
        if platform:
            entry["platform"] = str(platform)
        for k in ("flops", "bytes_accessed", "memory"):
            if k in analysis:
                entry[k] = analysis[k]
        key = entry_key(model, family, bucket, sharding)
        save_now = False
        with self._lock:
            prev = self._entries.get(key)
            entry["n_compiles"] = (prev.get("n_compiles", 0) if prev else 0) + 1
            self._entries[key] = entry
            self._dirty += 1
            if self.path is not None and self._dirty >= self.save_every:
                self._dirty = 0
                save_now = True
        if save_now:
            self.save()

    # -- the read side (/metrics, /v1/stats, warmup, CLI) ----------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def entries(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for _, e in sorted(self._entries.items())]

    def snapshot(self) -> Dict[str, Any]:
        """The /v1/stats ``ledger`` block: the entries plus the
        per-model HBM projection."""
        return {
            "version": SCHEMA_VERSION,
            "path": self.path,
            "entries": self.entries(),
            "hbm_projection": self.hbm_projection(),
        }

    def hbm_projection(self) -> Dict[str, Dict[str, int]]:
        """Per-model projected resident-HBM bytes, from entries built
        for a platform that *has* HBM (anything except cpu; entries
        with no platform or no memory block are skipped — CPU runs
        project nothing, by design).

        The projection is a deliberate approximation, documented in
        docs/observability.md: arguments (weights + the largest input
        batch) / outputs / temp are MAXed across a model's entries —
        the weights dominate ``argument_bytes`` and are shared by every
        bucket variant, so summing would multiply the model by its
        bucket count — while generated code is SUMMED (each executable's
        program stays resident). ``resident`` is their total: the
        peak-executable footprint with every bucket variant loaded."""
        out: Dict[str, Dict[str, int]] = {}
        for e in self.entries():
            platform = e.get("platform")
            mem = e.get("memory")
            if not mem or not platform or platform == "cpu":
                continue
            proj = out.setdefault(e["model"], {
                "arguments": 0, "outputs": 0, "temp": 0, "generated_code": 0,
            })
            proj["arguments"] = max(proj["arguments"], mem.get("argument_bytes", 0))
            proj["outputs"] = max(proj["outputs"], mem.get("output_bytes", 0))
            proj["temp"] = max(proj["temp"], mem.get("temp_bytes", 0))
            proj["generated_code"] += mem.get("generated_code_bytes", 0)
        for proj in out.values():
            proj["resident"] = (
                proj["arguments"] + proj["outputs"]
                + proj["temp"] + proj["generated_code"]
            )
        return out

    def projected_resident_bytes(self, models: Optional[Sequence[str]] = None) -> int:
        """Total projected resident set across ``models`` (default: every
        model in the ledger) — the number the serve warmup checks
        against ``--hbm_budget_bytes``. 0 on CPU backends (no HBM
        entries), so the budget gate is trivially satisfied there."""
        proj = self.hbm_projection()
        if models is not None:
            proj = {m: p for m, p in proj.items() if m in models}
        return sum(p["resident"] for p in proj.values())

    # -- persistence (the costmodel pattern) -----------------------------

    def save(self, path: Optional[str] = None) -> Optional[str]:
        """Atomic rewrite: snapshot under the lock, write outside it."""
        path = path or self.path
        if path is None:
            return None
        with self._lock:
            doc = {"version": SCHEMA_VERSION, "entries": dict(self._entries)}
        from video_features_tpu.io.sink import atomic_write_json

        return atomic_write_json(path, doc)

    def _load(self, path: str) -> None:
        try:
            with open(path, "r", encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return  # no/torn prior ledger: start cold
        if not isinstance(doc, dict) or doc.get("version") != SCHEMA_VERSION:
            return
        entries = doc.get("entries")
        if not isinstance(entries, dict):
            return
        with self._lock:
            for key, e in entries.items():
                if isinstance(e, dict) and "model" in e and "family" in e:
                    self._entries[str(key)] = e


def load_ledger(path: str) -> Optional[CostLedger]:
    """Read-side open for the CLI: None when the file is missing (the
    rc-2 contract lives in telemetry/__main__.py); a torn file loads
    as an empty ledger, like every other warm-start artifact."""
    if not os.path.isfile(path):
        return None
    return CostLedger(path)


# -- the capture seam -----------------------------------------------------


def _array_leaves(tree: Any) -> List[Any]:
    """Array-ish leaves of a nested args structure, pure python (no jax
    import: shapes are all the signature needs)."""
    out: List[Any] = []
    stack = [tree]
    while stack:
        node = stack.pop()
        if isinstance(node, dict):
            stack.extend(node.values())
        elif isinstance(node, (list, tuple)):
            stack.extend(node)
        elif hasattr(node, "shape") and hasattr(node, "dtype"):
            out.append(node)
    return out


def _signature(args: tuple, kwargs: dict) -> tuple:
    return tuple(
        (tuple(leaf.shape), str(leaf.dtype))
        for leaf in _array_leaves((args, kwargs))
    )


def bucket_of(args: tuple, kwargs: dict = {}) -> str:  # noqa: B006 - read-only default
    """The ledger's spatial-bucket string for one call: the shape of the
    largest data leaf, ``"24x240x448x3"``-style. Model params (a leading
    mapping arg, the ``fn(params, x)`` convention) are excluded so the
    bucket tracks the *input*, not the weights; ``"~"`` when no data
    leaf exists (nullary warms)."""
    data_args = args[1:] if args and isinstance(args[0], dict) else args
    leaves = _array_leaves((data_args, kwargs))
    if not leaves:
        return "~"
    best = max(leaves, key=lambda a: (len(a.shape), _leaf_size(a)))
    return "x".join(str(int(d)) for d in best.shape) or "scalar"


def _leaf_size(a: Any) -> int:
    n = 1
    for d in a.shape:
        n *= int(d)
    return n


def _platform_name(device: Any) -> Optional[str]:
    p = getattr(device, "platform", None)
    if p:
        return str(p)
    try:
        import jax

        return str(jax.default_backend())
    except Exception:  # noqa: BLE001 - no backend, no platform tag
        return None


def instrument_state(
    state: Any,
    ledger: CostLedger,
    model: str,
    sharding: str = "queue",
    device: Any = None,
) -> Any:
    """Wrap an extractor's built state dict so every jitted callable's
    first call per argument signature captures its executable's
    cost/memory analysis into ``ledger``.

    The fn family is the state-dict key (``forward`` / ``encode_image``
    / ``forward_raw_group`` …, the GC401 budget vocabulary). Execution
    is untouched — the wrapper forwards to the original jitted fn; the
    analysis runs a one-time AOT ``lower().compile()`` on the side,
    inside :func:`~video_features_tpu.runtime.telemetry.
    suppress_compile_watch` so RecompileWatch (and its manifest
    warnings) never count it. Any analysis failure is swallowed: the
    ledger is observability, the dispatch must win every race with it.

    Non-dict states and non-jit values pass through unchanged."""
    if not isinstance(state, dict):
        return state
    platform = _platform_name(device if device is not None else state.get("device"))
    out = dict(state)
    for family, fn in state.items():
        if family in _NON_CALLABLE_KEYS or not callable(fn):
            continue
        if not hasattr(fn, "lower"):  # jit-wrapped callables only
            continue
        out[family] = _wrap_callable(fn, ledger, model, family, sharding, platform)
    return out


def _wrap_callable(
    fn: Callable,
    ledger: CostLedger,
    model: str,
    family: str,
    sharding: str,
    platform: Optional[str],
) -> Callable:
    seen: set = set()
    lock = threading.Lock()

    @functools.wraps(fn)
    def wrapped(*args: Any, **kwargs: Any) -> Any:
        try:
            sig = _signature(args, kwargs)
            with lock:
                first = sig not in seen
                if first:
                    seen.add(sig)
        except Exception:  # noqa: BLE001 - signature failure: skip capture
            first = False
        if first:
            # analysis OUTSIDE the lock (GC312: a compile is blocking
            # I/O as far as any other thread's dispatch is concerned)
            _capture(fn, args, kwargs, ledger, model, family, sharding, platform)
        return fn(*args, **kwargs)

    wrapped.__wrapped_for_ledger__ = fn  # type: ignore[attr-defined]
    return wrapped


def _capture(
    fn: Callable,
    args: tuple,
    kwargs: dict,
    ledger: CostLedger,
    model: str,
    family: str,
    sharding: str,
    platform: Optional[str],
) -> None:
    from video_features_tpu.runtime.telemetry import suppress_compile_watch

    try:
        with suppress_compile_watch():
            compiled = fn.lower(*args, **kwargs).compile()
        analysis = analyze_compiled(compiled)
    except Exception:  # noqa: BLE001 - observability must never kill dispatch
        return
    if not analysis:
        return  # backend answered nothing: omit the entry, don't zero-fill
    ledger.record(
        model, family, bucket_of(args, kwargs), sharding, platform, analysis
    )


# -- live device-memory gauges -------------------------------------------


class DeviceMemorySampler:
    """Polls ``device.memory_stats()`` into a MetricsRegistry as
    ``device_mem_bytes.<device>|<kind>`` gauges plus a cross-device
    ``device_mem_headroom_bytes`` minimum (limit - in_use), for
    /metrics and the serve heartbeat.

    Backends whose devices lack the API or return None (CPU) set **no**
    gauges — the exposition simply has no ``vft_device_mem_*`` families
    there, per the degradation contract. ``sample_once()`` is public so
    tests and the warmup path can poll synchronously; ``start``/``stop``
    run it on a daemon thread."""

    def __init__(
        self,
        metrics: Any,
        interval_s: float = 10.0,
        devices: Optional[Sequence[Any]] = None,
    ) -> None:
        self.metrics = metrics
        self.interval_s = max(float(interval_s), 0.5)
        self._devices = list(devices) if devices is not None else None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _resolve_devices(self) -> List[Any]:
        if self._devices is not None:
            return self._devices
        try:
            import jax

            return list(jax.local_devices())
        except Exception:  # noqa: BLE001 - no jax/backend: nothing to sample
            return []

    def sample_once(self) -> int:
        """One poll; returns the number of per-device stat sets
        recorded (0 on backends without the API)."""
        recorded = 0
        headroom: Optional[int] = None
        for dev in self._resolve_devices():
            try:
                stats = dev.memory_stats()
            except Exception:  # noqa: BLE001 - API absent on this backend
                stats = None
            if not isinstance(stats, dict):
                continue
            name = f"{getattr(dev, 'platform', 'dev')}:{getattr(dev, 'id', 0)}"
            got = False
            for stat_key, kind in _MEMSTAT_KINDS:
                v = stats.get(stat_key)
                if isinstance(v, (int, float)):
                    self.metrics.set_gauge(
                        f"device_mem_bytes.{name}{KEY_SEP}{kind}", float(v)
                    )
                    got = True
            if got:
                recorded += 1
            limit, used = stats.get("bytes_limit"), stats.get("bytes_in_use")
            if isinstance(limit, (int, float)) and isinstance(used, (int, float)):
                free = int(limit) - int(used)
                headroom = free if headroom is None else min(headroom, free)
        if headroom is not None:
            self.metrics.set_gauge("device_mem_headroom_bytes", float(headroom))
        return recorded

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="device-mem-sampler", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        # first sample immediately (a daemon's /metrics should show
        # device gauges before the first interval elapses), then poll
        while True:
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 - sampling must never kill serving
                pass
            if self._stop.wait(self.interval_s):
                return

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None


def format_bytes(n: float) -> str:
    """Human bytes for warmup prints and the CLI table (binary units)."""
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024.0
    return f"{n:.1f} TiB"
