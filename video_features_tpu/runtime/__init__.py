"""Run-level runtime services that sit above the extractors: fault
classification, retry policy, the run manifest, and the deterministic
fault-injection hook (faults.py). Nothing here may import jax — the
manifest must stay writable from decode worker threads and from the
scheduler's death paths even when the accelerator runtime is wedged."""
