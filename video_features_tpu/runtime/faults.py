"""Fault tolerance: error classification, retry policy, the run
manifest, and deterministic fault injection.

The reference's only failure contract is "print the traceback and
continue" (ref extract_clip.py:78-84): after a million-video run there
is no machine-readable record of WHICH videos failed, WHY, or whether
retrying would help. This module is the missing contract layer
(docs/robustness.md):

- :func:`classify_error` buckets an exception into ``transient`` (I/O
  flake, decode deadline, RESOURCE_EXHAUSTED — retrying may help),
  ``oom`` (device memory pressure — retrying alone or after splitting a
  fused group may help), ``compile`` (XLA lowering/compilation failure —
  retrying the same program is useless, but a different program, e.g.
  the host preprocess chain, may work), or ``permanent`` (corrupt
  container, shape mismatch — fail fast, record, move on).
- :class:`RunManifest` appends one JSONL record per per-video outcome
  (status, stage, error class, attempts, wall time) to a per-process
  file under ``<output_path>/_manifest/``; :func:`merge_manifest` folds
  every process's records (including prior runs' — that is what makes
  ``--resume`` consult them) into one summary, and :func:`finalize_run`
  writes it as ``summary.json``.
- :func:`backoff_delay` is the exponential-backoff-with-deterministic-
  jitter schedule the retry paths share (the jitter hashes the video
  path so two workers retrying different videos never thundering-herd,
  while a re-run of the same job stays reproducible).
- :class:`FaultInjector` (``--fault_inject STAGE:KIND:EVERY_N``,
  test-only) deterministically raises or hangs at the decode, prepare,
  dispatch, or sink stage, so every retry/fallback/manifest path is
  exercised by fast CPU tests instead of trusted on faith.

No jax imports here: the manifest must stay writable from decode worker
threads and the scheduler's worker-death path even when the accelerator
runtime is wedged.
"""

from __future__ import annotations

import dataclasses
import glob
import hashlib
import json
import os
import signal
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence

MANIFEST_DIRNAME = "_manifest"
SUMMARY_BASENAME = "summary.json"

# decode/prepare/dispatch/sink are the batch extraction pipeline;
# admission/serve_dispatch/extractor/tracker_write are serve-daemon
# stages (ISSUE 8): request admission, the group body around the
# extractor call, the resident extractor itself (breaker/teardown
# coverage), and the durable result write.
# replica_kill/hbm_squeeze/lease_stall are the fleet chaos stages
# (ISSUE 18): replica_kill fires in the spool watcher's poll pass (kind
# 'kill' SIGKILLs the whole replica process — the work-stealing drill),
# hbm_squeeze fires in the daemon's headroom read (any raising kind
# collapses the observed HBM headroom to zero, forcing the preemption
# path without a real device), and lease_stall fires in the lease
# heartbeat (a raising kind skips that pass's mtime refresh, so the
# replica's leases go stale while the process is still alive).
STAGES = (
    "decode", "prepare", "dispatch", "sink",
    "admission", "serve_dispatch", "extractor", "tracker_write",
    "replica_kill", "hbm_squeeze", "lease_stall",
)
KINDS = ("error", "corrupt", "hang", "oom", "compile", "kill")
# how long an injected 'hang' sleeps; tests pair it with a shorter
# --decode_timeout so the REAL deadline check fires, not a mock
HANG_SECONDS = 0.4

RETRYABLE_CLASSES = ("transient", "oom")


# --- exception taxonomy -----------------------------------------------------

class DecodeTimeout(Exception):
    """Decode exceeded ``--decode_timeout`` (a stalled demuxer/NFS read,
    or an injected hang). Transient: the next attempt gets a fresh
    deadline."""

    stage = "decode"


class CorruptVideoError(IOError):
    """The container itself is bad (cannot open, zero frames decodable,
    too short to sample). Permanent: no number of retries fixes bytes."""

    stage = "decode"


class MediaRejected(CorruptVideoError):
    """The preflight probe (io/probe.py) rejected the input before any
    real decode work: container does not open, no stream of the kind the
    consumer needs, no decodable first frame. Permanent, with the
    probe's precise reason in the message."""

    stage = "preflight"


class ResourceCapExceeded(Exception):
    """The input busts a declared resource cap (``--max_pixels`` /
    ``--max_duration_s`` / ``--max_decode_bytes``) — caught either at
    preflight from its own metadata, or by the running decode budget in
    io/video.py when the metadata lied. Permanent: a bigger input never
    shrinks on retry."""

    stage = "decode"


class AudioDecodeError(IOError):
    """The audio payload is bad (unparseable wav, an ffmpeg rip that
    dies on the bitstream) — io/audio.py's analog of
    :class:`CorruptVideoError`. Permanent."""

    stage = "decode"


class MissingStreamError(AudioDecodeError):
    """The container opened but carries no stream of the kind the
    consumer needs (e.g. a silent mp4 through VGGish). Permanent, with
    the missing stream named in the message."""


class InjectedTransientError(OSError):
    """--fault_inject KIND=error: an I/O flake."""


class InjectedPermanentError(ValueError):
    """--fault_inject KIND=corrupt: unfixable bad input."""


class InjectedOOMError(RuntimeError):
    """--fault_inject KIND=oom: message carries RESOURCE_EXHAUSTED so the
    real classifier (not a test-only branch) routes it."""


class InjectedCompileError(RuntimeError):
    """--fault_inject KIND=compile: message carries 'lowering' so the
    real classifier routes it to the degradation path."""


class InjectedSinkKill(RuntimeError):
    """--fault_inject KIND=kill: simulates the process dying mid-save —
    raised after the tmp file is written but before the atomic rename."""

    stage = "sink"


# --- classification ---------------------------------------------------------

# message markers for errors whose TYPE is opaque (jaxlib wraps most
# device failures in one XlaRuntimeError); heuristic by necessity,
# documented in docs/robustness.md
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "out of memory", "Out of memory", "OOM")
_COMPILE_MARKERS = (
    "lowering",
    "compilation",
    "Compilation",
    "UNIMPLEMENTED",
    "Mosaic",
    "INVALID_ARGUMENT",
)


def classify_error(exc: BaseException) -> str:
    """Bucket ``exc`` into 'transient' | 'oom' | 'compile' | 'permanent'.

    Order matters: the specific contracts (corrupt container, decode
    deadline) win over the broad isinstance checks (CorruptVideoError IS
    an OSError, but bad bytes never become good bytes)."""
    if isinstance(exc, (CorruptVideoError, AudioDecodeError, ResourceCapExceeded)):
        return "permanent"
    if isinstance(exc, DecodeTimeout):
        return "transient"
    if isinstance(exc, MemoryError):
        return "oom"
    msg = str(exc)
    if any(m in msg for m in _OOM_MARKERS):
        return "oom"
    if any(m in msg for m in _COMPILE_MARKERS):
        return "compile"
    if isinstance(exc, (OSError, TimeoutError)):
        # covers IOError decode/sink flakes and subprocess deadline kills
        return "transient"
    return "permanent"


def is_retryable(error_class: str) -> bool:
    """Whether re-entering the work queue can help (docs/robustness.md:
    'compile' is NOT retryable — the same program lowers the same way —
    it degrades to the host chain instead)."""
    return error_class in RETRYABLE_CLASSES


# exception types that indict the INPUT rather than the stack. The serve
# circuit breaker must ignore these — a burst of corrupt user uploads is
# not a sick model, and tearing down a healthy resident extractor over
# them is the hostile-traffic DoS docs/robustness.md warns about.
# InjectedPermanentError is the test-only stand-in for "unfixable bad
# input" and rides the same contract.
INPUT_ERROR_TYPES = (
    CorruptVideoError,    # includes MediaRejected
    AudioDecodeError,     # includes MissingStreamError
    ResourceCapExceeded,
    InjectedPermanentError,
)


def is_input_error(exc: BaseException) -> bool:
    """True when ``exc`` blames the input media, not the infrastructure
    — the breaker-correctness predicate (serve/daemon.py gates
    ``CircuitBreaker.record_failure`` on it)."""
    return isinstance(exc, INPUT_ERROR_TYPES)


def backoff_delay(attempt: int, base: float, key: str) -> float:
    """Exponential backoff with deterministic jitter for retry ``attempt``
    (1-based). Jitter derives from sha1(key, attempt): different videos
    desynchronize (no thundering herd after a shared-filesystem blip),
    identical re-runs reproduce exactly."""
    if base <= 0:
        return 0.0
    digest = hashlib.sha1(f"{key}:{attempt}".encode()).digest()
    frac = digest[0] / 255.0  # [0, 1]
    return base * (2.0 ** (attempt - 1)) * (0.5 + 0.5 * frac)


# --- fault injection --------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class FaultSpec:
    stage: str
    kind: str
    every_n: int


def parse_fault_specs(specs: Optional[Sequence[str]]) -> List[FaultSpec]:
    """Parse ``--fault_inject STAGE:KIND:EVERY_N`` values; raises
    ValueError naming the bad spec (sanity_check calls this so a typo
    dies at arg-parse time, not mid-run)."""
    out: List[FaultSpec] = []
    for raw in specs or ():
        parts = str(raw).split(":")
        if len(parts) != 3:
            raise ValueError(
                f"--fault_inject expects STAGE:KIND:EVERY_N, got {raw!r}"
            )
        stage, kind, every = parts
        if stage not in STAGES:
            raise ValueError(
                f"--fault_inject stage {stage!r} not in {STAGES} ({raw!r})"
            )
        if kind not in KINDS:
            raise ValueError(
                f"--fault_inject kind {kind!r} not in {KINDS} ({raw!r})"
            )
        try:
            n = int(every)
        except ValueError:
            n = 0
        if n < 1:
            raise ValueError(
                f"--fault_inject EVERY_N must be a positive int ({raw!r})"
            )
        out.append(FaultSpec(stage, kind, n))
    return out


class FaultInjector:
    """Deterministic stage-counter injection: ``fire(stage)`` increments
    that stage's call counter and raises/hangs when any spec's
    ``counter % every_n == 0``. Counters are process-global per injector,
    so what constitutes one 'call' is the stage's own unit (decode: one
    reader open; prepare/dispatch/sink: one video or group)."""

    def __init__(self, specs: Sequence[FaultSpec]) -> None:
        self._specs: Dict[str, List[FaultSpec]] = {}
        for s in specs:
            self._specs.setdefault(s.stage, []).append(s)
        self._counts: Dict[str, int] = {}
        self._lock = threading.Lock()

    def fire(self, stage: str) -> None:
        specs = self._specs.get(stage)
        if not specs:
            return
        with self._lock:
            count = self._counts.get(stage, 0) + 1
            self._counts[stage] = count
        for spec in specs:
            if count % spec.every_n == 0:
                self._raise(spec, count)

    @staticmethod
    def _raise(spec: FaultSpec, count: int) -> None:
        tag = f"injected fault {spec.stage}:{spec.kind} (call {count})"
        if spec.kind == "hang":
            time.sleep(HANG_SECONDS)  # the real deadline check must fire
            return
        if spec.stage == "replica_kill" and spec.kind == "kill":
            # the chaos drill is a REAL SIGKILL: no atexit, no finally,
            # no flush — exactly the death the lease-expiry reclamation
            # and foreign-replica reconcile exist to survive
            os.kill(os.getpid(), signal.SIGKILL)
        exc: Exception
        if spec.kind == "error":
            exc = InjectedTransientError(f"{tag}: transient I/O error")
        elif spec.kind == "corrupt":
            exc = InjectedPermanentError(f"{tag}: unfixable corrupt input")
        elif spec.kind == "oom":
            exc = InjectedOOMError(f"{tag}: RESOURCE_EXHAUSTED: device OOM")
        elif spec.kind == "compile":
            exc = InjectedCompileError(f"{tag}: XLA lowering failed")
        else:  # kill
            exc = InjectedSinkKill(f"{tag}: process killed mid-save")
        exc.stage = spec.stage  # lets handlers attribute the true stage
        raise exc


_INJECTOR: Optional[FaultInjector] = None
# the serve daemon (re)installs the injector on every extractor build,
# which can happen from the dispatcher thread — the rebind needs a lock
# even though fire() reads the reference atomically
_INJECTOR_LOCK = threading.Lock()


def install_injector(specs: Optional[Sequence[str]]) -> None:
    """Install (or, with None/empty, clear) the process-global injector.
    Test-only by design: the most recently constructed extractor's config
    wins, which is exactly the one-run-per-process CLI lifecycle."""
    global _INJECTOR
    parsed = parse_fault_specs(specs)
    with _INJECTOR_LOCK:
        _INJECTOR = FaultInjector(parsed) if parsed else None


def fire(stage: str) -> None:
    """Injection point hook; a no-op attribute check on the happy path
    (bench.py fault_overhead pins its cost at well under 1%)."""
    inj = _INJECTOR
    if inj is not None:
        inj.fire(stage)


# --- run manifest -----------------------------------------------------------

def manifest_dir(output_root: str) -> str:
    return os.path.join(output_root, MANIFEST_DIRNAME)


_SKIP_CLAIM_DIRNAME = "_skip_claims"


def claim_skip_record(output_root: str, video_key: str) -> bool:
    """Cross-host dedup for ``--resume`` ``skipped`` manifest records on
    shared storage: two replicas resuming the same output root both
    probe the same already-done video, and without coordination both
    append a ``skipped`` record — double-counting the video in the
    merged summary. The claim is a file created O_CREAT|O_EXCL next to
    the manifest (atomic on POSIX and NFS alike), keyed by the video
    key's sha1 — exactly one process wins and records; losers still
    skip the work, just silently. A claim-side I/O failure (read-only
    fs, permissions) returns True: recording a duplicate beats dropping
    the record."""
    claim_dir = os.path.join(manifest_dir(output_root), _SKIP_CLAIM_DIRNAME)
    digest = hashlib.sha1(str(video_key).encode("utf-8", "replace")).hexdigest()
    path = os.path.join(claim_dir, f"{digest}.claim")
    try:
        os.makedirs(claim_dir, exist_ok=True)
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        return False
    except OSError:
        return True
    try:
        os.write(fd, f"{os.getpid()} {video_key}\n".encode("utf-8", "replace"))
    finally:
        os.close(fd)
    return True


class RunManifest:
    """Append-only per-process JSONL event log under
    ``<output_root>/_manifest/events-<pid>-<runid>.jsonl``.

    One file per process (multi-process queue runs and multi-host pods
    never contend on a writer); one :class:`threading.Lock` per process
    (decode workers, device workers, and the scheduler's death path all
    record). Records are flushed per line so a killed run keeps every
    outcome that preceded the kill."""

    def __init__(self, output_root: str) -> None:
        self.output_root = output_root
        self.run_id = uuid.uuid4().hex[:8]
        self.path = os.path.join(
            manifest_dir(output_root), f"events-{os.getpid()}-{self.run_id}.jsonl"
        )
        self._lock = threading.Lock()
        self._seq = 0
        self._fh = None

    def record(
        self,
        video: Optional[str],
        status: str,
        stage: Optional[str] = None,
        error_class: Optional[str] = None,
        error_type: Optional[str] = None,
        message: Optional[str] = None,
        attempts: Optional[int] = None,
        wall_s: Optional[float] = None,
        **extra: Any,
    ) -> None:
        row: Dict[str, Any] = {"video": video, "status": status}
        if stage is not None:
            row["stage"] = stage
        if error_class is not None:
            row["error_class"] = error_class
        if error_type is not None:
            row["error_type"] = error_type
        if message is not None:
            row["message"] = str(message)[:500]
        if attempts is not None:
            row["attempts"] = int(attempts)
        if wall_s is not None:
            row["wall_s"] = round(float(wall_s), 4)
        row.update(extra)
        self._append(row)

    def event(self, name: str, **fields: Any) -> None:
        """Non-per-video happenings (worker deaths, group fallbacks)."""
        self._append({"event": name, **fields})

    def _append(self, row: Dict[str, Any]) -> None:
        with self._lock:
            self._seq += 1
            row = {
                "ts": round(time.time(), 4),
                "pid": os.getpid(),
                "run": self.run_id,
                "seq": self._seq,
                **row,
            }
            if self._fh is None:
                os.makedirs(os.path.dirname(self.path), exist_ok=True)
                self._fh = open(self.path, "a", encoding="utf-8")
            self._fh.write(json.dumps(row) + "\n")
            self._fh.flush()


class _NullManifest:
    """No-op stand-in for external_call / print-mode ad-hoc runs."""

    path = None
    output_root = None

    def record(self, *a: Any, **kw: Any) -> None:
        pass

    def event(self, *a: Any, **kw: Any) -> None:
        pass


NULL_MANIFEST = _NullManifest()


def iter_manifest_records(output_root: str) -> List[Dict[str, Any]]:
    """Every record from every process's (and prior run's) events file,
    in (ts, pid, seq) order. Truncated trailing lines (a killed writer)
    are skipped, never fatal."""
    rows: List[Dict[str, Any]] = []
    for path in glob.glob(os.path.join(manifest_dir(output_root), "events-*.jsonl")):
        try:
            with open(path, "r", encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rows.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue  # torn tail of a killed writer
        except OSError:
            continue
    rows.sort(key=lambda r: (r.get("ts", 0), r.get("pid", 0), r.get("seq", 0)))
    return rows


def merge_manifest(output_root: str) -> Optional[Dict[str, Any]]:
    """Fold every events file under ``output_root`` into one summary, or
    None when no manifest exists (e.g. a print-mode run).

    Per-video final status: the chronologically LAST terminal record
    (done/failed — plus 'rejected' for serve-mode request manifests)
    wins — so a retry that recovers reads 'done', a resume run that
    re-fails reads 'failed', and a 'skipped' probe can never demote an
    earlier 'done'. Videos with only non-terminal records (skipped,
    retry) keep the last of those."""
    records = iter_manifest_records(output_root)
    if not records:
        return None
    videos: Dict[str, Dict[str, Any]] = {}
    warnings: List[Dict[str, Any]] = []
    events: List[Dict[str, Any]] = []
    retries = 0
    for r in records:
        if "event" in r:
            events.append(r)
            continue
        status = r.get("status")
        if status == "warning":
            warnings.append(r)
            continue
        if status == "retry":
            retries += 1
        key = r.get("video")
        if key is None:
            continue
        cur = videos.setdefault(key, {"status": None})
        cur["attempts"] = max(int(cur.get("attempts") or 0), int(r.get("attempts") or 0))
        terminal = status in ("done", "failed", "rejected", "expired", "cancelled")
        if terminal or cur["status"] not in (
            "done", "failed", "rejected", "expired", "cancelled"
        ):
            cur["status"] = status
            # 'span' links a failure to its interval in
            # _telemetry/spans-*.jsonl (runtime/telemetry.py)
            for field in ("stage", "error_class", "error_type", "message",
                          "wall_s", "span"):
                if field in r:
                    cur[field] = r[field]
                elif field in cur and terminal:
                    del cur[field]
    counts = {"done": 0, "failed": 0, "skipped": 0, "retry": 0,
              "rejected": 0, "expired": 0, "cancelled": 0, "other": 0}
    for v in videos.values():
        counts[v["status"] if v["status"] in counts else "other"] += 1
    worker_deaths = [e for e in events if e.get("event") == "worker_death"]
    return {
        "videos": videos,
        "total": len(videos),
        "done": counts["done"],
        "failed": counts["failed"],
        "skipped": counts["skipped"],
        "rejected": counts["rejected"],
        "expired": counts["expired"],
        "cancelled": counts["cancelled"],
        "retries": retries,
        "warnings": warnings,
        "events": events,
        "worker_deaths": worker_deaths,
    }


def finalize_run(output_root: str) -> Optional[Dict[str, Any]]:
    """Merge and atomically write ``_manifest/summary.json`` (tmp +
    rename: concurrent multi-host finalizers last-write-win a COMPLETE
    file). Returns the summary, or None when there is no manifest."""
    summary = merge_manifest(output_root)
    if summary is None:
        return None
    # telemetry block: merged metrics snapshots (stage totals, counters,
    # throughput) + the overlap-efficiency report over the span files.
    # A telemetry bug must never lose the run record, so failures land
    # as a string instead of raising.
    try:
        from video_features_tpu.runtime import telemetry as _telemetry

        tblock = _telemetry.collect(output_root)
        if tblock:
            summary["telemetry"] = tblock
    except Exception as e:  # noqa: BLE001 - keep the manifest writable
        summary["telemetry_error"] = repr(e)
    # lazy import: io/sink.py imports this module for fault injection
    from video_features_tpu.io.sink import atomic_write_json

    path = os.path.join(manifest_dir(output_root), SUMMARY_BASENAME)
    atomic_write_json(path, summary, indent=1, sort_keys=True)
    return summary


def format_summary(summary: Dict[str, Any]) -> str:
    parts = [
        f"run manifest: {summary['done']}/{summary['total']} done",
        f"{summary['failed']} failed",
        f"{summary['skipped']} skipped",
        f"{summary['retries']} retries",
    ]
    if summary.get("rejected"):
        parts.insert(2, f"{summary['rejected']} rejected")
    if summary.get("expired"):
        parts.append(f"{summary['expired']} expired")
    if summary.get("cancelled"):
        parts.append(f"{summary['cancelled']} cancelled")
    if summary["warnings"]:
        parts.append(f"{len(summary['warnings'])} warning(s)")
    if summary["worker_deaths"]:
        parts.append(f"{len(summary['worker_deaths'])} worker death(s)")
    tput = summary.get("telemetry", {}).get("throughput")
    if tput:
        parts.append(f"{tput.get('videos_per_s', 0.0):.2f} videos/s")
        parts.append(f"{tput.get('decode_fps', 0.0):.0f} decode fps")
    line = ", ".join(parts)
    failed = [k for k, v in summary["videos"].items() if v["status"] == "failed"]
    if failed:
        shown = ", ".join(failed[:5]) + (", ..." if len(failed) > 5 else "")
        line += f"\n  failed: {shown}"
    return line


def strict_failures(summary: Dict[str, Any]) -> List[str]:
    """What ``--strict`` turns into a nonzero exit: failed videos,
    empty-feature warnings, and worker deaths."""
    problems = [
        f"failed: {k} ({v.get('error_class', '?')}: {v.get('message', '')[:80]})"
        for k, v in summary["videos"].items()
        if v["status"] == "failed"
    ]
    problems += [f"warning: {w.get('message', '')[:120]}" for w in summary["warnings"]]
    problems += [
        f"worker death: {d.get('device', '?')}: {d.get('message', '')[:80]}"
        for d in summary["worker_deaths"]
    ]
    return problems


def permanently_failed_videos(output_root: str) -> set:
    """Videos whose merged final status is a PERMANENT failure — the set
    ``--resume`` skips unless ``--retry_failed`` (transient-exhausted
    failures are re-attempted on resume by default: retrying may help,
    that is what transient means)."""
    summary = merge_manifest(output_root)
    if summary is None:
        return set()
    return {
        k
        for k, v in summary["videos"].items()
        if v["status"] == "failed" and v.get("error_class") == "permanent"
    }
