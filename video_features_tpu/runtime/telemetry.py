"""Structured telemetry for the extraction hot path: spans, metrics, heartbeat.

The reference pipeline's only observability was a tqdm bar (SURVEY.md
§5) and ours was an aggregate :class:`~video_features_tpu.utils.profiling.StageTimer`
printed behind ``--profile_dir``. This module replaces both with the
three primitives every ROADMAP item ahead of us needs:

* **Spans** — one record per (video, stage) interval with monotonic
  start/end, thread + worker id, attempt, and arbitrary attributes,
  buffered in memory and drained to ``<output>/_telemetry/spans-*.jsonl``
  by a single shared daemon thread so the hot loops never block on I/O.
  Stage names are the pipeline's own: ``decode`` / ``reencode`` /
  ``prepare`` / ``h2d`` / ``dispatch`` / ``fetch`` / ``sink`` /
  ``compile`` / ``extract`` (the serial loop's fused stage).
* **Metrics registry** — process-wide counters (videos done, frames
  decoded, H2D bytes, retries, compiles), gauges (pipelined queue
  depths), and log-bucketed stage-latency histograms, snapshotted
  atomically to ``_telemetry/metrics-*.json`` on every drain so a
  crashed run still reports throughput.
* **Heartbeat** — a periodic one-line progress print (videos/sec,
  decode fps, ETA) replacing silence on long runs.

Two consumers live in :mod:`video_features_tpu.telemetry` (the package):
``python -m video_features_tpu.telemetry export`` emits Chrome-trace /
Perfetto JSON from a spans file, and ``report`` prints the
overlap-efficiency summary computed by :func:`overlap_report` here — the
fraction of wall time where host decode/prepare overlaps device
dispatch/fetch, the measurement baseline for the async-ingest ROADMAP
item.

Like :mod:`video_features_tpu.runtime.faults` this module imports no
jax at module scope: telemetry records host-side wall time only and must
introduce no device syncs (graftcheck GC10x covers this file). All
module-level mutable state is lock-guarded (GC301); the drain thread is
shared across Telemetry instances so a process that builds many
extractors (tests, service mode later) holds one background thread, not
one per run.
"""

from __future__ import annotations

import bisect
import glob
import io
import json
import math
import os
import sys
import threading
import time
import uuid
import weakref
from collections import deque
from contextlib import contextmanager
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from video_features_tpu.utils.profiling import StageTimer

STAGES = (
    "decode", "reencode", "prepare", "h2d",
    "dispatch", "fetch", "sink", "compile", "extract",
    "request",  # serve mode: one request's lifetime, parent of its group's stages
    "admission",   # serve mode: parse + preflight + queue admit of one request
    "queue_wait",  # serve mode: admission -> group dispatch (the queueing delay)
)

# Host-side ingest stages vs device dispatch/fetch stages, for the
# overlap-efficiency report. ``extract`` (the serial loop's fused
# prepare+device stage) is deliberately in neither set: the serial loop
# has no overlap story to measure. The serve lifecycle stages
# (``request``/``admission``/``queue_wait``) are in neither either —
# they bracket queueing + dispatch end-to-end, so counting them as busy
# time in either set would double-book their children.
HOST_STAGES = frozenset({"decode", "reencode", "prepare"})
DEVICE_STAGES = frozenset({"h2d", "dispatch", "fetch"})

# Log-ish latency buckets (seconds) for stage histograms: fine-grained
# where per-video stages actually land (1ms..1s), coarse above.
HIST_BOUNDS: Tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
)

_DRAIN_INTERVAL_S = 0.5
# Bounded retention when there is no file sink (external_call / bench):
# enough for overlap math over a bench pass, small enough to never
# matter for memory.
_MEM_RETAIN_SPANS = 100_000

# -- process-global state (all writes under _STATE_LOCK; GC301) ---------
_STATE_LOCK = threading.Lock()
_CURRENT: Optional["Telemetry"] = None
_DRAINER: Optional[threading.Thread] = None
_TARGETS: "weakref.WeakSet[Telemetry]" = weakref.WeakSet()
# the armed RecompileWatch is process-global latest-wins (like
# faults.install_injector): jax_log_compiles + the pxla log handler are
# process state, so exactly one watch may be attached at a time
_WATCH: Optional["RecompileWatch"] = None
# thread-local flag raised while the cost ledger (telemetry/ledger.py)
# runs its analysis-only AOT compile: jax emits its "Compiling <fn>"
# log on the calling thread, so the watch can tell a ledger capture
# from a real (re)compile and skip the event — the GC401 budgets and
# the runtime allowance count executions, not bookkeeping
_CAPTURE_LOCAL = threading.local()


@contextmanager
def suppress_compile_watch() -> Iterator[None]:
    """Mark this thread's compile-log events as ledger-capture noise;
    :meth:`RecompileWatch.on_compile` drops them. Reentrant."""
    prev = getattr(_CAPTURE_LOCAL, "on", False)
    _CAPTURE_LOCAL.on = True
    try:
        yield
    finally:
        _CAPTURE_LOCAL.on = prev


def compile_watch_suppressed() -> bool:
    return bool(getattr(_CAPTURE_LOCAL, "on", False))


def set_current(tele: Optional["Telemetry"]) -> None:
    """Install ``tele`` as the process-current telemetry, the sink for
    module-level hooks (:func:`frame_decoded`, :func:`begin`/:func:`end`,
    :func:`note_bucket`) used by code that has no extractor reference
    (io/ decode, ops/ bucketing). Latest-wins, like
    ``faults.install_injector``."""
    global _CURRENT
    with _STATE_LOCK:
        _CURRENT = tele


def current() -> Optional["Telemetry"]:
    return _CURRENT


def frame_decoded(n: int = 1) -> None:
    """Count decoded frames into the current telemetry (io/video.py hook)."""
    t = _CURRENT
    if t is not None and t.enabled:
        t.metrics.inc("frames_decoded", n)


def note_bucket(key: Any) -> None:
    """Record a distinct spatial/output bucket (ops/window.py hook); the
    recompile watch scales its runtime ceilings by the bucket count."""
    t = _CURRENT
    if t is not None and t.enabled:
        t.note_bucket(key)


def begin(stage: str, video: Optional[str] = None, **extra: Any) -> Optional["SpanToken"]:
    """Open a span on the current telemetry; returns None when telemetry
    is absent/disabled so callers can pass the token straight to
    :func:`end` unconditionally. For code (io/ readers) whose interval
    does not nest lexically."""
    t = _CURRENT
    if t is None or not t.enabled:
        return None
    return t.begin(stage, video=video, **extra)


def end(token: Optional["SpanToken"]) -> None:
    if token is not None:
        token.finish()


def _ensure_drainer() -> None:
    global _DRAINER
    with _STATE_LOCK:
        if _DRAINER is not None and _DRAINER.is_alive():
            return
        t = threading.Thread(target=_drain_loop, name="telemetry-drain", daemon=True)
        _DRAINER = t
    t.start()


def _drain_loop() -> None:
    while True:
        time.sleep(_DRAIN_INTERVAL_S)
        for tele in list(_TARGETS):
            try:
                tele.flush()
                tele.maybe_heartbeat()
            except Exception:  # noqa: BLE001 - observability must never kill the run
                pass


class MetricsRegistry:
    """Thread-safe counters / gauges / histograms with a dict snapshot."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        # name -> [count, sum, min, max, bucket_counts(len(HIST_BOUNDS)+1)]
        self._hists: Dict[str, list] = {}
        self.t_start = time.time()

    def inc(self, name: str, n: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = [0, 0.0, value, value, [0] * (len(HIST_BOUNDS) + 1)]
                self._hists[name] = h
            h[0] += 1
            h[1] += value
            h[2] = min(h[2], value)
            h[3] = max(h[3], value)
            h[4][bisect.bisect_left(HIST_BOUNDS, value)] += 1

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0)

    def gauge(self, name: str, default: Optional[float] = None) -> Optional[float]:
        with self._lock:
            return self._gauges.get(name, default)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "t_start": self.t_start,
                "t_snapshot": time.time(),
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {
                    name: {
                        "count": h[0], "sum": h[1], "min": h[2], "max": h[3],
                        "bounds": list(HIST_BOUNDS), "buckets": list(h[4]),
                    }
                    for name, h in self._hists.items()
                },
            }


def _quantile(sorted_vals: Sequence[float], q: float) -> float:
    """Nearest-rank quantile over an ascending list (empty -> 0.0)."""
    if not sorted_vals:
        return 0.0
    idx = max(math.ceil(q * len(sorted_vals)) - 1, 0)
    return float(sorted_vals[min(idx, len(sorted_vals) - 1)])


class SloTracker:
    """Rolling-window SLO accounting for serve mode (ISSUE 12).

    One sample per terminal request: end-to-end latency (admission to
    terminal, on the daemon's scheduling clock), queue wait, priority
    tier, terminal state, and whether its deadline was missed. The
    window is time-bounded (``window_s``) and size-bounded
    (``max_samples``), so a week-old burst never skews today's p99 and
    memory stays O(1) under any traffic.

    ``snapshot()`` feeds /metrics, /v1/stats, and the serve heartbeat
    line: p50/p95/p99 latency + queue wait and deadline-miss rate,
    overall and per priority tier. The miss-rate denominator counts only
    requests that were *supposed* to complete (done/failed/expired);
    cancelled and rejected requests still contribute latency samples but
    a user hitting DELETE is not a missed promise.

    Thread-safe (records arrive from the dispatcher thread, snapshots
    from HTTP handler threads and the drain-thread heartbeat); no I/O
    under the lock."""

    # terminal states that count toward the deadline-miss denominator
    _MISS_DENOM_STATES = ("done", "failed", "expired")

    def __init__(
        self,
        window_s: float = 300.0,
        max_samples: int = 4096,
        clock: Any = time.monotonic,
    ) -> None:
        self.window_s = max(float(window_s), 1.0)
        self._clock = clock
        self._lock = threading.Lock()
        # (t, tier, state, latency_s, queue_wait_s|None, missed)
        self._samples: deque = deque(maxlen=max(int(max_samples), 16))

    def record(
        self,
        state: str,
        latency_s: float,
        queue_wait_s: Optional[float] = None,
        priority: int = 0,
        deadline_missed: bool = False,
        now: Optional[float] = None,
    ) -> None:
        t = self._clock() if now is None else now
        with self._lock:
            self._samples.append((
                t, int(priority), str(state), float(latency_s),
                None if queue_wait_s is None else float(queue_wait_s),
                bool(deadline_missed),
            ))

    def _window(self, now: Optional[float]) -> list:
        t = self._clock() if now is None else now
        cutoff = t - self.window_s
        with self._lock:
            # prune from the left (samples are time-ordered), then copy
            while self._samples and self._samples[0][0] < cutoff:
                self._samples.popleft()
            return list(self._samples)

    @staticmethod
    def _digest(samples: list) -> Dict[str, Any]:
        lats = sorted(s[3] for s in samples)
        waits = sorted(s[4] for s in samples if s[4] is not None)
        denom = [s for s in samples if s[2] in SloTracker._MISS_DENOM_STATES]
        missed = sum(1 for s in denom if s[5])
        return {
            "count": len(samples),
            "miss_rate": (missed / len(denom)) if denom else 0.0,
            "deadline_missed": missed,
            "latency_s": {
                "p50": round(_quantile(lats, 0.50), 4),
                "p95": round(_quantile(lats, 0.95), 4),
                "p99": round(_quantile(lats, 0.99), 4),
            },
            "queue_wait_s": {
                "p50": round(_quantile(waits, 0.50), 4),
                "p95": round(_quantile(waits, 0.95), 4),
                "p99": round(_quantile(waits, 0.99), 4),
            },
        }

    def snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        samples = self._window(now)
        tiers: Dict[str, list] = {}
        for s in samples:
            tiers.setdefault(str(s[1]), []).append(s)
        return {
            "window_s": self.window_s,
            "overall": self._digest(samples),
            "tiers": {k: self._digest(v) for k, v in sorted(tiers.items())},
        }

    def miss_rate(self, now: Optional[float] = None) -> float:
        return self._digest(self._window(now))["miss_rate"]


class SpanToken:
    """Handle for a begin/end span (non-lexical intervals: io/ readers)."""

    __slots__ = ("_tele", "_row", "_t0", "_done")

    def __init__(self, tele: "Telemetry", row: Dict[str, Any], t0: float) -> None:
        self._tele = tele
        self._row = row
        self._t0 = t0
        self._done = False

    @property
    def span_id(self) -> str:
        return self._row["span"]

    def finish(self, **extra: Any) -> None:
        if self._done:
            return
        self._done = True
        if extra:
            self._row.update(extra)
        self._tele._finish_row(self._row, self._t0)


class Telemetry:
    """Per-run span recorder + metrics registry + heartbeat.

    ``enabled=False`` degrades :meth:`span` to bare StageTimer timing —
    the exact pre-telemetry behaviour, used as the baseline by the
    ``telemetry_overhead`` bench part. With no ``output_root`` (external
    calls, bench passes) spans are retained in a bounded in-memory deque
    instead of a file so overlap math still works.
    """

    def __init__(
        self,
        output_root: Optional[str] = None,
        enabled: bool = True,
        heartbeat_s: float = 0.0,
        total_videos: Optional[int] = None,
        run_id: Optional[str] = None,
    ) -> None:
        self.enabled = bool(enabled)
        self.output_root = output_root
        self.heartbeat_s = float(heartbeat_s or 0.0)
        self.total_videos = total_videos
        # uuid tail: a daemon builds several Telemetry instances in the
        # same process-second (its own + one per pooled extractor), and
        # their spans files must never collide
        self.run_id = run_id or (
            f"{int(time.time()):x}-{os.getpid():x}-{uuid.uuid4().hex[:6]}"
        )
        self.timer = StageTimer()  # span-backed aggregate view
        self.metrics = MetricsRegistry()
        self._lock = threading.Lock()
        self._flush_lock = threading.Lock()
        self._seq = 0
        self._rows: deque = deque()
        self._mem: deque = deque(maxlen=_MEM_RETAIN_SPANS)
        self._buckets: set = set()
        self._local = threading.local()
        self._path: Optional[str] = None
        self._metrics_path: Optional[str] = None
        self._file: Optional[io.TextIOBase] = None
        self._next_heartbeat = (
            time.monotonic() + self.heartbeat_s if self.heartbeat_s > 0 else None
        )
        self._closed = False
        self._watch: Optional["RecompileWatch"] = None
        # serve mode swaps the batch-progress heartbeat line for its own
        # (queue depth, inflight, miss rate): a callable returning the
        # line, or None/raising to fall back to heartbeat_line()
        self.heartbeat_provider: Optional[Any] = None
        if self.enabled and output_root:
            tdir = os.path.join(output_root, "_telemetry")
            os.makedirs(tdir, exist_ok=True)
            base = f"{os.getpid()}-{self.run_id}"
            self._path = os.path.join(tdir, f"spans-{base}.jsonl")
            self._metrics_path = os.path.join(tdir, f"metrics-{base}.json")
        if self.enabled:
            _TARGETS.add(self)
            _ensure_drainer()

    # -- spans ----------------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = []
            self._local.stack = st
        return st

    def _new_row(self, stage: str, video: Optional[str], extra: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            self._seq += 1
            seq = self._seq
        th = threading.current_thread()
        stack = self._stack()
        row: Dict[str, Any] = {
            "span": f"{self.run_id}.{seq}",
            "seq": seq,
            "parent": stack[-1]["span"] if stack else None,
            "stage": stage,
            "video": video,
            "pid": os.getpid(),
            "run": self.run_id,
            "thread": th.ident or 0,
            "thread_name": th.name,
        }
        if extra:
            row.update(extra)
        return row

    def _finish_row(self, row: Dict[str, Any], t0: float) -> None:
        t1 = time.monotonic()
        row["t0"] = t0
        row["t1"] = t1
        dt = t1 - t0
        stage = row["stage"]
        with self.timer._lock:
            self.timer.seconds[stage] += dt
            self.timer.counts[stage] += 1
        self.metrics.observe(f"stage_s.{stage}", dt)
        with self._lock:
            self._rows.append(row)
            if self._path is None:
                self._mem.append(row)

    @contextmanager
    def span(
        self, stage: str, video: Optional[str] = None, **extra: Any
    ) -> Iterator[Optional[Dict[str, Any]]]:
        """Time a stage. Disabled mode keeps the StageTimer aggregate
        (pre-telemetry behaviour) and yields None; enabled mode yields
        the mutable row (callers may add attributes) and, on an escaping
        exception, stamps the span id onto the exception as
        ``telemetry_span`` (innermost span wins) so manifest failure
        records link to the timeline."""
        if not self.enabled:
            with self.timer.stage(stage):
                yield None
            return
        row = self._new_row(stage, video, extra)
        stack = self._stack()
        stack.append(row)
        t0 = time.monotonic()
        try:
            yield row
        except BaseException as exc:
            if not hasattr(exc, "telemetry_span"):
                try:
                    exc.telemetry_span = row["span"]
                except Exception:  # noqa: BLE001 - exceptions with __slots__
                    pass
            raise
        finally:
            stack.pop()
            self._finish_row(row, t0)

    def begin(self, stage: str, video: Optional[str] = None, **extra: Any) -> Optional[SpanToken]:
        """Non-lexical span open; pair with ``token.finish()``. The span
        records the opener's thread and current parent but is NOT pushed
        on the nesting stack (the interval may outlive the opening
        frame, e.g. an io/ reader's lifetime)."""
        if not self.enabled:
            return None
        row = self._new_row(stage, video, extra)
        return SpanToken(self, row, time.monotonic())

    def point(self, stage: str, **extra: Any) -> None:
        """Zero-duration event span (compile events)."""
        if not self.enabled:
            return
        row = self._new_row(stage, None, extra)
        self._finish_row(row, time.monotonic())

    # -- registry hooks -------------------------------------------------

    def note_bucket(self, key: Any) -> None:
        with self._lock:
            self._buckets.add(key)
        self.metrics.set_gauge("buckets_seen", len(self._buckets))

    def buckets_seen(self) -> int:
        with self._lock:
            return len(self._buckets)

    def count_h2d(self, payload: Any) -> None:
        n = payload_nbytes(payload)
        if n:
            self.metrics.inc("h2d_bytes", n)

    # -- recompile watch ------------------------------------------------

    def arm_recompile_watch(self, manifest: Any) -> None:
        """Attach a ``jax_log_compiles`` listener recording compile
        events as point spans and warning (once per fn name, via the
        manifest) when a device-preprocess family exceeds its committed
        per-bucket budget at runtime. Latest-wins process-global: arming
        detaches any previously armed watch (the log handler and the
        jax_log_compiles flag are process state)."""
        global _WATCH
        if not self.enabled or self._watch is not None:
            return
        watch = RecompileWatch(self, manifest)
        with _STATE_LOCK:
            prev, _WATCH = _WATCH, watch
        if prev is not None:
            prev.detach()
        watch.attach()
        self._watch = watch

    # -- sinks ----------------------------------------------------------

    def flush(self) -> None:
        """Drain buffered spans to the JSONL file and refresh the
        metrics snapshot. Called by the shared drain thread and by
        :meth:`close`; safe from any thread. ``_flush_lock`` serializes
        WRITERS only — span recording contends on ``_lock`` alone, so a
        slow disk never stalls the hot path — and the file I/O itself
        lives in the ``_flush_sink`` boundary (the one sanctioned
        blocking region, same contract as the GC10x fetch/sink
        allowlist; GC312 holds every other lock region to it)."""
        with self._flush_lock:
            with self._lock:
                rows = list(self._rows)
                self._rows.clear()
            self._flush_sink(rows)

    def _flush_sink(self, rows: List[Dict[str, Any]]) -> None:
        """The blocking sink boundary: JSONL append + metrics snapshot
        rewrite. Only ever entered with ``_flush_lock`` held (one writer
        at a time); takes no state locks beyond the short ``_lock`` in
        :meth:`buckets_seen`."""
        if self._path is not None and rows:
            if self._file is None:
                self._file = open(self._path, "a", encoding="utf-8")
            f = self._file
            for r in rows:
                f.write(json.dumps(r, default=str) + "\n")
            f.flush()
        if self._metrics_path is not None:
            from video_features_tpu.io.sink import atomic_write_json

            snap = self.metrics.snapshot()
            snap["run"] = self.run_id
            snap["buckets_seen"] = self.buckets_seen()
            atomic_write_json(self._metrics_path, snap)

    def maybe_heartbeat(self) -> None:
        if self._next_heartbeat is None or time.monotonic() < self._next_heartbeat:
            return
        self._next_heartbeat = time.monotonic() + self.heartbeat_s
        line: Optional[str] = None
        if self.heartbeat_provider is not None:
            try:
                line = self.heartbeat_provider()
            except Exception:  # noqa: BLE001 - a broken provider must not kill the drain thread
                line = None
        print(line if line is not None else self.heartbeat_line(),
              file=sys.stderr, flush=True)

    def heartbeat_line(self) -> str:
        done = int(self.metrics.counter("videos_done"))
        frames = int(self.metrics.counter("frames_decoded"))
        elapsed = max(time.time() - self.metrics.t_start, 1e-9)
        vps = done / elapsed
        fps = frames / elapsed
        total = self.total_videos
        if total and vps > 0:
            eta = f"{(total - done) / vps:.0f}s"
        else:
            eta = "?"
        frac = f"{done}/{total}" if total else f"{done}"
        line = (
            f"telemetry: {frac} videos, {vps:.2f} videos/s, "
            f"{fps:.0f} decode fps, eta {eta}"
        )
        # serve mode: surface live admission-queue depth (the bounded
        # backpressure queue) on the same line the operator already reads
        depth = self.metrics.gauge("queue_depth.admission")
        if depth is not None:
            line += f", queue {int(depth)}"
        # async-ingest pipeline depths (extract/base.py::_run_pipelined):
        # dispatched-but-unfetched device groups and host-resident
        # prepared payloads waiting to dispatch — a stalled pipeline
        # shows up here live, not just post-hoc in the overlap report
        inflight = self.metrics.gauge("queue_depth.inflight")
        prepared = self.metrics.gauge("queue_depth.prepared")
        if inflight is not None or prepared is not None:
            line += (
                f", inflight {int(inflight or 0)}, prepared {int(prepared or 0)}"
            )
        return line

    def spans(self) -> List[Dict[str, Any]]:
        """All spans recorded so far (memory mode only reflects the
        bounded retention window). Flushes first so the file is
        complete."""
        self.flush()
        if self._path is not None:
            return read_spans(self._path)
        with self._lock:
            return list(self._mem)

    def close(self) -> None:
        """Final flush, detach the recompile watch, release the file.
        Idempotent."""
        if self._closed:
            return
        self._closed = True
        if self._watch is not None:
            global _WATCH
            self._watch.detach()
            with _STATE_LOCK:
                if _WATCH is self._watch:
                    _WATCH = None
            self._watch = None
        self.flush()
        with self._flush_lock:
            if self._file is not None:
                self._file.close()
                self._file = None
        _TARGETS.discard(self)


NULL_TELEMETRY = Telemetry(enabled=False)


class RecompileWatch:
    """``jax_log_compiles`` listener for production runs.

    Reuses the CompileCounter machinery (same logger names + regex) but
    instead of asserting a test scenario it (a) records every executable
    build as a zero-duration ``compile`` span + ``compiles`` counter and
    (b) emits ONE manifest *warning* per jitted-fn name whose build
    count exceeds ``per_bucket_ceiling(name) * max(1, buckets seen)`` —
    the runtime form of the GC401 invariant that executables are shared
    per bucket, so compiles must scale with distinct buckets, never with
    videos. Ceilings come from ``analysis/compile_budget.json`` (the min
    across scenarios budgeting the name, i.e. the tightest committed
    per-corpus ceiling)."""

    def __init__(self, tele: Telemetry, manifest: Any) -> None:
        self.tele = tele
        self.manifest = manifest
        self.counts: Dict[str, int] = {}
        self.warned: set = set()
        self._lock = threading.Lock()
        self._handler: Optional[Any] = None
        self._prev_flag: Optional[bool] = None
        self.limits = runtime_compile_limits()

    def attach(self) -> None:
        import logging

        import jax

        from video_features_tpu.analysis import compile_budget as cb

        watch = self

        class _Handler(logging.Handler):
            def emit(self, record: logging.LogRecord) -> None:
                try:
                    m = cb._COMPILING_RE.match(record.getMessage())
                except Exception:  # noqa: BLE001 - a broken record must not kill the run
                    return
                if m:
                    watch.on_compile(m.group(1))

        handler = _Handler(level=logging.DEBUG)
        self._prev_flag = bool(jax.config.jax_log_compiles)
        jax.config.update("jax_log_compiles", True)
        for name in cb._LOGGER_NAMES:
            logging.getLogger(name).addHandler(handler)
        self._handler = handler

    def detach(self) -> None:
        if self._handler is None:
            return
        import logging

        import jax

        from video_features_tpu.analysis import compile_budget as cb

        for name in cb._LOGGER_NAMES:
            logging.getLogger(name).removeHandler(self._handler)
        if self._prev_flag is not None:
            jax.config.update("jax_log_compiles", bool(self._prev_flag))
        self._handler = None

    def on_compile(self, fn_name: str) -> None:
        if compile_watch_suppressed():
            return  # ledger analysis compile, not a real (re)build
        with self._lock:
            self.counts[fn_name] = self.counts.get(fn_name, 0) + 1
            count = self.counts[fn_name]
            already_warned = fn_name in self.warned
        self.tele.metrics.inc("compiles")
        self.tele.point("compile", fn=fn_name, n=count)
        ceiling = self.limits.get(fn_name)
        if ceiling is None or already_warned:
            return
        allowance = ceiling * max(1, self.tele.buckets_seen())
        if count > allowance:
            with self._lock:
                if fn_name in self.warned:
                    return
                self.warned.add(fn_name)
            try:
                self.manifest.record(
                    None, "warning", stage="compile",
                    message=(
                        f"recompile watch: {fn_name!r} built {count} executables, "
                        f"runtime allowance is {allowance} "
                        f"({ceiling}/bucket x {max(1, self.tele.buckets_seen())} "
                        f"buckets seen) — per-video state may be leaking into "
                        f"trace-time (see analysis/compile_budget.json)"
                    ),
                )
            except Exception:  # noqa: BLE001 - observability must never kill the run
                pass


def runtime_compile_limits(path: Optional[str] = None) -> Dict[str, int]:
    """Per-bucket runtime ceilings derived from compile_budget.json: for
    each budgeted fn name, the MIN ceiling across scenarios (tightest
    committed per-corpus bound). The watch multiplies by observed
    distinct buckets, so a 10-bucket corpus legitimately compiling 10
    ``encode_raw`` variants stays quiet while an O(videos) leak fires."""
    from video_features_tpu.analysis.compile_budget import load_budget

    limits: Dict[str, int] = {}
    try:
        scenarios = load_budget(path)
    except Exception:  # noqa: BLE001 - missing budget file disables enforcement
        return limits
    for spec in scenarios.values():
        for name, ceiling in spec.get("max_compiles", {}).items():
            limits[name] = min(limits.get(name, ceiling), int(ceiling))
    return limits


# -- pure helpers (no Telemetry state) ----------------------------------


def payload_nbytes(payload: Any) -> int:
    """Total array bytes in a (possibly nested) host payload, duck-typed
    on ``.nbytes`` so no numpy import is needed here."""
    n = getattr(payload, "nbytes", None)
    if n is not None:
        return int(n)
    if isinstance(payload, dict):
        return sum(payload_nbytes(v) for v in payload.values())
    if isinstance(payload, (list, tuple)):
        return sum(payload_nbytes(v) for v in payload)
    return 0


def read_spans(path: str) -> List[Dict[str, Any]]:
    """Load one spans-*.jsonl file, skipping torn trailing lines."""
    rows: List[Dict[str, Any]] = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except ValueError:
                continue
    return rows


def _intersect(xs: List[Tuple[float, float]], ys: List[Tuple[float, float]]) -> float:
    """Seconds where the two (already merged-disjoint, sorted) interval
    unions overlap."""
    total = 0.0
    i = j = 0
    while i < len(xs) and j < len(ys):
        a = max(xs[i][0], ys[j][0])
        b = min(xs[i][1], ys[j][1])
        if b > a:
            total += b - a
        if xs[i][1] < ys[j][1]:
            i += 1
        else:
            j += 1
    return total


def _merged(intervals: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    if not intervals:
        return []
    intervals.sort()
    out = [list(intervals[0])]
    for a, b in intervals[1:]:
        if a > out[-1][1]:
            out.append([a, b])
        else:
            out[-1][1] = max(out[-1][1], b)
    return [(a, b) for a, b in out]


def overlap_report(rows: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Overlap efficiency from span intervals: how much of the run's
    wall time had host ingest (decode/reencode/prepare) running
    concurrently with device work (h2d/dispatch/fetch).

    ``overlap_efficiency`` is overlap seconds / wall seconds — the
    headline the async-ingest PR is judged on. ``overlap_of_device``
    (overlap / device-busy) answers the sharper question: while the
    chip was busy, was the host feeding it? Single-process spans only
    use monotonic clocks, so rows from different pids are compared
    per-pid and summed."""
    by_pid: Dict[int, Tuple[list, list]] = {}
    for r in rows:
        stage = r.get("stage")
        t0, t1 = r.get("t0"), r.get("t1")
        if t0 is None or t1 is None or t1 < t0:
            continue
        pid = int(r.get("pid", 0))
        h, d = by_pid.setdefault(pid, ([], []))
        if stage in HOST_STAGES:
            h.append((float(t0), float(t1)))
        elif stage in DEVICE_STAGES:
            d.append((float(t0), float(t1)))
    wall = host_busy = dev_busy = overlap = 0.0
    for h, d in by_pid.values():
        host, dev = _merged(h), _merged(d)
        host_busy += sum(b - a for a, b in host)
        dev_busy += sum(b - a for a, b in dev)
        overlap += _intersect(host, dev)
        ts = [a for a, _ in host] + [a for a, _ in dev]
        te = [b for _, b in host] + [b for _, b in dev]
        if ts:
            wall += max(te) - min(ts)
    return {
        "wall_s": wall,
        "host_busy_s": host_busy,
        "device_busy_s": dev_busy,
        "overlap_s": overlap,
        "overlap_efficiency": (overlap / wall) if wall > 0 else 0.0,
        "overlap_of_device": (overlap / dev_busy) if dev_busy > 0 else 0.0,
        "spans": sum(len(h) + len(d) for h, d in by_pid.values()),
    }


def _device_of_row(r: Dict[str, Any]) -> str:
    """The device lane a span belongs to: the pipelined loop stamps
    device spans with ``worker=str(device)`` (extract/base.py); spans
    missing it (the serial loop, old files) share one per-pid lane."""
    w = r.get("worker")
    return str(w) if w else f"pid{int(r.get('pid', 0))}"


def utilization_report(rows: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Per-device busy/idle accounting over the device stages
    (h2d/dispatch/fetch) — the per-device refinement of
    :func:`overlap_report`. Busy time is the merged union of one
    device's span intervals; wall time is per-pid (monotonic clocks
    never compare across processes), taken over ALL stage spans so a
    device idle while the host decodes counts as idle.

    ``device_utilization`` is the headline fraction in summary.json:
    total device-busy seconds / total device-lane wall seconds (each
    pid's wall counted once per device it drove). 0.0 when no device
    spans exist (serial loop, --telemetry off)."""
    # pid -> (wall intervals over every stage, device -> intervals)
    by_pid: Dict[int, Tuple[list, Dict[str, list]]] = {}
    for r in rows:
        t0, t1 = r.get("t0"), r.get("t1")
        if t0 is None or t1 is None or t1 < t0:
            continue
        pid = int(r.get("pid", 0))
        walls, devs = by_pid.setdefault(pid, ([], {}))
        walls.append((float(t0), float(t1)))
        if r.get("stage") in DEVICE_STAGES:
            devs.setdefault(_device_of_row(r), []).append((float(t0), float(t1)))
    devices: Dict[str, Dict[str, Any]] = {}
    busy_total = wall_total = 0.0
    for walls, devs in by_pid.values():
        if not devs:
            continue
        merged_wall = _merged(walls)
        pid_wall = (merged_wall[-1][1] - merged_wall[0][0]) if merged_wall else 0.0
        for name, intervals in devs.items():
            merged = _merged(intervals)
            busy = sum(b - a for a, b in merged)
            d = devices.setdefault(
                name, {"busy_s": 0.0, "wall_s": 0.0, "spans": 0}
            )
            d["busy_s"] += busy
            d["wall_s"] += pid_wall
            d["spans"] += len(intervals)
            busy_total += busy
            wall_total += pid_wall
    for d in devices.values():
        d["busy_frac"] = (d["busy_s"] / d["wall_s"]) if d["wall_s"] > 0 else 0.0
        d["idle_s"] = max(d["wall_s"] - d["busy_s"], 0.0)
    return {
        "devices": {k: devices[k] for k in sorted(devices)},
        "device_busy_s": busy_total,
        "device_wall_s": wall_total,
        "device_utilization": (busy_total / wall_total) if wall_total > 0 else 0.0,
    }


def request_trace_rows(
    rows: Sequence[Dict[str, Any]], request_id: str
) -> List[Dict[str, Any]]:
    """Assemble the spans belonging to ONE serve request out of a run's
    combined span rows (``python -m video_features_tpu.telemetry trace
    <request_id>``).

    A serve request's spans live in two files: the daemon's telemetry
    records the lifecycle (``admission``/``request``/``queue_wait``
    spans carrying ``request=<id>``), while the resident extractor's
    telemetry records the group dispatch (a ``request`` span whose
    ``requests`` list links the member ids) and the per-video pipeline
    stages. Selection:

    1. anchors — every span whose ``request`` equals the id, plus every
       group span whose ``requests`` list contains it;
    2. descendants of an anchor via ``parent`` links (the dispatcher
       thread's dispatch/fetch/sink spans nest under the group span);
    3. same-pid spans for the request's video overlapping a group
       span's interval (decode/prepare run on worker threads whose
       spans do not parent-link into the group).

    Result is t0-ordered; empty when the id appears nowhere."""
    anchors: List[Dict[str, Any]] = []
    for r in rows:
        if r.get("request") == request_id:
            anchors.append(r)
        else:
            reqs = r.get("requests")
            if isinstance(reqs, (list, tuple)) and request_id in reqs:
                anchors.append(r)
    if not anchors:
        return []
    children: Dict[str, List[Dict[str, Any]]] = {}
    for r in rows:
        p = r.get("parent")
        if p:
            children.setdefault(p, []).append(r)
    selected: Dict[str, Dict[str, Any]] = {}
    stack = list(anchors)
    while stack:
        r = stack.pop()
        sid = r.get("span")
        if not sid or sid in selected:
            continue
        selected[sid] = r
        stack.extend(children.get(sid, ()))
    videos = {r.get("video") for r in anchors if r.get("video")}
    windows = [
        (int(r.get("pid", 0)), float(r["t0"]), float(r["t1"]))
        for r in anchors
        if isinstance(r.get("requests"), (list, tuple))
        and r.get("t0") is not None and r.get("t1") is not None
    ]
    if videos and windows:
        for r in rows:
            sid = r.get("span")
            if not sid or sid in selected or r.get("video") not in videos:
                continue
            t0, t1 = r.get("t0"), r.get("t1")
            if t0 is None or t1 is None:
                continue
            pid = int(r.get("pid", 0))
            if any(pid == wp and float(t1) >= w0 and float(t0) <= w1
                   for wp, w0, w1 in windows):
                selected[sid] = r
    return sorted(selected.values(), key=lambda r: (r.get("t0") or 0.0, r.get("seq", 0)))


# synthetic tid base for the per-device Perfetto lanes: far above any
# real thread ident so lanes never collide with OS thread ids
_DEVICE_LANE_TID_BASE = 1 << 22


def spans_to_chrome_trace(
    rows: Sequence[Dict[str, Any]], device_lanes: bool = False
) -> Dict[str, Any]:
    """Chrome-trace ("Trace Event Format") JSON from span rows, loadable
    in Perfetto / chrome://tracing. Complete ("X") events with µs
    ``ts``/``dur`` rebased to the earliest span, plus thread_name
    metadata so lanes are labelled decode-*/worker threads.

    ``device_lanes=True`` (``telemetry export --device-lanes``)
    additionally mirrors every device-stage span (h2d/dispatch/fetch)
    into one synthetic ``device <name>`` lane per device, so the
    busy/idle timeline :func:`utilization_report` summarizes is visible
    as a row per chip rather than scattered across dispatcher threads."""
    events: List[Dict[str, Any]] = []
    t_base = min(
        (float(r["t0"]) for r in rows if r.get("t0") is not None),
        default=0.0,
    )
    seen_threads: set = set()
    device_tids: Dict[Tuple[int, str], int] = {}
    for r in rows:
        t0, t1 = r.get("t0"), r.get("t1")
        if t0 is None or t1 is None:
            continue
        pid = int(r.get("pid", 0))
        tid = int(r.get("thread", 0))
        key = (pid, tid)
        if key not in seen_threads and r.get("thread_name"):
            seen_threads.add(key)
            events.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": r["thread_name"]},
            })
        args = {
            k: v for k, v in r.items()
            if k not in ("stage", "t0", "t1", "pid", "thread", "thread_name")
            and v is not None
        }
        ev = {
            "ph": "X",
            "name": r.get("stage", "?"),
            "cat": r.get("stage", "?"),
            "ts": int(round((float(t0) - t_base) * 1e6)),
            "dur": max(int(round((float(t1) - float(t0)) * 1e6)), 0),
            "pid": pid,
            "tid": tid,
            "args": args,
        }
        events.append(ev)
        if device_lanes and r.get("stage") in DEVICE_STAGES:
            dev = _device_of_row(r)
            lane_key = (pid, dev)
            lane_tid = device_tids.get(lane_key)
            if lane_tid is None:
                lane_tid = _DEVICE_LANE_TID_BASE + len(device_tids)
                device_tids[lane_key] = lane_tid
                events.append({
                    "ph": "M", "name": "thread_name", "pid": pid,
                    "tid": lane_tid, "args": {"name": f"device {dev}"},
                })
            events.append({**ev, "tid": lane_tid})
    events.sort(key=lambda e: (e.get("ts", -1), e["ph"] != "M"))
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# -- summary.json integration ------------------------------------------


def merge_metrics_files(output_root: str) -> Optional[Dict[str, Any]]:
    """Merge every ``_telemetry/metrics-*.json`` under ``output_root``:
    counters sum, gauges max, histograms merge bucket-wise. Returns None
    when no telemetry was recorded."""
    paths = sorted(glob.glob(os.path.join(output_root, "_telemetry", "metrics-*.json")))
    if not paths:
        return None
    counters: Dict[str, float] = {}
    gauges: Dict[str, float] = {}
    hists: Dict[str, Dict[str, Any]] = {}
    t_start: Optional[float] = None
    t_end: Optional[float] = None
    buckets = 0
    for p in paths:
        try:
            with open(p, "r", encoding="utf-8") as f:
                snap = json.load(f)
        except Exception:  # noqa: BLE001 - torn snapshot from a crashed process
            continue
        for k, v in snap.get("counters", {}).items():
            counters[k] = counters.get(k, 0) + v
        for k, v in snap.get("gauges", {}).items():
            gauges[k] = max(gauges.get(k, v), v)
        for k, h in snap.get("histograms", {}).items():
            cur = hists.get(k)
            if cur is None:
                hists[k] = {
                    "count": h["count"], "sum": h["sum"],
                    "min": h["min"], "max": h["max"],
                    "bounds": h["bounds"], "buckets": list(h["buckets"]),
                }
            else:
                cur["count"] += h["count"]
                cur["sum"] += h["sum"]
                cur["min"] = min(cur["min"], h["min"])
                cur["max"] = max(cur["max"], h["max"])
                cur["buckets"] = [a + b for a, b in zip(cur["buckets"], h["buckets"])]
        ts = snap.get("t_start")
        te = snap.get("t_snapshot")
        if ts is not None:
            t_start = ts if t_start is None else min(t_start, ts)
        if te is not None:
            t_end = te if t_end is None else max(t_end, te)
        buckets = max(buckets, int(snap.get("buckets_seen", 0)))
    if t_start is None:
        t_start = t_end = 0.0
    wall = max((t_end or 0.0) - t_start, 1e-9)
    done = counters.get("videos_done", 0)
    frames = counters.get("frames_decoded", 0)
    decode_s = hists.get("stage_s.decode", {}).get("sum", 0.0)
    return {
        "counters": counters,
        "gauges": gauges,
        "histograms": hists,
        "buckets_seen": buckets,
        "stages": {
            name[len("stage_s."):]: {"seconds": h["sum"], "calls": h["count"]}
            for name, h in hists.items() if name.startswith("stage_s.")
        },
        "throughput": {
            "wall_s": wall,
            "videos_per_s": done / wall,
            "decode_fps": (frames / decode_s) if decode_s > 0 else (frames / wall),
        },
    }


def collect(output_root: str) -> Optional[Dict[str, Any]]:
    """The ``summary.json`` telemetry block: merged metrics plus the
    overlap report over every spans file under ``output_root``."""
    block = merge_metrics_files(output_root)
    span_paths = sorted(glob.glob(os.path.join(output_root, "_telemetry", "spans-*.jsonl")))
    rows: List[Dict[str, Any]] = []
    for p in span_paths:
        rows.extend(read_spans(p))
    if block is None and not rows:
        return None
    if block is None:
        block = {}
    if rows:
        block["overlap"] = overlap_report(rows)
        # the per-device busy/idle refinement; its device_utilization
        # fraction is THE headline the fleet-scale placement work reads
        util = utilization_report(rows)
        block["utilization"] = util
        block["device_utilization"] = util["device_utilization"]
        block["span_files"] = [os.path.basename(p) for p in span_paths]
    return block
