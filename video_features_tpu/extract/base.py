"""Extractor runtime: the per-video loop every feature type shares.

This is the framework contract layer (SURVEY.md §1 L4). The reference
implements it as a ``torch.nn.Module`` per feature type with a uniform
shape — path list in ``__init__``, model built inside ``forward`` per
replica, per-video try/except, results routed to the output sink (e.g.
ref models/resnet/extract_resnet.py:25-71, models/CLIP/extract_clip.py:69-87).

The TPU-native equivalent: a plain class whose per-device state is a
lazily-built, cached bundle of jit-compiled functions + device-resident
params (``warmup``/``_build``); ``__call__(indices, device)`` runs the
video loop with the same error isolation and sink routing; the
``external_call`` mode returns feature dicts in-memory instead
(ref models/CLIP/extract_clip.py:22,73-77).
"""

from __future__ import annotations

import os
import threading
import traceback
from typing import Any, Dict, List, Optional, Sequence

import numpy as np
from tqdm import tqdm

from video_features_tpu.config import as_config
from video_features_tpu.io.paths import form_list_from_user_input, video_path_of
from video_features_tpu.io.sink import action_on_extraction, expected_output_files
from video_features_tpu.utils.profiling import StageTimer, device_trace


class BaseExtractor:
    """Subclasses set ``feature_type`` and implement ``_build`` + ``extract``."""

    feature_type: str = ""

    def __init__(self, config, external_call: bool = False) -> None:
        self.config = as_config(config)
        self.external_call = external_call
        if not self.feature_type:
            self.feature_type = self.config.feature_type
        self.path_list = form_list_from_user_input(self.config)
        self.progress = tqdm(total=len(self.path_list))
        # features land in <output_path>/<feature_type>/ unless output_direct
        # (ref models/CLIP/extract_clip.py:30-35)
        if self.config.output_direct:
            self.output_path = self.config.output_path
        else:
            self.output_path = os.path.join(self.config.output_path, self.feature_type)
        self.tmp_path = os.path.join(self.config.tmp_path, self.feature_type)
        self._device_state: Dict[Any, Any] = {}
        self._build_lock = threading.Lock()
        self.timer = StageTimer()

    def feature_keys(self):
        """The keys a feats_dict will carry (used by --resume to probe for
        existing outputs). I3D overrides with its streams."""
        return [self.feature_type]

    def _already_done(self, entry) -> bool:
        files = expected_output_files(
            self.feature_keys(),
            video_path_of(entry),
            self.output_path,
            self.config.on_extraction,
            self.config.output_direct,
        )
        return bool(files) and all(os.path.exists(f) for f in files)

    # --- per-device model state -------------------------------------------
    def _build(self, device) -> Any:
        """Build jitted fns + device-resident params for ``device``."""
        raise NotImplementedError

    def warmup(self, device) -> Any:
        """Build (once) and cache this device's model state. Thread-safe."""
        key = device
        state = self._device_state.get(key)
        if state is None:
            with self._build_lock:
                state = self._device_state.get(key)
                if state is None:
                    state = self._build(device)
                    self._device_state[key] = state
        return state

    # --- the video loop ----------------------------------------------------
    def _default_device(self):
        from video_features_tpu.parallel.devices import resolve_devices

        return resolve_devices(self.config)[0]

    def __call__(
        self,
        indices: Optional[Sequence[int]] = None,
        device=None,
    ) -> Optional[List[Dict[str, np.ndarray]]]:
        if indices is None:
            indices = range(len(self.path_list))
        if device is None:
            device = self._default_device()
        state = self.warmup(device)

        results: List[Dict[str, np.ndarray]] = []
        with device_trace(self.config.profile_dir):
            for idx in indices:
                entry = self.path_list[int(idx)]
                try:
                    if (
                        self.config.resume
                        and not self.external_call
                        and self._already_done(entry)
                    ):
                        self.progress.update()
                        continue
                    with self.timer.stage("extract"):
                        feats_dict = self.extract(device, state, entry)
                    if self.external_call:
                        results.append(feats_dict)
                    else:
                        with self.timer.stage("sink"):
                            action_on_extraction(
                                feats_dict,
                                video_path_of(entry),
                                self.output_path,
                                self.config.on_extraction,
                                self.config.output_direct,
                            )
                except KeyboardInterrupt:
                    raise
                except Exception:  # noqa: BLE001 - per-video isolation (ref extract_clip.py:78-84)
                    print(f"An error occurred extracting {video_path_of(entry)}:")
                    traceback.print_exc()
                    print("Continuing...")
                self.progress.update()
        if self.config.profile_dir:
            print(self.timer.summary())
        if self.external_call:
            return results
        return None

    # torch-API compatibility: the reference invokes extractors as modules
    forward = __call__

    def extract(self, device, state, path_entry) -> Dict[str, np.ndarray]:
        """Decode -> preprocess -> model -> {feature_type, fps, timestamps_ms}."""
        raise NotImplementedError
